"""AOT compile-check the Pallas kernels AND the full bench train steps
for a real TPU target WITHOUT hardware: libtpu's compile-only PJRT
topology client lowers through Mosaic exactly as a real chip would, so
kernel lowering errors, VMEM exhaustion, and whole-step HBM overflow
surface here instead of in the driver's benchmark run.

Usage: python tools/aot_check.py [--topology v5e:2x2]
        [--kernels] [--steps] [--collectives]   (default: all three)

- Kernel checks shard the batch over a dp mesh (Mosaic kernels are not
  auto-partitionable), sized so PER-DEVICE shapes equal the single-chip
  bench shapes.
- Step checks compile the ACTUAL `bench.py` train steps single-device
  with donated state and report the HBM breakdown — these are the
  numbers the bench.py batch/layer comments cite.
- Collectives checks compile the distributed shard_map programs (ring
  attention, Ulysses, MoE double-all_to_all, scan+ppermute pipeline)
  against the multi-chip topology — ICI collective lowering + Mosaic
  in one program.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# persistent cache: repeated AOT gates on this single-core box are
# compile-dominated; cached Mosaic/XLA artifacts make re-runs cheap
from apex1_tpu.testing import (  # noqa: E402
    enable_persistent_compilation_cache)

enable_persistent_compilation_cache()


def _gen_from_topology(topology: str) -> str:
    return topology.split(":")[0]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--topology", default="v5e:2x2")
    ap.add_argument("--kernels", action="store_true")
    ap.add_argument("--steps", action="store_true")
    ap.add_argument("--collectives", action="store_true")
    ap.add_argument("--flagship", action="store_true",
                    help="Llama-3-8B dp x pp x tp train step at v5p-32 "
                         "scale (BASELINE config 4)")
    ap.add_argument("--flagship-topology", default="v5p:2x2x4")
    args = ap.parse_args()
    if not (args.kernels or args.steps or args.collectives
            or args.flagship):
        args.kernels = args.steps = args.collectives = True
        args.flagship = True

    # Before ANY apex1_tpu import: make dispatch pick the REAL (non-
    # interpret) Pallas path, and block planning match the target chip.
    os.environ["PALLAS_AXON_TPU_GEN"] = _gen_from_topology(args.topology)
    import apex1_tpu.ops._common as _common
    _common.on_tpu = lambda: True          # use_pallas() -> True
    _common.interpret_mode = lambda: False  # real Mosaic lowering

    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, SingleDeviceSharding
    from jax.sharding import PartitionSpec as P

    from apex1_tpu.ops import force_impl

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name=args.topology)
    n = len(topo.devices)
    mesh = Mesh(np.array(topo.devices).reshape(n), ("dp",))
    ok = True

    # Verify the patches reach the DISPATCH THE KERNELS USE (they import
    # interpret_mode/on_tpu by reference; a refactor that snapshots the
    # mode at import would silently AOT-check the interpreter instead of
    # Mosaic): a Mosaic lowering must contain a tpu_custom_call.
    from apex1_tpu.ops import layer_norm as _ln
    _s1 = SingleDeviceSharding(topo.devices[0])
    _txt = jax.jit(
        lambda x: _ln(x, jnp.ones((128,), jnp.float32),
                      jnp.zeros((128,), jnp.float32))).lower(
        jax.ShapeDtypeStruct((8, 128), jnp.float32,
                             sharding=_s1)).as_text()
    assert "tpu_custom_call" in _txt or "mosaic" in _txt.lower(), (
        "Pallas dispatch is NOT taking the Mosaic path — aot_check "
        "results would be meaningless")

    def report(name, lower_fn):
        nonlocal ok
        try:
            mem = lower_fn().compile().memory_analysis()
            tmp = mem.temp_size_in_bytes / 2**30
            arg = mem.argument_size_in_bytes / 2**30
            print(f"  OK   {name:48s} temp {tmp:6.2f} GiB  "
                  f"args {arg:6.2f} GiB", flush=True)
        except Exception as e:
            ok = False
            print(f"  FAIL {name}: {type(e).__name__}: {str(e)[:300]}",
                  flush=True)

    def check(name, fn, shapes, *, dtypes=jnp.bfloat16, in_specs=None,
              grad=False):
        """Kernel check: shapes are PER-DEVICE; sharded dims scale by n."""
        if not isinstance(dtypes, (tuple, list)):
            dtypes = [dtypes] * len(shapes)
        in_specs = in_specs or (P("dp"),) * len(shapes)
        # global shape = per-device shape scaled along the sharded dim
        def gshape(shp, spec):
            if spec == P():
                return shp
            return (shp[0] * n,) + tuple(shp[1:])
        arrs = [jax.ShapeDtypeStruct(
                    gshape(shp, spec), dt,
                    sharding=NamedSharding(mesh, spec))
                for shp, dt, spec in zip(shapes, dtypes, in_specs)]

        def run():
            def local(*xs):
                with force_impl("pallas"):
                    out = fn(*xs)
                return out

            if grad:
                base = local

                def local(*xs):  # noqa: F811
                    fi = tuple(i for i, x in enumerate(xs)
                               if jnp.issubdtype(x.dtype, jnp.floating))
                    return jax.grad(
                        lambda *a: jnp.sum(base(*a).astype(jnp.float32)),
                        argnums=fi)(*xs)

            out_specs = jax.tree_util.tree_map(
                lambda _: P("dp"), jax.eval_shape(local, *arrs))
            smapped = jax.shard_map(local, mesh=mesh, in_specs=tuple(in_specs),
                                    out_specs=out_specs, check_vma=False)
            return jax.jit(smapped).lower(*arrs)

        report(name, run)

    if args.kernels:
        print(f"== Pallas kernels (per-device = bench shapes), "
              f"{args.topology} ==", flush=True)
        from apex1_tpu.ops import (layer_norm, rms_norm,
                                   scaled_upper_triang_masked_softmax,
                                   softmax_cross_entropy_loss)
        from apex1_tpu.ops.attention import flash_attention
        from apex1_tpu.ops.linear_xent import linear_cross_entropy
        from apex1_tpu.ops.rope import apply_rotary_pos_emb, rope_tables

        fa = lambda q, k, v: flash_attention(q, k, v, causal=True)
        for nm, shp in (("flash gpt2 B16 (16,12,1024,64)",
                         (16, 12, 1024, 64)),
                        ("flash longctx (1,32,16384,64)",
                         (1, 32, 16384, 64))):
            check(f"{nm} fwd", fa, [shp] * 3)
            check(f"{nm} fwd+bwd", fa, [shp] * 3, grad=True)
        # GQA (Hq/Hkv = 8): the dkv kernel accumulates the group in VMEM
        # and writes Hkv-sized fp32 outputs — temp must stay near the
        # group=1 case, not 8x it
        gq, gkv = (1, 32, 16384, 64), (1, 4, 16384, 64)
        check("flash longctx GQA (Hq32/Hkv4,16k,64) fwd", fa,
              [gq, gkv, gkv])
        check("flash longctx GQA (Hq32/Hkv4,16k,64) fwd+bwd", fa,
              [gq, gkv, gkv], grad=True)
        # additive-bias flash (T5 rel-pos path): dbias rides the extra
        # broadcast-accumulating backward pass — bias replicated (head
        # bias shared across the dp shards)
        fab = lambda q, k, v, b: flash_attention(q, k, v, bias=b)
        bshp = (2, 8, 1024, 64)
        check("flash bias T5-ish (2,8,1024,64) fwd+bwd", fab,
              [bshp, bshp, bshp, (1, 8, 1024, 1024)],
              in_specs=(P("dp"), P("dp"), P("dp"), P()), grad=True)
        # in-kernel probability dropout (bert-pretrain config: attention
        # dropout 0.1): the Mosaic gate for pltpu.prng_seed/random_bits
        # in all three kernels — tier-1 only exercises the interpret-
        # mode hash path, so THIS is the real TPU guard (same standing-
        # risk shape as the ring collectives gate)
        fad = lambda q, k, v: flash_attention(
            q, k, v, causal=True, dropout_p=0.1, dropout_seed=1234)
        dshp = (8, 12, 512, 64)
        check("flash dropout p=0.1 (8,12,512,64) fwd", fad, [dshp] * 3)
        check("flash dropout p=0.1 (8,12,512,64) fwd+bwd", fad,
              [dshp] * 3, grad=True)
        check("flash dropout longctx (1,32,16384,64) fwd+bwd", fad,
              [(1, 32, 16384, 64)] * 3, grad=True)
        from apex1_tpu.ops import fused_bias_dropout_add
        check("bias_dropout_add (16384,1024) fwd+bwd",
              lambda x, r, b: fused_bias_dropout_add(
                  x, r, bias=b, p=0.1, seed=42),
              [(16384, 1024), (16384, 1024), (1024,)],
              dtypes=[jnp.bfloat16, jnp.bfloat16, jnp.float32],
              in_specs=(P("dp"), P("dp"), P()), grad=True)

        T, Hid, V = 16 * 1023, 768, 50432
        check(f"linear_xent gpt2 ({T},{Hid},{V}) fwd+bwd",
              lambda x, w: linear_cross_entropy(
                  x, w, jnp.zeros((x.shape[0],), jnp.int32),
                  num_classes=V - 200),
              [(T, Hid), (V, Hid)], in_specs=(P("dp"), P()), grad=True)

        g = jnp.ones((768,), jnp.float32)
        check("layer_norm (16384,768) fwd+bwd",
              lambda x: layer_norm(x, g, jnp.zeros_like(g)),
              [(16384, 768)], grad=True)
        check("rms_norm (16384,2048) fwd+bwd",
              lambda x: rms_norm(x, jnp.ones((2048,), jnp.float32)),
              [(16384, 2048)], grad=True)
        check("causal softmax (16,12,1024,1024) fwd+bwd",
              lambda x: scaled_upper_triang_masked_softmax(x, scale=0.125),
              [(16, 12, 1024, 1024)], dtypes=jnp.float32, grad=True)
        check("xentropy (16368,50432) fwd+bwd",
              lambda x: softmax_cross_entropy_loss(
                  x, jnp.zeros((x.shape[0],), jnp.int32),
                  num_classes=50257),
              [(16368, 50432)], dtypes=jnp.float32, grad=True)
        cos, sin = rope_tables(jnp.arange(16384), 64)
        check("rope llama (1,16384,32,64) fwd+bwd",
              lambda x: apply_rotary_pos_emb(x, cos, sin),
              [(1, 16384, 32, 64)], grad=True)
        # int8 weight-only decode GEMM (dequant fused in VMEM): decode-row
        # x against a llama-head-sized weight; weight+scale replicated
        from apex1_tpu.ops import int8_matmul
        check("int8 matmul decode (8,4096)x(32000,4096) fwd",
              lambda x, wq, s: int8_matmul(x, wq, s),
              [(8, 4096), (32000, 4096), (32000,)],
              dtypes=[jnp.bfloat16, jnp.int8, jnp.float32],
              in_specs=(P("dp"), P(), P()))

        # paged ragged decode attention + fused sampling epilogue
        # (ISSUE 18): the serving engine's paged decode step at real
        # engine shapes, BOTH cache tiers (int8 dequant fused in-kernel
        # and bf16). `check_paged_geometry` runs at trace time against
        # the registry-shared vmem model, so an unregistered/unfittable
        # page geometry fails THIS gate loudly — the kernel path never
        # silently falls back to the composite.
        from apex1_tpu.ops.paged_decode import (check_paged_geometry,
                                                fused_sample,
                                                paged_attend)

        # llama-head decode rows (Hq32/Hkv8 GQA, D=128) over a
        # 2048-token lane at page 16 -> T=128 pages per block-table row;
        # the page pool is pool-wide state (replicated), rows shard dp
        N_s, Hq_s, Hkv_s, D_s, P_s = 8, 32, 8, 128, 16
        T_s = 2048 // P_s
        n_pg = 1 + N_s * T_s
        pa = lambda q, kp, vp, bt, ln: paged_attend(q, kp, vp, bt, ln)
        pv = lambda q, kp, vp, bt, ln: paged_attend(
            q, kp, vp, bt, ln, total_len=T_s * P_s)
        for tier, cdt in (("int8", jnp.int8), ("bf16", jnp.bfloat16)):
            check(f"paged_attend decode {tier} "
                  f"(8,Hq32/Hkv8,D128,page16,T128)", pa,
                  [(N_s, Hq_s, 1, D_s), (n_pg, Hkv_s, P_s, D_s),
                   (n_pg, Hkv_s, P_s, D_s), (N_s, T_s), (N_s,)],
                  dtypes=[jnp.bfloat16, cdt, cdt, jnp.int32, jnp.int32],
                  in_specs=(P("dp"), P(), P(), P("dp"), P("dp")))
            # the speculative verify row class: S = K+1 = 5 queries per
            # slot through the same pages
            check(f"paged_attend verify {tier} (8,Hq32/Hkv8,S5)", pv,
                  [(N_s, Hq_s, 5, D_s), (n_pg, Hkv_s, P_s, D_s),
                   (n_pg, Hkv_s, P_s, D_s), (N_s, T_s), (N_s,)],
                  dtypes=[jnp.bfloat16, cdt, cdt, jnp.int32, jnp.int32],
                  in_specs=(P("dp"), P(), P(), P("dp"), P("dp")))
        for tag, kw in (("greedy", dict(temperature=0.0)),
                        ("T0.7", dict(temperature=0.7))):
            check(f"fused_sample epilogue {tag} (8,50432)",
                  lambda lg, s, p, kw=kw: fused_sample(
                      lg, s, p, vocab_size=50257, **kw),
                  [(N_s, 50432), (N_s,), (N_s,)],
                  dtypes=[jnp.float32, jnp.int32, jnp.int32],
                  in_specs=(P("dp"), P("dp"), P("dp")))
        # the loud-failure half of the contract: a sublane-misaligned
        # page and an over-budget page must RAISE at trace time, never
        # fall back
        for bad in (12, 1 << 20):
            try:
                check_paged_geometry(bad, D_s, Hq_s // Hkv_s, 1)
            except ValueError as e:
                print(f"  OK   paged geometry page={bad:>7} raises: "
                      f"{str(e)[:60]}", flush=True)
            else:
                ok = False
                print(f"  FAIL paged geometry gate: page={bad} must "
                      f"raise ValueError", flush=True)

        # chunked preference/distill losses, fused GLU, LoRA epilogue
        # (ISSUE 19): the chunked-loss VJP recomputes per vocab chunk
        # through the linear_xent stats kernels; fused_glu is the llama
        # fused_mlp tile; lora_delta is the multi-tenant serving
        # epilogue's scalar-prefetched page gather. Both dtypes — the
        # registry tables price each (kernel, dtype) separately.
        from apex1_tpu.ops.chunked_loss import (check_chunk_geometry,
                                                chunked_logprob)
        from apex1_tpu.ops.fused_dense import (check_glu_geometry,
                                               fused_glu)
        from apex1_tpu.ops.lora_epilogue import (check_lora_geometry,
                                                 lora_delta)

        T_c, H_c, V_c = 8 * 1024, 768, 50432
        R_l, Hd_l, V_l = 8, 4096, 50432
        n_lp = 1 + 4 * R_l
        for dt in (jnp.bfloat16, jnp.float32):
            tag = jnp.dtype(dt).name
            check(f"chunked_logprob gpt2 ({T_c},{H_c},{V_c}) cv8192 "
                  f"{tag} fwd+bwd",
                  lambda x, w: chunked_logprob(
                      x, w, jnp.zeros((x.shape[0],), jnp.int32),
                      chunk_v=8192, num_classes=V_c - 200),
                  [(T_c, H_c), (V_c, H_c)], dtypes=dt,
                  in_specs=(P("dp"), P()), grad=True)
            check(f"fused_glu llama mlp (8192,4096,14336) {tag} "
                  f"fwd+bwd", fused_glu,
                  [(8192, 4096), (4096, 14336), (4096, 14336)],
                  dtypes=dt, in_specs=(P("dp"), P(), P()), grad=True)
            check(f"lora_delta epilogue (8,H4096,V50432,r8) {tag}",
                  lora_delta,
                  [(8, Hd_l), (n_lp, Hd_l), (n_lp, V_l), (8, R_l)],
                  dtypes=[dt, jnp.float32, jnp.float32, jnp.int32],
                  in_specs=(P("dp"), P(), P(), P("dp")))
        # loud-failure half: misaligned and over-budget geometries for
        # all three new kernels must RAISE at trace time
        for nm, bad_fn in (
                ("chunk_v=100 misaligned",
                 lambda: check_chunk_geometry(100, 768)),
                ("chunk_v=1<<24 over-budget",
                 lambda: check_chunk_geometry(1 << 24, 8192)),
                ("glu block_t=7 misaligned",
                 lambda: check_glu_geometry(7, 128, 4096)),
                ("glu block_f=1<<16 over-budget",
                 lambda: check_glu_geometry(512, 1 << 16, 8192)),
                ("lora block_v=100 misaligned",
                 lambda: check_lora_geometry(8, 4096, 50432, 100)),
                ("lora block_v=1<<20 over-budget",
                 lambda: check_lora_geometry(8, 8192, 50432, 1 << 20))):
            try:
                bad_fn()
            except ValueError as e:
                print(f"  OK   geometry {nm} raises: {str(e)[:60]}",
                      flush=True)
            else:
                ok = False
                print(f"  FAIL geometry gate: {nm} must raise "
                      f"ValueError", flush=True)

    if args.steps:
        print(f"== full bench train steps (single device, exactly what "
              f"bench.py runs), {args.topology} ==", flush=True)
        import bench as bench_mod

        s1 = SingleDeviceSharding(topo.devices[0])

        def to_shape(tree):
            return jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(jnp.shape(x),
                                               jnp.asarray(x).dtype,
                                               sharding=s1), tree)

        # planner-driven configs (PLANNED_BENCHES) build their mesh
        # from the live device count — not single-device-lowerable
        # here; the planner's own pick is AOT-gated in the flagship
        # section below
        for cfg_name in sorted(set(bench_mod.BENCHES)
                               - bench_mod.PLANNED_BENCHES):
            def run(cfg_name=cfg_name):
                state, step, batch, *_ = bench_mod.BENCHES[cfg_name](True)
                return jax.jit(step, donate_argnums=0).lower(
                    to_shape(state), *to_shape(batch))

            report(f"bench step [{cfg_name}]", run)

        # speculative decoding: the vmap-of-while + chunk-verify program
        # is the one control-flow construct no bench config exercises —
        # prove it lowers for the real target (small model, real K)
        def run_spec():
            import functools

            from apex1_tpu.core.policy import get_policy
            from apex1_tpu.models.generate import (llama_decoder,
                                                   speculative_generate)
            from apex1_tpu.models.llama import Llama, LlamaConfig

            cfg_t = LlamaConfig.tiny(policy=get_policy("O2"),
                                     max_seq_len=128, num_layers=4,
                                     hidden_size=256, ffn_size=512,
                                     vocab_size=1024)
            cfg_d = LlamaConfig.tiny(policy=get_policy("O2"),
                                     max_seq_len=128, num_layers=1,
                                     hidden_size=128, ffn_size=256,
                                     vocab_size=1024)
            tgt, drf = Llama(cfg_t), Llama(cfg_d)
            prompt = jnp.zeros((4, 16), jnp.int32)
            # init must be jitted: EAGER pallas on the CPU host under
            # the Mosaic patches fails ("only interpret mode on CPU") —
            # same rule the bench builders follow
            pt = jax.jit(tgt.init)(jax.random.key(0), prompt)["params"]
            pd = jax.jit(drf.init)(jax.random.key(1), prompt)["params"]
            t_fn, mk_t = llama_decoder(tgt)
            d_fn, mk_d = llama_decoder(drf)
            N, K = 32, 4
            spec = functools.partial(
                speculative_generate, t_fn, pt, d_fn, pd,
                max_new_tokens=N, num_draft=K, vocab_size=1024)
            return jax.jit(spec).lower(
                to_shape(prompt),
                target_cache=to_shape(mk_t(4, 16 + N + K + 1)),
                draft_cache=to_shape(mk_d(4, 16 + N + K + 1)))

        report("speculative decode [vmap-of-while, chunk-verify]",
               run_spec)

    if args.collectives:
        print(f"== distributed shard_map programs (ICI collectives + "
              f"Mosaic), {args.topology} ==", flush=True)
        from apex1_tpu.core.mesh import make_mesh
        from apex1_tpu.parallel.ring_attention import ring_attention
        from apex1_tpu.parallel.ulysses import ulysses_attention
        from apex1_tpu.transformer.moe import (MoEConfig,
                                               moe_shard_map_apply)
        from apex1_tpu.transformer.pipeline_parallel.schedules import (
            pipeline_apply)

        def coll(name, builder):
            def run():
                f, arrs = builder()
                return jax.jit(f).lower(*arrs)
            report(name, run)

        B, H, S, D = 2, 16, 4096, 128   # S is GLOBAL (sharded over cp=n)
        cp_mesh = make_mesh(cp=n, dp=1, devices=list(topo.devices))

        def mk_attn(kind):
            def builder():
                qs = NamedSharding(cp_mesh, P(None, None, "cp"))
                arrs = [jax.ShapeDtypeStruct((B, H, S, D), jnp.bfloat16,
                                             sharding=qs)] * 3

                def local(q, k, v):
                    with force_impl("pallas"):
                        if kind == "ring":
                            return ring_attention(q, k, v, "cp",
                                                  causal=True)
                        return ulysses_attention(q, k, v, "cp",
                                                 causal=True)

                # DEFAULT check_vma (True): guards the vma
                # declaration on pallas_call out_shapes (review r5 —
                # with check_vma=False here, the shipped-default
                # config was untraceable and no gate caught it)
                f = jax.shard_map(local, mesh=cp_mesh,
                                  in_specs=(P(None, None, "cp"),) * 3,
                                  out_specs=P(None, None, "cp"))
                return f, arrs
            return builder

        coll(f"ring attention cp={n} (S={S} global)", mk_attn("ring"))
        coll(f"ulysses attention cp={n} (S={S} global)", mk_attn("uly"))

        # --- communication-overlap probes (apex1_tpu.testing.hlo_probe):
        # the double-buffered ring's pinned property — every scan body
        # issues collective-permute-start BEFORE the attention compute
        # and consumes -done AFTER it — asserted on the OPTIMIZED v5e
        # executable text, forward AND backward, with the retained
        # serialized ring as the negative control (the probe must be
        # falsifiable). This AOT gate is the REAL guard for the TPU
        # ring path: on the CPU suite the Pallas ring only executes in
        # interpret mode under check_vma=False (VERDICT r5 Weak #7) —
        # see testing/hlo_probe.py STANDING-RISK NOTE.
        from apex1_tpu.parallel.ring_attention import (ring_attention,
                                                       ring_attention_serial)
        from apex1_tpu.testing.hlo_probe import (assert_collective_overlap,
                                                 check_collective_overlap)

        def probe(name, build_fn, *, expect_fail=False):
            nonlocal ok
            try:
                f, arrs = build_fn()
                txt = jax.jit(f).lower(*arrs).compile().as_text()
                if expect_fail:
                    rep = check_collective_overlap(txt)
                    if rep.ok or not rep.bodies:
                        raise AssertionError(
                            f"negative control must FAIL the probe, got "
                            f"ok={rep.ok} bodies={len(rep.bodies)}")
                    print(f"  OK   {name:48s} FAILS probe as required",
                          flush=True)
                else:
                    rep = assert_collective_overlap(txt,
                                                    expect_mode="async")
                    det = "; ".join(b.detail for b in rep.bodies)
                    print(f"  OK   {name:48s} {det[:70]}", flush=True)
            except Exception as e:
                ok = False
                print(f"  FAIL {name}: {type(e).__name__}: "
                      f"{str(e)[:300]}", flush=True)

        Bp, Hp, Sp, Dp = 1, 4, 4096, 128
        cp_spec = P(None, None, "cp")
        psh = NamedSharding(cp_mesh, cp_spec)
        parrs = [jax.ShapeDtypeStruct((Bp, Hp, Sp, Dp), jnp.bfloat16,
                                      sharding=psh)] * 3

        def ring_fwd_builder():
            def local(q, k, v):
                with force_impl("pallas"):
                    return ring_attention(q, k, v, "cp", causal=True)
            return jax.shard_map(local, mesh=cp_mesh,
                                 in_specs=(cp_spec,) * 3,
                                 out_specs=cp_spec), parrs

        def ring_bwd_builder():
            def local(q, k, v):
                with force_impl("pallas"):
                    return ring_attention(q, k, v, "cp", causal=True)
            sm = jax.shard_map(local, mesh=cp_mesh,
                               in_specs=(cp_spec,) * 3,
                               out_specs=cp_spec)

            def loss(q, k, v):
                return jnp.sum(sm(q, k, v).astype(jnp.float32) ** 2)

            return jax.grad(loss, argnums=(0, 1, 2)), parrs

        def ring_serial_builder():
            def local(q, k, v):
                with force_impl("pallas"):
                    return ring_attention_serial(q, k, v, "cp",
                                                 causal=True)
            return jax.shard_map(local, mesh=cp_mesh,
                                 in_specs=(cp_spec,) * 3,
                                 out_specs=cp_spec), parrs

        probe(f"overlap probe: ring fwd cp={n}", ring_fwd_builder)
        probe(f"overlap probe: ring fwd+bwd cp={n}", ring_bwd_builder)
        probe(f"overlap probe: serialized ring (negative)",
              ring_serial_builder, expect_fail=True)

        # --- fused comm-kernels (PR 9, ops.fused_collective): Mosaic
        # lowering + async overlap probes for the forms tier-1 can only
        # execute in interpret mode. Positive/negative pairs per the
        # probe-falsifiability rule. The RDMA kernel below has NO
        # XLA collective at all — its gate is the compile itself
        # (numerics UNVERIFIED until the hardware window runs
        # tools/bench_fused_comm.py --rdma).
        from apex1_tpu.ops.fused_collective import (
            all_gather_flash_attention, fused_all_gather_matmul,
            fused_all_gather_matmul_serial, fused_matmul_reduce_scatter,
            matmul_reduce_scatter_rdma)

        tp_mesh3 = make_mesh(tp=n, dp=1, devices=list(topo.devices))
        S_f, hid_f, ffn_f = 8192, 2048, 8192
        ns3 = lambda spec: NamedSharding(tp_mesh3, spec)
        fused_arrs = [
            jax.ShapeDtypeStruct((S_f, hid_f), jnp.bfloat16,
                                 sharding=ns3(P("tp"))),
            jax.ShapeDtypeStruct((hid_f, ffn_f), jnp.bfloat16,
                                 sharding=ns3(P(None, "tp"))),
            jax.ShapeDtypeStruct((ffn_f, hid_f), jnp.bfloat16,
                                 sharding=ns3(P("tp", None))),
        ]

        def fused_mlp_builder():
            def local(x, w1, w2):
                with force_impl("pallas"):
                    h = fused_all_gather_matmul(x, w1, "tp", 0)
                    return fused_matmul_reduce_scatter(
                        h.astype(jnp.bfloat16), w2, "tp", 0)

            f = jax.shard_map(
                local, mesh=tp_mesh3,
                in_specs=(P("tp"), P(None, "tp"), P("tp", None)),
                out_specs=P("tp"), check_vma=False)
            return f, fused_arrs

        def fused_serial_builder():
            def local(x, w1):
                with force_impl("pallas"):
                    return fused_all_gather_matmul_serial(x, w1, "tp", 0)

            f = jax.shard_map(
                local, mesh=tp_mesh3,
                in_specs=(P("tp"), P(None, "tp")),
                out_specs=P(None, "tp"), check_vma=False)
            return f, fused_arrs[:2]

        probe(f"overlap probe: fused SP matmuls tp={n}",
              fused_mlp_builder)
        probe("overlap probe: serialized fused AG-matmul (negative)",
              fused_serial_builder, expect_fail=True)

        def agf_builder():
            # the 16k GQA llama_longctx target shape, merge fused into
            # the kernel epilogue
            def local(q, k, v):
                with force_impl("pallas"):
                    return all_gather_flash_attention(q, k, v, "cp",
                                                      causal=True)
            return jax.shard_map(local, mesh=cp_mesh,
                                 in_specs=(cp_spec,) * 3,
                                 out_specs=cp_spec,
                                 check_vma=False), [
                jax.ShapeDtypeStruct((1, 32, 16384, 64), jnp.bfloat16,
                                     sharding=NamedSharding(cp_mesh,
                                                            cp_spec)),
                jax.ShapeDtypeStruct((1, 4, 16384, 64), jnp.bfloat16,
                                     sharding=NamedSharding(cp_mesh,
                                                            cp_spec)),
                jax.ShapeDtypeStruct((1, 4, 16384, 64), jnp.bfloat16,
                                     sharding=NamedSharding(cp_mesh,
                                                            cp_spec))]

        probe(f"overlap probe: fused AG-flash 16k GQA cp={n}",
              agf_builder)

        def agf_bwd_builder():
            f, arrs = agf_builder()

            def loss(q, k, v):
                return jnp.sum(f(q, k, v).astype(jnp.float32) ** 2)

            return jax.grad(loss, argnums=(0, 1, 2)), arrs

        probe(f"overlap probe: fused AG-flash fwd+bwd cp={n}",
              agf_bwd_builder)

        def fused_vp_ce_builder():
            # packed-stat kernel + 2-collective merge, Mosaic-lowered
            from apex1_tpu.transformer.tensor_parallel.cross_entropy \
                import vocab_parallel_linear_cross_entropy
            T, Hd, V = 8192, 2048, 50432

            def local(x, w, t):
                with force_impl("pallas"):
                    return vocab_parallel_linear_cross_entropy(
                        x, w, t, axis_name="tp", fused=True,
                        num_classes=V - 200)

            f = jax.shard_map(local, mesh=tp_mesh3,
                              in_specs=(P(), P("tp", None), P()),
                              out_specs=P(), check_vma=False)
            arrs = [jax.ShapeDtypeStruct((T, Hd), jnp.bfloat16,
                                         sharding=ns3(P())),
                    jax.ShapeDtypeStruct((V, Hd), jnp.bfloat16,
                                         sharding=ns3(P("tp", None))),
                    jax.ShapeDtypeStruct((T,), jnp.int32,
                                         sharding=ns3(P()))]
            return f, arrs

        coll(f"fused vocab-parallel linear CE tp={n} (packed merge)",
             fused_vp_ce_builder)

        def rdma_builder():
            def local(x, w):
                with force_impl("pallas"):
                    return matmul_reduce_scatter_rdma(x, w, "tp")

            f = jax.shard_map(local, mesh=tp_mesh3,
                              in_specs=(P(None, "tp"), P("tp", None)),
                              out_specs=P("tp", None), check_vma=False)
            # per-shard (S=1024, K=1024, N=512): chunk 256 -> frame =
            # 2 send + 2 recv fp32 slots (2 MiB) + double-buffered
            # x/w/out blocks ~ 6 MiB, inside the v5e budget. The
            # kernel's VMEM rule (established BY this gate, now CODE in
            # apex1_tpu.vmem_model.rdma_check — shared with
            # tuning.registry's gating and graftlint's APX208 pass):
            # chunk=512, K=1024, N=1024 measured RESOURCE_EXHAUSTED.
            from apex1_tpu.vmem_model import budget_bytes, rdma_check
            fits, est = rdma_check(
                256, 1024, 512, 2,
                budget_bytes(_gen_from_topology(args.topology)))
            over, _ = rdma_check(512, 1024, 1024, 2,
                                 budget_bytes("v5e"))
            assert fits and not over, (
                "vmem_model.rdma_check disagrees with the gate's "
                "established data points — the shared sizing model "
                f"drifted (fits={fits} est={est} over={over})")
            arrs = [jax.ShapeDtypeStruct((1024, 1024 * n), jnp.bfloat16,
                                         sharding=ns3(P(None, "tp"))),
                    jax.ShapeDtypeStruct((1024 * n, 512), jnp.bfloat16,
                                         sharding=ns3(P("tp", None)))]
            return f, arrs

        coll(f"RDMA matmul->reduce-scatter kernel tp={n} (compile "
             f"gate; numerics await hardware)", rdma_builder)

        def tp_overlap_builder():
            # chunk-pipelined decomposed collective matmuls (the
            # overlap= path of Column/RowParallelLinear under SP)
            from apex1_tpu.transformer.tensor_parallel import mappings
            tp_mesh2 = make_mesh(tp=n, dp=1, devices=list(topo.devices))
            S_l, hid, ffn = 2048, 1024, 4096

            def local(x, w1, w2):
                h = mappings.all_gather_matmul(x, w1, "tp", 0)
                return mappings.matmul_reduce_scatter(
                    h.astype(jnp.bfloat16), w2, "tp", 0)

            f = jax.shard_map(
                local, mesh=tp_mesh2,
                in_specs=(P("tp"), P(None, "tp"), P("tp", None)),
                out_specs=P("tp"), check_vma=False)
            ns = lambda spec: NamedSharding(tp_mesh2, spec)
            arrs = [
                jax.ShapeDtypeStruct((S_l * n, hid), jnp.bfloat16,
                                     sharding=ns(P("tp"))),
                jax.ShapeDtypeStruct((hid, ffn), jnp.bfloat16,
                                     sharding=ns(P(None, "tp"))),
                jax.ShapeDtypeStruct((ffn, hid), jnp.bfloat16,
                                     sharding=ns(P("tp", None))),
            ]
            return f, arrs

        probe(f"overlap probe: decomposed TP matmuls tp={n}",
              tp_overlap_builder)

        def moe_builder():
            ep_mesh = make_mesh(ep=n, dp=1, devices=list(topo.devices))
            cfg = MoEConfig(num_experts=2 * n, top_k=2,
                            capacity_factor=1.25, hidden_size=2048,
                            ffn_size=5632)
            xs = NamedSharding(ep_mesh, P("ep"))
            ws = NamedSharding(ep_mesh, P("ep"))
            arrs = [
                jax.ShapeDtypeStruct((8192 * n, 2048), jnp.bfloat16,
                                     sharding=xs),
                jax.ShapeDtypeStruct((2048, 2 * n), jnp.float32,
                                     sharding=NamedSharding(ep_mesh, P())),
                jax.ShapeDtypeStruct((2 * n, 2048, 5632), jnp.bfloat16,
                                     sharding=ws),
                jax.ShapeDtypeStruct((2 * n, 5632, 2048), jnp.bfloat16,
                                     sharding=ws),
            ]

            def local(x, wg, w1, w2):
                y, aux = moe_shard_map_apply(x, wg, w1, w2, cfg)
                return y, jax.lax.pmean(aux, "ep")

            f = jax.shard_map(local, mesh=ep_mesh,
                              in_specs=(P("ep"), P(), P("ep"), P("ep")),
                              out_specs=(P("ep"), P()), check_vma=False)
            return f, arrs

        coll(f"MoE all_to_all ep={n} (8k tok/dev, H=2048)", moe_builder)

        def pp_builder():
            pp_mesh = make_mesh(pp=n, dp=1, devices=list(topo.devices))
            M, mb, hid = 2 * n, 2, 1024
            ps = NamedSharding(pp_mesh, P(None, "pp"))

            def stage_fn(p, x):
                return jnp.tanh(x @ p)

            def local(chunk_params, mbs):
                local_p = chunk_params[:, 0]   # (V=1, hid, hid)
                outs = pipeline_apply(stage_fn, local_p, mbs,
                                      num_chunks=1)
                return jnp.sum(outs.astype(jnp.float32))

            f = jax.shard_map(
                local, mesh=pp_mesh,
                in_specs=(P(None, "pp"), P()), out_specs=P(),
                check_vma=False)
            arrs = [jax.ShapeDtypeStruct((1, n, hid, hid), jnp.float32,
                                         sharding=ps),
                    jax.ShapeDtypeStruct((M, mb, hid), jnp.float32,
                                         sharding=NamedSharding(pp_mesh,
                                                                P()))]
            return f, arrs

        coll(f"pipeline scan+ppermute pp={n}", pp_builder)

        def tp_sp_builder():
            # phase-1 core of the driver dryrun: Megatron TP + sequence
            # parallelism fwd+bwd (all-gather fwd / reduce-scatter bwd
            # pairs + psum) in one integrated program
            from apex1_tpu.transformer.tensor_parallel import layers as tpl
            tp_mesh = make_mesh(tp=n, dp=1, devices=list(topo.devices))
            S_l, mb, hid, ffn = 512, 4, 2048, 8192  # per-dev seq shard

            def local(x, w1, b1, w2, b2):
                def loss_fn(w1, b1, w2, b2):
                    h = tpl.column_parallel_linear(
                        x, w1, b1, sequence_parallel_enabled=True)
                    h = jax.nn.gelu(h)
                    h = tpl.row_parallel_linear(
                        h, w2, bias=b2, sequence_parallel_enabled=True)
                    return jnp.sum(h.astype(jnp.float32))

                g_w1, g_b1, g_w2, g_b2 = jax.grad(
                    loss_fn, argnums=(0, 1, 2, 3))(w1, b1, w2, b2)
                # replicated b2 under SP: local db2 sums only the local
                # seq shard — psum completes it (and puts the psum
                # collective on the lowered path, per the section name)
                return g_w1, g_b1, g_w2, jax.lax.psum(g_b2, "tp")

            f = jax.shard_map(
                local, mesh=tp_mesh,
                in_specs=(P("tp"), P(None, "tp"), P("tp"),
                          P("tp", None), P()),
                out_specs=(P(None, "tp"), P("tp"), P("tp", None), P()),
                check_vma=False)
            ns = lambda spec: NamedSharding(tp_mesh, spec)
            arrs = [
                jax.ShapeDtypeStruct((S_l * n, mb, hid), jnp.bfloat16,
                                     sharding=ns(P("tp"))),
                jax.ShapeDtypeStruct((hid, ffn), jnp.bfloat16,
                                     sharding=ns(P(None, "tp"))),
                jax.ShapeDtypeStruct((ffn,), jnp.bfloat16,
                                     sharding=ns(P("tp"))),
                jax.ShapeDtypeStruct((ffn, hid), jnp.bfloat16,
                                     sharding=ns(P("tp", None))),
                jax.ShapeDtypeStruct((hid,), jnp.bfloat16,
                                     sharding=ns(P())),
            ]
            return f, arrs

        coll(f"TP+SP column/row linear fwd+bwd tp={n}", tp_sp_builder)

    if args.flagship:
        # BASELINE config 4 at target scale: Llama-3-8B full 3D train
        # step (dp x pp x tp + SP + remat + fused Adam) against a
        # v5p-32-class topology — OOMs surface HERE, not on hardware
        ftopo_name = args.flagship_topology
        print(f"== flagship: Llama-3-8B dp2 x pp2 x tp4 (+SP, remat) "
              f"train step, {ftopo_name} ==", flush=True)
        from apex1_tpu.core.mesh import make_mesh as mk
        from apex1_tpu.core.policy import get_policy
        from apex1_tpu.models.llama import LlamaConfig
        from apex1_tpu.models.llama_3d import (Llama3DConfig,
                                               abstract_state, build_step)

        from apex1_tpu.core import capability as _cap

        os.environ["PALLAS_AXON_TPU_GEN"] = _gen_from_topology(ftopo_name)
        # earlier sections cached the --topology generation; the Pallas
        # block planners must see the flagship chip's VMEM budget
        _cap.detect_generation.cache_clear()
        ftopo = topologies.get_topology_desc(platform="tpu",
                                             topology_name=ftopo_name)
        fn_dev = len(ftopo.devices)
        # dp=2 fixed; tp bounded by the 8 kv heads; pp >= 2 so the
        # pipeline axis is actually exercised
        cands = [t for t in (1, 2, 4, 8)
                 if fn_dev % (2 * t) == 0 and fn_dev // (2 * t) >= 2]
        if not cands:
            raise SystemExit(f"--flagship-topology needs >= 8 chips with "
                             f"even count, got {fn_dev}")
        tp = max(cands)
        dp = 2
        pp = fn_dev // (dp * tp)
        gen = _gen_from_topology(ftopo_name)
        print(f"   mesh dp={dp} pp={pp} tp={tp} over {fn_dev} chips",
              flush=True)
        # 8B defaults, bf16 compute, per-layer remat
        mcfg = LlamaConfig(policy=get_policy("O2"), remat=True)
        fcfg = Llama3DConfig(model=mcfg, dp=dp, pp=pp, tp=tp,
                             num_microbatches=max(4, 2 * pp),
                             microbatch_size=1)
        fmesh = mk(dp=dp, pp=pp, tp=tp, devices=list(ftopo.devices),
                   allow_split_physical_axes=True)

        def flagship_run():
            step, _, _, _ = build_step(fcfg, fmesh)
            state, data = abstract_state(fcfg, fmesh)
            return step.lower(state, data, data)

        report(f"flagship 8B train step ({gen} x{fn_dev})", flagship_run)

        # PLANNER GATE (ROADMAP item 1): the auto-parallel planner's
        # OWN 8B pick for this topology, AOT-lowered so XLA's memory
        # analysis verifies what the analytic pre-filter promised —
        # the planner must never queue an unverified layout into a
        # hardware window. dp/pp/tp family only: the gate guards the
        # search's HBM arithmetic, not every axis composition (cp/ep
        # lowering is covered by the dedicated sections above/below).
        from apex1_tpu import planner as _planner

        pshape = _planner.ModelShape.from_llama(
            mcfg, global_batch=2 * fn_dev // max(2, tp),
            name="llama8b")
        pplan = _planner.make_plan(pshape, fn_dev, generation=gen,
                                   allow_cp=False, allow_ep=False,
                                   allow_zero=False)
        pm = pplan["mesh"]
        print(f"   planner pick dp={pm['dp']} pp={pm['pp']} "
              f"tp={pm['tp']} "
              f"M={pplan['schedule']['num_microbatches']}: analytic "
              f"{pplan['memory']['total']:.1f} of "
              f"{pplan['memory']['budget']:.1f} GiB/chip, "
              f"{pplan['predicted']['calibrated_step_ms']:.1f} ms/step "
              f"calibrated", flush=True)
        pcfg = _planner.llama3d_config_from_plan(pplan, mcfg)
        pmesh = mk(dp=pm["dp"], pp=pm["pp"], tp=pm["tp"],
                   devices=list(ftopo.devices),
                   allow_split_physical_axes=True)

        def planner_run():
            step, _, _, _ = build_step(pcfg, pmesh)
            state, data = abstract_state(pcfg, pmesh)
            return step.lower(state, data, data)

        report(f"planner 8B pick dp{pm['dp']} pp{pm['pp']} "
               f"tp{pm['tp']} ({gen} x{fn_dev})", planner_run)

        # BASELINE config 5 at scale: 8B LONG-CONTEXT — sequence 32k
        # sharded over cp (ring attention inside the same step)
        lc_cfg = Llama3DConfig(
            model=LlamaConfig(policy=get_policy("O2"), remat=True,
                              max_seq_len=32768),
            dp=1, pp=2, cp=2, tp=fn_dev // 4, num_microbatches=4,
            microbatch_size=1)
        lc_mesh = mk(dp=1, pp=2, cp=2, tp=fn_dev // 4,
                     devices=list(ftopo.devices),
                     allow_split_physical_axes=True)

        def longctx_run():
            step, _, _, _ = build_step(lc_cfg, lc_mesh)
            state, data = abstract_state(lc_cfg, lc_mesh)
            return step.lower(state, data, data)

        report(f"flagship 8B long-ctx S=32k cp2 ({gen} x{fn_dev})",
               longctx_run)

        # the same 8B step on the INTERLEAVED true 1F1B schedule (V=2
        # group-cycled chunks, recirculation FIFOs, residual ring) —
        # proves the staggered-scan schedule lowers through Mosaic at
        # production scale, not just on the CPU test mesh
        il_cfg = Llama3DConfig(model=mcfg, dp=dp, pp=pp, tp=tp,
                               num_microbatches=2 * pp,
                               microbatch_size=1, num_chunks=2,
                               schedule="1f1b")

        def interleaved_run():
            step, _, _, _ = build_step(il_cfg, fmesh)
            state, data = abstract_state(il_cfg, fmesh)
            return step.lower(state, data, data)

        report(f"flagship 8B interleaved-1F1B V=2 ({gen} x{fn_dev})",
               interleaved_run)

        # SELECTIVE recompute (Megatron --recompute-activations) on both
        # schedules — the source rows of docs/parallel.md's schedule x
        # remat memory table; keep them reproducible by this command
        import dataclasses as _dc
        sel_m = _dc.replace(
            mcfg, remat_policy="dots_with_no_batch_dims_saveable")
        for sname, base in (("scan", fcfg), ("interleaved-1F1B V=2",
                                             il_cfg)):
            sel_cfg = _dc.replace(base, model=sel_m)

            def sel_run(cfg_=sel_cfg):
                step, _, _, _ = build_step(cfg_, fmesh)
                state, data = abstract_state(cfg_, fmesh)
                return step.lower(state, data, data)

            report(f"flagship 8B {sname} + selective remat "
                   f"({gen} x{fn_dev})", sel_run)
        # analytic per-stage parameter budget (SPMD allocates the
        # pp-replicated embedding/head on every stage)
        m = fcfg.model
        lay = sum(int(np.prod(s)) for s in (
            (m.hidden_size, m.num_heads * m.head_dim),
            (m.hidden_size, m.num_kv_heads * m.head_dim),
            (m.hidden_size, m.num_kv_heads * m.head_dim),
            (m.num_heads * m.head_dim, m.hidden_size),
            (m.hidden_size, m.ffn_size),
            (m.hidden_size, m.ffn_size),
            (m.ffn_size, m.hidden_size)))
        # layers_per_stage is per (chunk, stage) slot — a stage holds
        # num_chunks of them
        per_stage = lay * fcfg.layers_per_stage * fcfg.num_chunks / tp
        embhead = 2 * m.vocab_size * m.hidden_size / tp
        f32x3 = 12 / 2**30  # master + 2 moments, fp32 bytes
        from apex1_tpu.core.capability import get_capability
        hbm = get_capability(gen).hbm_bytes / 2**30
        print(f"       per-stage params/chip: blocks "
              f"{per_stage * f32x3:5.2f} GiB, emb+head "
              f"{embhead * f32x3:5.2f} GiB (fp32 x3 opt); chip HBM "
              f"{hbm:.0f} GiB ({gen})", flush=True)

    print("ALL OK" if ok else "FAILURES PRESENT", flush=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
