#!/bin/bash
# Full pre-hardware validation: unit/parity suite on the virtual CPU
# mesh, driver entry points, and AOT Mosaic/HBM checks for the real TPU
# target. Exits non-zero on any failure.
set -e
cd "$(dirname "$0")/.."
echo "== pytest (8-device virtual CPU mesh) =="
python -m pytest tests/ -q
echo "== driver entry points =="
python - <<'EOF'
import os
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
import __graft_entry__ as g
fn, args = g.entry()
jax.jit(fn)(*args)
print("entry OK")
g.dryrun_multichip(8)
EOF
echo "== AOT Mosaic + HBM checks (v5e) =="
python tools/aot_check.py
echo "ALL CHECKS PASSED"
