#!/bin/bash
# Pre-hardware validation: unit/parity suite on the virtual CPU mesh,
# driver entry points, and AOT Mosaic/HBM checks for the real TPU
# target. Exits non-zero on any failure.
#
# Default = the FAST gate: pytest -m "not slow" (<5 min warm) — the
# check-everything habit should never cost half an hour. Pass --all to
# run the composed-step/fuzz suites too (CI cadence / pre-commit on
# pipeline/3D changes).
#
# Wall-time note (VERDICT r3 Weak #5): the full suite is XLA-compile-
# bound. Measured r4: 450 tests in 26:56 on a SINGLE core. On a
# multi-core machine WITH pytest-xdist installed, shard explicitly:
# `pytest -n auto --maxprocesses=4 tests/` (no longer in pytest.ini
# addopts — images without xdist must still run plain `pytest tests/`;
# see the pytest.ini note).
set -e
cd "$(dirname "$0")/.."
echo "== graftlint kernels (APX1xx + APX2xx: JAX hazards, Pallas semaphore/DMA protocol model-check n=1..6, mesh/axis consistency, shared-VMEM budgets; jax-free; docs/lint.md) =="
# --kernels is a strict superset of the plain run (all APX1xx rules +
# the kernel analyzer), so ONE step gates both families
python tools/lint.py --kernels
echo "== graftlint protocols (APX3xx: bounded exhaustive model check of the scheduler/replica/frontend/disagg/autopilot protocols, every interleaving of every bounded config; jax-free, <15s budget; docs/lint.md) =="
python tools/lint.py --protocols
echo "== tuning tables (parse + per-capability VMEM-budget validity) =="
python tools/tune_kernels.py --validate
echo "== drift gate (calibrated_ratio bands + re-fit drift over the banked perf_results corpus; jax-free, fail-closed) =="
python tools/check_drift.py
echo "== chaos smoke (injected-NaN rollback + corrupt-ckpt fallback, CPU) =="
JAX_PLATFORMS=cpu python -m apex1_tpu.testing.chaos --smoke
echo "== serving chaos smoke (replica-kill token parity + poison quarantine, CPU) =="
JAX_PLATFORMS=cpu python -m apex1_tpu.testing.chaos --serve-smoke
echo "== elastic drill (8->4 mid-run shrink: planner re-plan + manifest-verified reshard, bit-exact vs the 4-dev control, episode from banked events; CPU) =="
JAX_PLATFORMS=cpu python -m apex1_tpu.resilience.elastic --drill
echo "== autopilot smoke (static ladder sweep misses SLO, autopilot holds it, replay bit-identical; CPU) =="
JAX_PLATFORMS=cpu python -m apex1_tpu.autopilot --smoke
echo "== disagg smoke (1+1 pool drill: manifest-verified handoff parity + radix hit skips prefill + handoff-window kill re-routes; CPU) =="
JAX_PLATFORMS=cpu python -m apex1_tpu.serving.disagg --smoke
echo "== obs smoke (CPU trace -> per-op report -> calibration fit, non-empty) =="
JAX_PLATFORMS=cpu python -m apex1_tpu.obs --smoke
echo "== planner smoke (enumerate -> price -> emit -> llama_3d dryrun from the plan, CPU mesh) =="
JAX_PLATFORMS=cpu python -m apex1_tpu.planner --smoke
if [ "${1:-}" = "--all" ]; then
  echo "== pytest (8-device virtual CPU mesh, FULL suite) =="
  python -m pytest tests/ -q
else
  echo "== pytest (8-device virtual CPU mesh, fast subset; --all for full) =="
  python -m pytest tests/ -q -m "not slow"
fi
echo "== driver entry points =="
python - <<'EOF'
import os
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
import __graft_entry__ as g
fn, args = g.entry()
jax.jit(fn)(*args)
print("entry OK")
g.dryrun_multichip(8)
EOF
echo "== paged parity drill (Pallas paged-attention + fused sampler vs the XLA-composed reference: bf16 + int8 pages, decode + verify shapes, tokens bitwise at T in {0, 0.7, 1.3}; CPU interpret, real Mosaic on TPU) =="
JAX_PLATFORMS=cpu python -m apex1_tpu.ops.paged_decode --drill
echo "== multi-tenant LoRA parity drill (adapter-page store lifecycle + one batch mixing two adapters and an adapterless control bitwise vs per-tenant solo runs, dense and paged-kernel epilogues; CPU interpret, real Mosaic on TPU) =="
JAX_PLATFORMS=cpu python -m apex1_tpu.serving.lora
echo "== serving engine smoke (CPU: correctness + two-executable gate + radix-hit/speculative goodput-multiplier rows with token parity + paged A/B with per-phase attribution + single- vs multi-tenant LoRA A/B) =="
python tools/bench_serving.py --smoke --lora-tenants 2 > /dev/null
echo "== hlo overlap probe (ring fwd+bwd vs serialized, CPU-compiled) =="
python -m apex1_tpu.testing.hlo_probe
echo "== AOT Mosaic + HBM checks (v5e; incl. async overlap probes) =="
python tools/aot_check.py
echo "ALL CHECKS PASSED"
