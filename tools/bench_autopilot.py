#!/usr/bin/env python
"""bench_autopilot — bank a goodput / SLO-attainment record per
replayed fleet trace (static baseline vs autopilot), CPU-only.

Runs the replayable fleet simulator (`apex1_tpu.testing.fleetsim`)
over the stated trace kinds — bursty, diurnal, adversarial_overload —
twice each: once with the static threshold-ladder frontend (the
drill's ``static-default`` arm), once with the autopilot attached.
Each trace's record is banked IMMEDIATELY via
``manifest.atomic_write_json`` (kill-safe: a partial sweep keeps every
completed row), carrying per-class offered/done/full counts, SLO
attainment at the drill's guaranteed-class target, goodput in
tokens per VIRTUAL second, the actuation count, and the episode
fingerprint (the bit-determinism handle: a reproduced run must match
it exactly).

EVERY number here is simulator evidence — virtual-clock queueing
behavior over the toy decoder, ``[sim]``-labelled. It scores control
policy (detection, actuation, SLO arithmetic), never silicon; nothing
in this record feeds calibration (docs/autopilot.md, "what the
simulator proves").

Usage::

    python tools/bench_autopilot.py [--traces bursty,diurnal,...]
        [--seed 20260804] [--scale 1.0] [--horizon 6.0]
        [--out perf_results/bench_autopilot_cpu.json]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_TRACES = ("bursty", "diurnal", "adversarial_overload")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--traces", default=",".join(DEFAULT_TRACES))
    ap.add_argument("--seed", type=int, default=20260804)
    ap.add_argument("--scale", type=float, default=1.0,
                    help="arrival-rate multiplier")
    ap.add_argument("--horizon", type=float, default=6.0,
                    help="trace horizon (virtual seconds)")
    ap.add_argument("--out", default=os.path.join(
        REPO, "perf_results", "bench_autopilot_cpu.json"))
    args = ap.parse_args(argv)

    from apex1_tpu.testing import (enable_persistent_compilation_cache,
                                   force_virtual_cpu_devices)

    force_virtual_cpu_devices(1)
    enable_persistent_compilation_cache()

    from apex1_tpu.autopilot import drill
    from apex1_tpu.resilience.manifest import atomic_write_json
    from apex1_tpu.testing.fleetsim import run_fleet, synthetic_trace

    doc = {"schema": "apex1-bench-autopilot-v1",
           "metric": "fleetsim goodput/SLO [sim]",
           "note": "virtual-clock simulator evidence — scores control "
                   "policy, not silicon; excluded from calibration",
           "seed": args.seed, "scale": args.scale,
           "slo": {"class": "guaranteed",
                   "latency_s": drill.SLO_LATENCY_S,
                   "attainment": drill.SLO_ATTAINMENT},
           "generated_unix": round(time.time(), 1), "rows": []}

    for kind in [t.strip() for t in args.traces.split(",") if t.strip()]:
        if kind == "adversarial_overload":
            trace = drill.overload_trace(args.seed, scale=args.scale,
                                         horizon_s=args.horizon)
        else:
            trace = synthetic_trace(kind, seed=args.seed,
                                    horizon_s=args.horizon,
                                    base_rate=25.0 * args.scale)
        row = {"trace": kind, "n_arrivals": len(trace.requests),
               "trace_fingerprint": trace.fingerprint()}
        for arm, pilot in (("static", None),
                           ("autopilot", drill.autopilot_config(
                               fit_hedge=True))):
            t0 = time.monotonic()
            rep = run_fleet(trace, drill.frontend_config(),
                            sim=drill.sim_config(), autopilot=pilot)
            att = rep.slo_attainment("guaranteed",
                                     drill.SLO_LATENCY_S)
            row[arm] = {**rep.to_json(),
                        "slo_attainment": round(att, 4),
                        "wall_s": round(time.monotonic() - t0, 2)}
            print(f"[{kind:22s}] {arm:9s} attainment {att:6.1%}  "
                  f"goodput {rep.goodput_tok_s():8.1f} tok/vs  "
                  f"actions {len(rep.actions):2d}  "
                  f"({row[arm]['wall_s']}s wall)", flush=True)
        doc["rows"].append(row)
        atomic_write_json(args.out, doc)   # banked per trace: a kill
        #                                    keeps every finished row
        print(f"banked {args.out} ({len(doc['rows'])} row(s))",
              flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
