"""Async-checkpointing overhead A/B — the acceptance number for the
resilient runtime (docs/robustness.md): steady-state step time with
background saves must sit within 5% of the no-checkpoint baseline.

Three loop variants over the SAME jitted (donating) train step:

- ``baseline``: N steps, no checkpointing.
- ``async``: N steps with ``ResilientCheckpointer.save`` at the
  configured cadence — the device-side snapshot + enqueue is the only
  on-loop cost; the host fetch, sha256 manifest, and orbax write run on
  the background worker while later steps train. The queue drain runs
  OUTSIDE the timed region (steady state is the claim; drain is bounded
  by one in-flight save).
- ``sync``: the same cadence through ``save_sync`` — the save-step
  samples (the steps that paid a full synchronous write) report the
  cost the async path is hiding, per save.

Measurement protocol: the three variants run interleaved across
``--rounds`` adjacent rounds (async first, fully drained before the
round's baseline starts, so no background work leaks across segments);
every individual step is blocked on and timed, and the headline is
the median over rounds of the per-round ratio of median step times
(async/baseline) — on a shared 2-core CI box background load both
spikes (single slow steps) and sustained shifts (slow seconds) swing
wall clocks 3x, so single A/Bs are noise; the within-round median
rejects spikes, the within-round ratio cancels shifts, and the
across-round median rejects rounds a shift split in half.

The save CADENCE is part of the claim: the interval must exceed one
save's duration (~0.5 s here; the checkpointer bounds in-flight saves
at one, so a faster cadence degrades toward sync BY DESIGN), as it
does by orders of magnitude at any production cadence. On the CPU
proxy the background fetch/sha256/write contends for the step's own
cores — the TPU number can only be better (the step runs on the
device, the worker on an otherwise idle host).

Emits one JSON line (the queue's tee-to-``perf_results/`` contract):
``value`` = median async overhead in %, plus per-variant median
ms/step and the sync comparison.

Usage: python tools/bench_ckpt_overhead.py [--iters N] [--every K]
       [--rounds R] (CPU proxy: JAX_PLATFORMS=cpu, banked at
       perf_results/ckpt_overhead_cpu.log)
"""

import argparse
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _emit(record):
    print(json.dumps(record), flush=True)


def _build(accel):
    """One jitted (donating) train step + a fresh-state factory, sized
    so a CPU step is ~25 ms. B sets the compute:state ratio — a
    realistic step does far more flops per byte of checkpoint state
    than a toy one, and on the CPU proxy the background worker contends
    for the step's cores, so a too-small step reads as phantom
    checkpoint overhead."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex1_tpu.amp import Amp
    from apex1_tpu.optim.fused_sgd import fused_sgd

    E, depth, B = (1024, 8, 256) if accel else (256, 6, 512)
    rng = np.random.default_rng(0)
    # host-side master copies: each make_state() call uploads FRESH
    # device buffers (the donating step deletes the previous loop's)
    host_params = {f"w{i}": (rng.normal(size=(E, E)) * 0.02
                             ).astype(np.float32)
                   for i in range(depth)}
    x = jnp.asarray(rng.normal(size=(B, E)), jnp.float32)

    def loss_fn(p, x):
        h = x
        for i in range(depth):
            h = jnp.tanh(h @ p[f"w{i}"])
        return jnp.mean(jnp.square(h))

    amp = Amp(tx=fused_sgd(1e-3), opt_level="O0")
    step = jax.jit(amp.make_train_step(loss_fn), donate_argnums=0)

    def make_state():
        return amp.init({k: jnp.asarray(v)
                         for k, v in host_params.items()})

    return step, make_state, x


def _segment(step, make_state, x, iters, *, save_every=None, ck=None,
             sync=False):
    """Per-step wall-clock samples (ms) for one segment — each step is
    blocked on, so a sample covers exactly one step plus whatever save
    cost (enqueue or full sync write) that step incurred. The donation
    + async-save combination is exactly the production hazard the
    checkpointer's device-side snapshot exists for."""
    import jax

    state = make_state()
    state, _ = step(state, x)                 # warmup (compile once)
    jax.block_until_ready(state.params)
    samples = []
    for i in range(iters):
        t0 = time.perf_counter()
        state, _m = step(state, x)
        if save_every and (i + 1) % save_every == 0:
            if sync:
                ck.save_sync(int(i + 1), state,
                             meta={"data_step": i + 1})
            else:
                ck.save(int(i + 1), state, meta={"data_step": i + 1})
        jax.block_until_ready(state.params)
        samples.append((time.perf_counter() - t0) * 1e3)
    return samples


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=80,
                    help="steps per segment")
    ap.add_argument("--every", type=int, default=40,
                    help="save cadence inside the saving segments "
                    "(interval must exceed one save's duration — see "
                    "module docstring)")
    ap.add_argument("--rounds", type=int, default=9,
                    help="adjacent async/baseline/sync rounds; the "
                    "headline is the median of per-round ratios")
    args = ap.parse_args()

    from apex1_tpu.testing import honor_jax_platforms_env
    honor_jax_platforms_env()
    import jax

    from apex1_tpu.resilience import ResilientCheckpointer

    backend = jax.default_backend()
    accel = backend not in ("cpu",)
    step, make_state, x = _build(accel)

    with tempfile.TemporaryDirectory() as d:
        # one untimed shakeout of each variant (compile, allocator,
        # orbax first-save setup) before any timed round
        _segment(step, make_state, x, 4)
        with ResilientCheckpointer(os.path.join(d, "w"), keep=2) as ck:
            _segment(step, make_state, x, 4, save_every=4, ck=ck)
            ck.wait()
        rounds = []
        drains = []
        for r in range(args.rounds):
            row = {}
            with ResilientCheckpointer(os.path.join(d, f"a{r}"),
                                       keep=2) as ck:
                row["async"] = _segment(
                    step, make_state, x, args.iters,
                    save_every=args.every, ck=ck)
                t0 = time.perf_counter()
                ck.wait()               # drain BEFORE baseline starts
                drains.append(time.perf_counter() - t0)
            row["baseline"] = _segment(step, make_state, x, args.iters)
            with ResilientCheckpointer(os.path.join(d, f"s{r}"),
                                       keep=2) as ck:
                row["sync"] = _segment(
                    step, make_state, x, args.iters,
                    save_every=args.every, ck=ck, sync=True)
            rounds.append(row)

    # per-round medians, then the MEDIAN-OF-RATIOS across rounds: the
    # within-round median rejects load spikes, the within-round ratio
    # cancels sustained load shifts (the variants of one round ran
    # adjacent in time), and the across-round median rejects any round
    # where a shift landed mid-round anyway
    rmed = lambda row, k: statistics.median(row[k])
    med = lambda k: statistics.median(rmed(row, k) for row in rounds)
    overhead = statistics.median(
        rmed(row, "async") / rmed(row, "baseline") - 1.0
        for row in rounds)
    # the saving steps themselves: sync pays the full write on-loop
    # (the hidden cost), async pays only the snapshot+enqueue
    save_step = lambda k: statistics.median(
        v for row in rounds
        for p, v in enumerate(row[k])
        if (p + 1) % args.every == 0)
    record = {
        "metric": f"ckpt_overhead [{backend}]",
        "value": round(overhead * 100, 2),
        "unit": "% steady-state step-time overhead (async vs none, "
                "per-step medians over interleaved rounds)",
        "baseline_ms": round(med("baseline"), 3),
        "async_ms": round(med("async"), 3),
        "async_save_step_ms": round(save_step("async"), 3),
        "sync_save_step_ms": round(save_step("sync"), 3),
        "hidden_ms_per_save": round(save_step("sync")
                                    - med("baseline"), 3),
        "drain_s": round(max(drains), 3),
        "saves_per_segment": args.iters // args.every,
        "iters": args.iters, "rounds": args.rounds,
        "pass_5pct": bool(overhead <= 0.05),
    }
    _emit(record)
    if not record["pass_5pct"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
