"""Turn banked profiler traces into per-op device-time breakdowns.

Every on-silicon ``bench.py`` record stamps a ``profile_artifact``
(PR 9): a ``perf_results/profiles/<config>_...`` directory holding the
``*.xplane.pb`` files of one untimed post-measurement dispatch. This
tool parses them with the dependency-free `apex1_tpu.obs.xspace`
walker (no TensorFlow import roulette) and persists a
``trace_report.json`` NEXT TO the trace it describes — Pallas-kernel /
collective / XLA-op buckets, so exposed-ICI time is directly readable
— plus a human table on stdout. A corrupt or truncated trace is a
typed, named error (`obs.xspace.TraceError`), never a traceback.

CPU-rehearsable end-to-end: ``jax.profiler.trace`` works on the CPU
backend (the report is then labelled ``host-xla-proxy`` — shares
meaningful, absolute times host wall-clock; docs/observability.md).

Usage:
    python tools/trace_report.py --trace perf_results/profiles/gpt2_...
    python tools/trace_report.py --log perf_results/bench_gpt2.log
    python tools/trace_report.py --all          # every banked artifact
"""

import argparse
import os
import sys

_TOOLS = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_TOOLS)
sys.path.insert(0, _REPO)

from apex1_tpu.obs import xspace  # noqa: E402
from apex1_tpu.obs.calibrate import json_lines  # noqa: E402


def _records_with_artifacts(results_dir):
    """[(log name, record)] for every banked JSON record carrying a
    ``profile_artifact`` pointer."""
    out = []
    for name in sorted(os.listdir(results_dir)):
        if not (name.startswith("bench_") and name.endswith(".log")):
            continue
        for rec in json_lines(os.path.join(results_dir, name)):
            if rec.get("profile_artifact"):
                out.append((name, rec))
    return out


def report_one(trace_dir, steps=None, top=25):
    """Build + persist + print one report. Returns the report dict."""
    report = xspace.build_report(trace_dir, steps=steps)
    path = xspace.write_report(trace_dir, report=report)
    print(f"== {trace_dir} ==")
    print(xspace.format_report(report, top=top))
    print(f"report banked at {path}")
    return report


def main():
    ap = argparse.ArgumentParser()
    g = ap.add_mutually_exclusive_group(required=True)
    g.add_argument("--trace", help="one trace directory (a banked "
                   "profile_artifact or any jax.profiler.trace output)")
    g.add_argument("--log", help="bench queue log: report the newest "
                   "record's profile_artifact")
    g.add_argument("--all", action="store_true",
                   help="report every banked profile_artifact in "
                   "--results")
    ap.add_argument("--results", default=os.path.join(_REPO,
                                                      "perf_results"))
    ap.add_argument("--steps", type=int, default=None,
                    help="steps the traced dispatch ran (adds ms/step)")
    ap.add_argument("--top", type=int, default=25)
    args = ap.parse_args()

    targets = []
    if args.trace:
        targets = [args.trace]
    elif args.log:
        recs = [r for r in json_lines(args.log)
                if r.get("profile_artifact")]
        if not recs:
            print(f"no record with a profile_artifact in {args.log}")
            return 1
        targets = [recs[-1]["profile_artifact"]]
    else:
        arts = _records_with_artifacts(args.results)
        if not arts:
            print(f"no banked profile_artifact records under "
                  f"{args.results} (none stamped yet — they appear on "
                  f"on-silicon bench runs)")
            return 0   # an empty corpus is a state, not a failure
        targets = sorted({r["profile_artifact"] for _n, r in arts})

    failures = 0
    for t in targets:
        # profile_artifact paths are repo-relative (bench.py stamps
        # them that way so records survive checkout moves)
        tdir = t if os.path.isabs(t) else os.path.join(_REPO, t)
        try:
            report_one(tdir, steps=args.steps, top=args.top)
        except xspace.TraceError as e:
            print(f"SKIP {t}: {e.reason}")
            failures += 1
    if failures:
        print(f"{failures}/{len(targets)} artifact(s) unreadable")
    return 1 if failures == len(targets) else 0


if __name__ == "__main__":
    sys.exit(main())
