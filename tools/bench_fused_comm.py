"""A/B: fused vs decomposed vs serialized communication at the two
fused-comm-kernel sites (`ops.fused_collective`) — the wall-clock form
of what hlo_probe pins structurally and predict_perf's fused comms term
prices analytically.

Legs, timed fwd+bwd over a tp/cp ring:

1. **SP boundary MLP** (column+row parallel linear at the Megatron-SP
   boundary): ``monolithic`` (gather-region + dot / dot +
   reduce-scatter-region — the legacy path), ``decomposed`` (PR 4's
   chunk-pipelined `mappings` rings, ``overlap=True``), ``fused``
   (`fused_all_gather_matmul` + `fused_matmul_reduce_scatter`: same
   ring, per-chunk dot in the Pallas chunk kernel), and ``serialized``
   (`fused_all_gather_matmul_serial`, the rotate-then-dot floor).
2. **ring attention** at the llama_longctx shape: `ring_attention`
   (decomposed merge) vs `all_gather_flash_attention` (merge fused into
   the kernel epilogue) — fwd+bwd.
3. with ``--rdma`` (accelerator, >= 2 devices): the single-kernel
   `matmul_reduce_scatter_rdma` fwd — the first wall-clock datum for
   the paper-shape kernel (numerics UNVERIFIED until this runs; the
   tool also checks its output against the ppermute form and reports
   the max abs diff in the record — the hardware-window parity drill).

Device requirements: a ring needs >= 2 devices; single-chip windows
emit a skip record (rc 0 — the queue must keep moving). On CPU the
8-device virtual mesh auto-builds and shapes shrink (command-line
rehearsal; timings meaningless, plumbing validated). Queued as
``fused_comm_ab`` in tools/tpu_watch.sh AHEAD of the llama_longctx
re-bench.

Usage: python tools/bench_fused_comm.py [--n N] [--iters K] [--rdma]
"""

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _emit(record):
    print(json.dumps(record), flush=True)


def _backend_is_cpu(timeout_s=120.0):
    """Subprocess backend probe — see tools/bench_ring_ab.py (the main
    process must not initialize a backend before deciding whether to
    build the virtual CPU mesh)."""
    import subprocess
    code = ("import os, jax; p = os.environ.get('JAX_PLATFORMS'); "
            "p and jax.config.update('jax_platforms', p); "
            "print('BACKEND=' + jax.default_backend())")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s)
        return "BACKEND=cpu" in out.stdout
    except Exception:
        return False


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=None,
                    help="ring size (default: all available devices)")
    ap.add_argument("--iters", type=int, default=None)
    ap.add_argument("--rdma", action="store_true",
                    help="also time + parity-check the single-kernel "
                         "RDMA reduce-scatter (accelerator only)")
    args = ap.parse_args()

    plat = os.environ.get("JAX_PLATFORMS", "").strip()
    on_cpu = plat == "cpu" if plat else _backend_is_cpu()
    if on_cpu:
        from apex1_tpu.testing import force_virtual_cpu_devices
        force_virtual_cpu_devices(8)
    else:
        from apex1_tpu.testing import honor_jax_platforms_env
        honor_jax_platforms_env()
    from apex1_tpu.testing import enable_persistent_compilation_cache
    enable_persistent_compilation_cache()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from apex1_tpu.core.mesh import make_mesh
    from apex1_tpu.ops import fused_collective as fc
    from apex1_tpu.parallel.ring_attention import ring_attention
    from apex1_tpu.transformer import tensor_parallel as tp

    backend = jax.default_backend()
    devices = jax.devices()
    n = args.n or min(len(devices), 8)
    if n < 2:
        _emit({"metric": f"fused_comm_ab [{backend}]", "value": 0.0,
               "error": f"ring needs >= 2 devices, have {len(devices)} "
                        f"— skipped (multichip window required)"})
        return
    accel = backend not in ("cpu",)
    if accel:
        S, hid, ffn = 8192, 2048, 8192
        B, Hq, Hkv, Sa, D = 1, 32, 4, 16384, 64
        iters = args.iters or 8
        dtype = jnp.bfloat16
    else:
        S, hid, ffn = 64, 16, 32
        B, Hq, Hkv, Sa, D = 1, 4, 2, 128, 16
        iters = args.iters or 2
        dtype = jnp.float32
    mesh = make_mesh(tp=n, dp=1, devices=devices[:n])
    rng = np.random.default_rng(0)
    rc = 0

    def timed(make_loss, arrs, in_specs, name):
        """fwd+bwd iters in one dispatch (bench.py methodology); each
        iteration feeds the previous gradient back so the body is not
        loop-invariant."""
        sm = jax.shard_map(make_loss, mesh=mesh, in_specs=in_specs,
                           out_specs=P(), check_vma=False)

        def loss(*a):
            return sm(*a).sum()

        grad = jax.grad(loss, argnums=0)

        def many(*a):
            def one(x):
                g = grad(x, *a[1:])
                return (x + (1e-6 * g).astype(x.dtype),
                        jnp.sum(g.astype(jnp.float32)))

            def body(_, carry):
                return one(carry[0])

            return jax.lax.fori_loop(0, iters - 1, body, one(a[0]))

        compiled = jax.jit(many).lower(*arrs).compile()
        out = compiled(*arrs)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        out = compiled(*arrs)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters
        if not math.isfinite(float(out[1])):
            raise RuntimeError(f"{name}: non-finite check value")
        return dt

    # ---- leg 1: SP boundary MLP (GLOBAL arrays; shard_map shards) ----
    x = jnp.asarray(rng.normal(size=(S, hid)), dtype)
    w1 = jnp.asarray(rng.normal(size=(hid, ffn)) * 0.02, dtype)
    w2 = jnp.asarray(rng.normal(size=(ffn, hid)) * 0.02, dtype)
    mlp_specs = (P("tp", None), P(None, "tp"), P("tp", None))

    def mlp(col_kw, row_kw):
        def run(x, w1, w2):
            h = tp.column_parallel_linear(
                x, w1, sequence_parallel_enabled=True, axis_name="tp",
                **col_kw)
            h = jax.nn.gelu(h)
            y = tp.row_parallel_linear(
                h, w2, sequence_parallel_enabled=True, axis_name="tp",
                **row_kw)
            return jnp.sum(y.astype(jnp.float32) ** 2)
        return run

    def serial_mlp(x, w1, w2):
        h = fc.fused_all_gather_matmul_serial(x, w1, "tp", 0)
        h = jax.nn.gelu(h.astype(x.dtype))
        y = tp.row_parallel_linear(
            h, w2, sequence_parallel_enabled=True, axis_name="tp")
        return jnp.sum(y.astype(jnp.float32) ** 2)

    try:
        legs = {
            "monolithic": mlp({}, {}),
            "decomposed": mlp(dict(overlap=True), dict(overlap=True)),
            "fused": mlp(dict(fused=True), dict(fused=True)),
            "serialized": serial_mlp,
        }
        times = {k: timed(f, (x, w1, w2), mlp_specs, k)
                 for k, f in legs.items()}
        _emit({
            "metric": f"fused_comm_ab sp_mlp fwd+bwd tp={n} S={S} "
                      f"[{backend}]",
            "value": round(times["monolithic"] / times["fused"], 4),
            "unit": "x (monolithic/fused step time)",
            **{f"{k}_ms": round(v * 1e3, 3) for k, v in times.items()},
            "shape": {"S": S, "hid": hid, "ffn": ffn, "tp": n,
                      "iters": iters},
        })
    except Exception as e:
        _emit({"metric": f"fused_comm_ab sp_mlp [{backend}]",
               "value": 0.0,
               "error": f"{type(e).__name__}: {str(e)[:300]}"})
        rc = 1

    # ---- leg 2: ring attention, merge in the kernel epilogue ---------
    q = jnp.asarray(rng.normal(size=(B, Hq, Sa, D)), dtype)
    k = jnp.asarray(rng.normal(size=(B, Hkv, Sa, D)), dtype)
    v = jnp.asarray(rng.normal(size=(B, Hkv, Sa, D)), dtype)
    aspec = (P(None, None, "tp", None),) * 3

    try:
        def ring_loss(q, k, v):
            return jnp.sum(ring_attention(
                q, k, v, "tp", causal=True).astype(jnp.float32) ** 2)

        def agf_loss(q, k, v):
            return jnp.sum(fc.all_gather_flash_attention(
                q, k, v, "tp", causal=True).astype(jnp.float32) ** 2)

        t_ring = timed(ring_loss, (q, k, v), aspec, "ring")
        t_agf = timed(agf_loss, (q, k, v), aspec, "agf")
        _emit({
            "metric": f"fused_comm_ab attn fwd+bwd cp={n} S={Sa} "
                      f"[{backend}]",
            "value": round(t_ring / t_agf, 4),
            "unit": "x (decomposed-merge/fused-merge step time)",
            "ring_ms": round(t_ring * 1e3, 3),
            "fused_ms": round(t_agf * 1e3, 3),
            "shape": {"B": B, "Hq": Hq, "Hkv": Hkv, "S": Sa, "D": D,
                      "cp": n, "iters": iters},
        })
    except Exception as e:
        _emit({"metric": f"fused_comm_ab attn [{backend}]", "value": 0.0,
               "error": f"{type(e).__name__}: {str(e)[:300]}"})
        rc = 1

    # ---- leg 3 (opt-in, accelerator): the RDMA kernel ----------------
    if args.rdma:
        if not accel:
            _emit({"metric": "fused_comm_ab rdma [cpu]", "value": 0.0,
                   "error": "rdma kernel is compiled-TPU only — "
                            "skipped on cpu rehearsal"})
        else:
            try:
                # gate-verified VMEM frame (see matmul_reduce_scatter_
                # rdma docstring): chunk=256, per-shard K=1024, N=512
                Sr, Kr, Nr = 256 * n, 1024 * n, 512
                xr = jnp.asarray(rng.normal(size=(Sr, Kr)), dtype)
                wr = jnp.asarray(rng.normal(size=(Kr, Nr)) * 0.02,
                                 dtype)
                rspec = (P(None, "tp"), P("tp", None))

                def run_rdma(x, w):
                    return fc.matmul_reduce_scatter_rdma(x, w, "tp")

                def run_ring(x, w):
                    return fc.fused_matmul_reduce_scatter(x, w, "tp", 0)

                outs = {}
                ts = {}
                for nm, f in (("rdma", run_rdma), ("ring", run_ring)):
                    sm = jax.shard_map(f, mesh=mesh, in_specs=rspec,
                                       out_specs=P("tp", None),
                                       check_vma=False)
                    compiled = jax.jit(sm).lower(xr, wr).compile()
                    o = compiled(xr, wr)
                    jax.block_until_ready(o)
                    t0 = time.perf_counter()
                    for _ in range(iters):
                        o = compiled(xr, wr)
                    jax.block_until_ready(o)
                    ts[nm] = (time.perf_counter() - t0) / iters
                    outs[nm] = np.asarray(o, np.float32)
                # THE hardware parity drill: first execution evidence
                # for the RDMA kernel's numerics
                maxdiff = float(np.abs(outs["rdma"] - outs["ring"]).max())
                _emit({
                    "metric": f"fused_comm_ab rdma_mrs fwd tp={n} "
                              f"[{backend}]",
                    "value": round(ts["ring"] / ts["rdma"], 4),
                    "unit": "x (ppermute-ring/rdma-kernel time)",
                    "rdma_ms": round(ts["rdma"] * 1e3, 3),
                    "ring_ms": round(ts["ring"] * 1e3, 3),
                    "max_abs_diff_vs_ring": maxdiff,
                    "shape": {"S": Sr, "K": Kr // n, "N": Nr,
                              "tp": n},
                })
            except Exception as e:
                _emit({"metric": f"fused_comm_ab rdma [{backend}]",
                       "value": 0.0,
                       "error": f"{type(e).__name__}: {str(e)[:300]}"})
                rc = 1

    sys.exit(rc)


if __name__ == "__main__":
    main()
