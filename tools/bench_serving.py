"""Offered-load benchmark for `apex1_tpu.serving.Engine` — the
continuous-batching headline: tokens/sec, p50/p99 time-to-first-token,
and slot occupancy across an offered-load sweep, against the SERIAL
baseline (each request through its own jitted `models.generate` call,
one after another — the repo's status quo before the engine).

Emits ONE JSON line (bench.py's `_emit` convention) with the peak
sweep point as the headline ``value`` plus the per-load rows, e.g.::

  {"metric": "serving tokens/sec gpt2-serving [cpu]", "value": ...,
   "unit": "tokens/sec", "vs_serial": 2.7, "sweep": [...]}

``vs_serial`` >= 2.0 at 8 concurrent staggered requests is the
acceptance line (CPU proxy): decode is weight-streaming-bound, so the
pooled step serves 8 rows for nearly the price of 1 — continuous
batching converts that into throughput the serial loop leaves idle.

``--replicas N [M ...]`` adds the multi-replica axis: the same offered
load through a `serving.ServingFrontend` (threaded supervised
replicas), reporting tokens/sec per replica count — the ROADMAP 2(d)
near-linear-scaling observable. ``--chaos`` arms a seed-keyed
replica-kill mid-sweep and reports GOODPUT (tokens of COMPLETED
requests per second) across the kill + restart + resubmission cycle —
the number that shows fault tolerance costing throughput, not
correctness (every request still completes; parity is tier-1's job).

``--prefix-len N`` (default 24) arms the GOODPUT-MULTIPLIER sweep
(ISSUE 15): a shared-system-prompt trace (every request = one shared
N-token system prompt + its own tail, submitted WITHOUT ``prefix=`` —
the radix matcher must find the sharing itself) measured at the peak
load three ways — the PR-14 baseline (prefix cache off, no
speculation), radix cache on, and radix + speculative decode
(``--num-draft`` drafts, n-gram self-drafting). Rows carry
``prefix_hit_rate``, ``accept_rate``, and goodput; the headline
``goodput_multiple`` is radix+spec over baseline at EQUAL offered
load, with token parity vs the solo-generate oracle asserted on every
rep of every row. An analytic int8-KV capacity row
(`perf_model.serving_capacity`) prices the third multiplier: slots the
same pool HBM buys at int8 vs bf16 (correctness of the dtype flip is
tier-1's dtype-flip parity drills, not this bench).

The PAGED A/B sweep (ISSUE 18, on unless ``--skip-paged``) re-measures
the peak load through the paged KV pool (`EngineConfig(paged=True)`:
block-table page addressing, no copy-on-admit) adjacent to a fresh
dense run, with token parity vs the solo-generate oracle asserted on
every rep of BOTH engines — the A/B prices pool bookkeeping, never
correctness. It also banks a per-phase attribution of the paged decode
step — attention (gather + attend at the live block table), dequant
(the int8 lane cast the TPU kernel fuses away), sample (the fused
epilogue at the step's logits shape), host (engine step wall minus the
decode executable) — measured as standalone jitted phases at the
engine's EXACT mid-decode shapes, emitted onto the obs spine and
parsed back off the banked events (the trace-parser path, like the
disagg breakdown). CPU-proxy caveat: these rows price the COMPOSITE
ops; what the proxy cannot measure (kernel fusion wins, HBM page
streaming) is spelled out in docs/paged_decode.md.

The LoRA A/B (ISSUE 19, ``--lora-tenants N``) prices multi-tenancy at
the peak load: the same offered load through a LoRA-armed engine with
every request on ONE adapter (single-tenant) vs round-robin across N
adapters (multi-tenant), measured adjacent so the ratio isolates the
cross-tenant page gather; ``lora_vs_dense`` prices the fused adapter
epilogue itself against the plain head. Token parity is asserted on
every rep of both rows against per-tenant SOLO runs (each
(prompt, tenant) pair alone through the same engine config) — the
tier-1 mixed-batch bitwise criterion re-asserted at bench scale, so
the A/B prices the epilogue, never correctness.

``--out FILE`` banks the accumulating record via
``manifest.atomic_write_json`` after EVERY sweep point (kill-safe,
like bench.py --out): an interrupted sweep keeps each completed point.

Usage::

  python tools/bench_serving.py                  # full sweep (1,2,4,8)
  python tools/bench_serving.py --smoke          # CPU-gate smoke (~1 min)
  python tools/bench_serving.py --replicas 1 2 --chaos \
      --out perf_results/bench_serving_replicas.json
"""

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _bank(path, record):
    """Kill-safe banking: temp-file + atomic rename on every call, so
    an interrupted sweep keeps every completed point (the bench.py
    --out contract)."""
    if not path:
        return
    from apex1_tpu.resilience.manifest import atomic_write_json
    atomic_write_json(path, record)


def main():
    ap = argparse.ArgumentParser()
    # decode is weight-streaming-bound; the model must be big enough
    # that streaming its weights (not per-step dispatch) dominates, or
    # the CPU proxy under-reports the batching win (hidden 256 measured
    # 1.4x where hidden 512 measures ~2.9x steady-state)
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--layers", type=int, default=6)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=1024)
    ap.add_argument("--new", type=int, default=32,
                    help="tokens generated per request")
    ap.add_argument("--prompt-len", type=int, default=12)
    ap.add_argument("--loads", type=int, nargs="*", default=[1, 2, 4, 8],
                    help="concurrency sweep points (engine slots)")
    ap.add_argument("--requests-per-slot", type=int, default=3,
                    help="offered load: requests = this x slots, so the "
                         "pool stays saturated past the arrival ramp "
                         "(concurrency is still bounded by the slots)")
    ap.add_argument("--stagger", type=int, default=2,
                    help="engine steps between arrivals")
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--prefix-len", type=int, default=24,
                    help="shared system-prompt length for the "
                         "goodput-multiplier sweep (0 disables it)")
    ap.add_argument("--num-draft", type=int, default=4,
                    help="drafts per verify for the speculative axis "
                         "of the multiplier sweep")
    ap.add_argument("--skip-paged", action="store_true",
                    help="skip the paged-pool A/B + per-phase "
                         "attribution at the peak load")
    ap.add_argument("--phase-reps", type=int, default=5,
                    help="timing reps per attribution phase")
    ap.add_argument("--lora-tenants", type=int, default=0,
                    help="multi-tenant LoRA A/B at the peak load: "
                         "single-tenant vs N adapters round-robin, "
                         "token parity vs per-tenant solo runs on "
                         "every rep (0 disables the axis)")
    ap.add_argument("--lora-rank", type=int, default=4)
    ap.add_argument("--replicas", type=int, nargs="*", default=[],
                    help="multi-replica sweep points (ServingFrontend; "
                         "empty = skip the replica axis)")
    ap.add_argument("--slots-per-replica", type=int, default=4)
    ap.add_argument("--chaos", action="store_true",
                    help="kill one replica mid-sweep (seed-keyed, "
                         "testing.chaos.kill_schedule) and measure "
                         "goodput across restart + resubmission")
    ap.add_argument("--chaos-seed", type=int, default=20260804)
    ap.add_argument("--disagg", action="store_true",
                    help="unified vs disaggregated fleetsim A/B at "
                         "EQUAL offered load on an adversarial "
                         "long-prompt trace (virtual clock; control "
                         "logic, not silicon numbers), with per-phase "
                         "TTFT/TPOT breakdown parsed back off the obs "
                         "spine")
    ap.add_argument("--disagg-seed", type=int, default=20260807)
    ap.add_argument("--out", type=str, default=None,
                    help="bank the record here (atomic write after "
                         "every sweep point — kill-safe)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny model + [1, 4] sweep for the CPU gate "
                         "(correctness/plumbing only: a dispatch-"
                         "dominated tiny model can't show the batching "
                         "win — the ratio is the full sweep's job)")
    args = ap.parse_args()
    if args.smoke:
        args.hidden, args.layers, args.vocab = 128, 2, 256
        args.new, args.loads = 16, [1, 4]
        args.prefix_len = min(args.prefix_len, 12)
        args.num_draft = min(args.num_draft, 3)
        args.lora_tenants = min(args.lora_tenants, 2)
        if args.replicas:
            args.replicas = args.replicas[:2]

    # examples/tools convention: the env var must beat the container's
    # sitecustomize platform pin; default to CPU for a proxy-able bench
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    from apex1_tpu.testing import (enable_persistent_compilation_cache,
                                   honor_jax_platforms_env)
    honor_jax_platforms_env()
    enable_persistent_compilation_cache()

    import jax.numpy as jnp
    import numpy as np

    from apex1_tpu.core.policy import get_policy
    from apex1_tpu.models.generate import generate, gpt2_decoder
    from apex1_tpu.models.gpt2 import GPT2, GPT2Config
    from apex1_tpu.serving import (Backpressure, Engine, EngineConfig,
                                   ServingMetrics)

    max_slots = max(args.loads)
    n_req_max = args.requests_per_slot * max_slots
    max_len = args.prompt_len + args.new + 8
    # the position table must also cover the multiplier sweep's
    # prefix-extended prompts (prefix + own + new) — sizing from
    # max_len alone would run sequences past max_seq_len and fail on
    # a confusing token-parity assert instead (review finding)
    mult_total = args.prefix_len + args.prompt_len + args.new + 8
    cfg = GPT2Config.tiny(policy=get_policy("O0"), vocab_size=args.vocab,
                          hidden_size=args.hidden, num_layers=args.layers,
                          num_heads=args.heads,
                          max_seq_len=max(128, max_len, mult_total))
    model = GPT2(cfg)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size,
                            (args.prompt_len,)).astype(np.int32)
               for _ in range(n_req_max)]
    params = model.init(jax.random.key(0),
                        jnp.asarray(prompts[0][None]))["params"]
    apply_fn, make_cache = gpt2_decoder(model)

    # ---- serial baseline: one jitted generate per request, back to
    # back (compile excluded — one warmup call at the fixed shape)
    gen = jax.jit(functools.partial(
        generate, apply_fn, max_new_tokens=args.new,
        vocab_size=cfg.vocab_size))

    def serial_run(n_req):
        outs = []
        for i in range(n_req):
            cache = make_cache(1, max_len)
            outs.append(gen(params, jnp.asarray(prompts[i][None]),
                            cache=cache))
        return [np.asarray(o)[0] for o in outs]

    serial_out = serial_run(n_req_max)      # compile + the oracle run

    def serial_best(n_req, reps=3):
        """Best-of-``reps`` serial tokens/sec over ``n_req`` requests —
        measured ADJACENT to each engine point so machine drift over
        the sweep cancels in the ratio instead of polluting it (the
        baseline still gets every benefit of the doubt: its best rep).
        """
        best_s = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            serial_run(n_req)
            best_s = min(best_s, time.perf_counter() - t0)
        return n_req * args.new / best_s

    # ---- engine sweep: n staggered arrivals into an n-slot pool
    sweep = []
    serial_tps = 0.0
    for load in args.loads:
        n_req = args.requests_per_slot * load
        serial_tps = serial_best(n_req)
        eng = Engine(apply_fn, make_cache, params,
                     EngineConfig(max_slots=load, max_len=max_len,
                                  prefill_chunk=args.chunk,
                                  vocab_size=cfg.vocab_size,
                                  max_queue=n_req))
        # warm both executables off the clock (jit compile), then bench
        # a fresh engine-shaped workload on the SAME engine (the two
        # executables are already traced; trace_counts pins that)
        wid = eng.submit(prompts[0], max_new_tokens=2)
        eng.run(max_steps=8)
        assert eng.results[wid].status == "done"
        # best-of-3, mirroring the serial baseline's best-of-3: both
        # sides shed co-tenant noise; parity is asserted on every rep
        dt = float("inf")
        for _ in range(3):
            eng.metrics = ServingMetrics()  # drop prior reps' records
            eng.results.clear()
            t0 = time.perf_counter()
            ids = []
            k = 0
            while k < n_req or eng.scheduler.depth or eng.n_active:
                if k < n_req:
                    ids.append(eng.submit(prompts[k],
                                          max_new_tokens=args.new))
                    k += 1
                    for _ in range(args.stagger - 1):
                        eng.step()
                eng.step()
            rep = time.perf_counter() - t0
            for i, rid in enumerate(ids):  # parity stays the oracle
                np.testing.assert_array_equal(eng.results[rid].tokens,
                                              serial_out[i])
            if rep < dt:
                dt, s = rep, eng.metrics.summary()
        assert eng.trace_counts == {"prefill": 1, "decode": 1}, \
            eng.trace_counts
        tps = n_req * args.new / dt
        sweep.append({
            "load": load, "tokens_per_sec": round(tps, 1),
            "serial_tokens_per_sec": round(serial_tps, 1),
            "vs_serial": round(tps / serial_tps, 3),
            "ttft_p50_ms": round(s.get("ttft_p50_ms", 0.0), 2),
            "ttft_p99_ms": round(s.get("ttft_p99_ms", 0.0), 2),
            "mean_occupancy": round(s.get("mean_occupancy", 0.0), 3),
        })

    best = max(sweep, key=lambda r: r["tokens_per_sec"])
    backend = jax.default_backend()
    record = {
        "metric": f"serving tokens/sec gpt2-serving [{backend}]",
        "value": best["tokens_per_sec"],
        "unit": "tokens/sec",
        "vs_serial": best["vs_serial"],
        "serial_tokens_per_sec": best["serial_tokens_per_sec"],
        "model": {"hidden": args.hidden, "layers": args.layers,
                  "vocab": args.vocab, "new": args.new,
                  "prompt_len": args.prompt_len},
        "sweep": sweep,
    }
    _bank(args.out, record)

    # ---- goodput-multiplier sweep (ISSUE 15): a shared-system-prompt
    # trace at the peak load, measured at EQUAL offered load under the
    # PR-14 baseline (no sharing exploited, no speculation), the radix
    # prefix cache, and radix + speculative decode. Parity vs the
    # solo-generate oracle holds on every rep of every row — the
    # multipliers must be invisible in the tokens.
    if args.prefix_len > 0:
        from apex1_tpu.perf_model import (kv_cache_bytes,
                                          serving_capacity)

        load = max(args.loads)
        n_req = args.requests_per_slot * load
        sysp = rng.integers(0, cfg.vocab_size,
                            (args.prefix_len,)).astype(np.int32)
        mult_prompts = [np.concatenate([sysp, p]) for p in
                        prompts[:n_req]]
        mult_len = args.prefix_len + args.prompt_len + args.new + 8
        # the oracle: solo generate of each FULL prompt (compile once
        # at the new shape, off the clock)
        m_oracle = []
        for p in mult_prompts:
            cache = make_cache(1, mult_len)
            m_oracle.append(np.asarray(
                gen(params, jnp.asarray(p[None]), cache=cache))[0])

        def mult_row(tag, prefix_cache, num_draft):
            eng = Engine(apply_fn, make_cache, params,
                         EngineConfig(max_slots=load, max_len=mult_len,
                                      prefill_chunk=args.chunk,
                                      vocab_size=cfg.vocab_size,
                                      max_queue=n_req,
                                      prefix_cache=prefix_cache,
                                      num_draft=num_draft))
            # warm the executables off the clock with a NON-sharing
            # prompt: the warmup must not seed the radix store with
            # the trace's system prompt (the first REAL request pays
            # the cold miss, like production)
            wid = eng.submit(prompts[0][:4], max_new_tokens=2)
            eng.run(max_steps=16)
            assert eng.results[wid].status == "done"
            best_s, s = float("inf"), None
            for _ in range(3):
                eng.metrics = ServingMetrics()
                eng.results.clear()
                t0 = time.perf_counter()
                ids = []
                k = 0
                while k < n_req or eng.scheduler.depth or eng.n_active:
                    if k < n_req:
                        ids.append(eng.submit(mult_prompts[k],
                                              max_new_tokens=args.new))
                        k += 1
                        for _ in range(args.stagger - 1):
                            eng.step()
                    eng.step()
                rep = time.perf_counter() - t0
                for i, rid in enumerate(ids):   # parity stays the oracle
                    np.testing.assert_array_equal(
                        eng.results[rid].tokens, m_oracle[i])
                if rep < best_s:
                    best_s, s = rep, eng.metrics.summary()
            expect = {"prefill": 1,
                      ("verify" if num_draft else "decode"): 1}
            assert eng.trace_counts == expect, eng.trace_counts
            return {
                "config": tag,
                "prefix_cache": prefix_cache,
                "num_draft": num_draft,
                "goodput_tokens_per_sec": round(
                    n_req * args.new / best_s, 1),
                "prefix_hit_rate": (round(s["prefix_hit_rate"], 4)
                                    if "prefix_hit_rate" in s else None),
                "prefix_saved_tokens": s.get("prefix_saved_tokens"),
                "accept_rate": (round(s["accept_rate"], 4)
                                if "accept_rate" in s else None),
            }

        base_row = mult_row("baseline_pr14", False, 0)
        radix_row = mult_row("radix", True, 0)
        spec_row = mult_row("radix_spec", True, args.num_draft)
        # structural gates (the check_all --smoke coverage of the radix
        # and speculative paths): the multipliers actually fired. The
        # goodput RATIO is read off the banked record, not asserted —
        # same policy as the main sweep's >= 2x line.
        assert radix_row["prefix_hit_rate"] > 0, radix_row
        assert spec_row["prefix_hit_rate"] > 0, spec_row
        assert spec_row["accept_rate"] > 0, spec_row
        head_dim = args.hidden // args.heads
        pool_len = mult_len + max(args.chunk, args.num_draft + 1) - 1
        bf16_budget = kv_cache_bytes(args.layers, args.heads, head_dim,
                                     pool_len, load, 2)
        record["multiplier_sweep"] = {
            "offered_load": {"slots": load, "requests": n_req,
                             "prefix_len": args.prefix_len,
                             "own_len": args.prompt_len,
                             "new": args.new},
            "rows": [base_row, radix_row, spec_row],
            # the headline: the best multiplier configuration over the
            # PR-14 baseline at EQUAL offered load (the operator picks
            # ONE config per deployment; speculation's win is
            # TPU-shaped — weight-streaming-bound decode — and may
            # invert on the CPU proxy, where the bankable observable
            # is its accept_rate, not its wall-clock: docs/serving.md)
            "goodput_multiple": round(
                max(radix_row["goodput_tokens_per_sec"],
                    spec_row["goodput_tokens_per_sec"])
                / base_row["goodput_tokens_per_sec"], 3),
            "best_config": max(
                (radix_row, spec_row),
                key=lambda r: r["goodput_tokens_per_sec"])["config"],
            # the third multiplier, priced analytically: the same pool
            # HBM at the int8 tier (capacity only — the dtype-flip
            # parity drills in tier-1 license the flip, this bench's
            # fp32 test model would not survive a raw int8 cast)
            "int8_capacity": {
                "pool_len": pool_len,
                "kv_pool_bytes_bf16": bf16_budget,
                "slots_bf16": load,
                "slots_int8_same_budget": serving_capacity(
                    bf16_budget, args.layers, args.heads, head_dim,
                    pool_len, 1),
            },
        }
        _bank(args.out, record)

    # ---- paged A/B + per-phase attribution (ISSUE 18): the peak load
    # through the paged KV pool, measured ADJACENT to a fresh dense run
    # (drift cancels in the ratio), token parity vs the solo-generate
    # oracle on every rep of both engines. The attribution measures the
    # paged decode step's phases as standalone jitted callables at the
    # engine's EXACT mid-decode shapes, emits each rep onto the obs
    # spine, and reconstructs the breakdown from the banked events —
    # proving the trace carries the attribution, not just this process.
    if not args.skip_paged:
        import tempfile

        from apex1_tpu.obs import spine as obs_spine
        from apex1_tpu.ops.paged_decode import (cache_attend,
                                                fused_sample,
                                                gather_pages)

        load = max(args.loads)
        n_req = args.requests_per_slot * load

        def ab_engine(paged):
            eng = Engine(apply_fn, make_cache, params,
                         EngineConfig(max_slots=load, max_len=max_len,
                                      prefill_chunk=args.chunk,
                                      vocab_size=cfg.vocab_size,
                                      max_queue=n_req, paged=paged))
            wid = eng.submit(prompts[0], max_new_tokens=2)
            eng.run(max_steps=8)
            assert eng.results[wid].status == "done"
            best = float("inf")
            for _ in range(3):
                eng.metrics = ServingMetrics()
                eng.results.clear()
                t0 = time.perf_counter()
                ids = []
                k = 0
                while k < n_req or eng.scheduler.depth or eng.n_active:
                    if k < n_req:
                        ids.append(eng.submit(prompts[k],
                                              max_new_tokens=args.new))
                        k += 1
                        for _ in range(args.stagger - 1):
                            eng.step()
                    eng.step()
                rep = time.perf_counter() - t0
                for i, rid in enumerate(ids):  # paged must be invisible
                    np.testing.assert_array_equal(
                        eng.results[rid].tokens, serial_out[i])
                best = min(best, rep)
            assert eng.trace_counts == {"prefill": 1, "decode": 1}, \
                eng.trace_counts
            return eng, n_req * args.new / best

        _, dense_tps = ab_engine(False)
        eng, paged_tps = ab_engine(True)

        # park the paged engine mid-decode so the live block table,
        # page store, and control vectors give the attribution its
        # real shapes (all rows admitted, none near retirement)
        for p in prompts[:load]:
            eng.submit(p, max_new_tokens=args.new)
        while eng.scheduler.depth:
            eng.step()
        for _ in range(2):
            eng.step()

        L = eng.kv.lane_len
        bt = eng._d_bt
        entry = next(iter(eng.kv.pages.values()))
        kp, vp = entry["k"], entry["v"]
        D = kp.shape[-1]
        prng = np.random.default_rng(7)
        q = jnp.asarray(prng.standard_normal(
            (load, args.heads, 1, D)), jnp.float32)
        lg = jnp.asarray(prng.standard_normal(
            (load, cfg.vocab_size)), jnp.float32)

        def attn_fn(kp, vp, bt, idxs, q):
            # one layer of the step's attention math: block-table
            # gather + masked attend at each row's live depth
            k_all = gather_pages(kp, bt, L).astype(jnp.float32)
            v_all = gather_pages(vp, bt, L).astype(jnp.float32)
            return cache_attend(q, k_all, v_all, idxs)

        # the dequant pass the TPU kernel fuses away: int8 lanes (one
        # layer's K, as gathered for one step) cast up to f32
        lanes8 = jax.jit(lambda p, b: gather_pages(p, b, L).astype(
            jnp.int8))(kp, bt)
        sample_kw = dict(temperature=0.7, vocab_size=cfg.vocab_size)
        phases = {
            "attention": (jax.jit(attn_fn),
                          (kp, vp, bt, eng._d_idxs, q)),
            "dequant": (jax.jit(lambda x: x.astype(jnp.float32)),
                        (lanes8,)),
            "sample": (jax.jit(functools.partial(fused_sample,
                                                 **sample_kw)),
                       (lg, eng._d_seeds, eng._d_pos)),
        }

        def dev_step():
            out = eng._decode(eng.params, eng.kv.pages, eng._d_bt,
                              eng._d_toks, eng._d_idxs, eng._d_active,
                              eng._d_seeds, eng._d_pos)
            jax.block_until_ready(out)   # state untouched: outputs
            #                              dropped, no donation on cpu

        obs_tmp = tempfile.mkdtemp(prefix="bench_paged_obs_")
        run = obs_spine.ObsRun(dir=obs_tmp, component="bench_paged")
        obs_spine.set_default_run(run)
        try:
            for name, (fn, fargs) in phases.items():
                jax.block_until_ready(fn(*fargs))    # compile off-clock
                for r in range(args.phase_reps):
                    t0 = time.perf_counter()
                    jax.block_until_ready(fn(*fargs))
                    obs_spine.emit(
                        "event", "bench.paged_phase", phase=name,
                        rep=r, ms=(time.perf_counter() - t0) * 1e3)
            # host = full engine step minus the decode executable —
            # slot bookkeeping, token fetch, metrics, retire scan
            dev_step()                               # executable warm
            for r in range(args.phase_reps):
                t0 = time.perf_counter()
                dev_step()
                dev_ms = (time.perf_counter() - t0) * 1e3
                t0 = time.perf_counter()
                assert eng.step() == load            # rows stay active
                step_ms = (time.perf_counter() - t0) * 1e3
                obs_spine.emit("event", "bench.paged_phase",
                               phase="host", rep=r,
                               ms=max(0.0, step_ms - dev_ms))
        finally:
            run.close()
            obs_spine.set_default_run(None)

        # the trace-parser path: the breakdown is rebuilt from the
        # banked events, not from in-process floats
        samples = {}
        for e in obs_spine.read_events(run.path):
            if e.get("name") == "bench.paged_phase":
                samples.setdefault(e["phase"], []).append(
                    float(e["ms"]))
        assert set(samples) == {"attention", "dequant", "sample",
                                "host"}, sorted(samples)
        per_phase = {
            name: {"n": len(v),
                   "p50_ms": round(float(np.percentile(v, 50)), 4),
                   "min_ms": round(float(min(v)), 4)}
            for name, v in sorted(samples.items())}
        record["paged_sweep"] = {
            "load": load,
            "page_size": eng.kv.page_size,
            "pages_per_lane": eng.kv.pages_per_lane,
            "tokens_per_sec_dense": round(dense_tps, 1),
            "tokens_per_sec_paged": round(paged_tps, 1),
            # pool bookkeeping priced at equal load; parity asserted
            # above, so any gap here is block-table plumbing, never
            # tokens. CPU-proxy caveat: composite-op timings — the
            # fusion/page-streaming wins are TPU-only
            # (docs/paged_decode.md)
            "paged_vs_dense": round(paged_tps / dense_tps, 3),
            "per_phase": per_phase,
            "phase_shapes": {
                "slots": load, "lane_len": L,
                "page_size": eng.kv.page_size,
                "head_dim": D, "heads": args.heads,
                "vocab": cfg.vocab_size, "layers_note":
                    "attention/dequant rows are PER LAYER "
                    f"(x{args.layers} per step); dequant is one "
                    "layer's K lanes (x2 for K+V)"},
        }
        _bank(args.out, record)

    # ---- multi-tenant LoRA A/B (ISSUE 19): the peak load through a
    # LoRA-armed engine, single-tenant vs N tenants round-robin,
    # measured adjacent so the ratio isolates the cross-tenant page
    # gather in the fused logits epilogue. Parity on every rep of both
    # rows is against per-tenant SOLO runs — the tier-1 mixed-batch
    # bitwise criterion at bench scale, so the A/B prices the
    # epilogue's wall-clock, never its tokens.
    if args.lora_tenants > 0:
        load = max(args.loads)
        n_req = args.requests_per_slot * load
        R = args.lora_rank
        names = [f"tenant-{i}" for i in range(args.lora_tenants)]
        arng = np.random.default_rng(11)
        adapters = {nm: (arng.standard_normal((args.hidden, R)) * 0.05,
                         arng.standard_normal((R, args.vocab)) * 0.05)
                    for nm in names}

        def lora_engine():
            eng = Engine(
                apply_fn, make_cache, params,
                EngineConfig(max_slots=load, max_len=max_len,
                             prefill_chunk=args.chunk,
                             vocab_size=cfg.vocab_size, max_queue=n_req,
                             lora_rank=R,
                             lora_max_adapters=args.lora_tenants),
                lora_head=params["wte"])   # gpt2: weight-tied (V, H)
            for nm, (A, B) in adapters.items():
                eng.register_adapter(nm, A, B, scale=2.0)
            # warmup rides the SAME two executables (LoRA-off slots
            # share them via the zero page — no retrace)
            wid = eng.submit(prompts[0], max_new_tokens=2, seed=1)
            eng.run(max_steps=8)
            assert eng.results[wid].status == "done"
            return eng

        # the oracle: every (prompt, tenant) pair either row will
        # batch, run ALONE through one reusable engine (slot reuse +
        # page refcounts are tier-1's job; seeds pinned per request so
        # solo and mixed draw identical sampling streams)
        oracle = {}
        solo = lora_engine()
        for nt in (1, args.lora_tenants):
            for k in range(n_req):
                key = (k, names[k % nt])
                if key in oracle:
                    continue
                solo.results.clear()
                rid = solo.submit(prompts[k], max_new_tokens=args.new,
                                  tenant=key[1], seed=7000 + k)
                solo.run(max_steps=args.new + 32)
                assert solo.results[rid].status == "done"
                oracle[key] = np.asarray(solo.results[rid].tokens)

        def lora_row(tag, nt):
            eng = lora_engine()
            best = float("inf")
            for _ in range(3):
                eng.metrics = ServingMetrics()
                eng.results.clear()
                t0 = time.perf_counter()
                ids = []
                k = 0
                while k < n_req or eng.scheduler.depth or eng.n_active:
                    if k < n_req:
                        ids.append(eng.submit(
                            prompts[k], max_new_tokens=args.new,
                            tenant=names[k % nt], seed=7000 + k))
                        k += 1
                        for _ in range(args.stagger - 1):
                            eng.step()
                    eng.step()
                rep = time.perf_counter() - t0
                for i, rid in enumerate(ids):   # mixed == solo, bitwise
                    np.testing.assert_array_equal(
                        eng.results[rid].tokens,
                        oracle[(i, names[i % nt])])
                best = min(best, rep)
            assert eng.trace_counts == {"prefill": 1, "decode": 1}, \
                eng.trace_counts
            assert not eng._lora._slot_pages   # pages all released
            return {"config": tag, "tenants": nt,
                    "tokens_per_sec": round(n_req * args.new / best, 1)}

        single_row = lora_row("lora_single_tenant", 1)
        multi_row = lora_row("lora_multi_tenant", args.lora_tenants)
        # dense reference from the main sweep's peak-load point: the
        # epilogue's cost over the plain head (same offered load; the
        # sweep ran moments ago on this machine)
        dense_tps = next(r["tokens_per_sec"] for r in sweep
                         if r["load"] == load)
        record["lora_sweep"] = {
            "rank": R, "adapters": args.lora_tenants, "load": load,
            "requests": n_req,
            "rows": [single_row, multi_row],
            "multi_vs_single": round(
                multi_row["tokens_per_sec"]
                / single_row["tokens_per_sec"], 3),
            "dense_tokens_per_sec": dense_tps,
            "lora_vs_dense": round(
                multi_row["tokens_per_sec"] / dense_tps, 3),
        }
        _bank(args.out, record)

    # ---- replica axis: the same offered load through the supervised
    # multi-replica frontend (threaded serve loops; the main thread is
    # the supervision tick) — near-linear scaling is ROADMAP 2(d)'s
    # acceptance observable, goodput-under-kill is PR 7's
    if args.replicas:
        from apex1_tpu.serving import (EngineConfig, FrontendConfig,
                                       ReplicaConfig, ServingFrontend)
        from apex1_tpu.testing.chaos import kill_schedule

        slots = args.slots_per_replica
        record["replica_sweep"] = []
        for n_rep in args.replicas:
            n_req = args.requests_per_slot * slots * n_rep
            e_cfg = EngineConfig(max_slots=slots,
                                 max_len=max_len,
                                 prefill_chunk=args.chunk,
                                 vocab_size=cfg.vocab_size,
                                 max_queue=max(n_req, 8))

            def make_engine():
                return Engine(apply_fn, make_cache, params, e_cfg)

            front = ServingFrontend(
                make_engine,
                FrontendConfig(
                    n_replicas=n_rep,
                    capacity_per_replica=slots + e_cfg.max_queue,
                    hedge_after_s=None,
                    # worst-case first step INCLUDES the fresh
                    # engine's XLA compile — the watchdog must not
                    # read a compile as a hang
                    replica=ReplicaConfig(watchdog_s=600.0))).start()
            # warm every replica's two executables off the clock
            # (mirrors the engine sweep's warmup; a CHAOS restart's
            # recompile stays IN the window — that is the honest cost
            # of the kill)
            warm = [front.submit(prompts[0], max_new_tokens=2)
                    for _ in range(n_rep)]
            front.run_until_drained(timeout_s=1800.0)
            t0 = time.perf_counter()
            k = 0
            while k < n_req:
                try:
                    front.submit(prompts[k % len(prompts)],
                                 max_new_tokens=args.new)
                    k += 1
                except Backpressure:
                    front.pump()
            fault = None
            if args.chaos and n_rep > 1:
                # armed only NOW, offset from the victim's CURRENT
                # step count: supervisor steps tick on idle iterations
                # too, so a pre-armed absolute step would fire inside
                # the off-the-clock warmup and the "chaos" row would
                # measure an uninterrupted sweep (review finding).
                # With every request just accepted, the offset lands
                # mid-decode — streams are genuinely in flight.
                fault = kill_schedule(args.chaos_seed,
                                      n_replicas=n_rep, lo=2,
                                      hi=2 + args.new)
                fault.at_step += front.replicas[fault.replica].steps
                front.replicas[fault.replica].fault = fault
            results = front.run_until_drained(timeout_s=1800.0)
            dt = time.perf_counter() - t0
            front.stop()
            done = [r for rid, r in results.items()
                    if r.status == "done" and rid not in warm]
            good_tokens = sum(int(r.tokens.size) for r in done)
            counters = front.metrics.summary()["counters"]
            row = {
                "replicas": n_rep,
                "requests": n_req,
                "completed": len(done),
                "goodput_tokens_per_sec": round(good_tokens / dt, 1),
                "chaos": bool(fault),
                "replica_restarts": counters["replica_restarts"],
            }
            if fault is not None:
                row["kill"] = {"replica": fault.replica,
                               "step": fault.at_step,
                               "fired": fault.fired}
            record["replica_sweep"].append(row)
            _bank(args.out, record)

    # ---- disaggregation axis (ISSUE 16): unified vs two-pool fleet
    # at EQUAL offered load and EQUAL total replicas on an adversarial
    # long-prompt trace, under the metered prefill-cost model — the
    # CPU-proxy record of the head-of-line claim. Virtual-clock
    # numbers: this banks control-loop/routing behavior (what the
    # proxy CAN prove), never silicon latency (docs/serving.md).
    if args.disagg:
        import tempfile

        from apex1_tpu.obs import spine as obs_spine
        from apex1_tpu.serving import FrontendConfig
        from apex1_tpu.testing.fleetsim import (FleetSimConfig,
                                                run_fleet,
                                                synthetic_trace)

        horizon = 2.0 if args.smoke else 4.0
        ttft_slo_s = 0.12
        tr = synthetic_trace(
            "adversarial_long_prompt", seed=args.disagg_seed,
            horizon_s=horizon, base_rate=25.0,
            # guaranteed stays short (direct-decode under disagg);
            # best_effort/sheddable drag 18-30-token prefills through
            prompt_lens=(2, 4), long_prompt_lens=(18, 30),
            class_mix={"guaranteed": 0.4, "best_effort": 0.35,
                       "sheddable": 0.25})
        fcfg = FrontendConfig(n_replicas=3, capacity_per_replica=8,
                              hedge_after_s=None)
        sims = (
            ("unified", FleetSimConfig(max_len=64,
                                       prefill_round_cost=True)),
            ("disagg", FleetSimConfig(max_len=64,
                                      prefill_round_cost=True,
                                      disagg=True,
                                      prefill_replicas=1)),
        )

        def phase_breakdown(events):
            """Per-phase percentiles per QoS class, reconstructed from
            the spine's ``serving.request`` lifecycle events alone (the
            obs trace parser path — proves the banked events carry the
            episode, not just the in-memory records). Disagg pools
            mirror their own lifecycle beside the end-to-end one under
            the same request id; min(first_token)/max(done) collapses
            the duplicates back to the end-to-end view."""
            per = {}
            for e in events:
                if e.get("name") != "serving.request":
                    continue
                r = per.setdefault(int(e["req"]), {})
                ev, t = e.get("event"), e.get("t_serving")
                if ev == "queued":
                    r.setdefault("qos", e.get("qos"))
                    r["t_q"] = min(t, r.get("t_q", t))
                elif ev == "first_token":
                    r["t_f"] = min(t, r.get("t_f", t))
                elif ev == "done":
                    r["t_d"] = max(t, r.get("t_d", t))
                    r["n"] = max(int(e.get("n_generated", 0)),
                                 r.get("n", 0))
            out = {}
            for r in per.values():
                if not ("qos" in r and "t_q" in r and "t_f" in r
                        and "t_d" in r):
                    continue
                d = out.setdefault(r["qos"], {"ttfts": [], "tpots": []})
                d["ttfts"].append(r["t_f"] - r["t_q"])
                if r.get("n", 0) >= 2:
                    d["tpots"].append(
                        (r["t_d"] - r["t_f"]) / (r["n"] - 1))
            return {
                cls: {
                    "n": len(d["ttfts"]),
                    "ttft_p50_ms": round(float(np.percentile(
                        d["ttfts"], 50)) * 1e3, 2),
                    "ttft_p99_ms": round(float(np.percentile(
                        d["ttfts"], 99)) * 1e3, 2),
                    "tpot_p99_ms": (round(float(np.percentile(
                        d["tpots"], 99)) * 1e3, 2)
                        if d["tpots"] else None),
                } for cls, d in sorted(out.items())}

        obs_tmp = tempfile.mkdtemp(prefix="bench_disagg_obs_")
        rows, reports = [], {}
        for tag, sim in sims:
            run = obs_spine.ObsRun(dir=obs_tmp,
                                   component=f"bench_disagg_{tag}")
            obs_spine.set_default_run(run)
            try:
                rep = run_fleet(tr, fcfg, sim=sim)
            finally:
                run.close()
                obs_spine.set_default_run(None)
            reports[tag] = rep
            j = rep.to_json()
            row = {
                "config": tag,
                "guaranteed_ttft_attainment": round(
                    rep.ttft_attainment("guaranteed", ttft_slo_s), 4),
                "goodput_tok_per_virtual_s":
                    j["goodput_tok_per_virtual_s"],
                "per_class": j["per_class"],
                "per_phase": phase_breakdown(
                    obs_spine.read_events(run.path)),
                "fingerprint": j["fingerprint"],
            }
            for k in ("handoffs", "handoff_failures",
                      "handoff_reroutes"):
                if k in j:
                    row[k] = j[k]
            rows.append(row)
        # cross-fleet token parity: a request done under BOTH fleets
        # carries the same id, hence the same derived seed, hence must
        # carry the SAME tokens — the handoff (and every re-route) is
        # invisible in the stream, which transitively pins the disagg
        # streams to solo generate (the unified engine's tier-1
        # contract)
        uni = {o["idx"]: o["tokens_sha1"]
               for o in reports["unified"].outcomes
               if o["status"] == "done"}
        dis = {o["idx"]: o["tokens_sha1"]
               for o in reports["disagg"].outcomes
               if o["status"] == "done"}
        common = sorted(set(uni) & set(dis))
        assert common, "no request completed under both fleets"
        for idx in common:
            assert uni[idx] == dis[idx], \
                f"request {idx}: disagg stream diverged from unified"
        d_row, u_row = rows[1], rows[0]
        assert d_row["handoffs"] > 0 and \
            d_row["handoff_failures"] == 0, d_row
        # structural gate only (like the >= 2x line): the banked
        # record carries the margin, the gate just proves the split
        # didn't LOSE the guaranteed class
        assert (d_row["guaranteed_ttft_attainment"]
                >= u_row["guaranteed_ttft_attainment"]), rows
        record["disagg_sweep"] = {
            "trace": {"kind": tr.kind, "seed": tr.seed,
                      "arrivals": len(tr.requests),
                      "horizon_s": horizon,
                      "fingerprint": tr.fingerprint()},
            "replicas_total": fcfg.n_replicas,
            "ttft_slo_s": ttft_slo_s,
            "parity_checked_requests": len(common),
            "rows": rows,
        }
        _bank(args.out, record)

    print(json.dumps(record), flush=True)
    # every sweep point already asserted (a) token parity against the
    # solo-generate oracle for every request and (b) exactly two traced
    # executables — reaching here IS the smoke gate; the >= 2x
    # acceptance ratio is read off the banked full-size sweep
    # (perf_results/bench_serving_cpu.log), where the model is big
    # enough for weight streaming, not dispatch, to dominate
    return 0


if __name__ == "__main__":
    sys.exit(main())
