"""Capture a jax.profiler trace of one bench config's train step on the
current backend and print the per-op device-time breakdown.

Since PR 10 the parsing/attribution lives in `apex1_tpu.obs.xspace`
(a dependency-free XSpace wire-format walker — the old three-way
``xplane_pb2`` import-location roulette is gone) and the breakdown is
ALSO persisted as ``trace_report.json`` next to the trace, same format
as ``tools/trace_report.py`` banks for every bench `profile_artifact`.

Usage: python tools/profile_step.py [--config gpt2] [--top 40]
"""

import argparse
import os
import sys
import tempfile

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apex1_tpu.obs import xspace  # noqa: E402


def build_step(config):
    import bench
    on_accel = jax.default_backend() not in ("cpu",)
    state, step, batch, units, iters, metric, unit, proxy = \
        bench.BENCHES[config](on_accel)
    jstep = jax.jit(step)
    # compile + warm
    out = jstep(state, *batch)
    jax.block_until_ready(out)
    return jstep, state, batch


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="gpt2")
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--steps", type=int, default=3)
    args = ap.parse_args()

    # Honor JAX_PLATFORMS despite the container sitecustomize pinning
    # jax_platforms=axon,cpu (the r4 CPU rehearsal caught this script
    # initializing the axon backend under JAX_PLATFORMS=cpu and hanging
    # on the dead tunnel), and probe the backend in a killable
    # SUBPROCESS first — in-process init on a dead tunnel blocks
    # uninterruptibly and would eat the whole tpu_watch phase budget.
    from apex1_tpu.testing import honor_jax_platforms_env
    honor_jax_platforms_env()
    import bench
    backend, probe_stderr = bench.probe_backend()
    if backend is None:
        print(f"backend init unreachable; last stderr: {probe_stderr}",
              flush=True)
        sys.exit(1)

    print(f"backend={jax.default_backend()}", flush=True)
    jstep, state, batch = build_step(args.config)
    print("compiled; tracing...", flush=True)

    tmp = tempfile.mkdtemp(prefix="jaxprof_")
    with jax.profiler.trace(tmp):
        for _ in range(args.steps):
            out = jstep(state, *batch)
        jax.block_until_ready(out)

    try:
        report = xspace.build_report(tmp, steps=args.steps)
    except xspace.TraceError as e:
        print(f"trace unreadable: {e.reason}", flush=True)
        sys.exit(1)
    path = xspace.write_report(tmp, report=report)
    print(xspace.format_report(report, top=args.top), flush=True)
    print(f"report banked at {path}", flush=True)


if __name__ == "__main__":
    main()
