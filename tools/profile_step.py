"""Capture a jax.profiler trace of one bench config's train step on the
current backend and print per-op device-time totals (top N).

Usage: python tools/profile_step.py [--config gpt2] [--top 40]
"""

import argparse
import glob
import gzip
import os
import sys
import tempfile
from collections import defaultdict

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_step(config):
    import bench
    on_accel = jax.default_backend() not in ("cpu",)
    state, step, batch, units, iters, metric, unit, proxy = \
        bench.BENCHES[config](on_accel)
    jstep = jax.jit(step)
    # compile + warm
    out = jstep(state, *batch)
    jax.block_until_ready(out)
    return jstep, state, batch


def parse_xspace(path):
    """Walk the XSpace proto: planes -> lines -> events; return
    [(plane_name, line_name, event_name, total_ps, count)] aggregated."""
    # import-location roulette across TF/profiler versions; this image
    # ships it under tensorflow.tsl (verified in the r4 CPU rehearsal —
    # the first two locations exist but are empty namespace dirs)
    xplane_pb2 = None
    for modname in ("tensorflow.tsl.profiler.protobuf.xplane_pb2",
                    "tensorboard_plugin_profile.protobuf.xplane_pb2",
                    "xprof.protobuf.xplane_pb2"):
        try:
            import importlib
            xplane_pb2 = importlib.import_module(modname)
            break
        except ImportError:
            continue
    if xplane_pb2 is None:
        raise ImportError("no xplane_pb2 proto module found")
    data = open(path, "rb").read()
    if path.endswith(".gz"):
        data = gzip.decompress(data)
    space = xplane_pb2.XSpace()
    space.ParseFromString(data)
    rows = []
    for plane in space.planes:
        emeta = {m.id: m.name for m in plane.event_metadata.values()}
        agg = defaultdict(lambda: [0, 0])
        for line in plane.lines:
            for ev in line.events:
                name = emeta.get(ev.metadata_id, str(ev.metadata_id))
                a = agg[(line.name, name)]
                a[0] += ev.duration_ps
                a[1] += 1
        for (ln, name), (ps, n) in agg.items():
            rows.append((plane.name, ln, name, ps, n))
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="gpt2")
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--steps", type=int, default=3)
    args = ap.parse_args()

    # Honor JAX_PLATFORMS despite the container sitecustomize pinning
    # jax_platforms=axon,cpu (the r4 CPU rehearsal caught this script
    # initializing the axon backend under JAX_PLATFORMS=cpu and hanging
    # on the dead tunnel), and probe the backend in a killable
    # SUBPROCESS first — in-process init on a dead tunnel blocks
    # uninterruptibly and would eat the whole tpu_watch phase budget.
    from apex1_tpu.testing import honor_jax_platforms_env
    honor_jax_platforms_env()
    import bench
    backend, probe_stderr = bench.probe_backend()
    if backend is None:
        print(f"backend init unreachable; last stderr: {probe_stderr}",
              flush=True)
        sys.exit(1)

    print(f"backend={jax.default_backend()}", flush=True)
    jstep, state, batch = build_step(args.config)
    print("compiled; tracing...", flush=True)

    tmp = tempfile.mkdtemp(prefix="jaxprof_")
    with jax.profiler.trace(tmp):
        for _ in range(args.steps):
            out = jstep(state, *batch)
        jax.block_until_ready(out)

    paths = glob.glob(os.path.join(tmp, "**", "*.xplane.pb"), recursive=True)
    print(f"trace files: {paths}", flush=True)
    rows = []
    for p in paths:
        rows.extend(parse_xspace(p))

    # device planes only; aggregate across lines by event name
    dev = defaultdict(lambda: [0, 0])
    total = 0
    for plane, line, name, ps, n in rows:
        if "TPU" in plane or "/device:" in plane or "gpu" in plane.lower():
            if "XLA Ops" in line or "XLA Op" in line or line.startswith("XLA"):
                dev[name][0] += ps
                dev[name][1] += n
                total += ps
    if not dev:
        # fallback: dump line names so we can adapt
        seen = sorted({(p, l) for p, l, *_ in rows})
        for p, l in seen[:50]:
            print("plane/line:", p, "|", l)
        return
    print(f"total device op time: {total/1e9/args.steps:.2f} ms/step")
    items = sorted(dev.items(), key=lambda kv: -kv[1][0])
    for name, (ps, n) in items[:args.top]:
        print(f"{ps/1e9/args.steps:9.3f} ms  {n//args.steps:5d}x  "
              f"{ps/total*100:5.1f}%  {name[:110]}")


if __name__ == "__main__":
    main()
