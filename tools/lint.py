#!/usr/bin/env python
"""graftlint CLI — the repo's static JAX-hazard gate.

Usage::

    python tools/lint.py                  # lint apex1_tpu/ tools/ examples/
    python tools/lint.py --kernels        # + APX2xx kernel/collective pass
    python tools/lint.py --protocols      # + APX3xx serving-protocol pass
    python tools/lint.py --json           # machine-readable (baseline bank)
    python tools/lint.py --changed        # only files changed vs merge-base
    python tools/lint.py path/to/file.py  # explicit targets
    python tools/lint.py --list-rules

Exit codes: 0 clean (suppressed findings are fine — each carries a
mandatory reason), 1 unsuppressed findings, 2 usage/internal error.

Parses AND whole-run results are cached in ``.graftlint_cache`` keyed
by (mtime_ns, size) so the repo-wide no-change rerun stays ~1s as the
tree grows (one stat per file); ``--no-cache`` disables it.

The gate also runs as the ``== graftlint ==`` step of
``tools/check_all.sh`` and inside tier-1 via
``tests/test_lint.py::test_repo_self_check``. Rule catalogue and the
suppression grammar: docs/lint.md.
"""

import argparse
import json
import os
import subprocess
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CACHE_PATH = os.path.join(REPO, ".graftlint_cache")


def _import_lint():
    """Import ``apex1_tpu.lint`` WITHOUT executing the package
    ``__init__`` (which imports jax to install the compat bridge —
    ~4s of startup the stdlib-ast linter doesn't need). A stub parent
    module with the real ``__path__`` lets the import machinery find
    the subpackage while skipping the parent's body. ``apex1_tpu.core``
    gets the same stub so the ``--kernels`` budget pass can read
    ``core.capability``'s generation table (itself jax-free; only
    chip *detection* touches jax, and the analyzer passes the planning
    generation explicitly) without running ``core/__init__``'s mesh
    imports. CLI-process-only: the lint subpackage and
    ``apex1_tpu.vmem_model`` import nothing else from apex1_tpu, and
    in-process users (tests, check_all's pytest) import the real
    package normally. tests/test_lint_kernels.py and
    tests/test_lint_protocols.py pin the whole CLI jax-free by running
    it against a poisoned ``jax`` module."""
    for name, sub in (("apex1_tpu", ""), ("apex1_tpu.core", "core")):
        if name not in sys.modules:
            stub = types.ModuleType(name)
            stub.__path__ = [os.path.join(REPO, "apex1_tpu", sub)
                             if sub else os.path.join(REPO, "apex1_tpu")]
            sys.modules[name] = stub
    import apex1_tpu.lint as lint
    return lint


DEFAULT_ROOTS = ["apex1_tpu", "tools", "examples"]

#: candidate refs for the --changed diff base, tried in order. The
#: point (vs plain HEAD): on a feature branch with commits, HEAD-only
#: diffing silently skips everything already committed on the branch —
#: the pre-commit gate must see the whole branch delta.
_BASE_REFS = ("@{upstream}", "origin/main", "origin/master", "main",
              "master")


def merge_base():
    """SHA of the merge-base of HEAD and the first resolvable base
    ref, or "HEAD" when none resolves (detached/fresh/remoteless
    repos keep the old vs-HEAD behavior)."""
    for ref in _BASE_REFS:
        try:
            proc = subprocess.run(
                ["git", "merge-base", "HEAD", ref], cwd=REPO,
                capture_output=True, text=True, check=True)
        except (subprocess.CalledProcessError, OSError):
            continue
        sha = proc.stdout.strip()
        if sha:
            return sha
    return "HEAD"


def changed_files(base=None):
    """Repo-relative .py files touched vs the merge-base (committed on
    the branch, staged, unstaged, and untracked) — the pre-commit
    scope."""
    base = merge_base() if base is None else base
    out = set()
    for args in (["git", "diff", "--name-only", base],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(args, cwd=REPO, capture_output=True,
                                  text=True, check=True)
        except (subprocess.CalledProcessError, OSError) as e:
            print(f"graftlint: --changed needs git: {e}",
                  file=sys.stderr)
            raise SystemExit(2)
        out.update(ln.strip() for ln in proc.stdout.splitlines()
                   if ln.strip())
    keep = []
    for f in sorted(out):
        if not f.endswith(".py"):
            continue
        top = f.split("/", 1)[0]
        if top in DEFAULT_ROOTS and os.path.exists(
                os.path.join(REPO, f)):
            keep.append(f)
    return keep


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="graftlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to lint (default: "
                         f"{' '.join(DEFAULT_ROOTS)})")
    ap.add_argument("--json", action="store_true",
                    help="emit the full JSON report on stdout")
    ap.add_argument("--changed", action="store_true",
                    help="lint only files changed vs the merge-base "
                         "(plus untracked) under the default roots")
    ap.add_argument("--kernels", action="store_true",
                    help="also run the APX2xx kernel/collective "
                         "analyzer (Pallas semaphore/DMA protocol "
                         "model-check, mesh consistency, VMEM budget)")
    ap.add_argument("--protocols", action="store_true",
                    help="also run the APX3xx serving-protocol model "
                         "checker (bounded exhaustive exploration of "
                         "the scheduler/replica/frontend/disagg/"
                         "autopilot state machines)")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the on-disk (mtime,size) parse cache")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed findings (text mode)")
    args = ap.parse_args(argv)

    lint = _import_lint()

    if args.list_rules:
        from apex1_tpu.lint.kernels import KERNEL_RULES
        from apex1_tpu.lint.protocols import PROTOCOL_RULES
        for r in (list(lint.RULES) + list(KERNEL_RULES)
                  + list(PROTOCOL_RULES)):
            print(f"{r.code}  {r.slug:16s} {r.summary}")
        return 0

    cache = None if args.no_cache else CACHE_PATH
    if args.changed:
        if args.paths:
            ap.error("--changed and explicit paths are exclusive")
        files = changed_files()
        if not files:
            if not args.json:
                print("graftlint: no changed .py files under "
                      + ", ".join(DEFAULT_ROOTS))
            else:
                print(json.dumps({"tool": "graftlint", "ok": True,
                                  "n_files": 0, "findings": []}))
            return 0
        res = lint.lint_files([os.path.join(REPO, f) for f in files],
                              root=REPO, kernels=args.kernels,
                              protocols=args.protocols, cache=cache)
    else:
        # fail CLOSED on bad targets: a typoed path in a CI job must
        # not read as a passing gate forever
        for p in args.paths:
            full = p if os.path.isabs(p) else os.path.join(REPO, p)
            if not os.path.exists(full):
                print(f"graftlint: no such path: {p}", file=sys.stderr)
                return 2
        res = lint.lint_paths(args.paths or DEFAULT_ROOTS, root=REPO,
                              kernels=args.kernels,
                              protocols=args.protocols, cache=cache)
        if args.paths and res.n_files == 0:
            print("graftlint: the given paths contain no .py files",
                  file=sys.stderr)
            return 2

    if args.json:
        print(json.dumps(res.as_dict(), indent=2))
        return 0 if res.ok else 1

    shown = res.findings if args.show_suppressed else res.unsuppressed()
    for f in shown:
        print(f.render())
    for path, line, rules in res.unused:
        print(f"{path}:{line}: note: unused suppression for {rules}")
    n_bad = len(res.unsuppressed())
    n_sup = len(res.suppressed())
    print(f"graftlint: {res.n_files} files, {n_bad} finding"
          f"{'s' if n_bad != 1 else ''}"
          f" ({n_sup} suppressed with reasons)")
    return 0 if res.ok else 1


if __name__ == "__main__":
    sys.exit(main())
