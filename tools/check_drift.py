#!/usr/bin/env python
"""check_drift — the perf-drift tripwire (ROADMAP item 4's last
clause): pin ``calibrated_ratio`` bands over the banked
``perf_results`` corpus so any drift on banked history fails LOUD.

The committed ``perf_results/calibration.json`` is the fleet's banked
performance memory: per-key slowdown factors fit from every joinable
(predicted, measured) pair (`apex1_tpu.obs.calibrate`). This gate
re-collects those pairs from the logs/tables as they exist NOW and
checks, for every banked measurement, its calibrated ratio

    calibrated_ratio = factor.slowdown / pair.slowdown
                     = measured_rate / (predicted_rate / factor)

against a stated band (default [0.70, 1.45] — outside PR 10's pinned
x1.35 residual envelope with margin). It also re-FITS the factors on
the current corpus and requires them within ``--refit-tol`` (default
5%) of the committed table, and requires the key sets to match
exactly. So ALL of these fail loud instead of rotting silently:

- a new banked record (hardware window, bad merge) whose
  calibrated_ratio says the fleet got slower/faster than banked
  history — the regression signal `bench._attach_roofline` stamps,
  enforced at CI time instead of eyeballed;
- an edited/corrupted log shifting a fitted factor;
- re-swept tuning tables or new logs without a calibration re-fit
  (run ``python -m apex1_tpu.obs.calibrate`` and commit);
- an unreadable calibration table or corpus file (exit 2,
  fail-closed: a gate that can't read its evidence must not pass).

jax-free by the same stub-parent import as tools/lint.py (the
capability table is jax-free when the generation is explicit, and the
generation comes from the committed table) — the gate costs ~1s in
check_all's ``== drift gate ==`` step.

Exit codes: 0 in-band, 1 drift, 2 fail-closed (unreadable evidence).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: default calibrated_ratio band: PR 10 pinned post-fit residuals
#: within x1.35 on the banked corpus; the gate allows a hair more so
#: it trips on NEW drift, not on the committed history re-checking
#: itself
BAND = (0.70, 1.45)
REFIT_TOL = 0.05


def _import_calibrate():
    """Import ``apex1_tpu.obs.calibrate`` without executing the
    package ``__init__`` (which imports jax for the compat bridge) —
    the lint.py stub-parent recipe. ``apex1_tpu.core`` gets the same
    stub so the lazy capability lookups inside calibrate stay
    jax-free (explicit generation ⇒ no chip detection)."""
    for name, sub in (("apex1_tpu", ""), ("apex1_tpu.core", "core")):
        if name not in sys.modules:
            stub = types.ModuleType(name)
            stub.__path__ = [os.path.join(REPO, "apex1_tpu", sub)
                             if sub else os.path.join(REPO, "apex1_tpu")]
            sys.modules[name] = stub
    import apex1_tpu.obs.calibrate as calibrate
    return calibrate


def fail_closed(msg: str) -> int:
    print(f"DRIFT GATE FAIL-CLOSED: {msg}", file=sys.stderr, flush=True)
    return 2


def _check_corpus_readable(calibrate, results_dir: str,
                           tuning_dir: str) -> list:
    """Every evidence file that EXISTS must be readable and, for
    tables, parseable — the collectors deliberately degrade on damage
    (a decorating consumer must not die), but a GATE that silently
    skips damaged evidence is a gate that passes on corruption."""
    problems = []
    for logname in sorted(calibrate.LOG_TO_CONFIG):
        path = os.path.join(results_dir, logname)
        if not os.path.exists(path):
            continue
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                f.read()
        except OSError as e:
            problems.append(f"{path}: unreadable ({e})")
    if os.path.isdir(tuning_dir):
        for name in sorted(os.listdir(tuning_dir)):
            if not name.endswith(".json"):
                continue
            path = os.path.join(tuning_dir, name)
            try:
                with open(path, encoding="utf-8") as f:
                    json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                problems.append(f"{path}: unreadable/corrupt ({e})")
    return problems


def run_gate(results_dir: str, *, calibration_path: str = None,
             band: tuple = BAND, refit_tol: float = REFIT_TOL,
             json_out: bool = False) -> int:
    calibrate = _import_calibrate()
    cal_path = calibration_path or os.path.join(results_dir,
                                                calibrate.CAL_NAME)
    # fail-closed table load: load_calibration's lenient None would
    # let a corrupt table pass the gate as "no factors, no drift"
    try:
        with open(cal_path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return fail_closed(f"cannot read calibration table "
                           f"{cal_path}: {e}")
    if not isinstance(doc, dict) or doc.get("schema") != calibrate.SCHEMA:
        return fail_closed(
            f"{cal_path}: schema {doc.get('schema')!r} != "
            f"{calibrate.SCHEMA!r}" if isinstance(doc, dict)
            else f"{cal_path}: not a JSON object")
    generation = str(doc.get("generation", "v5e"))
    # keyed by (key, backend): the same key can carry BOTH a tpu
    # factor and a cpu-proxy factor — one flat dict would let the
    # proxy entry shadow the tpu one and the gate would cry
    # UNCALIBRATED on a perfectly committed table
    banked = {(k, v.get("backend")): v
              for table in ("factors", "proxy_factors")
              for k, v in doc.get(table, {}).items()}

    env = os.environ.get("APEX1_TUNING_DIR", "").strip()
    tuning_dir = env or os.path.join(results_dir, "tuning")
    problems = _check_corpus_readable(calibrate, results_dir, tuning_dir)
    if problems:
        return fail_closed("; ".join(problems))

    pairs, _excluded = calibrate.collect_pairs(results_dir, generation,
                                               tuning_dir)
    rows, drifted = [], []
    for p in pairs:
        f = banked.get((p.key, p.backend))
        if f is None:
            drifted.append(p)
            rows.append((p, None, "UNCALIBRATED (re-fit + commit "
                                  "calibration.json)"))
            continue
        ratio = f["slowdown"] / p.slowdown
        ok = band[0] <= ratio <= band[1]
        if not ok:
            drifted.append(p)
        rows.append((p, ratio, "ok" if ok else
                     f"DRIFT (band [{band[0]}, {band[1]}])"))

    # re-fit drift: the committed factors must still be what the
    # corpus says (same keys, within tol) — new evidence requires a
    # recommitted table, not a silently stale one
    fresh_tpu, fresh_proxy = calibrate.fit(pairs)
    fresh = {(k, v.get("backend")): v
             for table in (fresh_tpu, fresh_proxy)
             for k, v in table.items()}
    refit_bad = []
    for key in sorted(set(banked) | set(fresh)):
        b, g = banked.get(key), fresh.get(key)
        if b is None or g is None:
            refit_bad.append((key, b, g, "key set changed"))
            continue
        rel = abs(g["slowdown"] - b["slowdown"]) / b["slowdown"]
        if rel > refit_tol:
            refit_bad.append((key, b, g, f"re-fit moved {rel:.1%} "
                                         f"(> {refit_tol:.0%})"))

    for p, ratio, verdict in rows:
        r = "      -" if ratio is None else f"{ratio:7.3f}"
        print(f"  [{p.backend:9s}] {p.key:28s} ratio {r}  "
              f"({p.source})  {verdict}")
        if verdict != "ok":
            # a failure must NAME the offending record so the fix is
            # one open() away, not a corpus-wide hunt
            print(f"              offending record: "
                  f"{os.path.join(results_dir, p.source)}")
    for (key, backend), b, g, why in refit_bad:
        bs = "-" if b is None else f"{b['slowdown']:.4f}"
        gs = "-" if g is None else f"{g['slowdown']:.4f}"
        print(f"  [refit    ] {f'{key} ({backend})':28s} banked {bs} "
              f"vs corpus {gs}  REFIT DRIFT: {why}")
        srcs = sorted(set((g or b or {}).get("sources", [])))
        if srcs:
            print("              offending record(s): "
                  + ", ".join(os.path.join(results_dir, s)
                              for s in srcs))
    n_bad = len(drifted) + len(refit_bad)
    print(f"drift gate: {len(rows)} banked measurement(s) vs "
          f"{len(banked)} committed factor(s), band "
          f"[{band[0]}, {band[1]}], refit tol {refit_tol:.0%} -> "
          f"{'OK' if n_bad == 0 else f'{n_bad} FAILURE(S)'}",
          flush=True)
    if json_out:
        print(json.dumps({
            "pairs": len(rows), "factors": len(banked),
            "band": list(band), "refit_tol": refit_tol,
            "drifted": [p.key for p in drifted],
            "refit_drift": [f"{k} ({b})" for (k, b), *_ in refit_bad]}))
    return 0 if n_bad == 0 else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--results", default=os.path.join(REPO,
                                                      "perf_results"))
    ap.add_argument("--calibration", default=None,
                    help="calibration table (default "
                         "<results>/calibration.json)")
    ap.add_argument("--band", nargs=2, type=float, default=list(BAND),
                    metavar=("LO", "HI"),
                    help=f"allowed calibrated_ratio band "
                         f"(default {BAND[0]} {BAND[1]})")
    ap.add_argument("--refit-tol", type=float, default=REFIT_TOL,
                    help="max relative movement of a re-fit factor "
                         "vs the committed one (default 0.05)")
    ap.add_argument("--json", action="store_true",
                    help="append a JSON verdict line")
    args = ap.parse_args(argv)
    return run_gate(args.results, calibration_path=args.calibration,
                    band=tuple(args.band), refit_tol=args.refit_tol,
                    json_out=args.json)


if __name__ == "__main__":
    sys.exit(main())
