"""Measure the scan-pipeline's cost model (VERDICT r1 next#6):

1. bubble-FLOP overhead — `cost_analysis` FLOPs of the pipelined fwd+bwd
   vs the unpartitioned model on the same global batch (predicted ratio:
   (VM + P − 1) / (VM) since bubble ticks execute `stage_fn` on zeros);
2. activation memory — `memory_analysis` temp bytes of the pipeline
   step with and without the `remat_stage` lever.

Runs on the virtual CPU mesh (analysis only; no TPU needed).
Usage: python tools/pipeline_cost.py [--layers 8] [--hidden 1024]
       [--mb 2] [--seq 256] [--microbatches 8] [--pp 4]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apex1_tpu.testing import (enable_persistent_compilation_cache,
                               force_virtual_cpu_devices)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=1024)
    ap.add_argument("--mb", type=int, default=2)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--microbatches", type=int, default=8)
    ap.add_argument("--pp", type=int, default=4)
    args = ap.parse_args()

    force_virtual_cpu_devices(max(args.pp, 4))
    enable_persistent_compilation_cache()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as Ps

    from apex1_tpu.core.mesh import make_mesh
    from apex1_tpu.transformer.pipeline_parallel.schedules import (
        pipeline_apply)

    P_, M, L = args.pp, args.microbatches, args.layers
    lps = L // P_
    E, mb, S = args.hidden, args.mb, args.seq
    mesh = make_mesh(pp=P_, dp=1)
    rng = np.random.default_rng(0)
    params = jnp.asarray(rng.normal(size=(1, P_, lps, E, E)) * 0.02,
                         jnp.float32)
    mbs = jnp.asarray(rng.normal(size=(M, S, mb, E)), jnp.float32)

    def stage(p_stage, x):
        # unrolled so cost_analysis counts every layer (scan bodies are
        # priced once regardless of trip count)
        def layer(x, w):
            return x + jnp.tanh(x @ w)
        x, _ = jax.lax.scan(lambda x, w: (layer(x, w), None), x, p_stage,
                            unroll=True)
        return x

    def pipe_loss(params, mbs, remat, unroll, skip=True):
        def inner(params, mbs):
            s = jax.lax.axis_index("pp")
            last = (s == P_ - 1).astype(jnp.float32)
            outs = pipeline_apply(stage, params[:, 0], mbs,
                                  broadcast_outputs=False,
                                  remat_stage=remat, scan_unroll=unroll,
                                  skip_bubbles=skip)
            return last * jnp.mean(jnp.square(outs))

        return jax.shard_map(inner, mesh=mesh,
                             in_specs=(Ps(None, "pp"), Ps()),
                             out_specs=Ps(), check_vma=False)(params, mbs)

    def flat_loss(params, mbs):
        def apply_all(x):
            for s in range(P_):
                x = stage(params[0, s], x)
            return x
        return jnp.mean(jnp.square(jax.vmap(apply_all)(mbs)))

    def analyze(name, fn, *a):
        c = jax.jit(jax.value_and_grad(fn)).lower(*a).compile()
        cost = c.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        mem = c.memory_analysis()
        fl = float(cost.get("flops", float("nan")))
        print(f"{name:34s} flops {fl/1e9:8.2f} G   "
              f"temp {mem.temp_size_in_bytes/2**20:8.1f} MiB")
        return fl, mem.temp_size_in_bytes

    print(f"pp={P_} M={M} V=1 layers={L} hidden={E} mb={mb} seq={S}")
    fl_flat, _ = analyze("unpartitioned fwd+bwd",
                         lambda p: flat_loss(p, mbs), params)
    # FLOPs need the tick scan UNROLLED (cost_analysis prices a scan body
    # once); memory uses the production rolled form
    fl_pipe, _ = analyze("pipeline fwd+bwd (unrolled ticks)",
                         lambda p: pipe_loss(p, mbs, False, True), params)
    _, tmp_pipe = analyze("pipeline fwd+bwd",
                          lambda p: pipe_loss(p, mbs, False, 1), params)
    _, tmp_remat = analyze("pipeline fwd+bwd (remat_stage)",
                           lambda p: pipe_loss(p, mbs, True, 1), params)
    pred = (M + P_ - 1) / M
    # fl_pipe is PER-DEVICE; the flat program runs the whole model on one
    # device, so total pipeline work = P x per-device. NOTE: static
    # cost_analysis prices a lax.cond's branches whether or not they run,
    # so with skip_bubbles this ratio is an UPPER bound — the executed
    # ratio is measured by the wall-clock A/B below.
    print(f"\nbubble-FLOP ratio pipeline/flat (static): "
          f"{P_ * fl_pipe / fl_flat:.3f}  "
          f"(mask-only predicted (M+P-1)/M = {pred:.3f})")
    print(f"activation temp: naive {tmp_pipe/2**20:.1f} MiB -> remat "
          f"{tmp_remat/2**20:.1f} MiB "
          f"({tmp_pipe / max(tmp_remat, 1):.2f}x reduction)")

    # --- true 1F1B: memory/FLOPs vs the grad-through-scan forms ---
    from apex1_tpu.transformer.pipeline_parallel.schedules import (
        one_f_one_b)

    def fb_1f1b(params, mbs):
        def loss_mb(y, m):
            return jnp.mean(jnp.square(y)) / M

        def inner(params, mbs):
            loss, grads, dmb = one_f_one_b(stage, params[0, 0], mbs,
                                           loss_mb)
            return jax.lax.psum(loss, "pp"), grads[None, None], dmb

        return jax.shard_map(inner, mesh=mesh,
                             in_specs=(Ps(None, "pp"), Ps()),
                             out_specs=(Ps(), Ps(None, "pp"), Ps()),
                             check_vma=False)(params, mbs)

    c = jax.jit(fb_1f1b).lower(params, mbs).compile()
    mem = c.memory_analysis()
    print(f"{'true 1F1B (one_f_one_b)':34s} flops      n/a   "
          f"temp {mem.temp_size_in_bytes/2**20:8.1f} MiB   "
          f"(ring: P x activations, no recompute)")

    # --- bubble-skip A/B: does the lax.cond actually elide the compute? ---
    import time

    def timed(fn, *a, iters=5):
        c = jax.jit(jax.value_and_grad(fn)).lower(*a).compile()
        hlo = c.as_text()
        has_cond = " conditional(" in hlo or "conditional." in hlo
        jax.block_until_ready(c(*a))  # warmup
        t0 = time.perf_counter()
        for _ in range(iters):
            out = c(*a)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters, has_cond

    t_skip, cond_in_hlo = timed(
        lambda p: pipe_loss(p, mbs, False, 1, skip=True), params)
    t_mask, _ = timed(
        lambda p: pipe_loss(p, mbs, False, 1, skip=False), params)
    # ideal executed-tick ratio: mask runs T=M+P-1 stage ticks, skip runs M
    print(f"\nbubble-skip wall-clock A/B (fwd+bwd, rolled scan): "
          f"mask {t_mask*1e3:.1f} ms -> cond-skip {t_skip*1e3:.1f} ms "
          f"({t_mask/t_skip:.3f}x; ideal {(M+P_-1)/M:.3f}x), "
          f"HLO conditional present: {cond_in_hlo}")


if __name__ == "__main__":
    main()
