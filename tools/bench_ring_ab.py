"""A/B: serialized vs double-buffered ring attention at the 16k
llama_longctx attention shape — the wall-clock form of the overlap win
the hlo_probe pins structurally and predict_perf's comms term prices
analytically (VERDICT r5 Weak #5: the exposed ppermute latency at 16k
context is the largest unclaimed perf item; llama_longctx measured
0.36x its roofline).

Runs the SAME fwd+bwd attention step through
`parallel.ring_attention_serial` (rotate→attend, every transfer
exposed) and `parallel.ring_attention` (double-buffered, custom-VJP
overlapped backward) over a cp ring and emits one JSON line with both
timings. Queue entry ``ring_overlap_ab`` in tools/tpu_watch.sh runs it
AHEAD of the llama_longctx re-bench.

Device requirements: a cp ring needs >= 2 devices. On a single-chip
window the tool emits a skip record (rc 0 — the queue must keep
moving); on CPU (rehearsal) it builds the 8-device virtual mesh and
auto-shrinks shapes, validating the command line end-to-end.

Usage: python tools/bench_ring_ab.py [--cp N] [--iters K] [--seq S]
"""

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _emit(record):
    print(json.dumps(record), flush=True)


def _backend_is_cpu(timeout_s=120.0):
    """Probe the default backend in a SUBPROCESS (the main process must
    not initialize a backend before deciding whether to build the
    8-device virtual CPU mesh — device-count flags only act before
    first init). False on probe failure: a dead accelerator tunnel then
    follows the accelerator path, whose init failure is the honest
    error (tpu_watch only runs this entry after its tunnel probe)."""
    import subprocess
    code = ("import os, jax; p = os.environ.get('JAX_PLATFORMS'); "
            "p and jax.config.update('jax_platforms', p); "
            "print('BACKEND=' + jax.default_backend())")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s)
        return "BACKEND=cpu" in out.stdout
    except Exception:
        return False


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cp", type=int, default=None,
                    help="ring size (default: all available devices)")
    ap.add_argument("--iters", type=int, default=None)
    ap.add_argument("--seq", type=int, default=None,
                    help="GLOBAL sequence length (default 16384 on "
                         "accelerators, 512 on cpu)")
    args = ap.parse_args()

    import jax

    # env pin wins when present; otherwise ask the backend itself (in a
    # subprocess) so a plain CPU-only box rehearses on the virtual mesh
    # instead of emitting a bogus single-device skip
    plat = os.environ.get("JAX_PLATFORMS", "").strip()
    on_cpu = plat == "cpu" if plat else _backend_is_cpu()
    if on_cpu:
        from apex1_tpu.testing import force_virtual_cpu_devices
        force_virtual_cpu_devices(8)
    else:
        from apex1_tpu.testing import honor_jax_platforms_env
        honor_jax_platforms_env()
    from apex1_tpu.testing import enable_persistent_compilation_cache
    enable_persistent_compilation_cache()

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from apex1_tpu.core.mesh import make_mesh
    from apex1_tpu.parallel.ring_attention import (ring_attention,
                                                   ring_attention_serial)

    backend = jax.default_backend()
    devices = jax.devices()
    n = args.cp or min(len(devices), 8)
    if n < 2:
        _emit({"metric": f"ring_overlap_ab [{backend}]", "value": 0.0,
               "error": f"cp ring needs >= 2 devices, have "
                        f"{len(devices)} — skipped (multichip window "
                        f"required)"})
        return
    accel = backend not in ("cpu",)
    # llama_longctx attention shape (B=1, Hq=32, Hkv=4, D=64, S=16k);
    # cpu rehearsal auto-shrinks like bench.py configs do
    if accel:
        B, Hq, Hkv, D = 1, 32, 4, 64
        S = args.seq or 16384
        iters = args.iters or 8
        dtype = jnp.bfloat16
    else:
        B, Hq, Hkv, D = 1, 4, 2, 16
        S = args.seq or 512
        iters = args.iters or 2
        dtype = jnp.float32
    mesh = make_mesh(cp=n, dp=1, devices=devices[:n])
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, Hq, S, D)), dtype)
    k = jnp.asarray(rng.normal(size=(B, Hkv, S, D)), dtype)
    v = jnp.asarray(rng.normal(size=(B, Hkv, S, D)), dtype)
    spec = P(None, None, "cp", None)

    def timed(ring_fn, name):
        sm = jax.shard_map(
            lambda q, k, v: ring_fn(q, k, v, "cp", causal=True),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
            check_vma=False)

        def loss(q, k, v):
            return jnp.sum(sm(q, k, v).astype(jnp.float32) ** 2)

        grad = jax.grad(loss, argnums=(0, 1, 2))

        def many(q, k, v):
            # fwd+bwd iters in ONE dispatch (bench.py methodology: the
            # tunneled backend's dispatch latency must not pollute it);
            # each iteration's q depends on the previous gradient so the
            # loop body is NOT loop-invariant (XLA would hoist a single
            # grad out and the timing would measure one step, not iters)
            def one(q):
                dq, dk, dv = grad(q, k, v)
                return (q + (1e-6 * dq).astype(q.dtype),
                        jnp.sum(dq) + jnp.sum(dk) + jnp.sum(dv))

            def body(_, carry):
                q, _acc = carry
                return one(q)

            return jax.lax.fori_loop(0, iters - 1, body, one(q))

        compiled = jax.jit(many).lower(q, k, v).compile()
        out = compiled(q, k, v)
        jax.block_until_ready(out)              # warmup
        t0 = time.perf_counter()
        out = compiled(q, k, v)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters
        s = float(out[1])
        if not math.isfinite(s):
            raise RuntimeError(f"{name}: non-finite check value {s}")
        return dt

    try:
        t_serial = timed(ring_attention_serial, "serial")
        t_overlap = timed(ring_attention, "overlapped")
        _emit({
            "metric": f"ring_overlap_ab fwd+bwd cp={n} S={S} "
                      f"[{backend}]",
            "value": round(t_serial / t_overlap, 4),   # speedup
            "unit": "x (serial/overlapped step time)",
            "serial_ms": round(t_serial * 1e3, 3),
            "overlapped_ms": round(t_overlap * 1e3, 3),
            "shape": {"B": B, "Hq": Hq, "Hkv": Hkv, "S": S, "D": D,
                      "cp": n, "iters": iters},
        })
    except Exception as e:
        _emit({"metric": f"ring_overlap_ab [{backend}]", "value": 0.0,
               "error": f"{type(e).__name__}: {str(e)[:300]}"})
        sys.exit(1)


if __name__ == "__main__":
    main()
