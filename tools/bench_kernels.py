"""Microbenchmarks for the Pallas kernels on the current backend.

Times fwd and fwd+bwd against the XLA-composite golds: flash attention
and the fused LM-head CE across block sizes; layer/rms norm, causal
softmax, RoPE, and plain xentropy as pallas-vs-xla A/Bs; fused_dense as
an achieved-TFLOPs roofline check; the flat-buffer fused optimizer vs
per-tensor optax. Prints immediately (unbuffered) — safe to tail.

Usage: python tools/bench_kernels.py
         [attn|xent|norm|softmax|rope|xent_plain|dense|opt|all] [--llama]
"""

import argparse
import functools
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timeit(fn, *args, iters=20):
    """Seconds/call with the loop in ONE dispatch (tunnel latency hidden).

    Each iteration's inputs depend on the previous output (a 0-valued
    scalar tap added to every float arg) so XLA cannot hoist the
    loop-invariant call out of the fori_loop."""
    fn2 = jax.jit(fn)

    def many(n, args):
        def body(_, carry):
            cargs, out = carry
            eps = jax.tree.leaves(out)[0].ravel()[0] * 0
            cargs = jax.tree.map(
                lambda a: (a + eps.astype(a.dtype)
                           if jnp.issubdtype(a.dtype, jnp.floating) else a),
                cargs)
            return cargs, fn2(*cargs)
        return jax.lax.fori_loop(0, n, body, (args, fn2(*args)))[1]

    manyj = jax.jit(many, static_argnums=0)
    out = manyj(iters, args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = manyj(iters, args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / (iters + 1)
    return dt


def bench_attn(shape):
    from apex1_tpu.ops.attention import _xla_attention, flash_attention
    B, H, S, D = shape
    print(f"== flash attention (B,H,S,D)=({B},{H},{S},{D}) causal bf16 ==",
          flush=True)
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.bfloat16)

    def xla_fn(q, k, v):
        return _xla_attention(q, k, v, None, None, 0, 0, 0.125, True)

    def xla_grad(q, k, v):
        return jax.grad(lambda q, k, v: jnp.sum(
            xla_fn(q, k, v).astype(jnp.float32)), argnums=(0, 1, 2))(q, k, v)

    dt = timeit(xla_fn, q, k, v)
    print(f"  xla fwd                  {dt*1e3:8.2f} ms", flush=True)
    dt = timeit(xla_grad, q, k, v)
    print(f"  xla fwd+bwd              {dt*1e3:8.2f} ms", flush=True)

    for bq, bk in [(128, 128), (256, 256), (256, 512), (512, 512),
                   (512, 1024), (1024, 1024)]:
        if bq > S or bk > S:
            continue
        f = functools.partial(flash_attention, causal=True,
                              block_q=bq, block_k=bk)
        def g(q, k, v):
            return jax.grad(lambda q, k, v: jnp.sum(
                f(q, k, v).astype(jnp.float32)), argnums=(0, 1, 2))(q, k, v)
        try:
            dt = timeit(f, q, k, v)
            dt2 = timeit(g, q, k, v)
            print(f"  flash bq={bq:4d} bk={bk:4d}   fwd {dt*1e3:8.2f} ms   "
                  f"fwd+bwd {dt2*1e3:8.2f} ms", flush=True)
        except Exception as e:
            print(f"  flash bq={bq} bk={bk}: {type(e).__name__}: "
                  f"{str(e)[:120]}", flush=True)

    # additive-bias A/B (T5 rel-pos path): flash+bias (O(S·D) activations
    # + the dbias pass) vs the biased XLA composite (O(S²) scores) —
    # the number behind docs/ops.md's bias-row claim. Skipped at long-ctx
    # shapes: the (1, H, S, S) bias itself is O(S²) host memory (~17 GiB
    # at 16k), so the A/B is only meaningful at rel-pos-scale S
    if S > 4096:
        print(f"  (bias A/B skipped at S={S}: the bias operand itself "
              f"is O(S²))", flush=True)
        return
    bias = jnp.asarray(
        rng.normal(size=(1, H, S, S)).astype(np.float32), jnp.bfloat16)

    def xla_bias_grad(q, k, v, b):
        return jax.grad(lambda q, k, v, b: jnp.sum(
            _xla_attention(q, k, v, None, None, 0, 0, 0.125, False,
                           bias=b).astype(jnp.float32)),
            argnums=(0, 1, 2, 3))(q, k, v, b)

    def flash_bias_grad(q, k, v, b):
        return jax.grad(lambda q, k, v, b: jnp.sum(
            flash_attention(q, k, v, bias=b).astype(jnp.float32)),
            argnums=(0, 1, 2, 3))(q, k, v, b)

    for name, fn in (("xla +bias fwd+bwd", xla_bias_grad),
                     ("flash +bias fwd+bwd", flash_bias_grad)):
        try:
            dt = timeit(fn, q, k, v, bias)
            print(f"  {name:22s} {dt*1e3:8.2f} ms", flush=True)
        except Exception as e:
            print(f"  {name}: {type(e).__name__}: {str(e)[:120]}",
                  flush=True)


def bench_xent(T, H, V):
    from apex1_tpu.ops.linear_xent import (_xla_linear_xent,
                                           linear_cross_entropy)
    print(f"== linear_xent T={T} H={H} V={V} bf16 ==", flush=True)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(T, H)) * 0.02, jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(V, H)) * 0.02, jnp.bfloat16)
    t = jnp.asarray(rng.integers(0, V - 300, (T,)), jnp.int32)

    def xla_fn(x, w):
        return jnp.mean(_xla_linear_xent(x, w, t, 0.0, None, V - 300))

    dt = timeit(xla_fn, x, w)
    print(f"  xla fwd                  {dt*1e3:8.2f} ms", flush=True)
    dt = timeit(jax.grad(xla_fn, argnums=(0, 1)), x, w)
    print(f"  xla fwd+bwd              {dt*1e3:8.2f} ms", flush=True)

    for bt, bv in [(256, 512), (512, 512), (512, 1024), (1024, 1024),
                   (256, 2048), (512, 2048)]:
        def f(x, w, bt=bt, bv=bv):
            return jnp.mean(linear_cross_entropy(
                x, w, t, num_classes=V - 300, block_t=bt, block_v=bv))
        try:
            dt = timeit(f, x, w)
            dt2 = timeit(jax.grad(f, argnums=(0, 1)), x, w)
            print(f"  fused bt={bt:4d} bv={bv:4d}   fwd {dt*1e3:8.2f} ms   "
                  f"fwd+bwd {dt2*1e3:8.2f} ms", flush=True)
        except Exception as e:
            print(f"  fused bt={bt} bv={bv}: {type(e).__name__}: "
                  f"{str(e)[:120]}", flush=True)


def bench_norm(R, H):
    from apex1_tpu.ops import layer_norm, rms_norm
    from apex1_tpu.ops._common import force_impl
    print(f"== layer_norm rows={R} H={H} bf16 ==", flush=True)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(R, H)), jnp.bfloat16)
    g = jnp.ones((H,), jnp.float32)
    b = jnp.zeros((H,), jnp.float32)

    for name, op in (("ln", lambda x, impl: layer_norm(x, g, b)),
                     ("rms", lambda x, impl: rms_norm(x, g))):
        for impl in ("xla", "pallas"):
            def f(x, name=name, op=op, impl=impl):
                with force_impl(impl):
                    return jnp.sum(op(x, impl).astype(jnp.float32))
            dt = timeit(f, x)
            dt2 = timeit(jax.grad(f), x)
            print(f"  {name:4s} {impl:6s} fwd {dt*1e3:8.3f} ms   fwd+bwd "
                  f"{dt2*1e3:8.3f} ms", flush=True)


def _ab_bench(title, x, op):
    """pallas-vs-xla A/B: times fwd and fwd+bwd of ``op(x) -> scalar``
    under each dispatch mode."""
    from apex1_tpu.ops._common import force_impl
    print(f"== {title} ==", flush=True)
    for impl in ("xla", "pallas"):
        def f(x, impl=impl):
            with force_impl(impl):
                return op(x)
        dt = timeit(f, x)
        dt2 = timeit(jax.grad(f), x)
        print(f"  {impl:6s} fwd {dt*1e3:8.3f} ms   fwd+bwd "
              f"{dt2*1e3:8.3f} ms", flush=True)


def bench_softmax(B, H, S):
    from apex1_tpu.ops import scaled_upper_triang_masked_softmax
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(B, H, S, S)), jnp.float32)
    _ab_bench(f"causal softmax (B,H,S,S)=({B},{H},{S},{S}) fp32", x,
              lambda x: jnp.sum(scaled_upper_triang_masked_softmax(
                  x, scale=0.125)))


def bench_rope(B, S, H, D):
    from apex1_tpu.ops import apply_rotary_pos_emb, rope_tables
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.bfloat16)
    cos, sin = rope_tables(jnp.arange(S), D)
    _ab_bench(f"rope (B,S,H,D)=({B},{S},{H},{D}) bf16", x,
              lambda x: jnp.sum(apply_rotary_pos_emb(x, cos, sin)
                                .astype(jnp.float32)))


def bench_xent_plain(T, V):
    from apex1_tpu.ops import softmax_cross_entropy_loss
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(T, V)), jnp.float32)
    t = jnp.asarray(rng.integers(0, V - 200, (T,)), jnp.int32)
    _ab_bench(f"xentropy T={T} V={V} fp32", x,
              lambda x: jnp.mean(softmax_cross_entropy_loss(
                  x, t, num_classes=V - 200)))


def bench_int8(T, N, K):
    """int8 weight-only decode GEMM A/B: Pallas dequant-in-VMEM kernel vs
    the XLA dequant composite vs plain bf16 matmul. Decode is HBM-bound,
    so the interesting number is achieved GB/s of weight traffic — the
    int8 paths should approach 2x the bf16 tokens/step at small T."""
    from apex1_tpu.ops import force_impl, int8_matmul, quantize_int8
    print(f"== int8 weight-only GEMM ({T},{K})x({N},{K}) ==", flush=True)
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(N, K)) * 0.02, jnp.float32)
    x = jnp.asarray(rng.normal(size=(T, K)), jnp.bfloat16)
    wq, s = quantize_int8(w)
    wb = w.astype(jnp.bfloat16)
    cases = (
        ("bf16 matmul", lambda x: jnp.matmul(
            x, wb.T, preferred_element_type=jnp.float32), None),
        ("int8 xla composite", lambda x: int8_matmul(x, wq, s), "xla"),
        ("int8 pallas kernel", lambda x: int8_matmul(x, wq, s), "pallas"),
    )
    for name, fn, impl in cases:
        if impl is None:
            dt = timeit(fn, x)
            wbytes = N * K * 2
        else:
            with force_impl(impl):
                dt = timeit(fn, x)
            wbytes = N * K
        print(f"  {name:22s} {dt*1e3:8.3f} ms  weight {wbytes/2**20:6.1f} "
              f"MiB -> {wbytes/dt/2**30:6.1f} GiB/s", flush=True)


def bench_dense(B, In, Hid):
    """fused_dense decision check: gemm+bias+gelu(+gemm) in one jit —
    achieved TFLOP/s vs chip peak tells whether XLA's epilogue fusion
    leaves anything on the table (the 'XLA already fuses this' claim)."""
    from apex1_tpu.core.capability import get_capability
    from apex1_tpu.ops.fused_dense import fused_dense_gelu_dense
    print(f"== fused_dense_gelu_dense B={B} {In}->{Hid}->{In} bf16 ==",
          flush=True)
    rng = np.random.default_rng(0)
    # torch nn.Linear weight convention: (out_features, in_features)
    x = jnp.asarray(rng.normal(size=(B, In)) * 0.02, jnp.bfloat16)
    w1 = jnp.asarray(rng.normal(size=(Hid, In)) * 0.02, jnp.bfloat16)
    b1 = jnp.zeros((Hid,), jnp.bfloat16)
    w2 = jnp.asarray(rng.normal(size=(In, Hid)) * 0.02, jnp.bfloat16)
    b2 = jnp.zeros((In,), jnp.bfloat16)

    def f(x, w1, b1, w2, b2):
        return jnp.sum(fused_dense_gelu_dense(x, w1, b1, w2, b2)
                       .astype(jnp.float32))

    flops = 2 * B * In * Hid * 2          # two gemms
    for name, fn in (("fwd", f), ("fwd+bwd", jax.grad(f, argnums=(0, 1, 2,
                                                                  3, 4)))):
        mult = 1 if name == "fwd" else 3
        dt = timeit(fn, x, w1, b1, w2, b2)
        tf = flops * mult / dt / 1e12
        peak = get_capability().bf16_tflops
        print(f"  {name:8s} {dt*1e3:8.2f} ms  ~{tf:6.1f} TF/s "
              f"({100 * tf / peak:4.1f}% of {peak:.0f} peak)", flush=True)


def bench_opt(n_leaves=148, leaf=(1024, 768)):
    """flat-buffer fused update (multi_tensor_apply analog) vs per-tensor
    optax adam over a GPT-2-sized tree."""
    import optax

    from apex1_tpu.optim.fused_adam import fused_adam
    print(f"== optimizer: {n_leaves} leaves x {leaf} fp32 ==", flush=True)
    rng = np.random.default_rng(0)
    params = {f"p{i}": jnp.asarray(rng.normal(size=leaf), jnp.float32)
              for i in range(n_leaves)}
    grads = {f"p{i}": jnp.asarray(rng.normal(size=leaf), jnp.float32)
             for i in range(n_leaves)}
    for name, tx in (("fused_adam (flat)", fused_adam(1e-4)),
                     ("optax.adam (per-tensor)", optax.adam(1e-4))):
        state = tx.init(params)

        def f(params, grads, state, tx=tx):
            up, st = tx.update(grads, state, params)
            return optax.apply_updates(params, up), st

        dt = timeit(f, params, grads, state)
        print(f"  {name:26s} {dt*1e3:8.2f} ms/step", flush=True)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("what", nargs="?", default="all",
                    choices=["attn", "xent", "norm", "softmax", "rope",
                             "xent_plain", "dense", "int8", "opt", "all"])
    ap.add_argument("--llama", action="store_true",
                    help="long-context llama shapes instead of GPT-2")
    ap.add_argument("--tiny", action="store_true",
                    help="tiny shapes for the CPU rehearsal of the "
                         "tpu_watch queue — validates every code path, "
                         "not the timings")
    args = ap.parse_args()
    from apex1_tpu.testing import (enable_persistent_compilation_cache,
                                   honor_jax_platforms_env)

    honor_jax_platforms_env()
    # warmup absorbs compilation, so a warm cache never perturbs the timed
    # numbers — it only makes a resumed sweep after a tunnel death cheap
    enable_persistent_compilation_cache()
    print(f"backend={jax.default_backend()}", flush=True)
    if args.tiny:
        attn_shape, xent = (1, 2, 256, 64), (256, 128, 512)
        norm_shape, sm_shape = (256, 128), (1, 2, 128)
        rope_shape, xp_shape = (1, 256, 2, 256), (256, 512)
        dense_shape, opt_shape = (256, 128, 256), (4, (64, 32))
    elif args.llama:
        attn_shape, xent = (1, 32, 16384, 64), (4096, 2048, 32000)
        norm_shape, sm_shape = (16384, 2048), (8, 12, 1024)
        rope_shape, xp_shape = (1, 16384, 32, 64), (4096, 32000)
        dense_shape, opt_shape = (16384, 2048, 5632), (32, (2048, 2048))
    else:
        attn_shape, xent = (8, 12, 1024, 64), (8184, 768, 50432)
        norm_shape, sm_shape = (8192, 768), (8, 12, 1024)
        rope_shape, xp_shape = (1, 1024, 12, 64), (8184, 50432)
        dense_shape, opt_shape = (16384, 768, 3072), (148, (1024, 768))
    if args.what in ("attn", "all"):
        bench_attn(attn_shape)
    if args.what in ("xent", "all"):
        bench_xent(*xent)
    if args.what in ("norm", "all"):
        bench_norm(*norm_shape)
    if args.what in ("softmax", "all"):
        # GPT-2 shape in llama mode too: the llama 16k score matrix would
        # materialize (1,32,16k,16k) fp32 = 32 GiB — flash owns that case
        bench_softmax(*sm_shape)
    if args.what in ("rope", "all"):
        bench_rope(*rope_shape)
    if args.what in ("xent_plain", "all"):
        bench_xent_plain(*xp_shape)
    if args.what in ("dense", "all"):
        bench_dense(*dense_shape)
    if args.what in ("int8", "all"):
        if args.tiny:
            bench_int8(4, 256, 128)
        elif args.llama:
            bench_int8(8, 32000, 2048)   # decode rows vs the LM head
        else:
            bench_int8(8, 2048, 2048)    # decode rows vs a block matmul
    if args.what in ("opt", "all"):
        bench_opt(*opt_shape)
