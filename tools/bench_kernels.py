"""Microbenchmarks for the Pallas kernels on the current backend.

Times fwd and fwd+bwd for flash attention and linear_cross_entropy across
block sizes, against their XLA-composite golds. Prints immediately
(unbuffered) — safe to tail.

Usage: python tools/bench_kernels.py [attn|xent|all] [--gpt2|--llama]
"""

import argparse
import functools
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timeit(fn, *args, iters=20):
    """Seconds/call with the loop in ONE dispatch (tunnel latency hidden).

    Each iteration's inputs depend on the previous output (a 0-valued
    scalar tap added to every float arg) so XLA cannot hoist the
    loop-invariant call out of the fori_loop."""
    fn2 = jax.jit(fn)

    def many(n, args):
        def body(_, carry):
            cargs, out = carry
            eps = jax.tree.leaves(out)[0].ravel()[0] * 0
            cargs = tuple(
                a + eps.astype(a.dtype) if jnp.issubdtype(a.dtype,
                                                          jnp.floating)
                else a for a in cargs)
            return cargs, fn2(*cargs)
        return jax.lax.fori_loop(0, n, body, (args, fn2(*args)))[1]

    manyj = jax.jit(many, static_argnums=0)
    out = manyj(iters, args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = manyj(iters, args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / (iters + 1)
    return dt


def bench_attn(shape):
    from apex1_tpu.ops.attention import _xla_attention, flash_attention
    B, H, S, D = shape
    print(f"== flash attention (B,H,S,D)=({B},{H},{S},{D}) causal bf16 ==",
          flush=True)
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.bfloat16)

    def xla_fn(q, k, v):
        return _xla_attention(q, k, v, None, None, 0, 0, 0.125, True)

    def xla_grad(q, k, v):
        return jax.grad(lambda q, k, v: jnp.sum(
            xla_fn(q, k, v).astype(jnp.float32)), argnums=(0, 1, 2))(q, k, v)

    dt = timeit(xla_fn, q, k, v)
    print(f"  xla fwd                  {dt*1e3:8.2f} ms", flush=True)
    dt = timeit(xla_grad, q, k, v)
    print(f"  xla fwd+bwd              {dt*1e3:8.2f} ms", flush=True)

    for bq, bk in [(128, 128), (256, 256), (256, 512), (512, 512),
                   (512, 1024), (1024, 1024)]:
        if bq > S or bk > S:
            continue
        f = functools.partial(flash_attention, causal=True,
                              block_q=bq, block_k=bk)
        def g(q, k, v):
            return jax.grad(lambda q, k, v: jnp.sum(
                f(q, k, v).astype(jnp.float32)), argnums=(0, 1, 2))(q, k, v)
        try:
            dt = timeit(f, q, k, v)
            dt2 = timeit(g, q, k, v)
            print(f"  flash bq={bq:4d} bk={bk:4d}   fwd {dt*1e3:8.2f} ms   "
                  f"fwd+bwd {dt2*1e3:8.2f} ms", flush=True)
        except Exception as e:
            print(f"  flash bq={bq} bk={bk}: {type(e).__name__}: "
                  f"{str(e)[:120]}", flush=True)


def bench_xent(T, H, V):
    from apex1_tpu.ops.linear_xent import (_xla_linear_xent,
                                           linear_cross_entropy)
    print(f"== linear_xent T={T} H={H} V={V} bf16 ==", flush=True)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(T, H)) * 0.02, jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(V, H)) * 0.02, jnp.bfloat16)
    t = jnp.asarray(rng.integers(0, V - 300, (T,)), jnp.int32)

    def xla_fn(x, w):
        return jnp.mean(_xla_linear_xent(x, w, t, 0.0, None, V - 300))

    dt = timeit(xla_fn, x, w)
    print(f"  xla fwd                  {dt*1e3:8.2f} ms", flush=True)
    dt = timeit(jax.grad(xla_fn, argnums=(0, 1)), x, w)
    print(f"  xla fwd+bwd              {dt*1e3:8.2f} ms", flush=True)

    for bt, bv in [(256, 512), (512, 512), (512, 1024), (1024, 1024),
                   (256, 2048), (512, 2048)]:
        def f(x, w, bt=bt, bv=bv):
            return jnp.mean(linear_cross_entropy(
                x, w, t, num_classes=V - 300, block_t=bt, block_v=bv))
        try:
            dt = timeit(f, x, w)
            dt2 = timeit(jax.grad(f, argnums=(0, 1)), x, w)
            print(f"  fused bt={bt:4d} bv={bv:4d}   fwd {dt*1e3:8.2f} ms   "
                  f"fwd+bwd {dt2*1e3:8.2f} ms", flush=True)
        except Exception as e:
            print(f"  fused bt={bt} bv={bv}: {type(e).__name__}: "
                  f"{str(e)[:120]}", flush=True)


def bench_norm(R, H):
    from apex1_tpu.ops import layer_norm, rms_norm
    from apex1_tpu.ops._common import force_impl
    print(f"== layer_norm rows={R} H={H} bf16 ==", flush=True)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(R, H)), jnp.bfloat16)
    g = jnp.ones((H,), jnp.float32)
    b = jnp.zeros((H,), jnp.float32)

    for name, op in (("ln", lambda x, impl: layer_norm(x, g, b)),
                     ("rms", lambda x, impl: rms_norm(x, g))):
        for impl in ("xla", "pallas"):
            def f(x, name=name, op=op, impl=impl):
                with force_impl(impl):
                    return jnp.sum(op(x, impl).astype(jnp.float32))
            dt = timeit(f, x)
            dt2 = timeit(jax.grad(f), x)
            print(f"  {name:4s} {impl:6s} fwd {dt*1e3:8.3f} ms   fwd+bwd "
                  f"{dt2*1e3:8.3f} ms", flush=True)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("what", nargs="?", default="all",
                    choices=["attn", "xent", "norm", "all"])
    ap.add_argument("--llama", action="store_true",
                    help="long-context llama shapes instead of GPT-2")
    args = ap.parse_args()
    print(f"backend={jax.default_backend()}", flush=True)
    if args.llama:
        attn_shape, xent = (1, 32, 16384, 64), (4096, 2048, 32000)
    else:
        attn_shape, xent = (8, 12, 1024, 64), (8184, 768, 50432)
    if args.what in ("attn", "all"):
        bench_attn(attn_shape)
    if args.what in ("xent", "all"):
        bench_xent(*xent)
    if args.what in ("norm", "all"):
        bench_norm(8192 if not args.llama else 16384,
                   768 if not args.llama else 2048)
