"""Offline roofline PREDICTION for every bench config + Pallas kernel —
the falsifiable perf table VERDICT r4 Missing #2 asked for.

Four rounds of kernel/step tuning are AOT- and numerics-verified but have
never been timed (the axon tunnel has been down since round 1's single
42k tok/s GPT-2 reading). This tool makes that work scoreable offline:
it AOT-compiles the EXACT bench.py train steps and the individual Pallas
kernels through libtpu's compile-only topology client (same machinery as
tools/aot_check.py), reads post-optimization FLOPs and bytes-accessed
from XLA's cost model, and tables the roofline prediction

    t_pred = max(flops / peak_bf16_flops, hbm_bytes / peak_hbm_bw)

per config against the v5e (bench chip) and v5p capability rows
(core/capability.py spec-sheet numbers). The first real hardware window
then CONFIRMS or EMBARRASSES this table (bench.py prints measured
step_ms + MFU in the same units).

How to read the numbers honestly:
- The prediction is an UPPER BOUND on throughput: XLA's "bytes accessed"
  is the post-fusion HLO cost model's count of operand+output bytes per
  op, which approximates HBM traffic but ignores achieved-bandwidth
  derating, DMA/compute overlap gaps, scalar-core stalls, and ICI time.
  Measured tokens/sec at or above ~60% of predicted = the program is
  roofline-shaped; below ~50% = a schedule or kernel is leaving real
  performance on the floor and the per-kernel table localizes where.
- THE PALLAS BLIND SPOT (the reason each step compiles TWICE): the HLO
  cost model cannot see inside `tpu_custom_call`, so a Pallas-lowered
  program under-reports flops by exactly the kernels' share (flash
  attention + fused LM-head CE are ~40% of a GPT-2 step). Logical
  FLOPs therefore come from a second compile with `force_impl("xla")`
  (same math through composite ops); HBM bytes come from the Pallas
  compile (the composite would overcount bytes by the S^2 score
  materializations flash exists to avoid — while the Pallas compile's
  custom-call operand bytes are the right first-order traffic).
  `flops_xla / flops_pallas_visible` is tabled per config as the MFU
  CORRECTION FACTOR: bench.py's on-hardware `mfu` divides measured
  time into cost_analysis flops of the Pallas program, so multiply
  bench.py's mfu by this factor for true model-flops utilization.
- XLA counts a fused multiply-add as 2 flops, matching bench.py.
- SCANNED-LOOP BLIND SPOT (decode/decode_int8): the cost model counts
  a `lax.scan`/`fori_loop` body's loop-INVARIANT operands (the model
  weights a decode loop streams every step) ONCE, not once per
  iteration, so the decode rows' bytes — and therefore their
  HBM-bound time — are ~Nx optimistic for an N-step decode. The
  decode rows are retained for flop bookkeeping only; the honest
  decode floor is BASELINE.md's weight-streaming arithmetic, and the
  round-5 measurement (208 ms vs the ~36 ms "prediction" vs the
  ~220-240 ms streaming floor) confirmed exactly this.
- The per-kernel table is ANALYTIC (formulas in `_KERNEL_CASES`):
  cost-model numbers are meaningless for custom calls, so kernel
  rooflines use counted matmul flops and operand/result bytes.
- v5p columns reuse the v5e-lowered program's flops/bytes with v5p
  peaks (identical HLO math; Pallas block shapes differ on v5p but
  block shape changes traffic only at the margin).

Usage:
    python tools/predict_perf.py [--out perf_results/predicted_r5.md]
        [--json perf_results/predicted_r5.json] [--configs gpt2,bert,...]
        [--skip-kernels]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from apex1_tpu.testing import (  # noqa: E402
    enable_persistent_compilation_cache)

enable_persistent_compilation_cache()

TOPOLOGY = "v5e:2x2"   # lowering target; single-device programs


def _cost(compiled):
    """(flops, bytes_accessed) from the optimized executable's cost model."""
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    flops = float(cost.get("flops", 0.0))
    # total operand+output traffic: XLA reports the aggregate under
    # "bytes accessed"; per-operand keys ("bytes accessed0{}", ...)
    # are subsets of it, so the aggregate alone is the roofline input
    nbytes = float(cost.get("bytes accessed", 0.0))
    return flops, nbytes


def _roofline(flops, nbytes, cap, ici_exposed_bytes=0.0):
    """Predicted (seconds, bound, mfu) — now the LIBRARY roofline
    (`apex1_tpu.perf_model.roofline`, docstring there): the planner and
    this CLI must price through the same arithmetic or their numbers
    drift (the reason perf_model exists)."""
    from apex1_tpu.perf_model import roofline

    return roofline(flops, nbytes, cap,
                    ici_exposed_bytes=ici_exposed_bytes)


def predict_steps(topo, configs):
    """AOT-compile each bench step single-device; return prediction rows."""
    import bench as bench_mod
    from jax.sharding import SingleDeviceSharding

    s1 = SingleDeviceSharding(topo.devices[0])

    def to_shape(tree):
        import jax.numpy as jnp
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(jnp.shape(x),
                                           jnp.asarray(x).dtype,
                                           sharding=s1), tree)

    from apex1_tpu.ops import force_impl

    def to_shape_cpu(tree):
        import jax.numpy as jnp
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(jnp.shape(x),
                                           jnp.asarray(x).dtype), tree)

    rows = []
    for name in configs:
        try:
            (state, step, batch, units_per_step, _iters, metric, unit,
             proxy) = bench_mod.BENCHES[name](True)
            sh_state, sh_batch = to_shape(state), to_shape(batch)
            cpu_state, cpu_batch = to_shape_cpu(state), to_shape_cpu(batch)
            del state, batch

            # impl pinned INSIDE a fresh closure per mode: jax's trace
            # cache is keyed on the function object, so two lowerings of
            # the SAME `step` would alias one jaxpr and force_impl at
            # lower()-time would silently no-op (the r3 hw_numerics
            # vacuous-comparison bug class, re-observed here in r5)
            def mode_step(mode):
                def run(st, *b):
                    with force_impl(mode):
                        return step(st, *b)
                return run

            # Pallas compile: bytes are first-order honest, flops are
            # blind to custom-call interiors
            compiled_p = jax.jit(mode_step("auto"), donate_argnums=0).lower(
                sh_state, *sh_batch).compile()
            flops_vis, nbytes = _cost(compiled_p)
            mem = compiled_p.memory_analysis()
            # forced-composite compile: the LOGICAL flop count (same
            # math, every matmul visible to the cost model). Compiled
            # for CPU, unsharded: the composite materializes the S^2
            # score tensors flash exists to avoid, so it cannot FIT the
            # v5e HBM budget — it only needs to COUNT (flop counting on
            # optimized HLO is backend-invariant for these programs)
            compiled_x = jax.jit(mode_step("xla"), donate_argnums=0).lower(
                cpu_state, *cpu_batch).compile()
            flops, _bytes_x = _cost(compiled_x)
            rows.append(dict(
                name=name, metric=metric, unit=unit, proxy=proxy,
                units_per_step=units_per_step, flops=flops, bytes=nbytes,
                flops_pallas_visible=flops_vis,
                mfu_correction=(flops / flops_vis if flops_vis else None),
                # single-chip bench programs move no ICI bytes; the keys
                # exist so multichip rows can carry the comms term
                # bench.py::_predicted_rate prices (exposed = NOT hidden
                # behind compute; see predict_comms)
                ici_bytes=0.0, ici_exposed_bytes=0.0,
                temp_gib=mem.temp_size_in_bytes / 2**30,
                args_gib=mem.argument_size_in_bytes / 2**30))
            print(f"  OK   {name:14s} flops {flops:.3e} "
                  f"(visible {flops_vis:.3e})  bytes {nbytes:.3e}",
                  flush=True)
        except Exception as e:
            print(f"  FAIL {name}: {type(e).__name__}: {str(e)[:200]}",
                  flush=True)
            rows.append(dict(name=name, error=f"{type(e).__name__}: {e}"))
    return rows


def _kernel_cases():
    """The per-kernel analytic table — moved verbatim to
    `apex1_tpu.perf_model.kernel_cases` (formula docstring there) so
    the planner's attention/CE pricing and this CLI share one set of
    formulas."""
    from apex1_tpu.perf_model import kernel_cases

    return kernel_cases()


def predict_kernels(_topo):
    """Analytic roofline rows for the Pallas kernels (the HLO cost model
    is blind inside tpu_custom_call — see module docstring)."""
    rows = []
    for name, flops, nbytes in _kernel_cases():
        rows.append(dict(name=name, flops=float(flops),
                         bytes=float(nbytes), source="analytic"))
        print(f"  OK   {name:40s} flops {flops:.3e}  "
              f"bytes {nbytes:.3e}  [analytic]", flush=True)
    return rows


def predict_comms():
    """Analytic ICI comms term for the ring-attention CP path at the
    llama_longctx attention shape (the 16k config that measured 0.36x
    its single-chip roofline): per ring step the K/V shard transfer
    either serializes against the attend (the pre-overlap schedule) or
    hides behind it (the double-buffered schedule, hlo_probe-pinned).
    ``exposed_bytes`` is what `_roofline`'s comms term prices — the
    overlapped rows carry only the residual the attend cannot cover,
    so bench.py's `predicted`/`roofline_ratio` sees the win instead of
    silently crediting serialized transfers as free.

    Forward per visiting shard: K+V bf16 hops vs 4·B·Hq·S_l²·D·0.5
    causal attend flops. Backward: K/V hops + fp32 dK/dV accumulator
    hops (the travelling-accumulator schedule pays one extra seed hop,
    n instead of n−1 — see parallel/ring_attention.py) vs the ~2.5x
    fwd per-shard backward compute.
    """
    from apex1_tpu.perf_model import ring_attention_comms

    B, Hq, Hkv, S, D = 1, 32, 4, 16384, 64
    rows = []
    for gen in ("v5e", "v5p"):
        for n in (4, 8):
            m = ring_attention_comms(gen, n, B=B, Hq=Hq, Hkv=Hkv, S=S,
                                     D=D)
            if m is None:
                # capability row carries no ICI figure — nothing to
                # price
                print(f"  SKIP ring comms {gen}: no ici_gbps in "
                      f"capability row", flush=True)
                break
            link = m["link_gbps"]
            for phase, total, serial_t, overlap_exp in (
                    ("fwd", m["fwd_bytes"], (n - 1) * m["t_hop_f"],
                     m["exp_f_overlap"]),
                    ("bwd", m["bwd_bytes"], n * m["t_hop_b"],
                     m["exp_b_overlap"])):
                rows.append(dict(
                    name=f"ring llama_longctx {phase} cp={n}",
                    generation=gen, cp=n, phase=phase,
                    ici_bytes=float(total),
                    exposed_bytes_serial=float(total),
                    exposed_bytes_overlap=float(overlap_exp),
                    t_serial_ms=serial_t * 1e3,
                    t_exposed_overlap_ms=(overlap_exp / (link * 1e9))
                    * 1e3,
                    source="analytic"))
            print(f"  OK   ring comms {gen} cp={n}: fwd hop "
                  f"{m['kv_hop'] / 2**20:.1f} MiB vs attend "
                  f"{m['t_att'] * 1e3:.2f} "
                  f"ms -> exposed {m['exp_f_overlap'] / 2**20:.1f} MiB "
                  f"(serial {m['fwd_bytes'] / 2**20:.1f})", flush=True)
    return rows


def predict_comms_fused():
    """Analytic ICI term for the Megatron-SP boundary matmul at a
    llama-8B-ish MLP shape, priced across the THREE schedules the repo
    now ships (docs/parallel.md "Fused comm-kernels"):

    - ``serial``: the monolithic collective (or the rotate-then-dot
      negative control) — every byte exposed.
    - ``overlap``: PR 4's chunk-pipelined ppermute ring AND the fused
      ppermute form (`ops.fused_collective.fused_matmul_reduce_scatter`,
      same schedule with the dot in a Pallas kernel) — exposed = the
      per-hop residual the chunk dot cannot cover. This is the
      BEST-CASE number: it assumes the XLA scheduler actually hoists
      every permute (hlo_probe pins the dependence shape, not the
      achieved schedule).
    - ``fused_rdma``: the single-kernel RDMA form
      (`matmul_reduce_scatter_rdma`) — grid-sequenced overlap, so the
      bound is STRUCTURAL, not scheduler-dependent: exposed ≈ the
      prologue hop (pipeline fill) plus the same bandwidth residual;
      on compute-rich shapes that is the prologue hop only.

    bench.py's `roofline_ratio` prices a record's `ici_exposed_bytes`
    at the per-link rate, so the three forms are scored honestly
    against each other, not assumed free.
    """
    from apex1_tpu.perf_model import sp_boundary_comms

    S, hid, ffn = 8192, 4096, 14336   # global seq, llama-8B MLP dims
    rows = []
    for gen in ("v5e", "v5p"):
        for n in (4, 8):
            # matmul->reduce-scatter at the row-parallel boundary:
            # x (S, ffn/n) @ w (ffn/n, hid), travelling fp32 chunk acc
            m = sp_boundary_comms(gen, n, rows=S, out_width=hid,
                                  ffn=ffn)
            if m is None:
                print(f"  SKIP fused comms {gen}: no ici_gbps in "
                      f"capability row", flush=True)
                break
            link = m["link_gbps"]
            rows.append(dict(
                name=f"SP matmul_reduce_scatter tp={n}",
                generation=gen, tp=n,
                ici_bytes=m["total"],
                exposed_bytes_serial=m["exposed_serial"],
                exposed_bytes_overlap=m["exposed_overlap"],
                exposed_bytes_fused=m["exposed_fused"],
                t_serial_ms=n * m["t_hop"] * 1e3,
                t_exposed_overlap_ms=(m["exposed_overlap"]
                                      / (link * 1e9)) * 1e3,
                t_exposed_fused_ms=(m["exposed_fused"]
                                    / (link * 1e9)) * 1e3,
                source="analytic"))
            print(f"  OK   fused comms {gen} tp={n}: hop "
                  f"{m['hop'] / 2**20:.1f} MiB vs dot "
                  f"{m['t_dot'] * 1e3:.2f} ms "
                  f"-> exposed serial {m['total'] / 2**20:.0f} / overlap "
                  f"{m['exposed_overlap'] / 2**20:.1f} / fused "
                  f"{m['exposed_fused'] / 2**20:.1f}"
                  f" MiB", flush=True)
    return rows


def annotate_calibration(step_rows):
    """Stamp each step row with the banked TPU-fitted slowdown factor
    (`apex1_tpu.obs.calibrate` — perf_results/calibration.json) and the
    calibrated v5e prediction: ``calibrated = analytic x slowdown`` in
    time terms. Fail-safe: no table, or no factor for a config, leaves
    the row untouched — the analytic prediction stands alone, as it did
    before any silicon was measured."""
    from apex1_tpu.obs.calibrate import load_calibration

    doc = load_calibration()
    if doc is None:
        return None
    for r in step_rows:
        if "error" in r:
            continue
        f = doc.get("factors", {}).get(f"step:{r['name']}")
        if isinstance(f, dict) and isinstance(f.get("slowdown"),
                                              (int, float)):
            r["calibration_slowdown"] = f["slowdown"]
            r["calibration_n"] = f.get("n")
    return doc


def render(step_rows, kernel_rows, comms_rows=(), fused_rows=(),
           calibration=None):
    from apex1_tpu.core.capability import get_capability
    v5e, v5p = get_capability("v5e"), get_capability("v5p")
    lines = []
    w = lines.append
    w("# Predicted performance — round 5 (offline roofline, NOT measured)")
    w("")
    w("Source: `python tools/predict_perf.py` — XLA cost model (flops, "
      "bytes accessed) of the post-optimization v5e executables for the "
      "exact `bench.py` steps and Pallas kernels, against the "
      "`core/capability.py` spec rows "
      f"(v5e {v5e.bf16_tflops:.0f} TF bf16 / {v5e.hbm_gbps:.0f} GB/s; "
      f"v5p {v5p.bf16_tflops:.0f} TF / {v5p.hbm_gbps:.0f} GB/s).")
    w("")
    w("`t_pred = max(flops/peak_flops, bytes/peak_bw)` — an UPPER bound "
      "on throughput (no overlap gaps, no bandwidth derating, no ICI). "
      "Measured ≥ ~60% of predicted tok/s = roofline-shaped program; "
      "< ~50% = localize the loss with the per-kernel table + "
      "`tools/profile_step.py`. See module docstring for the full "
      "honesty contract.")
    w("")
    w("## Bench configs (per train step, single chip)")
    w("")
    w("| config | units/step | GFLOPs | HBM GiB | AI (fl/B) | bound "
      "| v5e pred ms | v5e pred rate | v5e pred MFU | v5p pred ms "
      "| proxy | pred/proxy | mfu corr |")
    w("|---|---|---|---|---|---|---|---|---|---|---|---|---|")
    for r in step_rows:
        if "error" in r:
            w(f"| {r['name']} | — | — | — | — | — | — | — | — | — | — "
              f"| — | ERROR: {r['error'][:80]} |")
            continue
        te, be, me = _roofline(r["flops"], r["bytes"], v5e)
        tp, _, _ = _roofline(r["flops"], r["bytes"], v5p)
        rate = r["units_per_step"] / te
        ai = r["flops"] / r["bytes"] if r["bytes"] else float("inf")
        corr = r.get("mfu_correction")
        corr_s = f"{corr:.2f}x" if corr else "n/a"
        w(f"| {r['name']} | {r['units_per_step']} "
          f"| {r['flops'] / 1e9:,.1f} | {r['bytes'] / 2**30:.2f} "
          f"| {ai:.0f} | {be} | {te * 1e3:.1f} | {rate:,.0f} {r['unit']} "
          f"| {me:.2f} | {tp * 1e3:.1f} | {r['proxy']:,.0f} "
          f"| {rate / r['proxy']:.2f} | {corr_s} |")
    w("")
    w("`mfu corr` = logical flops / Pallas-visible flops: multiply "
      "bench.py's measured on-chip `mfu` by this factor for true model-"
      "flops utilization (bench.py's cost_analysis cannot see inside "
      "tpu_custom_call). decode_int8's huge factor is expected: "
      "essentially every matmul of that program runs inside the int8 "
      "Pallas GEMM, so the visible count is near zero.")
    w("")
    w("The `pred/proxy` column is the prediction of `bench.py`'s "
      "`vs_baseline` against the PINNED A100 comparator rows "
      "(BASELINE.md \"Pinned A100 comparator\"); the headline claim on "
      "the table is GPT-2, whose only measurement (round 1, pre-tuning) "
      "was 42,027 tok/s.")
    w("")
    cal_rows = [r for r in step_rows if r.get("calibration_slowdown")]
    if cal_rows:
        w("## Calibrated predictions (banked silicon history applied)")
        w("")
        w("Factors from `perf_results/calibration.json` "
          "(`apex1_tpu.obs.calibrate` — TPU-fitted slowdown = analytic "
          "rate / measured rate over the banked bench logs"
          + (f", {calibration.get('n_pairs')} pairs"
             if calibration else "") + "). `calibrated ms` = analytic "
          "x slowdown: what the NEXT run of this config should "
          "actually take if nothing regressed — the planner-facing "
          "number. cpu-proxy factors are never applied here.")
        w("")
        w("| config | slowdown (n) | v5e analytic ms | v5e calibrated "
          "ms | calibrated rate |")
        w("|---|---|---|---|---|")
        # priced through the SAME function the factors were fitted
        # against (calibrate.predicted_step_rate, comms term included)
        # — _roofline alone would drop a multichip row's exposed-ICI
        # term and overstate the calibrated rate by exactly that share
        from apex1_tpu.obs.calibrate import predicted_step_rate
        for r in cal_rows:
            rate = predicted_step_rate(r, "v5e")
            if not rate:
                continue
            te = r["units_per_step"] / rate
            s = r["calibration_slowdown"]
            w(f"| {r['name']} | {s:.2f}x ({r.get('calibration_n')}) "
              f"| {te * 1e3:.1f} | {te * s * 1e3:.1f} "
              f"| {r['units_per_step'] / (te * s):,.0f} {r['unit']} |")
        w("")
    w("DECODE-ROW CAVEAT: the cost model counts the scanned decode "
      "loop's loop-invariant weight buffers ONCE, not once per decode "
      "step, so the decode/decode_int8 bytes — and their HBM-bound "
      "predictions — are ~Nx optimistic for an N-step decode. Those "
      "rows are flop bookkeeping only; the honest decode floor is "
      "BASELINE.md's weight-streaming arithmetic (module docstring, "
      "\"SCANNED-LOOP BLIND SPOT\").")
    w("")
    w("## Pallas kernels (per invocation at bench shapes)")
    w("")
    w("Flops/bytes here are ANALYTIC (formulas in "
      "`tools/predict_perf.py::_kernel_cases` — the HLO cost model "
      "cannot see inside `tpu_custom_call`, so compiled numbers would "
      "be zeros). `tools/bench_kernels.py` measures the same shapes on "
      "silicon.")
    w("")
    w("| kernel | GFLOPs | HBM MiB | AI | bound | v5e pred ms "
      "| v5e pred TF/s |")
    w("|---|---|---|---|---|---|---|")
    for r in kernel_rows:
        if "error" in r:
            w(f"| {r['name']} | — | — | — | — | — | ERROR: "
              f"{r['error'][:80]} |")
            continue
        te, be, _ = _roofline(r["flops"], r["bytes"], v5e)
        ai = r["flops"] / r["bytes"] if r["bytes"] else float("inf")
        tf = r["flops"] / te / 1e12 if te else 0.0
        w(f"| {r['name']} | {r['flops'] / 1e9:,.2f} "
          f"| {r['bytes'] / 2**20:,.1f} | {ai:.0f} | {be} "
          f"| {te * 1e3:.3f} | {tf:.1f} |")
    w("")
    if comms_rows:
        w("## ICI comms term — ring attention at the llama_longctx "
          "shape (analytic)")
        w("")
        w("`exposed` = transfer time NOT hidden behind compute — the "
          "serialized (pre-overlap) schedule exposes every hop; the "
          "double-buffered schedule exposes only the residual per-hop "
          "time the attend cannot cover. bench.py's "
          "`predicted`/`roofline_ratio` prices a row's "
          "`ici_exposed_bytes` at the per-link rate "
          "(`core.capability.ici_link_gbps`), so the overlap win is "
          "scoreable, not just asserted (the schedule property itself "
          "is pinned by `testing.hlo_probe` in tools/aot_check.py).")
        w("")
        w("| ring phase | gen | cp | ICI MiB | exposed serial ms "
          "| exposed overlapped ms |")
        w("|---|---|---|---|---|---|")
        for r in comms_rows:
            w(f"| {r['phase']} | {r['generation']} | {r['cp']} "
              f"| {r['ici_bytes'] / 2**20:,.1f} "
              f"| {r['t_serial_ms']:.2f} "
              f"| {r['t_exposed_overlap_ms']:.2f} |")
        w("")
    if fused_rows:
        w("## ICI comms term — fused comm-kernels at the SP boundary "
          "(analytic)")
        w("")
        w("Three schedules for the same matmul+reduce-scatter "
          "(`tools/predict_perf.py::predict_comms_fused`): `serial` "
          "exposes every byte; `overlap` (PR 4's ppermute ring and the "
          "fused ppermute form — same schedule, dot in a Pallas "
          "kernel) exposes only the per-hop residual the chunk dot "
          "cannot cover; `fused rdma` "
          "(`ops.fused_collective.matmul_reduce_scatter_rdma`) "
          "exposes ≈ the prologue hop only — tile-granular overlap "
          "inside one kernel. `tools/bench_fused_comm.py` measures "
          "the same three forms (queued as fused_comm_ab).")
        w("")
        w("| boundary | gen | tp | ICI MiB | exposed serial ms "
          "| exposed overlap ms | exposed fused ms |")
        w("|---|---|---|---|---|---|---|")
        for r in fused_rows:
            w(f"| {r['name']} | {r['generation']} | {r['tp']} "
              f"| {r['ici_bytes'] / 2**20:,.1f} "
              f"| {r['t_serial_ms']:.2f} "
              f"| {r['t_exposed_overlap_ms']:.2f} "
              f"| {r['t_exposed_fused_ms']:.2f} |")
        w("")
    w("Validation protocol for the first hardware window: "
      "`tools/tpu_watch.sh`'s queue writes measured step_ms/MFU for "
      "every config above; divide measured by predicted and record the "
      "ratio per row in BASELINE.md. Ratios cluster tight (±15%) for "
      "roofline-shaped programs; an outlier row is the tuning target.")
    return "\n".join(lines) + "\n"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="perf_results/predicted_r5.md")
    ap.add_argument("--json", default="perf_results/predicted_r5.json")
    ap.add_argument("--configs", default=None,
                    help="comma-separated subset of bench configs")
    ap.add_argument("--skip-kernels", action="store_true")
    args = ap.parse_args()

    # identical dispatch patching to aot_check.py: real Mosaic lowering,
    # v5e block planning — the numbers must price the REAL kernels
    os.environ["PALLAS_AXON_TPU_GEN"] = "v5e"
    import apex1_tpu.ops._common as _common
    _common.on_tpu = lambda: True
    _common.interpret_mode = lambda: False

    from jax.experimental import topologies
    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name=TOPOLOGY)

    import bench as bench_mod
    # planner-driven multichip configs (bench.PLANNED_BENCHES) build
    # their mesh from the live device count — they cannot be priced by
    # this single-chip AOT path and are priced by the planner's own
    # cost engine instead; excluding them keeps the banked
    # predicted_*.json rows byte-stable across the planner's arrival
    configs = (args.configs.split(",") if args.configs
               else sorted(set(bench_mod.BENCHES)
                           - bench_mod.PLANNED_BENCHES))

    print(f"== step cost models ({TOPOLOGY}) ==", flush=True)
    step_rows = predict_steps(topo, configs)
    kernel_rows = []
    if not args.skip_kernels:
        print(f"== kernel cost models ({TOPOLOGY}) ==", flush=True)
        kernel_rows = predict_kernels(topo)
    print("== ICI comms term (ring attention, analytic) ==", flush=True)
    comms_rows = predict_comms()
    print("== ICI comms term (fused SP boundary, analytic) ==",
          flush=True)
    fused_rows = predict_comms_fused()

    print("== calibration annotation (banked factors) ==", flush=True)
    cal_doc = annotate_calibration(step_rows)
    print("  applied" if cal_doc else
          "  no banked calibration.json — analytic only", flush=True)

    md = render(step_rows, kernel_rows, comms_rows, fused_rows,
                calibration=cal_doc)
    for path in (args.out, args.json):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
    with open(args.out, "w") as f:
        f.write(md)
    with open(args.json, "w") as f:
        json.dump({"topology": TOPOLOGY, "steps": step_rows,
                   "kernels": kernel_rows, "comms": comms_rows,
                   "comms_fused": fused_rows,
                   "calibration": ({"source": "perf_results/"
                                    "calibration.json",
                                    "generated_unix":
                                    cal_doc.get("generated_unix")}
                                   if cal_doc else None)},
                  f, indent=1)
    print(f"wrote {args.out} + {args.json}", flush=True)
    failures = sum("error" in r
                   for r in step_rows + kernel_rows + comms_rows
                   + fused_rows)
    print(f"{failures} failures" if failures else "ALL OK", flush=True)
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
