"""Ablation timing for the GPT-2 headline bench — localize the bottleneck.

Times per-step seconds for variants of the config-1 recipe on the current
backend. Each timed region rides one dispatch (bench.timed_steps).
"""

import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import timed_steps  # noqa: E402

from apex1_tpu.amp import Amp  # noqa: E402
from apex1_tpu.core.policy import get_policy  # noqa: E402
from apex1_tpu.models.gpt2 import GPT2, GPT2Config, gpt2_loss_fn  # noqa: E402
from apex1_tpu.optim.fused_adam import fused_adam  # noqa: E402

B, S, iters = 8, 1024, 8
tokens = jnp.asarray(
    np.random.default_rng(0).integers(0, 50257, (B, S)), jnp.int32)


def run(name, use_flash, fuse_head, opt_level="O2"):
    cfg = GPT2Config(policy=get_policy(opt_level), use_flash=use_flash)
    model = GPT2(cfg)
    params = jax.jit(model.init)(jax.random.key(0), tokens)["params"]
    amp = Amp(tx=fused_adam(1e-4, weight_decay=0.01), opt_level=opt_level)
    state = amp.init(params)
    step = amp.make_train_step(gpt2_loss_fn(model, fuse_head=fuse_head))
    t0 = time.time()
    per_step, *_ = timed_steps(step, state, (tokens,), iters)
    print(f"{name:40s} {per_step*1e3:8.1f} ms/step  "
          f"{B*S/per_step:9.0f} tok/s  (compile+run {time.time()-t0:.0f}s)",
          flush=True)
    return per_step


def fwd_only(name, use_flash, fuse_head):
    cfg = GPT2Config(policy=get_policy("O2"), use_flash=use_flash)
    model = GPT2(cfg)
    params = jax.jit(model.init)(jax.random.key(0), tokens)["params"]
    loss_fn = gpt2_loss_fn(model, fuse_head=fuse_head)

    def step(params, tokens):
        return params, {"loss": loss_fn(params, tokens)}

    t0 = time.time()
    per_step, *_ = timed_steps(step, params, (tokens,), iters)
    print(f"{name:40s} {per_step*1e3:8.1f} ms/step  "
          f"(compile+run {time.time()-t0:.0f}s)", flush=True)


print(f"backend={jax.default_backend()}", flush=True)
run("O2 flash fused-head (= bench)", True, True)
run("O2 xla-attn fused-head", False, True)
run("O2 flash materialized-logits", True, False)
run("O2 xla-attn materialized-logits", False, False)
run("O3(bf16) flash fused-head", True, True, "O3")
fwd_only("fwd-only flash fused-head", True, True)
fwd_only("fwd-only xla-attn fused-head", False, True)
