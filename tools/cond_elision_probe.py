"""Does ``lax.cond`` ELIDE the untaken branch's compute on TPU?

VERDICT r2 weak #6: the pipeline bubble-skip (`schedules.pipeline_apply
skip_bubbles`) and ring-attention causal-skip (`parallel/ring_attention`)
both claim `lax.cond` saves the work of invalid ticks. XLA is allowed to
flatten a conditional into `select` (both branches execute) when the
branches are cheap or the predicate is vectorized — in which case the
"skip" saves nothing. This probe times, on the real chip:

  heavy(x)                      # unconditional heavy branch
  cond(False, heavy, light, x)  # traced predicate, always light
  light(x)                      # unconditional light branch

inside a fori_loop (one dispatch), where heavy = N chained matmuls and
light = x + 1. If cond-false tracks light (not heavy), the branch is
genuinely skipped and the per-tick skip claims hold on this backend.

Run: python tools/cond_elision_probe.py
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    from apex1_tpu.testing import (enable_persistent_compilation_cache,
                                   honor_jax_platforms_env)

    honor_jax_platforms_env()
    enable_persistent_compilation_cache()
    backend = jax.default_backend()
    if backend == "cpu":        # smoke-test the harness only
        N, D, LOOP = 4, 256, 5
    else:
        N, D, LOOP = 24, 2048, 50
    x = jnp.asarray(np.random.default_rng(0).normal(size=(D, D)),
                    jnp.bfloat16)

    def heavy(x):
        def body(_, a):
            return jnp.tanh(a @ x)
        return jax.lax.fori_loop(0, N, body, x)

    def light(x):
        return x + 1.0

    def timed(fn, *args):
        def looped(*args):
            def body(_, a):
                return fn(a, *args[1:])
            return jax.lax.fori_loop(0, LOOP, body, args[0])
        c = jax.jit(looped)
        c(*args).block_until_ready()          # compile + warm
        t0 = time.perf_counter()
        c(*args).block_until_ready()
        return (time.perf_counter() - t0) / LOOP * 1e3   # ms/iter

    t_heavy = timed(heavy, x)
    # the predicate must be TRACED (a constant would fold at compile time
    # and prove nothing) — same situation as the pipeline's per-tick
    # validity scalar
    pred_false = jnp.asarray(False)
    pred_true = jnp.asarray(True)
    t_cond_false = timed(
        lambda a, p: jax.lax.cond(p, heavy, light, a), x, pred_false)
    t_cond_true = timed(
        lambda a, p: jax.lax.cond(p, heavy, light, a), x, pred_true)
    t_light = timed(light, x)

    # elided if the false-branch cond costs << the heavy branch
    elides = t_cond_false < 0.25 * t_heavy
    print(json.dumps({
        "backend": backend,
        "ms_heavy": round(t_heavy, 4),
        "ms_cond_true": round(t_cond_true, 4),
        "ms_cond_false": round(t_cond_false, 4),
        "ms_light": round(t_light, 4),
        "cond_elides_untaken_branch": bool(elides),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
