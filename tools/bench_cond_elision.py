"""A/B wall-clock timing of the two production ``lax.cond`` skips —
the pipeline bubble-skip (``schedules.pipeline_apply skip_bubbles``) and
the ring-attention causal-skip (``parallel.ring_attention``).

VERDICT item: both skips are EXECUTABLE-verified (cond survives to the
optimized TPU executable — tools/cond_elision_aot.py r4) and
synthetically timed (tools/cond_elision_probe.py: cond-false tracks the
light branch), but the production sites themselves were never A/B
timed. This tool runs each site twice — skip enabled vs disabled — in
one process and emits a single JSON line with both speedups:

- pipeline: ``pipeline_apply(..., skip_bubbles=True/False)`` over a pp
  ring with a transformer-stage-sized ``stage_fn``. Expected win scales
  with the bubble share (p−1)/(M+p−1).
- ring: causal ``ring_attention(..., skip_masked=True/False)`` fwd+bwd.
  Expected win approaches the strictly-future shard share ~(n−1)/2n of
  attend FLOPs.

Device requirements: >= 2 devices for both sites. On a single-chip
window it emits a skip record (rc 0 — the queue must keep moving); on
CPU (rehearsal) it builds the 8-device virtual mesh with tiny shapes,
validating the command line end-to-end. NOTE: CPU cond elision differs
from TPU (that is the point of measuring on silicon) — rehearsal
numbers validate plumbing, not the claim.

Usage: python tools/bench_cond_elision.py [--pp N] [--cp N] [--iters K]
"""

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _emit(record):
    print(json.dumps(record), flush=True)


def _backend_is_cpu(timeout_s=120.0):
    """Subprocess probe — see tools/bench_ring_ab.py for why the main
    process must not initialize a backend before the mesh decision."""
    import subprocess
    code = ("import os, jax; p = os.environ.get('JAX_PLATFORMS'); "
            "p and jax.config.update('jax_platforms', p); "
            "print('BACKEND=' + jax.default_backend())")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s)
        return "BACKEND=cpu" in out.stdout
    except Exception:
        return False


def _timed(compiled, args, iters):
    import jax
    out = compiled(*args)
    jax.block_until_ready(out)               # warmup, same executable
    t0 = time.perf_counter()
    out = compiled(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    chk = float(jax.tree_util.tree_leaves(out)[-1])
    if not math.isfinite(chk):
        raise RuntimeError(f"non-finite check value {chk}")
    return dt


def _bench_pipeline(mesh, n, accel, iters):
    """pipeline_apply fwd with a stage-sized matmul chain, skip on/off."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from apex1_tpu.transformer.pipeline_parallel import schedules

    E, M, depth = (1024, 2 * n, 4) if accel else (128, 2 * n, 2)
    dtype = jnp.bfloat16 if accel else jnp.float32
    rng = np.random.default_rng(0)
    # (stages, V=1, depth, E, E) weights, stage-major so P("pp") shards
    w = jnp.asarray(rng.normal(size=(n, 1, depth, E, E)) * 0.02, dtype)
    mbs = jnp.asarray(rng.normal(size=(M, 8, E)), dtype)

    def stage_fn(params, x):
        for i in range(depth):
            x = jnp.tanh(x @ params[i])
        return x

    def run(skip):
        def inner(w, mbs):
            last = (jax.lax.axis_index("pp") == n - 1).astype(jnp.float32)
            outs = schedules.pipeline_apply(
                stage_fn, w[0], mbs, broadcast_outputs=False,
                skip_bubbles=skip)
            return jax.lax.psum(
                last * jnp.mean(jnp.square(outs.astype(jnp.float32))),
                "pp")

        sm = jax.shard_map(inner, mesh=mesh,
                           in_specs=(P("pp"), P()), out_specs=P(),
                           check_vma=False)

        def many(w, mbs):
            def body(_, acc):
                return acc + sm(w, mbs)
            return jax.lax.fori_loop(0, iters, body, jnp.float32(0.0))

        return jax.jit(many).lower(w, mbs).compile()

    t_on = _timed(run(True), (w, mbs), iters)
    t_off = _timed(run(False), (w, mbs), iters)
    return {"skip_ms": round(t_on * 1e3, 3),
            "noskip_ms": round(t_off * 1e3, 3),
            "speedup": round(t_off / t_on, 4),
            "shape": {"pp": n, "E": E, "M": M, "depth": depth}}


def _bench_ring(mesh, n, accel, iters):
    """Causal ring attention fwd+bwd, future-shard skip on/off."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from apex1_tpu.parallel.ring_attention import ring_attention

    if accel:
        B, Hq, Hkv, D, S = 1, 32, 4, 64, 16384
        dtype = jnp.bfloat16
    else:
        B, Hq, Hkv, D, S = 1, 4, 2, 16, 512
        dtype = jnp.float32
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, Hq, S, D)), dtype)
    k = jnp.asarray(rng.normal(size=(B, Hkv, S, D)), dtype)
    v = jnp.asarray(rng.normal(size=(B, Hkv, S, D)), dtype)
    spec = P(None, None, "cp", None)

    def run(skip):
        sm = jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, "cp", causal=True,
                                           skip_masked=skip),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
            check_vma=False)
        grad = jax.grad(
            lambda q, k, v: jnp.sum(sm(q, k, v).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2))

        def many(q, k, v):
            def one(q):
                dq, dk, dv = grad(q, k, v)
                return (q + (1e-6 * dq).astype(q.dtype),
                        jnp.sum(dq) + jnp.sum(dk) + jnp.sum(dv))

            def body(_, carry):
                return one(carry[0])

            return jax.lax.fori_loop(0, iters - 1, body, one(q))

        return jax.jit(many).lower(q, k, v).compile()

    t_on = _timed(run(True), (q, k, v), iters)
    t_off = _timed(run(False), (q, k, v), iters)
    return {"skip_ms": round(t_on * 1e3, 3),
            "noskip_ms": round(t_off * 1e3, 3),
            "speedup": round(t_off / t_on, 4),
            "shape": {"cp": n, "B": B, "Hq": Hq, "Hkv": Hkv, "S": S,
                      "D": D}}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pp", type=int, default=None)
    ap.add_argument("--cp", type=int, default=None)
    ap.add_argument("--iters", type=int, default=None)
    args = ap.parse_args()

    import jax

    plat = os.environ.get("JAX_PLATFORMS", "").strip()
    on_cpu = plat == "cpu" if plat else _backend_is_cpu()
    if on_cpu:
        from apex1_tpu.testing import force_virtual_cpu_devices
        force_virtual_cpu_devices(8)
    else:
        from apex1_tpu.testing import honor_jax_platforms_env
        honor_jax_platforms_env()
    from apex1_tpu.testing import enable_persistent_compilation_cache
    enable_persistent_compilation_cache()

    from apex1_tpu.core.mesh import make_mesh

    backend = jax.default_backend()
    devices = jax.devices()
    accel = backend not in ("cpu",)
    n_pp = args.pp or min(len(devices), 4)
    n_cp = args.cp or min(len(devices), 4)
    iters = args.iters or (8 if accel else 2)
    if min(n_pp, n_cp) < 2:
        _emit({"metric": f"cond_elision_ab [{backend}]", "value": 0.0,
               "error": f"pipeline/ring need >= 2 devices, have "
                        f"{len(devices)} — skipped (multichip window "
                        f"required)"})
        return

    record = {"metric": f"cond_elision_ab [{backend}]", "unit":
              "x (noskip/skip step time)"}
    failed = False
    for name, fn, n in (("pipeline_bubble_skip", _bench_pipeline, n_pp),
                        ("ring_causal_skip", _bench_ring, n_cp)):
        try:
            axis = "pp" if name.startswith("pipeline") else "cp"
            mesh = make_mesh(**{axis: n}, dp=1, devices=devices[:n])
            record[name] = fn(mesh, n, accel, iters)
        except Exception as e:
            failed = True
            record[name] = {"error":
                            f"{type(e).__name__}: {str(e)[:300]}"}
    # headline value: the ring skip speedup (the larger claimed win)
    record["value"] = (record.get("ring_causal_skip", {})
                       .get("speedup", 0.0))
    _emit(record)
    if failed:
        sys.exit(1)


if __name__ == "__main__":
    main()
