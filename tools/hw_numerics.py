"""On-device Pallas-kernel numerics parity (VERDICT r2 Missing #2).

The 351-test suite proves kernel numerics in *interpret* mode on CPU and
`tools/aot_check.py` proves Mosaic *lowering* — this script closes the gap
in between: it runs each Pallas kernel through the real Mosaic compiler on
the attached TPU and compares against the XLA-composite gold (the same
gold the interpret-mode tests use, SURVEY §4.2.1 parity-vs-gold).

Designed to be FIRST in the tpu_watch.sh revival queue: small shapes, one
compile per check, a hard watchdog, and a PASS/FAIL line per check plus a
final JSON summary line, so a tunnel that dies mid-run still leaves
evidence.

Run: python tools/hw_numerics.py [--timeout 900]
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def probe(timeout_s=150.0):
    code = ("import os, jax; p = os.environ.get('JAX_PLATFORMS'); "
            "p and jax.config.update('jax_platforms', p); "
            "jax.devices(); print('BACKEND=' + jax.default_backend())")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s)
        for line in out.stdout.splitlines():
            if line.startswith("BACKEND="):
                return line.split("=", 1)[1]
    except subprocess.TimeoutExpired:
        pass
    return None


RESULTS = []
ONLY = None  # --only substring filter; None = run every check


class _Watchdog(BaseException):
    """Deadline signal. Derives from BaseException so check()'s broad
    ``except Exception`` (which must keep the sweep going on per-kernel
    failures) can NOT swallow it — a swallowed watchdog would leave the
    process sweeping past tpu_watch's `timeout`, wedging the queue."""


def check(name, fn, pallas_args, gold_args=None, tol=2e-2, grad_tol=5e-2,
          grad_argnums=None, reduce_for_grad=None):
    """Compare fn under force_impl('pallas') vs force_impl('xla').

    fn returns an array or tuple of arrays. If grad_argnums is set, also
    compare grads of sum(reduce_for_grad(fn(*args))) w.r.t. those args.
    """
    import jax
    import jax.numpy as jnp

    from apex1_tpu.ops import force_impl

    if ONLY is not None and not any(s in name for s in ONLY):
        return
    gold_args = gold_args if gold_args is not None else pallas_args
    t0 = time.time()
    try:
        # Impl choice must live INSIDE a per-impl closure: jitting one
        # shared function under two `force_impl` contexts lets JAX's
        # global pjit cache hand the second call the first call's
        # executable (observed on the axon backend: the "xla" gold came
        # back as the Pallas kernel, relerr exactly 0.0 — a vacuous
        # parity check). Distinct function objects → distinct cache
        # entries; `force_impl` applies at trace time.
        def make_run(impl):
            def run(args):
                with force_impl(impl):
                    out = fn(*args)
                return out if isinstance(out, tuple) else (out,)
            return run

        got = jax.jit(make_run("pallas"))(pallas_args)
        got = [np.asarray(g, np.float32) for g in got]
        want = jax.jit(make_run("xla"))(gold_args)
        want = [np.asarray(w, np.float32) for w in want]
        errs = []
        for g, w in zip(got, want):
            denom = np.maximum(np.abs(w), 1.0)
            errs.append(float(np.max(np.abs(g - w) / denom)))
        ok = all(e <= tol for e in errs) and all(
            np.isfinite(g).all() for g in got)
        detail = f"fwd_relerr={max(errs):.2e} tol={tol:.0e}"

        if ok and grad_argnums is not None:
            red = reduce_for_grad or (
                lambda outs: sum(jnp.sum(o.astype(jnp.float32))
                                 for o in outs))

            def make_gfn(impl):
                run = make_run(impl)

                def scalar(*args):
                    return red(run(args))

                return jax.grad(scalar, argnums=grad_argnums)

            gp = jax.jit(make_gfn("pallas"))(*pallas_args)
            gx = jax.jit(make_gfn("xla"))(*gold_args)
            gerrs = []
            for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gx)):
                a = np.asarray(a, np.float32)
                b = np.asarray(b, np.float32)
                denom = np.maximum(np.abs(b), 1.0)
                gerrs.append(float(np.max(np.abs(a - b) / denom)))
            ok = all(e <= grad_tol for e in gerrs)
            detail += f" grad_relerr={max(gerrs):.2e} gtol={grad_tol:.0e}"
        status = "OK  " if ok else "FAIL"
        print(f"{status} {name:<34s} {detail} ({time.time()-t0:.1f}s)",
              flush=True)
        RESULTS.append({"name": name, "ok": bool(ok), "detail": detail})
    except Exception as e:  # keep sweeping — partial evidence is the point
        print(f"FAIL {name:<34s} {type(e).__name__}: {e}", flush=True)
        RESULTS.append({"name": name, "ok": False,
                        "detail": f"{type(e).__name__}: {e}"})


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--timeout", type=float, default=900.0)
    ap.add_argument("--allow-cpu", action="store_true",
                    help="smoke-test the harness on CPU (Pallas runs in "
                         "interpret mode — validates the script, not "
                         "Mosaic numerics)")
    ap.add_argument("--only", default=None,
                    help="comma-separated substrings: run only checks "
                         "whose name contains one of them (e.g. "
                         "'bias,int8' = the checks added after the "
                         "round-3 hardware window)")
    args = ap.parse_args()
    global ONLY
    ONLY = args.only.split(",") if args.only else None

    backend = probe()
    if backend is None or (backend == "cpu" and not args.allow_cpu):
        print(json.dumps({"ok": False, "error": f"backend={backend}"}),
              flush=True)
        return 1

    def _alarm(signum, frame):
        raise _Watchdog("hw_numerics watchdog")

    signal.signal(signal.SIGALRM, _alarm)
    # tpu_watch's `timeout` SIGTERMs the whole process; route it into the
    # same partial-summary path. (Neither handler can fire while blocked
    # inside a native tunnel compile — the per-check flushed PASS/FAIL
    # lines are the evidence that always survives.)
    signal.signal(signal.SIGTERM, _alarm)
    signal.alarm(int(args.timeout))
    timed_out = False
    try:
        _sweep(backend)
    except _Watchdog:
        timed_out = True  # partial RESULTS still get summarized
    signal.alarm(0)
    n_fail = sum(not r["ok"] for r in RESULTS)
    # an --only filter that matches nothing must not read as a pass
    ran_any = len(RESULTS) > 0
    print(json.dumps({
        "ok": n_fail == 0 and not timed_out and ran_any, "backend": backend,
        "timed_out": timed_out,
        "n_pass": len(RESULTS) - n_fail, "n_fail": n_fail,
        "failures": [r["name"] for r in RESULTS if not r["ok"]],
    }), flush=True)
    return 0 if (n_fail == 0 and not timed_out and ran_any) else 1


def _sweep(backend):
    import jax.numpy as jnp

    from apex1_tpu import ops
    from apex1_tpu.testing import (enable_persistent_compilation_cache,
                                   honor_jax_platforms_env)

    honor_jax_platforms_env()
    # ~4 jit compiles per check x ~12 checks at 20-40s each over the
    # tunnel: the first full sweep runs long, but a warm cache makes any
    # re-run (or a sweep resumed after a dead-tunnel kill) near-free
    enable_persistent_compilation_cache()
    rng = np.random.default_rng(0)

    def bf(*shape, scale=1.0):
        return jnp.asarray(rng.normal(size=shape) * scale, jnp.bfloat16)

    # --- flash attention: fwd+bwd, causal / GQA / segments / offsets ---
    B, H, S, D = 2, 8, 512, 64
    q, k, v = bf(B, H, S, D), bf(B, H, S, D), bf(B, H, S, D)
    check("flash_fwd_bwd_causal",
          lambda q, k, v: ops.flash_attention(q, k, v, causal=True),
          (q, k, v), grad_argnums=(0, 1, 2))
    kg, vg = bf(B, 2, S, D), bf(B, 2, S, D)
    check("flash_fwd_bwd_gqa",
          lambda q, k, v: ops.flash_attention(q, k, v, causal=True),
          (q, kg, vg), grad_argnums=(0, 1, 2))
    segs = jnp.asarray(np.repeat(np.arange(4), S // 4)[None].repeat(B, 0),
                       jnp.int32)
    check("flash_fwd_bwd_segments",
          lambda q, k, v: ops.flash_attention(q, k, v, causal=True,
                                              segment_ids=segs),
          (q, k, v), grad_argnums=(0, 1, 2))
    att_bias = jnp.asarray(rng.normal(size=(1, H, S, S)), jnp.float32)
    check("flash_fwd_bwd_bias",
          lambda q, k, v, b: ops.flash_attention(q, k, v, bias=b),
          (q, k, v, att_bias), grad_argnums=(0, 1, 2, 3))
    check("flash_fwd_ring_offset",
          lambda q, k, v: ops.flash_attention(
              q, k, v, causal=True, q_offset=S, k_offset=0,
              return_lse=True),
          (q, k, v))

    # --- layer norm / rms norm: bf16 x, fp32 scales ---
    R, Hn = 2048, 1024
    x = bf(R, Hn)
    g1 = jnp.asarray(rng.normal(size=(Hn,)), jnp.float32)
    b1 = jnp.asarray(rng.normal(size=(Hn,)), jnp.float32)
    check("layer_norm_fwd_bwd",
          lambda x, g, b: ops.layer_norm(x, g, b),
          (x, g1, b1), grad_argnums=(0, 1, 2))
    check("rms_norm_fwd_bwd",
          lambda x, g: ops.rms_norm(x, g),
          (x, g1), grad_argnums=(0, 1))

    # --- softmax (masked + causal) ---
    sc = bf(2, 4, 256, 256)
    mask = jnp.where(
        jnp.asarray(rng.random((2, 1, 256, 256)) < 0.2), ops.NEG_INF, 0.0
    ).astype(jnp.bfloat16)
    check("scaled_masked_softmax",
          lambda x, m: ops.scaled_masked_softmax(x, m, scale=0.5),
          (sc, mask), grad_argnums=(0,))
    check("causal_softmax",
          lambda x: ops.scaled_upper_triang_masked_softmax(x, scale=0.5),
          (sc,), grad_argnums=(0,))

    # --- xentropy: fp32 logits (production: fp32 logits from bf16 mm) ---
    T, V = 1024, 8192
    logits = jnp.asarray(rng.normal(size=(T, V)) * 2, jnp.float32)
    labels = jnp.asarray(rng.integers(0, V, (T,)), jnp.int32)
    labels = labels.at[::17].set(0)
    check("xentropy_fwd_bwd_smooth",
          lambda lg, lb: ops.softmax_cross_entropy_loss(
              lg, lb, smoothing=0.1, padding_idx=0),
          (logits, labels), tol=1e-3, grad_tol=1e-3, grad_argnums=(0,),
          reduce_for_grad=lambda outs: jnp.sum(outs[0]))

    # --- fused LM-head CE (linear_xent): bf16 x/W ---
    Tt, Hh, Vv = 512, 512, 16000
    xt = bf(Tt, Hh)
    wt = bf(Vv, Hh, scale=0.02)
    lb = jnp.asarray(rng.integers(0, Vv, (Tt,)), jnp.int32)
    check("linear_xent_fwd_bwd",
          lambda x, w, l: ops.linear_cross_entropy(x, w, l, smoothing=0.1),
          (xt, wt, lb), grad_argnums=(0, 1),
          reduce_for_grad=lambda outs: jnp.sum(outs[0]))

    # --- RoPE --- head_dim 256 so half=128 satisfies the Pallas kernel's
    # `half % 128 == 0` gate (rope.py:109); at the flash check's D=64 both
    # impls silently take the XLA composite and the parity is vacuous
    Dr = 256
    pos = jnp.arange(S)
    cos, sin = ops.rope_tables(pos, Dr)
    xr = bf(B, S, H, Dr)
    check("rope_half_split",
          lambda x: ops.apply_rotary_pos_emb(x, cos, sin),
          (xr,), grad_argnums=(0,))
    check("rope_interleaved",
          lambda x: ops.apply_rotary_pos_emb(x, cos, sin, interleaved=True),
          (xr,), grad_argnums=(0,))

    # --- int8 weight-only decode GEMM (added round 4; never yet run on
    # silicon) — decode-row x vs a head-sized weight; dequant in VMEM ---
    wq8, s8 = ops.quantize_int8(
        jnp.asarray(rng.normal(size=(2048, 1024)) * 0.05, jnp.float32))
    x8 = bf(8, 1024)
    check("int8_matmul_decode",
          lambda x: ops.int8_matmul(x, wq8, s8),
          (x8,))


if __name__ == "__main__":
    sys.exit(main())
