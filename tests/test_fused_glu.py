"""Fused SwiGLU/GeGLU (``ops.fused_dense.fused_glu``) — the contract the
``LlamaConfig.fused_mlp`` flag rides on: the XLA path is BITWISE the
inline gate/up expression (flag flip is a no-op off-TPU), the Pallas
path matches within fp32 tile tolerance, grads recompute (activations
never saved), and geometry negatives raise loudly at trace time."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.ops import _common
from apex1_tpu.ops.fused_dense import check_glu_geometry, fused_glu

FP32_TOL = dict(rtol=2e-5, atol=2e-5)


def _inline(x, wg, wu, activation):
    act = (jax.nn.silu if activation == "silu"
           else lambda v: jax.nn.gelu(v, approximate=True))
    return (act(x @ wg) * (x @ wu)).astype(x.dtype)


def _mk(rng, *shape, dtype=jnp.float32):
    return jnp.asarray(rng.normal(size=shape) * 0.3, dtype)


class TestFusedGLU:
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    @pytest.mark.parametrize("activation", ["silu", "gelu"])
    def test_xla_path_bitwise_vs_inline(self, rng, dtype, activation):
        B, S, H, F = 2, 9, 48, 112
        x = _mk(rng, B, S, H, dtype=dtype)
        wg = _mk(rng, H, F, dtype=dtype)
        wu = _mk(rng, H, F, dtype=dtype)
        with _common.force_impl("xla"):
            out = fused_glu(x, wg, wu, activation=activation)
        ref = _inline(x, wg, wu, activation)
        assert out.dtype == dtype
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_pallas_path_matches(self, rng):
        T, H, F = 24, 64, 256
        x, wg, wu = _mk(rng, T, H), _mk(rng, H, F), _mk(rng, H, F)
        with _common.force_impl("pallas"):
            out = fused_glu(x, wg, wu, block_t=8, block_f=128)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(_inline(x, wg, wu, "silu")),
            **FP32_TOL)

    def test_pallas_grads_match_xla(self, rng):
        T, H, F = 16, 32, 128

        def run(x, wg, wu, impl):
            with _common.force_impl(impl):
                return jnp.sum(fused_glu(x, wg, wu, block_t=8,
                                         block_f=128) ** 2)

        x, wg, wu = _mk(rng, T, H), _mk(rng, H, F), _mk(rng, H, F)
        gp = jax.grad(run, argnums=(0, 1, 2))(x, wg, wu, "pallas")
        gg = jax.grad(run, argnums=(0, 1, 2))(x, wg, wu, "xla")
        for a, b in zip(gp, gg):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       **FP32_TOL)

    def test_bad_activation_raises(self):
        with pytest.raises(ValueError, match="activation"):
            fused_glu(jnp.zeros((4, 8)), jnp.zeros((8, 16)),
                      jnp.zeros((8, 16)), activation="relu")

    def test_geometry_negatives_raise(self):
        with pytest.raises(ValueError, match="multiple of 8"):
            check_glu_geometry(7, 128, 64)
        with pytest.raises(ValueError, match="multiple of 128"):
            check_glu_geometry(8, 100, 64)
        with pytest.raises(ValueError, match="VMEM"):
            check_glu_geometry(512, 1 << 16, 8192)


class TestLlamaFusedMlpFlag:
    def test_flag_is_bitwise_neutral_off_tpu(self, rng):
        from apex1_tpu.models.llama import Llama, LlamaConfig

        tokens = jnp.asarray(rng.integers(0, 97, size=(2, 8)), jnp.int32)

        def logits(fused):
            cfg = LlamaConfig.tiny(vocab_size=97, fused_mlp=fused)
            model = Llama(cfg)
            params = model.init(jax.random.PRNGKey(0), tokens)
            return model.apply(params, tokens)

        a = np.asarray(logits(False))
        b = np.asarray(logits(True))
        np.testing.assert_array_equal(a, b)
