"""Cross-product integration matrix — reference
``tests/L1/cross_product/run.sh`` + ``compare.py``: the same training
loop over every amp config, loss curves diffed across equivalent configs
(catches policy × optimizer × DDP interaction bugs).

Here: tiny GPT-2 on fixed synthetic data for {O0, O1, O1_fp16(static),
O2, O3} × {single, DDP dp=4}; every mixed-precision config must track the
fp32 curve within dtype tolerance, and DDP must be step-identical to
single-device for the same global batch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex1_tpu.amp import Amp
from apex1_tpu.core.mesh import make_mesh
from apex1_tpu.core.policy import get_policy
from apex1_tpu.models.gpt2 import GPT2, GPT2Config, gpt2_loss_fn
from apex1_tpu.optim.fused_adam import fused_adam

STEPS = 6
B, S = 4, 64


def _data():
    # one fixed batch repeated: loss must fall monotonically-ish, and the
    # cross-config curves stay comparable point-by-point
    rng = np.random.default_rng(7)
    batch = rng.integers(0, 256, (B, S))
    return jnp.asarray(np.broadcast_to(batch, (STEPS, B, S)), jnp.int32)


def _run(opt_level, *, ddp=False, devices=None, **amp_kw):
    cfg = GPT2Config.tiny(policy=get_policy(opt_level, **amp_kw))
    model = GPT2(cfg)
    data = _data()
    params = model.init(jax.random.key(0), data[0])["params"]
    amp = Amp(tx=fused_adam(1e-3), opt_level=opt_level,
              grad_psum_axes=("dp",) if ddp else (), **amp_kw)
    state = amp.init(params)
    train = amp.make_train_step(gpt2_loss_fn(model))
    if ddp:
        mesh = make_mesh(dp=4, devices=devices[:4])
        step = jax.jit(jax.shard_map(
            train, mesh=mesh, in_specs=(P(), P("dp")),
            out_specs=(P(), P()), check_vma=False))
    else:
        step = jax.jit(train)
    losses = []
    for i in range(STEPS):
        state, m = step(state, data[i])
        losses.append(float(m["loss"]))
    return np.asarray(losses)


@pytest.fixture(scope="module")
def o0_curve():
    return _run("O0")


@pytest.mark.parametrize("opt_level,kw,tol", [
    ("O1", {}, 2e-2),
    ("O1_fp16", {"loss_scale": 128.0}, 2e-2),
    ("O2", {}, 2e-2),
    ("O2", {"loss_scale": "dynamic"}, 2e-2),
    ("O3", {}, 5e-2),
])
def test_policy_tracks_fp32(o0_curve, opt_level, kw, tol):
    curve = _run(opt_level, **kw)
    assert np.all(np.isfinite(curve))
    # loss trajectories must match fp32 within dtype tolerance
    np.testing.assert_allclose(curve, o0_curve, rtol=tol, atol=tol)
    assert curve[-1] < curve[0]  # and actually train


@pytest.mark.parametrize("opt_level", ["O0", "O2"])
def test_ddp_matches_single(o0_curve, opt_level, devices):
    single = o0_curve if opt_level == "O0" else _run(opt_level)
    ddp = _run(opt_level, ddp=True, devices=devices)
    # same global batch split over 4 replicas -> identical steps
    np.testing.assert_allclose(ddp, single, rtol=1e-4, atol=1e-4)
