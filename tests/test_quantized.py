"""Weight-only int8 quantized matmul + decode (`ops.quantized`,
`models.quant_decode`): kernel parity vs the dequant composite, bounded
round-trip error, and decode parity vs the full-precision model — exact
(to bf16 rounding) when weights are constructed int8-representable."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.core.policy import get_policy
from apex1_tpu.models.generate import generate, llama_decoder
from apex1_tpu.models.llama import Llama, LlamaConfig
from apex1_tpu.models.quant_decode import (llama_quant_decoder,
                                           quantize_llama_params)
from apex1_tpu.ops import force_impl, int8_matmul, quantize_int8
from apex1_tpu.ops.quantized import _dequant_matmul_xla


class TestQuantizeInt8:
    def test_roundtrip_error_bounded_by_half_step(self):
        rng = np.random.default_rng(0)
        w = jnp.asarray(rng.normal(size=(64, 96)), jnp.float32)
        wq, s = quantize_int8(w)
        assert wq.dtype == jnp.int8 and s.shape == (64,)
        back = np.asarray(wq, np.float32) * np.asarray(s)[:, None]
        step = np.asarray(s)[:, None]  # per-channel quantization step
        assert (np.abs(back - np.asarray(w)) <= step / 2 + 1e-7).all()

    def test_zero_channel_stays_zero(self):
        w = jnp.zeros((4, 8), jnp.float32).at[1].set(1.0)
        wq, s = quantize_int8(w)
        assert (np.asarray(wq)[0] == 0).all()
        back = np.asarray(wq, np.float32) * np.asarray(s)[:, None]
        np.testing.assert_allclose(back, np.asarray(w), atol=1e-6)

    def test_rejects_bad_rank(self):
        with pytest.raises(ValueError, match="2-D"):
            quantize_int8(jnp.zeros((2, 3, 4)))


class TestInt8Matmul:
    def test_pallas_matches_composite(self):
        rng = np.random.default_rng(1)
        w = jnp.asarray(rng.normal(size=(256, 128)) * 0.1, jnp.float32)
        x = jnp.asarray(rng.normal(size=(4, 128)), jnp.bfloat16)
        wq, s = quantize_int8(w)
        with force_impl("pallas"):
            got = jax.jit(lambda x: int8_matmul(x, wq, s))(x)
        want = _dequant_matmul_xla(x, wq, s)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-2, atol=2e-2)

    def test_non_block_multiple_dims_stay_exact(self):
        """K/N that are 128-aligned but NOT multiples of the default
        block sizes (the Llama-7B ffn shape class): blocks must be
        divisor-fitted — a cdiv ragged tail block would accumulate
        out-of-bounds garbage into every output."""
        rng = np.random.default_rng(7)
        w = jnp.asarray(rng.normal(size=(384, 768)) * 0.1, jnp.float32)
        x = jnp.asarray(rng.normal(size=(4, 768)), jnp.bfloat16)
        wq, s = quantize_int8(w)
        with force_impl("pallas"):  # default block_k=512 does not divide
            got = jax.jit(lambda x: int8_matmul(x, wq, s))(x)
        want = _dequant_matmul_xla(x, wq, s)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-2, atol=2e-2)

    def test_unaligned_shapes_take_composite(self):
        rng = np.random.default_rng(2)
        w = jnp.asarray(rng.normal(size=(60, 72)), jnp.float32)
        x = jnp.asarray(rng.normal(size=(3, 72)), jnp.bfloat16)
        wq, s = quantize_int8(w)
        with force_impl("pallas"):  # gate must fall back, not crash
            got = int8_matmul(x, wq, s)
        np.testing.assert_allclose(np.asarray(got),
                                   np.asarray(_dequant_matmul_xla(x, wq,
                                                                  s)),
                                   rtol=1e-5, atol=1e-5)

    def test_leading_dims_and_grad(self):
        rng = np.random.default_rng(3)
        w = jnp.asarray(rng.normal(size=(128, 128)), jnp.float32)
        wq, s = quantize_int8(w)
        x = jnp.asarray(rng.normal(size=(2, 5, 128)), jnp.float32)
        y = int8_matmul(x, wq, s)
        assert y.shape == (2, 5, 128) and y.dtype == jnp.float32
        dx, dwq, ds = jax.grad(
            lambda x, wq, s: jnp.sum(int8_matmul(x, wq, s)),
            argnums=(0, 1, 2), allow_int=True)(x, wq, s)
        wdq = np.asarray(wq, np.float32) * np.asarray(s)[:, None]
        # bwd is the fp32 AD transpose (round 5: the old bf16-everything
        # form was the shape-dependent-numerics class ADVICE r4 flagged)
        # — near-exact, and tight enough that a bf16-scale regression
        # (~0.4% off) cannot hide inside the band
        np.testing.assert_allclose(np.asarray(dx),
                                   np.broadcast_to(wdq.sum(0), x.shape),
                                   rtol=1e-4, atol=1e-4)
        assert (np.asarray(ds) == 0).all()  # weights frozen


class TestQuantDecode:
    @staticmethod
    def _exactly_representable(params):
        """Replace every matmul weight by q*s with q in [-127, 127] so
        quantization is lossless — decode parity then isolates the code
        path, not the quantization error."""
        rng = np.random.default_rng(7)

        def fix(path, p):
            name = path[-1].key if hasattr(path[-1], "key") else path[-1]
            if name in ("wq", "wk", "wv", "wo", "w_gate", "w_up",
                        "w_down", "output", "w1", "w2"):
                q = rng.integers(-127, 128, size=p.shape)
                return jnp.asarray(q * 2e-3, jnp.float32)
            return p

        return jax.tree_util.tree_map_with_path(fix, params)

    @pytest.fixture(scope="class")
    def setup(self):
        cfg = LlamaConfig.tiny(policy=get_policy("O0"), max_seq_len=32)
        model = Llama(cfg)
        rng = np.random.default_rng(5)
        prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 5)),
                             jnp.int32)
        params = model.init(jax.random.key(0), prompt)["params"]
        params = self._exactly_representable(params)
        return cfg, model, params, prompt

    def test_quant_logits_match_full_precision(self, setup):
        cfg, model, params, prompt = setup
        apply_q, make_cache, qparams = llama_quant_decoder(model, params)
        cache = make_cache(2, 16)
        logits_q, _ = apply_q(qparams, prompt, cache, 0)
        apply_f, make_cache_f = llama_decoder(model)
        logits_f, _ = apply_f(params, prompt, make_cache_f(2, 16), 0)
        # exactly-representable weights: differences are bf16 rounding.
        # atol covers the unified activation cast (round 5): the
        # composite fallback now casts x to bf16 like the Pallas kernel
        # (one numerics contract for both paths), so the CPU path
        # faithfully carries the kernel's activation rounding instead of
        # being quietly more precise than production
        np.testing.assert_allclose(np.asarray(logits_q),
                                   np.asarray(logits_f),
                                   rtol=5e-2, atol=1e-1)

    def test_quant_generate_matches_full_precision_tokens(self, setup):
        cfg, model, params, prompt = setup
        N = 6
        apply_q, make_cache, qparams = llama_quant_decoder(model, params)
        got = generate(apply_q, qparams, prompt, max_new_tokens=N,
                       cache=make_cache(2, 11))
        apply_f, make_cache_f = llama_decoder(model)
        want = generate(apply_f, params, prompt, max_new_tokens=N,
                        cache=make_cache_f(2, 11))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_real_weights_quant_error_is_small(self, setup):
        cfg, model, _, prompt = setup
        rng = np.random.default_rng(11)
        params = model.init(jax.random.key(1), prompt)["params"]
        apply_q, make_cache, qparams = llama_quant_decoder(model, params)
        logits_q, _ = apply_q(qparams, prompt, make_cache(2, 16), 0)
        apply_f, make_cache_f = llama_decoder(model)
        logits_f, _ = apply_f(params, prompt, make_cache_f(2, 16), 0)
        lq, lf = np.asarray(logits_q), np.asarray(logits_f)
        denom = max(1.0, np.abs(lf).max())
        assert np.abs(lq - lf).max() / denom < 0.15, (
            np.abs(lq - lf).max(), denom)

    def test_gpt2_quant_generate_matches_full_precision(self):
        """GPT-2 family on the int8 path: with int8-representable
        weights the quantized decode emits the same tokens as the flax
        model's cached decode."""
        from apex1_tpu.models.generate import gpt2_decoder
        from apex1_tpu.models.gpt2 import GPT2, GPT2Config
        from apex1_tpu.models.quant_decode import gpt2_quant_decoder
        cfg = GPT2Config.tiny(policy=get_policy("O0"), max_seq_len=32)
        model = GPT2(cfg)
        rng = np.random.default_rng(13)
        prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 5)),
                             jnp.int32)
        params = model.init(jax.random.key(0), prompt)["params"]

        def fix(path, p):
            name = path[-1].key if hasattr(path[-1], "key") else path[-1]
            if name == "kernel" or name == "wte":
                q = rng.integers(-127, 128, size=p.shape)
                return jnp.asarray(q * 2e-3, jnp.float32)
            return p

        params = jax.tree_util.tree_map_with_path(fix, params)
        N = 6
        apply_q, make_cache, qparams = gpt2_quant_decoder(model, params)
        got = generate(apply_q, qparams, prompt, max_new_tokens=N,
                       cache=make_cache(2, 11),
                       vocab_size=cfg.vocab_size)
        apply_f, make_cache_f = gpt2_decoder(model)
        want = generate(apply_f, params, prompt, max_new_tokens=N,
                        cache=make_cache_f(2, 11),
                        vocab_size=cfg.vocab_size)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_int8_prefix_cache_continuation_matches_flat(self, setup):
        """docs/serving.md matrix cell: int8 x prefix caching. A prefix
        prefilled once through the int8 decoder, continued via
        cache_start, equals the flat int8 decode token-for-token."""
        cfg, model, params, _ = setup
        rng = np.random.default_rng(17)
        B, Lp, Ls, N = 2, 6, 4, 5
        prefix = jnp.asarray(rng.integers(1, cfg.vocab_size, (B, Lp)),
                             jnp.int32)
        suffix = jnp.asarray(rng.integers(1, cfg.vocab_size, (B, Ls)),
                             jnp.int32)
        apply_q, make_cache, qparams = llama_quant_decoder(model, params)
        cache0 = make_cache(B, Lp + Ls + N)
        _, cache0 = apply_q(qparams, prefix, cache0, 0)
        got = generate(apply_q, qparams, suffix, max_new_tokens=N,
                       cache=cache0, cache_start=Lp,
                       vocab_size=cfg.vocab_size)
        flat = jnp.concatenate([prefix, suffix], axis=1)
        want = generate(apply_q, qparams, flat, max_new_tokens=N,
                        cache=make_cache(B, Lp + Ls + N),
                        vocab_size=cfg.vocab_size)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_int8_beam1_equals_int8_greedy(self, setup):
        """docs/serving.md matrix cell: int8 x beam search. num_beams=1
        beam search over the int8 decoder reduces to its greedy decode."""
        from apex1_tpu.models.generate import beam_search
        cfg, model, params, prompt = setup
        N = 5
        apply_q, make_cache, qparams = llama_quant_decoder(model, params)
        beam, _ = beam_search(apply_q, qparams, prompt, max_new_tokens=N,
                              cache=make_cache(2, 16), num_beams=1,
                              vocab_size=cfg.vocab_size)
        greedy = generate(apply_q, qparams, prompt, max_new_tokens=N,
                          cache=make_cache(2, 16),
                          vocab_size=cfg.vocab_size)
        np.testing.assert_array_equal(np.asarray(beam),
                                      np.asarray(greedy))

    @pytest.fixture(scope="class")
    def moe_setup(self):
        """Tiny MoE Llama (every layer expert-routed) — the int8 expert
        path (VERDICT r4 item 4: expert weights are the bulk of MoE
        checkpoint bytes, the HBM-bound case int8 decode exists for)."""
        cfg = LlamaConfig.tiny(policy=get_policy("O0"), max_seq_len=32,
                               moe_every=1, num_experts=2, moe_top_k=1)
        model = Llama(cfg)
        rng = np.random.default_rng(23)
        prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 5)),
                             jnp.int32)
        params = model.init(jax.random.key(0), prompt)["params"]
        params = self._exactly_representable(params)
        return cfg, model, params, prompt

    def test_moe_quant_logits_match_full_precision(self, moe_setup):
        cfg, model, params, prompt = moe_setup
        apply_q, make_cache, qparams = llama_quant_decoder(model, params)
        logits_q, _ = apply_q(qparams, prompt, make_cache(2, 16), 0)
        apply_f, make_cache_f = llama_decoder(model)
        logits_f, _ = apply_f(params, prompt, make_cache_f(2, 16), 0)
        np.testing.assert_allclose(np.asarray(logits_q),
                                   np.asarray(logits_f),
                                   rtol=5e-2, atol=5e-2)

    def test_moe_quant_generate_matches_full_precision_tokens(
            self, moe_setup):
        """Greedy decode through int8 experts is token-identical to the
        flax MoE model's cached decode — routing decisions (fp32 router
        in both paths) and capacity/drop semantics must line up exactly,
        not just the matmul numerics."""
        cfg, model, params, prompt = moe_setup
        N = 6
        apply_q, make_cache, qparams = llama_quant_decoder(model, params)
        got = generate(apply_q, qparams, prompt, max_new_tokens=N,
                       cache=make_cache(2, 11))
        apply_f, make_cache_f = llama_decoder(model)
        want = generate(apply_f, params, prompt, max_new_tokens=N,
                        cache=make_cache_f(2, 11))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    @pytest.mark.slow  # 870s-cap headroom (12s of generate compiles):
    # the MoE x int8 x ragged TRIPLE composition; its pairs stay tier-1
    # (moe_quant logits/generate above, non-MoE ragged-int8 pins in
    # test_generate/test_speculative)
    def test_moe_int8_ragged_rows_match_solo(self):
        """docs/serving.md matrix: MoE x int8 x ragged. Ample expert
        capacity (no overflow -> no batched-vs-solo capacity coupling):
        each ragged row through the int8 MoE decoder equals its solo
        int8 decode; pad slots claim no capacity (segment -1)."""
        cfg = LlamaConfig.tiny(policy=get_policy("O0"), max_seq_len=32,
                               moe_every=1, num_experts=2, moe_top_k=1,
                               moe_capacity_factor=4.0)
        model = Llama(cfg)
        rng = np.random.default_rng(41)
        S0, lens, N = 6, [6, 3, 5], 4
        prompts = np.asarray(rng.integers(1, cfg.vocab_size, (3, S0)),
                             np.int32)
        prompts[~(np.arange(S0)[None, :]
                  < np.asarray(lens)[:, None])] = 0
        prompts = jnp.asarray(prompts)
        params = model.init(jax.random.key(0), prompts)["params"]
        params = self._exactly_representable(params)
        apply_q, make_cache, qparams = llama_quant_decoder(model, params)
        got = generate(apply_q, qparams, prompts, max_new_tokens=N,
                       cache=make_cache(3, S0 + N),
                       vocab_size=cfg.vocab_size,
                       prompt_lens=jnp.asarray(lens, jnp.int32))
        for b, ln in enumerate(lens):
            solo = generate(apply_q, qparams, prompts[b:b + 1, :ln],
                            max_new_tokens=N, cache=make_cache(1, ln + N),
                            vocab_size=cfg.vocab_size)
            np.testing.assert_array_equal(
                np.asarray(got[b]), np.asarray(solo[0]),
                err_msg=f"int8 MoE row {b} (len {ln}) diverged")

    def test_moe_real_weights_quant_error_is_small(self, moe_setup):
        cfg, model, _, prompt = moe_setup
        params = model.init(jax.random.key(2), prompt)["params"]
        apply_q, make_cache, qparams = llama_quant_decoder(model, params)
        logits_q, _ = apply_q(qparams, prompt, make_cache(2, 16), 0)
        apply_f, make_cache_f = llama_decoder(model)
        logits_f, _ = apply_f(params, prompt, make_cache_f(2, 16), 0)
        lq, lf = np.asarray(logits_q), np.asarray(logits_f)
        denom = max(1.0, np.abs(lf).max())
        assert np.abs(lq - lf).max() / denom < 0.15, (
            np.abs(lq - lf).max(), denom)
