"""Weight-only int8 quantized matmul + decode (`ops.quantized`,
`models.quant_decode`): kernel parity vs the dequant composite, bounded
round-trip error, and decode parity vs the full-precision model — exact
(to bf16 rounding) when weights are constructed int8-representable."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.core.policy import get_policy
from apex1_tpu.models.generate import generate, llama_decoder
from apex1_tpu.models.llama import Llama, LlamaConfig
from apex1_tpu.models.quant_decode import (llama_quant_decoder,
                                           quantize_llama_params)
from apex1_tpu.ops import force_impl, int8_matmul, quantize_int8
from apex1_tpu.ops.quantized import _dequant_matmul_xla


class TestQuantizeInt8:
    def test_roundtrip_error_bounded_by_half_step(self):
        rng = np.random.default_rng(0)
        w = jnp.asarray(rng.normal(size=(64, 96)), jnp.float32)
        wq, s = quantize_int8(w)
        assert wq.dtype == jnp.int8 and s.shape == (64,)
        back = np.asarray(wq, np.float32) * np.asarray(s)[:, None]
        step = np.asarray(s)[:, None]  # per-channel quantization step
        assert (np.abs(back - np.asarray(w)) <= step / 2 + 1e-7).all()

    def test_zero_channel_stays_zero(self):
        w = jnp.zeros((4, 8), jnp.float32).at[1].set(1.0)
        wq, s = quantize_int8(w)
        assert (np.asarray(wq)[0] == 0).all()
        back = np.asarray(wq, np.float32) * np.asarray(s)[:, None]
        np.testing.assert_allclose(back, np.asarray(w), atol=1e-6)

    def test_rejects_bad_rank(self):
        with pytest.raises(ValueError, match="2-D"):
            quantize_int8(jnp.zeros((2, 3, 4)))


class TestInt8Matmul:
    def test_pallas_matches_composite(self):
        rng = np.random.default_rng(1)
        w = jnp.asarray(rng.normal(size=(256, 128)) * 0.1, jnp.float32)
        x = jnp.asarray(rng.normal(size=(4, 128)), jnp.bfloat16)
        wq, s = quantize_int8(w)
        with force_impl("pallas"):
            got = jax.jit(lambda x: int8_matmul(x, wq, s))(x)
        want = _dequant_matmul_xla(x, wq, s)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-2, atol=2e-2)

    def test_non_block_multiple_dims_stay_exact(self):
        """K/N that are 128-aligned but NOT multiples of the default
        block sizes (the Llama-7B ffn shape class): blocks must be
        divisor-fitted — a cdiv ragged tail block would accumulate
        out-of-bounds garbage into every output."""
        rng = np.random.default_rng(7)
        w = jnp.asarray(rng.normal(size=(384, 768)) * 0.1, jnp.float32)
        x = jnp.asarray(rng.normal(size=(4, 768)), jnp.bfloat16)
        wq, s = quantize_int8(w)
        with force_impl("pallas"):  # default block_k=512 does not divide
            got = jax.jit(lambda x: int8_matmul(x, wq, s))(x)
        want = _dequant_matmul_xla(x, wq, s)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-2, atol=2e-2)

    def test_unaligned_shapes_take_composite(self):
        rng = np.random.default_rng(2)
        w = jnp.asarray(rng.normal(size=(60, 72)), jnp.float32)
        x = jnp.asarray(rng.normal(size=(3, 72)), jnp.bfloat16)
        wq, s = quantize_int8(w)
        with force_impl("pallas"):  # gate must fall back, not crash
            got = int8_matmul(x, wq, s)
        np.testing.assert_allclose(np.asarray(got),
                                   np.asarray(_dequant_matmul_xla(x, wq,
                                                                  s)),
                                   rtol=1e-5, atol=1e-5)

    def test_leading_dims_and_grad(self):
        rng = np.random.default_rng(3)
        w = jnp.asarray(rng.normal(size=(128, 128)), jnp.float32)
        wq, s = quantize_int8(w)
        x = jnp.asarray(rng.normal(size=(2, 5, 128)), jnp.float32)
        y = int8_matmul(x, wq, s)
        assert y.shape == (2, 5, 128) and y.dtype == jnp.float32
        dx, dwq, ds = jax.grad(
            lambda x, wq, s: jnp.sum(int8_matmul(x, wq, s)),
            argnums=(0, 1, 2), allow_int=True)(x, wq, s)
        wdq = np.asarray(wq, np.float32) * np.asarray(s)[:, None]
        # bwd runs in bf16 (decode dtype): 128-term column sums carry
        # ~0.4% relative rounding
        np.testing.assert_allclose(np.asarray(dx),
                                   np.broadcast_to(wdq.sum(0), x.shape),
                                   rtol=5e-2, atol=0.1)
        assert (np.asarray(ds) == 0).all()  # weights frozen


class TestQuantDecode:
    @staticmethod
    def _exactly_representable(params):
        """Replace every matmul weight by q*s with q in [-127, 127] so
        quantization is lossless — decode parity then isolates the code
        path, not the quantization error."""
        rng = np.random.default_rng(7)

        def fix(path, p):
            name = path[-1].key if hasattr(path[-1], "key") else path[-1]
            if name in ("wq", "wk", "wv", "wo", "w_gate", "w_up",
                        "w_down", "output"):
                q = rng.integers(-127, 128, size=p.shape)
                return jnp.asarray(q * 2e-3, jnp.float32)
            return p

        return jax.tree_util.tree_map_with_path(fix, params)

    @pytest.fixture(scope="class")
    def setup(self):
        cfg = LlamaConfig.tiny(policy=get_policy("O0"), max_seq_len=32)
        model = Llama(cfg)
        rng = np.random.default_rng(5)
        prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 5)),
                             jnp.int32)
        params = model.init(jax.random.key(0), prompt)["params"]
        params = self._exactly_representable(params)
        return cfg, model, params, prompt

    def test_quant_logits_match_full_precision(self, setup):
        cfg, model, params, prompt = setup
        apply_q, make_cache, qparams = llama_quant_decoder(model, params)
        cache = make_cache(2, 16)
        logits_q, _ = apply_q(qparams, prompt, cache, 0)
        apply_f, make_cache_f = llama_decoder(model)
        logits_f, _ = apply_f(params, prompt, make_cache_f(2, 16), 0)
        # exactly-representable weights: differences are bf16 rounding
        np.testing.assert_allclose(np.asarray(logits_q),
                                   np.asarray(logits_f),
                                   rtol=5e-2, atol=5e-2)

    def test_quant_generate_matches_full_precision_tokens(self, setup):
        cfg, model, params, prompt = setup
        N = 6
        apply_q, make_cache, qparams = llama_quant_decoder(model, params)
        got = generate(apply_q, qparams, prompt, max_new_tokens=N,
                       cache=make_cache(2, 11))
        apply_f, make_cache_f = llama_decoder(model)
        want = generate(apply_f, params, prompt, max_new_tokens=N,
                        cache=make_cache_f(2, 11))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_real_weights_quant_error_is_small(self, setup):
        cfg, model, _, prompt = setup
        rng = np.random.default_rng(11)
        params = model.init(jax.random.key(1), prompt)["params"]
        apply_q, make_cache, qparams = llama_quant_decoder(model, params)
        logits_q, _ = apply_q(qparams, prompt, make_cache(2, 16), 0)
        apply_f, make_cache_f = llama_decoder(model)
        logits_f, _ = apply_f(params, prompt, make_cache_f(2, 16), 0)
        lq, lf = np.asarray(logits_q), np.asarray(logits_f)
        denom = max(1.0, np.abs(lf).max())
        assert np.abs(lq - lf).max() / denom < 0.15, (
            np.abs(lq - lf).max(), denom)

    def test_gpt2_quant_generate_matches_full_precision(self):
        """GPT-2 family on the int8 path: with int8-representable
        weights the quantized decode emits the same tokens as the flax
        model's cached decode."""
        from apex1_tpu.models.generate import gpt2_decoder
        from apex1_tpu.models.gpt2 import GPT2, GPT2Config
        from apex1_tpu.models.quant_decode import gpt2_quant_decoder
        cfg = GPT2Config.tiny(policy=get_policy("O0"), max_seq_len=32)
        model = GPT2(cfg)
        rng = np.random.default_rng(13)
        prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 5)),
                             jnp.int32)
        params = model.init(jax.random.key(0), prompt)["params"]

        def fix(path, p):
            name = path[-1].key if hasattr(path[-1], "key") else path[-1]
            if name == "kernel" or name == "wte":
                q = rng.integers(-127, 128, size=p.shape)
                return jnp.asarray(q * 2e-3, jnp.float32)
            return p

        params = jax.tree_util.tree_map_with_path(fix, params)
        N = 6
        apply_q, make_cache, qparams = gpt2_quant_decoder(model, params)
        got = generate(apply_q, qparams, prompt, max_new_tokens=N,
                       cache=make_cache(2, 11),
                       vocab_size=cfg.vocab_size)
        apply_f, make_cache_f = gpt2_decoder(model)
        want = generate(apply_f, params, prompt, max_new_tokens=N,
                        cache=make_cache_f(2, 11),
                        vocab_size=cfg.vocab_size)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_moe_guarded(self):
        cfg = LlamaConfig.tiny(policy=get_policy("O0"), moe_every=1,
                               num_experts=2, moe_top_k=1)
        model = Llama(cfg)
        prompt = jnp.zeros((1, 4), jnp.int32)
        params = model.init(jax.random.key(0), prompt)["params"]
        with pytest.raises(NotImplementedError, match="MoE"):
            llama_quant_decoder(model, params)
