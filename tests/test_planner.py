"""apex1_tpu.planner — legality, memory pre-filter, calibrated pricing,
plan determinism, and the ISSUE-12 acceptance contract (planner pick
within ~10% of the hand-tuned layouts on the banked bench shapes,
against the COMMITTED perf_results/calibration.json)."""

import json
import os
import subprocess
import sys

import pytest

from apex1_tpu import perf_model, planner
from apex1_tpu.planner.__main__ import TINY

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _layout(**kw):
    return planner.Layout(**kw)


# ---------------------------------------------------------------------------
# legality
# ---------------------------------------------------------------------------

class TestLegality:
    def test_enumerated_layouts_all_legal(self):
        for shape, n in ((TINY, 8), (TINY, 4),
                         (planner.BANKED_SHAPES["llama8b"], 16)):
            lays = list(planner.enumerate_layouts(shape, n))
            assert lays, f"{shape.name}/{n}: nothing enumerated"
            for lay in lays:
                assert lay.n_devices == n
                vs = planner.check_layout(shape, lay, n)
                assert not vs, f"{lay} enumerated but illegal: {vs}"

    def test_enumeration_deterministic(self):
        a = list(planner.enumerate_layouts(TINY, 8))
        b = list(planner.enumerate_layouts(TINY, 8))
        assert a == b

    @pytest.mark.parametrize("kw,rule", [
        (dict(tp=3), "tp-heads"),
        (dict(tp=3), "tp-vocab"),
        (dict(tp=3), "sp-seq"),
        (dict(pp=3, num_microbatches=8), "pp-stages"),
        (dict(pp=2, num_microbatches=3, num_chunks=2,
              schedule="1f1b"), "pp-microbatches"),
        (dict(dp=3), "dp-batch"),
        (dict(ep=2, dp=1), "ep-moe"),
        (dict(zero=True), "zero-dp"),
        (dict(sp_mode="bogus"), "sp-mode"),
    ])
    def test_rule_names(self, kw, rule):
        # TINY: 2 layers, 4/2 heads, vocab 256, seq 64, batch 8 — each
        # kw breaks exactly the named rule (others may fire too)
        kw.setdefault("num_microbatches", 8)
        lay = _layout(**kw)
        rules = {v.rule for v in planner.check_layout(TINY, lay)}
        assert rule in rules, rules

    def test_device_product_rule(self):
        lay = _layout(dp=2, num_microbatches=4)
        rules = {v.rule for v in planner.check_layout(TINY, lay, 8)}
        assert "device-product" in rules

    def test_legal_layout_clean(self):
        lay = _layout(dp=2, pp=2, tp=2, num_microbatches=4)
        assert planner.check_layout(TINY, lay, 8) == []

    def test_zero_axis_is_a_violation_not_a_crash(self):
        # review fix: --tp 0 must come back as [axis-positive], not a
        # ZeroDivisionError from the divisibility rules downstream
        vs = planner.check_layout(TINY, _layout(tp=0,
                                                num_microbatches=8))
        assert {v.rule for v in vs} == {"axis-positive"}
        vs = planner.check_layout(TINY, _layout(dp=0, pp=0,
                                                num_microbatches=8))
        assert all(v.rule == "axis-positive" for v in vs)
        assert len(vs) == 2

    def test_check_plan_model(self):
        # the ONE replay-validation helper both --plan consumers use
        import dataclasses
        plan = planner.make_plan(TINY, 8)
        assert planner.check_plan_model(plan, TINY) == []
        other = dataclasses.replace(TINY, num_layers=4,
                                    num_experts=4)
        bad = planner.check_plan_model(plan, other)
        assert any("num_layers" in m for m in bad)
        assert any("num_experts" in m for m in bad)
        # global_batch deliberately unchecked: the plan's schedule is
        # the batch authority on replay
        gb = dataclasses.replace(TINY, global_batch=99)
        assert planner.check_plan_model(plan, gb) == []

    def test_bubbly_scan_schedule_legal_but_pruned(self):
        # review fix: M < pp RUNS under the scan schedule
        # (Llama3DConfig accepts it — a hand --pp 2 --microbatches 1
        # must not be refused), but the enumerator prunes it as
        # dominated (bubble >= 2x)
        import dataclasses
        s = dataclasses.replace(TINY, global_batch=1)
        lay = _layout(pp=2, num_microbatches=1)
        assert planner.check_layout(s, lay, 2) == []
        assert all(l.num_microbatches >= l.pp
                   for l in planner.enumerate_layouts(TINY, 8))

    def test_example_rejects_illegal_layout_loudly(self):
        # the satellite fix: examples/llama_3d.py exits 2 NAMING the
        # rule, before any jax compilation
        proc = subprocess.run(
            [sys.executable, os.path.join("examples", "llama_3d.py"),
             "--tp", "3", "--steps", "1"],
            cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=180)
        assert proc.returncode == 2
        assert "ILLEGAL LAYOUT" in proc.stderr
        assert "tp-heads" in proc.stderr


# ---------------------------------------------------------------------------
# memory pre-filter
# ---------------------------------------------------------------------------

class TestMemory:
    def test_prefilter_reproduces_banked_aot_verdicts(self):
        # the llama_longctx sizing episode (bench.py docstring + the
        # banked aot logs): 16-layer 0.8B fits a v5e (~14.4 GiB
        # measured), the 22-layer variant does not (18.7 GiB > 15.75)
        import dataclasses
        s16 = planner.BANKED_SHAPES["llama_longctx"]
        s22 = dataclasses.replace(s16, num_layers=22)
        lay = _layout(num_microbatches=1)
        assert planner.fit_check(s16, lay, "v5e") is None
        msg = planner.fit_check(s22, lay, "v5e")
        assert msg is not None
        assert "hbm-fit" in msg and "GiB" in msg
        # the sizing is STATED: needs-X > budget-Y with the breakdown
        assert "15.75" in msg and "opt" in msg and "weights" in msg

    def test_over_budget_plan_raises_with_sizing(self):
        import dataclasses
        s22 = dataclasses.replace(planner.BANKED_SHAPES["llama_longctx"],
                                  num_layers=22)
        with pytest.raises(planner.PlanError) as ei:
            planner.make_plan(s22, 1, generation="v5e")
        assert "hbm-fit" in str(ei.value) and "GiB" in str(ei.value)

    def test_zero_shards_optimizer_memory(self):
        s = planner.BANKED_SHAPES["llama8b"]
        base = _layout(dp=4, tp=4, num_microbatches=2)
        zero = _layout(dp=4, tp=4, num_microbatches=2, zero=True)
        b0 = planner.hbm_breakdown(s, base, "v5p")
        b1 = planner.hbm_breakdown(s, zero, "v5p")
        assert b1["opt"] == pytest.approx(b0["opt"] / 4)
        assert b1["total"] < b0["total"]

    def test_8b_fits_v5p_not_v5e_unsharded(self):
        s = planner.BANKED_SHAPES["llama8b"]
        lay = _layout(dp=2, pp=2, tp=4, num_microbatches=4)
        assert planner.fit_check(s, lay, "v5p") is None
        assert planner.fit_check(s, lay, "v5e") is not None


# ---------------------------------------------------------------------------
# pricing + calibration
# ---------------------------------------------------------------------------

class TestPricing:
    def test_calibration_factor_from_committed_table(self):
        # the committed calibration.json must drive the price: the
        # calibrated/analytic ratio IS the banked step:gpt2 slowdown
        doc = json.load(open(os.path.join(REPO, "perf_results",
                                          "calibration.json")))
        want = doc["factors"]["step:gpt2"]["slowdown"]
        shape = planner.BANKED_SHAPES["gpt2"]
        lay = _layout(num_microbatches=16)
        cal = planner.price_layout(shape, lay, generation="v5e")
        raw = planner.price_layout(shape, lay, generation="v5e",
                                   use_calibration=False)
        assert cal["calibrated_step_ms"] / cal["step_ms"] == \
            pytest.approx(want)
        assert raw["calibrated_step_ms"] == raw["step_ms"]
        assert "step:gpt2" in cal["calibration"]["source"]

    def test_uncalibrated_shape_gets_fleet_geomean(self):
        s = planner.BANKED_SHAPES["llama8b"]
        lay = _layout(dp=2, pp=2, tp=4, num_microbatches=4)
        p = planner.price_layout(s, lay, generation="v5p")
        assert "fleet-geomean" in p["calibration"]["source"]
        assert p["calibrated_step_ms"] > p["step_ms"]   # slowdowns > 1

    def test_no_table_is_labelled_uncalibrated(self, tmp_path):
        p = planner.price_layout(
            planner.BANKED_SHAPES["gpt2"], _layout(num_microbatches=16),
            generation="v5e", results_dir=str(tmp_path))
        assert p["calibration"]["slowdown"] == 1.0
        assert "uncalibrated" in p["calibration"]["source"]

    def test_sp_mode_prices_differently(self):
        # the kernel-selection dimension: serial exposes every SP
        # boundary byte, overlap only the residual — serial must never
        # price cheaper
        s = planner.BANKED_SHAPES["llama8b"]
        t = {}
        for mode in ("serial", "overlap", "fused"):
            lay = _layout(dp=2, pp=2, tp=4, num_microbatches=4,
                          sp_mode=mode)
            p = planner.price_layout(s, lay, generation="v5p")
            t[mode] = p["step_ms"]
            assert p["ici_exposed_bytes"]["sp_boundary"] >= 0.0
        assert t["serial"] >= t["overlap"]
        assert t["fused"] >= t["overlap"]   # fused pays the prologue
        #   hop on compute-rich shapes; overlap's BEST-case residual
        #   can be 0 (perf_model.sp_boundary_comms docstring)

    def test_bubble_factor(self):
        s = planner.BANKED_SHAPES["llama8b"]
        p1 = planner.price_layout(
            s, _layout(dp=2, pp=2, tp=4, num_microbatches=4),
            generation="v5p")
        assert p1["bubble_factor"] == pytest.approx((4 + 2 - 1) / 4)

    def test_acceptance_planner_within_10pct_of_hand_tuned(self):
        # ISSUE 12 acceptance: on the banked bench shapes the
        # planner's pick prices within ~10% of the best hand-tuned
        # config, against the COMMITTED calibration.json. The hand
        # layouts: the single-chip bench configs and aot_check
        # --flagship's dp2 x pp2 x tp4 8B recipe.
        cases = [
            ("gpt2", 1, "v5e", _layout(num_microbatches=16)),
            ("llama_longctx", 1, "v5e", _layout(num_microbatches=1)),
            ("llama8b", 16, "v5p",
             _layout(dp=2, pp=2, tp=4, num_microbatches=4)),
        ]
        for name, n, gen, hand in cases:
            shape = planner.BANKED_SHAPES[name]
            # the hand layout must be IN the search space (legal)…
            assert planner.check_layout(shape, hand, n) == []
            hand_ms = planner.price_layout(
                shape, hand, generation=gen)["calibrated_step_ms"]
            plan = planner.make_plan(shape, n, generation=gen)
            pick_ms = plan["predicted"]["calibrated_step_ms"]
            # …so the pick is at worst 10% over it (and usually at or
            # below: the argmin saw the hand layout too)
            assert pick_ms <= 1.10 * hand_ms, (name, pick_ms, hand_ms)


# ---------------------------------------------------------------------------
# plan emission
# ---------------------------------------------------------------------------

class TestPlan:
    def test_plan_byte_determinism(self):
        a = planner.plan_json(planner.make_plan(TINY, 8))
        b = planner.plan_json(planner.make_plan(TINY, 8))
        assert a == b
        assert a.endswith("\n")

    def test_save_load_roundtrip(self, tmp_path):
        plan = planner.make_plan(TINY, 8)
        path = str(tmp_path / "plan.json")
        planner.save_plan(plan, path)
        assert planner.load_plan(path) == plan

    def test_load_plan_rejects_foreign_files(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text("{\"schema\": \"something-else\"}")
        with pytest.raises(ValueError):
            planner.load_plan(str(bad))
        notjson = tmp_path / "x.json"
        notjson.write_text("not json at all")
        with pytest.raises(ValueError):
            planner.load_plan(str(notjson))
        with pytest.raises(ValueError):
            planner.load_plan(str(tmp_path / "missing.json"))

    def test_plan_carries_calibration_provenance(self):
        plan = planner.make_plan(planner.BANKED_SHAPES["gpt2"], 1)
        assert plan["provenance"]["calibration_table"] == \
            "calibration.json"
        assert plan["schema"] == planner.PLAN_SCHEMA

    def test_llama3d_config_from_plan(self):
        from apex1_tpu.core.policy import get_policy
        from apex1_tpu.models.llama import LlamaConfig

        plan = planner.make_plan(TINY, 8, allow_zero=False)
        mcfg = LlamaConfig.tiny(
            num_layers=TINY.num_layers, max_seq_len=TINY.seq_len,
            vocab_size=TINY.vocab_size, num_heads=TINY.num_heads,
            num_kv_heads=TINY.num_kv_heads,
            hidden_size=TINY.hidden_size, ffn_size=TINY.ffn_size,
            policy=get_policy("O2"))
        cfg = planner.llama3d_config_from_plan(plan, mcfg)
        m = plan["mesh"]
        assert (cfg.dp, cfg.pp, cfg.cp, cfg.ep, cfg.tp) == \
            (m["dp"], m["pp"], m["cp"], m["ep"], m["tp"])
        assert cfg.num_microbatches == \
            plan["schedule"]["num_microbatches"]

    def test_partition_rules_reproduce_llama3d_specs(self):
        # the emitted regex rules, pushed through the generic
        # parallel.specs engine, must equal the model's hand-written
        # spec tables leaf-for-leaf — dense AND MoE
        from apex1_tpu.core.policy import get_policy
        from apex1_tpu.models.llama import LlamaConfig
        from apex1_tpu.models.llama_3d import (Llama3DConfig,
                                               chunk_param_specs,
                                               init_params,
                                               shared_param_specs)

        for moe in (False, True):
            moe_kw = (dict(moe_every=1, num_experts=4, moe_top_k=2)
                      if moe else {})
            mcfg = LlamaConfig.tiny(num_layers=2, max_seq_len=64,
                                    policy=get_policy("O2"), **moe_kw)
            cfg = Llama3DConfig(model=mcfg, dp=2, pp=2, tp=1, moe=moe,
                                ep=2 if moe else 1,
                                num_microbatches=4)
            chunk, shared = init_params(cfg)
            params = {"chunk": chunk, "shared": shared}
            shape = planner.ModelShape.from_llama(
                mcfg, global_batch=8, name="t")
            lay = planner.Layout(dp=2, pp=2, ep=2 if moe else 1,
                                 num_microbatches=4 if moe else 4)
            plan = planner.build_plan(
                shape, lay,
                planner.price_layout(shape, lay),
                planner.hbm_breakdown(shape, lay),
                generation="v5e", search={})
            got = planner.plan_param_specs(plan, params)
            cspecs = chunk_param_specs(cfg)
            want = {"chunk": {k: cspecs[k] for k in chunk},
                    "shared": shared_param_specs()}
            assert got == want, f"moe={moe}"

    def test_zero_plan_refused_by_config_bridge(self):
        # review fix: a zero=True plan's HBM verdict divided opt
        # state by dp; Llama3DConfig has no ZeRO wiring, so the
        # bridge must refuse rather than silently run unsharded
        from apex1_tpu.core.policy import get_policy
        from apex1_tpu.models.llama import LlamaConfig

        lay = planner.Layout(dp=2, pp=2, tp=2, num_microbatches=4,
                             zero=True)
        plan = planner.build_plan(
            TINY, lay, planner.price_layout(TINY, lay),
            planner.hbm_breakdown(TINY, lay), generation="v5e",
            search={})
        mcfg = LlamaConfig.tiny(num_layers=2, max_seq_len=64,
                                policy=get_policy("O2"))
        with pytest.raises(ValueError, match="zero"):
            planner.llama3d_config_from_plan(plan, mcfg)
        cfg = planner.llama3d_config_from_plan(plan, mcfg,
                                               ignore_zero=True)
        assert cfg.dp == 2

    def test_rules_roundtrip_spec_json(self):
        from jax.sharding import PartitionSpec as P

        from apex1_tpu.planner import emit
        assert emit.spec_from_json([None, "pp", ["dp", "ep"]]) == \
            P(None, "pp", ("dp", "ep"))
        assert emit.spec_to_json((None, "pp", ("dp", "ep"))) == \
            [None, "pp", ["dp", "ep"]]


# ---------------------------------------------------------------------------
# perf_model (the refactored pricing library predict_perf rides)
# ---------------------------------------------------------------------------

class TestPerfModel:
    def test_roofline_arithmetic(self):
        from apex1_tpu.core.capability import get_capability
        cap = get_capability("v5e")
        # compute-bound: flops term dominates
        t, bound, mfu = perf_model.roofline(cap.bf16_tflops * 1e12,
                                            1.0, cap)
        assert t == pytest.approx(1.0) and bound == "MXU"
        assert mfu == pytest.approx(1.0)
        # bandwidth-bound
        t, bound, _ = perf_model.roofline(1.0, cap.hbm_gbps * 1e9, cap)
        assert t == pytest.approx(1.0) and bound == "HBM"
        # exposed ICI adds serially
        from apex1_tpu.core.capability import ici_link_gbps
        link = ici_link_gbps("v5e")
        t2, bound2, _ = perf_model.roofline(
            1.0, cap.hbm_gbps * 1e9, cap,
            ici_exposed_bytes=2 * link * 1e9)
        assert t2 == pytest.approx(3.0) and bound2 == "ICI"

    def test_kernel_cases_formulas_stable(self):
        # the values predict_perf banked pre-refactor — the flash gpt2
        # fwd row and the linear_xent row, recomputed by hand
        cases = {name: (f, b) for name, f, b
                 in perf_model.kernel_cases()}
        f, b = cases["flash gpt2 (16,12,1024,64) fwd"]
        assert f == 4 * 16 * 12 * 1024 * 1024 * 64 * 0.5
        assert b == (16 * 12 * 1024 * 64 * 2) * 2 \
            + 2 * 16 * 12 * 1024 * 64 * 2
        f, _ = cases["linear_xent gpt2 (16k,768,50k) f+b"]
        assert f == 6 * (16 * 1023) * 768 * 50432
        assert len(cases) == 11

    def test_sp_boundary_comms_matches_predict_comms_fused(self):
        # the exact arithmetic predict_perf.predict_comms_fused
        # printed before the refactor, recomputed inline
        from apex1_tpu.core.capability import (get_capability,
                                               ici_link_gbps)
        S, hid, ffn, n, gen = 8192, 4096, 14336, 4, "v5e"
        m = perf_model.sp_boundary_comms(gen, n, rows=S,
                                         out_width=hid, ffn=ffn)
        link, cap = ici_link_gbps(gen), get_capability(gen)
        chunk_rows = S // n
        hop = chunk_rows * hid * 4
        dot = 2 * chunk_rows * (ffn // n) * hid
        t_hop, t_dot = hop / (link * 1e9), dot / (cap.bf16_tflops
                                                  * 1e12)
        resid = n * max(0.0, t_hop - t_dot) * (link * 1e9)
        assert m["total"] == float(n * hop)
        assert m["exposed_overlap"] == pytest.approx(resid)
        assert m["exposed_fused"] == pytest.approx(hop + resid)

    def test_ring_comms_matches_predict_comms(self):
        from apex1_tpu.core.capability import (get_capability,
                                               ici_link_gbps)
        gen, n = "v5e", 4
        m = perf_model.ring_attention_comms(gen, n)
        link, cap = ici_link_gbps(gen), get_capability(gen)
        S_l = 16384 // n
        kv_hop = 2 * 1 * 4 * S_l * 64 * 2
        att = 4 * 1 * 32 * S_l * S_l * 64 * 0.5
        assert m["kv_hop"] == kv_hop
        assert m["t_att"] == pytest.approx(att / (cap.bf16_tflops
                                                  * 1e12))
        assert m["fwd_bytes"] == (n - 1) * kv_hop
        exp = (n - 1) * max(0.0, kv_hop / (link * 1e9)
                            - m["t_att"]) * (link * 1e9)
        assert m["exp_f_overlap"] == pytest.approx(exp)

    def test_sp_boundary_hop_width_decoupled_from_dot(self):
        # review fix: an all-gather boundary hops the INPUT activation
        # (width E, constant in tp) — not the dot's output shard. The
        # hop bytes must follow hop_width; the dot keeps out_width.
        E, n = 4096, 4
        m = perf_model.sp_boundary_comms(
            "v5e", n, rows=1024, local_k=E, out_width=1536 // n,
            acc_bytes=2, hop_width=E)
        assert m["hop"] == (1024 // n) * E * 2
        assert m["dot"] == 2 * (1024 // n) * E * (1536 // n)
        # default (None) keeps the reduce-scatter semantics —
        # predict_comms_fused's banked arithmetic is unchanged
        m2 = perf_model.sp_boundary_comms("v5e", n, rows=1024,
                                          local_k=E, out_width=512)
        assert m2["hop"] == (1024 // n) * 512 * 4

    def test_allreduce_bytes(self):
        assert perf_model.allreduce_bytes(100.0, 1) == 0.0
        assert perf_model.allreduce_bytes(100.0, 4) == \
            pytest.approx(150.0)
