"""Property-based shape fuzzing for the Pallas kernels (interpret mode)
vs their XLA-composite golds — catches ragged-edge/padding bugs the
fixed-shape parity tests can't (odd seqlens, non-128 head dims, GQA
ratios, Sq != Sk). Bounded example counts keep the suite fast."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import HealthCheck, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from apex1_tpu.ops._common import force_impl
from apex1_tpu.ops.attention import _xla_attention, flash_attention

pytestmark = pytest.mark.slow  # composed-step / fuzz suite: full run via check_all.sh --all

# 5 examples/property (was 8): each example is a fresh-shape interpret
# compile (~8s on one core); wall-time budget per VERDICT r3 Weak #5 —
# the shape-space coverage is random anyway, the property doesn't weaken.
# APEX1_FUZZ_EXAMPLES overrides for deep one-off hunts
# (e.g. APEX1_FUZZ_EXAMPLES=40 pytest tests/test_fuzz_kernels.py).
_SETTINGS = dict(
    max_examples=int(os.environ.get("APEX1_FUZZ_EXAMPLES") or "5"),
    deadline=None, suppress_health_check=list(HealthCheck))


@settings(**_SETTINGS)
@given(
    sq=st.integers(1, 70),
    sk=st.integers(1, 70),
    d=st.sampled_from([8, 24, 64]),
    hq_mult=st.sampled_from([1, 2, 3]),
    hkv=st.sampled_from([1, 2]),
    causal=st.booleans(),
    seed=st.integers(0, 2**16),
)
def test_flash_attention_fuzz(sq, sk, d, hq_mult, hkv, causal, seed):
    rng = np.random.default_rng(seed)
    B, Hq = 1, hkv * hq_mult
    q = jnp.asarray(rng.normal(size=(B, Hq, sq, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, hkv, sk, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, hkv, sk, d)), jnp.float32)

    def loss(fn):
        def f(q, k, v):
            return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)
        return f

    with force_impl("pallas"):
        fn = lambda q, k, v: flash_attention(q, k, v, causal=causal)
        out = fn(q, k, v)
        gq, gk, gv = jax.grad(loss(fn), argnums=(0, 1, 2))(q, k, v)
    gold_fn = lambda q, k, v: _xla_attention(
        q, k, v, None, None, 0, 0, 1.0 / np.sqrt(d), causal)
    want = gold_fn(q, k, v)
    wq, wk, wv = jax.grad(loss(gold_fn), argnums=(0, 1, 2))(q, k, v)

    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    for g, w, nm in ((gq, wq, "dq"), (gk, wk, "dk"), (gv, wv, "dv")):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-4, err_msg=nm)


@settings(**_SETTINGS)
@given(
    t=st.integers(1, 50),
    h=st.sampled_from([8, 40, 128]),
    v=st.sampled_from([12, 64, 200]),
    smoothing=st.sampled_from([0.0, 0.1]),
    seed=st.integers(0, 2**16),
)
def test_linear_xent_fuzz(t, h, v, smoothing, seed):
    from apex1_tpu.ops.linear_xent import (_xla_linear_xent,
                                           linear_cross_entropy)
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(t, h)) * 0.1, jnp.float32)
    w = jnp.asarray(rng.normal(size=(v, h)) * 0.1, jnp.float32)
    tgt = jnp.asarray(rng.integers(0, v, (t,)), jnp.int32)

    with force_impl("pallas"):
        f = lambda x, w: jnp.mean(linear_cross_entropy(
            x, w, tgt, smoothing=smoothing))
        got = f(x, w)
        gx, gw = jax.grad(f, argnums=(0, 1))(x, w)
    gold = lambda x, w: jnp.mean(_xla_linear_xent(
        x, w, tgt, smoothing, None, None))
    want = gold(x, w)
    wx, ww = jax.grad(gold, argnums=(0, 1))(x, w)

    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(wx),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(ww),
                               rtol=2e-4, atol=2e-5)


@settings(**_SETTINGS)
@given(
    t=st.integers(1, 40),
    n=st.sampled_from([8, 128, 200, 256]),
    k=st.sampled_from([16, 128, 512, 520]),
    block_n=st.sampled_from([128, 256]),
    block_k=st.sampled_from([128, 512]),
    seed=st.integers(0, 2**16),
)
def test_int8_matmul_fuzz(t, n, k, block_n, block_k, seed):
    """int8 weight-only GEMM across aligned AND unaligned shapes — the
    aligned path runs the Pallas kernel (sublane row padding, block-fit
    heuristics), unaligned falls back to the composite; both must match
    the explicit dequant gold, and dx must flow (dw is defined zero)."""
    from apex1_tpu.ops.quantized import (_dequant_matmul_xla, int8_matmul,
                                         quantize_int8)
    rng = np.random.default_rng(seed)
    w = jnp.asarray(rng.normal(size=(n, k)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(t, k)), jnp.float32)
    wq, scale = quantize_int8(w)

    with force_impl("pallas"):
        f = lambda x: jnp.sum(
            int8_matmul(x, wq, scale, block_n, block_k) ** 2)
        got = int8_matmul(x, wq, scale, block_n, block_k)
        gx = jax.grad(f)(x)
    # gold = the op's OWN numerics contract (_dequant_matmul_xla: bf16
    # operands, fp32 accumulation, fp32 per-channel scale — also the
    # unaligned-shape fallback, so unaligned draws compare exactly).
    # A fp32-activation reference would diverge by the bf16 input cast
    # on cancellation-heavy outputs (observed 9% relative on ~2% of
    # elements) — quantization noise shared by both paths, not a kernel
    # defect; this fuzz also caught the composite NOT casting x, i.e.
    # shape-dependent numerics for fp32 callers (fixed in quantized.py).
    want = _dequant_matmul_xla(x, wq, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3 * np.sqrt(k))
    # dx gold: the ANALYTIC fp32 transpose dy·s₃₂ @ wq — the op's bwd
    # is the same fp32 dot, so this matches tightly; tight enough that
    # the bf16-scale bug this fuzz originally caught (~0.4% off) cannot
    # hide. AD of the composite is NOT the gold here: jax's matmul
    # transpose emits the x-cotangent in x's bf16 operand dtype, i.e.
    # the gold itself would be bf16-rounded.
    dy = 2.0 * jnp.asarray(got)
    wantg = (dy * scale[None, :]) @ wq.astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(wantg),
                               rtol=1e-4, atol=1e-4 * np.sqrt(k) * 8)


@settings(**_SETTINGS)
@given(
    s=st.integers(1, 50),
    h=st.sampled_from([1, 3]),
    # 256 is the ONLY dim here that passes rope.py's `half % 128 == 0`
    # kernel gate — without it every draw silently compares the XLA
    # composite to itself (the hw_numerics.py:270 trap); the small dims
    # keep fuzzing the composite's own edge shapes
    d=st.sampled_from([8, 32, 64, 256]),
    interleaved=st.booleans(),
    offset=st.integers(0, 100),
    seed=st.integers(0, 2**16),
)
def test_rope_fuzz(s, h, d, interleaved, offset, seed):
    """Fused RoPE vs the composite rotation at fuzzed seq/heads/dim and
    position offsets, both conventions, fwd + the rotate-by-minus-theta
    backward (kernel-eligible only at d=256 — see the d note above)."""
    from apex1_tpu.ops.rope import apply_rotary_pos_emb, rope_tables
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(1, s, h, d)), jnp.float32)
    cos, sin = rope_tables(offset + jnp.arange(s), d)

    def run(impl):
        with force_impl(impl):
            f = lambda x: jnp.sum(apply_rotary_pos_emb(
                x, cos, sin, interleaved=interleaved) ** 2)
            return (f(x), jax.grad(f)(x))

    (got, gx), (want, wx) = run("pallas"), run("xla")
    np.testing.assert_allclose(float(got), float(want), rtol=3e-5)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(wx),
                               rtol=3e-5, atol=3e-5)


@settings(**_SETTINGS)
@given(
    rows=st.integers(1, 40),
    h=st.sampled_from([8, 96, 130]),
    rms=st.booleans(),
    seed=st.integers(0, 2**16),
)
def test_norm_fuzz(rows, h, rms, seed):
    from apex1_tpu.ops import layer_norm, rms_norm
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(rows, h)), jnp.float32)
    g = jnp.asarray(rng.normal(size=(h,)) * 0.1 + 1.0, jnp.float32)
    b = jnp.asarray(rng.normal(size=(h,)) * 0.1, jnp.float32)

    def run(impl):
        with force_impl(impl):
            if rms:
                f = lambda x, g: jnp.sum(rms_norm(x, g) ** 2)
                return (f(x, g),) + jax.grad(f, argnums=(0, 1))(x, g)
            f = lambda x, g, b: jnp.sum(layer_norm(x, g, b) ** 2)
            return (f(x, g, b),) + jax.grad(f, argnums=(0, 1, 2))(x, g, b)

    got, want = run("pallas"), run("xla")
    for gg, ww in zip(got, want):
        np.testing.assert_allclose(np.asarray(gg), np.asarray(ww),
                                   rtol=3e-5, atol=3e-5)
