"""Property-based shape fuzzing for the Pallas kernels (interpret mode)
vs their XLA-composite golds — catches ragged-edge/padding bugs the
fixed-shape parity tests can't (odd seqlens, non-128 head dims, GQA
ratios, Sq != Sk). Bounded example counts keep the suite fast."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import HealthCheck, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from apex1_tpu.ops._common import force_impl
from apex1_tpu.ops.attention import _xla_attention, flash_attention

pytestmark = pytest.mark.slow  # composed-step / fuzz suite: full run via check_all.sh --all

# 5 examples/property (was 8): each example is a fresh-shape interpret
# compile (~8s on one core); wall-time budget per VERDICT r3 Weak #5 —
# the shape-space coverage is random anyway, the property doesn't weaken.
# APEX1_FUZZ_EXAMPLES overrides for deep one-off hunts
# (e.g. APEX1_FUZZ_EXAMPLES=40 pytest tests/test_fuzz_kernels.py).
_SETTINGS = dict(
    max_examples=int(os.environ.get("APEX1_FUZZ_EXAMPLES") or "5"),
    deadline=None, suppress_health_check=list(HealthCheck))


@settings(**_SETTINGS)
@given(
    sq=st.integers(1, 70),
    sk=st.integers(1, 70),
    d=st.sampled_from([8, 24, 64]),
    hq_mult=st.sampled_from([1, 2, 3]),
    hkv=st.sampled_from([1, 2]),
    causal=st.booleans(),
    seed=st.integers(0, 2**16),
)
def test_flash_attention_fuzz(sq, sk, d, hq_mult, hkv, causal, seed):
    rng = np.random.default_rng(seed)
    B, Hq = 1, hkv * hq_mult
    q = jnp.asarray(rng.normal(size=(B, Hq, sq, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, hkv, sk, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, hkv, sk, d)), jnp.float32)

    def loss(fn):
        def f(q, k, v):
            return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)
        return f

    with force_impl("pallas"):
        fn = lambda q, k, v: flash_attention(q, k, v, causal=causal)
        out = fn(q, k, v)
        gq, gk, gv = jax.grad(loss(fn), argnums=(0, 1, 2))(q, k, v)
    gold_fn = lambda q, k, v: _xla_attention(
        q, k, v, None, None, 0, 0, 1.0 / np.sqrt(d), causal)
    want = gold_fn(q, k, v)
    wq, wk, wv = jax.grad(loss(gold_fn), argnums=(0, 1, 2))(q, k, v)

    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    for g, w, nm in ((gq, wq, "dq"), (gk, wk, "dk"), (gv, wv, "dv")):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-4, err_msg=nm)


@settings(**_SETTINGS)
@given(
    t=st.integers(1, 50),
    h=st.sampled_from([8, 40, 128]),
    v=st.sampled_from([12, 64, 200]),
    smoothing=st.sampled_from([0.0, 0.1]),
    seed=st.integers(0, 2**16),
)
def test_linear_xent_fuzz(t, h, v, smoothing, seed):
    from apex1_tpu.ops.linear_xent import (_xla_linear_xent,
                                           linear_cross_entropy)
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(t, h)) * 0.1, jnp.float32)
    w = jnp.asarray(rng.normal(size=(v, h)) * 0.1, jnp.float32)
    tgt = jnp.asarray(rng.integers(0, v, (t,)), jnp.int32)

    with force_impl("pallas"):
        f = lambda x, w: jnp.mean(linear_cross_entropy(
            x, w, tgt, smoothing=smoothing))
        got = f(x, w)
        gx, gw = jax.grad(f, argnums=(0, 1))(x, w)
    gold = lambda x, w: jnp.mean(_xla_linear_xent(
        x, w, tgt, smoothing, None, None))
    want = gold(x, w)
    wx, ww = jax.grad(gold, argnums=(0, 1))(x, w)

    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(wx),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(ww),
                               rtol=2e-4, atol=2e-5)


@settings(**_SETTINGS)
@given(
    rows=st.integers(1, 40),
    h=st.sampled_from([8, 96, 130]),
    rms=st.booleans(),
    seed=st.integers(0, 2**16),
)
def test_norm_fuzz(rows, h, rms, seed):
    from apex1_tpu.ops import layer_norm, rms_norm
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(rows, h)), jnp.float32)
    g = jnp.asarray(rng.normal(size=(h,)) * 0.1 + 1.0, jnp.float32)
    b = jnp.asarray(rng.normal(size=(h,)) * 0.1, jnp.float32)

    def run(impl):
        with force_impl(impl):
            if rms:
                f = lambda x, g: jnp.sum(rms_norm(x, g) ** 2)
                return (f(x, g),) + jax.grad(f, argnums=(0, 1))(x, g)
            f = lambda x, g, b: jnp.sum(layer_norm(x, g, b) ** 2)
            return (f(x, g, b),) + jax.grad(f, argnums=(0, 1, 2))(x, g, b)

    got, want = run("pallas"), run("xla")
    for gg, ww in zip(got, want):
        np.testing.assert_allclose(np.asarray(gg), np.asarray(ww),
                                   rtol=3e-5, atol=3e-5)
