"""`serving.disagg.kv_transfer` error taxonomy + the re-route ladder.

Every `HandoffError` reason the module can raise is pinned here as
REACHABLE by a concrete fault — eviction, torn transfer, renamed /
reshaped / retyped / bit-flipped leaves, and the frontend's own
no-alive-source window — and every rung of the frontend's bounded
re-route ladder is exercised end-to-end:

  rung 1  radix-hit skip      (an earlier attempt's page already landed)
  rung 2  re-prefill survivor (prefill pool still routable)
  rung 3  decode re-prefill   (no prefill survivor this round)
  rung 4  LOUD eviction       (attempts > max_handoff_attempts)

Rungs 1-3 must end token-identical to an uninterrupted single-engine
run (the counter-keyed seed contract); rung 4 must end in a typed
`evicted` result that names the attempt budget — never a hang, never
silent garbage. The APX3xx protocol models
(`apex1_tpu.lint.protocols`, DisaggHandoffModel) prove this ladder
over every interleaving of the bounded configs; these tests pin the
SAME ladder on the shipped code with real pages.
"""

import numpy as np
import pytest

from apex1_tpu.serving import Engine, EngineConfig, FrontendConfig
from apex1_tpu.serving.disagg import (DisaggConfig, DisaggFrontend,
                                      HandoffError, extract_page,
                                      install_page, verify_page)
from apex1_tpu.testing.chaos import (HandoffCorruption, HandoffWindowKill,
                                     ServingFault, toy_decoder)

ECFG = dict(max_slots=3, max_len=48, prefill_chunk=4, vocab_size=61,
            temperature=0.8, seed=7)


@pytest.fixture(scope="module")
def toy():
    return toy_decoder()


def _engine(toy, **kw):
    apply_fn, make_cache, params = toy
    return Engine(apply_fn, make_cache, params,
                  EngineConfig(**{**ECFG, **kw}))


def _front(toy, fault=None, **dkw):
    apply_fn, make_cache, params = toy

    def make_engine():
        return Engine(apply_fn, make_cache, params, EngineConfig(**ECFG))

    pool = dict(n_replicas=1, capacity_per_replica=8, hedge_after_s=None)
    return DisaggFrontend(
        make_engine,
        DisaggConfig(prefill=FrontendConfig(**pool),
                     decode=FrontendConfig(**pool),
                     prefill_chunk=ECFG["prefill_chunk"], **dkw),
        fault=fault)


def _assert_solo_parity(toy, front, prompts, rids):
    ref = _engine(toy)
    for p, rid in zip(prompts, rids):
        res = front.poll(rid)
        assert res is not None and res.status == "done", (rid, res)
        sub = front._subs[rid]
        rr = ref.submit(p, max_new_tokens=sub.max_new_tokens,
                        seed=sub.seed)
        ref.run(max_steps=300)
        np.testing.assert_array_equal(res.tokens, ref.results[rr].tokens)


def _events(front, name):
    return [t for t in front.metrics.transitions if t["event"] == name]


# ---------------------------------------------------------------------------
# unit tier: every HandoffError reason, by mutation class
# ---------------------------------------------------------------------------


class TestHandoffErrorTaxonomy:
    @pytest.fixture()
    def src(self, toy):
        """An engine holding one chunk-aligned 8-token prefix page."""
        eng = _engine(toy)
        prompt = np.random.default_rng(3).integers(
            0, 61, (9,)).astype(np.int32)
        eng.submit(prompt, max_new_tokens=4, seed=11)
        eng.run(max_steps=100)
        return eng, tuple(int(t) for t in prompt[:8])

    def _leaf(self, page):
        return np.array(page.lane["toy"]["h"])

    def test_lru_evicted_page_is_typed_at_extract(self, src):
        """The availability reason: the page existed at prefill
        completion but was evicted before the transfer started — the
        exact race `extract_page`'s message names."""
        eng, key = src
        assert eng.kv.evict_prefix(key, force=True)
        with pytest.raises(HandoffError,
                           match="evicted before transfer"):
            extract_page(eng, key)

    def test_torn_transfer_leaf_count_both_directions(self, src):
        eng, key = src
        page = extract_page(eng, key)
        arr = self._leaf(page)
        page.lane = {"toy": {}}                  # a leaf lost in flight
        with pytest.raises(HandoffError,
                           match="0 leaves on arrival, 1 at departure"):
            verify_page(page)
        page.lane = {"toy": {"h": arr, "h2": arr}}   # a leaf invented
        with pytest.raises(HandoffError,
                           match="2 leaves on arrival, 1 at departure"):
            verify_page(page)

    def test_renamed_leaf_is_a_path_mismatch(self, src):
        eng, key = src
        page = extract_page(eng, key)
        page.lane = {"toy": {"z": self._leaf(page)}}
        with pytest.raises(HandoffError, match="path mismatch"):
            verify_page(page)

    def test_transposed_leaf_is_a_shape_mismatch(self, src):
        eng, key = src
        page = extract_page(eng, key)
        arr = self._leaf(page)
        page.lane = {"toy": {"h": arr.reshape(arr.shape[::-1])}}
        with pytest.raises(HandoffError, match="shape mismatch"):
            verify_page(page)

    def test_reinterpreted_leaf_is_a_dtype_mismatch(self, src):
        """Same bytes, same shape, different dtype (the classic
        serialization-metadata bug): the dtype field must catch it —
        the sha256 alone would pass."""
        eng, key = src
        page = extract_page(eng, key)
        arr = self._leaf(page)
        page.lane = {"toy": {"h": arr.view(np.int32)}}
        with pytest.raises(HandoffError, match="dtype mismatch"):
            verify_page(page)

    def test_bit_flip_is_a_sha256_mismatch_naming_the_leaf(self, src):
        eng, key = src
        page = extract_page(eng, key)
        arr = self._leaf(page)
        arr.reshape(-1).view(np.uint8)[-1] ^= 0x01
        page.lane = {"toy": {"h": arr}}
        with pytest.raises(HandoffError,
                           match=r"leaf \['toy'\]\['h'\] sha256"):
            verify_page(page)

    def test_install_never_touches_pool_on_any_mismatch(self, toy, src):
        eng, key = src
        dst = _engine(toy)
        for mutate in (lambda p: p.entries.pop(),
                       lambda p: p.entries[0].update(sha256="0" * 64)):
            page = extract_page(eng, key)
            mutate(page)
            with pytest.raises(HandoffError):
                install_page(dst, page)
            assert not dst.kv.has_prefix(key)


# ---------------------------------------------------------------------------
# integration tier: each rung of the re-route ladder, with parity
# ---------------------------------------------------------------------------


class _InstallThenKill(ServingFault):
    """The lost-ack race: the page REACHES the decode pool, then the
    source dies before the acknowledgment — the re-route must take the
    radix-hit-skip rung, not redo the prefill."""

    def __init__(self):
        self.front = None                # bound after construction
        self.fired = 0

    def on_handoff(self, replica_id, req_id, page):
        if self.fired:
            return
        self.fired += 1
        eng = self.front.decode.replicas[0].engine
        assert eng is not None, "decode pool not started at handoff"
        install_page(eng, page)
        from apex1_tpu.serving.replica import ReplicaKilled
        raise ReplicaKilled(
            f"chaos: source {replica_id} died after transfer of "
            f"request {req_id}, before the ack")


class _AlwaysCorrupt(ServingFault):
    """Sticky corruption: every handoff attempt's page is flipped on
    the wire — the crash-loop form the attempt budget exists for."""

    def __init__(self):
        self.fired = 0

    def on_handoff(self, replica_id, req_id, page):
        arr = np.array(page.lane["toy"]["h"])
        arr.reshape(-1).view(np.uint8)[0] ^= 0xFF
        page.lane = {"toy": {"h": arr}}
        self.fired += 1


class TestRerouteLadder:
    def _prompt(self, seed, n=9):
        return np.random.default_rng(seed).integers(
            0, 61, (n,)).astype(np.int32)

    def test_rung1_radix_hit_skip_after_lost_ack(self, toy):
        fault = _InstallThenKill()
        front = _front(toy, fault=fault)
        fault.front = front
        p = self._prompt(21)
        rid = front.submit(p, max_new_tokens=6)
        front.run_until_drained(timeout_s=60.0)
        assert fault.fired == 1
        _assert_solo_parity(toy, front, [p], [rid])
        # the rung's identity: one window_kill failure, one reroute,
        # and ZERO delivered handoffs — the page was already there, so
        # the decode pool radix-hit the installed prefix instead
        c = front.summary()["counters"]
        assert c["handoff_failures"] == 1 and c["handoff_reroutes"] == 1
        assert c.get("handoffs", 0) == 0
        assert _events(front, "handoff_failure")[0]["failure"] \
            == "window_kill"
        eng = front.decode.replicas[0].engine
        assert eng.metrics.get_counter("prefix_hits") >= 1

    def test_rung2_reprefill_on_survivor_after_integrity(self, toy):
        """One corrupt wire transfer: the arrival digest rejects it,
        the prefill pool is still alive, so attempt 1 re-prefills
        there and the SECOND handoff delivers."""
        fault = HandoffCorruption(at_handoff=0)
        front = _front(toy, fault=fault)
        p = self._prompt(22)
        rid = front.submit(p, max_new_tokens=6)
        front.run_until_drained(timeout_s=60.0)
        _assert_solo_parity(toy, front, [p], [rid])
        fails = _events(front, "handoff_failure")
        assert [f["failure"] for f in fails] == ["integrity"]
        assert "sha256" in fails[0]["reason"]
        delivered = _events(front, "handoff")
        assert delivered and delivered[-1]["attempt"] == 1
        assert _events(front, "handoff_reroute")[0]["attempt"] == 1

    def test_rung2_source_store_eviction_reroutes(self, toy):
        """The frontend's own availability reason ("no alive prefill
        replica"): the page vanishes from the source store between
        prefill completion and collection — typed, rerouted, parity."""
        front = _front(toy)
        p = self._prompt(23)
        rid = front.submit(p, max_new_tokens=6)
        # drive the PREFILL pool alone to completion (poll does not
        # pop — the frontend has not collected the leg yet)...
        for _ in range(200):
            front.prefill.pump(1)
            if front.prefill.poll(rid) is not None:
                break
        assert front.prefill.poll(rid).status == "done"
        # ...then evict its page from the source store before the
        # frontend's next pump opens the handoff window
        key = tuple(int(t) for t in p[:8])
        assert front.prefill.replicas[0].engine.kv.evict_prefix(
            key, force=True)
        front.run_until_drained(timeout_s=60.0)
        _assert_solo_parity(toy, front, [p], [rid])
        fails = _events(front, "handoff_failure")
        assert fails and fails[0]["failure"] == "integrity"
        assert "no alive prefill replica" in fails[0]["reason"]
        # the re-prefill re-registered the page: attempt 1 delivered
        assert _events(front, "handoff")[-1]["attempt"] == 1

    def test_rung3_decode_reprefills_when_no_survivor(self, toy):
        """Window kill of the ONLY prefill replica: at re-route time
        there is no prefill survivor, so the decode pool re-prefills
        the whole prompt — slower, never stranded, still parity."""
        kill = HandoffWindowKill(at_handoff=0)
        front = _front(toy, fault=kill)
        p = self._prompt(24)
        rid = front.submit(p, max_new_tokens=6)
        front.run_until_drained(timeout_s=60.0)
        assert kill.fired == 1
        _assert_solo_parity(toy, front, [p], [rid])
        # the rung's identity: rerouted once, and NO handoff ever
        # delivered — the whole stream came out of the decode pool
        c = front.summary()["counters"]
        assert c["handoff_reroutes"] == 1 and c.get("handoffs", 0) == 0
        assert _events(front, "handoff") == []

    def test_rung4_loud_eviction_at_attempt_budget(self, toy):
        """Sticky corruption on EVERY attempt: the ladder must stop at
        ``max_handoff_attempts`` with a typed `evicted` result naming
        the budget and the cause — a loud eviction, not a hang — and
        an unrelated healthy request must be untouched by it."""
        fault = _AlwaysCorrupt()
        front = _front(toy, fault=fault, max_handoff_attempts=2)
        p_bad = self._prompt(25)
        p_ok = self._prompt(26, n=3)       # < chunk: routed direct
        rid_bad = front.submit(p_bad, max_new_tokens=6)
        rid_ok = front.submit(p_ok, max_new_tokens=5)
        front.run_until_drained(timeout_s=60.0)
        res = front.poll(rid_bad)
        assert res is not None and res.status == "evicted"
        assert "handoff failed after 2 attempts" in res.reason
        assert "sha256" in res.reason
        # attempts 1..2 rerouted; the 3rd failure breaches the budget
        assert fault.fired == 3
        c = front.summary()["counters"]
        assert c["handoff_failures"] == 3 and c["handoff_reroutes"] == 2
        assert [t["attempt"] for t in _events(front, "handoff_reroute")] \
            == [1, 2]
        _assert_solo_parity(toy, front, [p_ok], [rid_ok])
