"""`apex1_tpu.testing.hlo_probe` — the overlap property as a pinned,
FALSIFIABLE check: the double-buffered ring / decomposed TP matmul loop
bodies must pass, and a deliberately serialized loop must FAIL (a probe
that cannot fail guards nothing). Parser + async-mode semantics are
pinned on synthetic TPU-style HLO (no TPU needed); dependence-mode
semantics on real CPU-mesh executables. The async mode runs for real
against v5e executables in tools/aot_check.py (check_all gate)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex1_tpu.core.mesh import make_mesh
from apex1_tpu.testing import hlo_probe as hp

B, H, S, D = 1, 2, 64, 16
CP = 4


# ---------------------------------------------------------------------------
# parser + async mode on synthetic HLO (schedule order is the TPU case)
# ---------------------------------------------------------------------------

def _synthetic(overlapped: bool) -> str:
    if overlapped:
        body = """  %p = (f32[8]{0}, f32[8]{0}, u32[], u32[]) collective-permute-start(f32[8]{0} %kc), source_target_pairs={{0,1},{1,0}}
  %d = f32[8]{0} dot(f32[8]{0} %kc, f32[8]{0} %q), lhs_contracting_dims={0}, rhs_contracting_dims={0}
  %pd = f32[8]{0} collective-permute-done((f32[8]{0}, f32[8]{0}, u32[], u32[]) %p)
  ROOT %t = (f32[8]{0}, f32[8]{0}) tuple(f32[8]{0} %pd, f32[8]{0} %d)"""
    else:
        body = """  %p = (f32[8]{0}, f32[8]{0}, u32[], u32[]) collective-permute-start(f32[8]{0} %kc), source_target_pairs={{0,1},{1,0}}
  %pd = f32[8]{0} collective-permute-done((f32[8]{0}, f32[8]{0}, u32[], u32[]) %p)
  %d = f32[8]{0} dot(f32[8]{0} %pd, f32[8]{0} %q), lhs_contracting_dims={0}, rhs_contracting_dims={0}
  ROOT %t = (f32[8]{0}, f32[8]{0}) tuple(f32[8]{0} %pd, f32[8]{0} %d)"""
    return f"""HloModule probe_test

%body (arg: (f32[8], f32[8])) -> (f32[8], f32[8]) {{
  %arg = (f32[8]{{0}}, f32[8]{{0}}) parameter(0)
  %kc = f32[8]{{0}} get-tuple-element((f32[8]{{0}}, f32[8]{{0}}) %arg), index=0
  %q = f32[8]{{0}} get-tuple-element((f32[8]{{0}}, f32[8]{{0}}) %arg), index=1
{body}
}}

%cond (arg: (f32[8], f32[8])) -> pred[] {{
  %arg = (f32[8]{{0}}, f32[8]{{0}}) parameter(0)
  ROOT %lt = pred[] constant(true)
}}

ENTRY %main (x: f32[8], y: f32[8]) -> (f32[8], f32[8]) {{
  %x = f32[8]{{0}} parameter(0)
  %y = f32[8]{{0}} parameter(1)
  %init = (f32[8]{{0}}, f32[8]{{0}}) tuple(f32[8]{{0}} %x, f32[8]{{0}} %y)
  ROOT %w = (f32[8]{{0}}, f32[8]{{0}}) while((f32[8]{{0}}, f32[8]{{0}}) %init), condition=%cond, body=%body
}}
"""


class TestSyntheticAsync:
    def test_overlapped_passes(self):
        rep = hp.check_collective_overlap(_synthetic(overlapped=True))
        assert rep.mode == "async" and rep.ok
        assert len(rep.bodies) == 1
        assert rep.bodies[0].n_permutes == 1

    def test_serialized_fails(self):
        """done consumed by the dot -> no pair brackets the compute."""
        rep = hp.check_collective_overlap(_synthetic(overlapped=False))
        assert rep.mode == "async" and not rep.ok

    def test_assert_raises_on_serialized(self):
        with pytest.raises(AssertionError, match="serialized"):
            hp.assert_collective_overlap(_synthetic(overlapped=False))

    def test_expect_mode_mismatch_raises(self):
        with pytest.raises(AssertionError, match="mode"):
            hp.assert_collective_overlap(_synthetic(overlapped=True),
                                         expect_mode="dependence")

    def test_no_loop_found_fails(self):
        rep = hp.check_collective_overlap("HloModule empty\n")
        assert not rep.ok and "nothing to probe" in rep.detail

    def test_parser_finds_while_body(self):
        comps = hp.parse_computations(_synthetic(True))
        assert "body" in hp._while_bodies(comps)
        ops = [i.opcode for i in comps["body"]]
        assert "collective-permute-start" in ops
        assert "dot" in ops


# ---------------------------------------------------------------------------
# dependence mode on real CPU-mesh executables
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def ring_args():
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
               for _ in range(3))
    return q, k, v


def _smap(mesh, fn):
    spec = P(None, None, "cp", None)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec,) * 3,
                         out_specs=spec)


class TestDependenceModeOnRealPrograms:
    def test_ring_fwd_passes(self, devices, ring_args):
        from apex1_tpu.parallel.ring_attention import ring_attention
        mesh = make_mesh(cp=CP, dp=1, devices=devices[:CP])
        f = _smap(mesh, lambda q, k, v: ring_attention(q, k, v, "cp",
                                                       causal=True))
        rep = hp.assert_collective_overlap(hp.optimized_hlo(f, *ring_args),
                                           expect_mode="dependence")
        assert len(rep.bodies) >= 1

    def test_ring_bwd_passes(self, devices, ring_args):
        """The custom-VJP backward ring: its own scan body must carry
        only carry-dependent permutes (fwd AND bwd bodies probed)."""
        from apex1_tpu.parallel.ring_attention import ring_attention
        mesh = make_mesh(cp=CP, dp=1, devices=devices[:CP])
        f = _smap(mesh, lambda q, k, v: ring_attention(q, k, v, "cp",
                                                       causal=True))

        def loss(q, k, v):
            return jnp.sum(f(q, k, v) ** 2)

        rep = hp.assert_collective_overlap(
            hp.optimized_hlo(jax.grad(loss, argnums=(0, 1, 2)),
                             *ring_args),
            expect_mode="dependence")
        assert len(rep.bodies) >= 2  # forward scan + backward scan

    def test_serialized_ring_fails(self, devices, ring_args):
        """The negative control the acceptance criterion demands: the
        retained rotate-then-attend loop MUST fail the probe."""
        from apex1_tpu.parallel.ring_attention import ring_attention_serial
        mesh = make_mesh(cp=CP, dp=1, devices=devices[:CP])
        f = _smap(mesh, lambda q, k, v: ring_attention_serial(
            q, k, v, "cp", causal=True))
        rep = hp.check_collective_overlap(hp.optimized_hlo(f, *ring_args))
        assert rep.bodies and not rep.ok

    def test_decomposed_tp_matmuls_pass(self, devices, rng):
        from apex1_tpu.transformer.tensor_parallel import mappings
        mesh = make_mesh(dp=2, tp=4)
        x = jnp.asarray(rng.normal(size=(32, 16)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(16, 24)), jnp.float32)

        def local(x, w):
            h = mappings.all_gather_matmul(x, w, "tp", 0)
            return mappings.matmul_reduce_scatter(
                h.astype(x.dtype), jnp.swapaxes(w, 0, 1), "tp", 0)

        f = jax.shard_map(local, mesh=mesh,
                          in_specs=(P("tp", None), P(None, "tp")),
                          out_specs=P("tp", None), check_vma=False)
        rep = hp.assert_collective_overlap(hp.optimized_hlo(f, x, w),
                                           expect_mode="dependence")
        assert len(rep.bodies) >= 1
