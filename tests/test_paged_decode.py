"""`ops.paged_decode` + paged serving mode — the ISSUE 18 acceptance
spine. The paged engine must be INVISIBLE in the tokens: bit-identical
streams vs the dense engine at every tested temperature (greedy and
two sampling regimes), across speculative-decode verify, radix prefix
hits (with page sharing actually engaged), and the int8 cache tier —
all with the usual two traced executables. Below the engine: the
in-kernel threefry/gumbel stream is pinned BITWISE against
``jax.random`` (the counter-seed resubmission contract rides on it),
the fused sampling kernel against its composite, the paged-attention
kernel against the shared `cache_attend` composite, and the
`PagedKVPool` page-refcount lifecycle (a shared page is freed only at
zero references)."""

from contextlib import contextmanager

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.core.policy import get_policy
from apex1_tpu.models.generate import gpt2_decoder
from apex1_tpu.models.gpt2 import GPT2, GPT2Config
from apex1_tpu.ops import _common
from apex1_tpu.ops.paged_decode import (PagedCache, _bits_to_gumbel,
                                        _uniform_bits, cache_attend,
                                        check_paged_geometry,
                                        fused_sample, gather_pages,
                                        paged_attend,
                                        paged_update_attend,
                                        sample_token, scatter_pages)
from apex1_tpu.serving import Engine, EngineConfig, PagedKVPool


# ---------------------------------------------------------------------------
# the in-kernel PRNG stream: bitwise against jax.random
# ---------------------------------------------------------------------------


@contextmanager
def _threefry_mode(partitionable):
    prev = bool(jax.config.jax_threefry_partitionable)
    jax.config.update("jax_threefry_partitionable", partitionable)
    try:
        yield
    finally:
        jax.config.update("jax_threefry_partitionable", prev)


class TestThreefryStream:
    @pytest.mark.parametrize("partitionable", [True, False])
    @pytest.mark.parametrize("n", [6, 7, 200, 257])
    def test_uniform_bits_bitwise_vs_jax_random(self, n, partitionable):
        """The pure-jnp threefry-2x32 reimplementation must reproduce
        jax's draw exactly under BOTH stream configs (the tier-1
        harness runs partitionable, the jax 0.4.x default is the
        original stream; odd counts exercise the original stream's
        zero-padded pair-partner path)."""
        key = jax.random.fold_in(jax.random.key(123), 7)
        k1, k2 = (jnp.uint32(x) for x in jax.random.key_data(key))
        col = jnp.arange(n, dtype=jnp.int32)
        mine = np.asarray(_uniform_bits(k1, k2, col, n,
                                        partitionable=partitionable))
        with _threefry_mode(partitionable):
            ref = np.asarray(jax.random.bits(key, (n,), jnp.uint32))
        np.testing.assert_array_equal(mine, ref)

    @pytest.mark.parametrize("partitionable", [True, False])
    def test_gumbel_bitwise_vs_jax_random(self, partitionable):
        key = jax.random.fold_in(jax.random.key(9), 3)
        k1, k2 = (jnp.uint32(x) for x in jax.random.key_data(key))
        with _threefry_mode(partitionable):
            g = np.asarray(_bits_to_gumbel(
                _uniform_bits(k1, k2, jnp.arange(129), 129)))
            ref = np.asarray(jax.random.gumbel(key, (129,), jnp.float32))
        np.testing.assert_array_equal(g, ref)

    def test_categorical_bitwise_vs_jax_random(self):
        """argmax(gumbel + logits) over the recomputed stream IS
        jax.random.categorical — the sampling identity the fused
        kernel's epilogue rests on."""
        key = jax.random.fold_in(jax.random.key(5), 11)
        lg = jax.random.normal(jax.random.key(1), (64,), jnp.float32)
        k1, k2 = (jnp.uint32(x) for x in jax.random.key_data(key))
        g = _bits_to_gumbel(_uniform_bits(k1, k2, jnp.arange(64), 64))
        assert int(jnp.argmax(g + lg)) == int(
            jax.random.categorical(key, lg))


# ---------------------------------------------------------------------------
# fused sampling epilogue
# ---------------------------------------------------------------------------


def _sample_rows_loop(logits, seeds, positions, **kw):
    """The dense engine's literal sampling ops, one row at a time."""
    out = []
    for r in range(logits.shape[0]):
        key = jax.random.fold_in(jax.random.key(int(seeds[r])),
                                 int(positions[r]))
        out.append(int(sample_token(logits[r][None], key, **kw)[0]))
    return np.asarray(out, np.int32)


class TestFusedSample:
    @pytest.mark.parametrize("temperature", [0.0, 0.7, 1.3])
    @pytest.mark.parametrize("top_k", [None, 5])
    def test_composite_matches_per_row_sampling(self, temperature,
                                                top_k):
        lg = jax.random.normal(jax.random.key(2), (5, 64), jnp.float32)
        seeds = np.asarray([3, 3, 7, 11, 7], np.int32)
        pos = np.asarray([0, 1, 9, 2, 9], np.int32)
        got = np.asarray(fused_sample(
            lg, seeds, pos, temperature=temperature, top_k=top_k,
            vocab_size=60))
        want = _sample_rows_loop(lg, seeds, pos,
                                 temperature=temperature, top_k=top_k,
                                 vocab_size=60)
        np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("temperature", [0.0, 0.7, 1.3])
    @pytest.mark.parametrize("top_k", [None, 5])
    def test_kernel_bitwise_vs_composite(self, temperature, top_k):
        """The Pallas epilogue (interpret mode off-TPU) emits the SAME
        token ids as the composite — integer outputs make this an
        exact, not approximate, contract."""
        lg = jax.random.normal(jax.random.key(4), (4, 200), jnp.float32)
        seeds = np.asarray([1, 2, 3, 2], np.int32)
        pos = np.asarray([5, 0, 1, 7], np.int32)
        kw = dict(temperature=temperature, top_k=top_k, vocab_size=180)
        with _common.force_impl("xla"):
            want = np.asarray(fused_sample(lg, seeds, pos, **kw))
        with _common.force_impl("pallas"):
            got = np.asarray(fused_sample(lg, seeds, pos, **kw))
        np.testing.assert_array_equal(got, want)

    def test_vocab_mask_never_samples_padded_tail(self):
        lg = jnp.full((3, 64), 5.0)
        lg = lg.at[:, 50:].set(100.0)          # huge logits in the pad
        got = np.asarray(fused_sample(lg, [1, 2, 3], [0, 0, 0],
                                      temperature=1.3, vocab_size=50))
        assert (got < 50).all()


# ---------------------------------------------------------------------------
# page plumbing + the paged attention kernel
# ---------------------------------------------------------------------------


def _random_pages(key, num_pages, Hkv, P, D, dtype=jnp.float32):
    k1, k2 = jax.random.split(key)
    if dtype == jnp.int8:
        mk = lambda k: jax.random.randint(  # noqa: E731
            k, (num_pages, Hkv, P, D), -127, 128, jnp.int8)
    else:
        mk = lambda k: jax.random.normal(  # noqa: E731
            k, (num_pages, Hkv, P, D), dtype)
    return mk(k1), mk(k2)


class TestPagePlumbing:
    def test_gather_scatter_roundtrip_page_spanning(self):
        """A write window that straddles a page boundary at an
        unaligned start must read back exactly."""
        kp, _ = _random_pages(jax.random.key(0), 9, 2, 4, 8)
        bt = jnp.asarray([[3, 1, 7], [2, 8, 5]], jnp.int32)
        vals = jax.random.normal(jax.random.key(1), (2, 2, 6, 8))
        start = jnp.asarray([3, 1], jnp.int32)   # spans pages 0->2 / 0->1
        kp2 = scatter_pages(kp, bt, vals, start)
        dense = gather_pages(kp2, bt, 12)
        for n in range(2):
            s = int(start[n])
            np.testing.assert_array_equal(
                np.asarray(dense[n, :, s:s + 6, :]),
                np.asarray(vals[n]))

    def test_composite_matches_dense_cache_attend_bitwise(self):
        """Gather→cache_attend through a permuted block table must be
        BITWISE the dense math on the same logical lanes."""
        kp, vp = _random_pages(jax.random.key(2), 7, 2, 4, 8)
        bt = jnp.asarray([[5, 2, 6], [1, 4, 3]], jnp.int32)
        q = jax.random.normal(jax.random.key(3), (2, 4, 1, 8))
        lengths = jnp.asarray([9, 4], jnp.int32)
        k_all = gather_pages(kp, bt, 12)
        v_all = gather_pages(vp, bt, 12)
        want = cache_attend(q, k_all, v_all, lengths)
        got = paged_attend(q, kp, vp, bt, lengths, total_len=12)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    @pytest.mark.parametrize("s", [1, 4])
    def test_kernel_matches_composite_f32(self, s):
        kp, vp = _random_pages(jax.random.key(4), 7, 2, 8, 16)
        bt = jnp.asarray([[5, 2, 6], [1, 4, 3]], jnp.int32)
        q = jax.random.normal(jax.random.key(5), (2, 4, s, 16))
        lengths = jnp.asarray([17, 6], jnp.int32)
        want = np.asarray(paged_attend(q, kp, vp, bt, lengths,
                                       total_len=24))
        with _common.force_impl("pallas"):
            got = np.asarray(paged_attend(q, kp, vp, bt, lengths,
                                          total_len=24))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_kernel_matches_composite_int8_fused_dequant(self):
        """int8 pages dequantize IN the kernel; tolerance is relative —
        ±127-scale values make online-softmax reassociation error scale
        with magnitude."""
        kp, vp = _random_pages(jax.random.key(6), 7, 2, 8, 16,
                               dtype=jnp.int8)
        bt = jnp.asarray([[5, 2, 6], [1, 4, 3]], jnp.int32)
        q = jax.random.normal(jax.random.key(7), (2, 4, 1, 16))
        lengths = jnp.asarray([20, 3], jnp.int32)
        want = np.asarray(paged_attend(q, kp, vp, bt, lengths,
                                       total_len=24))
        with _common.force_impl("pallas"):
            got = np.asarray(paged_attend(q, kp, vp, bt, lengths,
                                          total_len=24))
        np.testing.assert_allclose(got, want, rtol=1e-5,
                                   atol=1e-5 * np.abs(want).max())

    def test_paged_update_attend_matches_dense_update(self):
        """Scatter+attend == dynamic_update_slice+attend on the dense
        equivalent — the per-layer cache step the models thread."""
        kp, vp = _random_pages(jax.random.key(8), 7, 2, 4, 8)
        bt = jnp.asarray([[5, 2, 6], [1, 4, 3]], jnp.int32)
        q = jax.random.normal(jax.random.key(9), (2, 4, 1, 8))
        k_new = jax.random.normal(jax.random.key(10), (2, 2, 1, 8))
        v_new = jax.random.normal(jax.random.key(11), (2, 2, 1, 8))
        idx = jnp.asarray([7, 2], jnp.int32)
        pc = PagedCache(kp, vp, bt, 12)
        got, new_pc = paged_update_attend(q, k_new, v_new, pc, idx)
        k_all = gather_pages(kp, bt, 12)
        v_all = gather_pages(vp, bt, 12)
        k_up = jnp.stack([
            jax.lax.dynamic_update_slice(k_all[n], k_new[n],
                                         (0, int(idx[n]), 0))
            for n in range(2)])
        v_up = jnp.stack([
            jax.lax.dynamic_update_slice(v_all[n], v_new[n],
                                         (0, int(idx[n]), 0))
            for n in range(2)])
        want = cache_attend(q, k_up, v_up, idx)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        np.testing.assert_array_equal(
            np.asarray(gather_pages(new_pc.k_pages, bt, 12)),
            np.asarray(k_up))

    def test_geometry_rejects_unaligned_page(self):
        with pytest.raises(ValueError, match="sublane-aligned"):
            check_paged_geometry(12, 64, 2, 1)

    def test_geometry_rejects_over_budget_page(self):
        with pytest.raises(ValueError, match="over budget"):
            check_paged_geometry(1 << 20, 128, 2, 1)


# ---------------------------------------------------------------------------
# the paged KV pool: page-granular sharing + refcounts
# ---------------------------------------------------------------------------


def _toy_cache(n, s, dtype=jnp.float32):
    shape = (n, 2, s, 4)
    return {"layer0": {"k": jnp.zeros(shape, dtype),
                       "v": jnp.zeros(shape, dtype)}}


class TestPagedPool:
    def _pool(self, **kw):
        kw.setdefault("max_slots", 2)
        kw.setdefault("lane_len", 16)
        kw.setdefault("page_size", 4)
        return PagedKVPool(_toy_cache, **kw)

    def test_alloc_populates_row_free_resets_to_trash(self):
        pool = self._pool()
        assert pool.pages_per_lane == 4
        slot = pool.alloc()
        row = list(pool.block_tables[slot])
        assert 0 not in row and len(set(row)) == 4
        assert all(pool.page_refcount(p) == 1 for p in row)
        pool.free(slot)
        assert pool.block_tables[slot] == [0, 0, 0, 0]
        assert all(pool.page_refcount(p) == 0 for p in row)

    def test_sizing_invariant_alloc_never_fails(self):
        """Worst case — every slot full AND every registry entry
        pinning a retired donor's full lane — still leaves a free page
        for the next alloc (the no-page-faults decode-loop contract)."""
        pool = self._pool(max_pages=2)
        assert pool.num_pages == 1 + (2 + 2) * 4
        for i in range(2):
            s = pool.alloc()
            pool.register_prefix(s, (i,), 16)
            pool.free(s)
        a, b = pool.alloc(), pool.alloc()
        assert a is not None and b is not None
        assert pool.n_free_pages == 0     # exactly sized, never negative

    def test_shared_page_freed_only_at_zero_refs(self):
        """The central refcount property: a page shared by a registry
        entry and two block-table rows survives every partial release
        and is freed ONLY when the last reference drops."""
        pool = self._pool()
        free0 = pool.n_free_pages
        a = pool.alloc()
        key = (101, 102)
        page = pool.register_prefix(a, key, 9)   # floors to 2 pages
        assert page is not None and page.length == 8
        shared = list(page.page_ids)
        assert [pool.page_refcount(p) for p in shared] == [2, 2]
        pool.acquire_prefix(key, a)              # donor: bookkeeping no-op
        assert [pool.page_refcount(p) for p in shared] == [2, 2]

        b = pool.alloc()
        displaced = pool.block_tables[b][:2]
        pool.acquire_prefix(key, b)              # sharer: rewires by id
        assert pool.block_tables[b][:2] == shared
        assert [pool.page_refcount(p) for p in shared] == [3, 3]
        assert all(pool.page_refcount(p) == 0 for p in displaced)

        pool.free(a)                             # donor retires
        assert [pool.page_refcount(p) for p in shared] == [2, 2]
        pool.free(b)                             # last sharer retires
        assert [pool.page_refcount(p) for p in shared] == [1, 1]
        assert not set(shared) & set(pool._free_pages)

        assert pool.evict_prefix(key)            # registry entry drops
        assert all(pool.page_refcount(p) == 0 for p in shared)
        assert pool.n_free_pages == free0        # fully reclaimed

    def test_live_prefix_refuses_eviction(self):
        pool = self._pool()
        a = pool.alloc()
        key = (9,)
        pool.register_prefix(a, key, 8)
        pool.acquire_prefix(key, a)
        assert not pool.evict_prefix(key)        # refcount > 0
        with pytest.raises(RuntimeError, match="live"):
            pool.evict_prefix(key, force=True)
        pool.free(a)                             # releases via slot map
        assert pool.evict_prefix(key)

    def test_register_floors_to_page_multiple(self):
        pool = self._pool()
        a = pool.alloc()
        assert pool.register_prefix(a, (1,), 3) is None
        page = pool.register_prefix(a, (2,), 7)
        assert page.length == 4 and len(page.page_ids) == 1

    def test_lru_eviction_respects_refcounts(self):
        pool = self._pool(max_pages=1)
        a = pool.alloc()
        pool.register_prefix(a, (1,), 8)
        pool.acquire_prefix((1,), a)
        b = pool.alloc()
        pool.register_prefix(b, (2, 2), 8)     # over cap, but "one" live
        assert pool.has_prefix((1,)) and pool.has_prefix((2, 2))
        pool.free(a)                             # "one" refcount -> 0
        pool.register_prefix(b, (3, 3, 3), 16)  # triggers LRU sweep
        assert not pool.has_prefix((1,))


# ---------------------------------------------------------------------------
# the paged engine: token parity with the dense engine
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny():
    cfg = GPT2Config.tiny(policy=get_policy("O0"), max_seq_len=64)
    model = GPT2(cfg)
    rng = np.random.default_rng(11)
    base = rng.integers(1, cfg.vocab_size, size=(12,)).astype(np.int32)
    prompt = jnp.asarray(base[None])
    params = model.init(jax.random.key(0), prompt)["params"]
    apply_fn, make_cache = gpt2_decoder(model)
    return cfg, params, apply_fn, make_cache, base


def _engine(tiny, **kw):
    cfg, params, apply_fn, make_cache, _ = tiny
    ekw = dict(max_slots=3, max_len=48, prefill_chunk=4,
               vocab_size=cfg.vocab_size)
    ekw.update(kw)
    return Engine(apply_fn, make_cache, params, EngineConfig(**ekw))


def _run_workload(eng, base, *, news=(6, 5, 7, 4), seeds=(5, 9, 2, 7)):
    """More requests than slots, mixed prompt lengths crossing chunk
    boundaries, staggered joins — the dense suite's acceptance shape."""
    lens = [3, 7, 5, 9]
    ids = [eng.submit(base[:lens[i]], max_new_tokens=news[i],
                      seed=seeds[i]) for i in range(3)]
    eng.step()
    ids.append(eng.submit(base[:lens[3]], max_new_tokens=news[3],
                          seed=seeds[3]))
    eng.run(max_steps=200)
    return [list(eng.results[r].tokens) for r in ids]


class TestPagedEngineParity:
    @pytest.mark.parametrize("temperature", [0.0, 0.7, 1.3])
    def test_tokens_bitwise_vs_dense_engine(self, tiny, temperature):
        """The tentpole acceptance: paged == dense token streams,
        exactly (counter-keyed sampling included), with the usual two
        executables and no retraces."""
        base = tiny[4]
        dense = _run_workload(_engine(tiny, temperature=temperature),
                              base)
        eng = _engine(tiny, temperature=temperature, paged=True)
        paged = _run_workload(eng, base)
        assert paged == dense
        assert eng.trace_counts == {"prefill": 1, "decode": 1}

    def test_spec_decode_verify_bitwise(self, tiny):
        """Speculative decode's verify executable (counter-keyed accept
        chain) through the paged path: same tokens, same executables."""
        base = tiny[4]
        dense = _run_workload(
            _engine(tiny, temperature=0.7, num_draft=2), base)
        eng = _engine(tiny, temperature=0.7, num_draft=2, paged=True)
        paged = _run_workload(eng, base)
        assert paged == dense
        assert eng.trace_counts == {"prefill": 1, "verify": 1}

    def test_int8_cache_tier_bitwise(self, tiny):
        """The int8 KV tier quantizes at scatter exactly like the dense
        tier's update — the paged path must not perturb a single
        token."""
        base = tiny[4]
        dense = _run_workload(
            _engine(tiny, temperature=0.7, cache_dtype=jnp.int8), base)
        eng = _engine(tiny, temperature=0.7, cache_dtype=jnp.int8,
                      paged=True)
        paged = _run_workload(eng, base)
        assert paged == dense

    def test_radix_prefix_hits_bitwise_with_page_sharing(self, tiny):
        """Three requests sharing a 10-token prefix: the paged pool
        must register page-aligned shared pages, serve hits by page id
        (no copy-on-admit), and still match the dense engine token for
        token."""
        base = tiny[4]

        def run(paged):
            eng = _engine(tiny, max_slots=2, temperature=0.7,
                          paged=paged)
            rids = [eng.submit(
                np.concatenate([base[:10],
                                np.asarray([3 + i], np.int32)]),
                max_new_tokens=6, seed=50 + i) for i in range(3)]
            eng.run(max_steps=300)
            return [list(eng.results[r].tokens) for r in rids], eng

        dense, _ = run(False)
        paged, eng = run(True)
        assert paged == dense
        stats = eng.kv.prefix_stats()
        assert any(v["hits"] >= 2 and v["pages"]
                   for v in stats.values()), stats

    def test_explicit_prefix_submission_bitwise(self, tiny):
        base = tiny[4]
        pre = tuple(int(t) for t in base[:9])

        def run(paged):
            eng = _engine(tiny, max_slots=2, temperature=1.3,
                          paged=paged)
            rids = [eng.submit(np.asarray([5 + i, 9], np.int32),
                               max_new_tokens=5, prefix=pre,
                               seed=7 + i) for i in range(3)]
            eng.run(max_steps=300)
            return [list(eng.results[r].tokens) for r in rids]

        assert run(True) == run(False)

    def test_pallas_interpret_engine_bitwise(self, tiny):
        """The kernel path end-to-end: an engine BUILT under
        force_impl('pallas') routes decode through the paged-attention
        kernel + fused sampling epilogue (interpret mode on CPU) and
        still emits the dense engine's exact tokens."""
        base = tiny[4]
        dense_eng = _engine(tiny, max_slots=2, temperature=0.7)
        rd = [dense_eng.submit(base[:7 + i], max_new_tokens=4,
                               seed=3 + i) for i in range(2)]
        dense_eng.run(max_steps=100)
        dense = [list(dense_eng.results[r].tokens) for r in rd]
        with _common.force_impl("pallas"):
            eng = _engine(tiny, max_slots=2, temperature=0.7,
                          paged=True)
            rp = [eng.submit(base[:7 + i], max_new_tokens=4,
                             seed=3 + i) for i in range(2)]
            eng.run(max_steps=100)
        paged = [list(eng.results[r].tokens) for r in rp]
        assert paged == dense

    def test_page_size_validation(self, tiny):
        with pytest.raises(ValueError, match="page_size"):
            EngineConfig(max_slots=2, max_len=32, vocab_size=256,
                         paged=True, page_size=0)
