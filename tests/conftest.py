"""Test harness: run everything on an 8-device virtual CPU mesh.

Reference analogue: ``apex/transformer/testing/distributed_test_base.py``
spawns N NCCL processes; on JAX a single process with
``--xla_force_host_platform_device_count=8`` provides 8 CPU devices for full
mesh/pjit/shard_map/collective coverage (SURVEY.md §4.2.4). The mechanism
(incl. the jax.config override the container's sitecustomize makes
necessary) lives in `apex1_tpu.testing.force_virtual_cpu_devices`.
"""

from apex1_tpu.testing import (enable_persistent_compilation_cache,
                               force_virtual_cpu_devices)

force_virtual_cpu_devices(8)
enable_persistent_compilation_cache()

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {devs}"
    return devs


@pytest.fixture()
def rng():
    return np.random.default_rng(1234)
