"""Test harness: run everything on an 8-device virtual CPU mesh.

Reference analogue: ``apex/transformer/testing/distributed_test_base.py``
spawns N NCCL processes; on JAX a single process with
``--xla_force_host_platform_device_count=8`` provides 8 CPU devices for full
mesh/pjit/shard_map/collective coverage (SURVEY.md §4.2.4).

NOTE: the container's sitecustomize registers the 'axon' TPU platform and
pins ``jax_platforms=axon,cpu`` via jax.config, so env vars alone don't
switch backends — we must override through jax.config before any backend
client is instantiated.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {devs}"
    return devs


@pytest.fixture()
def rng():
    return np.random.default_rng(1234)
