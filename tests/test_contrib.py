"""Contrib long-tail tests — reference analogues:
``apex/contrib/test/{focal_loss,index_mul_2d,transducer,group_norm}`` +
``tests/L0/run_fp16util`` + halo-exchange parity (spatial parallelism,
``apex/contrib/test/bottleneck``)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from apex1_tpu import fp16_utils
from apex1_tpu.contrib import (GroupNorm, TransducerJoint, TransducerLoss,
                               focal_loss, group_norm, index_mul_2d,
                               transducer_joint, transducer_loss)
from apex1_tpu.core.mesh import make_mesh
from apex1_tpu.parallel.halo import (exchange_overlap, halo_exchange,
                                     spatial_conv2d)


class TestFocalLoss:
    def test_matches_numpy_gold(self, rng):
        logits = jnp.asarray(rng.normal(size=(16, 5)), jnp.float32)
        targets = jnp.asarray(rng.integers(0, 5, (16,)), jnp.int32)
        got = focal_loss(logits, targets, alpha=0.25, gamma=2.0)
        x = np.asarray(logits)
        t = np.eye(5)[np.asarray(targets)]
        p = 1 / (1 + np.exp(-x))
        loss = (t * 0.25 * (1 - p) ** 2 * -np.log(p)
                + (1 - t) * 0.75 * p ** 2 * -np.log(1 - p))
        np.testing.assert_allclose(float(got), loss.sum(), rtol=1e-5)

    def test_grads_and_smoothing(self, rng):
        logits = jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)
        targets = jnp.asarray(rng.integers(0, 4, (8,)), jnp.int32)
        g = jax.grad(lambda l: focal_loss(l, targets,
                                          label_smoothing=0.1))(logits)
        assert np.all(np.isfinite(g))


class TestIndexMul2d:
    def test_forward_and_grads(self, rng):
        in1 = jnp.asarray(rng.normal(size=(10, 8)), jnp.float32)
        in2 = jnp.asarray(rng.normal(size=(6, 8)), jnp.float32)
        idx = jnp.asarray(rng.integers(0, 10, (6,)), jnp.int32)
        out = index_mul_2d(in1, in2, idx)
        np.testing.assert_allclose(out, np.asarray(in1)[np.asarray(idx)]
                                   * np.asarray(in2), rtol=1e-6)
        # d_in1 is a scatter-add over repeated indices
        d1 = jax.grad(lambda a: jnp.sum(index_mul_2d(a, in2, idx)))(in1)
        want = np.zeros_like(np.asarray(in1))
        np.add.at(want, np.asarray(idx), np.asarray(in2))
        np.testing.assert_allclose(d1, want, rtol=1e-6)


class TestGroupNorm:
    def test_matches_numpy_gold(self, rng):
        x = jnp.asarray(rng.normal(size=(2, 4, 4, 8)), jnp.float32)
        got = group_norm(x, num_groups=2)
        xn = np.asarray(x).reshape(2, 16, 2, 4)
        mean = xn.mean(axis=(1, 3), keepdims=True)
        var = xn.var(axis=(1, 3), keepdims=True)
        want = ((xn - mean) / np.sqrt(var + 1e-5)).reshape(x.shape)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_module_affine_silu(self, rng):
        x = jnp.asarray(rng.normal(size=(2, 4, 4, 8)), jnp.float32)
        m = GroupNorm(num_groups=4, num_channels=8, act="silu")
        p = m.init(jax.random.key(0), x)["params"]
        out = m.apply({"params": p}, x)
        base = group_norm(x, 4, p["weight"], p["bias"])
        np.testing.assert_allclose(
            out, np.asarray(base) / (1 + np.exp(-np.asarray(base))),
            rtol=1e-5, atol=1e-6)


def _brute_force_rnnt(lp, targets, blank):
    """O(T·U) reference DP in numpy (log domain)."""
    T, U, V = lp.shape
    alpha = np.full((T, U), -np.inf)
    alpha[0, 0] = 0.0
    for t in range(T):
        for u in range(U):
            terms = []
            if t == 0 and u == 0:
                continue
            if t > 0:
                terms.append(alpha[t - 1, u] + lp[t - 1, u, blank])
            if u > 0:
                terms.append(alpha[t, u - 1] + lp[t, u - 1,
                                                  targets[u - 1]])
            alpha[t, u] = np.logaddexp.reduce(terms)
    return -(alpha[T - 1, U - 1] + lp[T - 1, U - 1, blank])


class TestTransducer:
    def test_joint_shapes_and_relu(self, rng):
        f = jnp.asarray(rng.normal(size=(2, 5, 8)), jnp.float32)
        g = jnp.asarray(rng.normal(size=(2, 3, 8)), jnp.float32)
        out = transducer_joint(f, g, relu=True)
        assert out.shape == (2, 5, 3, 8)
        assert float(jnp.min(out)) >= 0
        joint = TransducerJoint(relu=True)
        np.testing.assert_allclose(joint(f, g), out)

    def test_loss_matches_brute_force(self, rng):
        B, T, U, V = 3, 6, 4, 7
        logits = jnp.asarray(rng.normal(size=(B, T, U, V)), jnp.float32)
        targets = jnp.asarray(rng.integers(1, V, (B, U - 1)), jnp.int32)
        f_len = jnp.asarray([T, T, T], jnp.int32)
        y_len = jnp.asarray([U - 1] * B, jnp.int32)
        got = transducer_loss(logits, targets, f_len, y_len,
                              blank_idx=0, reduction="none")
        lp = jax.nn.log_softmax(logits, axis=-1)
        for b in range(B):
            want = _brute_force_rnnt(np.asarray(lp[b]),
                                     np.asarray(targets[b]), 0)
            np.testing.assert_allclose(float(got[b]), want, rtol=1e-4)

    def test_varlen_matches_truncated(self, rng):
        B, T, U, V = 1, 8, 5, 6
        logits = jnp.asarray(rng.normal(size=(B, T, U, V)), jnp.float32)
        targets = jnp.asarray(rng.integers(1, V, (B, U - 1)), jnp.int32)
        t_v, u_v = 6, 3
        got = transducer_loss(logits, targets,
                              jnp.asarray([t_v]), jnp.asarray([u_v]),
                              reduction="none")
        trunc = transducer_loss(
            logits[:, :t_v, :u_v + 1], targets[:, :u_v],
            jnp.asarray([t_v]), jnp.asarray([u_v]), reduction="none")
        np.testing.assert_allclose(float(got[0]), float(trunc[0]),
                                   rtol=1e-4)

    def test_loss_grads_finite(self, rng):
        B, T, U, V = 2, 5, 4, 6
        logits = jnp.asarray(rng.normal(size=(B, T, U, V)), jnp.float32)
        targets = jnp.asarray(rng.integers(1, V, (B, U - 1)), jnp.int32)
        crit = TransducerLoss()
        g = jax.grad(lambda l: crit(l, targets, jnp.asarray([T] * B),
                                    jnp.asarray([U - 1] * B)))(logits)
        assert np.all(np.isfinite(g))


class TestFp16Utils:
    def test_network_to_half_and_back(self, rng):
        params = {"dense": {"kernel": jnp.ones((4, 4))},
                  "ln_scale": jnp.ones((4,)),
                  "step": jnp.int32(3)}
        half = fp16_utils.network_to_half(params)
        assert half["dense"]["kernel"].dtype == jnp.float16
        assert half["step"].dtype == jnp.int32
        keep = fp16_utils.BN_convert_float(params)
        assert keep["ln_scale"].dtype == jnp.float32
        model, master = fp16_utils.prep_param_lists(params)
        assert master["dense"]["kernel"].dtype == jnp.float32

    def test_fp16_optimizer_trains_and_skips(self, rng):
        opt = fp16_utils.FP16_Optimizer(optax.sgd(0.1),
                                        static_loss_scale=128.0)
        params = {"w": jnp.ones((4,), jnp.float16)}
        state = opt.init(params)

        def loss_fn(p, x):
            return jnp.sum(jnp.square(p["w"].astype(jnp.float32))) * x

        loss, model, state = opt.step(loss_fn, state, jnp.float32(1.0))
        assert float(jnp.sum(state["master"]["w"])) < 4.0
        w_before = state["master"]["w"]
        loss, model, state = opt.step(loss_fn, state, jnp.float32(1e38))
        np.testing.assert_array_equal(state["master"]["w"], w_before)

    def test_dynamic_loss_scaler_facade(self):
        s = fp16_utils.DynamicLossScaler(init_scale=16.0)
        assert s.loss_scale == 16.0
        s.update_scale(overflow=True)
        assert s.loss_scale == 8.0
        s2 = fp16_utils.LossScaler(scale=4.0)
        s2.update_scale(overflow=True)
        assert s2.loss_scale == 4.0


class TestHaloExchange:
    def test_matches_global_conv(self, rng, devices):
        mesh = make_mesh(pp=1, dp=1, cp=4, devices=devices[:4])
        x = jnp.asarray(rng.normal(size=(2, 16, 8, 3)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(3, 3, 3, 5)), jnp.float32)

        fn = jax.jit(jax.shard_map(
            lambda x: spatial_conv2d(x, k, "cp", dim=1),
            mesh=mesh, in_specs=P(None, "cp"), out_specs=P(None, "cp")))
        got = fn(x)
        want = jax.lax.conv_general_dilated(
            x, k, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO",
                                                     "NHWC"))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_halo_shapes_periodic(self, rng, devices):
        mesh = make_mesh(cp=4, dp=1, devices=devices[:4])
        x = jnp.asarray(rng.normal(size=(1, 8, 4, 2)), jnp.float32)
        fn = jax.jit(jax.shard_map(
            lambda x: halo_exchange(x, "cp", halo=1, dim=1, periodic=True),
            mesh=mesh, in_specs=P(None, "cp"), out_specs=P(None, "cp")))
        out = fn(x)
        assert out.shape == (1, 8 + 2 * 4, 4, 2)  # +2 halo rows per shard

    @pytest.mark.parametrize("periodic", [False, True])
    def test_exchange_overlap_matches_exchange_plus_interior(
            self, rng, devices, periodic):
        """The overlap entry changes scheduling, not values: extended
        shard == halo_exchange(x), interior == interior_fn(x)."""
        mesh = make_mesh(cp=4, dp=1, devices=devices[:4])
        x = jnp.asarray(rng.normal(size=(2, 16, 4, 3)), jnp.float32)

        def interior_fn(x):
            return jnp.tanh(x) * 2.0

        def overlapped(x):
            return exchange_overlap(x, interior_fn, "cp", halo=2, dim=1,
                                    periodic=periodic)

        def composite(x):
            return (halo_exchange(x, "cp", halo=2, dim=1,
                                  periodic=periodic), interior_fn(x))

        specs = (P(None, "cp"), P(None, "cp"))
        got = jax.jit(jax.shard_map(overlapped, mesh=mesh,
                                    in_specs=P(None, "cp"),
                                    out_specs=specs))(x)
        want = jax.jit(jax.shard_map(composite, mesh=mesh,
                                     in_specs=P(None, "cp"),
                                     out_specs=specs))(x)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w))

    def test_exchange_overlap_zero_halo(self, rng, devices):
        mesh = make_mesh(cp=4, dp=1, devices=devices[:4])
        x = jnp.asarray(rng.normal(size=(1, 8, 2, 1)), jnp.float32)
        ext, interior = jax.jit(jax.shard_map(
            lambda x: exchange_overlap(x, lambda v: v + 1.0, "cp",
                                       halo=0, dim=1),
            mesh=mesh, in_specs=P(None, "cp"),
            out_specs=(P(None, "cp"), P(None, "cp"))))(x)
        np.testing.assert_array_equal(np.asarray(ext), np.asarray(x))
        np.testing.assert_allclose(np.asarray(interior),
                                   np.asarray(x) + 1.0)


def test_network_to_half_dense_bias_goes_half():
    """BN_convert_float must NOT keep plain Dense biases fp32 (only
    norm/BN params) — a fp32 bias would promote the whole network."""
    params = {"dense": {"kernel": jnp.ones((2, 2)), "bias": jnp.ones((2,))},
              "logit_scale": jnp.ones(()),
              "bn1": {"scale": jnp.ones((2,)), "bias": jnp.ones((2,))},
              "attn_norm": jnp.ones((2,)),
              "ln2_scale": jnp.ones((2,))}
    out = fp16_utils.BN_convert_float(params)
    assert out["dense"]["bias"].dtype == jnp.float16
    assert out["logit_scale"].dtype == jnp.float16
    assert out["bn1"]["scale"].dtype == jnp.float32
    assert out["attn_norm"].dtype == jnp.float32
    assert out["ln2_scale"].dtype == jnp.float32


def test_spatial_conv2d_w_sharded(rng, devices):
    mesh = make_mesh(cp=4, dp=1, devices=devices[:4])
    x = jnp.asarray(rng.normal(size=(2, 8, 16, 3)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(3, 3, 3, 5)), jnp.float32)
    fn = jax.jit(jax.shard_map(
        lambda x: spatial_conv2d(x, k, "cp", dim=2),
        mesh=mesh, in_specs=P(None, None, "cp"),
        out_specs=P(None, None, "cp")))
    got = fn(x)
    want = jax.lax.conv_general_dilated(
        x, k, (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


class TestOpenfold:
    """``apex/contrib/openfold_triton`` capability — pair-bias attention
    core vs a hand softmax, gating, swiglu."""

    def test_attention_core_matches_manual(self, rng):
        from apex1_tpu.contrib import openfold
        B, H, S, D = 2, 3, 16, 8
        q = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
        bias2 = jnp.asarray(rng.normal(size=(B, 1, S, S)), jnp.float32)
        gate = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)

        got = openfold.attention_core(q, k, v, bias2=bias2, gate=gate)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D) + bias2
        p = jax.nn.softmax(s, axis=-1)
        want = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        want = want * jax.nn.sigmoid(gate)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_masked_rows_and_swiglu(self, rng):
        from apex1_tpu.contrib import openfold
        B, H, S, D = 1, 2, 8, 4
        q = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
        # mask out the second half of keys: result must equal attention
        # computed over the first half only
        mask = jnp.ones((B, 1, S, S), bool).at[..., S // 2:].set(False)
        out = openfold.attention_core(q, q, q, mask=mask)
        half = openfold.attention_core(q, q[..., :S // 2, :],
                                       q[..., :S // 2, :])
        np.testing.assert_allclose(np.asarray(out), np.asarray(half),
                                   rtol=2e-5, atol=2e-5)

        x = jnp.asarray(rng.normal(size=(6, 8)), jnp.float32)
        wg = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
        wu = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
        wd = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)
        got = openfold.swiglu(x, wg, wu, wd)
        want = (jax.nn.silu(x @ wg) * (x @ wu)) @ wd
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6)
