"""Tensor-parallel tests — ≙ ``tests/L0/run_transformer/{test_mapping,
test_layers,test_cross_entropy,test_parallel_state}.py``: collectives'
fwd/bwd duals, sharded layers vs dense gold, vocab-parallel CE vs full CE,
all on a tp=4 shard_map over the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex1_tpu.core.mesh import make_mesh
from apex1_tpu.transformer import parallel_state
from apex1_tpu.transformer import tensor_parallel as tp


@pytest.fixture()
def mesh(devices):
    return make_mesh(dp=2, tp=4)


def tp_shard_map(mesh, fn, in_specs, out_specs):
    # check_vma=False: replication of custom_vjp collective outputs can't be
    # statically inferred (same flag Megatron-JAX ports use)
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)


class TestParallelState:
    def test_initialize_and_getters(self, devices):
        parallel_state.destroy_model_parallel()
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=2, pipeline_model_parallel_size=2)
        assert parallel_state.get_tensor_model_parallel_world_size() == 2
        assert parallel_state.get_pipeline_model_parallel_world_size() == 2
        assert parallel_state.get_data_parallel_world_size() == 2
        assert parallel_state.get_world_size() == 8
        assert parallel_state.model_parallel_is_initialized()
        with pytest.raises(RuntimeError):
            parallel_state.initialize_model_parallel(1, 1)
        parallel_state.destroy_model_parallel()
        assert not parallel_state.model_parallel_is_initialized()

    def test_virtual_pp(self, devices):
        parallel_state.destroy_model_parallel()
        parallel_state.initialize_model_parallel(
            1, 2, virtual_pipeline_model_parallel_size=2)
        assert parallel_state.get_virtual_pipeline_model_parallel_world_size() == 2
        parallel_state.set_virtual_pipeline_model_parallel_rank(1)
        assert parallel_state.get_virtual_pipeline_model_parallel_rank() == 1
        parallel_state.destroy_model_parallel()


class TestMappings:
    """fwd/bwd duals of every region mapping."""

    def test_copy_and_reduce(self, mesh, rng):
        x = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)

        def f(x):
            # copy: identity fwd; grad of sum → psum of ones = tp_size
            y = tp.copy_to_tensor_model_parallel_region(x)
            return y

        y = tp_shard_map(mesh, f, P(), P())(x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x))

        def g(x):
            return jax.grad(
                lambda x: jnp.sum(
                    tp.copy_to_tensor_model_parallel_region(x)))(x)

        gx = tp_shard_map(mesh, g, P(), P())(x)
        np.testing.assert_allclose(np.asarray(gx), 4.0)  # psum over tp=4

        def h(x):
            # reduce: psum fwd
            return tp.reduce_from_tensor_model_parallel_region(x)

        y = tp_shard_map(mesh, h, P(), P())(x)
        np.testing.assert_allclose(np.asarray(y), 4.0 * np.asarray(x),
                                   rtol=1e-6)

    def test_scatter_gather_roundtrip(self, mesh, rng):
        x = jnp.asarray(rng.normal(size=(8, 32)), jnp.float32)

        def f(x):
            s = tp.scatter_to_tensor_model_parallel_region(x)
            assert s.shape == (8, 8)
            return tp.gather_from_tensor_model_parallel_region(s)

        y = tp_shard_map(mesh, f, P(), P())(x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x))

    def test_sequence_parallel_trio(self, mesh, rng):
        x = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)

        def f(x):
            s = tp.scatter_to_sequence_parallel_region(x)  # (4, 8) local
            assert s.shape == (4, 8)
            g = tp.gather_from_sequence_parallel_region(s)
            return g

        y = tp_shard_map(mesh, f, P(), P())(x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x))

        def h(x):
            # reduce_scatter fwd: each rank ends with the psum of its slice
            r = tp.reduce_scatter_to_sequence_parallel_region(x)
            return tp.gather_from_sequence_parallel_region(r)

        y = tp_shard_map(mesh, h, P(), P())(x)
        np.testing.assert_allclose(np.asarray(y), 4.0 * np.asarray(x),
                                   rtol=1e-6)

    def test_gather_bwd_is_reduce_scatter(self, mesh, rng):
        # grad of sum(gather(x_shard)) wrt x_shard = ones (each rank's slice
        # receives the full-grad slice reduce-scattered: tp copies of 1 → 4)
        x = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)

        def g(x):
            local = tp.scatter_to_sequence_parallel_region(x)
            return jax.grad(lambda l: jnp.sum(
                tp.gather_from_sequence_parallel_region(l)) / 4.0)(local)

        gx = tp_shard_map(mesh, g, P(), P("tp"))(x)
        np.testing.assert_allclose(np.asarray(gx), 1.0, rtol=1e-6)


class TestLayersShardMap:
    def test_column_then_row_equals_dense(self, mesh, rng):
        """ParallelMLP pattern: Column → gelu → Row == dense gold."""
        B, D, H = 8, 32, 64
        x = jnp.asarray(rng.normal(size=(B, D)), jnp.float32)
        w1 = jnp.asarray(rng.normal(size=(D, H)) * 0.1, jnp.float32)
        b1 = jnp.asarray(rng.normal(size=(H,)) * 0.1, jnp.float32)
        w2 = jnp.asarray(rng.normal(size=(H, D)) * 0.1, jnp.float32)
        b2 = jnp.asarray(rng.normal(size=(D,)) * 0.1, jnp.float32)

        def parallel_mlp(x, w1, b1, w2, b2):
            h = tp.column_parallel_linear(x, w1, b1)   # (B, H/4) local
            h = jax.nn.gelu(h)
            return tp.row_parallel_linear(h, w2, bias=b2)

        y = tp_shard_map(
            mesh, parallel_mlp,
            (P(), P(None, "tp"), P("tp"), P("tp", None), P()),
            P())(x, w1, b1, w2, b2)
        gold = jax.nn.gelu(x @ w1 + b1) @ w2 + b2
        np.testing.assert_allclose(np.asarray(y), np.asarray(gold),
                                   rtol=1e-4, atol=1e-5)

    def test_grads_match_dense(self, mesh, rng):
        B, D, H = 4, 16, 32
        x = jnp.asarray(rng.normal(size=(B, D)), jnp.float32)
        w1 = jnp.asarray(rng.normal(size=(D, H)) * 0.1, jnp.float32)
        w2 = jnp.asarray(rng.normal(size=(H, D)) * 0.1, jnp.float32)

        def loss_parallel(x, w1, w2):
            h = tp.column_parallel_linear(x, w1)
            return jnp.sum(tp.row_parallel_linear(h, w2) ** 2)

        def grads(x, w1, w2):
            return jax.grad(loss_parallel, argnums=(1, 2))(x, w1, w2)

        gw1, gw2 = tp_shard_map(
            mesh, grads, (P(), P(None, "tp"), P("tp", None)),
            (P(None, "tp"), P("tp", None)))(x, w1, w2)
        gold_g = jax.grad(
            lambda w1, w2: jnp.sum((x @ w1 @ w2) ** 2), argnums=(0, 1))(
                w1, w2)
        np.testing.assert_allclose(np.asarray(gw1), np.asarray(gold_g[0]),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gw2), np.asarray(gold_g[1]),
                                   rtol=1e-4, atol=1e-5)

    def test_vocab_parallel_embedding(self, mesh, rng):
        V, D = 64, 16
        table = jnp.asarray(rng.normal(size=(V, D)), jnp.float32)
        tokens = jnp.asarray(rng.integers(0, V, size=(4, 8)), jnp.int32)

        def f(tokens, table):
            return tp.vocab_parallel_embedding(tokens, table)

        y = tp_shard_map(mesh, f, (P(), P("tp", None)), P())(tokens, table)
        np.testing.assert_allclose(np.asarray(y),
                                   np.asarray(table[tokens]), rtol=1e-6)

    def test_sequence_parallel_column_row(self, mesh, rng):
        S, D, H = 16, 16, 32
        x = jnp.asarray(rng.normal(size=(S, D)), jnp.float32)
        w1 = jnp.asarray(rng.normal(size=(D, H)) * 0.1, jnp.float32)
        w2 = jnp.asarray(rng.normal(size=(H, D)) * 0.1, jnp.float32)

        def f(x_sp, w1, w2):
            # x_sp: (S/4, D) sequence-sharded, as after LN/dropout under SP
            h = tp.column_parallel_linear(x_sp, w1,
                                          sequence_parallel_enabled=True)
            return tp.row_parallel_linear(h, w2,
                                          sequence_parallel_enabled=True)

        y = tp_shard_map(mesh, f, (P("tp"), P(None, "tp"), P("tp", None)),
                         P("tp"))(x, w1, w2)
        gold = x @ w1 @ w2
        np.testing.assert_allclose(np.asarray(y), np.asarray(gold),
                                   rtol=1e-4, atol=1e-5)


class TestLayersGSPMD:
    def test_pjit_column_row_mlp(self, mesh, rng):
        """GSPMD mode: full-size params with partitioning metadata under
        jit-with-mesh == dense gold."""
        import flax.linen as nn

        class MLP(nn.Module):
            @nn.compact
            def __call__(self, x):
                h = tp.ColumnParallelLinear(64, name="fc1")(x)
                h = nn.gelu(h)
                return tp.RowParallelLinear(16, name="fc2")(h)

        m = MLP()
        x = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
        params = m.init(jax.random.PRNGKey(0), x)
        specs = nn.get_partition_spec(params)
        assert specs["params"]["fc1"]["kernel"] == P(None, "tp")
        assert specs["params"]["fc2"]["kernel"] == P("tp", None)
        params_plain = jax.tree.map(
            lambda x: x.unbox() if hasattr(x, "unbox") else x, params,
            is_leaf=lambda x: hasattr(x, "unbox"))
        gold = m.apply(params_plain, x)
        with jax.set_mesh(mesh):
            y = jax.jit(m.apply)(params_plain, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(gold),
                                   rtol=1e-5, atol=1e-6)


class TestVocabParallelCE:
    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_vs_full_ce(self, mesh, rng, smoothing):
        N, V = 16, 64
        logits = jnp.asarray(rng.normal(size=(N, V)) * 3, jnp.float32)
        targets = jnp.asarray(rng.integers(0, V, size=(N,)), jnp.int32)

        def f(lg, t):
            return tp.vocab_parallel_cross_entropy(lg, t, smoothing)

        loss = tp_shard_map(mesh, f, (P(None, "tp"), P()), P())(
            logits, targets)
        from apex1_tpu.ops import softmax_cross_entropy_loss
        gold = softmax_cross_entropy_loss(logits, targets,
                                          smoothing=smoothing)
        np.testing.assert_allclose(np.asarray(loss), np.asarray(gold),
                                   rtol=1e-5, atol=1e-5)

    def test_grad_vs_full_ce(self, mesh, rng):
        N, V = 8, 32
        logits = jnp.asarray(rng.normal(size=(N, V)), jnp.float32)
        targets = jnp.asarray(rng.integers(0, V, size=(N,)), jnp.int32)

        def g(lg, t):
            return jax.grad(lambda lg: jnp.sum(
                tp.vocab_parallel_cross_entropy(lg, t, 0.0)))(lg)

        grad = tp_shard_map(mesh, g, (P(None, "tp"), P()),
                            P(None, "tp"))(logits, targets)
        gold = jax.grad(lambda lg: jnp.sum(
            -jax.nn.log_softmax(lg)[jnp.arange(N), targets]))(logits)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(gold),
                                   rtol=1e-5, atol=1e-6)


class TestUtils:
    def test_divide(self):
        assert tp.divide(12, 4) == 3
        with pytest.raises(ValueError):
            tp.divide(10, 3)

    def test_vocab_utility(self):
        assert tp.VocabUtility.vocab_range_from_global_vocab_size(
            64, rank=2, world_size=4) == (32, 48)

    def test_broadcast_data(self, mesh, rng):
        x = jnp.asarray(rng.normal(size=(4, 4)), jnp.float32)

        def f(x):
            out = tp.broadcast_data(["x"], {"x": x})
            return out["x"]

        y = tp_shard_map(mesh, f, P(), P())(x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x), rtol=1e-6)

    def test_rng_tracker(self):
        from apex1_tpu.transformer.tensor_parallel import random as tpr
        tpr.model_parallel_seed(1234)
        tr = tpr.get_rng_tracker()
        k_default = tr.fork("default", tp_axis=None)
        k_mp = tr.fork(tp_axis=None)
        assert not np.array_equal(np.asarray(k_default), np.asarray(k_mp))
        with pytest.raises(RuntimeError):
            tr.add("default", 1)


class TestDecomposedCollectiveMatmul:
    """The chunk-pipelined overlap forms (`all_gather_matmul`,
    `matmul_reduce_scatter`) against the monolithic collective+dot
    composites they decompose — fwd and grads — plus the layer-level
    `overlap=` plumbing (off = the untouched legacy path)."""

    S, IN, OUT = 32, 16, 24

    def _arrs(self, rng):
        x = jnp.asarray(rng.normal(size=(self.S, self.IN)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(self.IN, self.OUT)), jnp.float32)
        return x, w

    def test_all_gather_matmul_matches_composite(self, mesh, rng):
        x, w = self._arrs(rng)

        def got(x, w):
            return tp.all_gather_matmul(x, w, "tp", 0)

        def want(x, w):
            xg = jax.lax.all_gather(x, "tp", axis=0, tiled=True)
            return jnp.dot(xg, w, preferred_element_type=jnp.float32)

        a = tp_shard_map(mesh, got, (P("tp", None), P(None, "tp")),
                         P(None, "tp"))(x, w)
        b = tp_shard_map(mesh, want, (P("tp", None), P(None, "tp")),
                         P(None, "tp"))(x, w)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)

    def test_matmul_reduce_scatter_matches_composite(self, mesh, rng):
        x, w = self._arrs(rng)

        def got(x, w):
            return tp.matmul_reduce_scatter(x, w, "tp", 0)

        def want(x, w):
            y = jnp.dot(x, w, preferred_element_type=jnp.float32)
            return jax.lax.psum_scatter(y, "tp", scatter_dimension=0,
                                        tiled=True)

        a = tp_shard_map(mesh, got, (P(None, "tp"), P("tp", None)),
                         P("tp", None))(x, w)
        b = tp_shard_map(mesh, want, (P(None, "tp"), P("tp", None)),
                         P("tp", None))(x, w)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("which", ["agm", "mrs"])
    def test_grads_match_composite(self, mesh, rng, which):
        x, w = self._arrs(rng)
        if which == "agm":
            in_specs = (P("tp", None), P(None, "tp"))

            def dec(x, w):
                return tp.all_gather_matmul(x, w, "tp", 0)

            def ref(x, w):
                xg = jax.lax.all_gather(x, "tp", axis=0, tiled=True)
                return jnp.dot(xg, w, preferred_element_type=jnp.float32)
        else:
            in_specs = (P(None, "tp"), P("tp", None))

            def dec(x, w):
                return tp.matmul_reduce_scatter(x, w, "tp", 0)

            def ref(x, w):
                y = jnp.dot(x, w, preferred_element_type=jnp.float32)
                return jax.lax.psum_scatter(y, "tp", scatter_dimension=0,
                                            tiled=True)

        def grads(f):
            sm = tp_shard_map(mesh,
                              lambda x, w: jnp.sum(f(x, w) ** 2),
                              in_specs, P())
            return jax.jit(jax.grad(lambda x, w: sm(x, w).sum(),
                                    argnums=(0, 1)))(x, w)

        for a, b in zip(grads(dec), grads(ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-5)

    def test_layer_overlap_kwarg_parity(self, mesh, rng):
        """Column/Row SP paths with overlap on == off (tolerance: the
        chunked dots re-associate the sum); off IS the legacy code."""
        x, w = self._arrs(rng)

        def col(ov):
            return tp_shard_map(
                mesh,
                lambda x, w: tp.column_parallel_linear(
                    x, w, sequence_parallel_enabled=True, axis_name="tp",
                    overlap=ov),
                (P("tp", None), P(None, "tp")), P(None, "tp"))(x, w)

        np.testing.assert_allclose(np.asarray(col(True)),
                                   np.asarray(col(False)),
                                   rtol=1e-5, atol=1e-5)

        def row(ov):
            return tp_shard_map(
                mesh,
                lambda x, w: tp.row_parallel_linear(
                    x, w, sequence_parallel_enabled=True, axis_name="tp",
                    overlap=ov),
                (P(None, "tp"), P("tp", None)), P("tp", None))(x, w)

        np.testing.assert_allclose(np.asarray(row(True)),
                                   np.asarray(row(False)),
                                   rtol=1e-5, atol=1e-5)
