"""Observability subsystem (`apex1_tpu.obs`) — spine schema round-trip,
XSpace parse → bucket → report against the committed CPU-trace fixture
(incl. the corrupt/truncated typed-error contract), and the calibration
acceptance pin: predicted-vs-measured within a STATED band on the
repo's banked records (perf_results/bench_*.log + tuning tables), so
the flywheel stays verified with no hardware attached.
"""

import gzip
import importlib.util
import json
import os
import pathlib
import shutil

import pytest

from apex1_tpu.obs import calibrate, spine, xspace

_REPO = pathlib.Path(__file__).resolve().parents[1]
FIXTURE = _REPO / "tests" / "fixtures" / "cpu_trace"
FIXTURE_PB = (FIXTURE / "plugins" / "profile" / "fixture"
              / "fixture.xplane.pb")


@pytest.fixture()
def no_default_run(monkeypatch):
    """Isolate the process-global default run."""
    monkeypatch.delenv("APEX1_OBS_DIR", raising=False)
    spine.set_default_run(None)
    yield
    spine.set_default_run(None)


# ==========================================================================
# spine
# ==========================================================================

class TestSpine:
    def test_run_roundtrip(self, tmp_path):
        with spine.ObsRun(dir=str(tmp_path), component="t") as run:
            with run.span("work", iters=3):
                pass
            run.counter("steps", 7)
            run.gauge("loss", 1.5)
            run.event("note", detail="x")
            path = run.path
        evs = spine.read_events(path)
        assert [e["kind"] for e in evs] == [
            "run", "span", "counter", "gauge", "event"]
        header = evs[0]
        assert header["schema"] == spine.SCHEMA
        assert header["component"] == "t"
        span = evs[1]
        assert span["name"] == "work" and span["iters"] == 3
        assert span["dur_s"] >= 0 and span["t"] >= 0
        assert evs[2]["value"] == 7
        assert evs[3]["value"] == 1.5
        assert evs[4]["detail"] == "x"

    def test_torn_tail_skipped(self, tmp_path):
        with spine.ObsRun(dir=str(tmp_path)) as run:
            run.event("ok")
            path = run.path
        with open(path, "a") as f:
            f.write('{"kind": "event", "name": "torn half li')
        evs = spine.read_events(path)
        assert [e["kind"] for e in evs] == ["run", "event"]

    def test_kind_filter_and_unknown_kind(self, tmp_path):
        with spine.ObsRun(dir=str(tmp_path)) as run:
            run.counter("a", 1)
            run.event("b")
            with pytest.raises(ValueError):
                run.emit("bogus", "x")
            path = run.path
        assert [e["name"] for e in
                spine.read_events(path, kinds=("counter",))] == ["a"]

    def test_emit_inert_without_env(self, no_default_run, tmp_path):
        assert spine.default_run() is None
        spine.emit("event", "nobody-home")   # must be a silent no-op
        assert list(tmp_path.iterdir()) == []

    def test_emit_activates_on_env(self, no_default_run, monkeypatch,
                                   tmp_path):
        monkeypatch.setenv("APEX1_OBS_DIR", str(tmp_path))
        spine.emit("event", "hello", n=1)
        run = spine.default_run()
        assert run is not None
        files = list(tmp_path.glob("*.jsonl"))
        assert len(files) == 1
        evs = spine.read_events(str(files[0]))
        assert evs[0]["kind"] == "run"
        assert evs[1]["name"] == "hello" and evs[1]["n"] == 1

    def test_stopwatch_cumulative_and_reset(self):
        sw = spine.StopWatch()
        sw.start()
        d1 = sw.stop()
        sw.start()
        sw.stop()
        assert sw.count == 2
        assert sw.elapsed() >= d1
        assert sw.elapsed(reset=True) >= 0
        assert sw.count == 0 and sw.elapsed() == 0.0

    def test_timers_adapter_is_stopwatch(self):
        from apex1_tpu.utils.observability import Timers
        timers = Timers()
        t = timers("fwd")
        assert isinstance(t, spine.StopWatch)   # the ONE primitive
        t.start()
        t.stop()
        out = timers.log(reset=True)
        assert out["fwd"] >= 0
        assert timers("fwd").elapsed() == 0.0

    def test_metrics_logger_mirrors_to_spine(self, no_default_run,
                                             tmp_path):
        from apex1_tpu.utils.observability import MetricsLogger
        run = spine.ObsRun(dir=str(tmp_path))
        spine.set_default_run(run)
        sunk = []
        logger = MetricsLogger(writer=sunk.append, n_chips=1)
        logger.log(3, {"loss": 2.5})
        logger.log(4, {"loss": 2.4}, _obs_name=None)   # suppressed
        run.close()
        assert len(sunk) == 2                      # writer unaffected
        evs = spine.read_events(run.path, kinds=("event",))
        assert len(evs) == 1
        assert evs[0]["name"] == "metrics"
        assert evs[0]["step"] == 3 and evs[0]["loss"] == 2.5

    def test_serving_metrics_mirror(self, no_default_run, tmp_path):
        from apex1_tpu.serving.metrics import ServingMetrics
        run = spine.ObsRun(dir=str(tmp_path))
        spine.set_default_run(run)
        m = ServingMetrics()
        m.event(11, "queued", n_prompt=4)
        m.event(11, "token")              # never mirrored (volume)
        m.transition("replica_death", replica=0)
        run.close()
        evs = spine.read_events(run.path, kinds=("event",))
        names = [(e["name"], e.get("event")) for e in evs]
        assert ("serving.request", "queued") in names
        assert ("serving.transition", "replica_death") in names
        assert not any(e.get("event") == "token" for e in evs)

    def test_sentinel_diagnostic_mirror(self, no_default_run, tmp_path):
        from apex1_tpu.resilience.sentinel import Sentinel
        run = spine.ObsRun(dir=str(tmp_path))
        spine.set_default_run(run)
        s = Sentinel(None, check_every=1, rollback_after=1,
                     abort_after=1)
        rec = s._bank({"action": "skip", "steps_seen": 5})
        run.close()
        evs = spine.read_events(run.path, kinds=("event",))
        assert evs and evs[0]["name"] == "sentinel.diagnostic"
        assert evs[0]["action"] == "skip" and rec["action"] == "skip"


# ==========================================================================
# xspace — parse -> bucket -> report against the committed fixture
# ==========================================================================

class TestXSpace:
    def test_fixture_parses(self):
        planes = xspace.parse_xspace(FIXTURE_PB)
        names = [p.name for p in planes]
        assert "/host:CPU" in names
        cpu = planes[names.index("/host:CPU")]
        assert cpu.lines and cpu.event_names

    def test_report_attributes_ops(self):
        report = xspace.build_report(FIXTURE)
        assert report["schema"] == xspace.REPORT_SCHEMA
        # the fixture traced tanh(x @ w) @ w.T: both dots must appear
        assert report["plane_class"] == "host-xla-proxy"
        op_names = [o["name"] for o in report["ops"]]
        assert any(n.startswith("dot") for n in op_names)
        assert any(n.startswith("tanh") for n in op_names)
        assert report["total_op_ms"] > 0
        # ops sorted by time desc; shares consistent
        ms = [o["ms"] for o in report["ops"]]
        assert ms == sorted(ms, reverse=True)
        share_sum = sum(o["share"] for o in report["ops"])
        assert 0.98 < share_sum < 1.02
        assert set(report["buckets"]) == set(xspace.BUCKETS)
        bucket_ms = sum(b["ms"] for b in report["buckets"].values())
        assert bucket_ms == pytest.approx(report["total_op_ms"],
                                          rel=1e-6)

    def test_per_step_division(self):
        report = xspace.build_report(FIXTURE, steps=4)
        assert report["per_step_ms"] == pytest.approx(
            report["total_op_ms"] / 4, abs=1e-5)

    def test_gz_variant_parses_identically(self, tmp_path):
        raw = FIXTURE_PB.read_bytes()
        gz = tmp_path / "fixture.xplane.pb.gz"
        gz.write_bytes(gzip.compress(raw))
        a = xspace.op_totals(xspace.parse_xspace(FIXTURE_PB))
        b = xspace.op_totals(xspace.parse_xspace(gz))
        assert a == b

    def test_report_persisted_roundtrip(self, tmp_path):
        tdir = tmp_path / "trace"
        shutil.copytree(FIXTURE, tdir)
        path = xspace.write_report(tdir, steps=2)
        assert os.path.basename(path) == xspace.REPORT_NAME
        banked = json.loads(pathlib.Path(path).read_text())
        assert banked["schema"] == xspace.REPORT_SCHEMA
        assert banked["steps"] == 2 and banked["n_ops"] > 0

    def test_truncated_trace_typed_error(self, tmp_path):
        raw = FIXTURE_PB.read_bytes()
        for cut in (100, len(raw) // 2, len(raw) - 5):
            p = tmp_path / f"cut{cut}.xplane.pb"
            p.write_bytes(raw[:cut])
            with pytest.raises(xspace.TraceError) as ei:
                xspace.parse_xspace(p)
            assert "corrupt protobuf" in ei.value.reason

    def test_garbage_and_missing_typed_error(self, tmp_path):
        p = tmp_path / "junk.xplane.pb"
        p.write_bytes(b"\xff" * 64)
        with pytest.raises(xspace.TraceError):
            xspace.parse_xspace(p)
        with pytest.raises(xspace.TraceError):
            xspace.parse_xspace(tmp_path / "nope.xplane.pb")
        bad_gz = tmp_path / "bad.xplane.pb.gz"
        bad_gz.write_bytes(b"not gzip at all")
        with pytest.raises(xspace.TraceError):
            xspace.parse_xspace(bad_gz)
        # valid gzip HEADER over a corrupt deflate body: raises
        # zlib.error, not BadGzipFile — must still be typed
        blob = bytearray(gzip.compress(FIXTURE_PB.read_bytes()))
        mid = len(blob) // 2
        blob[mid:mid + 16] = b"\x00" * 16
        corrupt_body = tmp_path / "body.xplane.pb.gz"
        corrupt_body.write_bytes(bytes(blob))
        with pytest.raises(xspace.TraceError):
            xspace.parse_xspace(corrupt_body)

    def test_empty_dir_typed_error(self, tmp_path):
        with pytest.raises(xspace.TraceError) as ei:
            xspace.build_report(tmp_path)
        assert "no *.xplane.pb" in ei.value.reason

    def test_corrupt_trace_in_report_path(self, tmp_path):
        d = tmp_path / "plugins" / "profile" / "x"
        d.mkdir(parents=True)
        (d / "t.xplane.pb").write_bytes(b"\x07" * 32)
        with pytest.raises(xspace.TraceError):
            xspace.build_report(tmp_path)

    def test_bucket_rules(self):
        assert xspace.bucket_of("all-reduce-start.1") == "collective"
        assert xspace.bucket_of("collective-permute-done") == "collective"
        assert xspace.bucket_of("reduce-scatter.3") == "collective"
        assert xspace.bucket_of("custom-call.7") == "pallas"
        assert xspace.bucket_of("tpu_custom_call") == "pallas"
        assert xspace.bucket_of("fusion.12") == "xla"
        assert xspace.bucket_of("dot.4") == "xla"
        # near-misses must NOT land in collective
        assert xspace.bucket_of("reduce-window") == "xla"
        assert xspace.bucket_of("reduce.8") == "xla"

    def test_live_capture_roundtrip(self, tmp_path):
        """The CPU-rehearsable leg: a real jax.profiler.trace of one
        tiny jitted step parses and attributes through the same path
        the banked profile_artifacts will use on silicon."""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return jnp.sum(x @ x)

        x = jnp.ones((64, 64), jnp.float32)
        f(x).block_until_ready()
        tdir = str(tmp_path / "live")
        with jax.profiler.trace(tdir):
            f(x).block_until_ready()
        report = xspace.build_report(tdir, steps=1)
        assert report["n_ops"] > 0 and report["total_op_ms"] > 0


# ==========================================================================
# calibrate
# ==========================================================================

def _write(path, doc):
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(doc) if isinstance(doc, dict)
                    else doc)


def _synthetic_results(tmp_path):
    """A results dir with one priceable tpu record, one excluded decode
    record, one cpu record, and a tuning table with a measured + an
    interpret entry."""
    res = tmp_path / "perf_results"
    _write(res / "predicted_r9.json", {
        "steps": [
            # flops/bytes chosen so v5e roofline rate = units/t is easy
            {"name": "gpt2", "units_per_step": 16384,
             "flops": 1e12, "bytes": 1e9},
            {"name": "decode", "units_per_step": 1024,
             "flops": 1e10, "bytes": 1e8},
        ]})
    _write(res / "bench_gpt2.log",
           json.dumps({"metric": "tok/s gpt2 [tpu]", "value": 50_000.0,
                       "unit": "u"}) + "\n")
    _write(res / "bench_decode.log",
           json.dumps({"metric": "tok/s decode [tpu]", "value": 9_000.0,
                       "unit": "u"}) + "\n")
    _write(res / "bench_bert.log",
           json.dumps({"metric": "tok/s bert [cpu]", "value": 123.0,
                       "unit": "u"}) + "\n")
    _write(res / "tuning" / "layer_norm.json", {
        "schema": 1, "kernel": "layer_norm", "entries": {
            "v5e|bfloat16|lanes=768": {
                "blocks": {"block_rows": 128}, "time_ms": 2.0,
                "timing": "measured", "backend": "tpu",
                "predicted": {"ms": 1.0, "flops": 1.0, "bytes": 1.0,
                              "generation": "v5e"}},
            "v5e|bfloat16|lanes=128": {
                "blocks": {"block_rows": 64}, "time_ms": 500.0,
                "timing": "interpret", "backend": "cpu",
                "predicted": {"ms": 0.5, "flops": 1.0, "bytes": 1.0,
                              "generation": "v5e"}},
            "v5e|bfloat16|lanes=256": {   # no predicted -> no pair
                "blocks": {"block_rows": 64}, "time_ms": 1.0,
                "timing": "measured", "backend": "tpu"},
        }})
    return res


class TestCalibrate:
    def test_log_map_in_sync_with_bench(self):
        spec = importlib.util.spec_from_file_location(
            "_bench_for_obs", _REPO / "bench.py")
        bench_mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench_mod)
        for config, logs in bench_mod._BANKED_LOGS.items():
            for log in logs:
                assert calibrate.LOG_TO_CONFIG.get(log) == config, (
                    f"obs.calibrate.LOG_TO_CONFIG out of sync with "
                    f"bench._BANKED_LOGS for {log}")
        # and nothing stale pointing the other way
        known = {log for logs in bench_mod._BANKED_LOGS.values()
                 for log in logs}
        assert set(calibrate.LOG_TO_CONFIG) == known

    def test_newest_prediction_by_mtime(self, tmp_path):
        a = tmp_path / "predicted_r9.json"
        b = tmp_path / "predicted_r10.json"
        _write(a, {"steps": []})
        _write(b, {"steps": []})
        os.utime(a, (1_000_000_000, 1_000_000_000))
        os.utime(b, (2_000_000_000, 2_000_000_000))
        assert calibrate.newest_prediction_path(
            str(tmp_path)).endswith("predicted_r10.json")
        # mtime, not lexicographic: flip the clock and r9 wins
        os.utime(a, (3_000_000_000, 3_000_000_000))
        assert calibrate.newest_prediction_path(
            str(tmp_path)).endswith("predicted_r9.json")

    def test_roofline_ms_arithmetic(self):
        from apex1_tpu.core.capability import get_capability
        cap = get_capability("v5e")
        # compute-bound case
        ms = calibrate.roofline_ms(cap.bf16_tflops * 1e12, 0.0, "v5e")
        assert ms == pytest.approx(1e3)
        # bandwidth-bound case
        ms = calibrate.roofline_ms(0.0, cap.hbm_gbps * 1e9, "v5e")
        assert ms == pytest.approx(1e3)

    def test_collect_fit_and_exclusions(self, tmp_path):
        res = _synthetic_results(tmp_path)
        pairs, excluded = calibrate.collect_pairs(
            str(res), "v5e", str(res / "tuning"))
        by_key = {}
        for p in pairs:
            by_key.setdefault(p.key, []).append(p)
        # the tpu step pair: slowdown = predicted_rate / measured
        assert len(by_key["step:gpt2"]) == 1
        sp = by_key["step:gpt2"][0]
        pred = calibrate.predicted_step_rate(
            {"name": "gpt2", "units_per_step": 16384,
             "flops": 1e12, "bytes": 1e9}, "v5e")
        assert sp.predicted == pytest.approx(pred, rel=1e-3)
        assert sp.slowdown == pytest.approx(pred / 50_000.0, rel=1e-3)
        assert sp.backend == "tpu"
        # kernel pairs: measured->tpu, interpret->cpu-proxy, the
        # predicted-less entry contributes nothing
        kps = by_key["kernel:layer_norm"]
        assert sorted((p.backend, p.slowdown) for p in kps) == [
            ("cpu-proxy", pytest.approx(1000.0)),
            ("tpu", pytest.approx(2.0))]
        # decode excluded WITH its stated reason; cpu bench rec skipped
        assert any(e["key"] == "step:decode"
                   and "scanned-loop" in e["reason"] for e in excluded)
        assert "step:bert" not in by_key
        factors, proxy = calibrate.fit(pairs)
        assert factors["step:gpt2"]["n"] == 1
        assert factors["kernel:layer_norm"]["slowdown"] == \
            pytest.approx(2.0)
        assert proxy["kernel:layer_norm"]["slowdown"] == \
            pytest.approx(1000.0)
        assert proxy["kernel:layer_norm"]["backend"] == "cpu-proxy"

    def test_save_load_roundtrip_and_failsafe(self, tmp_path):
        res = _synthetic_results(tmp_path)
        doc = calibrate.build_calibration(str(res), "v5e",
                                          str(res / "tuning"))
        path = calibrate.save_calibration(doc, results_dir=str(res))
        loaded = calibrate.load_calibration(str(res))
        assert loaded["factors"] == doc["factors"]
        # the lookup API serves tpu-backed factors only
        assert calibrate.step_slowdown("gpt2", str(res))
        assert calibrate.kernel_slowdown("layer_norm", str(res))[
            "backend"] == "tpu"
        assert calibrate.step_slowdown("nope", str(res)) is None
        # corrupt / foreign-schema files are a miss, never a raise
        pathlib.Path(path).write_text("{ not json")
        assert calibrate.load_calibration(str(res)) is None
        pathlib.Path(path).write_text(json.dumps({"schema": "other"}))
        assert calibrate.load_calibration(str(res)) is None
        assert calibrate.step_slowdown("gpt2", str(res)) is None

    # -- THE acceptance pin (ISSUE 10): predicted-vs-measured within a
    # stated band on the repo's banked records ------------------------

    #: stated band for raw tpu step slowdowns on the banked corpus:
    #: every banked on-silicon record sits between 2x FASTER than its
    #: roofline (cost model overcounted bytes — bert/resnet territory)
    #: and 4x slower (llama_longctx's 0.36 ratio = 2.79x). Outside this
    #: band = either a broken join or a real regression; widen only
    #: with a reason in the commit.
    RAW_BAND = (0.5, 4.0)
    #: post-fit residual band: each pair within 1.35x of its key's
    #: fitted factor (multi-record keys like gpt2 must agree with
    #: themselves this tightly)
    RESIDUAL = 1.35

    def test_banked_corpus_within_stated_band(self):
        pairs, excluded = calibrate.collect_pairs()
        tpu_steps = [p for p in pairs
                     if p.backend == "tpu" and p.key.startswith("step:")]
        assert len(tpu_steps) >= 5, (
            "banked tpu step corpus shrank — bench logs missing?")
        for p in tpu_steps:
            assert self.RAW_BAND[0] <= p.slowdown <= self.RAW_BAND[1], (
                f"{p.key} slowdown {p.slowdown:.2f} outside stated "
                f"band {self.RAW_BAND} (source {p.source})")
        factors, proxy = calibrate.fit(pairs)
        assert factors, "no tpu factors fitted from the banked corpus"
        for p in pairs:
            f = (factors if p.backend == "tpu" else proxy)[p.key]
            resid = p.slowdown / f["slowdown"]
            assert 1 / self.RESIDUAL <= resid <= self.RESIDUAL, (
                f"{p.key} residual x{resid:.2f} outside "
                f"x{self.RESIDUAL} of fitted factor")
        # the decode blind spot stays excluded, with its reason banked
        assert any(e["key"] == "step:decode" for e in excluded)
        # kernel corpus present (cpu-proxy until a hardware window) and
        # every proxy factor is labelled as such
        assert all(f["backend"] == "cpu-proxy"
                   for f in proxy.values())

    def test_banked_calibration_table_fresh(self):
        """perf_results/calibration.json must exist, parse, and agree
        with a re-fit of the banked corpus (the table is a build
        product of the corpus, not hand-maintained state)."""
        doc = calibrate.load_calibration()
        assert doc is not None, "perf_results/calibration.json missing"
        refit, _proxy = calibrate.fit(calibrate.collect_pairs()[0])
        assert set(doc["factors"]) == set(refit)
        for key, f in refit.items():
            assert doc["factors"][key]["slowdown"] == pytest.approx(
                f["slowdown"], rel=0.05), (
                f"banked factor for {key} stale vs corpus — rerun "
                f"python -m apex1_tpu.obs.calibrate")


# ==========================================================================
# feedback into bench records
# ==========================================================================

@pytest.fixture(scope="module")
def bench_mod():
    spec = importlib.util.spec_from_file_location("_bench_for_obs2",
                                                  _REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestBenchCalibrationFeedback:
    def _results_with_calibration(self, tmp_path, slowdown=2.0):
        res = tmp_path / "perf_results"
        _write(res / "predicted_r9.json", {
            "steps": [{"name": "gpt2", "units_per_step": 1000,
                       "flops": 1e12, "bytes": 1e9}]})
        _write(res / "calibration.json", {
            "schema": calibrate.SCHEMA,
            "factors": {"step:gpt2": {"slowdown": slowdown, "n": 3,
                                      "backend": "tpu"}},
            "proxy_factors": {}, "excluded": [], "pairs": []})
        return str(res)

    def test_calibrated_fields_attached(self, bench_mod, tmp_path):
        res = self._results_with_calibration(tmp_path, slowdown=2.0)
        rec = {"metric": "m [tpu]", "value": 4000.0}
        out = bench_mod._attach_roofline(dict(rec), "gpt2", res)
        assert out["predicted"] > 0
        assert out["calibrated_predicted"] == pytest.approx(
            out["predicted"] / 2.0, rel=1e-3)
        assert out["calibrated_ratio"] == pytest.approx(
            out["value"] / out["calibrated_predicted"], rel=1e-3)
        assert out["calibration"] == {"slowdown": 2.0, "n": 3}
        # raw localizer untouched
        assert out["roofline_ratio"] == pytest.approx(
            out["value"] / out["predicted"], rel=1e-3)

    def test_no_calibration_no_fields(self, bench_mod, tmp_path):
        res = self._results_with_calibration(tmp_path)
        os.remove(os.path.join(res, "calibration.json"))
        out = bench_mod._attach_roofline(
            {"metric": "m [tpu]", "value": 4000.0}, "gpt2", res)
        assert "predicted" in out
        assert "calibrated_predicted" not in out

    def test_corrupt_calibration_never_breaks_record(self, bench_mod,
                                                     tmp_path):
        res = self._results_with_calibration(tmp_path)
        with open(os.path.join(res, "calibration.json"), "w") as f:
            f.write("!! not json")
        out = bench_mod._attach_roofline(
            {"metric": "m [tpu]", "value": 4000.0}, "gpt2", res)
        assert out["value"] == 4000.0 and "predicted" in out
        assert "calibrated_predicted" not in out

    def test_cpu_records_never_calibrated(self, bench_mod, tmp_path):
        res = self._results_with_calibration(tmp_path)
        out = bench_mod._attach_roofline(
            {"metric": "m [cpu]", "value": 10.0}, "gpt2", res)
        assert "predicted" not in out
        assert "calibrated_predicted" not in out


# ==========================================================================
# measured_vs_predicted: newest-table resolution (satellite fix)
# ==========================================================================

class TestMeasuredVsPredicted:
    @pytest.fixture()
    def mvp_main(self, monkeypatch):
        spec = importlib.util.spec_from_file_location(
            "_mvp_for_obs", _REPO / "tools" / "measured_vs_predicted.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_resolves_newest_and_derives_out_name(self, mvp_main,
                                                  tmp_path,
                                                  monkeypatch, capsys):
        res = tmp_path / "perf_results"
        _write(res / "predicted_r5.json", {"steps": []})
        _write(res / "predicted_r12.json", {"steps": [
            {"name": "gpt2", "units_per_step": 1000, "flops": 1e12,
             "bytes": 1e9}]})
        os.utime(res / "predicted_r5.json", (1e9, 1e9))
        os.utime(res / "predicted_r12.json", (2e9, 2e9))
        monkeypatch.setattr(
            "sys.argv", ["measured_vs_predicted.py",
                         "--results", str(res)])
        mvp_main.main()
        out = res / "measured_r12.md"
        assert out.exists(), "out name must follow the resolved table"
        text = out.read_text()
        assert "predicted_r12.json" in text
        assert "predicted_r5.json" not in text

    def test_exits_loud_when_no_table(self, mvp_main, tmp_path,
                                      monkeypatch):
        monkeypatch.setattr(
            "sys.argv", ["measured_vs_predicted.py",
                         "--results", str(tmp_path)])
        with pytest.raises(SystemExit):
            mvp_main.main()
