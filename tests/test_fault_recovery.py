"""Failure detection / recovery + race-detection tools — SURVEY.md
§5.2/§5.3.

The reference has NO elastic recovery (a dead rank = NCCL timeout = dead
job); the plan gives checkpoint-restart + divergence pre-flight instead.
The fault-injection test kills a 2-process distributed training job
mid-run (simulated preemption) and asserts clean resume from the latest
checkpoint to completion."""

import os
import subprocess
import sys
import tempfile
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.utils.debug import (assert_donation_safe,
                                   assert_same_program_across_processes,
                                   program_fingerprint)

pytestmark = pytest.mark.slow  # composed-step suite: full run via check_all.sh --all


class TestDebugTools:
    def test_fingerprint_stable_and_sensitive(self):
        f1 = lambda x: x * 2 + 1
        f2 = lambda x: x * 3 + 1
        x = jnp.ones((4,))
        assert program_fingerprint(f1, x) == program_fingerprint(f1, x)
        assert program_fingerprint(f1, x) != program_fingerprint(f2, x)
        # single-process pre-flight is a no-op that returns the fp
        assert assert_same_program_across_processes(f1, x) == \
            program_fingerprint(f1, x)

    def test_donation_safe_passes_for_pure_step(self):
        step = jax.jit(lambda s: jax.tree.map(lambda x: x + 1, s))
        assert_donation_safe(step, {"w": jnp.ones((8,))})

    def test_donation_check_catches_impure_step(self):
        calls = []

        def impure(s):
            calls.append(1)
            return jax.tree.map(lambda x: x + len(calls), s)

        with pytest.raises(AssertionError, match="corruption|nondet"):
            assert_donation_safe(impure, {"w": jnp.ones((4,))})


_CHILD = textwrap.dedent("""
    import os, sys
    import jax
    from apex1_tpu.parallel import multiproc
    multiproc.init_from_env()
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh
    from apex1_tpu.amp import Amp
    from apex1_tpu.checkpoint import (CheckpointManager, to_global,
                                      to_host_local)
    from apex1_tpu.optim.fused_sgd import fused_sgd

    ckdir = sys.argv[1]
    fail_at = int(os.environ.get("FAIL_AT", "-1"))
    target_steps = 6

    amp = Amp(tx=fused_sgd(0.1), opt_level="O0")
    params = {"w": jnp.ones((4,), jnp.float32)}
    state = amp.init(params)

    def loss_fn(p, x):
        return jnp.sum(jnp.square(p["w"])) * x

    step_fn = jax.jit(amp.make_train_step(loss_fn))

    rank = jax.process_index()
    # orbax managers are COLLECTIVE (every process joins the barriers),
    # and multi-controller saves need globally-addressable arrays:
    # to_global/to_host_local do the conversion around save/restore
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    mgr = CheckpointManager(ckdir, max_to_keep=2)
    start = 0
    if mgr.latest() is not None:
        gstate = mgr.restore(jax.eval_shape(lambda: state))
        state = to_host_local(gstate, mesh)
        start = int(state.step)
        print(f"rank {rank} resumed from step {start}", flush=True)

    for i in range(start, target_steps):
        state, m = step_fn(state, jnp.float32(1.0))
        mgr.save(int(state.step), to_global(state, mesh), force=True)
        mgr.wait_until_finished()
        if int(state.step) == fail_at:
            print(f"rank {rank} injecting failure at step {fail_at}",
                  flush=True)
            os._exit(17)   # simulated preemption: no cleanup
    mgr.close()
    print(f"rank {rank} finished at step {int(state.step)}", flush=True)
""")


@pytest.mark.slow
def test_checkpoint_restart_after_fault(tmp_path):
    from apex1_tpu.parallel import multiproc

    import pathlib
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    ckdir = tmp_path / "ckpts"
    repo_root = str(pathlib.Path(__file__).resolve().parents[1])
    from apex1_tpu.testing import child_cache_env
    env_base = {"PYTHONPATH": repo_root + os.pathsep
                + os.environ.get("PYTHONPATH", ""),
                # fresh child processes: share the suite's persistent
                # compile cache or every run recompiles cold
                **child_cache_env()}

    # run 1: both processes die at step 3 (simulated preemption)
    rc1 = multiproc.launch(
        str(script), [str(ckdir)], num_processes=2,
        cpu_devices_per_process=1, coordinator_port=12391,
        env={**env_base, "FAIL_AT": "3"})
    assert rc1 == 17, f"expected injected failure, got rc={rc1}"

    # run 2: clean relaunch resumes from the latest checkpoint
    rc2 = multiproc.launch(
        str(script), [str(ckdir)], num_processes=2,
        cpu_devices_per_process=1, coordinator_port=12392,
        env=env_base)
    assert rc2 == 0

    # the final checkpoint reflects a completed run (step target reached)
    from apex1_tpu.checkpoint import CheckpointManager
    with CheckpointManager(ckdir) as mgr:
        assert mgr.latest() == 6
