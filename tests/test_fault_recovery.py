"""Failure detection / recovery + race-detection tools — SURVEY.md
§5.2/§5.3.

The reference has NO elastic recovery (a dead rank = NCCL timeout = dead
job); the plan gives checkpoint-restart + divergence pre-flight instead.
Two kill-and-resume drills: the CHEAP single-process one (chaos-injected
SIGTERM through the resilient runtime, bit-exact continuation asserted)
runs in tier-1; the 2-process orbax-manager variant stays in the slow
lane (full run via check_all.sh --all)."""

import json
import os
import subprocess
import sys
import tempfile
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.utils.debug import (assert_donation_safe,
                                   assert_same_program_across_processes,
                                   program_fingerprint)


class TestDebugTools:
    def test_fingerprint_stable_and_sensitive(self):
        f1 = lambda x: x * 2 + 1
        f2 = lambda x: x * 3 + 1
        x = jnp.ones((4,))
        assert program_fingerprint(f1, x) == program_fingerprint(f1, x)
        assert program_fingerprint(f1, x) != program_fingerprint(f2, x)
        # single-process pre-flight is a no-op that returns the fp
        assert assert_same_program_across_processes(f1, x) == \
            program_fingerprint(f1, x)

    def test_donation_safe_passes_for_pure_step(self):
        step = jax.jit(lambda s: jax.tree.map(lambda x: x + 1, s))
        assert_donation_safe(step, {"w": jnp.ones((8,))})

    def test_donation_check_catches_impure_step(self):
        calls = []

        def impure(s):
            calls.append(1)
            return jax.tree.map(lambda x: x + len(calls), s)

        with pytest.raises(AssertionError, match="corruption|nondet"):
            assert_donation_safe(impure, {"w": jnp.ones((4,))})


_CHILD_SOLO = textwrap.dedent("""
    # single-process resilient training child: SIGTERM (self-injected by
    # the chaos harness at CHAOS_SIGTERM_STEP) -> final sync checkpoint
    # -> EXIT_RESUMABLE; a relaunch resumes EXACTLY (data position from
    # the manifest meta). Pure-jnp model: the drill is about the
    # runtime, not the network, and tier-1 pays for every compile.
    import json, os, sys
    from apex1_tpu.testing import force_virtual_cpu_devices
    force_virtual_cpu_devices(1)
    import jax, jax.numpy as jnp, numpy as np
    from apex1_tpu.amp import Amp
    from apex1_tpu.optim.fused_sgd import fused_sgd
    from apex1_tpu.resilience import (PreemptionHandler,
                                      ResilientCheckpointer)
    from apex1_tpu.testing.chaos import sigterm_self_at

    ckdir, losslog, outnpy = sys.argv[1:4]
    kill_env = os.environ.get("CHAOS_SIGTERM_STEP", "")
    kill_at = int(kill_env) if kill_env else None
    TOTAL = 8

    amp = Amp(tx=fused_sgd(0.1), opt_level="O0")
    state = amp.init(
        {"w": jnp.linspace(0.5, 2.0, 8).astype(jnp.float32)})
    step = jax.jit(
        amp.make_train_step(
            lambda p, x: jnp.sum(jnp.square(p["w"])) * x),
        donate_argnums=0)

    ck = ResilientCheckpointer(ckdir, keep=3)
    start = 0
    if ck.latest_valid() is not None:
        state, man = ck.restore(template=state)
        start = int(man.meta["data_step"])
        print(f"resumed at data step {start}", flush=True)

    with PreemptionHandler() as pre, ck:
        for i in range(start, TOTAL):
            # "data" is a pure function of the step index: resume
            # exactness is then a pure property of the runtime
            state, m = step(state, jnp.float32(1.0 + 0.125 * i))
            with open(losslog, "a") as f:
                f.write(json.dumps(
                    {"step": i, "loss": float(m["loss"])}) + "\\n")
            ck.save(int(state.step), state, meta={"data_step": i + 1})
            sigterm_self_at(i + 1, kill_at)
            if pre.triggered:
                ck.wait()
                ck.save_sync(int(state.step), state,
                             meta={"data_step": i + 1})
                pre.exit_resumable(f"preempted at data step {i + 1}")
        ck.wait()
    np.save(outnpy, np.asarray(state.params["w"]))
    print(f"FINISHED step={int(state.step)}", flush=True)
""")


def _run_solo(script, ckdir, losslog, outnpy, *, kill_at=None):
    import pathlib

    from apex1_tpu.testing import child_cache_env

    repo_root = str(pathlib.Path(__file__).resolve().parents[1])
    env = {**os.environ,
           "PYTHONPATH": repo_root + os.pathsep
           + os.environ.get("PYTHONPATH", ""),
           **child_cache_env()}
    if kill_at is not None:
        env["CHAOS_SIGTERM_STEP"] = str(kill_at)
    else:
        env.pop("CHAOS_SIGTERM_STEP", None)
    return subprocess.run(
        [sys.executable, str(script), str(ckdir), str(losslog),
         str(outnpy)], env=env, capture_output=True, text=True,
        timeout=240)


def _losses(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _reference_trajectory():
    """The uninterrupted run, computed IN-PROCESS (CPU XLA is
    deterministic across processes, and the interrupted+resumed pair
    below already proves bit-exactness across a process boundary —
    a third cold jax boot would buy nothing but tier-1 wall time).
    Must mirror _CHILD_SOLO's model/loop exactly."""
    from apex1_tpu.amp import Amp
    from apex1_tpu.optim.fused_sgd import fused_sgd

    amp = Amp(tx=fused_sgd(0.1), opt_level="O0")
    state = amp.init(
        {"w": jnp.linspace(0.5, 2.0, 8).astype(jnp.float32)})
    step = jax.jit(amp.make_train_step(
        lambda p, x: jnp.sum(jnp.square(p["w"])) * x))
    losses = []
    for i in range(8):
        state, m = step(state, jnp.float32(1.0 + 0.125 * i))
        losses.append({"step": i, "loss": float(m["loss"])})
    return losses, np.asarray(state.params["w"])


def test_chaos_kill_and_resume_bit_exact(tmp_path):
    """Tier-1 acceptance drill: SIGTERM mid-run → EXIT_RESUMABLE with a
    banked checkpoint → relaunch auto-resumes from the newest valid
    checkpoint → final params AND the loss trajectory are BIT-identical
    to an uninterrupted run."""
    from apex1_tpu.resilience import EXIT_RESUMABLE

    script = tmp_path / "child_solo.py"
    script.write_text(_CHILD_SOLO)

    ref_losses, ref_params = _reference_trajectory()

    # interrupted run: chaos SIGTERM after data step 4 → resumable exit
    r1 = _run_solo(script, tmp_path / "ck", tmp_path / "int.jsonl",
                   tmp_path / "int.npy", kill_at=4)
    assert r1.returncode == EXIT_RESUMABLE, (r1.returncode,
                                             r1.stderr[-2000:])
    assert "resumable" in r1.stdout

    # relaunch: resumes from the banked checkpoint, runs to completion
    r2 = _run_solo(script, tmp_path / "ck", tmp_path / "int.jsonl",
                   tmp_path / "int.npy")
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert "resumed at data step 4" in r2.stdout

    # loss trajectory: interrupted(0..3) ++ resumed(4..7) == reference,
    # bit-exact (same floats, not allclose)
    got_losses = _losses(tmp_path / "int.jsonl")
    assert [r["step"] for r in got_losses] == list(range(8))
    assert got_losses == ref_losses

    # final params bit-identical
    np.testing.assert_array_equal(np.load(tmp_path / "int.npy"),
                                  ref_params)


_CHILD = textwrap.dedent("""
    import os, sys
    import jax
    from apex1_tpu.parallel import multiproc
    multiproc.init_from_env()
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh
    from apex1_tpu.amp import Amp
    from apex1_tpu.checkpoint import (CheckpointManager, to_global,
                                      to_host_local)
    from apex1_tpu.optim.fused_sgd import fused_sgd

    ckdir = sys.argv[1]
    fail_at = int(os.environ.get("FAIL_AT", "-1"))
    target_steps = 6

    amp = Amp(tx=fused_sgd(0.1), opt_level="O0")
    params = {"w": jnp.ones((4,), jnp.float32)}
    state = amp.init(params)

    def loss_fn(p, x):
        return jnp.sum(jnp.square(p["w"])) * x

    step_fn = jax.jit(amp.make_train_step(loss_fn))

    rank = jax.process_index()
    # orbax managers are COLLECTIVE (every process joins the barriers),
    # and multi-controller saves need globally-addressable arrays:
    # to_global/to_host_local do the conversion around save/restore
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    mgr = CheckpointManager(ckdir, max_to_keep=2)
    start = 0
    if mgr.latest() is not None:
        gstate = mgr.restore(jax.eval_shape(lambda: state))
        state = to_host_local(gstate, mesh)
        start = int(state.step)
        print(f"rank {rank} resumed from step {start}", flush=True)

    for i in range(start, target_steps):
        state, m = step_fn(state, jnp.float32(1.0))
        mgr.save(int(state.step), to_global(state, mesh), force=True)
        mgr.wait_until_finished()
        if int(state.step) == fail_at:
            print(f"rank {rank} injecting failure at step {fail_at}",
                  flush=True)
            os._exit(17)   # simulated preemption: no cleanup
    mgr.close()
    print(f"rank {rank} finished at step {int(state.step)}", flush=True)
""")


@pytest.mark.slow
def test_checkpoint_restart_after_fault(tmp_path):
    from apex1_tpu.parallel import multiproc

    import pathlib
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    ckdir = tmp_path / "ckpts"
    repo_root = str(pathlib.Path(__file__).resolve().parents[1])
    from apex1_tpu.testing import child_cache_env
    env_base = {"PYTHONPATH": repo_root + os.pathsep
                + os.environ.get("PYTHONPATH", ""),
                # fresh child processes: share the suite's persistent
                # compile cache or every run recompiles cold
                **child_cache_env()}

    # run 1: both processes die at step 3 (simulated preemption)
    rc1 = multiproc.launch(
        str(script), [str(ckdir)], num_processes=2,
        cpu_devices_per_process=1, coordinator_port=12391,
        env={**env_base, "FAIL_AT": "3"})
    assert rc1 == 17, f"expected injected failure, got rc={rc1}"

    # run 2: clean relaunch resumes from the latest checkpoint
    rc2 = multiproc.launch(
        str(script), [str(ckdir)], num_processes=2,
        cpu_devices_per_process=1, coordinator_port=12392,
        env=env_base)
    assert rc2 == 0

    # the final checkpoint reflects a completed run (step target reached)
    from apex1_tpu.checkpoint import CheckpointManager
    with CheckpointManager(ckdir) as mgr:
        assert mgr.latest() == 6
