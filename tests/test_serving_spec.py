"""Engine-integrated speculative decode (ISSUE 15): the draft/verify
state machine must be INVISIBLE in the tokens — exact-match acceptance
against the target's counter-keyed stream means the emitted sequence is
bit-identical to the non-speculative engine AND to solo
`models.generate`, at temperature 0 and > 0, under staggered
join/leave, resubmission, int8 KV, and radix prefix hits. Drafts are
pure latency hints; what speculation changes is dispatch count, and the
accept-rate observables are what the banked benches read."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.core.policy import get_policy
from apex1_tpu.models.generate import generate, gpt2_decoder
from apex1_tpu.models.gpt2 import GPT2, GPT2Config
from apex1_tpu.serving import Engine, EngineConfig, ngram_propose
from apex1_tpu.testing.chaos import toy_decoder


@pytest.fixture(scope="module")
def tiny():
    """Tiny fp32 GPT-2 + its decoder pair + a solo-generate oracle."""
    cfg = GPT2Config.tiny(policy=get_policy("O0"), max_seq_len=64)
    model = GPT2(cfg)
    rng = np.random.default_rng(7)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 6)),
                         jnp.int32)
    params = model.init(jax.random.key(0), prompt)["params"]
    apply_fn, make_cache = gpt2_decoder(model)

    def solo(tokens, n_new):
        cache = make_cache(1, len(tokens) + n_new)
        return np.asarray(generate(
            apply_fn, params, jnp.asarray([tokens], jnp.int32),
            max_new_tokens=n_new, cache=cache,
            vocab_size=cfg.vocab_size))[0]

    return cfg, params, apply_fn, make_cache, solo


def _toy_engine(**kw):
    apply_fn, make_cache, params = toy_decoder()
    ekw = dict(max_slots=3, max_len=48, prefill_chunk=4, vocab_size=61,
               temperature=0.9, seed=5)
    ekw.update(kw)
    dp = ekw.pop("draft_propose", None)
    return Engine(apply_fn, make_cache, params, EngineConfig(**ekw),
                  draft_propose=dp)


class TestNgramPropose:
    def test_prompt_lookup_copies_continuation(self):
        # suffix (7, 8) occurred earlier, followed by 9, 1
        h = [3, 7, 8, 9, 1, 2, 7, 8]
        np.testing.assert_array_equal(ngram_propose(h, 2), [9, 1])

    def test_most_recent_occurrence_wins(self):
        # suffix (5,) occurs at idx 0 (-> 1) and idx 2 (-> 9): recency
        h = [5, 1, 5, 9, 5]
        np.testing.assert_array_equal(ngram_propose(h, 1), [9])

    def test_fallback_repeats_last_token(self):
        np.testing.assert_array_equal(ngram_propose([4], 3), [4, 4, 4])
        np.testing.assert_array_equal(ngram_propose([1, 2, 3], 2),
                                      [3, 3])

    def test_short_continuation_padded(self):
        # match lands at the very end: continuation shorter than k
        h = [7, 8, 2, 7, 8]
        out = ngram_propose(h, 3)
        assert out[0] == 2 and out.shape == (3,)

    def test_validation(self):
        with pytest.raises(ValueError, match="k must be"):
            ngram_propose([1], 0)
        with pytest.raises(ValueError, match="non-empty"):
            ngram_propose([], 2)


class TestSpecTokenParity:
    def test_greedy_staggered_join_leave_token_identical(self, tiny,
                                                         rng):
        """THE tentpole pin at temperature 0: the speculative engine
        under the mixed staggered workload emits exactly what solo
        greedy `generate` does, with exactly its two executables
        (prefill + verify — decode is never traced)."""
        cfg, params, apply_fn, make_cache, solo = tiny
        eng = Engine(apply_fn, make_cache, params,
                     EngineConfig(max_slots=3, max_len=48,
                                  prefill_chunk=4, num_draft=3,
                                  vocab_size=cfg.vocab_size))
        lens = [3, 7, 5, 9, 4, 6]
        news = [6, 5, 7, 4, 6, 5]
        prompts = [rng.integers(0, cfg.vocab_size, (L,)).tolist()
                   for L in lens]
        ids = [eng.submit(p, max_new_tokens=n)
               for p, n in zip(prompts[:3], news[:3])]
        eng.step()
        ids.append(eng.submit(prompts[3], max_new_tokens=news[3]))
        eng.step()
        ids += [eng.submit(p, max_new_tokens=n)
                for p, n in zip(prompts[4:], news[4:])]
        eng.run(max_steps=200)
        for p, n, rid in zip(prompts, news, ids):
            res = eng.results[rid]
            assert res.status == "done"
            np.testing.assert_array_equal(res.tokens, solo(p, n))
        assert eng.trace_counts == {"prefill": 1, "verify": 1}
        s = eng.metrics.summary()
        assert s["done"] == 6
        assert "accept_rate" in s        # banked, whatever its value

    def test_sampled_identical_to_nonspec_engine(self):
        """Temperature 0.9: exact-match verify emits the target's
        counter stream verbatim — bit-identical to the plain engine,
        whatever the drafts guessed."""
        a = _toy_engine()
        b = _toy_engine(num_draft=4)
        prompts = [[7, 3, 9, 1, 4], [2, 2, 5], [8, 1, 1, 6, 6, 6]]
        ra = [a.submit(p, max_new_tokens=9, seed=100 + i)
              for i, p in enumerate(prompts)]
        rb = [b.submit(p, max_new_tokens=9, seed=100 + i)
              for i, p in enumerate(prompts)]
        a.run(max_steps=80)
        b.run(max_steps=80)
        for x, y in zip(ra, rb):
            np.testing.assert_array_equal(a.results[x].tokens,
                                          b.results[y].tokens)
        assert b.trace_counts == {"prefill": 1, "verify": 1}

    def test_oracle_draft_accepts_everything(self):
        """A draft source that knows the answer (the non-spec engine's
        own output) is fully accepted: accept_rate 1.0 where the
        request's tail still has K tokens to verify, and the whole
        stream lands in ceil((new-1)/(K+1)) verify rounds."""
        a = _toy_engine()
        ra = a.submit([7, 3, 9], max_new_tokens=9, seed=42)
        a.run(max_steps=60)
        want = [int(t) for t in a.results[ra].tokens]
        full = [7, 3, 9] + want

        def oracle(history, k):
            i = len(history) - 3            # tokens emitted so far
            out = (want + [0] * k)[i:i + k]
            return np.asarray(out, np.int32)

        b = _toy_engine(num_draft=2, draft_propose=oracle)
        rb = b.submit([7, 3, 9], max_new_tokens=9, seed=42)
        b.run(max_steps=60)
        np.testing.assert_array_equal(b.results[rb].tokens, want)
        rec = b.metrics.records[rb]
        # 8 post-prefill tokens over K+1=3 per round = 3 rounds; the
        # last round caps emission at the remaining 2, and every draft
        # the verify could reach matched
        assert rec.n_drafted == 6 and rec.n_accepted == 6
        assert rec.accept_rate == 1.0
        assert b.metrics.summary()["accept_rate"] == 1.0

    def test_truncated_final_round_never_inflates_accept_rate(self):
        """Review-finding regression: drafts past the emission window
        (max_new_tokens reached mid-round) are not credited — a
        2-token request under K=4 oracle drafting banks exactly the
        one draft position that could land, not 4."""
        a = _toy_engine()
        ra = a.submit([7, 3, 9], max_new_tokens=2, seed=42)
        a.run(max_steps=20)
        want = [int(t) for t in a.results[ra].tokens]

        def oracle(history, k):
            i = len(history) - 3
            return np.asarray((want + [0] * (k + 2))[i:i + k], np.int32)

        b = _toy_engine(num_draft=4, draft_propose=oracle)
        rb = b.submit([7, 3, 9], max_new_tokens=2, seed=42)
        b.run(max_steps=20)
        np.testing.assert_array_equal(b.results[rb].tokens, want)
        rec = b.metrics.records[rb]
        # one verify round, remaining=1: one usable draft position
        assert rec.n_drafted == 1 and rec.n_accepted == 1
        assert rec.accept_rate == 1.0

    def test_eos_early_stop_matches_nonspec_truncation(self):
        """EOS inside an accepted speculative run retires at exactly
        the non-spec stream's truncation point — tokens past the EOS
        in the same verify round are discarded. (Toy decoder: the
        truncation logic is model-agnostic, and the GPT-2 composition
        is already covered by the staggered greedy pin — no second
        real-model engine compile on the fast gate.)"""
        a = _toy_engine()
        ra = a.submit([4, 2, 7, 7], max_new_tokens=10, seed=77)
        a.run(max_steps=60)
        full = [int(t) for t in a.results[ra].tokens]
        eos = full[3]
        b = _toy_engine(eos_id=eos, num_draft=3)
        rb = b.submit([4, 2, 7, 7], max_new_tokens=10, seed=77)
        b.run(max_steps=60)
        res = b.results[rb]
        assert res.status == "done" and res.reason == "eos"
        want = full[:full.index(eos) + 1]
        np.testing.assert_array_equal(res.tokens, want)


class TestSpecSeedContract:
    def test_resubmission_idempotent_mid_flight(self):
        """The counter-seed contract survives speculation: a spec
        request killed mid-flight and resubmitted (same id, fresh spec
        engine) regenerates the identical stream — and a NON-spec
        engine given the same id produces it too (speculation is not
        part of the stream's identity)."""
        from apex1_tpu.serving import new_request_id
        rid = new_request_id()
        a = _toy_engine(num_draft=3)
        a.submit([5, 1, 2, 8], max_new_tokens=9, req_id=rid)
        a.step(); a.step()                    # mid-flight...
        partial = a.cancel(rid)               # ...the stream dies
        assert partial
        b = _toy_engine(num_draft=3)
        b.submit([5, 1, 2, 8], max_new_tokens=9, req_id=rid)
        b.run(max_steps=60)
        c = _toy_engine()
        c.submit([5, 1, 2, 8], max_new_tokens=9, req_id=rid)
        c.run(max_steps=60)
        np.testing.assert_array_equal(b.results[rid].tokens,
                                      c.results[rid].tokens)
        # the cancelled partial is a strict prefix of the regenerated
        # stream — same contract as non-spec eviction partials
        part = a.results[rid].tokens
        np.testing.assert_array_equal(
            part, b.results[rid].tokens[:part.size])


class TestSpecComposition:
    def test_int8_tier_with_radix_and_spec_token_identical(self):
        """The dtype-flip parity drill extended to the new paths
        (ISSUE 15 satellite): int8 KV pool + radix prefix hits + the
        speculative verify loop, tokens bit-identical to the fp32
        non-spec engine (toy cache values < 128 make int8 exact)."""
        shared = [9, 9, 4, 4, 1, 2, 3, 4, 5]   # >= 2 chunks shared
        tails = [[6, 7], [6, 7], [8]]
        gold = _toy_engine()
        g_ids = [gold.submit(shared + t, max_new_tokens=7,
                             seed=50 + i)
                 for i, t in enumerate(tails)]
        gold.run(max_steps=80)
        q = _toy_engine(num_draft=3, cache_dtype=jnp.int8)
        q_ids = [q.submit(shared + t, max_new_tokens=7, seed=50 + i)
                 for i, t in enumerate(tails)]
        q.run(max_steps=80)
        for gr, qr in zip(g_ids, q_ids):
            np.testing.assert_array_equal(gold.results[gr].tokens,
                                          q.results[qr].tokens)
        s = q.metrics.summary()
        assert s["prefix_hit_rate"] > 0      # the radix path really ran
        # int8 pool really is the half-size tier
        assert q.kv.pool_bytes() * 4 == gold.kv.pool_bytes()

    @pytest.mark.slow  # 870s-cap headroom (~3s): fleet-LEVEL spec
    # composition; the tier-1 pins already cover spec determinism at
    # engine level (TestSpecSeedContract) and fleetsim determinism
    # without spec (test_autopilot) — full run via check_all --all
    def test_fleetsim_episode_with_spec_is_deterministic(self):
        """Fleet-level: the same (trace, seed, spec config) replays to
        a bit-identical fingerprint, and the per-request token digests
        match the non-spec episode's exactly (speculation shifts
        latency, never tokens) — with accept_rate flowing into the
        report."""
        from apex1_tpu.serving import FrontendConfig
        from apex1_tpu.testing.fleetsim import (FleetSimConfig,
                                                run_fleet,
                                                synthetic_trace)
        trace = synthetic_trace("steady", seed=3, horizon_s=2.0,
                                base_rate=12.0)
        fc = dict(n_replicas=2, capacity_per_replica=8,
                  hedge_after_s=None)
        spec = FleetSimConfig(num_draft=2)
        r1 = run_fleet(trace, FrontendConfig(**fc), sim=spec)
        r2 = run_fleet(trace, FrontendConfig(**fc), sim=spec)
        assert r1.fingerprint() == r2.fingerprint()
        base = run_fleet(trace, FrontendConfig(**fc),
                         sim=FleetSimConfig())
        d_spec = {o["idx"]: o["tokens_sha1"] for o in r1.outcomes
                  if o["status"] == "done"}
        d_base = {o["idx"]: o["tokens_sha1"] for o in base.outcomes
                  if o["status"] == "done"}
        shared = set(d_spec) & set(d_base)
        assert shared
        assert all(d_spec[i] == d_base[i] for i in shared)
        assert "accept_rate" in r1.to_json()
