"""KV-cached generation tests (`models.generate`): incremental
prefill+decode must reproduce the full-context forward exactly — token
for token — for GPT-2 (learned positions) and Llama (RoPE + GQA)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.core.policy import get_policy
from apex1_tpu.models.generate import (generate, gpt2_decoder,
                                       llama_decoder, sample_token)
from apex1_tpu.models.gpt2 import GPT2, GPT2Config
from apex1_tpu.models.llama import Llama, LlamaConfig


def _full_forward_greedy(model, params, prompt, n_new, vocab_size=None):
    """Gold: re-run the whole context each step, argmax the last logit."""
    tokens = prompt
    out = []
    for _ in range(n_new):
        logits = model.apply({"params": params}, tokens)[:, -1]
        nxt = sample_token(logits, jax.random.key(0),
                           vocab_size=vocab_size)
        out.append(nxt)
        tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


class TestGPT2Generate:
    @pytest.fixture(scope="class")
    def setup(self):
        cfg = GPT2Config.tiny(policy=get_policy("O0"), max_seq_len=64)
        model = GPT2(cfg)
        rng = np.random.default_rng(5)
        prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 7)),
                             jnp.int32)
        params = model.init(jax.random.key(0), prompt)["params"]
        return cfg, model, params, prompt

    def test_cached_matches_full_forward(self, setup):
        cfg, model, params, prompt = setup
        N = 6
        apply_fn, make_cache = gpt2_decoder(model)
        cache = make_cache(prompt.shape[0], prompt.shape[1] + N)
        got = generate(apply_fn, params, prompt, max_new_tokens=N,
                       cache=cache, vocab_size=cfg.vocab_size)
        want = _full_forward_greedy(model, params, prompt, N,
                                    vocab_size=cfg.vocab_size)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_vocab_mask_excludes_padded_tail(self, setup):
        cfg, model, params, prompt = setup
        apply_fn, make_cache = gpt2_decoder(model)
        cache = make_cache(prompt.shape[0], prompt.shape[1] + 4)
        toks = generate(apply_fn, params, prompt, max_new_tokens=4,
                        cache=cache, vocab_size=cfg.vocab_size)
        assert int(jnp.max(toks)) < cfg.vocab_size

    def test_eos_pads_after(self, setup):
        cfg, model, params, prompt = setup
        N = 6
        apply_fn, make_cache = gpt2_decoder(model)
        cache = make_cache(prompt.shape[0], prompt.shape[1] + N)
        first = generate(apply_fn, params, prompt, max_new_tokens=N,
                         cache=cache, vocab_size=cfg.vocab_size)
        # use the token actually emitted at step 2 of row 0 as the EOS id
        eos = int(first[0, 2])
        got = generate(apply_fn, params, prompt, max_new_tokens=N,
                       cache=make_cache(prompt.shape[0],
                                        prompt.shape[1] + N),
                       vocab_size=cfg.vocab_size, eos_id=eos, pad_id=0)
        row = np.asarray(got[0])
        hits = np.nonzero(row == eos)[0]
        assert hits.size > 0
        assert (row[hits[0] + 1:] == 0).all(), row

    def test_temperature_sampling_reproducible_and_topk1_greedy(
            self, setup):
        cfg, model, params, prompt = setup
        N = 5
        apply_fn, make_cache = gpt2_decoder(model)

        def run(**kw):
            return generate(apply_fn, params, prompt, max_new_tokens=N,
                            cache=make_cache(prompt.shape[0],
                                             prompt.shape[1] + N),
                            vocab_size=cfg.vocab_size, **kw)

        a = run(temperature=0.8, rng=jax.random.key(3))
        b = run(temperature=0.8, rng=jax.random.key(3))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        g = run()
        k1 = run(temperature=0.7, top_k=1, rng=jax.random.key(9))
        np.testing.assert_array_equal(np.asarray(g), np.asarray(k1))


class TestT5Generate:
    @pytest.mark.slow  # 870s-cap headroom (~24s): T5 x generate full
    # cached-decode parity COMPOSITION; halves pinned tier-1 — T5 model
    # kernel parity (test_t5::test_pallas_xla_parity), T5 prefill-vs-
    # uncached parity (test_multi_token_prefill_matches_uncached), and
    # cached-decode parity on gpt2/llama; full run via check_all --all
    def test_cached_decode_matches_full_forward(self):
        from apex1_tpu.models.generate import t5_generate
        from apex1_tpu.models.t5 import T5, T5Config

        cfg = T5Config.tiny(policy=get_policy("O0"))
        model = T5(cfg)
        rng = np.random.default_rng(4)
        enc = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 10)),
                          jnp.int32)
        params = model.init(
            jax.random.key(0), enc,
            jnp.zeros((2, 1), jnp.int32))["params"]
        # N=4 (was 6): the gold loop recompiles per step (context grows),
        # ~6s/step on one core; 4 steps still crosses the
        # prefill->decode boundary and several cache writes
        N = 4
        got = t5_generate(model, params, enc, max_new_tokens=N,
                          dec_start_id=0)
        # gold: grow the decoder context from the start token, full
        # forward each step
        dec = jnp.zeros((2, 1), jnp.int32)
        want = []
        for _ in range(N):
            logits = model.apply({"params": params}, enc, dec)[:, -1]
            nxt = jnp.argmax(logits.astype(jnp.float32),
                             axis=-1).astype(jnp.int32)
            want.append(nxt)
            dec = jnp.concatenate([dec, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(jnp.stack(want, 1)))

    def test_multi_token_prefill_matches_uncached(self):
        """Covers cached_attention's bias-bearing prefill branch (S>1,
        bias set): T5.decode with a 3-token decoder prompt through an
        empty cache must match the uncached decode logits, and the
        filled cache must continue correctly into decode steps."""
        from apex1_tpu.models.generate import init_cache
        from apex1_tpu.models.t5 import T5, T5Config

        cfg = T5Config.tiny(policy=get_policy("O0"))
        model = T5(cfg)
        rng = np.random.default_rng(6)
        enc = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 8)),
                          jnp.int32)
        dec = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 3)),
                          jnp.int32)
        params = model.init(jax.random.key(0), enc, dec)["params"]
        bound = model.bind({"params": params})
        memory = bound.encode(enc)
        cache = init_cache(cfg.num_decoder_layers, 2, cfg.num_heads,
                           6, cfg.head_dim, jnp.float32)
        got, cache = model.apply({"params": params}, dec, memory,
                                 cache=cache, cache_index=0,
                                 method=model.decode)
        want = model.apply({"params": params}, dec, memory,
                           method=model.decode)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
        # continue decoding from the prefilled cache: next-step logits
        # must equal the uncached 4-token decode's last position
        nxt = jnp.argmax(got[:, -1].astype(jnp.float32),
                         axis=-1).astype(jnp.int32)
        step, _ = model.apply({"params": params}, nxt[:, None], memory,
                              cache=cache, cache_index=3,
                              method=model.decode)
        dec4 = jnp.concatenate([dec, nxt[:, None]], axis=1)
        full = model.apply({"params": params}, dec4, memory,
                           method=model.decode)
        np.testing.assert_allclose(np.asarray(step[:, 0]),
                                   np.asarray(full[:, -1]),
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.slow  # 870s-cap headroom (~15s): beam x T5
    # COMPOSITION; halves pinned tier-1 — beam search on gpt2
    # (TestBeamSearch) and T5 decode parity above; check_all --all
    def test_beam_matches_hand_built_beam_path(self):
        """t5_generate(num_beams=K) must equal beam_search driven
        through an INDEPENDENTLY constructed cached-decode closure
        (explicit b-major memory tiling) — catches memory/lane-ordering
        wiring bugs in the adapter, which a K=1 comparison cannot."""
        from apex1_tpu.models.generate import (beam_search, init_cache,
                                               t5_generate)
        from apex1_tpu.models.t5 import T5, T5Config

        cfg = T5Config.tiny(policy=get_policy("O0"))
        model = T5(cfg)
        rng = np.random.default_rng(14)
        B, K, N = 3, 2, 5
        enc = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, 7)),
                          jnp.int32)
        params = model.init(
            jax.random.key(0), enc,
            jnp.zeros((B, 1), jnp.int32))["params"]
        got = t5_generate(model, params, enc, max_new_tokens=N,
                          num_beams=K)

        bound = model.bind({"params": params})
        memory = bound.encode(enc)
        mem_tiled = jnp.repeat(memory, K, axis=0)

        def apply_fn(p, tokens, cache, cache_index):
            mem = memory if tokens.shape[0] == B else mem_tiled
            return model.apply({"params": p}, tokens, mem, cache=cache,
                               cache_index=cache_index,
                               method=model.decode)

        cache = init_cache(cfg.num_decoder_layers, B * K, cfg.num_heads,
                           1 + N, cfg.head_dim, jnp.float32)
        want, _ = beam_search(apply_fn, params,
                              jnp.zeros((B, 1), jnp.int32),
                              max_new_tokens=N, cache=cache, num_beams=K)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        with pytest.raises(ValueError, match="deterministic"):
            t5_generate(model, params, enc, max_new_tokens=N,
                        num_beams=2, temperature=0.5)

    def test_enc_pad_mask_respected(self):
        from apex1_tpu.models.generate import t5_generate
        from apex1_tpu.models.t5 import T5, T5Config

        cfg = T5Config.tiny(policy=get_policy("O0"))
        model = T5(cfg)
        rng = np.random.default_rng(8)
        enc = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 8)),
                          jnp.int32)
        params = model.init(
            jax.random.key(0), enc,
            jnp.zeros((1, 1), jnp.int32))["params"]
        mask = jnp.asarray([[True] * 5 + [False] * 3])
        a = t5_generate(model, params, enc, max_new_tokens=4,
                        enc_pad_mask=mask)
        b = t5_generate(model, params, enc.at[0, 5:].set(3),
                        max_new_tokens=4, enc_pad_mask=mask)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestBeamSearch:
    @pytest.fixture(scope="class")
    def setup(self):
        cfg = GPT2Config.tiny(policy=get_policy("O0"), max_seq_len=64)
        model = GPT2(cfg)
        rng = np.random.default_rng(17)
        prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 5)),
                             jnp.int32)
        params = model.init(jax.random.key(0), prompt)["params"]
        return cfg, model, params, prompt

    def test_beam1_equals_greedy(self, setup):
        from apex1_tpu.models.generate import beam_search
        cfg, model, params, prompt = setup
        N = 6
        apply_fn, make_cache = gpt2_decoder(model)
        greedy = generate(apply_fn, params, prompt, max_new_tokens=N,
                          cache=make_cache(2, 11),
                          vocab_size=cfg.vocab_size)
        beam, _ = beam_search(apply_fn, params, prompt,
                              max_new_tokens=N,
                              cache=make_cache(2 * 1, 11), num_beams=1,
                              vocab_size=cfg.vocab_size)
        np.testing.assert_array_equal(np.asarray(beam),
                                      np.asarray(greedy))

    def test_beam_score_is_sequence_logprob(self, setup):
        """The returned score must EQUAL the returned sequence's summed
        valid-vocab log-prob under the full forward (the sound beam
        invariant — beam-K >= greedy is NOT guaranteed in general, since
        the greedy prefix can be pruned mid-decode)."""
        from apex1_tpu.models.generate import beam_search
        cfg, model, params, prompt = setup
        N = 6
        apply_fn, make_cache = gpt2_decoder(model)
        toks, beam_scores = beam_search(apply_fn, params, prompt,
                                        max_new_tokens=N,
                                        cache=make_cache(2 * 4, 11),
                                        num_beams=4,
                                        vocab_size=cfg.vocab_size)
        full = jnp.concatenate([prompt, toks], axis=1)
        logits = model.apply({"params": params}, full)
        lg = logits[:, prompt.shape[1] - 1:-1].astype(jnp.float32)
        lg = jnp.where(jnp.arange(lg.shape[-1]) < cfg.vocab_size, lg,
                       -1e30)
        lp = jax.nn.log_softmax(lg, -1)
        want = jnp.sum(
            jnp.take_along_axis(lp, toks[..., None], -1)[..., 0], -1)
        np.testing.assert_allclose(np.asarray(beam_scores),
                                   np.asarray(want), rtol=1e-5,
                                   atol=1e-4)

    def test_eos_finished_beams_pad(self, setup):
        """K=1 so the beam follows the greedy path deterministically:
        the eos token (taken from the no-eos run) is guaranteed to
        appear, making the pad-after-eos assertion non-vacuous."""
        from apex1_tpu.models.generate import beam_search
        cfg, model, params, prompt = setup
        N = 6
        apply_fn, make_cache = gpt2_decoder(model)
        first, _ = beam_search(apply_fn, params, prompt,
                               max_new_tokens=N,
                               cache=make_cache(2, 11), num_beams=1,
                               vocab_size=cfg.vocab_size)
        eos = int(first[0, 2])
        toks, _ = beam_search(apply_fn, params, prompt,
                              max_new_tokens=N,
                              cache=make_cache(2, 11), num_beams=1,
                              eos_id=eos, pad_id=0,
                              vocab_size=cfg.vocab_size)
        row = np.asarray(toks[0])
        hits = np.nonzero(row == eos)[0]
        assert hits.size > 0, (row, eos)
        assert (row[hits[0] + 1:] == 0).all(), row


class TestRaggedGenerate:
    """Batched ragged decode (`generate(prompt_lens=...)`): left-aligned
    rows with per-row positions/segment masking must emit EXACTLY the
    tokens each row produces when generated alone at its true length."""

    @pytest.mark.parametrize("family", [
        "gpt2",
        # llama adds GQA x ragged on top of gpt2's contract — GQA cached
        # decode stays tier-1 via TestLlamaGenerate::
        # test_gqa_cached_matches_full_forward; full run via check_all --all
        pytest.param("llama", marks=pytest.mark.slow),
    ])
    def test_rows_match_solo_generation(self, family):
        if family == "gpt2":
            cfg = GPT2Config.tiny(policy=get_policy("O0"), max_seq_len=64)
            model = GPT2(cfg)
            mk = gpt2_decoder
            vocab = cfg.vocab_size
        else:
            cfg = LlamaConfig.tiny(policy=get_policy("O0"),
                                   max_seq_len=64)
            model = Llama(cfg)
            mk = llama_decoder
            vocab = cfg.vocab_size
        rng = np.random.default_rng(21)
        S0, N = 7, 5
        lens = [7, 4, 2]
        prompts = jnp.asarray(rng.integers(1, vocab, (3, S0)), jnp.int32)
        # right-pad: junk beyond each row's length must not matter
        pad_mask = jnp.arange(S0)[None, :] < jnp.asarray(lens)[:, None]
        prompts = jnp.where(pad_mask, prompts, 0)
        params = model.init(jax.random.key(0), prompts)["params"]
        apply_fn, make_cache = mk(model)

        got = generate(apply_fn, params, prompts, max_new_tokens=N,
                       cache=make_cache(3, S0 + N),
                       vocab_size=vocab,
                       prompt_lens=jnp.asarray(lens, jnp.int32))

        for b, ln in enumerate(lens):
            solo = generate(apply_fn, params, prompts[b:b + 1, :ln],
                            max_new_tokens=N,
                            cache=make_cache(1, ln + N),
                            vocab_size=vocab)
            np.testing.assert_array_equal(
                np.asarray(got[b]), np.asarray(solo[0]),
                err_msg=f"{family} row {b} (len {ln}) diverged from its "
                        f"solo decode")

    def _moe_ragged(self, capacity_factor):
        cfg = LlamaConfig.tiny(policy=get_policy("O0"), max_seq_len=64,
                               moe_every=1, num_experts=2, moe_top_k=1,
                               moe_capacity_factor=capacity_factor)
        model = Llama(cfg)
        rng = np.random.default_rng(37)
        S0, lens = 6, [6, 3, 5]
        prompts = np.asarray(rng.integers(1, cfg.vocab_size, (3, S0)),
                             np.int32)
        pad_mask = np.arange(S0)[None, :] < np.asarray(lens)[:, None]
        params = model.init(jax.random.key(0),
                            jnp.asarray(prompts))["params"]
        return cfg, model, params, prompts, pad_mask, lens

    @pytest.mark.slow  # 870s-cap headroom: MoE x ragged pad
    # invariance COMPOSITION (6s generate compile); non-MoE ragged pad
    # invariance + MoE routing stay tier-1, full run via check_all --all
    def test_ragged_moe_pad_content_invariance(self):
        """MoE x ragged (review r5): pad tokens must claim NO expert
        capacity — with a tight capacity factor, a routed pad would
        displace another row's valid token from its expert, so the
        output would depend on pad-slot CONTENT. Two different pad
        garbage fills must decode identically."""
        cfg, model, params, prompts, pad_mask, lens = self._moe_ragged(
            capacity_factor=0.75)
        apply_fn, make_cache = llama_decoder(model)
        N = 5
        outs = []
        for fill in (0, 7):
            p = jnp.asarray(np.where(pad_mask, prompts, fill), jnp.int32)
            outs.append(np.asarray(generate(
                apply_fn, params, p, max_new_tokens=N,
                cache=make_cache(3, prompts.shape[1] + N),
                vocab_size=cfg.vocab_size,
                prompt_lens=jnp.asarray(lens, jnp.int32))))
        np.testing.assert_array_equal(
            outs[0], outs[1],
            err_msg="pad-slot content leaked into MoE ragged decode "
                    "(pads claiming expert capacity?)")

    @pytest.mark.slow  # 4 distinct-shape generate compiles (~28s); tier-1
    # keeps MoE-ragged routing via test_ragged_moe_pad_content_invariance
    # and rows-match-solo via test_rows_match_solo_generation; full run
    # via check_all --all
    def test_ragged_moe_rows_match_solo_decode(self):
        """MoE x ragged with AMPLE capacity (no expert ever overflows,
        so batched-vs-solo capacity coupling vanishes): each row must
        match its solo decode exactly, like the dense-model contract."""
        cfg, model, params, prompts, pad_mask, lens = self._moe_ragged(
            capacity_factor=4.0)
        apply_fn, make_cache = llama_decoder(model)
        N = 5
        p = jnp.asarray(np.where(pad_mask, prompts, 0), jnp.int32)
        got = generate(apply_fn, params, p, max_new_tokens=N,
                       cache=make_cache(3, p.shape[1] + N),
                       vocab_size=cfg.vocab_size,
                       prompt_lens=jnp.asarray(lens, jnp.int32))
        for b, ln in enumerate(lens):
            solo = generate(apply_fn, params, p[b:b + 1, :ln],
                            max_new_tokens=N, cache=make_cache(1, ln + N),
                            vocab_size=cfg.vocab_size)
            np.testing.assert_array_equal(
                np.asarray(got[b]), np.asarray(solo[0]),
                err_msg=f"MoE row {b} (len {ln}) diverged from solo")

    @pytest.mark.slow  # ~15s of MoE generate compiles; MoE-ragged routing
    # stays tier-1 via test_ragged_moe_pad_content_invariance and the
    # prefix-cache contract via TestPrefixCaching::
    # test_continuation_matches_flat_prompt; full run via check_all --all
    def test_moe_prefix_cache_continuation_matches_flat(self):
        """docs/serving.md matrix: MoE x prefix caching — a prefix
        prefilled once through the MoE decoder, continued via
        cache_start, equals the flat decode (ample capacity so the
        prefill-vs-chunk token-count split cannot change drop
        behavior)."""
        cfg, model, params, prompts, pad_mask, lens = self._moe_ragged(
            capacity_factor=4.0)
        apply_fn, make_cache = llama_decoder(model)
        B, Lp, Ls, N = 3, 4, 2, 4
        full = jnp.asarray(np.where(pad_mask, prompts, 1), jnp.int32)
        prefix, suffix = full[:, :Lp], full[:, Lp:Lp + Ls]
        cache0 = make_cache(B, Lp + Ls + N)
        _, cache0 = apply_fn(params, prefix, cache0, 0)
        got = generate(apply_fn, params, suffix, max_new_tokens=N,
                       cache=cache0, cache_start=Lp,
                       vocab_size=cfg.vocab_size)
        want = generate(apply_fn, params, full[:, :Lp + Ls],
                        max_new_tokens=N,
                        cache=make_cache(B, Lp + Ls + N),
                        vocab_size=cfg.vocab_size)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    @pytest.mark.slow  # 870s-cap headroom (~11s): MoE x beam
    # COMPOSITION; halves pinned tier-1 — beam-1==greedy on dense
    # (TestBeamSearch::test_beam1_equals_greedy family) and MoE
    # generate via test_ragged_moe_pad_content_invariance;
    # check_all --all
    def test_moe_beam1_equals_greedy(self):
        """docs/serving.md matrix: MoE x beam — num_beams=1 beam search
        over the MoE decoder reduces to its greedy decode."""
        from apex1_tpu.models.generate import beam_search
        cfg, model, params, prompts, pad_mask, lens = self._moe_ragged(
            capacity_factor=4.0)
        apply_fn, make_cache = llama_decoder(model)
        p = jnp.asarray(np.where(pad_mask, prompts, 1), jnp.int32)
        N = 4
        beam, _ = beam_search(apply_fn, params, p, max_new_tokens=N,
                              cache=make_cache(3, p.shape[1] + N),
                              num_beams=1, vocab_size=cfg.vocab_size)
        greedy = generate(apply_fn, params, p, max_new_tokens=N,
                          cache=make_cache(3, p.shape[1] + N),
                          vocab_size=cfg.vocab_size)
        np.testing.assert_array_equal(np.asarray(beam),
                                      np.asarray(greedy))

    def test_prompt_lens_out_of_range_raises(self):
        cfg = GPT2Config.tiny(policy=get_policy("O0"), max_seq_len=64)
        model = GPT2(cfg)
        prompts = jnp.ones((2, 6), jnp.int32)
        params = model.init(jax.random.key(0), prompts)["params"]
        apply_fn, make_cache = gpt2_decoder(model)
        for bad in ([7, 3], [0, 3]):
            with pytest.raises(ValueError, match="prompt_lens"):
                generate(apply_fn, params, prompts, max_new_tokens=2,
                         cache=make_cache(2, 10),
                         prompt_lens=jnp.asarray(bad, jnp.int32))

    @pytest.mark.slow  # 870s-cap headroom (13s: int8 generate
    # compiles); the pair's halves stay tier-1 (ragged rows-match-solo
    # above, int8 decode parity in test_quantized) and the triple runs
    # via check_all.sh --all
    def test_ragged_composes_with_int8_decode(self):
        """The serving stack's two features must compose: ragged
        generate through the int8 quant decoder, each row token-exact
        vs its solo int8 decode."""
        from apex1_tpu.models.quant_decode import llama_quant_decoder
        cfg = LlamaConfig.tiny(policy=get_policy("O0"), max_seq_len=64)
        model = Llama(cfg)
        rng = np.random.default_rng(31)
        S0, N = 6, 4
        lens = [6, 3]
        prompts = jnp.asarray(rng.integers(1, cfg.vocab_size, (2, S0)),
                              jnp.int32)
        params = model.init(jax.random.key(0), prompts)["params"]
        apply_q, make_cache, qparams = llama_quant_decoder(model, params)
        got = generate(apply_q, qparams, prompts, max_new_tokens=N,
                       cache=make_cache(2, S0 + N),
                       vocab_size=cfg.vocab_size,
                       prompt_lens=jnp.asarray(lens, jnp.int32))
        for b, ln in enumerate(lens):
            solo = generate(apply_q, qparams, prompts[b:b + 1, :ln],
                            max_new_tokens=N,
                            cache=make_cache(1, ln + N),
                            vocab_size=cfg.vocab_size)
            np.testing.assert_array_equal(
                np.asarray(got[b]), np.asarray(solo[0]),
                err_msg=f"int8 ragged row {b} (len {ln}) diverged")

    def test_ragged_eos_per_row_stop(self):
        cfg = GPT2Config.tiny(policy=get_policy("O0"), max_seq_len=64)
        model = GPT2(cfg)
        rng = np.random.default_rng(23)
        S0, N = 6, 6
        lens = jnp.asarray([6, 3], jnp.int32)
        prompts = jnp.asarray(rng.integers(1, cfg.vocab_size, (2, S0)),
                              jnp.int32)
        params = model.init(jax.random.key(0), prompts)["params"]
        apply_fn, make_cache = gpt2_decoder(model)
        first = generate(apply_fn, params, prompts, max_new_tokens=N,
                         cache=make_cache(2, S0 + N),
                         vocab_size=cfg.vocab_size, prompt_lens=lens)
        eos = int(first[1, 1])  # a token row 1 actually emits
        got = generate(apply_fn, params, prompts, max_new_tokens=N,
                       cache=make_cache(2, S0 + N),
                       vocab_size=cfg.vocab_size, prompt_lens=lens,
                       eos_id=eos, pad_id=0)
        row = np.asarray(got[1])
        hits = np.nonzero(row == eos)[0]
        assert hits.size > 0
        assert (row[hits[0] + 1:] == 0).all(), row


class TestPrefixCaching:
    """`generate(cache_start=L)`: prefill a shared prefix once, continue
    many generations from it — tokens must equal the flat (prefix +
    prompt in one go) decode exactly."""

    # llama variant to @slow for 870s-cap headroom (~19s): prefix-cache
    # x llama COMPOSITION; halves pinned tier-1 — the gpt2 variant
    # (same prefix machinery) and llama GQA cached-decode parity
    # (TestLlamaGenerate); full run via check_all --all
    @pytest.mark.parametrize("family", [
        "gpt2", pytest.param("llama", marks=pytest.mark.slow)])
    def test_continuation_matches_flat_prompt(self, family):
        if family == "gpt2":
            cfg = GPT2Config.tiny(policy=get_policy("O0"), max_seq_len=64)
            model, mk = GPT2(cfg), gpt2_decoder
        else:
            cfg = LlamaConfig.tiny(policy=get_policy("O0"),
                                   max_seq_len=64)
            model, mk = Llama(cfg), llama_decoder
        rng = np.random.default_rng(41)
        B, Lp, Ls, N = 2, 6, 4, 5
        prefix = jnp.asarray(rng.integers(1, cfg.vocab_size, (B, Lp)),
                             jnp.int32)
        suffixes = jnp.asarray(rng.integers(1, cfg.vocab_size,
                                            (2, B, Ls)), jnp.int32)
        params = model.init(jax.random.key(0), prefix)["params"]
        apply_fn, make_cache = mk(model)

        # the shared prefix is prefilled ONCE
        cache0 = make_cache(B, Lp + Ls + N)
        _, cache0 = apply_fn(params, prefix, cache0, 0)

        for s in range(2):  # two different continuations off one prefix
            got = generate(apply_fn, params, suffixes[s],
                           max_new_tokens=N,
                           cache=jax.tree_util.tree_map(
                               lambda c: c, cache0),
                           cache_start=Lp, vocab_size=cfg.vocab_size)
            flat = jnp.concatenate([prefix, suffixes[s]], axis=1)
            want = generate(apply_fn, params, flat, max_new_tokens=N,
                            cache=make_cache(B, Lp + Ls + N),
                            vocab_size=cfg.vocab_size)
            np.testing.assert_array_equal(
                np.asarray(got), np.asarray(want),
                err_msg=f"{family} continuation {s} diverged from flat")

    def test_chained_generate_via_return_cache(self):
        """generate(return_cache=True) hands back a cache positioned for
        a further continuation: two chained calls must reproduce one
        longer call exactly (greedy)."""
        cfg = LlamaConfig.tiny(policy=get_policy("O0"), max_seq_len=64)
        model = Llama(cfg)
        rng = np.random.default_rng(43)
        B, S0, N1, N2 = 2, 5, 4, 4
        prompt = jnp.asarray(rng.integers(1, cfg.vocab_size, (B, S0)),
                             jnp.int32)
        params = model.init(jax.random.key(0), prompt)["params"]
        apply_fn, make_cache = llama_decoder(model)

        t1, cache = generate(apply_fn, params, prompt,
                             max_new_tokens=N1,
                             cache=make_cache(B, S0 + N1 + 1 + N2),
                             vocab_size=cfg.vocab_size,
                             return_cache=True)
        # the final emitted token was never FED (its K/V is not cached):
        # it is the continuation's one-token prompt
        t2 = generate(apply_fn, params, t1[:, -1:], max_new_tokens=N2,
                      cache=cache, cache_start=S0 + N1 - 1,
                      vocab_size=cfg.vocab_size)
        want = generate(apply_fn, params, prompt,
                        max_new_tokens=N1 + N2,
                        cache=make_cache(B, S0 + N1 + N2),
                        vocab_size=cfg.vocab_size)
        got = jnp.concatenate([t1, t2], axis=1)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_undersized_cache_raises(self):
        cfg = GPT2Config.tiny(policy=get_policy("O0"), max_seq_len=64)
        model = GPT2(cfg)
        prompts = jnp.ones((2, 4), jnp.int32)
        params = model.init(jax.random.key(0), prompts)["params"]
        apply_fn, make_cache = gpt2_decoder(model)
        with pytest.raises(ValueError, match="cache holds"):
            generate(apply_fn, params, prompts, max_new_tokens=8,
                     cache=make_cache(2, 6))  # 4 + 8 > 6

    def test_incompatible_with_ragged(self):
        cfg = GPT2Config.tiny(policy=get_policy("O0"), max_seq_len=64)
        model = GPT2(cfg)
        prompts = jnp.ones((2, 4), jnp.int32)
        params = model.init(jax.random.key(0), prompts)["params"]
        apply_fn, make_cache = gpt2_decoder(model)
        with pytest.raises(ValueError, match="cache_start"):
            generate(apply_fn, params, prompts, max_new_tokens=2,
                     cache=make_cache(2, 12), cache_start=3,
                     prompt_lens=jnp.asarray([4, 2]))
        # the PRODUCTION side of the same hole: a ragged-produced cache
        # carries garbage left-pad K/V a continuation would attend
        with pytest.raises(ValueError, match="return_cache"):
            generate(apply_fn, params, prompts, max_new_tokens=2,
                     cache=make_cache(2, 12),
                     prompt_lens=jnp.asarray([4, 2]), return_cache=True)


class TestBeamLengthPenalty:
    """ADVICE r3: in-beam pruning must use the SAME GNMT length-normalized
    metric as final selection. A table-driven Markov machine where the two
    rankings provably diverge: a finished short beam out-SUMS two longer
    live candidates at the critical step, but both out-NORM it at
    length_penalty=3 — pure-sum pruning would evict the eventual
    normalized winner."""

    def test_norm_ranked_winner_survives_pruning(self):
        from apex1_tpu.models.generate import beam_search
        V, eos = 5, 4
        P = np.full((V, V), 1e-3)
        P[3] = [.004, .62, .37, .002, .004]   # prompt token / X's last
        P[1] = [.132, .132, .132, .004, .6]   # -> eos .6 (finishes F1)
        P[2] = [.5, .17, .165, .155, .01]     # -> token0 .5 (Y's step)
        P[0] = [.003, .003, .002, .45, .497]  # -> token3/eos (F2 vs X)
        P /= P.sum(axis=1, keepdims=True)
        logP = jnp.asarray(np.log(P), jnp.float32)

        def apply_fn(params, tokens, cache, cache_index):
            logits = logP[tokens[:, -1]][:, None, :]
            return logits, cache

        prompt = jnp.full((1, 1), 3, jnp.int32)
        cache = {"x": jnp.zeros((2, 1))}  # B*K lanes, shape-agnostic
        toks, score = beam_search(apply_fn, None, prompt,
                                  max_new_tokens=4, cache=cache,
                                  num_beams=2, length_penalty=3.0,
                                  eos_id=eos, pad_id=0)
        # winner: [2,0,3,1] (len 4, sum ln.37+ln.5+ln.45+ln.62, /4^3);
        # sum-ranking would have returned the F2 path [2,0,4,...] instead
        np.testing.assert_array_equal(np.asarray(toks[0]), [2, 0, 3, 1])
        want = (np.log(P[3][2]) + np.log(P[2][0]) + np.log(P[0][3])
                + np.log(P[3][1])) / 4.0 ** 3
        np.testing.assert_allclose(float(score[0]), want, rtol=1e-5)

    def test_zero_penalty_keeps_pure_sum_ranking(self):
        """length_penalty=0 must stay the documented pure-sum ranking:
        the same machine then keeps and returns the best-sum finished
        beam (F2's eos path), not the normalized winner."""
        from apex1_tpu.models.generate import beam_search
        V, eos = 5, 4
        P = np.full((V, V), 1e-3)
        P[3] = [.004, .62, .37, .002, .004]
        P[1] = [.132, .132, .132, .004, .6]
        P[2] = [.5, .17, .165, .155, .01]
        P[0] = [.003, .003, .002, .45, .497]
        P /= P.sum(axis=1, keepdims=True)
        logP = jnp.asarray(np.log(P), jnp.float32)

        def apply_fn(params, tokens, cache, cache_index):
            return logP[tokens[:, -1]][:, None, :], cache

        prompt = jnp.full((1, 1), 3, jnp.int32)
        toks, score = beam_search(apply_fn, None, prompt,
                                  max_new_tokens=4,
                                  cache={"x": jnp.zeros((2, 1))},
                                  num_beams=2, length_penalty=0.0,
                                  eos_id=eos, pad_id=0)
        # pure sums: F1 = [1, eos] (ln.62 + ln.6) beats every longer path
        np.testing.assert_array_equal(np.asarray(toks[0]), [1, eos, 0, 0])


class TestSampleTokenGuards:
    """ADVICE r3: top_k bounds."""

    def test_top_k_exceeding_vocab_clamps_to_valid_width(self):
        rng = np.random.default_rng(3)
        logits = jnp.asarray(rng.normal(size=(4, 16)), jnp.float32)
        a = sample_token(logits, jax.random.key(0), temperature=0.9,
                         top_k=999, vocab_size=10)
        b = sample_token(logits, jax.random.key(0), temperature=0.9,
                         top_k=10, vocab_size=10)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert int(jnp.max(a)) < 10  # masked tail never sampled

    def test_top_k_below_one_raises(self):
        logits = jnp.zeros((2, 8), jnp.float32)
        with pytest.raises(ValueError, match="top_k"):
            sample_token(logits, jax.random.key(0), temperature=1.0,
                         top_k=0)


class TestCachedAttentionGuards:
    """ADVICE r3: prefill from a non-empty cache must fail fast when the
    index is concrete."""

    def test_prefill_nonzero_concrete_index_raises(self):
        from apex1_tpu.models.generate import cached_attention, init_cache
        cache = init_cache(1, 1, 2, 16, 8)["layer0"]
        q = jnp.zeros((1, 2, 4, 8), jnp.bfloat16)
        with pytest.raises(ValueError, match="empty cache"):
            cached_attention(q, q, q, cache, 3)

    def test_prefill_zero_index_ok(self):
        from apex1_tpu.models.generate import cached_attention, init_cache
        cache = init_cache(1, 1, 2, 16, 8)["layer0"]
        q = jnp.ones((1, 2, 4, 8), jnp.bfloat16)
        attn, entry = cached_attention(q, q, q, cache, 0)
        assert attn.shape == (1, 2, 4, 8)


class TestLlamaGenerate:
    def test_gqa_cached_matches_full_forward(self):
        cfg = LlamaConfig.tiny(policy=get_policy("O0"), max_seq_len=64)
        assert cfg.num_kv_heads < cfg.num_heads  # GQA decode path
        model = Llama(cfg)
        rng = np.random.default_rng(9)
        prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 5)),
                             jnp.int32)
        params = model.init(jax.random.key(0), prompt)["params"]
        N = 6
        apply_fn, make_cache = llama_decoder(model)
        cache = make_cache(prompt.shape[0], prompt.shape[1] + N)
        got = generate(apply_fn, params, prompt, max_new_tokens=N,
                       cache=cache)
        want = _full_forward_greedy(model, params, prompt, N)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_tp_sharded_generate_matches_unsharded(self, devices):
        """Distributed inference: the same generate() loop under a tp=2
        mesh with Megatron param shardings (GSPMD inserts the
        collectives) must emit the same tokens as the single-device
        run."""
        import functools

        from jax.sharding import NamedSharding

        from apex1_tpu.core.mesh import make_mesh
        from apex1_tpu.models.llama import param_specs

        cfg = LlamaConfig.tiny(policy=get_policy("O0"), max_seq_len=32)
        model = Llama(cfg)
        rng = np.random.default_rng(21)
        prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 4)),
                             jnp.int32)
        params = model.init(jax.random.key(0), prompt)["params"]
        apply_fn, make_cache = llama_decoder(model)
        N = 5
        want = generate(apply_fn, params, prompt, max_new_tokens=N,
                        cache=make_cache(2, 9))

        mesh = make_mesh(tp=2)
        specs = param_specs(params)
        params_sh = jax.tree_util.tree_map(
            lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
            params, specs)
        gen = jax.jit(functools.partial(generate, apply_fn,
                                        max_new_tokens=N))
        got = gen(params_sh, prompt, cache=make_cache(2, 9))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_generate_is_jittable_one_dispatch(self):
        import functools
        cfg = LlamaConfig.tiny(policy=get_policy("O0"), max_seq_len=32)
        model = Llama(cfg)
        rng = np.random.default_rng(2)
        prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 4)),
                             jnp.int32)
        params = model.init(jax.random.key(0), prompt)["params"]
        apply_fn, make_cache = llama_decoder(model)
        gen = jax.jit(functools.partial(generate, apply_fn,
                                        max_new_tokens=5))
        toks = gen(params, prompt, cache=make_cache(1, 9))
        assert toks.shape == (1, 5)
        toks2 = generate(apply_fn, params, prompt, max_new_tokens=5,
                         cache=make_cache(1, 9))
        np.testing.assert_array_equal(np.asarray(toks), np.asarray(toks2))
