"""PR 13 — telemetry-driven fleet autopilot + replayable fleet
simulator + perf-drift tripwire.

The headline drill (module fixture, shared by every assertion): on a
replayed adversarial-overload trace, EVERY static threshold-ladder
config in the stated sweep misses guaranteed-class SLO attainment
while the autopilot — same baseline provisioning, same (trace, seed) —
holds it; the full actuation history is reconstructable from banked
events; and the episode replays bit-identically. Around it: the
rolling-window metrics satellite, the frontend knob surface, the pure
policy hysteresis/ladder, simulator determinism under chaos, and the
jax-free drift gate's three exit codes.
"""

import importlib.util
import json
import pathlib

import numpy as np
import pytest

from apex1_tpu.autopilot import (Autopilot, AutopilotConfig,
                                 ControllerState, FleetView, SLOTarget,
                                 decide, drill)
from apex1_tpu.serving import (Backpressure, FrontendConfig,
                               ReplicaConfig, ServingFrontend,
                               ServingMetrics)
from apex1_tpu.testing.fleetsim import (FleetSimConfig, Trace,
                                        VirtualClock, run_fleet,
                                        synthetic_trace)

_REPO = pathlib.Path(__file__).resolve().parents[1]


@pytest.fixture(scope="module")
def headline():
    """ONE run of the full drill (3 static arms + the autopilot arm);
    every headline assertion reads from it."""
    return drill.run_headline()


# ---------------------------------------------------------------------------
# satellite: rolling-window per-class percentiles
# ---------------------------------------------------------------------------


class TestWindowMetrics:
    @staticmethod
    def _terminal(m, rid, t0, dt, *, qos, status="done", tenant=None):
        m.event(rid, "queued", now=t0, qos=qos, tenant=tenant)
        m.event(rid, "first_token", now=t0 + dt / 2)
        m.event(rid, status, now=t0 + dt)

    def test_window_diverges_from_whole_run_after_load_shift(self):
        """The satellite's point: whole-run percentiles freeze late
        signal under early history; the ring does not. 20 slow
        guaranteed requests then 8 fast ones — whole-run p99 stays
        ~2 s, the 8-deep window reads the NEW regime (~0.1 s)."""
        m = ServingMetrics(window=8)
        for i in range(20):
            self._terminal(m, i, float(i), 2.0, qos="guaranteed")
        for i in range(20, 28):
            self._terminal(m, i, float(i), 0.1, qos="guaranteed")
        s = m.summary()
        assert s["latency_p99_ms"] > 1500.0          # frozen history
        w = s["window"]
        assert w["size"] == 8
        g = w["per_class"]["guaranteed"]
        assert g["n"] == 8 and g["done"] == 8
        assert g["latency_p99_ms"] < 200.0           # live signal
        assert g["ttft_p99_ms"] < 100.0

    def test_window_separates_classes_and_tenants(self):
        m = ServingMetrics(window=32)
        for i in range(6):
            self._terminal(m, i, float(i), 0.5, qos="guaranteed",
                           tenant="acme")
        for i in range(6, 10):
            self._terminal(m, i, float(i), 3.0, qos="sheddable",
                           tenant="zeta",
                           status="evicted" if i % 2 else "done")
        w = m.summary()["window"]
        assert w["per_class"]["guaranteed"]["done"] == 6
        assert w["per_class"]["sheddable"]["n"] == 4
        assert w["per_class"]["sheddable"]["done"] == 2
        assert w["per_class"]["guaranteed"]["latency_p99_ms"] \
            < w["per_class"]["sheddable"]["latency_p99_ms"]
        assert set(w["per_tenant"]) == {"acme", "zeta"}
        # tenant stats are TTFT-only (they feed the hedge-budget fit)
        assert "latency_p99_ms" not in w["per_tenant"]["acme"]

    def test_whole_run_fields_unchanged_by_ring(self):
        """Whole-run keys keep their meaning and presence."""
        m = ServingMetrics(window=2)
        for i in range(5):
            self._terminal(m, i, float(i), 1.0, qos="best_effort")
        s = m.summary()
        assert s["requests"] == 5 and s["done"] == 5
        assert s["window"]["size"] == 2  # ring clamped, run fields not

    def test_rejections_hit_done_rate_not_latency_percentiles(self):
        """A refusal is terminal at its queued instant. It must count
        against the windowed done-rate (the signal that sees
        admission-induced misses) WITHOUT contributing a fake 0.0 s
        latency that would deflate the percentiles — under a rejection
        flood, a latency-only SLO must not read 'excellent' (review
        finding)."""
        m = ServingMetrics(window=16)
        for i in range(4):
            self._terminal(m, i, float(i), 2.0, qos="guaranteed")
        for i in range(4, 12):                  # flood of refusals
            m.event(i, "queued", now=float(i), qos="guaranteed")
            m.event(i, "rejected", now=float(i), reason="capacity")
        s = m.summary()
        g = s["window"]["per_class"]["guaranteed"]
        assert g["n"] == 12 and g["done"] == 4  # done-rate sees them
        assert g["latency_p99_ms"] > 1500.0     # percentiles do not
        assert s["latency_p99_ms"] > 1500.0     # whole-run likewise


# ---------------------------------------------------------------------------
# the frontend knob surface
# ---------------------------------------------------------------------------


def _never_build():
    raise AssertionError("engine must not be built in this test")


class TestFrontendKnobs:
    def test_admission_limit_caps_capacity_and_is_banked(self):
        clock = VirtualClock()
        front = ServingFrontend(
            _never_build,
            FrontendConfig(n_replicas=2, capacity_per_replica=8,
                           hedge_after_s=None),
            clock=clock)
        assert front.capacity == 16
        front.set_admission_limit(2, by="test", why="fit")
        assert front.capacity == 2
        front.submit([1, 2], max_new_tokens=4, req_id=0)
        front.submit([1, 2], max_new_tokens=4, req_id=1)
        with pytest.raises(Backpressure):
            front.submit([1, 2], max_new_tokens=4, req_id=2)
        front.set_admission_limit(None, by="test")
        assert front.capacity == 16
        front.submit([1, 2], max_new_tokens=4, req_id=3)
        lims = [t for t in front.metrics.transitions
                if t["event"] == "admission_limit"]
        assert [t["limit"] for t in lims] == [2, None]
        assert lims[0]["by"] == "test" and lims[0]["why"] == "fit"
        # the refusal joined the lifecycle stream: the window sees
        # admission-induced misses the latency percentiles cannot
        w = front.metrics.summary()["window"]
        assert w["per_class"]["best_effort"]["n"] == 1
        assert w["per_class"]["best_effort"]["done"] == 0

    def test_external_mode_control_disables_load_ladder(self):
        front = ServingFrontend(
            _never_build,
            FrontendConfig(n_replicas=1, capacity_per_replica=4,
                           mode_control="external", sustain_rounds=1,
                           hedge_after_s=None),
            clock=VirtualClock())
        for i in range(4):     # 100% load fraction, sustained
            front.submit([1], max_new_tokens=2, req_id=i)
        for _ in range(5):
            front._update_mode()
        assert front.mode == "normal"    # ladder is off
        front.set_mode("shedding", by="autopilot",
                       evidence={"breaches": ["x"]})
        assert front.mode == "shedding"
        flip = [t for t in front.metrics.transitions
                if t["event"] == "mode"][-1]
        assert flip["by"] == "autopilot" and flip["to"] == "shedding"
        assert flip["evidence"] == {"breaches": ["x"]}
        with pytest.raises(ValueError):
            front.set_mode("panic")
        with pytest.raises(ValueError):
            ServingFrontend(_never_build,
                            FrontendConfig(mode_control="bogus"))

    def test_attach_flips_only_this_frontend_not_shared_config(self):
        """Attaching an Autopilot must not mutate the (possibly
        shared) FrontendConfig: a sibling frontend built from the same
        config keeps its load ladder (review finding)."""
        cfg = FrontendConfig(n_replicas=1, capacity_per_replica=4,
                             hedge_after_s=None)
        fa = ServingFrontend(_never_build, cfg, clock=VirtualClock())
        fb = ServingFrontend(_never_build, cfg, clock=VirtualClock())
        Autopilot(fa, AutopilotConfig())
        assert fa.mode_control == "external"
        assert fb.mode_control == "load"      # sibling unaffected
        assert cfg.mode_control == "load"     # config untouched

    def test_retire_replica_unknown_id_is_none_not_a_crash(self):
        """A stale or negative explicit id (replayed from a banked
        transition of another episode) is 'nothing retirable', never
        an IndexError or an alias-from-the-end drain."""
        front = ServingFrontend(
            _never_build,
            FrontendConfig(n_replicas=2, capacity_per_replica=4,
                           hedge_after_s=None),
            clock=VirtualClock())
        assert front.retire_replica(99) is None
        assert front.retire_replica(-1) is None
        assert front.n_alive == 2             # nothing drained

    def test_hedge_budget_per_tenant_resolution(self):
        front = ServingFrontend(
            _never_build,
            FrontendConfig(n_replicas=1, hedge_after_s=0.25),
            clock=VirtualClock())
        assert front._hedge_budget_for("acme") == 0.25   # static cfg
        front.set_hedge_budget(0.5, by="autopilot")      # fitted default
        front.set_hedge_budget(0.1, tenant="acme", by="autopilot")
        front.set_hedge_budget(None, tenant="zeta")      # disabled
        assert front._hedge_budget_for("acme") == 0.1
        assert front._hedge_budget_for("zeta") is None
        assert front._hedge_budget_for("other") == 0.5
        banked = [t for t in front.metrics.transitions
                  if t["event"] == "hedge_budget"]
        assert [(t["tenant"], t["budget_s"]) for t in banked] == \
            [(None, 0.5), ("acme", 0.1), ("zeta", None)]

    def test_add_and_retire_replica_drains_then_stops(self):
        from apex1_tpu.serving import Engine, EngineConfig
        from apex1_tpu.testing.chaos import toy_decoder

        apply_fn, make_cache, params = toy_decoder()
        ecfg = EngineConfig(max_slots=2, max_len=32, prefill_chunk=4,
                            vocab_size=61, seed=3)
        clock = VirtualClock()
        front = ServingFrontend(
            lambda: Engine(apply_fn, make_cache, params, ecfg),
            FrontendConfig(n_replicas=1, capacity_per_replica=8,
                           hedge_after_s=None,
                           replica=ReplicaConfig(watchdog_s=1e9)),
            clock=clock)
        assert front.retire_replica() is None    # never below one
        rid2 = front.add_replica(by="autopilot")
        assert rid2 == 1 and front.n_alive == 2
        assert front.capacity == 16
        r0 = front.submit([1, 2, 3], max_new_tokens=4, req_id=100)
        front.pump(1)                            # route + admit work
        clock.advance(0.01)
        got = front.retire_replica(by="autopilot")
        assert got is not None
        assert front.n_alive == 1                # no new routes to it
        front.run_until_drained(timeout_s=60.0)
        for _ in range(3):
            front.pump(1)                        # let retirement land
        assert front.poll(r0).status == "done"
        summ = front.summary()
        assert summ["replicas"][got]["state"] == "stopped"
        assert not summ["replicas"][got]["retiring"]
        events = [t["event"] for t in front.metrics.transitions]
        assert "replica_added" in events
        assert "replica_retiring" in events
        assert "replica_retired" in events
        assert summ["n_replicas"] == 2 and summ["n_alive"] == 1
        # the retired supervisor stays (ids are route indices) but its
        # engine must not: a scale_up/scale_down cycle that pinned a
        # KV cache per retirement would leak the fleet's memory
        assert front.replicas[got].engine is None

    def test_summary_schema_has_control_surface(self, headline):
        """The satellite: summary() is ONE structured dict carrying
        mode history + per-replica restart/hedge/shed counters
        (docs/serving.md § Frontend summary)."""
        s = headline.auto.summary
        for key in ("mode", "mode_history", "n_replicas", "n_alive",
                    "capacity", "inflight", "load_fraction",
                    "admission_limit", "hedge_budgets", "window",
                    "counters", "replicas"):
            assert key in s, key
        for rep in s["replicas"].values():
            for key in ("state", "restarts", "generation",
                        "engines_built", "steps", "load", "retiring",
                        "hedges", "sheds"):
                assert key in rep, key
        assert all(t["event"] == "mode" for t in s["mode_history"])


# ---------------------------------------------------------------------------
# pure policy: hysteresis, ladder order, fits
# ---------------------------------------------------------------------------


def _view(**over) -> FleetView:
    base = dict(mode="normal", load_fraction=0.5, inflight=8,
                capacity=16, n_replicas=2, n_alive=2,
                admission_limit=None,
                window={"guaranteed": {
                    "n": 20, "done": 20, "latency_p99_ms": 100.0}},
                per_tenant={})
    base.update(over)
    return FleetView(**base)


def _breach_view(**over):
    return _view(window={"guaranteed": {
        "n": 20, "done": 20, "latency_p99_ms": 5000.0}}, **over)


def _cfg(**over) -> AutopilotConfig:
    kw = dict(slo={"guaranteed": SLOTarget(latency_p99_ms=1000.0,
                                           success_rate=0.9)},
              min_replicas=2, max_replicas=4, breach_sustain=3,
              clear_sustain=4, cooldown_ticks=2, min_window=8,
              fit_hedge=False)
    kw.update(over)
    return AutopilotConfig(**kw)


class TestPolicy:
    def test_no_evidence_freezes_instead_of_clearing(self):
        """An evidence-free tick is NOT a "clear" tick: with every
        SLO'd class below min_window (e.g. guaranteed entries crowded
        out of the shared ring by sheddable churn mid-overload), the
        controller must freeze — relaxing the admission limit or
        de-escalating on zero evidence walks straight back into the
        overload (review finding)."""
        cfg, st = _cfg(), ControllerState()
        blind = _view(mode="degraded", admission_limit=4,
                      window={"guaranteed": {"n": 2, "done": 2}})
        for _ in range(cfg.clear_sustain * 3):
            assert decide(blind, st, cfg) == []
        assert st.clear_ticks == 0 and st.breach_ticks == 0
        # evidence returns clean -> relaxation resumes normally
        clear = _view(mode="degraded", admission_limit=4)
        acts = []
        for _ in range(cfg.clear_sustain):
            acts += decide(clear, st, cfg)
        assert [a.kind for a in acts] == ["set_admission"]

    def test_sub_sustain_breach_never_actuates(self):
        """Anti-flap, rung zero: a breach shorter than breach_sustain
        produces NOTHING, however severe."""
        cfg, st = _cfg(), ControllerState()
        for _ in range(cfg.breach_sustain - 1):
            assert decide(_breach_view(), st, cfg) == []
        assert decide(_view(), st, cfg) == []        # burst over
        assert st.breach_ticks == 0
        for _ in range(cfg.breach_sustain - 1):      # second burst:
            assert decide(_breach_view(), st, cfg) == []   # no carry

    def test_thin_evidence_never_actuates(self):
        cfg, st = _cfg(), ControllerState()
        thin = _view(window={"guaranteed": {
            "n": 3, "done": 0, "latency_p99_ms": 9000.0}})
        for _ in range(10):
            assert decide(thin, st, cfg) == []

    def test_escalation_ladder_order_and_cooldown(self):
        """Sustained breach walks shed → scale → scale → degrade →
        admission, one rung per cooldown window, evidence attached."""
        cfg, st = _cfg(), ControllerState()
        view = _breach_view()
        kinds = []
        for _ in range(60):
            acts = decide(view, st, cfg)
            for a in acts:
                kinds.append(a.kind)
                assert a.evidence["breaches"], "evidence required"
                if a.kind == "escalate":
                    view = _breach_view(mode=a.params["mode"],
                                        n_alive=view.n_alive)
                elif a.kind == "scale_up":
                    view = _breach_view(mode=view.mode,
                                        n_alive=view.n_alive + 1)
                elif a.kind == "set_admission":
                    view = _breach_view(
                        mode=view.mode, n_alive=view.n_alive,
                        admission_limit=a.params["limit"])
            if kinds and kinds[-1] == "set_admission":
                break
        assert kinds == ["escalate", "scale_up", "scale_up",
                         "escalate", "set_admission"]

    def test_relaxation_unwinds_in_reverse_and_needs_headroom(self):
        cfg = _cfg()
        st = ControllerState()
        view = _view(mode="degraded", n_alive=4, admission_limit=10,
                     load_fraction=0.2)
        kinds = []
        for _ in range(80):
            for a in decide(view, st, cfg):
                kinds.append((a.kind, a.params.get("mode")))
                if a.kind == "set_admission":
                    view = _view(mode=view.mode, n_alive=view.n_alive,
                                 admission_limit=None,
                                 load_fraction=0.2)
                elif a.kind == "deescalate":
                    view = _view(mode=a.params["mode"],
                                 n_alive=view.n_alive,
                                 load_fraction=0.2)
                elif a.kind == "scale_down":
                    view = _view(mode=view.mode,
                                 n_alive=view.n_alive - 1,
                                 load_fraction=0.2)
            if view.mode == "normal" and view.n_alive == 2:
                break
        assert kinds == [("set_admission", None),
                         ("deescalate", "shedding"),
                         ("scale_down", None), ("scale_down", None),
                         ("deescalate", "normal")]
        # and NO scale-down without percentile headroom, however low
        # the load: clear ticks accumulate but capacity stays
        st2 = ControllerState()
        tight = _view(n_alive=4, load_fraction=0.1,
                      window={"guaranteed": {
                          "n": 20, "done": 20,
                          "latency_p99_ms": 800.0}})  # > 0.5 * target
        for _ in range(20):
            assert decide(tight, st2, cfg) == []

    def test_success_rate_breach_detected(self):
        """The admission-miss dimension: healthy latency, rotten
        done-rate — the exact signature a hard overload shows through
        a rejecting front door."""
        cfg, st = _cfg(), ControllerState()
        v = _view(window={"guaranteed": {
            "n": 40, "done": 20, "latency_p99_ms": 100.0}})
        acts = []
        for _ in range(cfg.breach_sustain):
            acts = decide(v, st, cfg)
        assert [a.kind for a in acts] == ["escalate"]
        b = acts[0].evidence["breaches"]
        assert b[0]["metric"] == "success_rate"
        assert b[0]["value"] == 0.5

    def test_hedge_fit_from_tenant_ttft(self):
        cfg = _cfg(fit_hedge=True, fit_every=1, hedge_multiplier=3.0,
                   hedge_floor_s=0.05)
        st = ControllerState()
        v = _view(per_tenant={"acme": {"n": 20, "ttft_p99_ms": 100.0},
                              "thin": {"n": 2, "ttft_p99_ms": 9.0}})
        acts = decide(v, st, cfg)
        assert [(a.kind, a.params["tenant"]) for a in acts] == \
            [("fit_hedge", "acme")]
        assert acts[0].params["budget_s"] == pytest.approx(0.3)
        assert decide(v, st, cfg) == []   # unchanged ⇒ no re-emit
        v2 = _view(per_tenant={"acme": {"n": 20,
                                        "ttft_p99_ms": 500.0}})
        assert [a.params["budget_s"] for a in decide(v2, st, cfg)] \
            == [pytest.approx(1.5)]


# ---------------------------------------------------------------------------
# simulator determinism (+ chaos composition) and the traces
# ---------------------------------------------------------------------------


def _small_sim(seed=11, autopilot=True, chaos=True):
    from apex1_tpu.testing.chaos import kill_schedule

    trace = synthetic_trace("bursty", seed=seed, horizon_s=2.5,
                            base_rate=20.0)
    return run_fleet(
        trace, drill.frontend_config(),
        sim=drill.sim_config(),
        autopilot=drill.autopilot_config(fit_hedge=True)
        if autopilot else None,
        chaos=kill_schedule(seed=seed, n_replicas=2, lo=5, hi=40)
        if chaos else None)


class TestSimulatorDeterminism:
    def test_same_trace_seed_bit_identical_with_chaos(self):
        """THE determinism pin: same (trace, seed) — autopilot on,
        replica kill mid-episode — twice, bit-identical transition
        history AND token streams (the fingerprint hashes both)."""
        a, b = _small_sim(), _small_sim()
        assert a.transitions == b.transitions
        assert a.outcomes == b.outcomes
        assert a.actions == b.actions
        assert a.fingerprint() == b.fingerprint()
        # the kill really happened and was recovered
        events = [t["event"] for t in a.transitions]
        assert "replica_dead" in events and "replica_restart" in events

    def test_different_seed_differs(self):
        assert _small_sim(seed=12, chaos=False).fingerprint() \
            != _small_sim(seed=13, chaos=False).fingerprint()

    def test_single_token_requests_get_ttft(self):
        """A request whose first token and terminal result land in the
        same supervision round still gets its first_token stamp —
        TTFT percentiles (and the hedge-budget fit they feed) must not
        systematically exclude the FASTEST requests (review finding:
        collection used to pop them from the live set before the TTFT
        probe ran)."""
        trace = synthetic_trace("steady", seed=3, horizon_s=2.0,
                                base_rate=10.0, new_tokens=(1, 1))
        rep = run_fleet(trace, drill.frontend_config(),
                        sim=drill.sim_config())
        done = [o for o in rep.outcomes if o["status"] == "done"]
        assert done and all(o["ttft"] is not None for o in done)

    def test_trace_save_load_replay(self, tmp_path):
        """A recorded trace replays identically to the in-memory one
        that was banked."""
        t1 = synthetic_trace("diurnal", seed=5, horizon_s=2.0,
                             base_rate=15.0)
        path = t1.save(str(tmp_path / "trace.jsonl"))
        t2 = Trace.load(path)
        assert t2 == t1
        assert t2.fingerprint() == t1.fingerprint()
        with pytest.raises(ValueError, match="not a"):
            (tmp_path / "bad.jsonl").write_text('{"schema": "nope"}\n')
            Trace.load(str(tmp_path / "bad.jsonl"))

    def test_trace_kinds_and_generator_determinism(self):
        with pytest.raises(ValueError, match="unknown trace kind"):
            synthetic_trace("weekly", seed=1)
        t1 = synthetic_trace("adversarial_overload", seed=9,
                             horizon_s=3.0)
        t2 = synthetic_trace("adversarial_overload", seed=9,
                             horizon_s=3.0)
        assert t1.fingerprint() == t2.fingerprint()
        # the overload phase is really hotter than the shoulders
        mid = [r for r in t1.requests if 0.75 <= r.t < 2.4]
        edge = [r for r in t1.requests if r.t < 0.75 or r.t >= 2.4]
        assert len(mid) / 1.65 > 2.0 * len(edge) / 1.35


# ---------------------------------------------------------------------------
# anti-flap on a live fleet
# ---------------------------------------------------------------------------


class TestAntiFlap:
    def test_single_burst_never_scales_or_degrades(self):
        """A one-burst trace whose spike would trip any load-fraction
        trigger (arrivals in one control window exceed the shed
        threshold) actuates NOTHING: the percentile+hysteresis
        controller holds still through a burst the queue can absorb."""
        trace = synthetic_trace("bursty", seed=77, horizon_s=2.5,
                                base_rate=25.0, n_bursts=1,
                                burst_len_s=0.2, burst_mult=6.0)
        # the burst is real: some 0.25s window carries more arrivals
        # than the static ladder's shed threshold of frontend capacity
        times = np.asarray([r.t for r in trace.requests])
        peak = max(np.sum((times >= t) & (times < t + 0.25))
                   for t in np.arange(0.0, 2.3, 0.05))
        assert peak >= 0.75 * 32
        rep = run_fleet(trace, drill.frontend_config(),
                        sim=drill.sim_config(),
                        autopilot=drill.autopilot_config())
        assert rep.actions == []
        assert rep.summary["mode"] == "normal"
        assert rep.summary["n_replicas"] == drill.N_BASELINE

    def test_no_oscillation_on_sustained_overload(self, headline):
        """The overload episode escalates monotonically and relaxes at
        most once — never the up/down/up ping-pong hysteresis exists
        to forbid."""
        kinds = [a["action"] for a in headline.auto.actions]
        assert kinds.count("scale_up") <= drill.N_MAX - drill.N_BASELINE
        if "scale_down" in kinds:
            assert "scale_up" not in kinds[kinds.index("scale_down"):]
        ups = [i for i, k in enumerate(kinds) if k == "escalate"]
        downs = [i for i, k in enumerate(kinds) if k == "deescalate"]
        assert not ups or not downs or max(ups) < min(downs)


# ---------------------------------------------------------------------------
# THE headline drill
# ---------------------------------------------------------------------------


class TestHeadlineDrill:
    def test_every_static_misses_autopilot_holds(self, headline):
        v = headline.verdict()
        assert v["every_static_misses"], v
        assert v["autopilot_holds"], v
        # with margin on both sides of the SLO line, so ambient noise
        # in future refactors shows up as a drift, not a flake
        assert all(a <= 0.85 for a in v["static"].values()), v
        assert v["autopilot"] >= 0.93, v

    def test_autopilot_scaled_and_scoped(self, headline):
        """It held the SLO the way the tentpole claims: elastic
        capacity + percentile-driven modes, from baseline
        provisioning."""
        rep = headline.auto
        kinds = [a["action"] for a in rep.actions]
        assert "scale_up" in kinds
        assert "escalate" in kinds
        assert rep.summary["n_replicas"] > drill.N_BASELINE
        assert rep.summary["n_replicas"] <= drill.N_MAX
        added = [t for t in rep.transitions
                 if t["event"] == "replica_added"]
        assert len(added) == kinds.count("scale_up")
        # every static arm stayed at baseline (the sweep premise)
        for r in headline.static.values():
            assert r.summary["n_replicas"] == drill.N_BASELINE
            assert r.actions == []

    def test_actuations_banked_with_evidence(self, headline):
        """Every actuation appears in the transition history as an
        ``autopilot`` event whose evidence names the triggering
        breach."""
        rep = headline.auto
        banked = [t for t in rep.transitions
                  if t["event"] == "autopilot"]
        assert [t["action"] for t in banked] == \
            [a["action"] for a in rep.actions]
        for t, a in zip(banked, rep.actions):
            assert t["evidence"] == a["evidence"]
            if t["action"] in ("escalate", "scale_up",
                               "set_admission"):
                br = t["evidence"]["breaches"]
                assert br and br[0]["class"] == "guaranteed"
                assert br[0]["metric"] in ("latency_p99_ms",
                                           "success_rate")

    def test_headline_replay_bit_identical(self, headline):
        """Acceptance: the drill itself is bit-deterministic under
        (trace, seed)."""
        rerun = run_fleet(headline.trace, drill.frontend_config(),
                          sim=drill.sim_config(),
                          autopilot=drill.autopilot_config())
        assert rerun.fingerprint() == headline.auto.fingerprint()

    def test_episode_reconstructable_from_spine(self, tmp_path,
                                                monkeypatch):
        """With the obs spine armed, a (smaller) episode's full
        actuation history is reconstructable from the banked run file
        alone — action kinds, params, evidence, and order."""
        from apex1_tpu.obs import spine

        monkeypatch.setenv("APEX1_OBS_DIR", str(tmp_path))
        try:
            rep = run_fleet(
                drill.overload_trace(horizon_s=3.5),
                drill.frontend_config(),
                sim=drill.sim_config(),
                autopilot=drill.autopilot_config())
        finally:
            run = spine.default_run()
            path = run.path
            spine.set_default_run(None)
        assert rep.actions, "episode must have actuated"
        events = spine.read_events(path)
        acts = [e for e in events if e.get("name") == "autopilot.action"]
        got = [{"t": a["t_ctrl"], "tick": a["tick"],
                "action": a["action"], "params": a["params"],
                "result": a["result"], "evidence": a["evidence"]}
               for a in acts]
        assert got == rep.actions
        # the detections rode along too: serving transitions (mode
        # flips, sheds) are in the same stream
        names = {e.get("name") for e in events}
        assert "serving.transition" in names
        assert "serving.request" in names


# ---------------------------------------------------------------------------
# the drift gate (jax-free tripwire)
# ---------------------------------------------------------------------------


def _load_check_drift():
    spec = importlib.util.spec_from_file_location(
        "_check_drift_for_test", _REPO / "tools" / "check_drift.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def drift_mod():
    return _load_check_drift()


def _mini_corpus(tmp_path, *, measured_scale=1.0):
    """A minimal joinable corpus: one prediction row, one [tpu]
    record, a calibration table whose factor matches the fit
    exactly."""
    from apex1_tpu.obs import calibrate

    d = tmp_path / "pr"
    d.mkdir(exist_ok=True)
    row = {"name": "gpt2", "flops": 1e12, "bytes": 1e9,
           "units_per_step": 1e6}
    (d / "predicted_r1.json").write_text(json.dumps({"steps": [row]}))
    rate = calibrate.predicted_step_rate(row, "v5e")
    measured = rate / 2.0 * measured_scale
    (d / "bench_gpt2.log").write_text(json.dumps(
        {"metric": "tok/s [tpu]", "value": measured}) + "\n")
    cal = {"schema": calibrate.SCHEMA, "generation": "v5e",
           "factors": {"step:gpt2": {"slowdown": 2.0, "n": 1,
                                     "backend": "tpu"}},
           "proxy_factors": {}, "excluded": [], "pairs": []}
    (d / "calibration.json").write_text(json.dumps(cal))
    (d / "tuning").mkdir(exist_ok=True)
    return d


class TestDriftGate:
    def test_committed_corpus_in_band(self, drift_mod):
        """The gate must be green on the repo's own banked state —
        that IS the check_all step."""
        assert drift_mod.run_gate(str(_REPO / "perf_results")) == 0

    def test_in_band_synthetic(self, tmp_path, drift_mod):
        assert drift_mod.run_gate(str(_mini_corpus(tmp_path))) == 0

    def test_drifted_record_fails(self, tmp_path, drift_mod):
        d = _mini_corpus(tmp_path, measured_scale=0.5)  # 2x slower
        assert drift_mod.run_gate(str(d)) == 1

    def test_failure_output_names_offending_record_path(
            self, tmp_path, drift_mod, capsys):
        """ISSUE 14 satellite: a drift failure must name the record
        PATH that carries the out-of-band measurement, not just the
        key — the fix is one open() away."""
        d = _mini_corpus(tmp_path, measured_scale=0.5)
        assert drift_mod.run_gate(str(d)) == 1
        out = capsys.readouterr().out
        assert ("offending record: "
                + str(d / "bench_gpt2.log")) in out

    def test_uncalibrated_new_key_fails(self, tmp_path, drift_mod):
        d = _mini_corpus(tmp_path)
        cal = json.loads((d / "calibration.json").read_text())
        cal["factors"] = {}                  # stale table, new record
        (d / "calibration.json").write_text(json.dumps(cal))
        assert drift_mod.run_gate(str(d)) == 1

    def test_tolerates_spec_serving_record_fields(self, tmp_path,
                                                  drift_mod):
        """ISSUE 15 satellite: the new bench_serving record shape
        (multiplier_sweep rows with prefix_hit_rate / accept_rate /
        goodput + the int8 capacity block) banked into the corpus dir
        must not move the gate — serving benches join no
        predicted-step row, so they are NOT calibration evidence and
        the gate must neither fit from them nor fail-closed on them."""
        d = _mini_corpus(tmp_path)
        rec = {"metric": "serving tokens/sec gpt2-serving [cpu]",
               "value": 1234.5, "unit": "tokens/sec",
               "multiplier_sweep": {
                   "rows": [{"config": "radix_spec",
                             "prefix_hit_rate": 0.92,
                             "accept_rate": 0.41,
                             "goodput_tokens_per_sec": 999.0}],
                   "goodput_multiple": 1.31,
                   "int8_capacity": {"slots_bf16": 8,
                                     "slots_int8_same_budget": 16}}}
        (d / "bench_spec_serving.json").write_text(json.dumps(rec))
        (d / "bench_spec_serving_cpu.log").write_text(
            json.dumps(rec) + "\n")
        assert drift_mod.run_gate(str(d)) == 0

    def test_fail_closed_on_unreadable_evidence(self, tmp_path,
                                                drift_mod):
        d = _mini_corpus(tmp_path)
        (d / "calibration.json").write_text("{broken")
        assert drift_mod.run_gate(str(d)) == 2
        _mini_corpus(tmp_path)               # restore the table
        assert drift_mod.run_gate(str(d)) == 0
        (d / "tuning" / "flash_attention.json").write_text("{nope")
        assert drift_mod.run_gate(str(d)) == 2
        missing = tmp_path / "nowhere"
        missing.mkdir()
        assert drift_mod.run_gate(str(missing)) == 2   # no table at all

    def test_band_is_configurable(self, tmp_path, drift_mod):
        d = _mini_corpus(tmp_path, measured_scale=0.8)  # ratio 0.8
        assert drift_mod.run_gate(str(d), band=(0.75, 1.3),
                                  refit_tol=0.5) == 0
        assert drift_mod.run_gate(str(d), band=(0.9, 1.1),
                                  refit_tol=0.5) == 1
