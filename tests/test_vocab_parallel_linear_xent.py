"""Vocab-parallel fused LM-head+CE (``vocab_parallel_linear_cross_entropy``)
— the TP composition of ``ops/linear_xent.py``: W vocab-sharded over tp=4,
partial online-softmax stats merged with pmax/psum. Parity vs the
UNSHARDED fused kernel (loss, dx, and the re-assembled dW), on both the
Pallas-interpret and XLA-composite paths."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex1_tpu.core.mesh import make_mesh
from apex1_tpu.ops import _common
from apex1_tpu.ops.linear_xent import linear_cross_entropy
from apex1_tpu.transformer.tensor_parallel import (
    vocab_parallel_linear_cross_entropy)

pytestmark = pytest.mark.slow  # heavy kernel-parity suite: full run via check_all.sh --all

TP = 4
TOL = dict(rtol=3e-5, atol=3e-5)


@pytest.fixture()
def mesh(devices):
    return make_mesh(dp=2, tp=TP)


def _mk(rng, T=24, H=96, V=256):
    x = jnp.asarray(rng.normal(size=(T, H)) * 0.3, jnp.float32)
    w = jnp.asarray(rng.normal(size=(V, H)) * 0.3, jnp.float32)
    labels = jnp.asarray(rng.integers(0, V, size=(T,)), jnp.int32)
    return x, w, labels


def _run(mesh, impl, x, w, labels, **kw):
    """loss + grads of the sharded op; jax.grad runs INSIDE shard_map —
    the contract a sharded train step uses (grads of a replicated loss wrt
    the replicated activation and the local W shard)."""

    def fn(x, w_shard, labels):
        def local_loss(x, w_shard):
            with _common.force_impl(impl):
                return jnp.sum(vocab_parallel_linear_cross_entropy(
                    x, w_shard, labels, **kw))

        loss = local_loss(x, w_shard)
        dx, dw = jax.grad(local_loss, argnums=(0, 1))(x, w_shard)
        return loss, dx, dw

    return jax.shard_map(
        fn, mesh=mesh, in_specs=(P(), P("tp", None), P()),
        out_specs=(P(), P(), P("tp", None)), check_vma=False)(x, w, labels)


@pytest.mark.parametrize("impl", ["pallas", "xla"])
@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_parity_vs_unsharded(mesh, rng, impl, smoothing):
    x, w, labels = _mk(rng)

    def gold_fn(x, w):
        with _common.force_impl("pallas"):
            return jnp.sum(linear_cross_entropy(
                x, w, labels, smoothing=smoothing, block_t=16, block_v=64))

    want = gold_fn(x, w)
    gdx, gdw = jax.grad(gold_fn, argnums=(0, 1))(x, w)

    loss, dx, dw = _run(mesh, impl, x, w, labels,
                        label_smoothing=smoothing)
    np.testing.assert_allclose(float(loss), float(want), **TOL)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(gdx), **TOL)
    np.testing.assert_allclose(np.asarray(dw), np.asarray(gdw), **TOL)


@pytest.mark.parametrize("impl", ["pallas", "xla"])
def test_padding_idx_and_lane_pad(mesh, rng, impl):
    """padding_idx rows zero; num_classes masks the global lane-pad tail
    (which lives entirely in the LAST shard)."""
    T, H, V, K, pad = 16, 64, 256, 250, 3
    x = jnp.asarray(rng.normal(size=(T, H)) * 0.3, jnp.float32)
    w = jnp.asarray(rng.normal(size=(V, H)) * 0.3, jnp.float32)
    labels = np.asarray(rng.integers(0, K, size=(T,)), np.int32)
    labels[::4] = pad
    labels = jnp.asarray(labels)

    def gold_fn(x, w):
        with _common.force_impl("pallas"):
            return jnp.sum(linear_cross_entropy(
                x, w, labels, padding_idx=pad, num_classes=K,
                block_t=16, block_v=64))

    want = gold_fn(x, w)
    gdx, gdw = jax.grad(gold_fn, argnums=(0, 1))(x, w)

    loss, dx, dw = _run(mesh, impl, x, w, labels,
                        padding_idx=pad, num_classes=K)
    np.testing.assert_allclose(float(loss), float(want), **TOL)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(gdx), **TOL)
    np.testing.assert_allclose(np.asarray(dw), np.asarray(gdw), **TOL)
    assert np.all(np.asarray(dw)[K:] == 0.0)  # lane-pad rows get no grad


@pytest.mark.parametrize("impl", ["pallas", "xla"])
def test_sequence_parallel_input(mesh, rng, impl):
    """x arrives SEQUENCE-sharded over tp (Megatron-SP head pattern): the
    op's internal all_gather owns the input collective, so the activation
    cotangent comes back as the correct LOCAL shard — the exact
    composition that double-counted by tp when the bwd psum'd dx."""
    T, H, V = 32, 64, 256
    x = jnp.asarray(rng.normal(size=(T, H)) * 0.3, jnp.float32)
    w = jnp.asarray(rng.normal(size=(V, H)) * 0.3, jnp.float32)
    labels = jnp.asarray(rng.integers(0, V, size=(T,)), jnp.int32)

    def fn(x_shard, w_shard, labels):
        def local_loss(x_shard, w_shard):
            with _common.force_impl(impl):
                return jnp.sum(vocab_parallel_linear_cross_entropy(
                    x_shard, w_shard, labels,
                    sequence_parallel_input=True))

        loss = local_loss(x_shard, w_shard)
        dx, dw = jax.grad(local_loss, argnums=(0, 1))(x_shard, w_shard)
        return loss, dx, dw

    loss, dx, dw = jax.shard_map(
        fn, mesh=mesh, in_specs=(P("tp"), P("tp", None), P()),
        out_specs=(P(), P("tp"), P("tp", None)), check_vma=False)(
        x, w, labels)

    def gold_fn(x, w):
        with _common.force_impl("pallas"):
            return jnp.sum(linear_cross_entropy(x, w, labels,
                                                block_t=16, block_v=64))

    np.testing.assert_allclose(float(loss), float(gold_fn(x, w)), **TOL)
    gdx, gdw = jax.grad(gold_fn, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(gdx), **TOL)
    np.testing.assert_allclose(np.asarray(dw), np.asarray(gdw), **TOL)


def test_loss_replicated_across_ranks(mesh, rng):
    """Every tp rank must see the identical merged per-token loss."""
    x, w, labels = _mk(rng, T=8)

    def fn(x, w_shard, labels):
        loss = vocab_parallel_linear_cross_entropy(x, w_shard, labels)
        return loss[None]  # keep a rank axis

    per_rank = jax.shard_map(
        fn, mesh=mesh, in_specs=(P(), P("tp", None), P()),
        out_specs=P("tp"), check_vma=False)(x, w, labels)
    for r in range(1, TP):
        np.testing.assert_allclose(np.asarray(per_rank[0]),
                                   np.asarray(per_rank[r]), rtol=1e-6)
