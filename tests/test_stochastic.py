"""In-kernel stochasticity determinism contract (`ops.stochastic` + the
flash kernels' fused probability dropout).

The contract (docs/perf_playbook.md "In-kernel dropout"):

- same seed → BIT-IDENTICAL output across calls and across jit
  boundaries, per backend;
- dropout=0 lowers to the pre-existing program bit-for-bit;
- keep-rate is statistically correct at p ∈ {0.1, 0.5};
- the backward recomputes the forward's mask exactly from the seed
  (recompute identity) — pinned in interpret mode, where the kernel
  hash and the XLA composites are bit-equal so AD-of-composite is an
  exact oracle for the custom-VJP kernels;
- masks are NOT bitwise-matched to a jax.random.bernoulli composite —
  statistical parity only.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.ops._common import force_impl
from apex1_tpu.ops.attention import flash_attention
from apex1_tpu.ops.stochastic import (fold_seed, fused_bias_dropout_add,
                                      fused_dropout_add_layer_norm,
                                      hash_bits_u32, seed_from_key,
                                      threshold_u32)

SEED = jnp.int32(20240801)


def _xrb(rng, *shape, dtype=jnp.float32):
    return jnp.asarray(rng.normal(size=shape), dtype)


# ---------------------------------------------------------------------------
# the counter hash itself
# ---------------------------------------------------------------------------

class TestCounterHash:
    @pytest.mark.parametrize("p", [0.1, 0.5])
    def test_keep_rate(self, p):
        row = jax.lax.broadcasted_iota(jnp.int32, (512, 512), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (512, 512), 1)
        bits = hash_bits_u32(SEED, 3, row, col)
        keep = np.asarray(bits >= threshold_u32(p))
        rate = keep.mean()
        # 512² draws: binomial σ ≈ 0.001 — 5σ bounds
        assert abs(rate - (1.0 - p)) < 0.005, (p, rate)

    def test_streams_disjoint_across_salts_and_seeds(self):
        row = jax.lax.broadcasted_iota(jnp.int32, (64, 128), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (64, 128), 1)
        a = np.asarray(hash_bits_u32(SEED, 0, row, col))
        b = np.asarray(hash_bits_u32(SEED, 1, row, col))
        c = np.asarray(hash_bits_u32(SEED + 1, 0, row, col))
        assert (a != b).mean() > 0.99
        assert (a != c).mean() > 0.99

    def test_shift_invariance(self):
        """The stream is a pure function of GLOBAL position: evaluating
        a window at an offset reproduces the global stream's slice —
        the property that makes ring shards schedule-invariant."""
        row = jax.lax.broadcasted_iota(jnp.int32, (32, 32), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (32, 32), 1)
        full = np.asarray(hash_bits_u32(SEED, 7, row, col + 0))
        shifted = np.asarray(hash_bits_u32(SEED, 7, row[:, :16],
                                           col[:, :16] + 16))
        np.testing.assert_array_equal(full[:, 16:], shifted)

    def test_salt_row_not_interchangeable(self):
        """(salt=a, row=b) and (salt=b, row=a) must draw DIFFERENT
        streams — a symmetric hash would pairwise-correlate per-head
        attention masks across (batch·head, q-row) index pairs."""
        col = jnp.arange(128, dtype=jnp.int32)
        pairs = [(3, 5), (0, 1), (7, 96)]
        for a, b in pairs:
            x = np.asarray(hash_bits_u32(
                SEED, a, jnp.full_like(col, b), col))
            y = np.asarray(hash_bits_u32(
                SEED, b, jnp.full_like(col, a), col))
            assert (x != y).mean() > 0.99, (a, b)

    def test_fold_seed_derives_distinct_streams(self):
        s0, s1 = fold_seed(SEED, 0), fold_seed(SEED, 1)
        assert int(s0) != int(s1)
        assert int(s0) >= 0 and int(s1) >= 0  # int32-seed value range

    def test_seed_from_key(self):
        s = seed_from_key(jax.random.key(0))
        assert s.dtype == jnp.int32 and s.shape == ()
        assert int(s) != int(seed_from_key(jax.random.key(1)))


# ---------------------------------------------------------------------------
# fused_bias_dropout_add
# ---------------------------------------------------------------------------

class TestBiasDropoutAdd:
    def test_p0_is_plain_add(self, rng):
        x, r = _xrb(rng, 4, 96), _xrb(rng, 4, 96)
        b = _xrb(rng, 96)
        got = fused_bias_dropout_add(x, r, p=0.0, bias=b)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(x + b + r))

    def test_bit_identical_across_calls_and_jit(self, rng):
        x, r = _xrb(rng, 6, 160), _xrb(rng, 6, 160)
        with force_impl("pallas"):
            a = fused_bias_dropout_add(x, r, p=0.5, seed=SEED)
            b = fused_bias_dropout_add(x, r, p=0.5, seed=SEED)
        f = jax.jit(lambda x, r, s: fused_bias_dropout_add(
            x, r, p=0.5, seed=s))
        with force_impl("pallas"):
            c, d = f(x, r, SEED), f(x, r, SEED)
        for other in (b, c, d):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(other))

    def test_kernel_matches_xla_bitwise_on_cpu(self, rng):
        """Interpret-mode kernel and XLA composite share the hash at
        global positions — outputs are bit-identical on CPU."""
        x, r = _xrb(rng, 40, 96), _xrb(rng, 40, 96)
        b = _xrb(rng, 96)
        with force_impl("pallas"):
            a = fused_bias_dropout_add(x, r, p=0.3, seed=SEED, bias=b)
        with force_impl("xla"):
            c = fused_bias_dropout_add(x, r, p=0.3, seed=SEED, bias=b)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))

    @pytest.mark.parametrize("p", [0.1, 0.5])
    def test_keep_rate_and_mean(self, rng, p):
        x = jnp.ones((128, 256), jnp.float32)
        r = jnp.zeros((128, 256), jnp.float32)
        with force_impl("pallas"):
            y = np.asarray(fused_bias_dropout_add(x, r, p=p, seed=SEED))
        kept = y != 0
        assert abs(kept.mean() - (1.0 - p)) < 0.01, kept.mean()
        # kept values carry 1/(1-p): the mean is preserved in expectation
        assert abs(y.mean() - 1.0) < 0.04, y.mean()

    def test_backward_recomputes_forward_mask(self, rng):
        """Recompute identity, observed directly: d(sum y)/dx must be
        EXACTLY mask/(1-p) — the mask the forward applied (observable
        as y - r != 0)."""
        x, r = _xrb(rng, 24, 128), _xrb(rng, 24, 128)
        p = 0.4

        def f(x):
            with force_impl("pallas"):
                return fused_bias_dropout_add(x, r, p=p, seed=SEED)

        y = f(x)
        fwd_mask = np.asarray(y - r) != 0
        dx = np.asarray(jax.grad(lambda x: jnp.sum(f(x)))(x))
        np.testing.assert_array_equal(dx != 0, fwd_mask)
        np.testing.assert_allclose(dx[fwd_mask], 1.0 / (1.0 - p),
                                   rtol=1e-6)

    @pytest.mark.slow  # cross-impl grad parity; identity pinned above
    def test_bias_and_residual_grads(self, rng):
        x, r = _xrb(rng, 24, 96), _xrb(rng, 24, 96)
        b = _xrb(rng, 96)

        def loss(impl):
            def f(x, r, b):
                with force_impl(impl):
                    y = fused_bias_dropout_add(x, r, p=0.25, seed=SEED,
                                               bias=b)
                return jnp.sum(y ** 2)
            return jax.grad(f, (0, 1, 2))(x, r, b)

        for gp, gx in zip(loss("pallas"), loss("xla")):
            np.testing.assert_allclose(np.asarray(gp), np.asarray(gx),
                                       rtol=1e-5, atol=1e-5)

    def test_bf16(self, rng):
        x, r = _xrb(rng, 16, 128, dtype=jnp.bfloat16), \
            _xrb(rng, 16, 128, dtype=jnp.bfloat16)
        with force_impl("pallas"):
            a = fused_bias_dropout_add(x, r, p=0.5, seed=SEED)
        with force_impl("xla"):
            b = fused_bias_dropout_add(x, r, p=0.5, seed=SEED)
        assert a.dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(a.astype(jnp.float32)),
            np.asarray(b.astype(jnp.float32)))

    def test_requires_seed(self, rng):
        x = _xrb(rng, 4, 96)
        with pytest.raises(ValueError, match="seed"):
            fused_bias_dropout_add(x, x, p=0.5)

    def test_not_bernoulli_matched_but_statistical(self, rng):
        """The contract explicitly does NOT promise bitwise equality
        with a jax.random.bernoulli composite — only the keep-rate."""
        x = jnp.ones((64, 128), jnp.float32)
        r = jnp.zeros_like(x)
        with force_impl("pallas"):
            y = np.asarray(fused_bias_dropout_add(x, r, p=0.5, seed=SEED))
        ref = np.asarray(jax.random.bernoulli(
            jax.random.key(int(SEED)), 0.5, x.shape))
        ours = y != 0
        assert not np.array_equal(ours, ref)  # different PRNGs
        assert abs(ours.mean() - ref.mean()) < 0.05


# ---------------------------------------------------------------------------
# fused_dropout_add_layer_norm
# ---------------------------------------------------------------------------

class TestDropoutAddLayerNorm:
    def test_composes_dropout_add_then_ln(self, rng):
        from apex1_tpu.ops import layer_norm
        x, r = _xrb(rng, 3, 8, 96), _xrb(rng, 3, 8, 96)
        g, b = jnp.ones((96,), jnp.float32), jnp.zeros((96,), jnp.float32)
        with force_impl("pallas"):
            y, z = fused_dropout_add_layer_norm(
                x, r, g, b, p=0.2, seed=SEED, prenorm=True)
            z_ref = fused_bias_dropout_add(x, r, p=0.2, seed=SEED)
            y_ref = layer_norm(z_ref, g, b)
        np.testing.assert_array_equal(np.asarray(z), np.asarray(z_ref))
        np.testing.assert_array_equal(np.asarray(y), np.asarray(y_ref))

    @pytest.mark.slow  # cross-impl grad parity; composition pinned above
    def test_rms_variant_and_grads(self, rng):
        x, r = _xrb(rng, 16, 128), _xrb(rng, 16, 128)
        g = jnp.ones((128,), jnp.float32)

        def loss(impl):
            def f(x, r, g):
                with force_impl(impl):
                    y = fused_dropout_add_layer_norm(
                        x, r, g, None, p=0.3, seed=SEED, rms=True)
                return jnp.sum(y ** 2)
            return jax.grad(f, (0, 1, 2))(x, r, g)

        for gp, gx in zip(loss("pallas"), loss("xla")):
            np.testing.assert_allclose(np.asarray(gp), np.asarray(gx),
                                       rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# flash-kernel fused probability dropout
# ---------------------------------------------------------------------------

class TestFlashDropout:
    B, H, S, D = 2, 2, 64, 16

    def _qkv(self, rng):
        sh = (self.B, self.H, self.S, self.D)
        return (_xrb(rng, *sh), _xrb(rng, *sh), _xrb(rng, *sh))

    def test_p0_lowers_bit_for_bit(self, rng):
        """dropout_p=0 is PINNED to the pre-dropout kernel: bit-equal
        output, and the traced program contains NO mask machinery (the
        unconsumed seed scalar is the only delta vs the default call —
        the kernels' arg lists and bodies are built identically)."""
        q, k, v = self._qkv(rng)

        def default(q, k, v):
            with force_impl("pallas"):
                return flash_attention(q, k, v, causal=True)

        def p0(q, k, v):
            with force_impl("pallas"):
                return flash_attention(q, k, v, causal=True,
                                       dropout_p=0.0)

        def pdrop(q, k, v):
            with force_impl("pallas"):
                return flash_attention(q, k, v, causal=True,
                                       dropout_p=0.2, dropout_seed=SEED)

        np.testing.assert_array_equal(np.asarray(default(q, k, v)),
                                      np.asarray(p0(q, k, v)))
        # the mask machinery (interpret: uint32 hash xor/shift chain;
        # TPU: prng_seed/prng_random_bits) traces ONLY at p > 0 —
        # falsifiable: the dropout'd jaxpr must contain it
        mask_ops = ("xor", "prng")
        txt_def = str(jax.make_jaxpr(default)(q, k, v))
        txt_p0 = str(jax.make_jaxpr(p0)(q, k, v))
        txt_drop = str(jax.make_jaxpr(pdrop)(q, k, v))
        for op in mask_ops:
            assert op not in txt_def and op not in txt_p0, op
        assert any(op in txt_drop for op in mask_ops)

    def test_deterministic_across_calls_and_jit(self, rng):
        q, k, v = self._qkv(rng)

        def f(q, k, v, s):
            with force_impl("pallas"):
                return flash_attention(q, k, v, causal=True,
                                       dropout_p=0.2, dropout_seed=s)

        a = f(q, k, v, SEED)
        b = f(q, k, v, SEED)
        jf = jax.jit(f)
        c, d = jf(q, k, v, SEED), jf(q, k, v, SEED)
        for other in (b, c, d):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(other))
        # and a different seed draws a different mask
        assert not np.array_equal(np.asarray(a),
                                  np.asarray(f(q, k, v, SEED + 1)))

    def test_grads_match_composite_oracle(self, rng):
        """Recompute identity for the flash custom VJPs: on CPU the
        interpret-mode kernels and the XLA composite share bit-equal
        masks, so AD of the explicit composite (which differentiates
        THROUGH the stored mask) is an exact oracle for the kernels'
        recompute-from-seed backward."""
        q, k, v = self._qkv(rng)

        def grads(impl, **kw):
            def f(q, k, v):
                with force_impl(impl):
                    o = flash_attention(q, k, v, dropout_p=0.2,
                                        dropout_seed=SEED, **kw)
                return jnp.sum(o.astype(jnp.float32) ** 2)
            return jax.grad(f, (0, 1, 2))(q, k, v)

        for kw in (dict(causal=True), dict()):
            for gp, gx in zip(grads("pallas", **kw), grads("xla", **kw)):
                np.testing.assert_allclose(np.asarray(gp), np.asarray(gx),
                                           rtol=3e-5, atol=3e-5)

    @pytest.mark.slow  # feature-matrix grads: full run via check_all --all
    def test_gqa_and_segments_compose(self, rng):
        q, _, _ = self._qkv(rng)
        kv = _xrb(rng, self.B, 1, self.S, self.D)
        seg = jnp.asarray(rng.integers(0, 3, (self.B, self.S)), jnp.int32)

        def grads(impl):
            def f(q, k, v):
                with force_impl(impl):
                    o = flash_attention(q, k, v, segment_ids=seg,
                                        dropout_p=0.3, dropout_seed=SEED)
                return jnp.sum(o.astype(jnp.float32) ** 2)
            return jax.grad(f, (0, 1, 2))(q, kv, kv)

        for gp, gx in zip(grads("pallas"), grads("xla")):
            np.testing.assert_allclose(np.asarray(gp), np.asarray(gx),
                                       rtol=3e-5, atol=3e-5)

    @pytest.mark.slow  # feature-matrix grads: full run via check_all --all
    def test_bias_dbias_composes(self, rng):
        q, k, v = self._qkv(rng)
        bias = _xrb(rng, 1, 1, self.S, self.S)

        def grads(impl):
            def f(q, k, v, bias):
                with force_impl(impl):
                    o = flash_attention(q, k, v, bias=bias, dropout_p=0.2,
                                        dropout_seed=SEED)
                return jnp.sum(o.astype(jnp.float32) ** 2)
            return jax.grad(f, (0, 1, 2, 3))(q, k, v, bias)

        for gp, gx in zip(grads("pallas"), grads("xla")):
            np.testing.assert_allclose(np.asarray(gp), np.asarray(gx),
                                       rtol=3e-5, atol=3e-5)

    def test_lse_is_dropout_free(self, rng):
        """lse (and the softmax denominator) must NOT see the mask —
        that is what keeps ring merges exact."""
        q, k, v = self._qkv(rng)
        with force_impl("pallas"):
            _, lse0 = flash_attention(q, k, v, causal=True,
                                      return_lse=True)
            _, lse1 = flash_attention(q, k, v, causal=True,
                                      dropout_p=0.5, dropout_seed=SEED,
                                      return_lse=True)
        np.testing.assert_array_equal(np.asarray(lse0), np.asarray(lse1))

    def test_requires_seed(self, rng):
        q, k, v = self._qkv(rng)
        with pytest.raises(ValueError, match="dropout_seed"):
            flash_attention(q, k, v, dropout_p=0.5)


# ---------------------------------------------------------------------------
# fp16 storage-dtype bridge (Mosaic has no f16 — AOT gate r5 caught the
# O1_fp16 bench step failing to compile: "Unsupported type: 'f16'")
# ---------------------------------------------------------------------------

class TestF16MosaicBridge:
    """Compiled-TPU kernels must never see float16 operands: the public
    entries cast f16 -> bf16 (storage vs compute dtype) and restore f16
    on the way out. Pinned at the jaxpr level under the same dispatch
    patch tools/aot_check.py uses, so the contract is testable on CPU."""

    @staticmethod
    def _tpu_dispatch(monkeypatch):
        import apex1_tpu.ops._common as _common
        monkeypatch.setattr(_common, "on_tpu", lambda: True)

    @staticmethod
    def _pallas_in_avals(jaxpr):
        out = []

        def walk(jx):
            for eqn in jx.eqns:
                if eqn.primitive.name == "pallas_call":
                    out.extend(v.aval for v in eqn.invars)
            for sub in jax.core.subjaxprs(jx):
                walk(sub)

        walk(jaxpr.jaxpr)
        assert out, "expected at least one pallas_call in the jaxpr"
        return out

    def test_mosaic_dtype(self, monkeypatch):
        from apex1_tpu.ops import _common
        assert _common.mosaic_dtype(jnp.float16) == jnp.float16  # off-TPU
        self._tpu_dispatch(monkeypatch)
        assert _common.mosaic_dtype(jnp.float16) == jnp.bfloat16
        assert _common.mosaic_dtype(jnp.bfloat16) == jnp.bfloat16
        assert _common.mosaic_dtype(jnp.float32) == jnp.float32

    def test_flash_attention_f16_bridged(self, monkeypatch):
        self._tpu_dispatch(monkeypatch)
        q = jax.ShapeDtypeStruct((1, 2, 64, 32), jnp.float16)

        def f(q, k, v):
            return flash_attention(q, k, v, causal=True, dropout_p=0.1,
                                   dropout_seed=SEED)

        jx = jax.make_jaxpr(f)(q, q, q)
        assert all(a.dtype != jnp.float16
                   for a in self._pallas_in_avals(jx))
        assert jx.out_avals[0].dtype == jnp.float16  # storage restored

    def test_bias_dropout_add_f16_bridged(self, monkeypatch):
        self._tpu_dispatch(monkeypatch)
        x = jax.ShapeDtypeStruct((64, 128), jnp.float16)
        b = jax.ShapeDtypeStruct((128,), jnp.float32)

        def f(x, r, b):
            return fused_bias_dropout_add(x, r, bias=b, p=0.1, seed=SEED)

        jx = jax.make_jaxpr(f)(x, x, b)
        assert all(a.dtype != jnp.float16
                   for a in self._pallas_in_avals(jx))
        assert jx.out_avals[0].dtype == jnp.float16

    def test_layer_norm_f16_bridged(self, monkeypatch):
        self._tpu_dispatch(monkeypatch)
        from apex1_tpu.ops import layer_norm
        x = jax.ShapeDtypeStruct((64, 128), jnp.float16)
        g = jax.ShapeDtypeStruct((128,), jnp.float32)

        jx = jax.make_jaxpr(
            lambda x, g, b: layer_norm(x, g, b))(x, g, g)
        assert all(a.dtype != jnp.float16
                   for a in self._pallas_in_avals(jx))
        assert jx.out_avals[0].dtype == jnp.float16
