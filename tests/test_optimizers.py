"""Optimizer parity tests — ≙ ``tests/L0/run_optimizers/test_fused_optimizer.py``:
step the fused optimizer and a gold reference (torch CPU where available,
hand-written numpy elsewhere) on identical params/grads and assert per-step
allclose."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex1_tpu import optim

try:
    import torch
    HAS_TORCH = True
except ImportError:
    HAS_TORCH = False


def make_tree(rng, scale=1.0):
    return {
        "w": jnp.asarray(rng.normal(size=(17, 31)) * scale, jnp.float32),
        "b": jnp.asarray(rng.normal(size=(31,)) * scale, jnp.float32),
        "deep": {"k": jnp.asarray(rng.normal(size=(5, 3, 2)), jnp.float32)},
    }


def grads_like(rng, tree, scale=0.1):
    return jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.normal(size=p.shape) * scale, jnp.float32),
        tree)


def torch_params_from(tree):
    leaves, _ = jax.tree_util.tree_flatten(tree)
    return [torch.nn.Parameter(torch.tensor(np.asarray(x))) for x in leaves]


def assert_tree_close(tree, torch_params, rtol=1e-5, atol=1e-6):
    leaves, _ = jax.tree_util.tree_flatten(tree)
    for ours, theirs in zip(leaves, torch_params):
        np.testing.assert_allclose(np.asarray(ours),
                                   theirs.detach().numpy(),
                                   rtol=rtol, atol=atol)


def run_both(opt, torch_opt, tree, torch_params, rng, n_steps=5):
    state = opt.init(tree)
    for _ in range(n_steps):
        g = grads_like(rng, tree)
        g_leaves, _ = jax.tree_util.tree_flatten(g)
        for p, gl in zip(torch_params, g_leaves):
            p.grad = torch.tensor(np.asarray(gl))
        tree, state = opt.step(g, state, tree)
        torch_opt.step()
        assert_tree_close(tree, torch_params)
    return tree


@pytest.mark.skipif(not HAS_TORCH, reason="torch gold unavailable")
class TestVsTorch:
    def test_adamw_mode(self, rng):
        tree = make_tree(rng)
        tp = torch_params_from(tree)
        run_both(optim.FusedAdam(lr=1e-2, weight_decay=0.05, adam_w_mode=True),
                 torch.optim.AdamW(tp, lr=1e-2, weight_decay=0.05),
                 tree, tp, rng)

    def test_adam_l2_mode(self, rng):
        tree = make_tree(rng)
        tp = torch_params_from(tree)
        run_both(optim.FusedAdam(lr=1e-2, weight_decay=0.05,
                                 adam_w_mode=False),
                 torch.optim.Adam(tp, lr=1e-2, weight_decay=0.05),
                 tree, tp, rng)

    def test_adam_no_bias_correction(self, rng):
        tree = make_tree(rng)
        opt = optim.FusedAdam(lr=1e-2, bias_correction=False)
        state = opt.init(tree)
        g = grads_like(rng, tree)
        new, _ = opt.step(g, state, tree)
        # without bias correction the first step is tiny (m = 0.1*g)
        delta = np.asarray(new["b"] - tree["b"])
        g32 = np.asarray(g["b"])
        expected = -1e-2 * (0.1 * g32) / (np.sqrt(0.001 * g32 ** 2) + 1e-8)
        np.testing.assert_allclose(delta, expected, rtol=1e-4, atol=1e-7)

    def test_sgd_momentum_nesterov(self, rng):
        for nesterov in (False, True):
            tree = make_tree(rng)
            tp = torch_params_from(tree)
            run_both(
                optim.FusedSGD(lr=1e-2, momentum=0.9, weight_decay=1e-4,
                               nesterov=nesterov),
                torch.optim.SGD(tp, lr=1e-2, momentum=0.9, weight_decay=1e-4,
                                nesterov=nesterov),
                tree, tp, rng)

    def test_sgd_dampening(self, rng):
        tree = make_tree(rng)
        tp = torch_params_from(tree)
        run_both(optim.FusedSGD(lr=1e-2, momentum=0.9, dampening=0.3),
                 torch.optim.SGD(tp, lr=1e-2, momentum=0.9, dampening=0.3),
                 tree, tp, rng)

    def test_adagrad(self, rng):
        tree = make_tree(rng)
        tp = torch_params_from(tree)
        run_both(optim.FusedAdagrad(lr=1e-2, eps=1e-10),
                 torch.optim.Adagrad(tp, lr=1e-2, eps=1e-10),
                 tree, tp, rng)


class TestLAMB:
    def gold_lamb_step(self, params, grads, m, v, step, lr=1e-2, b1=0.9,
                       b2=0.999, eps=1e-6, wd=0.01, max_gn=1.0):
        flat_g = np.concatenate([np.asarray(g).ravel()
                                 for g in jax.tree_util.tree_leaves(grads)])
        gnorm = np.linalg.norm(flat_g)
        clip = max(1.0, gnorm / max_gn)
        out = {}
        for k in ("w", "b"):
            g = np.asarray(grads[k]) / clip
            p = np.asarray(params[k])
            m[k] = b1 * m[k] + (1 - b1) * g
            v[k] = b2 * v[k] + (1 - b2) * g * g
            mh = m[k] / (1 - b1 ** step)
            vh = v[k] / (1 - b2 ** step)
            u = mh / (np.sqrt(vh) + eps) + wd * p
            wn, un = np.linalg.norm(p), np.linalg.norm(u)
            ratio = wn / un if (wn > 0 and un > 0) else 1.0
            out[k] = p - lr * ratio * u
        return out

    def test_vs_gold(self, rng):
        tree = {"w": jnp.asarray(rng.normal(size=(8, 4)), jnp.float32),
                "b": jnp.asarray(rng.normal(size=(4,)), jnp.float32)}
        opt = optim.FusedLAMB(lr=1e-2, weight_decay=0.01)
        state = opt.init(tree)
        m = {k: np.zeros(np.shape(v)) for k, v in tree.items()}
        v = {k: np.zeros(np.shape(x)) for k, x in tree.items()}
        gold = {k: np.asarray(x) for k, x in tree.items()}
        for step in range(1, 5):
            g = grads_like(rng, tree, scale=1.0)
            gold = self.gold_lamb_step(gold, g, m, v, step)
            tree, state = opt.step(g, state, tree)
            for k in gold:
                np.testing.assert_allclose(np.asarray(tree[k]), gold[k],
                                           rtol=1e-5, atol=1e-6)

    def test_trust_ratio_skipped_without_wd(self, rng):
        # wd=0, use_nvlamb=False → plain AdamW-like step (ratio 1);
        # use_nvlamb=True applies the ratio anyway → different update.
        tree = {"w": jnp.asarray(rng.normal(size=(4, 4)) * 5, jnp.float32)}
        g = {"w": jnp.asarray(rng.normal(size=(4, 4)) * 0.1, jnp.float32)}
        opt_plain = optim.FusedLAMB(lr=1e-2, weight_decay=0.0,
                                    use_nvlamb=False, max_grad_norm=1e9)
        opt_nv = optim.FusedLAMB(lr=1e-2, weight_decay=0.0,
                                 use_nvlamb=True, max_grad_norm=1e9)
        p1, _ = opt_plain.step(g, opt_plain.init(tree), tree)
        p2, _ = opt_nv.step(g, opt_nv.init(tree), tree)
        assert not np.allclose(np.asarray(p1["w"]), np.asarray(p2["w"]))


class TestNovoGrad:
    def test_vs_gold(self, rng):
        b1, b2, eps, lr, wd = 0.95, 0.98, 1e-8, 1e-2, 0.01
        tree = {"w": jnp.asarray(rng.normal(size=(6, 5)), jnp.float32)}
        opt = optim.FusedNovoGrad(lr=lr, weight_decay=wd,
                                  bias_correction=False)
        state = opt.init(tree)
        p = np.asarray(tree["w"], np.float64)
        m = np.zeros_like(p)
        v = 0.0
        for step in range(1, 5):
            g = grads_like(rng, tree, scale=1.0)
            gn = np.asarray(g["w"], np.float64)
            nsq = (gn ** 2).sum()
            v = nsq if step == 1 else b2 * v + (1 - b2) * nsq
            gp = gn / (np.sqrt(v) + eps) + wd * p
            m = b1 * m + (1 - b1) * gp
            p = p - lr * m
            tree, state = opt.step(g, state, tree)
            np.testing.assert_allclose(np.asarray(tree["w"]), p,
                                       rtol=1e-5, atol=1e-6)


class TestLARC:
    def test_clip_reduces_update(self, rng):
        # huge grads: LARC-clipped step must be smaller than raw SGD step
        tree = {"w": jnp.ones((4, 4), jnp.float32) * 0.01}
        g = {"w": jnp.ones((4, 4), jnp.float32) * 100.0}
        lr = 0.1
        tx = optax.chain(optim.larc(trust_coefficient=0.02,
                                    learning_rate=lr),
                         optim.fused_sgd(lr))
        state = tx.init(tree)
        upd, _ = tx.update(g, state, tree)
        raw = -lr * np.asarray(g["w"])
        np.testing.assert_array_less(np.abs(np.asarray(upd["w"])),
                                     np.abs(raw))

    def test_noop_when_local_lr_large(self, rng):
        # tiny grads → local_lr/lr > 1 → clip to 1 → exact SGD
        tree = {"w": jnp.ones((4,), jnp.float32)}
        g = {"w": jnp.full((4,), 1e-6, jnp.float32)}
        lr = 0.1
        tx = optax.chain(optim.larc(learning_rate=lr), optim.fused_sgd(lr))
        upd, _ = tx.update(g, tx.init(tree), tree)
        np.testing.assert_allclose(np.asarray(upd["w"]),
                                   -lr * np.asarray(g["w"]), rtol=1e-6)


class TestClipGrad:
    def test_clip(self, rng):
        g = {"a": jnp.full((3,), 4.0), "b": jnp.full((4,), 3.0)}
        clipped, norm = optim.clip_grad_norm(g, max_norm=1.0)
        expected_norm = np.sqrt(3 * 16 + 4 * 9)
        np.testing.assert_allclose(float(norm), expected_norm, rtol=1e-6)
        new_norm = float(jnp.sqrt(sum(jnp.sum(x ** 2)
                                      for x in jax.tree.leaves(clipped))))
        np.testing.assert_allclose(new_norm, 1.0, rtol=1e-4)

    def test_noop_below_max(self, rng):
        g = {"a": jnp.full((2,), 0.1)}
        clipped, norm = optim.clip_grad_norm(g, max_norm=10.0)
        np.testing.assert_allclose(np.asarray(clipped["a"]),
                                   np.asarray(g["a"]), rtol=1e-6)


class TestJit:
    def test_adam_step_jits(self, rng):
        tree = make_tree(rng)
        opt = optim.FusedAdam(lr=1e-3)
        state = opt.init(tree)
        g = grads_like(rng, tree)

        @jax.jit
        def step(g, s, p):
            return opt.step(g, s, p)

        p1, s1 = step(g, state, tree)
        p2, s2 = step(g, s1, p1)
        assert int(s2.step) == 2
        assert jnp.all(jnp.isfinite(p2["w"]))
