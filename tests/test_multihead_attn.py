"""Fused MHA module tests — reference analogue:
``apex/contrib/test/multihead_attn/test_{self,encdec}_multihead_attn.py``
(gold = hand-rolled attention; norm_add variants; mask handling)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.contrib import (EncdecMultiheadAttn, SelfMultiheadAttn,
                               SoftmaxCrossEntropyLoss)

S, B, E, H = 24, 2, 32, 4


def _gold_self_attn(params, x, causal=False, mask=None):
    """Hand-rolled reference attention, (S,B,E) layout."""
    qkv = np.asarray(params["in_proj_weight"])
    wo = np.asarray(params["out_proj_weight"])
    x_ = np.asarray(x, np.float32)
    proj = x_ @ qkv
    q, k, v = np.split(proj, 3, axis=-1)
    D = E // H

    def heads(t):
        return t.reshape(S, B, H, D).transpose(1, 2, 0, 3)

    q, k, v = heads(q), heads(k), heads(v)
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    if causal:
        r, c = np.meshgrid(np.arange(S), np.arange(S), indexing="ij")
        s = np.where(c > r, -1e30, s)
    if mask is not None:
        s = s + np.asarray(mask, np.float32)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    ctx = np.einsum("bhqk,bhkd->bhqd", p, v)
    ctx = ctx.transpose(2, 0, 1, 3).reshape(S, B, E)
    return ctx @ wo


@pytest.mark.parametrize("causal", [False, True])
def test_self_attn_matches_gold(rng, causal):
    x = jnp.asarray(rng.normal(size=(S, B, E)), jnp.float32)
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H)
    params = m.init(jax.random.key(0), x)["params"]
    out = m.apply({"params": params}, x, causal=causal, is_training=False)
    gold = _gold_self_attn(params, x, causal=causal)
    np.testing.assert_allclose(out, gold, rtol=1e-4, atol=1e-4)


def test_self_attn_additive_mask(rng):
    x = jnp.asarray(rng.normal(size=(S, B, E)), jnp.float32)
    mask = jnp.where(
        jnp.asarray(rng.random((B, 1, 1, S))) < 0.3, -1e30, 0.0)
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H)
    params = m.init(jax.random.key(0), x)["params"]
    out = m.apply({"params": params}, x, attn_mask=mask, is_training=False)
    gold = _gold_self_attn(params, x, mask=mask)
    np.testing.assert_allclose(out, gold, rtol=1e-4, atol=1e-4)


def test_norm_add_residual(rng):
    x = jnp.asarray(rng.normal(size=(S, B, E)), jnp.float32)
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H, include_norm_add=True)
    params = m.init(jax.random.key(0), x)["params"]
    out = m.apply({"params": params}, x, is_training=False)
    assert "lyr_nrm_gamma_weights" in params
    # zeroing the out-projection must leave exactly the residual
    params2 = dict(params)
    params2["out_proj_weight"] = jnp.zeros_like(params["out_proj_weight"])
    out2 = m.apply({"params": params2}, x, is_training=False)
    np.testing.assert_allclose(out2, x, rtol=1e-6, atol=1e-6)
    assert not np.allclose(out, x)


def test_separate_qkv_params(rng):
    x = jnp.asarray(rng.normal(size=(S, B, E)), jnp.float32)
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H,
                          separate_qkv_params=True)
    params = m.init(jax.random.key(0), x)["params"]
    assert set(params) >= {"q_weight", "k_weight", "v_weight"}
    out = m.apply({"params": params}, x, is_training=False)
    assert out.shape == (S, B, E)


def test_dropout_path(rng):
    x = jnp.asarray(rng.normal(size=(S, B, E)), jnp.float32)
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H, dropout=0.5)
    params = m.init({"params": jax.random.key(0),
                     "dropout": jax.random.key(1)}, x)["params"]
    o1 = m.apply({"params": params}, x, is_training=True,
                 rngs={"dropout": jax.random.key(2)})
    o2 = m.apply({"params": params}, x, is_training=True,
                 rngs={"dropout": jax.random.key(3)})
    o_eval = m.apply({"params": params}, x, is_training=False)
    assert not np.allclose(o1, o2)
    gold = _gold_self_attn(params, x)
    np.testing.assert_allclose(o_eval, gold, rtol=1e-4, atol=1e-4)


def test_encdec_attn(rng):
    Sk = 16
    q = jnp.asarray(rng.normal(size=(S, B, E)), jnp.float32)
    kv = jnp.asarray(rng.normal(size=(Sk, B, E)), jnp.float32)
    m = EncdecMultiheadAttn(embed_dim=E, num_heads=H)
    params = m.init(jax.random.key(0), q, kv)["params"]
    out = m.apply({"params": params}, q, kv, is_training=False)
    assert out.shape == (S, B, E)
    # gold
    wq = np.asarray(params["q_weight"])
    wkv = np.asarray(params["kv_weight"])
    wo = np.asarray(params["out_proj_weight"])
    D = E // H
    qh = (np.asarray(q) @ wq).reshape(S, B, H, D).transpose(1, 2, 0, 3)
    kvp = np.asarray(kv) @ wkv
    kh, vh = np.split(kvp, 2, axis=-1)
    kh = kh.reshape(Sk, B, H, D).transpose(1, 2, 0, 3)
    vh = vh.reshape(Sk, B, H, D).transpose(1, 2, 0, 3)
    s = np.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(D)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ctx = np.einsum("bhqk,bhkd->bhqd", p, vh)
    gold = ctx.transpose(2, 0, 1, 3).reshape(S, B, E) @ wo
    np.testing.assert_allclose(out, gold, rtol=1e-4, atol=1e-4)


def test_grads_flow(rng):
    x = jnp.asarray(rng.normal(size=(S, B, E)), jnp.float32)
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H, include_norm_add=True)
    params = m.init(jax.random.key(0), x)["params"]

    def loss(p):
        return jnp.sum(jnp.square(
            m.apply({"params": p}, x, causal=True, is_training=False)))

    g = jax.grad(loss)(params)
    for leaf in jax.tree.leaves(g):
        assert np.all(np.isfinite(leaf))
        assert float(jnp.sum(jnp.abs(leaf))) > 0


def test_contrib_xentropy_api(rng):
    logits = jnp.asarray(rng.normal(size=(6, 50)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 50, (6,)), jnp.int32)
    loss = SoftmaxCrossEntropyLoss.apply(logits, labels, 0.1, None, True)
    assert loss.shape == (6,)
    crit = SoftmaxCrossEntropyLoss(smoothing=0.1)
    np.testing.assert_allclose(crit(logits, labels), loss)
