"""Fused MHA module tests — reference analogue:
``apex/contrib/test/multihead_attn/test_{self,encdec}_multihead_attn.py``
(gold = hand-rolled attention; norm_add variants; mask handling)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.contrib import (EncdecMultiheadAttn, SelfMultiheadAttn,
                               SoftmaxCrossEntropyLoss)

S, B, E, H = 24, 2, 32, 4


def _gold_self_attn(params, x, causal=False, mask=None):
    """Hand-rolled reference attention, (S,B,E) layout."""
    qkv = np.asarray(params["in_proj_weight"])
    wo = np.asarray(params["out_proj_weight"])
    x_ = np.asarray(x, np.float32)
    proj = x_ @ qkv
    q, k, v = np.split(proj, 3, axis=-1)
    D = E // H

    def heads(t):
        return t.reshape(S, B, H, D).transpose(1, 2, 0, 3)

    q, k, v = heads(q), heads(k), heads(v)
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    if causal:
        r, c = np.meshgrid(np.arange(S), np.arange(S), indexing="ij")
        s = np.where(c > r, -1e30, s)
    if mask is not None:
        s = s + np.asarray(mask, np.float32)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    ctx = np.einsum("bhqk,bhkd->bhqd", p, v)
    ctx = ctx.transpose(2, 0, 1, 3).reshape(S, B, E)
    return ctx @ wo


@pytest.mark.parametrize("causal", [False, True])
def test_self_attn_matches_gold(rng, causal):
    x = jnp.asarray(rng.normal(size=(S, B, E)), jnp.float32)
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H)
    params = m.init(jax.random.key(0), x)["params"]
    out = m.apply({"params": params}, x, causal=causal, is_training=False)
    gold = _gold_self_attn(params, x, causal=causal)
    np.testing.assert_allclose(out, gold, rtol=1e-4, atol=1e-4)


def test_self_attn_additive_mask(rng):
    x = jnp.asarray(rng.normal(size=(S, B, E)), jnp.float32)
    mask = jnp.where(
        jnp.asarray(rng.random((B, 1, 1, S))) < 0.3, -1e30, 0.0)
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H)
    params = m.init(jax.random.key(0), x)["params"]
    out = m.apply({"params": params}, x, attn_mask=mask, is_training=False)
    gold = _gold_self_attn(params, x, mask=mask)
    np.testing.assert_allclose(out, gold, rtol=1e-4, atol=1e-4)


def test_norm_add_residual(rng):
    x = jnp.asarray(rng.normal(size=(S, B, E)), jnp.float32)
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H, include_norm_add=True)
    params = m.init(jax.random.key(0), x)["params"]
    out = m.apply({"params": params}, x, is_training=False)
    assert "lyr_nrm_gamma_weights" in params
    # zeroing the out-projection must leave exactly the residual
    params2 = dict(params)
    params2["out_proj_weight"] = jnp.zeros_like(params["out_proj_weight"])
    out2 = m.apply({"params": params2}, x, is_training=False)
    np.testing.assert_allclose(out2, x, rtol=1e-6, atol=1e-6)
    assert not np.allclose(out, x)


def test_separate_qkv_params(rng):
    x = jnp.asarray(rng.normal(size=(S, B, E)), jnp.float32)
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H,
                          separate_qkv_params=True)
    params = m.init(jax.random.key(0), x)["params"]
    assert set(params) >= {"q_weight", "k_weight", "v_weight"}
    out = m.apply({"params": params}, x, is_training=False)
    assert out.shape == (S, B, E)


def test_dropout_path(rng):
    x = jnp.asarray(rng.normal(size=(S, B, E)), jnp.float32)
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H, dropout=0.5)
    params = m.init({"params": jax.random.key(0),
                     "dropout": jax.random.key(1)}, x)["params"]
    o1 = m.apply({"params": params}, x, is_training=True,
                 rngs={"dropout": jax.random.key(2)})
    o2 = m.apply({"params": params}, x, is_training=True,
                 rngs={"dropout": jax.random.key(3)})
    o_eval = m.apply({"params": params}, x, is_training=False)
    assert not np.allclose(o1, o2)
    gold = _gold_self_attn(params, x)
    np.testing.assert_allclose(o_eval, gold, rtol=1e-4, atol=1e-4)


def test_encdec_attn(rng):
    Sk = 16
    q = jnp.asarray(rng.normal(size=(S, B, E)), jnp.float32)
    kv = jnp.asarray(rng.normal(size=(Sk, B, E)), jnp.float32)
    m = EncdecMultiheadAttn(embed_dim=E, num_heads=H)
    params = m.init(jax.random.key(0), q, kv)["params"]
    out = m.apply({"params": params}, q, kv, is_training=False)
    assert out.shape == (S, B, E)
    # gold
    wq = np.asarray(params["q_weight"])
    wkv = np.asarray(params["kv_weight"])
    wo = np.asarray(params["out_proj_weight"])
    D = E // H
    qh = (np.asarray(q) @ wq).reshape(S, B, H, D).transpose(1, 2, 0, 3)
    kvp = np.asarray(kv) @ wkv
    kh, vh = np.split(kvp, 2, axis=-1)
    kh = kh.reshape(Sk, B, H, D).transpose(1, 2, 0, 3)
    vh = vh.reshape(Sk, B, H, D).transpose(1, 2, 0, 3)
    s = np.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(D)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ctx = np.einsum("bhqk,bhkd->bhqd", p, vh)
    gold = ctx.transpose(2, 0, 1, 3).reshape(S, B, E) @ wo
    np.testing.assert_allclose(out, gold, rtol=1e-4, atol=1e-4)


def test_grads_flow(rng):
    x = jnp.asarray(rng.normal(size=(S, B, E)), jnp.float32)
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H, include_norm_add=True)
    params = m.init(jax.random.key(0), x)["params"]

    def loss(p):
        return jnp.sum(jnp.square(
            m.apply({"params": p}, x, causal=True, is_training=False)))

    g = jax.grad(loss)(params)
    for leaf in jax.tree.leaves(g):
        assert np.all(np.isfinite(leaf))
        assert float(jnp.sum(jnp.abs(leaf))) > 0


def test_contrib_xentropy_api(rng):
    logits = jnp.asarray(rng.normal(size=(6, 50)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 50, (6,)), jnp.int32)
    loss = SoftmaxCrossEntropyLoss.apply(logits, labels, 0.1, None, True)
    assert loss.shape == (6,)
    crit = SoftmaxCrossEntropyLoss(smoothing=0.1)
    np.testing.assert_allclose(crit(logits, labels), loss)


# ---------------------------------------------------------------------------
# no-materialization probe: SelfMultiheadAttn(dropout>0) must stay on the
# flash kernel — NO O(S²) probability tensor in the traced program
# (the pre-PR-5 module fell back to the materialized composite whenever
# attention-probability dropout was active, degrading the fused
# capability on exactly the BERT-pretrain headline workload)
# ---------------------------------------------------------------------------

def _nonkernel_avals(jaxpr, out):
    """Every intermediate aval OUTSIDE pallas kernel bodies: kernel-
    internal tiles are VMEM-resident blocks (bounded by block_q/block_k),
    not HBM tensors — the probe asserts nothing S×S exists in HBM."""
    for eqn in jaxpr.eqns:
        for var in eqn.outvars:
            out.append(var.aval)
        if eqn.primitive.name == "pallas_call":
            continue

        def visit(val):
            if isinstance(val, jax.core.ClosedJaxpr):
                _nonkernel_avals(val.jaxpr, out)
            elif isinstance(val, jax.core.Jaxpr):
                _nonkernel_avals(val, out)
            elif isinstance(val, (tuple, list)):
                for item in val:
                    visit(item)

        for val in eqn.params.values():
            visit(val)


def _probe_s2(fn, *args, seq):
    jaxpr = jax.make_jaxpr(fn)(*args)
    avals = []
    _nonkernel_avals(jaxpr.jaxpr, avals)
    return [a for a in avals
            if getattr(a, "ndim", 0) >= 2 and a.shape[-1] == seq
            and a.shape[-2] == seq]


def test_dropout_no_s2_materialization(rng):
    from apex1_tpu.ops._common import force_impl

    # S prime-ish and distinct from B/E/H so an S×S aval is unambiguous
    Sp = 72
    x = jnp.asarray(rng.normal(size=(Sp, B, E)), jnp.float32)
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H, dropout=0.1)
    params = m.init({"params": jax.random.key(0),
                     "dropout": jax.random.key(1)}, x)["params"]

    def fwd(params, x):
        with force_impl("pallas"):
            return m.apply({"params": params}, x, is_training=True,
                           rngs={"dropout": jax.random.key(2)})

    assert _probe_s2(fwd, params, x, seq=Sp) == [], \
        "dropout>0 forward materialized an S×S tensor"

    def loss(params, x):
        return jnp.sum(fwd(params, x) ** 2)

    assert _probe_s2(jax.grad(loss), params, x, seq=Sp) == [], \
        "dropout>0 backward materialized an S×S tensor"

    # negative control — the probe must be falsifiable: the XLA
    # composite path DOES materialize S×S probabilities
    def fwd_xla(params, x):
        with force_impl("xla"):
            return m.apply({"params": params}, x, is_training=True,
                           rngs={"dropout": jax.random.key(2)})

    assert _probe_s2(fwd_xla, params, x, seq=Sp), \
        "probe failed to flag the materialized composite"


def test_dropout_stays_on_flash_with_mask_and_norm_add(rng):
    """The full SelfMHA feature set (additive mask + norm_add epilogue)
    composes with in-kernel dropout — still no S×S materialization."""
    from apex1_tpu.ops._common import force_impl

    Sp = 72
    x = jnp.asarray(rng.normal(size=(Sp, B, E)), jnp.float32)
    mask = jnp.asarray(rng.normal(size=(B, 1, 1, Sp)) < 0, jnp.float32)
    mask = mask * -1e9
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H, dropout=0.1,
                          include_norm_add=True)
    params = m.init({"params": jax.random.key(0),
                     "dropout": jax.random.key(1)}, x)["params"]

    def fwd(params, x):
        with force_impl("pallas"):
            return m.apply({"params": params}, x, attn_mask=mask,
                           is_training=True,
                           rngs={"dropout": jax.random.key(2)})

    # the broadcast additive mask rides the kernel bias operand at
    # (B, 1, Sp, Sp)... which has head dim 1, not S — only a true
    # (.., Sp, Sp) PROBABILITY tensor (B, H, Sp, Sp) would trip probes
    # keyed on the last two dims; accept the (1-head) bias operand
    hits = _probe_s2(fwd, params, x, seq=Sp)
    assert all(a.ndim >= 3 and a.shape[-3] == 1 for a in hits), hits
