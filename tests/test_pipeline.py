"""Pipeline-parallel tests — ≙ ``tests/L0/run_transformer/
test_pipeline_parallel_fwd_bwd.py``: pipeline loss/grads must match the same
model run unpartitioned, for the 1F1B-equivalent (V=1) and interleaved
(V>1) schedules, plus the no-pipelining grad-accumulation schedule."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.core.mesh import make_mesh
from apex1_tpu.transformer import parallel_state
from apex1_tpu.transformer.microbatches import (
    build_num_microbatches_calculator)
from apex1_tpu.transformer.pipeline_parallel import schedules


D = 16  # feature width


def stage_fn(params, x):
    """One pipeline chunk = linear + tanh (shape-preserving)."""
    return jnp.tanh(x @ params["w"] + params["b"])


def full_model(all_chunk_params, x):
    """Unpartitioned gold: apply all chunks in order. Leaves are (V, P, ...)
    chunk-major; execution order is chunk 0 stages 0..P-1, chunk 1 ..."""
    V, P = all_chunk_params["w"].shape[:2]
    for v in range(V):
        for s in range(P):
            params = {k: p[v, s] for k, p in all_chunk_params.items()}
            x = stage_fn(params, x)
    return x


def make_params(rng, V, P):
    return {
        "w": jnp.asarray(rng.normal(size=(V, P, D, D)) * 0.5, jnp.float32),
        "b": jnp.asarray(rng.normal(size=(V, P, D)) * 0.1, jnp.float32),
    }


def loss_fn(outs, targets):
    return jnp.mean((outs - targets) ** 2)


@pytest.fixture()
def mesh(devices):
    return make_mesh(pp=4)


class TestPipelineForward:
    @pytest.mark.parametrize("V,M", [(1, 4), (1, 6), (2, 4), (2, 6)])
    def test_forward_matches_unpartitioned(self, mesh, rng, V, M):
        P = 4
        params = make_params(rng, V, P)
        mbs = jnp.asarray(rng.normal(size=(M, 2, D)), jnp.float32)
        targets = jnp.zeros_like(mbs)

        f = schedules.pipelined_loss_fn(stage_fn, loss_fn, mesh,
                                        num_chunks=V)
        loss = f(params, mbs, targets)
        gold_outs = jax.vmap(lambda x: full_model(params, x))(mbs)
        gold_loss = loss_fn(gold_outs, targets)
        np.testing.assert_allclose(float(loss), float(gold_loss),
                                   rtol=1e-5)

    def test_interleaved_requires_enough_microbatches(self, mesh, rng):
        params = make_params(rng, 2, 4)
        mbs = jnp.asarray(rng.normal(size=(2, 2, D)), jnp.float32)
        f = schedules.pipelined_loss_fn(stage_fn, loss_fn, mesh,
                                        num_chunks=2)
        with pytest.raises(ValueError):
            f(params, mbs, jnp.zeros_like(mbs))


@pytest.mark.slow
class TestPipelineBackward:
    @pytest.mark.parametrize("V,M", [(1, 4), (2, 4)])
    def test_grads_match_unpartitioned(self, mesh, rng, V, M):
        P = 4
        params = make_params(rng, V, P)
        mbs = jnp.asarray(rng.normal(size=(M, 2, D)), jnp.float32)
        targets = jnp.asarray(rng.normal(size=(M, 2, D)), jnp.float32)

        loss, grads = (
            schedules.forward_backward_pipelining_without_interleaving(
                stage_fn, loss_fn, mesh, params, mbs, targets)
            if V == 1 else
            schedules.forward_backward_pipelining_with_interleaving(
                stage_fn, loss_fn, mesh, params, mbs, targets,
                num_chunks=V))

        def gold(params):
            outs = jax.vmap(lambda x: full_model(params, x))(mbs)
            return loss_fn(outs, targets)

        gold_loss, gold_grads = jax.value_and_grad(gold)(params)
        np.testing.assert_allclose(float(loss), float(gold_loss), rtol=1e-5)
        for k in ("w", "b"):
            np.testing.assert_allclose(np.asarray(grads[k]),
                                       np.asarray(gold_grads[k]),
                                       rtol=1e-4, atol=1e-5)

    def test_jit_compiles_once(self, mesh, rng):
        params = make_params(rng, 1, 4)
        mbs = jnp.asarray(rng.normal(size=(4, 2, D)), jnp.float32)
        targets = jnp.zeros_like(mbs)
        f = schedules.pipelined_loss_fn(stage_fn, loss_fn, mesh)
        jf = jax.jit(jax.value_and_grad(f))
        l1, g1 = jf(params, mbs, targets)
        l2, g2 = jf(params, mbs, targets)
        assert np.isfinite(float(l1)) and float(l1) == float(l2)


@pytest.mark.slow
class TestTiedEmbedding:
    """≙ the reference's embedding-group semantics: tied vocab embedding on
    first+last stages, grads combined by the embedding-group all-reduce,
    must match the unpartitioned tied model exactly."""

    V_SIZE = 12  # vocab

    @staticmethod
    def embed_fn(tied, tokens):
        return tied["emb"][tokens]

    @staticmethod
    def head_fn(tied, outs):
        # (M, B, D) @ (V, D)^T -> per-microbatch mean CE against token 0
        logits = jnp.einsum("mbd,vd->mbv", outs, tied["emb"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.mean(logp[..., 0], axis=(1,))  # (M,)

    def _params(self, rng, P):
        chunk = make_params(rng, 1, P)
        tied = {"emb": jnp.asarray(
            rng.normal(size=(self.V_SIZE, D)) * 0.5, jnp.float32)}
        return chunk, tied

    def _gold(self, chunk, tied, tokens):
        def gold(chunk_params, tied_params):
            h = jax.vmap(lambda t: self.embed_fn(tied_params, t))(tokens)
            outs = jax.vmap(lambda x: full_model(chunk_params, x))(h)
            return jnp.mean(self.head_fn(tied_params, outs))

        return jax.value_and_grad(gold, argnums=(0, 1))(chunk, tied)

    @pytest.mark.parametrize("M", [4, 6])
    def test_tied_grads_outer_convention(self, mesh, rng, M):
        """broadcast_outputs=True + grad OUTSIDE shard_map: shard_map's
        replicated-input transpose is the embedding-group all-reduce."""
        from jax.sharding import PartitionSpec as Ps
        P = 4
        chunk, tied = self._params(rng, P)
        tokens = jnp.asarray(rng.integers(0, self.V_SIZE, (M, 2)),
                             jnp.int32)

        def inner(chunk_params, tied_params, tokens_mb):
            local = jax.tree_util.tree_map(lambda p: p[:, 0], chunk_params)
            per_mb = schedules.pipeline_tied_apply(
                stage_fn, local, self.embed_fn, self.head_fn,
                tied_params, tokens_mb)
            return jnp.mean(per_mb)

        def f(chunk_params, tied_params, tokens_mb):
            return jax.shard_map(
                inner, mesh=mesh,
                in_specs=(Ps(None, "pp"), Ps(), Ps()),
                out_specs=Ps(), check_vma=False)(
                    chunk_params, tied_params, tokens_mb)

        loss, (g_chunk, g_tied) = jax.value_and_grad(
            f, argnums=(0, 1))(chunk, tied, tokens)
        gold_loss, (gold_chunk, gold_tied) = self._gold(chunk, tied, tokens)

        np.testing.assert_allclose(float(loss), float(gold_loss), rtol=1e-5)
        for k in ("w", "b"):
            np.testing.assert_allclose(np.asarray(g_chunk[k]),
                                       np.asarray(gold_chunk[k]),
                                       rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(g_tied["emb"]),
                                   np.asarray(gold_tied["emb"]),
                                   rtol=1e-4, atol=1e-5)

    def test_tied_grads_inside_convention(self, mesh, rng):
        """broadcast_outputs=False + grad INSIDE shard_map (whole-train-
        step-in-one-shard_map, the dryrun pattern): partial losses, then
        the explicit embedding-group all-reduce combines tied grads."""
        from jax.sharding import PartitionSpec as Ps
        P, M = 4, 4
        chunk, tied = self._params(rng, P)
        tokens = jnp.asarray(rng.integers(0, self.V_SIZE, (M, 2)),
                             jnp.int32)

        def g_inner(chunk_params, tied_params, tokens_mb):
            local = jax.tree_util.tree_map(lambda p: p[:, 0], chunk_params)

            def scalar(local, tp):
                per_mb = schedules.pipeline_tied_apply(
                    stage_fn, local, self.embed_fn, self.head_fn,
                    tp, tokens_mb, broadcast_outputs=False)
                return jnp.mean(per_mb)  # PARTIAL: sums to loss over pp

            loss_part, (g_local, g_tied) = jax.value_and_grad(
                scalar, argnums=(0, 1))(local, tied_params)
            loss = jax.lax.psum(loss_part, "pp")  # logging broadcast
            g_tied = schedules.allreduce_embedding_grads(g_tied)
            # chunk grads are per-stage local; restore the stage dim
            g_chunk = jax.tree_util.tree_map(lambda g: g[:, None], g_local)
            return loss, g_chunk, g_tied

        loss, g_chunk, g_tied = jax.shard_map(
            g_inner, mesh=mesh,
            in_specs=(Ps(None, "pp"), Ps(), Ps()),
            out_specs=(Ps(), Ps(None, "pp"), Ps()), check_vma=False)(
                chunk, tied, tokens)
        gold_loss, (gold_chunk, gold_tied) = self._gold(chunk, tied, tokens)

        np.testing.assert_allclose(float(loss), float(gold_loss), rtol=1e-5)
        for k in ("w", "b"):
            np.testing.assert_allclose(np.asarray(g_chunk[k]),
                                       np.asarray(gold_chunk[k]),
                                       rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(g_tied["emb"]),
                                   np.asarray(gold_tied["emb"]),
                                   rtol=1e-4, atol=1e-5)

    def test_inside_grad_partial_convention_pipeline_apply(self, mesh, rng):
        """Chunk grads taken INSIDE shard_map with broadcast_outputs=False
        match the unpartitioned model (the broadcast form would scale them
        by P — transpose(psum) = psum with per-rank seeds)."""
        from jax.sharding import PartitionSpec as Ps
        P, M = 4, 4
        params = make_params(rng, 1, P)
        mbs = jnp.asarray(rng.normal(size=(M, 2, D)), jnp.float32)
        targets = jnp.asarray(rng.normal(size=(M, 2, D)), jnp.float32)

        def g_inner(params, mbs, targets):
            local = jax.tree_util.tree_map(lambda p: p[:, 0], params)
            s = jax.lax.axis_index("pp")
            last = (s == jax.lax.axis_size("pp") - 1).astype(jnp.float32)

            def scalar(local):
                outs = schedules.pipeline_apply(stage_fn, local, mbs,
                                                broadcast_outputs=False)
                return last * loss_fn(outs, targets)  # PARTIAL loss

            g = jax.grad(scalar)(local)
            return jax.tree_util.tree_map(lambda g: g[:, None], g)

        g = jax.shard_map(
            g_inner, mesh=mesh, in_specs=(Ps(None, "pp"), Ps(), Ps()),
            out_specs=Ps(None, "pp"), check_vma=False)(params, mbs, targets)

        def gold(params):
            outs = jax.vmap(lambda x: full_model(params, x))(mbs)
            return loss_fn(outs, targets)

        gold_grads = jax.grad(gold)(params)
        for k in ("w", "b"):
            np.testing.assert_allclose(np.asarray(g[k]),
                                       np.asarray(gold_grads[k]),
                                       rtol=1e-4, atol=1e-5)

    def test_embedding_group_getters(self, devices, mesh):
        from jax.sharding import PartitionSpec as Ps
        parallel_state.destroy_model_parallel()
        parallel_state.initialize_model_parallel(1, 4)
        assert parallel_state.get_embedding_group() == "pp"
        in_group = jax.shard_map(
            lambda: parallel_state.is_rank_in_embedding_group()[None],
            mesh=mesh, in_specs=(), out_specs=Ps("pp"),
            check_vma=False)()
        assert list(np.asarray(in_group)) == [True, False, False, True]
        parallel_state.destroy_model_parallel()


class TestNoPipelining:
    def test_grad_accumulation_matches_full_batch(self, rng):
        params = {"w": jnp.asarray(rng.normal(size=(D, D)) * 0.5,
                                   jnp.float32)}
        data = jnp.asarray(rng.normal(size=(8, 2, D)), jnp.float32)

        def loss(p, mb):
            return jnp.mean((jnp.tanh(mb @ p["w"]) - 1.0) ** 2)

        mean_loss, grads = schedules.forward_backward_no_pipelining(
            loss, params, data)
        gold_loss = jnp.mean(jnp.stack([loss(params, data[i])
                                        for i in range(8)]))
        gold_grads = jax.grad(
            lambda p: jnp.mean(jnp.stack(
                [loss(p, data[i]) for i in range(8)])))(params)
        np.testing.assert_allclose(float(mean_loss), float(gold_loss),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(grads["w"]),
                                   np.asarray(gold_grads["w"]), rtol=1e-4,
                                   atol=1e-7)


class TestScheduleSelection:
    def test_get_forward_backward_func(self, devices):
        parallel_state.destroy_model_parallel()
        parallel_state.initialize_model_parallel(1, 1)
        assert (schedules.get_forward_backward_func()
                is schedules.forward_backward_no_pipelining)
        parallel_state.destroy_model_parallel()
        parallel_state.initialize_model_parallel(1, 4)
        assert (schedules.get_forward_backward_func()
                is schedules.forward_backward_pipelining_without_interleaving)
        parallel_state.destroy_model_parallel()
        parallel_state.initialize_model_parallel(
            1, 4, virtual_pipeline_model_parallel_size=2)
        assert (schedules.get_forward_backward_func()
                is schedules.forward_backward_pipelining_with_interleaving)
        parallel_state.destroy_model_parallel()


class TestMicrobatchCalculator:
    def test_constant(self):
        c = build_num_microbatches_calculator(None, 64, 4, 2)
        assert c.get() == 8
        assert c.get_current_global_batch_size() == 64
        with pytest.raises(ValueError):
            build_num_microbatches_calculator(None, 65, 4, 2)

    def test_rampup(self):
        c = build_num_microbatches_calculator((16, 8, 1000), 64, 4, 2)
        assert c.get_current_global_batch_size() == 16
        assert c.get() == 2
        c.update(500)
        assert c.get_current_global_batch_size() == 40
        c.update(2000)
        assert c.get_current_global_batch_size() == 64
        assert c.get() == 8


class TestSkipBubbles:
    """Pin the ``skip_bubbles`` collective contract (schedules docstring):
    group-scoped collectives inside ``stage_fn`` must give EXACTLY the
    masked-bubble result under the per-tick cond; ppermute is the
    documented-unsafe class (single collective-permute rendezvous spans
    the mesh, so skipping ranks desynchronize tick pairing)."""

    @staticmethod
    def _pipe_loss(mesh, params, mbs, stage, skip):
        from jax.sharding import PartitionSpec as Ps

        def inner(params, mbs):
            s = jax.lax.axis_index("pp")
            last = (s == 1).astype(jnp.float32)
            outs = schedules.pipeline_apply(
                stage, params[:, 0], mbs, broadcast_outputs=False,
                skip_bubbles=skip)
            return jax.lax.psum(last * jnp.mean(jnp.square(outs)), "pp")

        return float(jax.jit(lambda p: jax.shard_map(
            inner, mesh=mesh, in_specs=(Ps(None, "pp"), Ps()),
            out_specs=Ps(), check_vma=False)(p, mbs))(params))

    @pytest.mark.parametrize("kind", ["none", "psum", "all_gather",
                                      "all_to_all", "ppermute"])
    def test_collective_classes(self, devices, kind):
        mesh = make_mesh(pp=2, cp=2)
        rng = np.random.default_rng(0)
        params = jnp.asarray(rng.normal(size=(1, 2, D, D)) * 0.2,
                             jnp.float32)
        mbs = jnp.asarray(rng.normal(size=(4, 6, D)), jnp.float32)

        def stage(w, x):
            y = jnp.tanh(x @ w)
            if kind == "psum":
                y2 = jax.lax.psum(y, "cp") / 2.0
            elif kind == "all_gather":
                g = jax.lax.all_gather(y, "cp")
                y2 = g[0] + g[1]
            elif kind == "all_to_all":
                a = jax.lax.all_to_all(y.reshape(2, 3, D), "cp", 0, 0)
                y2 = a.reshape(6, D)
            elif kind == "ppermute":
                y2 = jax.lax.ppermute(y, "cp", perm=[(0, 1), (1, 0)])
            else:
                y2 = y
            return x + y + 0.5 * y2

        mask = self._pipe_loss(mesh, params, mbs, stage, skip=False)
        if kind == "ppermute":
            # the contract is now ENFORCED at trace time (VERDICT r3
            # Weak #3): the formerly-silent ~2e-3 divergence is
            # unreachable — skip_bubbles=True + ppermute raises instead.
            # (If cond+ppermute ever becomes safe on TPU, lift the gate
            # in schedules._check_skippable and re-verify on hardware.)
            with pytest.raises(ValueError, match="ppermute"):
                self._pipe_loss(mesh, params, mbs, stage, skip=True)
            assert np.isfinite(mask)  # masked path stays the escape hatch
        else:
            skip = self._pipe_loss(mesh, params, mbs, stage, skip=True)
            assert mask == skip, (
                f"{kind}: cond-skip diverged from masked bubbles "
                f"({skip} vs {mask})")


class TestVariableBoundary:
    """SURVEY #56 (`decoder_seq_length` / `_communicate` shape
    negotiation): heterogeneous stage shapes via the pad-to-max boundary.
    Encoder-decoder toy — stage 0 consumes a 4-row microbatch padded into
    an 8-row boundary; decoder stages mask to their 4-row extent by stage
    index. Pipelined loss/grads must match the flat composition."""

    @pytest.mark.slow  # 870s-cap headroom (16s encdec pipeline
    # compile); the boundary CONTRACT check below stays tier-1, full
    # pad-vs-flat parity runs via check_all.sh --all
    def test_encdec_pad_to_max_matches_flat(self, devices):
        from jax.sharding import PartitionSpec as Ps

        P_, M_, S_in, S_b, mb = 4, 8, 4, 8, 2
        mesh = make_mesh(pp=P_)
        rng = np.random.default_rng(3)
        params = jnp.asarray(rng.normal(size=(1, P_, D, D)) * 0.5,
                             jnp.float32)
        mbs = jnp.asarray(rng.normal(size=(M_, S_in, mb, D)), jnp.float32)
        tgt = jnp.asarray(rng.normal(size=(M_, S_in, mb, D)), jnp.float32)
        rows = jnp.arange(S_b)

        def stage_at(w, x8, s):
            enc = jnp.tanh(x8 @ w)                       # all 8 rows
            dec = jnp.where((rows < S_in)[:, None, None],
                            jnp.tanh(x8 @ w), 0.0)       # 4-row extent
            return jnp.where(s == 0, enc, dec)

        def pipe_loss(params, mbs):
            def inner(params, mbs):
                s = jax.lax.axis_index("pp")
                outs = schedules.pipeline_apply(
                    lambda w, x: stage_at(w, x, s),
                    params[:, 0], mbs,
                    boundary_shape=(S_b, mb, D))
                return jnp.mean(jnp.square(outs[:, :S_in] - tgt))

            return jax.shard_map(
                inner, mesh=mesh, in_specs=(Ps(None, "pp"), Ps()),
                out_specs=Ps(), check_vma=False)(params, mbs)

        def flat_loss(params, mbs):
            def one(x):
                x8 = jnp.pad(x, ((0, S_b - S_in), (0, 0), (0, 0)))
                for s in range(P_):
                    x8 = stage_at(params[0, s], x8, s)
                return x8
            outs = jax.vmap(one)(mbs)
            return jnp.mean(jnp.square(outs[:, :S_in] - tgt))

        got, g_got = jax.value_and_grad(lambda p: pipe_loss(p, mbs))(params)
        want, g_want = jax.value_and_grad(
            lambda p: flat_loss(p, mbs))(params)
        np.testing.assert_allclose(float(got), float(want), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_want),
                                   rtol=1e-5, atol=1e-6)

    def test_boundary_shape_must_cover(self, devices):
        mesh = make_mesh(pp=4)
        from jax.sharding import PartitionSpec as Ps
        params = jnp.zeros((1, 4, D, D))
        mbs = jnp.zeros((4, 8, 2, D))

        def run():
            def inner(params, mbs):
                return schedules.pipeline_apply(
                    lambda w, x: x, params[:, 0], mbs,
                    boundary_shape=(4, 2, D))  # narrower than microbatch
            return jax.shard_map(inner, mesh=mesh,
                                 in_specs=(Ps(None, "pp"), Ps()),
                                 out_specs=Ps(), check_vma=False)(
                params, mbs)

        with pytest.raises(ValueError, match="cover"):
            run()


class Test1F1B:
    """True 1F1B (`schedules.one_f_one_b`): staggered fwd/bwd in one scan
    with the VJP-residual ring — loss, param grads, and microbatch-input
    cotangents must match the flat composition, with and without the
    idle-tick cond, and with group-scoped collectives in the stage."""

    @staticmethod
    def _run(mesh, P_, params, mbs, tgt, stage, skip):
        from jax.sharding import PartitionSpec as Ps

        M_ = mbs.shape[0]

        def loss_mb(y, m):
            t = jax.lax.dynamic_index_in_dim(tgt, m, 0, keepdims=False)
            return jnp.mean(jnp.square(y - t)) / M_

        def inner(params, mbs):
            local = jax.tree_util.tree_map(lambda p: p[0], params)
            loss, grads, dmb = schedules.one_f_one_b(
                stage, local, mbs, loss_mb, skip_idle=skip)
            return (jax.lax.psum(loss, "pp"),
                    jax.tree_util.tree_map(lambda g: g[None], grads), dmb)

        pspec = jax.tree_util.tree_map(lambda _: Ps("pp"), params)
        extra = {ax: Ps() for ax in mesh.axis_names if ax != "pp"}
        return jax.jit(jax.shard_map(
            inner, mesh=mesh, in_specs=(pspec, Ps()),
            out_specs=(Ps(), pspec, Ps()), check_vma=False))(params, mbs)

    @pytest.mark.parametrize("skip", [True, False],
                             ids=["cond-skip", "masked"])
    def test_matches_flat(self, devices, skip):
        mesh = make_mesh(pp=4)
        P_, M_, mb = 4, 6, 3
        rng = np.random.default_rng(5)
        params = {
            "w": jnp.asarray(rng.normal(size=(P_, D, D)) * 0.5,
                             jnp.float32),
            "b": jnp.asarray(rng.normal(size=(P_, D)) * 0.1, jnp.float32)}
        mbs = jnp.asarray(rng.normal(size=(M_, mb, D)), jnp.float32)
        tgt = jnp.asarray(rng.normal(size=(M_, mb, D)), jnp.float32)

        def stage(p, x):
            return jnp.tanh(x @ p["w"] + p["b"])

        loss, grads, dmb = self._run(mesh, P_, params, mbs, tgt, stage,
                                     skip)

        def flat(params, mbs):
            def one(x, t):
                for s in range(P_):
                    x = stage(jax.tree_util.tree_map(lambda p: p[s],
                                                     params), x)
                return jnp.mean(jnp.square(x - t)) / M_
            return jnp.sum(jax.vmap(one)(mbs, tgt))

        want, (gp, gx) = jax.value_and_grad(flat, argnums=(0, 1))(
            params, mbs)
        np.testing.assert_allclose(float(loss), float(want), rtol=1e-5)
        for k in params:
            np.testing.assert_allclose(np.asarray(grads[k]),
                                       np.asarray(gp[k]),
                                       rtol=1e-5, atol=1e-6, err_msg=k)
        np.testing.assert_allclose(np.asarray(dmb), np.asarray(gx),
                                   rtol=1e-5, atol=1e-6)

    def test_ppermute_stage_raises_under_skip_idle(self, devices):
        """The skip_idle collective contract is trace-time-enforced
        (VERDICT r3 Weak #3): a ring-attention-shaped (ppermute-bearing)
        stage under skip_idle=True must fail LOUDLY at trace time, not
        corrupt silently; skip_idle=False stays the working path."""
        mesh = make_mesh(pp=2, cp=2)
        P_, M_, mb = 2, 4, 2
        rng = np.random.default_rng(11)
        params = {"w": jnp.asarray(rng.normal(size=(P_, D, D)) * 0.5,
                                   jnp.float32)}
        mbs = jnp.asarray(rng.normal(size=(M_, mb, D)), jnp.float32)
        tgt = jnp.asarray(rng.normal(size=(M_, mb, D)), jnp.float32)

        def stage(p, x):
            y = jnp.tanh(x @ p["w"])
            return y + 0.5 * jax.lax.ppermute(y, "cp",
                                              perm=[(0, 1), (1, 0)])

        with pytest.raises(ValueError, match="skip_idle"):
            self._run(mesh, P_, params, mbs, tgt, stage, skip=True)
        loss, _, _ = self._run(mesh, P_, params, mbs, tgt, stage,
                               skip=False)
        assert np.isfinite(float(loss))

    # the (2, 2, 8) case drives M/P = 4 > G_live = 2 groups, exercising
    # residual-ring slot REUSE across groups (g mod G_live wraparound)
    @pytest.mark.parametrize("V,P_,M_",
                             [(2, 2, 4), (3, 2, 4), (2, 4, 4), (2, 2, 8)],
                             ids=["V2P2", "V3P2", "V2P4", "V2P2M8-reuse"])
    @pytest.mark.parametrize("skip", [True, False],
                             ids=["cond-skip", "masked"])
    def test_interleaved_matches_flat(self, devices, V, P_, M_, skip):
        """Interleaved (V>1) true 1F1B: group-cycled chunk schedule with
        recirculation FIFOs on both rings — loss, per-chunk param grads,
        and input cotangents must match the flat V·P-deep composition."""
        from jax.sharding import PartitionSpec as Ps

        mesh = make_mesh(pp=P_)
        mb = 3
        rng = np.random.default_rng(5)
        params = {
            "w": jnp.asarray(rng.normal(size=(V, P_, D, D)) * 0.5,
                             jnp.float32),
            "b": jnp.asarray(rng.normal(size=(V, P_, D)) * 0.1,
                             jnp.float32)}
        mbs = jnp.asarray(rng.normal(size=(M_, mb, D)), jnp.float32)
        tgt = jnp.asarray(rng.normal(size=(M_, mb, D)), jnp.float32)

        def stage(p, x):
            return jnp.tanh(x @ p["w"] + p["b"])

        def loss_mb(y, m):
            t = jax.lax.dynamic_index_in_dim(tgt, m, 0, keepdims=False)
            return jnp.mean(jnp.square(y - t)) / M_

        def inner(params, mbs):
            local = jax.tree_util.tree_map(lambda p: p[:, 0], params)
            loss, grads, dmb = schedules.one_f_one_b(
                stage, local, mbs, loss_mb, num_chunks=V,
                skip_idle=skip)
            return (jax.lax.psum(loss, "pp"),
                    jax.tree_util.tree_map(lambda g: g[:, None], grads),
                    dmb)

        pspec = jax.tree_util.tree_map(lambda _: Ps(None, "pp"), params)
        loss, grads, dmb = jax.jit(jax.shard_map(
            inner, mesh=mesh, in_specs=(pspec, Ps()),
            out_specs=(Ps(), pspec, Ps()), check_vma=False))(params, mbs)

        def flat(params, mbs):
            def one(x, t):
                for v in range(V):
                    for st in range(P_):
                        x = stage(jax.tree_util.tree_map(
                            lambda p: p[v, st], params), x)
                return jnp.mean(jnp.square(x - t)) / M_
            return jnp.sum(jax.vmap(one)(mbs, tgt))

        want, (gp, gx) = jax.value_and_grad(flat, argnums=(0, 1))(
            params, mbs)
        np.testing.assert_allclose(float(loss), float(want), rtol=1e-5)
        for k in params:
            np.testing.assert_allclose(np.asarray(grads[k]),
                                       np.asarray(gp[k]),
                                       rtol=1e-5, atol=1e-6, err_msg=k)
        np.testing.assert_allclose(np.asarray(dmb), np.asarray(gx),
                                   rtol=1e-5, atol=1e-6)

    def test_interleaved_rejects_bad_m(self, devices):
        from jax.sharding import PartitionSpec as Ps

        mesh = make_mesh(pp=2)
        params = {"w": jnp.zeros((2, 2, D, D))}
        mbs = jnp.zeros((3, 2, D))  # 3 % 2 != 0

        def inner(params, mbs):
            local = jax.tree_util.tree_map(lambda p: p[:, 0], params)
            return schedules.one_f_one_b(
                stage_fn, local, mbs, lambda y, m: jnp.sum(y),
                num_chunks=2)[0]

        with pytest.raises(ValueError, match="interleaved 1F1B"):
            jax.shard_map(
                inner, mesh=mesh,
                in_specs=(jax.tree_util.tree_map(
                    lambda _: Ps(None, "pp"), params), Ps()),
                out_specs=Ps(), check_vma=False)(params, mbs)

    @pytest.mark.parametrize("skip", [True, False],
                             ids=["cond-skip", "masked"])
    def test_loss_params_and_aux_match_flat(self, devices, skip):
        """The post-process channels: loss_params (an LM-head-style
        parameter used only inside loss_mb, grads accumulated on the
        last stage) and the aux side objective (stage returns (y, aux);
        cotangent seeded per backward tick). Objective:
        sum_m loss_mb + C_AUX * sum_{s,m} aux."""
        from jax.sharding import PartitionSpec as Ps

        mesh = make_mesh(pp=4)
        P_, M_, mb = 4, 5, 2
        C_AUX = 0.3
        rng = np.random.default_rng(7)
        params = {"w": jnp.asarray(rng.normal(size=(P_, D, D)) * 0.5,
                                   jnp.float32)}
        lp0 = {"v": jnp.asarray(rng.normal(size=(D, D)) * 0.5,
                                jnp.float32)}
        mbs = jnp.asarray(rng.normal(size=(M_, mb, D)), jnp.float32)
        tgt = jnp.asarray(rng.normal(size=(M_, mb, D)), jnp.float32)

        def stage_aux(p, x):
            y = jnp.tanh(x @ p["w"])
            return y, jnp.mean(jnp.square(y)) * jnp.sum(p["w"][0, :2])

        def loss_with_lp(lp, y, m):
            t = jax.lax.dynamic_index_in_dim(tgt, m, 0, keepdims=False)
            return jnp.mean(jnp.square(y @ lp["v"] - t)) / M_

        def inner(params, lp, mbs):
            local = jax.tree_util.tree_map(lambda p: p[0], params)
            loss, grads, dmb, dlp, aux_sum = schedules.one_f_one_b(
                stage_aux, local, mbs, loss_with_lp, skip_idle=skip,
                loss_params=lp, with_aux=True, aux_cotangent=C_AUX)
            total = jax.lax.psum(loss + C_AUX * aux_sum, "pp")
            return (total,
                    jax.tree_util.tree_map(lambda g: g[None], grads),
                    dmb, jax.lax.psum(dlp["v"], "pp"))

        pspec = jax.tree_util.tree_map(lambda _: Ps("pp"), params)
        loss, grads, dmb, dv = jax.jit(jax.shard_map(
            inner, mesh=mesh, in_specs=(pspec, Ps(), Ps()),
            out_specs=(Ps(), pspec, Ps(), Ps()), check_vma=False))(
            params, lp0, mbs)

        def flat(params, lp, mbs):
            def one(x, t, m):
                aux_tot = 0.0
                for st in range(P_):
                    x, a = stage_aux(
                        jax.tree_util.tree_map(lambda p: p[st], params),
                        x)
                    aux_tot = aux_tot + a
                return (jnp.mean(jnp.square(x @ lp["v"] - t)) / M_
                        + C_AUX * aux_tot)
            return jnp.sum(jax.vmap(one)(mbs, tgt, jnp.arange(M_)))

        want, (gp, glp, gx) = jax.value_and_grad(
            flat, argnums=(0, 1, 2))(params, lp0, mbs)
        np.testing.assert_allclose(float(loss), float(want), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(grads["w"]),
                                   np.asarray(gp["w"]),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(dv), np.asarray(glp["v"]),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(dmb), np.asarray(gx),
                                   rtol=1e-5, atol=1e-6)

    def test_interleaved_loss_params_and_aux(self, devices):
        """The post-process channels at V>1: loss_params grads must
        accumulate only on last-chunk/last-stage forwards, and every
        chunk's aux must both sum into aux_sum and receive the seeded
        cotangent."""
        from jax.sharding import PartitionSpec as Ps

        mesh = make_mesh(pp=2)
        V, P_, M_, mb = 2, 2, 4, 2
        C_AUX = 0.25
        rng = np.random.default_rng(13)
        params = {"w": jnp.asarray(rng.normal(size=(V, P_, D, D)) * 0.5,
                                   jnp.float32)}
        lp0 = {"v": jnp.asarray(rng.normal(size=(D, D)) * 0.5,
                                jnp.float32)}
        mbs = jnp.asarray(rng.normal(size=(M_, mb, D)), jnp.float32)
        tgt = jnp.asarray(rng.normal(size=(M_, mb, D)), jnp.float32)

        def stage_aux(p, x):
            y = jnp.tanh(x @ p["w"])
            return y, jnp.mean(jnp.square(y)) * jnp.sum(p["w"][0, :2])

        def loss_with_lp(lp, y, m):
            t = jax.lax.dynamic_index_in_dim(tgt, m, 0, keepdims=False)
            return jnp.mean(jnp.square(y @ lp["v"] - t)) / M_

        def inner(params, lp, mbs):
            local = jax.tree_util.tree_map(lambda p: p[:, 0], params)
            loss, grads, dmb, dlp, aux_sum = schedules.one_f_one_b(
                stage_aux, local, mbs, loss_with_lp, num_chunks=V,
                loss_params=lp, with_aux=True, aux_cotangent=C_AUX)
            total = jax.lax.psum(loss + C_AUX * aux_sum, "pp")
            return (total,
                    jax.tree_util.tree_map(lambda g: g[:, None], grads),
                    dmb, jax.lax.psum(dlp["v"], "pp"))

        pspec = jax.tree_util.tree_map(lambda _: Ps(None, "pp"), params)
        loss, grads, dmb, dv = jax.jit(jax.shard_map(
            inner, mesh=mesh, in_specs=(pspec, Ps(), Ps()),
            out_specs=(Ps(), pspec, Ps(), Ps()), check_vma=False))(
            params, lp0, mbs)

        def flat(params, lp, mbs):
            def one(x, t):
                aux_tot = 0.0
                for v in range(V):
                    for st in range(P_):
                        x, a = stage_aux(jax.tree_util.tree_map(
                            lambda p: p[v, st], params), x)
                        aux_tot = aux_tot + a
                return (jnp.mean(jnp.square(x @ lp["v"] - t)) / M_
                        + C_AUX * aux_tot)
            return jnp.sum(jax.vmap(one)(mbs, tgt))

        want, (gp, glp, gx) = jax.value_and_grad(
            flat, argnums=(0, 1, 2))(params, lp0, mbs)
        np.testing.assert_allclose(float(loss), float(want), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(grads["w"]),
                                   np.asarray(gp["w"]),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(dv), np.asarray(glp["v"]),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(dmb), np.asarray(gx),
                                   rtol=1e-5, atol=1e-6)

    def test_collective_stage_matches_flat(self, devices):
        """Stage contains an all_gather/psum_scatter pair over a second
        mesh axis — its TRANSPOSE (reduce-scatter/all-gather) runs inside
        the bwd cond; both directions must stay exact (the skip_bubbles
        collective contract, applied to one_f_one_b's skip_idle)."""
        mesh = make_mesh(pp=2, cp=2)
        P_, M_, mb = 2, 4, 2
        rng = np.random.default_rng(6)
        params = {"w": jnp.asarray(rng.normal(size=(P_, D, D)) * 0.5,
                                   jnp.float32)}
        mbs = jnp.asarray(rng.normal(size=(M_, mb, D)), jnp.float32)
        tgt = jnp.asarray(rng.normal(size=(M_, mb, D)), jnp.float32)

        def stage_sharded(p, x):
            # SP-style: gather over cp, compute, mean back — replicated
            # in/out so the flat gold is the plain average form
            g = jax.lax.all_gather(x, "cp")            # (2, mb, D)
            h = jnp.tanh((g[0] + g[1]) @ p["w"]) * 0.5
            return x + jax.lax.pmean(h, "cp")

        def stage_flat(p, x):
            return x + jnp.tanh((x + x) @ p["w"]) * 0.5

        loss, grads, dmb = self._run(mesh, P_, params, mbs, tgt,
                                     stage_sharded, True)

        def flat(params, mbs):
            def one(x, t):
                for s in range(P_):
                    x = stage_flat({"w": params["w"][s]}, x)
                return jnp.mean(jnp.square(x - t)) / M_
            return jnp.sum(jax.vmap(one)(mbs, tgt))

        want, (gp, gx) = jax.value_and_grad(flat, argnums=(0, 1))(
            params, mbs)
        np.testing.assert_allclose(float(loss), float(want), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(grads["w"]),
                                   np.asarray(gp["w"]),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(dmb), np.asarray(gx),
                                   rtol=1e-5, atol=1e-6)


class TestP2PCommunication:
    """Behavioral direction pins for the p2p shims (review r5: a flipped
    _ring_perm previously passed the whole suite — only the API surface
    was checked)."""

    def _vals(self, mesh, fn):
        from jax.sharding import PartitionSpec as Ps
        P_ = mesh.devices.size
        x = jnp.arange(P_, dtype=jnp.float32).reshape(P_, 1)
        y = jax.jit(jax.shard_map(
            lambda x: fn(x[0])[None],
            mesh=mesh, in_specs=Ps("pp"), out_specs=Ps("pp"),
            check_vma=False))(x)
        return np.asarray(y).ravel()

    def test_send_forward_shifts_down_ring(self, devices):
        from apex1_tpu.transformer.pipeline_parallel import (
            p2p_communication as p2p)
        mesh = make_mesh(pp=4, devices=devices[:4])
        # stage s receives stage s-1's value; stage 0 wraps to P-1
        got = self._vals(mesh, p2p.send_forward)
        np.testing.assert_array_equal(got, [3.0, 0.0, 1.0, 2.0])

    def test_send_backward_shifts_up_ring(self, devices):
        from apex1_tpu.transformer.pipeline_parallel import (
            p2p_communication as p2p)
        mesh = make_mesh(pp=4, devices=devices[:4])
        # stage s receives stage s+1's gradient; stage P-1 wraps to 0
        got = self._vals(mesh, p2p.send_backward)
        np.testing.assert_array_equal(got, [1.0, 2.0, 3.0, 0.0])

    def test_paired_send_recv_is_one_shift(self, devices):
        """The reference's two-call pattern must shift exactly once —
        recv_* are identity shims (the module's PAIRING CONTRACT)."""
        from apex1_tpu.transformer.pipeline_parallel import (
            p2p_communication as p2p)
        mesh = make_mesh(pp=4, devices=devices[:4])
        got = self._vals(
            mesh, lambda x: p2p.recv_forward(p2p.send_forward(x)))
        np.testing.assert_array_equal(got, [3.0, 0.0, 1.0, 2.0])
        got = self._vals(
            mesh, lambda x: p2p.recv_backward(p2p.send_backward(x)))
        np.testing.assert_array_equal(got, [1.0, 2.0, 3.0, 0.0])
