"""Resilient-runtime tests — ISSUE 6 acceptance criteria.

Covers: integrity manifests (round-trip + every corruption class a
typed error), the async checkpointer (donation safety, ring GC +
milestone pins, `find_restorable`'s backward scan past truncated AND
bit-flipped checkpoints, fingerprint refusal), the divergence sentinel
(on-device NaN catch pinned by jaxpr — no host sync — plus the
skip → rollback → abort ladder with banked diagnostics), the preemption
handler, the retry/backoff policy, and the chaos harness's own
determinism."""

import json
import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.amp import Amp
from apex1_tpu.checkpoint import CheckpointError
from apex1_tpu.optim.fused_sgd import fused_sgd
from apex1_tpu.resilience import (DivergenceError, EXIT_RESUMABLE,
                                  IntegrityError, PreemptionHandler,
                                  ResilientCheckpointer, Sentinel,
                                  TransientError, backoff_delays,
                                  find_restorable, guard_train_step,
                                  read_manifest, refold_key, refold_seed,
                                  retry_call, sentinel_init, verify_files,
                                  verify_tree, write_manifest)
from apex1_tpu.testing import chaos


def _amp_setup(poisonable=False):
    amp = Amp(tx=fused_sgd(0.1), opt_level="O0")
    state = amp.init({"w": jnp.arange(1.0, 9.0, dtype=jnp.float32),
                      "b": jnp.zeros((4,), jnp.float32)})
    if poisonable:
        def loss_fn(p, x, step):
            loss = jnp.sum(jnp.square(p["w"])) * x + jnp.sum(p["b"])
            return chaos.poison_at_steps(loss, step, (3, 4))
    else:
        def loss_fn(p, x):
            return jnp.sum(jnp.square(p["w"])) * x + jnp.sum(p["b"])
    return amp, state, loss_fn


# ---------------------------------------------------------------------------
# retry / backoff

class TestRetry:
    def test_delays_deterministic_capped_and_monotone_base(self):
        a = list(backoff_delays(6, base_s=0.01, cap_s=0.1, seed=7))
        b = list(backoff_delays(6, base_s=0.01, cap_s=0.1, seed=7))
        assert a == b                       # seeded jitter: reproducible
        assert all(d <= 0.1 for d in a)     # cap holds under jitter
        exact = list(backoff_delays(6, base_s=0.01, cap_s=10.0, jitter=0))
        assert exact == [0.01 * 2 ** i for i in range(6)]
        # jitter shrinks, never grows, a delay
        jit = list(backoff_delays(6, base_s=0.01, cap_s=10.0, seed=3))
        assert all(j <= e for j, e in zip(jit, exact))

    def test_retry_call_recovers_and_counts(self):
        flaky = chaos.Flaky(lambda: "ok", fails=3)
        seen = []
        out = retry_call(flaky, retries=5, base_s=0.0, jitter=0.0,
                         on_retry=lambda n, e: seen.append(n))
        assert out == "ok"
        assert flaky.attempts == 4 and flaky.failures == 3
        assert seen == [1, 2, 3]

    def test_retry_call_exhausts_and_reraises(self):
        flaky = chaos.Flaky(lambda: "ok", fails=10)
        with pytest.raises(TransientError):
            retry_call(flaky, retries=2, base_s=0.0, jitter=0.0)
        assert flaky.attempts == 3          # initial + 2 retries

    def test_retry_call_deadline_drops_early(self):
        flaky = chaos.Flaky(lambda: "ok", fails=10)
        with pytest.raises(TransientError):
            retry_call(flaky, retries=50, base_s=10.0, jitter=0.0,
                       deadline_s=0.05, sleep=lambda _d: None)
        assert flaky.attempts == 1          # first 10s delay > deadline

    def test_non_retryable_propagates_immediately(self):
        def boom():
            raise ValueError("not transient")

        with pytest.raises(ValueError):
            retry_call(boom, retries=5, base_s=0.0)


# ---------------------------------------------------------------------------
# checkpoint.py satellite: typed errors + atomic save

class TestCheckpointErrors:
    def test_missing_path_is_typed(self, tmp_path):
        from apex1_tpu.checkpoint import restore_checkpoint

        with pytest.raises(CheckpointError, match="missing"):
            restore_checkpoint(tmp_path / "nope")
        try:
            restore_checkpoint(tmp_path / "nope")
        except CheckpointError as e:
            assert "nope" in e.path and "missing" in e.reason

    def test_partial_tmp_dir_is_typed(self, tmp_path):
        from apex1_tpu.checkpoint import restore_checkpoint

        half = tmp_path / "ck.tmp-1234"
        half.mkdir()
        with pytest.raises(CheckpointError, match="partial"):
            restore_checkpoint(half)

    def test_corrupt_payload_is_typed_not_raw_orbax(self, tmp_path):
        from apex1_tpu.checkpoint import (restore_checkpoint,
                                          save_checkpoint)

        tree = {"w": jnp.arange(8.0)}
        save_checkpoint(tmp_path / "ck", tree)
        # wrong template structure → typed error, not an orbax traceback
        with pytest.raises(CheckpointError, match="restore failed"):
            restore_checkpoint(tmp_path / "ck",
                               template={"nope": jnp.zeros((3, 3))})

    def test_save_leaves_no_tmp_debris(self, tmp_path):
        from apex1_tpu.checkpoint import save_checkpoint

        save_checkpoint(tmp_path / "ck", {"w": jnp.ones((4,))})
        names = os.listdir(tmp_path)
        assert names == ["ck"]              # temp dir renamed away


# ---------------------------------------------------------------------------
# manifest

class TestManifest:
    def _write(self, tmp_path, tree=None):
        from apex1_tpu.checkpoint import save_checkpoint

        tree = tree if tree is not None else {
            "w": jnp.arange(16.0), "n": jnp.int32(3)}
        d = tmp_path / "ck"
        save_checkpoint(d / "state", tree)
        write_manifest(d, step=7, state=tree,
                       fingerprint=0xABC, meta={"data_step": 9})
        return d, tree

    def test_round_trip_and_verify(self, tmp_path):
        d, tree = self._write(tmp_path)
        m = read_manifest(d)
        assert (m.step, m.fingerprint, m.meta["data_step"]) == (7, "0xabc",
                                                                9)
        verify_files(d)
        verify_tree(d, tree, m)

    def test_truncation_detected(self, tmp_path):
        d, _ = self._write(tmp_path)
        chaos.truncate_checkpoint(d)
        with pytest.raises(IntegrityError, match="truncated|missing"):
            verify_files(d)

    def test_bitflip_detected(self, tmp_path):
        d, _ = self._write(tmp_path)
        chaos.bitflip_checkpoint(d)
        with pytest.raises(IntegrityError, match="digest mismatch"):
            verify_files(d)

    def test_missing_manifest_is_uncommitted(self, tmp_path):
        d, _ = self._write(tmp_path)
        os.unlink(d / "manifest.json")
        with pytest.raises(IntegrityError, match="manifest missing"):
            verify_files(d)

    def test_wrong_restore_is_typed(self, tmp_path):
        d, tree = self._write(tmp_path)
        wrong = dict(tree, w=tree["w"].at[0].set(99.0))
        with pytest.raises(IntegrityError, match="sha256 mismatch"):
            verify_tree(d, wrong)
        with pytest.raises(IntegrityError, match="structure mismatch"):
            verify_tree(d, {"w": tree["w"]})


# ---------------------------------------------------------------------------
# resilient checkpointer

class TestResilientCheckpointer:
    def test_async_save_restore_and_meta(self, tmp_path):
        amp, state, loss_fn = _amp_setup()
        step = jax.jit(amp.make_train_step(loss_fn))
        with ResilientCheckpointer(tmp_path / "ck", keep=2) as ck:
            for i in range(3):
                state, _ = step(state, jnp.float32(1.0))
                ck.save(int(state.step), state, meta={"data_step": i + 1})
            ck.wait()
            restored, man = ck.restore(template=state)
        assert man.step == 3 and man.meta["data_step"] == 3
        for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_snapshot_survives_donation(self, tmp_path):
        """The async snapshot must copy: the caller's next
        donate_argnums=0 step invalidates the live buffers while the
        save is still writing."""
        amp, state, loss_fn = _amp_setup()
        step = jax.jit(amp.make_train_step(loss_fn), donate_argnums=0)
        state, _ = step(state, jnp.float32(1.0))
        want = np.asarray(state.params["w"]).copy()
        with ResilientCheckpointer(tmp_path / "ck") as ck:
            ck.save(1, state)
            for _ in range(3):          # donates `state` repeatedly
                state, _ = step(state, jnp.float32(1.0))
            ck.wait()
            restored, man = ck.restore(
                template=jax.tree.map(np.asarray, state))
        np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                      want)

    def test_ring_gc_keeps_last_k_and_milestones(self, tmp_path):
        tree = {"w": jnp.ones((4,))}
        with ResilientCheckpointer(tmp_path / "ck", keep=2) as ck:
            ck.save_sync(1, tree, milestone=True)
            for s in (2, 3, 4, 5):
                ck.save_sync(s, tree)
        kept = sorted(p for p in os.listdir(tmp_path / "ck")
                      if p.startswith("step_"))
        assert kept == ["step_00000001", "step_00000004", "step_00000005"]

    def test_scan_past_truncated_and_bitflipped(self, tmp_path):
        """Acceptance criterion: newest truncated, next bit-flipped →
        the older valid one is selected and restores."""
        tree = {"w": jnp.arange(64.0)}
        with ResilientCheckpointer(tmp_path / "ck", keep=5) as ck:
            for s in (1, 2, 3):
                ck.save_sync(s, dict(tree, step=jnp.int32(s)))
            d = str(tmp_path / "ck")
            chaos.truncate_checkpoint(os.path.join(d, "step_00000003"))
            chaos.bitflip_checkpoint(os.path.join(d, "step_00000002"))
            best = find_restorable(d)
            assert best is not None
            assert os.path.basename(best) == "step_00000001"
            restored, man = ck.restore(
                template=dict(tree, step=jnp.int32(0)))
        assert man.step == 1 and int(restored["step"]) == 1

    def test_no_valid_checkpoint_is_typed(self, tmp_path):
        with ResilientCheckpointer(tmp_path / "ck") as ck:
            with pytest.raises(CheckpointError, match="no valid"):
                ck.restore(template={"w": jnp.ones((4,))})

    def test_fingerprint_refuses_changed_program(self, tmp_path):
        tree = {"w": jnp.ones((4,))}
        with ResilientCheckpointer(tmp_path / "ck",
                                   fingerprint=0x1111) as ck:
            ck.save_sync(1, tree)
        with ResilientCheckpointer(tmp_path / "ck",
                                   fingerprint=0x2222) as ck2:
            with pytest.raises(CheckpointError,
                               match="fingerprint mismatch"):
                ck2.restore(template=tree)
            restored, _ = ck2.restore(template=tree,
                                      allow_fingerprint_mismatch=True)
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.ones((4,)))

    def test_stale_latest_pointer_does_not_hide_newer_valid(self,
                                                            tmp_path):
        """A kill between the commit rename and the `latest` promote
        leaves the pointer naming an OLDER checkpoint; find_restorable
        must still return the newest valid one."""
        tree = {"w": jnp.ones((4,))}
        d = tmp_path / "ck"
        with ResilientCheckpointer(d, keep=3) as ck:
            ck.save_sync(1, tree)
            ck.save_sync(2, tree)
            with open(d / "latest", "w") as f:
                f.write("step_00000001\n")     # simulate the torn kill
            assert os.path.basename(find_restorable(d)) == "step_00000002"

    def test_snapshot_bound_third_save_blocks(self, tmp_path):
        """At most two snapshots outstanding: with the worker stalled,
        the first two save() calls return and the THIRD blocks (its
        snapshot not yet built) until the worker drains one."""
        import threading
        import time as _t

        gate = threading.Event()
        ck = ResilientCheckpointer(tmp_path / "ck")
        orig = ck._write_one
        ck._write_one = lambda *a: (gate.wait(30), orig(*a))[1]
        tree = {"w": jnp.ones((4,))}
        snaps = []
        orig_snap = ck._snapshot
        ck._snapshot = lambda s: snaps.append(1) or orig_snap(s)
        ck.save(1, tree)
        ck.save(2, tree)                       # fills the second slot
        t = threading.Thread(target=lambda: ck.save(3, tree))
        t.start()
        _t.sleep(0.3)
        assert len(snaps) == 2 and t.is_alive()   # third not snapshot
        gate.set()
        t.join(timeout=30)
        ck.close()
        assert len(snaps) == 3
        assert os.path.basename(ck.latest_valid()) == "step_00000003"

    def test_plan_banked_in_every_save_meta(self, tmp_path):
        """ISSUE 14 satellite: a plan-aware checkpointer banks the
        producing apex1-plan-v1 spec in every manifest meta, so any
        committed checkpoint is self-describing and reshardable."""
        from apex1_tpu import planner
        from apex1_tpu.resilience import read_plan

        shape = planner.ModelShape(
            name="bank", num_layers=2, hidden_size=32, ffn_size=64,
            num_heads=4, num_kv_heads=2, head_dim=8, vocab_size=64,
            seq_len=16, global_batch=4)
        plan = planner.plan_for_layout(
            shape, planner.Layout(dp=2, num_microbatches=2))
        tree = {"w": jnp.ones((4,))}
        with ResilientCheckpointer(tmp_path / "ck", plan=plan) as ck:
            ck.save_sync(1, tree, meta={"data_step": 1})
            ck.save_sync(2, tree)
            banked = read_plan(os.path.join(str(tmp_path / "ck"),
                                            "step_00000002"))
            assert banked == plan           # JSON round-trip intact
            restored, man = ck.restore(template=tree)   # spec matches
            assert man.meta["plan"]["mesh"] == plan["mesh"]
        with pytest.raises(ValueError, match="apex1-plan-v1"):
            ResilientCheckpointer(tmp_path / "ck2",
                                  plan={"schema": "nope"})

    def test_uncommitted_save_is_invisible(self, tmp_path):
        """A step dir without a manifest (killed between payload and
        commit) is not restorable and is GC-collectable."""
        tree = {"w": jnp.ones((4,))}
        d = tmp_path / "ck"
        with ResilientCheckpointer(d, keep=2) as ck:
            ck.save_sync(1, tree)
            # forge an uncommitted newer checkpoint
            os.makedirs(d / "step_00000002")
            assert os.path.basename(find_restorable(d)) == "step_00000001"
            restored, man = ck.restore(template=tree)
        assert man.step == 1


# ---------------------------------------------------------------------------
# sentinel

class TestSentinel:
    def _guarded(self, sent=None):
        amp, state, loss_fn = _amp_setup(poisonable=True)
        inner = amp.make_train_step(loss_fn)
        guard = (sent.guard(inner) if sent is not None
                 else guard_train_step(inner))
        return state, jax.jit(guard), guard

    def test_nan_caught_on_device_no_host_sync(self):
        """Acceptance criterion: the guarded step's jaxpr carries NO
        host callback — the flag is a carried device scalar (graftlint
        covers the source side; this pins the traced program)."""
        state, guarded, guard = self._guarded()
        carry = (state, sentinel_init())
        jaxpr = str(jax.make_jaxpr(guard)(carry, jnp.float32(1.0),
                                          state.step))
        for bad in ("callback", "py_func", "infeed", "outfeed"):
            assert bad not in jaxpr, f"host sync ({bad}) in guarded step"

    def test_poisoned_step_skipped_params_kept(self):
        state, guarded, _ = self._guarded()
        carry = (state, sentinel_init())
        for _ in range(3):   # steps 0,1,2 clean
            carry, m = guarded(carry, jnp.float32(1.0), carry[0].step)
            assert bool(m["sentinel_healthy"])
        good = np.asarray(carry[0].params["w"]).copy()
        carry, m = guarded(carry, jnp.float32(1.0), carry[0].step)  # 3: NaN
        assert not bool(m["sentinel_healthy"])
        np.testing.assert_array_equal(np.asarray(carry[0].params["w"]),
                                      good)
        assert int(carry[0].step) == 4          # step still advances
        s = carry[1]
        assert (int(s.consecutive_bad), int(s.total_bad),
                int(s.last_bad_step)) == (1, 1, 3)
        # params stay finite through the whole poisoned window
        assert np.isfinite(np.asarray(carry[0].params["w"])).all()

    def test_escalation_skip_then_rollback_with_banked_record(self,
                                                              tmp_path):
        """Acceptance criterion: first hit → skip; second consecutive
        hit → rollback-to-last-good + a diverged diagnostic banked."""
        ck = ResilientCheckpointer(tmp_path / "ck")
        sent = Sentinel(ck, check_every=1, rollback_after=2,
                        abort_after=4)
        state, guarded, _ = self._guarded(sent)
        carry = (state, sentinel_init())
        for _ in range(3):
            carry, _m = guarded(carry, jnp.float32(1.0), carry[0].step)
            assert sent.poll(carry[1]) is None
        ck.save_sync(int(carry[0].step), carry[0],
                     meta={"data_step": 3})
        good = np.asarray(carry[0].params["w"]).copy()

        carry, _m = guarded(carry, jnp.float32(1.0), carry[0].step)
        assert sent.poll(carry[1]) == "skip"
        carry, _m = guarded(carry, jnp.float32(1.0), carry[0].step)
        assert sent.poll(carry[1]) == "rollback"
        restored, man, s0 = sent.rollback(template=carry[0])
        np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                      good)
        assert man.meta["data_step"] == 3 and int(s0.consecutive_bad) == 0
        actions = [r["action"] for r in sent.records]
        assert actions == ["skip", "rollback"]
        banked = sorted(os.listdir(tmp_path / "ck" / "diagnostics"))
        assert len(banked) == 2 and banked[-1].endswith("rollback.json")
        rec = json.load(open(tmp_path / "ck" / "diagnostics" / banked[-1]))
        assert rec["consecutive_bad"] == 2 and rec["action"] == "rollback"
        ck.close()

    def test_abort_raises_divergence_error(self, tmp_path):
        sent = Sentinel(None, check_every=1, rollback_after=1,
                        abort_after=2, diagnostics_dir=str(tmp_path))
        state, guarded, _ = self._guarded(sent)
        # no checkpointer → rollback rung unavailable → 1st poll at
        # consecutive=1 reaches rollback_after but can't roll back: abort
        carry = (state, sentinel_init())
        for _ in range(3):
            carry, _m = guarded(carry, jnp.float32(1.0), carry[0].step)
        with pytest.raises(DivergenceError) as ei:
            for _ in range(2):
                carry, _m = guarded(carry, jnp.float32(1.0),
                                    carry[0].step)
                sent.poll(carry[1])
        assert ei.value.record["action"] == "abort"
        assert any(n.endswith("abort.json") for n in os.listdir(tmp_path))

    def test_diagnostics_dir_honors_late_attached_checkpointer(self,
                                                               tmp_path):
        """Training loops attach the checkpointer AFTER constructing
        the sentinel (the program-fingerprint chicken-and-egg in
        examples/gpt2_amp.py); diagnostics must still land under
        <ckpt dir>/diagnostics, not be silently unbanked."""
        sent = Sentinel(None, check_every=1, rollback_after=3,
                        abort_after=4)
        with ResilientCheckpointer(tmp_path / "ck") as ck:
            sent.checkpointer = ck
            state, guarded, _ = self._guarded(sent)
            carry = (state, sentinel_init())
            for _ in range(4):          # steps 0-2 clean, 3 poisoned
                carry, _m = guarded(carry, jnp.float32(1.0),
                                    carry[0].step)
            assert sent.poll(carry[1]) == "skip"
        banked = os.listdir(tmp_path / "ck" / "diagnostics")
        assert banked and sent.records[-1]["path"].endswith(banked[0])

    def test_gnorm_threshold_flags_finite_divergence(self):
        amp, state, _ = _amp_setup()
        inner = amp.make_train_step(
            lambda p, x: jnp.sum(jnp.square(p["w"])) * x)
        guarded = jax.jit(guard_train_step(inner, gnorm_threshold=1e3))
        carry = (state, sentinel_init())
        carry, m = guarded(carry, jnp.float32(1e8))   # huge but finite
        assert not bool(m["sentinel_healthy"])
        assert int(carry[1].consecutive_bad) == 1

    def test_refold_streams_distinct(self):
        k = jax.random.key(0)
        a, b = refold_key(k, 1), refold_key(k, 2)
        assert not np.array_equal(jax.random.key_data(a),
                                  jax.random.key_data(b))
        assert refold_seed(7, 1) != refold_seed(7, 2) != 7


# ---------------------------------------------------------------------------
# preemption handler (in-process; subprocess contract in
# test_fault_recovery.py)

class TestPreemption:
    def test_sigterm_sets_flag_and_uninstall_restores(self):
        old = signal.getsignal(signal.SIGTERM)
        with PreemptionHandler(signals=(signal.SIGTERM,)) as pre:
            assert not pre.triggered
            os.kill(os.getpid(), signal.SIGTERM)
            assert pre.triggered and pre.signum == signal.SIGTERM
            assert not pre.deadline_exceeded()
        assert signal.getsignal(signal.SIGTERM) is old

    def test_exit_resumable_code(self, capsys):
        pre = PreemptionHandler()
        with pytest.raises(SystemExit) as ei:
            pre.exit_resumable("test")
        assert ei.value.code == EXIT_RESUMABLE == 75
        assert "resumable" in capsys.readouterr().out

    def _double_signal_child(self, first, second):
        """Subprocess: install the handler, deliver two signals while
        the 'drain' (a sleep standing in for the final checkpoint) is
        in flight. The module is loaded by file path so the child
        skips the package imports (stdlib-only, <1s)."""
        import subprocess
        import sys
        import textwrap

        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "apex1_tpu", "resilience", "preemption.py")
        code = textwrap.dedent(f"""
            import importlib.util, os, signal, sys, time
            spec = importlib.util.spec_from_file_location(
                "preemption", {path!r})
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            h = mod.PreemptionHandler().install()
            os.kill(os.getpid(), signal.{first})
            assert h.triggered          # drain begins...
            os.kill(os.getpid(), signal.{second})
            time.sleep(5)               # ...must never finish
            sys.exit(3)
        """)
        return subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=60)

    def test_second_sigterm_mid_drain_escalates_to_exit_75(self):
        """ISSUE 14 satellite regression: a second SIGTERM while the
        drain/final checkpoint is in flight must be an IMMEDIATE
        `exit_resumable` (75 — the last committed checkpoint is still
        valid, re-queue the job), not 128+signum (a recorded failure)
        and not a swallowed flag (a hung drain)."""
        r = self._double_signal_child("SIGTERM", "SIGTERM")
        assert r.returncode == EXIT_RESUMABLE == 75, \
            (r.returncode, r.stderr)
        assert "immediate resumable exit" in r.stderr

    def test_cross_signal_double_tap_also_escalates(self):
        """SIGINT then SIGTERM was previously swallowed (the
        same-signum guard): any second installed signal must
        escalate."""
        r = self._double_signal_child("SIGINT", "SIGTERM")
        assert r.returncode == EXIT_RESUMABLE, (r.returncode, r.stderr)


# ---------------------------------------------------------------------------
# chaos harness determinism

class TestChaos:
    def test_poison_identity_when_empty(self):
        f = lambda x, s: chaos.poison_at_steps(x, s, ())
        x = jnp.ones((4,))
        np.testing.assert_array_equal(np.asarray(f(x, jnp.int32(3))),
                                      np.asarray(x))
        # empty steps trace to the identity program (no where/isin ops)
        assert "while" not in str(jax.make_jaxpr(f)(x, jnp.int32(0)))

    def test_poison_hits_exact_steps(self):
        x = jnp.ones((4,))
        for s, bad in ((2, True), (3, False)):
            out = np.asarray(chaos.poison_at_steps(x, jnp.int32(s), (2,)))
            assert np.isnan(out).all() == bad

    def test_corruption_is_deterministic(self, tmp_path):
        """Same (checkpoint, seed) → same payload-file pick. (Across
        SAVES the orbax file names differ — the pick is a pure function
        of the manifest, which is per-checkpoint.)"""
        from apex1_tpu.checkpoint import save_checkpoint

        tree = {"w": jnp.arange(256.0)}
        d = tmp_path / "ck"
        save_checkpoint(d / "state", tree)
        write_manifest(d, step=1, state=tree)
        a = chaos._pick_payload_file(str(d), seed=5)
        b = chaos._pick_payload_file(str(d), seed=5)
        assert a == b
        flipped = chaos.bitflip_checkpoint(d, seed=5)
        assert flipped == a             # the flip lands on the pick
