"""Disaggregated prefill/decode serving (ISSUE 16): phase-aware pools
behind the `ServingFrontend` surface with manifest-verified KV handoff.

What must hold, in order of importance:

1. **Handoff parity** — a stream that prefills in one pool and decodes
   in another is bit-identical to an uninterrupted single-engine run at
   temperature > 0 (the counter-keyed per-request seed, PR 7 — not
   greedy luck), including across corruption/kill re-routes.
2. **Integrity is typed** — a corrupt or torn page surfaces as a
   `HandoffError` at the arrival re-digest and the request re-routes;
   silent garbage tokens are structurally impossible.
3. **Never stranded** — a prefill replica dying inside the handoff
   window re-routes the request (decode-pool re-prefill), it does not
   strand it.
4. **Stability** — the fleetsim's new two-tier knobs at defaults leave
   every pre-existing trace kind and episode fingerprint byte-identical
   to what the perf_results corpus banked before disagg landed.
5. **The point of it all** — under an adversarial long-prompt trace the
   disaggregated fleet holds guaranteed-class TTFT where the unified
   fleet (same total replicas) fails, and the autopilot's pool-ratio
   law actuates `shift_pool` from windowed TTFT/TPOT evidence.
"""

import numpy as np
import pytest

from apex1_tpu.autopilot.policy import (AutopilotConfig, ControllerState,
                                        FleetView, SLOTarget, decide)
from apex1_tpu.serving import Engine, EngineConfig, FrontendConfig
from apex1_tpu.serving.disagg import (DisaggConfig, DisaggFrontend,
                                      HandoffError, extract_page,
                                      install_page, verify_page)
from apex1_tpu.testing.chaos import (HandoffCorruption, HandoffWindowKill,
                                     toy_decoder)
from apex1_tpu.testing.fleetsim import (FleetSimConfig, run_fleet,
                                        synthetic_trace)

ECFG = dict(max_slots=3, max_len=48, prefill_chunk=4, vocab_size=61,
            temperature=0.8, seed=7)


@pytest.fixture(scope="module")
def toy():
    return toy_decoder()


def _engine(toy, **kw):
    apply_fn, make_cache, params = toy
    return Engine(apply_fn, make_cache, params,
                  EngineConfig(**{**ECFG, **kw}))


def _front(toy, fault=None, **dkw):
    apply_fn, make_cache, params = toy

    def make_engine():
        return Engine(apply_fn, make_cache, params, EngineConfig(**ECFG))

    pool = dict(n_replicas=1, capacity_per_replica=8, hedge_after_s=None)
    return DisaggFrontend(
        make_engine,
        DisaggConfig(prefill=FrontendConfig(**pool),
                     decode=FrontendConfig(**pool),
                     prefill_chunk=ECFG["prefill_chunk"], **dkw),
        fault=fault)


def _assert_solo_parity(toy, front, prompts, rids):
    """Every stream must equal an uninterrupted single-engine run with
    the same derived seed — the acceptance bar for every handoff path,
    including the re-routed ones."""
    ref = _engine(toy)
    for p, rid in zip(prompts, rids):
        res = front.poll(rid)
        assert res is not None and res.status == "done", (rid, res)
        sub = front._subs[rid]
        rr = ref.submit(p, max_new_tokens=sub.max_new_tokens,
                        seed=sub.seed)
        ref.run(max_steps=300)
        np.testing.assert_array_equal(res.tokens, ref.results[rr].tokens)


# ---------------------------------------------------------------------------
# kv_transfer: the manifest-verified page contract
# ---------------------------------------------------------------------------


class TestKVTransfer:
    @pytest.fixture()
    def src(self, toy):
        """An engine that served one 9-token prompt — its chunk-aligned
        8-token prefix page sits in the radix store (engine
        auto-registration)."""
        eng = _engine(toy)
        prompt = np.random.default_rng(3).integers(
            0, 61, (9,)).astype(np.int32)
        eng.submit(prompt, max_new_tokens=4, seed=11)
        eng.run(max_steps=100)
        return eng, tuple(int(t) for t in prompt[:8])

    def test_extract_verify_install_roundtrip(self, toy, src):
        eng, key = src
        page = eng.kv.get_prefix(key)
        assert page is not None, "precondition: page registered"
        moved = extract_page(eng, key)
        assert moved.length == 8 and moved.key == key
        assert moved.nbytes() > 0
        verify_page(moved)                       # arrival gate passes
        dst = _engine(toy)
        assert install_page(dst, moved) is True
        assert dst.kv.has_prefix(key)
        # duplicate delivery: dropped (False), not a pool-contract crash
        assert install_page(dst, moved) is False

    def test_missing_page_is_typed(self, toy, src):
        eng, key = src
        with pytest.raises(HandoffError, match="not in the source"):
            extract_page(eng, key[:4])           # never registered

    def test_corrupt_page_is_typed_and_names_digest(self, src):
        import jax

        eng, key = src
        page = extract_page(eng, key)
        # one bit flipped on the "wire" after departure digests
        leaves, treedef = jax.tree_util.tree_flatten(page.lane)
        i = next(j for j, x in enumerate(leaves) if np.asarray(x).size)
        arr = np.array(leaves[i])
        arr.reshape(-1).view(np.uint8)[0] ^= 0xFF
        leaves[i] = arr
        page.lane = jax.tree_util.tree_unflatten(treedef, leaves)
        with pytest.raises(HandoffError, match="sha256"):
            verify_page(page)

    def test_install_verifies_before_touching_pool(self, toy, src):
        eng, key = src
        page = extract_page(eng, key)
        page.entries[0]["sha256"] = "0" * 64
        dst = _engine(toy)
        with pytest.raises(HandoffError):
            install_page(dst, page)
        assert not dst.kv.has_prefix(key)        # nothing installed


# ---------------------------------------------------------------------------
# the disaggregated frontend: routing, parity, fault paths
# ---------------------------------------------------------------------------


class TestDisaggServing:
    def test_handoff_parity_and_hit_skips_prefill(self, toy):
        rng = np.random.default_rng(0)
        # len 3: share point < chunk -> routed straight to decode; the
        # rest prefill in the prefill pool and hand their page off
        lens = (3, 5, 9, 7, 6)
        prompts = [rng.integers(0, 61, (n,)).astype(np.int32)
                   for n in lens]
        front = _front(toy)
        rids = [front.submit(p, max_new_tokens=6 + i % 4)
                for i, p in enumerate(prompts)]
        front.run_until_drained(timeout_s=60.0)
        _assert_solo_parity(toy, front, prompts, rids)
        s = front.summary()
        handoffs = [t for t in front.metrics.transitions
                    if t["event"] == "handoff"]
        assert len(handoffs) == len(lens) - 1
        # the 0-counters: failure counters REPORT AT ZERO on a clean
        # run (absence of evidence must be visible, not missing keys)
        assert s["counters"]["handoff_failures"] == 0
        assert s["counters"]["handoff_reroutes"] == 0
        assert "handoff_parity_mismatches" not in s["counters"]
        assert rids[0] not in front.prefill.metrics.records
        # per-phase split in the window: TTFT (prefill pressure) and
        # TPOT (decode pressure) per QoS class
        w = s["window"]["per_class"]["best_effort"]
        assert "ttft_p99_ms" in w and "tpot_p99_ms" in w
        assert s["pools"]["prefill"]["n_alive"] == 1

        # resubmission: the decode pool's radix index already holds the
        # full-prompt page — the prefill pool is NOT touched
        rid2 = front.submit(prompts[1], max_new_tokens=8)
        front.run_until_drained(timeout_s=60.0)
        assert rid2 not in front.prefill.metrics.records
        _assert_solo_parity(toy, front, [prompts[1]], [rid2])
        eng = front.decode.replicas[0].engine
        assert eng.metrics.get_counter("prefix_hits") >= 1

    def test_corrupt_handoff_rerouted_with_parity(self, toy):
        """A bit flipped on the wire AFTER departure digests: the
        arrival re-digest must catch it (typed `integrity` failure),
        the request must re-route and still finish solo-identical —
        never silent garbage."""
        fault = HandoffCorruption(at_handoff=0)
        front = _front(toy, fault=fault)
        p = np.random.default_rng(1).integers(0, 61, (9,)).astype(np.int32)
        rid = front.submit(p, max_new_tokens=7)
        front.run_until_drained(timeout_s=60.0)
        assert fault.fired == 1
        _assert_solo_parity(toy, front, [p], [rid])
        c = front.summary()["counters"]
        assert c["handoff_failures"] == 1 and c["handoff_reroutes"] == 1
        fails = [t for t in front.metrics.transitions
                 if t["event"] == "handoff_failure"]
        assert fails and fails[0]["failure"] == "integrity"
        assert "sha256" in fails[0]["reason"]

    def test_handoff_window_kill_rerouted_never_stranded(self, toy):
        """ISSUE 16 fix: the only prefill replica dies between prefill
        completion and handoff acknowledgment. The request must
        re-route (decode-pool re-prefill) and complete with parity; the
        supervisor restarts the replica."""
        kill = HandoffWindowKill(at_handoff=0)
        front = _front(toy, fault=kill)
        p = np.random.default_rng(2).integers(0, 61, (7,)).astype(np.int32)
        rid = front.submit(p, max_new_tokens=6)
        front.run_until_drained(timeout_s=60.0)
        assert kill.fired == 1
        _assert_solo_parity(toy, front, [p], [rid])
        c = front.summary()["counters"]
        assert c["handoff_failures"] == 1 and c["handoff_reroutes"] == 1
        fails = [t for t in front.metrics.transitions
                 if t["event"] == "handoff_failure"]
        assert fails and fails[0]["failure"] == "window_kill"
        front.prefill.pump(1)
        assert front.prefill.replica_states() == ["alive"]

    def test_handoff_latency_window_still_parity(self, toy):
        """A nonzero transfer latency holds pages in flight (the
        window the kill fault targets) — delivery after the delay must
        still verify + install + finish with parity."""
        front = _front(toy, handoff_latency_s=0.05)
        p = np.random.default_rng(4).integers(0, 61, (9,)).astype(np.int32)
        rid = front.submit(p, max_new_tokens=5)
        front.run_until_drained(timeout_s=60.0)
        _assert_solo_parity(toy, front, [p], [rid])
        assert front.summary()["counters"]["handoffs"] >= 1


# ---------------------------------------------------------------------------
# fleetsim: the two-tier model, and fingerprint stability of everything
# that predates it
# ---------------------------------------------------------------------------


class TestFleetsimDisagg:
    def test_new_trace_kind_shape_and_determinism(self):
        t1 = synthetic_trace("adversarial_long_prompt", seed=11,
                             horizon_s=2.0, base_rate=12.0)
        t2 = synthetic_trace("adversarial_long_prompt", seed=11,
                             horizon_s=2.0, base_rate=12.0)
        assert t1.fingerprint() == t2.fingerprint()
        # guaranteed keeps short prompts; the long-prompt pressure is
        # carried by the other classes (the head-of-line adversary)
        by_qos = {}
        for r in t1.requests:
            by_qos.setdefault(r.qos, []).append(r.prompt_len)
        assert max(by_qos["guaranteed"]) <= 8
        assert max(by_qos["best_effort"] + by_qos["sheddable"]) >= 18

    def test_preexisting_trace_fingerprints_unchanged(self):
        """The exact trace fingerprints banked in
        perf_results/bench_autopilot_cpu.json BEFORE the two-tier model
        landed — the new trace kind and knobs must not perturb the
        shared rng call order."""
        assert synthetic_trace("bursty", seed=20260804, horizon_s=6.0,
                               base_rate=25.0).fingerprint() \
            == "2981efa90ab97ccf"
        assert synthetic_trace("diurnal", seed=20260804, horizon_s=6.0,
                               base_rate=25.0).fingerprint() \
            == "d62120db0aafb066"
        from apex1_tpu.autopilot.drill import overload_trace
        assert overload_trace(seed=20260804, horizon_s=6.0).fingerprint() \
            == "d8cc6aa26cd8f672"

    def test_new_knobs_inert_on_preexisting_kinds(self):
        """`long_prompt_lens` only binds on the new kind; the sim's
        disagg knobs default off. Neither may move an old trace."""
        a = synthetic_trace("bursty", seed=9, horizon_s=2.0,
                            base_rate=12.0)
        b = synthetic_trace("bursty", seed=9, horizon_s=2.0,
                            base_rate=12.0, long_prompt_lens=(50, 60))
        assert a.fingerprint() == b.fingerprint()
        cfg = FleetSimConfig()
        assert (cfg.disagg, cfg.handoff_latency_s,
                cfg.prefill_round_cost) == (False, 0.0, False)

    def test_disagg_episode_deterministic_with_handoffs(self):
        trace = synthetic_trace("adversarial_long_prompt", seed=13,
                                horizon_s=1.5, base_rate=10.0,
                                prompt_lens=(2, 4))
        fcfg = FrontendConfig(n_replicas=2, capacity_per_replica=8,
                              hedge_after_s=None)
        sim = FleetSimConfig(disagg=True, prefill_replicas=1,
                             prefill_round_cost=True, max_len=64)
        r1 = run_fleet(trace, fcfg, sim=sim)
        r2 = run_fleet(trace, fcfg, sim=sim)
        assert r1.fingerprint() == r2.fingerprint()
        assert r1.summary["counters"]["handoffs"] > 0
        assert r1.summary["counters"]["handoff_failures"] == 0
        assert all(o["status"] != "lost" for o in r1.outcomes)

    def test_headline_disagg_holds_ttft_where_unified_fails(self):
        """The A/B the subsystem exists for: same total replicas, same
        adversarial long-prompt trace, honest prefill round cost.
        Unified interleaves long prefills with decode steps and blows
        the guaranteed-class TTFT bound; the split fleet keeps decode
        slots clear of long prefills and holds it — with every common
        finished stream token-identical across the two fleets (same
        request id ⇒ same derived seed ⇒ transitively pinned to solo
        generate)."""
        trace = synthetic_trace(
            "adversarial_long_prompt", seed=20260807, horizon_s=4.0,
            base_rate=25.0, prompt_lens=(2, 4),
            long_prompt_lens=(18, 30),
            class_mix={"guaranteed": 0.4, "best_effort": 0.35,
                       "sheddable": 0.25})
        fcfg = FrontendConfig(n_replicas=3, capacity_per_replica=8,
                              hedge_after_s=None)
        uni = run_fleet(trace, fcfg, sim=FleetSimConfig(
            prefill_round_cost=True, max_len=64))
        dis = run_fleet(trace, fcfg, sim=FleetSimConfig(
            disagg=True, prefill_replicas=1,
            prefill_round_cost=True, max_len=64))
        bound = 0.12
        att_uni = uni.ttft_attainment("guaranteed", bound)
        att_dis = dis.ttft_attainment("guaranteed", bound)
        assert att_uni <= 0.97, att_uni          # unified FAILS the bound
        assert att_dis >= 0.99, att_dis          # disagg HOLDS it
        assert dis.summary["counters"]["handoffs"] > 0
        assert dis.summary["counters"]["handoff_failures"] == 0
        # cross-fleet token parity on every request both fleets finished
        sha = {o["idx"]: o["tokens_sha1"] for o in uni.outcomes
               if o["status"] == "done"}
        common = [o for o in dis.outcomes
                  if o["status"] == "done" and o["idx"] in sha]
        assert len(common) >= 20
        for o in common:
            assert o["tokens_sha1"] == sha[o["idx"]], o


# ---------------------------------------------------------------------------
# pool-ratio law: pure policy, then the closed loop
# ---------------------------------------------------------------------------


def _pool_cfg(**over):
    kw = dict(slo={"best_effort": SLOTarget(ttft_p99_ms=100.0,
                                            tpot_p99_ms=50.0)},
              fit_hedge=False, pool_sustain=3, pool_cooldown=4)
    kw.update(over)
    return AutopilotConfig(**kw)


def _pool_view(ttft_ms, tpot_ms, *, pools="both", n=32):
    if pools == "both":
        pools = {"prefill": {"n_replicas": 1, "n_alive": 1,
                             "inflight": 0, "load_fraction": 0.0},
                 "decode": {"n_replicas": 3, "n_alive": 3,
                            "inflight": 0, "load_fraction": 0.0}}
    window = {"best_effort": {"n": n, "latency_p99_ms": 10.0}}
    if ttft_ms is not None:
        window["best_effort"]["ttft_p99_ms"] = ttft_ms
    if tpot_ms is not None:
        window["best_effort"]["tpot_p99_ms"] = tpot_ms
    return FleetView(mode="normal", load_fraction=0.4, inflight=4,
                     capacity=32, n_replicas=4, n_alive=4,
                     admission_limit=None, window=window,
                     per_tenant={}, pools=pools)


def _shifts(view, state, cfg, ticks):
    out = []
    for t in range(ticks):
        out += [(t, a) for a in decide(view, state, cfg)
                if a.kind == "shift_pool"]
    return out


class TestPoolRatioPolicy:
    def test_inert_on_unified_fleet(self):
        # massive imbalance, but no pools snapshot -> the law never fires
        v = _pool_view(400.0, 10.0, pools=None)
        assert _shifts(v, ControllerState(), _pool_cfg(), 20) == []

    def test_inert_on_half_a_comparison(self):
        # TTFT pressure measurable, TPOT not: which phase is slowER is
        # unknowable -> no action, and the sustain counter resets
        st = ControllerState()
        assert _shifts(_pool_view(400.0, None), st, _pool_cfg(), 20) == []
        assert st.pool_imbalance_ticks == 0

    def test_deadband_absorbs_mild_imbalance(self):
        # 1.2x vs 1.0x normalized: inside the 1.3 deadband forever
        v = _pool_view(120.0, 50.0)
        assert _shifts(v, ControllerState(), _pool_cfg(), 20) == []

    def test_thin_window_actuates_nothing(self):
        v = _pool_view(400.0, 10.0, n=3)       # < min_window samples
        assert _shifts(v, ControllerState(), _pool_cfg(), 20) == []

    def test_sustain_then_shift_then_cooldown(self):
        # prefill pressure 3.0 vs decode 0.5, sustained
        v = _pool_view(300.0, 25.0)
        cfg = _pool_cfg()
        got = _shifts(v, ControllerState(), cfg, 14)
        assert len(got) >= 2
        first_t, first = got[0]
        assert first_t == cfg.pool_sustain - 1  # not before sustain
        assert first.params == {"to": "prefill"}
        ev = first.evidence
        assert ev["pressure_prefill"] == pytest.approx(3.0)
        assert ev["pressure_decode"] == pytest.approx(0.5)
        assert ev["ttft"]["class"] == "best_effort"
        # refractory: consecutive shifts at least pool_cooldown apart
        assert got[1][0] - first_t >= cfg.pool_cooldown

    def test_decode_side_and_side_flip_resets_sustain(self):
        cfg = _pool_cfg()
        pools = {"prefill": {"n_alive": 2}, "decode": {"n_alive": 2}}
        v_dec = _pool_view(50.0, 200.0, pools=pools)
        got = _shifts(v_dec, ControllerState(), cfg, 6)
        assert got and got[0][1].params == {"to": "decode"}
        # alternating pressured side never accumulates sustain
        st = ControllerState()
        v_pre = _pool_view(300.0, 25.0, pools=pools)
        for i in range(12):
            acts = decide(v_pre if i % 2 else v_dec, st, cfg)
            assert [a for a in acts if a.kind == "shift_pool"] == []

    def test_donor_pool_never_drained(self):
        # decode is the donor but holds ONE replica: each phase always
        # keeps a pool, so the law must decline forever
        pools = {"prefill": {"n_alive": 3}, "decode": {"n_alive": 1}}
        v = _pool_view(300.0, 25.0, pools=pools)
        assert _shifts(v, ControllerState(), _pool_cfg(), 20) == []

    def test_closed_loop_shift_banked_on_live_fleet(self):
        """End to end: a long-prompt-heavy episode starves the 1-replica
        prefill tier, windowed TTFT/TPOT pressures diverge, and the
        autopilot actuates `shift_pool` toward prefill — banked as a
        `pool_shift` transition AND an autopilot episode entry with the
        per-phase evidence attached. Replayable bit-identically."""
        trace = synthetic_trace(
            "adversarial_long_prompt", seed=20260807, horizon_s=5.0,
            base_rate=25.0, prompt_lens=(2, 4),
            long_prompt_lens=(18, 30),
            class_mix={"guaranteed": 0.3, "best_effort": 0.45,
                       "sheddable": 0.25})
        fcfg = FrontendConfig(n_replicas=4, capacity_per_replica=8,
                              hedge_after_s=None)
        sim = FleetSimConfig(disagg=True, prefill_replicas=1,
                             prefill_round_cost=True, max_len=64)
        ap = AutopilotConfig(
            slo={"best_effort": SLOTarget(ttft_p99_ms=120.0,
                                          tpot_p99_ms=60.0)},
            max_replicas=4, fit_hedge=False)
        rep = run_fleet(trace, fcfg, sim=sim, autopilot=ap)
        shifts = [a for a in rep.actions if a["action"] == "shift_pool"]
        assert shifts, "pool-ratio law never actuated"
        assert all(a["params"] == {"to": "prefill"} for a in shifts)
        assert all("pressure_prefill" in a["evidence"] for a in shifts)
        banked = [t for t in rep.transitions
                  if t["event"] == "pool_shift"]
        assert len(banked) >= len(shifts)
        rep2 = run_fleet(trace, fcfg, sim=sim, autopilot=ap)
        assert rep.fingerprint() == rep2.fingerprint()
