"""ISSUE 14 acceptance tests — elastic resume: planner-driven re-plan
plus manifest-verified checkpoint resharding.

Covers: `resilience.reshard` determinism and A→B→A bit-exact round
trips for every dtype the repo trains (fp32 / bf16 / fp16-master /
int8 + scales), the ZeRO flat-shard repack, checkpoint-level reshard
(byte-identical leaf digests across independent reshards, corrupted
reshard output REFUSED at restore — never trusted), the typed
`LayoutMismatch` contract (no plan meta, layout change, structure
change), the chaos `shrink_schedule` / fleetsim `kill_k_of_n`
helpers, and THE acceptance drill: 8→4-device mid-run shrink through
a planner re-plan with a bit-exact loss trajectory vs the 4-device
from-checkpoint control, reconstructable from banked obs-spine
events alone."""

import dataclasses
import os
import subprocess
import sys

import jax
import ml_dtypes
import numpy as np
import pytest

from apex1_tpu import planner
from apex1_tpu.parallel.distributed_optimizer import (flat_param_len,
                                                      repack_flat_shard,
                                                      shard_padded_len)
from apex1_tpu.resilience import (IntegrityError, LayoutMismatch,
                                  ResilientCheckpointer, elastic_resume,
                                  read_manifest, read_plan,
                                  reshard_checkpoint, reshard_state)
from apex1_tpu.testing import chaos


def _shape_with(**over):
    return planner.ModelShape(**{**dataclasses.asdict(SHAPE), **over})

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SHAPE = planner.ModelShape(
    name="tiny-elastic", num_layers=4, hidden_size=32, ffn_size=64,
    num_heads=4, num_kv_heads=2, head_dim=8, vocab_size=64,
    seq_len=16, global_batch=8)

#: stated interleaved 8-dev plan (stack (2, 2, 1)) and a 4-dev plan
#: (stack (1, 2, 2)) — a genuine restack between them
PLAN_A = planner.plan_for_layout(
    SHAPE, planner.Layout(dp=2, pp=2, tp=2, num_microbatches=4,
                          num_chunks=2))
PLAN_B = planner.plan_for_layout(
    SHAPE, planner.Layout(dp=2, pp=2, tp=1, num_microbatches=4))


def _synth_state(stack=(2, 2, 1)):
    """Chunk-stacked state with every dtype the repo trains: fp32
    weights, bf16 activside weights, fp16 master-style copies, int8
    quantized weights + their fp16 scales."""
    rng = np.random.default_rng(7)
    V, PP, L = stack

    def w(dt):
        return rng.normal(size=(V, PP, L, 3, 5)).astype(dt)

    chunk = {
        "w_fp32": w(np.float32),
        "w_bf16": w(ml_dtypes.bfloat16),
        "w_fp16": w(np.float16),
        "q_int8": rng.integers(-127, 127,
                               (V, PP, L, 3, 5)).astype(np.int8),
        "q_scale": w(np.float16),
    }
    return {"step": np.int32(5),
            "params": {"chunk": chunk,
                       "shared": {"emb": w(np.float32)[0, 0, 0]}}}


def test_reshard_plan_schema_matches_planner():
    """reshard.py spells the schema string locally (reading plan meta
    must stay planner-free); the two constants must never drift."""
    from apex1_tpu.resilience import reshard

    assert reshard.PLAN_SCHEMA == planner.PLAN_SCHEMA


class TestReshardState:
    def test_restack_changes_stack_and_round_trips_bit_exact(self):
        state = _synth_state((2, 2, 1))
        mid, rep = reshard_state(state, PLAN_A, PLAN_B)
        assert rep["n_restacked"] == 5 and rep["conserved"]
        assert mid["params"]["chunk"]["w_fp32"].shape[:3] == (1, 2, 2)
        back, rep2 = reshard_state(mid, PLAN_B, PLAN_A)
        for k, v in state["params"]["chunk"].items():
            got = back["params"]["chunk"][k]
            assert got.dtype == v.dtype, k
            assert got.tobytes() == v.tobytes(), \
                f"A->B->A not bit-exact for dtype {v.dtype} ({k})"

    def test_same_inputs_byte_identical(self):
        state = _synth_state((2, 2, 1))
        a, _ = reshard_state(state, PLAN_A, PLAN_B)
        b, _ = reshard_state(state, PLAN_A, PLAN_B)
        la = jax.tree_util.tree_leaves(a)
        lb = jax.tree_util.tree_leaves(b)
        assert all(x.tobytes() == y.tobytes() for x, y in zip(la, lb))

    def test_zero_shard_repack_strips_and_repads(self):
        gb6 = _shape_with(name="tiny-z", global_batch=6)
        pa = planner.plan_for_layout(
            gb6, planner.Layout(dp=3, num_microbatches=2, zero=True))
        pb = planner.plan_for_layout(
            gb6, planner.Layout(dp=2, num_microbatches=3, zero=True))
        params = {"w": np.arange(34.0, dtype=np.float32).reshape(17, 2)}
        n = flat_param_len(params)
        assert n == 34
        # dp=3 pads 34 -> 36; the REAL padding is zero (see
        # repack_flat_shard's exactness contract), which is what makes
        # the round trip an identity
        shard = np.concatenate([np.arange(36.0, dtype=np.float32)[:34],
                                np.zeros(2, np.float32)])
        state = {"params": params,
                 "opt": {"exp_avg_shard": shard,
                         "exp_avg_sq_shard": shard * 2.0}}
        out, rep = reshard_state(state, pa, pb)
        assert rep["n_repacked"] == 2 and rep["conserved"]
        assert out["opt"]["exp_avg_shard"].shape == (34,)  # dp=2: no pad
        np.testing.assert_array_equal(out["opt"]["exp_avg_shard"],
                                      shard[:34])
        back, _ = reshard_state(out, pb, pa)
        np.testing.assert_array_equal(back["opt"]["exp_avg_sq_shard"],
                                      shard * 2.0)

    def test_nonzero_source_tail_refused(self):
        """A nonzero padded tail means the zero-padding invariant
        broke upstream; the repack must refuse loudly rather than
        silently truncate data."""
        gb6 = _shape_with(name="tiny-z4", global_batch=6)
        pa = planner.plan_for_layout(
            gb6, planner.Layout(dp=3, num_microbatches=2, zero=True))
        pb = planner.plan_for_layout(
            gb6, planner.Layout(dp=2, num_microbatches=3, zero=True))
        params = {"w": np.ones((17, 2), np.float32)}
        bad = np.arange(36.0, dtype=np.float32)   # tail 34,35 nonzero
        state = {"params": params,
                 "opt": {"exp_avg_shard": bad}}
        with pytest.raises(LayoutMismatch, match="conservation"):
            reshard_state(state, pa, pb)

    def test_repack_helper_contract(self):
        assert shard_padded_len(34, 3) == 36
        assert shard_padded_len(34, 2) == 34
        with pytest.raises(ValueError, match="expected 36"):
            repack_flat_shard(np.zeros(35, np.float32), flat_len=34,
                              world_from=3, world_to=2)

    def test_zero_flip_is_structure_change_refused(self):
        gb6 = _shape_with(name="tiny-z2", global_batch=6)
        pa = planner.plan_for_layout(
            gb6, planner.Layout(dp=3, num_microbatches=2, zero=True))
        pb = planner.plan_for_layout(
            gb6, planner.Layout(dp=2, num_microbatches=3))
        with pytest.raises(LayoutMismatch, match="zero"):
            reshard_state({"params": {"w": np.zeros(4, np.float32)}},
                          pa, pb)

    def test_model_change_refused(self):
        other = _shape_with(num_layers=8)
        pb = planner.plan_for_layout(
            other, planner.Layout(dp=2, pp=2, tp=1,
                                  num_microbatches=4))
        with pytest.raises(LayoutMismatch, match="never the model"):
            reshard_state(_synth_state(), PLAN_A, pb)

    def test_leaf_disagreeing_with_plan_stack_refused(self):
        state = _synth_state((1, 2, 2))   # plan says (2, 2, 1)
        with pytest.raises(LayoutMismatch, match="own plan meta"):
            reshard_state(state, PLAN_A, PLAN_B)


# ---------------------------------------------------------------------------
# checkpoint-level reshard


def _l3d_state(plan):
    from apex1_tpu.core.policy import get_policy
    from apex1_tpu.models.llama import LlamaConfig
    from apex1_tpu.models.llama_3d import state_template

    mcfg = LlamaConfig.tiny(
        num_layers=4, max_seq_len=16, vocab_size=64, num_heads=4,
        num_kv_heads=2, hidden_size=32, ffn_size=64,
        policy=get_policy("O2"))
    return state_template(planner.llama3d_config_from_plan(
        plan, mcfg, ignore_zero=True))


class TestReshardCheckpoint:
    def _save(self, directory, plan, state):
        with ResilientCheckpointer(directory, plan=plan) as ck:
            return ck.save_sync(3, state, meta={"data_step": 4})

    def test_reshard_deterministic_and_round_trip(self, tmp_path):
        state = _l3d_state(PLAN_A)
        src = self._save(tmp_path / "ck", PLAN_A, state)
        src_tree = [(e["path"], e["sha256"])
                    for e in read_manifest(src).tree]
        _o1, m1, r1 = reshard_checkpoint(src, _l3d_state(PLAN_A),
                                         PLAN_B, tmp_path / "o1")
        _o2, m2, _r2 = reshard_checkpoint(src, _l3d_state(PLAN_A),
                                          PLAN_B, tmp_path / "o2")
        dig = [(e["path"], e["sha256"]) for e in m1.tree]
        assert dig == [(e["path"], e["sha256"]) for e in m2.tree], \
            "same (checkpoint, target plan) must be byte-identical"
        assert r1["n_restacked"] > 0 and r1["conserved"]
        # B -> A restores the ORIGINAL leaf digests (identity)
        _o3, m3, _r3 = reshard_checkpoint(_o1, _l3d_state(PLAN_B),
                                          PLAN_A, tmp_path / "o3")
        assert [(e["path"], e["sha256"]) for e in m3.tree] == src_tree
        assert read_plan(_o1)["mesh"] == PLAN_B["mesh"]
        assert m1.meta["resharded_from"]["step"] == 3
        assert m1.meta["data_step"] == 4       # resume scalars survive

    def test_resharded_checkpoint_is_verified_not_trusted(self,
                                                          tmp_path):
        state = _l3d_state(PLAN_A)
        src = self._save(tmp_path / "ck", PLAN_A, state)
        out, _m, _r = reshard_checkpoint(src, _l3d_state(PLAN_A),
                                         PLAN_B, tmp_path / "out")
        with ResilientCheckpointer(tmp_path / "ck2",
                                   plan=PLAN_B) as ck2:
            restored, man = ck2.restore(template=_l3d_state(PLAN_B),
                                        path=out)
            assert man.meta["data_step"] == 4
            # now damage ONE payload byte: the restore path must
            # refuse — a resharded checkpoint gets zero trust credit
            chaos.bitflip_checkpoint(out)
            with pytest.raises(IntegrityError):
                ck2.restore(template=_l3d_state(PLAN_B), path=out)

    def test_no_plan_meta_is_clear_layout_mismatch(self, tmp_path):
        state = _l3d_state(PLAN_A)
        with ResilientCheckpointer(tmp_path / "ck") as ck:  # no plan=
            src = ck.save_sync(1, state)
        with pytest.raises(LayoutMismatch, match="no plan meta"):
            reshard_checkpoint(src, state, PLAN_B, tmp_path / "out")
        with pytest.raises(LayoutMismatch, match="no plan meta"):
            elastic_resume(tmp_path / "ck", n_devices=4,
                           make_template=lambda p: state)
        with ResilientCheckpointer(tmp_path / "ck",
                                   plan=PLAN_A) as ck2:
            with pytest.raises(LayoutMismatch, match="no plan meta"):
                ck2.restore(template=state)

    def test_layout_change_restore_is_typed_not_shape_error(self,
                                                            tmp_path):
        """The satellite contract: relaunching with changed axis flags
        gets a LayoutMismatch POINTING AT elastic resume, replacing
        the blanket fingerprint refusal / deep shape error."""
        state = _l3d_state(PLAN_A)
        self._save(tmp_path / "ck", PLAN_A, state)
        with ResilientCheckpointer(tmp_path / "ck",
                                   plan=PLAN_B) as ck2:
            with pytest.raises(LayoutMismatch,
                               match="elastic_resume"):
                ck2.restore(template=state)

    def test_same_device_count_is_plain_resume(self, tmp_path):
        state = _l3d_state(PLAN_A)
        src = self._save(tmp_path / "ck", PLAN_A, state)
        d = elastic_resume(tmp_path / "ck",
                           n_devices=PLAN_A["n_devices"],
                           make_template=lambda p: _l3d_state(p))
        assert not d.resharded and d.path == src
        assert d.plan["mesh"] == PLAN_A["mesh"]


class TestReplanConstraints:
    def test_require_zero_filters_the_search(self):
        """The elastic constraint: a zero-source checkpoint's re-plan
        must search ONLY zero layouts (allow_zero merely permits
        them), because the optimizer-state tree structure is fixed."""
        gb6 = _shape_with(name="tiny-z3", global_batch=6)
        lays = list(planner.enumerate_layouts(gb6, 2,
                                              require_zero=True))
        assert lays and all(l.zero for l in lays)
        assert planner.make_plan(gb6, 2, require_zero=True)[
            "zero"]["enabled"] is True
        assert planner.make_plan(gb6, 2, require_zero=False)[
            "zero"]["enabled"] is False

    def test_drill_batches_are_layout_canonical(self):
        """Step i's GLOBAL batch of sequences must be identical under
        any (M, B) factorization — the 'same data order' half of the
        elastic claim (a layout-shaped RNG draw would regroup the
        flat stream into different sequences)."""
        import types

        from apex1_tpu.resilience.elastic import _drill_fixture

        _s, _c, _m, batch_at = _drill_fixture(7)
        la = types.SimpleNamespace(num_microbatches=4,
                                   microbatch_size=1, dp=2, ep=1)
        lb = types.SimpleNamespace(num_microbatches=2,
                                   microbatch_size=1, dp=4, ep=1)
        ta, _ = batch_at(3, la)     # (4, S, 2)
        tb, _ = batch_at(3, lb)     # (2, S, 4)
        seq_a = np.asarray(ta).transpose(0, 2, 1).reshape(8, -1)
        seq_b = np.asarray(tb).transpose(0, 2, 1).reshape(8, -1)
        np.testing.assert_array_equal(seq_a, seq_b)


# ---------------------------------------------------------------------------
# shrink/kill schedules


class TestShrinkSchedules:
    def test_shrink_schedule_deterministic_and_bounded(self):
        a = chaos.shrink_schedule(11, n_devices=8, lo=2, hi=6)
        b = chaos.shrink_schedule(11, n_devices=8, lo=2, hi=6)
        assert a == b
        step, survivors = a
        assert 2 <= step < 6 and survivors == 4     # kill half of 8
        with pytest.raises(ValueError, match="proper divisor"):
            chaos.shrink_schedule(1, n_devices=1, lo=0, hi=2)

    def test_fleetsim_kill_k_of_n_serves_on_survivors(self):
        from apex1_tpu.serving import FrontendConfig, ReplicaConfig
        from apex1_tpu.testing import fleetsim

        sched = fleetsim.kill_k_of_n(7, n_replicas=3, k=1, lo=2,
                                     hi=10)
        again = fleetsim.kill_k_of_n(7, n_replicas=3, k=1, lo=2,
                                     hi=10)
        assert [(f.replica, f.at_step) for f in sched.faults] \
            == [(f.replica, f.at_step) for f in again.faults]
        trace = fleetsim.synthetic_trace("steady", seed=5,
                                         horizon_s=2.0,
                                         base_rate=10.0)
        rep = fleetsim.run_fleet(
            trace,
            FrontendConfig(n_replicas=3, capacity_per_replica=8,
                           hedge_after_s=None,
                           replica=ReplicaConfig(watchdog_s=30.0,
                                                 max_restarts=1)),
            chaos=sched)
        # the victim crash-loops to failed; every submitted request
        # still completes on the n-k survivors
        assert rep.outcomes and all(o["status"] == "done"
                                    for o in rep.outcomes)
        states = [r["state"] for r in rep.summary["replicas"].values()]
        assert states.count("failed") == 1
        assert rep.summary["n_alive"] == 2


# ---------------------------------------------------------------------------
# THE acceptance drill (ISSUE 14): 8 -> 4 mid-run shrink, planner
# re-plan, manifest-verified reshard, bit-exact vs the 4-device
# control, episode reconstructable from banked obs-spine events alone


class TestElasticDrill:
    def test_drill_8_to_4_bit_exact_and_reconstructable(self):
        from apex1_tpu.resilience import elastic

        res = elastic.drill(8, 4, verbose=False)
        assert res["n_to"] == 4 and res["old_mesh"] != res["new_mesh"]
        assert res["n_restacked"] > 0       # a REAL remap, not copies
        assert len(res["losses"]) >= 1      # resumed steps ran
        assert set(res["events"]) == {
            "elastic.detect", "elastic.replan", "elastic.reshard",
            "elastic.verify", "elastic.resume"}


@pytest.mark.slow
def test_example_kill_then_elastic_relaunch(tmp_path):
    """The examples/llama_3d.py --elastic integration across a REAL
    process boundary: chaos SIGTERM -> exit 75 with a plan-banking
    checkpoint -> relaunch on 4 devices re-plans, reshards, resumes.
    (@slow: two full jax boots + 3D compiles; the in-process drill
    above is the tier-1 pin. Runs via check_all --all.)"""
    from apex1_tpu.resilience import EXIT_RESUMABLE

    # JAX_COMPILATION_CACHE_DIR exported EMPTY = the operator-disable
    # form child_cache_env documents: on this image's jax 0.4.x
    # XLA:CPU, a 4-device shard_map executable RELOADED from a warm
    # persistent cache aborts (8-device reloads are fine; reproduced
    # cold-pass/warm-crash with a fresh cache dir), so the relaunch
    # children must compile cold. CPU-only; a TPU relaunch caches
    # normally.
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "APEX1_CHAOS_SIGTERM_STEP": "3",
           "JAX_COMPILATION_CACHE_DIR": ""}
    script = os.path.join(REPO, "examples", "llama_3d.py")
    common = [sys.executable, script, "--ckpt-dir",
              str(tmp_path / "ck"), "--steps", "6", "--layers", "4",
              "--chunks", "2", "--ckpt-every", "1", "--elastic"]
    r1 = subprocess.run(common, env=env, cwd=REPO,
                        capture_output=True, text=True, timeout=600)
    assert r1.returncode == EXIT_RESUMABLE, (r1.returncode,
                                             r1.stderr[-2000:])
    env.pop("APEX1_CHAOS_SIGTERM_STEP")
    r2 = subprocess.run(common + ["--devices", "4"], env=env,
                        cwd=REPO, capture_output=True, text=True,
                        timeout=600)
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert "re-planned and resharded" in r2.stdout
    assert "elastic resume at data step 3" in r2.stdout
    assert "step counter = 6" in r2.stdout
