"""Llama model tests — forward/grad sanity, TP sharding via param_specs on
the CPU mesh, ring-attention (context-parallel) equivalence, remat parity.
(BASELINE configs 4/5 models at tiny sizes.)"""

import pytest
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from apex1_tpu.core.mesh import make_mesh
from apex1_tpu.core.policy import get_policy
from apex1_tpu.models.llama import (Llama, LlamaConfig, llama_loss_fn,
                                    param_specs)


def _tiny(**kw):
    cfg = LlamaConfig.tiny(**kw)
    model = Llama(cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 32)),
        jnp.int32)
    params = model.init(jax.random.key(0), tokens)["params"]
    return cfg, model, tokens, params


def test_forward_shapes():
    cfg, model, tokens, params = _tiny()
    logits = model.apply({"params": params}, tokens)
    assert logits.shape == (2, 32, cfg.vocab_size)
    assert np.all(np.isfinite(logits))


def test_loss_and_grads_finite():
    cfg, model, tokens, params = _tiny()
    loss_fn = llama_loss_fn(model)
    loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
    assert np.isfinite(float(loss))
    for leaf in jax.tree.leaves(grads):
        assert np.all(np.isfinite(leaf))


@pytest.mark.parametrize("policy", [
    "nothing_saveable", "dots_saveable",
    "dots_with_no_batch_dims_saveable"])
def test_remat_matches_no_remat(policy):
    """Remat (full or selective recompute) is a memory/FLOPs knob — it
    must never change loss or gradients."""
    cfg, model, tokens, params = _tiny()
    cfg_r = LlamaConfig.tiny(remat=True, remat_policy=policy)
    model_r = Llama(cfg_r)
    g1 = jax.grad(llama_loss_fn(model))(params, tokens)
    g2 = jax.grad(llama_loss_fn(model_r))(params, tokens)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_bf16_policy_runs():
    cfg, model, tokens, params = _tiny(policy=get_policy("O2"))
    logits = model.apply({"params": params}, tokens)
    assert logits.dtype == jnp.float32  # preferred_element_type accumulate
    assert np.all(np.isfinite(logits))


def test_param_specs_rules():
    cfg, model, tokens, params = _tiny()
    specs = param_specs(params)
    flat = dict(jax.tree_util.tree_flatten_with_path(specs)[0])
    by_name = {jax.tree_util.keystr(k): v for k, v in
               jax.tree_util.tree_flatten_with_path(specs)[0]}
    assert by_name["['layer0']['wq']"] == P(None, "tp")
    assert by_name["['layer0']['wo']"] == P("tp", None)
    assert by_name["['layer0']['w_down']"] == P("tp", None)
    assert by_name["['layer0']['attn_norm']"] == P()
    assert by_name["['tok_embeddings']"] == P("tp", None)
    # head now stored (V, H) — embedding-table layout for the fused
    # LM-head+CE kernel; vocab axis still tp-sharded
    assert by_name["['output']"] == P("tp", None)


def test_tp_sharded_forward_matches_single(devices):
    """pjit + param_specs over tp=4: GSPMD-sharded forward ≡ replicated."""
    cfg, model, tokens, params = _tiny()
    mesh = make_mesh(tp=4, dp=1, devices=devices[:4])
    specs = param_specs(params)
    sharded = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)
    with jax.set_mesh(mesh):
        out_sharded = jax.jit(
            lambda p, t: model.apply({"params": p}, t))(sharded, tokens)
    out_single = model.apply({"params": params}, tokens)
    np.testing.assert_allclose(np.asarray(out_sharded),
                               np.asarray(out_single), rtol=2e-5, atol=2e-5)


@pytest.mark.slow  # 870s-cap headroom (~10s): llama x context-parallel
# COMPOSITION; halves pinned tier-1 — ring-attention parity/grads on
# the virtual mesh (test_ring_attention, incl. cp=2) and llama solo
# loss/grads; the 4-axis dryrun + check_all --all run the composition
def test_context_parallel_matches_global(devices):
    """Llama block with ring attention over cp=4 ≡ unsharded model."""
    cfg = LlamaConfig.tiny()
    model = Llama(cfg)
    model_cp = Llama(cfg, seq_shard_axis="cp")
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 64)),
        jnp.int32)
    params = model.init(jax.random.key(0), tokens)["params"]
    mesh = make_mesh(cp=4, dp=1, devices=devices[:4])

    def local(params, tokens):
        return model_cp.apply({"params": params}, tokens)

    fn = jax.jit(jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(None, "cp")), out_specs=P(None, "cp", None)))
    got = fn(params, tokens)
    want = model.apply({"params": params}, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
class TestPackedBatches:
    """Varlen/packed batches (≙ reference fmha cu_seqlens): packing two
    documents into one row with segment_ids + per-segment positions must
    reproduce each document's standalone forward exactly."""

    def test_packed_equals_separate(self, rng):
        import numpy as np

        from apex1_tpu.runtime import pack_documents

        cfg = LlamaConfig.tiny()
        model = Llama(cfg)
        d1 = rng.integers(0, cfg.vocab_size, 12).astype(np.int32)
        d2 = rng.integers(0, cfg.vocab_size, 9).astype(np.int32)
        tokens, segs, pos = pack_documents([d1, d2], seq_len=24)
        assert tokens.shape == (1, 24)
        assert segs[0, 11] == 0 and segs[0, 12] == 1 and segs[0, 21] == -1
        assert pos[0, 12] == 0  # second doc restarts

        params = model.init(jax.random.key(0),
                            jnp.asarray(tokens))["params"]
        packed = model.apply({"params": params}, jnp.asarray(tokens),
                             segment_ids=jnp.asarray(segs),
                             positions=jnp.asarray(pos))
        lone1 = model.apply({"params": params}, jnp.asarray(d1[None]))
        lone2 = model.apply({"params": params}, jnp.asarray(d2[None]))
        np.testing.assert_allclose(np.asarray(packed[0, :12]),
                                   np.asarray(lone1[0]), rtol=2e-4,
                                   atol=2e-4)
        np.testing.assert_allclose(np.asarray(packed[0, 12:21]),
                                   np.asarray(lone2[0]), rtol=2e-4,
                                   atol=2e-4)

    def test_packed_loss_masks_boundaries(self, rng):
        import numpy as np

        from apex1_tpu.runtime import pack_documents

        cfg = LlamaConfig.tiny()
        model = Llama(cfg)
        docs = [rng.integers(0, cfg.vocab_size, n).astype(np.int32)
                for n in (10, 7, 5)]
        tokens, segs, pos = pack_documents(docs, seq_len=16)
        params = model.init(jax.random.key(0),
                            jnp.asarray(tokens))["params"]
        loss_fn = llama_loss_fn(model)
        loss = loss_fn(params, jnp.asarray(tokens),
                       jnp.asarray(segs), jnp.asarray(pos))
        assert np.isfinite(float(loss))
        # grads flow
        g = jax.grad(lambda p: loss_fn(p, jnp.asarray(tokens),
                                       jnp.asarray(segs),
                                       jnp.asarray(pos)))(params)
        assert all(np.all(np.isfinite(le)) for le in jax.tree.leaves(g))
