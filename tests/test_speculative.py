"""Speculative decoding (`models.generate.speculative_generate`): the
output must be TOKEN-IDENTICAL to plain greedy decoding of the target
model alone — for any draft model (the draft changes only how many target
forwards run). Also pins the chunk-verify attention mode it rides on."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.core.policy import get_policy
from apex1_tpu.models.generate import (generate, gpt2_decoder,
                                       llama_decoder, speculative_generate)
from apex1_tpu.models.gpt2 import GPT2, GPT2Config
from apex1_tpu.models.llama import Llama, LlamaConfig


class TestChunkVerifyAttention:
    def test_chunk_decode_matches_token_by_token(self):
        """Feeding K tokens with chunk_decode=True must give the same
        last-position logits trajectory as K single-token decode steps."""
        cfg = LlamaConfig.tiny(policy=get_policy("O0"), max_seq_len=32)
        model = Llama(cfg)
        rng = np.random.default_rng(3)
        prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 5)),
                             jnp.int32)
        extra = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 3)),
                            jnp.int32)
        params = model.init(jax.random.key(0), prompt)["params"]
        apply_fn, make_cache = llama_decoder(model)

        # path A: prefill + 3 single-token decodes
        cache = make_cache(2, 16)
        la, cache = apply_fn(params, prompt, cache, 0)
        logits_steps = [la[:, -1]]
        for t in range(3):
            lt, cache = apply_fn(params, extra[:, t:t + 1], cache, 5 + t)
            logits_steps.append(lt[:, -1])

        # path B: prefill + ONE 3-token chunk-verify
        cache2 = make_cache(2, 16)
        lb, cache2 = apply_fn(params, prompt, cache2, 0)
        lc, cache2 = apply_fn(params, extra, cache2, 5, chunk_decode=True)
        np.testing.assert_allclose(np.asarray(lb[:, -1]),
                                   np.asarray(logits_steps[0]),
                                   rtol=2e-4, atol=2e-4)
        for t in range(3):
            np.testing.assert_allclose(
                np.asarray(lc[:, t]), np.asarray(logits_steps[t + 1]),
                rtol=2e-4, atol=2e-4,
                err_msg=f"chunk position {t} diverged from step decode")


class TestSpeculativeGenerate:
    def _models(self, family):
        rng = np.random.default_rng(17)
        if family == "llama":
            cfg_t = LlamaConfig.tiny(policy=get_policy("O0"),
                                     max_seq_len=64)
            cfg_d = LlamaConfig.tiny(policy=get_policy("O0"),
                                     max_seq_len=64, num_layers=1,
                                     hidden_size=32, ffn_size=64)
            tgt, drf = Llama(cfg_t), Llama(cfg_d)
            mk = llama_decoder
        else:
            cfg_t = GPT2Config.tiny(policy=get_policy("O0"),
                                    max_seq_len=64)
            cfg_d = GPT2Config.tiny(policy=get_policy("O0"),
                                    max_seq_len=64, num_layers=1,
                                    hidden_size=64)
            tgt, drf = GPT2(cfg_t), GPT2(cfg_d)
            mk = gpt2_decoder
        prompt = jnp.asarray(rng.integers(1, cfg_t.vocab_size, (2, 5)),
                             jnp.int32)
        pt = tgt.init(jax.random.key(0), prompt)["params"]
        pd = drf.init(jax.random.key(1), prompt)["params"]
        t_fn, t_cache = mk(tgt)
        d_fn, d_cache = mk(drf)
        return (cfg_t, prompt, t_fn, pt, t_cache, d_fn, pd, d_cache)

    # [1-llama] to @slow for 870s-cap headroom (~11s): the K=1
    # degenerate draft stays pinned on gpt2, llama spec stays pinned at
    # K=3/4 (the multi-token verify paths); check_all --all
    @pytest.mark.parametrize("family,K", [
        pytest.param("llama", 1, marks=pytest.mark.slow),
        ("llama", 3), ("llama", 4),
        ("gpt2", 1), ("gpt2", 3), ("gpt2", 4)])
    def test_matches_target_greedy(self, family, K):
        (cfg, prompt, t_fn, pt, mk_t, d_fn, pd, mk_d) = \
            self._models(family)
        N = 10
        S0 = prompt.shape[1]
        got, rounds = speculative_generate(
            t_fn, pt, d_fn, pd, prompt, max_new_tokens=N,
            target_cache=mk_t(2, S0 + N + K + 1),
            draft_cache=mk_d(2, S0 + N + K + 1),
            num_draft=K, vocab_size=cfg.vocab_size)
        want = generate(t_fn, pt, prompt, max_new_tokens=N,
                        cache=mk_t(2, S0 + N),
                        vocab_size=cfg.vocab_size)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert (np.asarray(rounds) >= 1).all()

    @pytest.mark.parametrize("N", [9, 17, 25])
    def test_self_draft_accepts_everything(self, N):
        """Draft == target: every proposal matches, so each round emits
        num_draft+1 tokens and rounds == ceil((N-1)/(K+1)) EXACTLY.
        The longer N cases are the regression for the draft-cache hole
        (review r4): the draft scan must also write drafts[K-1]'s K/V —
        a skipped slot stayed zero yet attended, and acceptance silently
        collapsed after the first all-accept round (observed 6 rounds vs
        the ideal 4 at N=17 before the fix)."""
        (cfg, prompt, t_fn, pt, mk_t, _, _, _) = self._models("llama")
        K = 3
        S0 = prompt.shape[1]
        got, rounds = speculative_generate(
            t_fn, pt, t_fn, pt, prompt, max_new_tokens=N,
            target_cache=mk_t(2, S0 + N + K + 1),
            draft_cache=mk_t(2, S0 + N + K + 1),
            num_draft=K, vocab_size=cfg.vocab_size)
        want = generate(t_fn, pt, prompt, max_new_tokens=N,
                        cache=mk_t(2, S0 + N),
                        vocab_size=cfg.vocab_size)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # all-accept: ceil((N-1) / (K+1)) rounds after the prefill token
        assert (np.asarray(rounds) == -(-(N - 1) // (K + 1))).all(), (
            np.asarray(rounds))

    def test_undersized_cache_raises(self):
        (cfg, prompt, t_fn, pt, mk_t, d_fn, pd, mk_d) = \
            self._models("llama")
        N, K = 8, 3
        S0 = prompt.shape[1]
        with pytest.raises(ValueError, match="positions"):
            speculative_generate(
                t_fn, pt, d_fn, pd, prompt, max_new_tokens=N,
                target_cache=mk_t(2, S0 + N),  # generate() sizing: too small
                draft_cache=mk_d(2, S0 + N + K + 1),
                num_draft=K, vocab_size=cfg.vocab_size)

    def test_eos_stops_and_pads(self):
        (cfg, prompt, t_fn, pt, mk_t, d_fn, pd, mk_d) = \
            self._models("gpt2")
        N, K = 10, 3
        S0 = prompt.shape[1]
        first, _ = speculative_generate(
            t_fn, pt, d_fn, pd, prompt, max_new_tokens=N,
            target_cache=mk_t(2, S0 + N + K + 1),
            draft_cache=mk_d(2, S0 + N + K + 1),
            num_draft=K, vocab_size=cfg.vocab_size)
        eos = int(first[0, 2])  # a token row 0 actually emits mid-stream
        got, _ = speculative_generate(
            t_fn, pt, d_fn, pd, prompt, max_new_tokens=N,
            target_cache=mk_t(2, S0 + N + K + 1),
            draft_cache=mk_d(2, S0 + N + K + 1),
            num_draft=K, vocab_size=cfg.vocab_size,
            eos_id=eos, pad_id=0)
        want = generate(t_fn, pt, prompt, max_new_tokens=N,
                        cache=mk_t(2, S0 + N),
                        vocab_size=cfg.vocab_size, eos_id=eos, pad_id=0)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        row = np.asarray(got[0])
        hits = np.nonzero(row == eos)[0]
        assert hits.size > 0 and (row[hits[0] + 1:] == 0).all()

    def test_sampling_accept_rule_preserves_target_distribution(self):
        """The round-level rejection rule is the mathematical heart of
        speculative SAMPLING: for ANY draft distribution q, the law of
        the first emitted token must be exactly p (Leviathan et al.).
        Checked empirically on a tiny vocab against a deliberately
        mismatched q, many independent rounds, fixed seed."""
        from apex1_tpu.models.generate import _speculative_accept
        V, K, TRIALS = 8, 3, 30000
        rng = np.random.default_rng(0)
        p_rows = rng.dirichlet(np.ones(V), size=K + 1)
        q_rows = rng.dirichlet(np.ones(V) * 0.3, size=K)  # mismatched
        p = jnp.asarray(p_rows, jnp.float32)
        q = jnp.asarray(q_rows, jnp.float32)

        def one(key):
            kd, ka = jax.random.split(key)
            drafts = jax.vmap(
                lambda k, lq: jax.random.categorical(k, jnp.log(lq)))(
                    jax.random.split(kd, K), q).astype(jnp.int32)
            a, corr = _speculative_accept(p, q, drafts, ka)
            # first emitted token: drafts[0] if a >= 1 else corr
            return jnp.where(a >= 1, drafts[0], corr)

        toks = jax.jit(jax.vmap(one))(
            jax.random.split(jax.random.key(42), TRIALS))
        emp = np.bincount(np.asarray(toks), minlength=V) / TRIALS
        # ~3.5 sigma at 30k trials per bin
        tol = 3.5 * np.sqrt(p_rows[0] * (1 - p_rows[0]) / TRIALS)
        assert (np.abs(emp - p_rows[0]) < tol + 1e-3).all(), (
            emp, p_rows[0], tol)

    def test_sampled_self_draft_accepts_everything(self):
        """temperature > 0 with draft == target: acceptance ratio
        min(1, p/q) == 1 up to chunk-verify-vs-step-decode numerics
        (~1e-4 rel), so rounds sit at the all-accept bound — allow one
        extra round for a borderline uniform draw landing inside that
        numeric window (review r4)."""
        (cfg, prompt, t_fn, pt, mk_t, _, _, _) = self._models("llama")
        N, K = 13, 3
        S0 = prompt.shape[1]

        def run():
            return speculative_generate(
                t_fn, pt, t_fn, pt, prompt, max_new_tokens=N,
                target_cache=mk_t(2, S0 + N + K + 1),
                draft_cache=mk_t(2, S0 + N + K + 1),
                num_draft=K, temperature=0.8,
                rng=jax.random.key(3), vocab_size=cfg.vocab_size)

        toks, rounds = run()
        bound = -(-(N - 1) // (K + 1))
        assert (np.asarray(rounds) <= bound + 1).all(), (
            np.asarray(rounds), bound)
        toks2, _ = run()
        np.testing.assert_array_equal(np.asarray(toks),
                                      np.asarray(toks2))
        assert (np.asarray(toks) < cfg.vocab_size).all()

    def test_sampled_runs_with_distinct_draft(self):
        """Sampled spec decode with a real (different) draft: emits
        valid tokens, respects eos padding, reproducible per seed."""
        (cfg, prompt, t_fn, pt, mk_t, d_fn, pd, mk_d) = \
            self._models("gpt2")
        N, K = 8, 2
        S0 = prompt.shape[1]
        toks, rounds = speculative_generate(
            t_fn, pt, d_fn, pd, prompt, max_new_tokens=N,
            target_cache=mk_t(2, S0 + N + K + 1),
            draft_cache=mk_d(2, S0 + N + K + 1),
            num_draft=K, temperature=0.7, top_k=20,
            rng=jax.random.key(5), vocab_size=cfg.vocab_size)
        assert toks.shape == (2, N)
        assert (np.asarray(toks) < cfg.vocab_size).all()
        assert (np.asarray(rounds) >= 1).all()

    def test_bad_num_draft_raises(self):
        (cfg, prompt, t_fn, pt, mk_t, d_fn, pd, mk_d) = \
            self._models("llama")
        with pytest.raises(ValueError, match="num_draft"):
            speculative_generate(
                t_fn, pt, d_fn, pd, prompt, max_new_tokens=4,
                target_cache=mk_t(2, 16), draft_cache=mk_d(2, 16),
                num_draft=0)


class TestSpeculativeRaggedAndQuant:
    """The serving support matrix's new composition rows (VERDICT r4
    Missing #5): ragged x speculative, int8 draft under bf16 target, and
    both at once. docs/serving.md tables the full matrix."""

    def _ragged_setup(self):
        rng = np.random.default_rng(29)
        cfg_t = LlamaConfig.tiny(policy=get_policy("O0"), max_seq_len=64)
        cfg_d = LlamaConfig.tiny(policy=get_policy("O0"), max_seq_len=64,
                                 num_layers=1, hidden_size=32, ffn_size=64)
        tgt, drf = Llama(cfg_t), Llama(cfg_d)
        S0 = 6
        lens = np.asarray([6, 3, 5])
        prompt = np.asarray(rng.integers(1, cfg_t.vocab_size, (3, S0)),
                            dtype=np.int32)
        for b, ln in enumerate(lens):   # right-padded ragged batch
            prompt[b, ln:] = 0
        prompt = jnp.asarray(prompt)
        pt = tgt.init(jax.random.key(0), prompt)["params"]
        pd = drf.init(jax.random.key(1), prompt)["params"]
        t_fn, mk_t = llama_decoder(tgt)
        d_fn, mk_d = llama_decoder(drf)
        return cfg_t, prompt, lens, t_fn, pt, mk_t, d_fn, pd, mk_d

    @pytest.mark.slow  # 870s-cap headroom: speculative x ragged x
    # quant TRIPLE composition (5.5s); each pair stays tier-1 (ragged
    # spec in TestSpeculativeGenerate, quant spec below), full run via
    # check_all --all
    def test_ragged_rows_match_solo_decode(self):
        """Greedy ragged speculative: every row must be token-identical
        to greedy-decoding that row ALONE (the per-row contract
        `generate(prompt_lens=...)` pins, now through the speculative
        path's draft steps + chunk-verify)."""
        (cfg, prompt, lens, t_fn, pt, mk_t, d_fn, pd, mk_d) = \
            self._ragged_setup()
        N, K = 8, 3
        S0 = prompt.shape[1]
        got, rounds = speculative_generate(
            t_fn, pt, d_fn, pd, prompt, max_new_tokens=N,
            target_cache=mk_t(3, S0 + N + K + 1),
            draft_cache=mk_d(3, S0 + N + K + 1),
            num_draft=K, vocab_size=cfg.vocab_size,
            prompt_lens=lens)
        assert (np.asarray(rounds) >= 1).all()
        for b, ln in enumerate(lens):
            solo = generate(t_fn, pt, prompt[b:b + 1, :ln],
                            max_new_tokens=N, cache=mk_t(1, ln + N),
                            vocab_size=cfg.vocab_size)
            np.testing.assert_array_equal(
                np.asarray(got[b]), np.asarray(solo[0]),
                err_msg=f"row {b} (len {ln}) diverged from solo decode")

    @pytest.mark.slow  # ~15s ragged x sampled COMPOSITION smoke; the
    # halves stay tier-1: sampled accept/resample in
    # test_sampled_self_draft_accepts_everything, ragged per-row
    # alignment in test_rows_match_solo (greedy) directly above, and
    # the ragged x int8 composition pins. Runs via check_all --all.
    def test_ragged_sampled_smoke(self):
        """Sampled ragged speculative: the accept rule runs per row under
        vmap with per-row alignment — valid tokens, reproducible."""
        (cfg, prompt, lens, t_fn, pt, mk_t, d_fn, pd, mk_d) = \
            self._ragged_setup()
        N, K = 6, 2
        S0 = prompt.shape[1]

        def run():
            return speculative_generate(
                t_fn, pt, d_fn, pd, prompt, max_new_tokens=N,
                target_cache=mk_t(3, S0 + N + K + 1),
                draft_cache=mk_d(3, S0 + N + K + 1),
                num_draft=K, temperature=0.7, rng=jax.random.key(7),
                vocab_size=cfg.vocab_size, prompt_lens=lens)

        toks, rounds = run()
        assert toks.shape == (3, N)
        assert (np.asarray(toks) < cfg.vocab_size).all()
        toks2, _ = run()
        np.testing.assert_array_equal(np.asarray(toks), np.asarray(toks2))

    def test_moe_target_matches_its_own_greedy(self):
        """docs/serving.md matrix: MoE x speculative — an MoE TARGET
        under a dense draft stays token-identical to the MoE model's own
        greedy decode (the chunk-verify path through expert routing)."""
        rng = np.random.default_rng(43)
        cfg_t = LlamaConfig.tiny(policy=get_policy("O0"), max_seq_len=64,
                                 moe_every=1, num_experts=2, moe_top_k=1,
                                 moe_capacity_factor=4.0)
        cfg_d = LlamaConfig.tiny(policy=get_policy("O0"), max_seq_len=64,
                                 num_layers=1, hidden_size=32,
                                 ffn_size=64)
        tgt, drf = Llama(cfg_t), Llama(cfg_d)
        prompt = jnp.asarray(rng.integers(1, cfg_t.vocab_size, (2, 5)),
                             jnp.int32)
        pt = tgt.init(jax.random.key(0), prompt)["params"]
        pd = drf.init(jax.random.key(1), prompt)["params"]
        t_fn, mk_t = llama_decoder(tgt)
        d_fn, mk_d = llama_decoder(drf)
        N, K = 8, 3
        S0 = prompt.shape[1]
        got, rounds = speculative_generate(
            t_fn, pt, d_fn, pd, prompt, max_new_tokens=N,
            target_cache=mk_t(2, S0 + N + K + 1),
            draft_cache=mk_d(2, S0 + N + K + 1),
            num_draft=K, vocab_size=cfg_t.vocab_size)
        want = generate(t_fn, pt, prompt, max_new_tokens=N,
                        cache=mk_t(2, S0 + N),
                        vocab_size=cfg_t.vocab_size)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert (np.asarray(rounds) >= 1).all()

    def test_int8_draft_under_bf16_target(self):
        """An int8-quantized draft under a full-precision target: greedy
        output stays token-identical to the target's own greedy decode
        (the draft can only change HOW MANY verify rounds run)."""
        from apex1_tpu.models.quant_decode import llama_quant_decoder

        rng = np.random.default_rng(31)
        cfg_t = LlamaConfig.tiny(policy=get_policy("O0"), max_seq_len=64)
        cfg_d = LlamaConfig.tiny(policy=get_policy("O2"), max_seq_len=64,
                                 num_layers=1)
        tgt, drf = Llama(cfg_t), Llama(cfg_d)
        prompt = jnp.asarray(rng.integers(1, cfg_t.vocab_size, (2, 5)),
                             jnp.int32)
        pt = tgt.init(jax.random.key(0), prompt)["params"]
        pd = drf.init(jax.random.key(1), prompt)["params"]
        t_fn, mk_t = llama_decoder(tgt)
        d_fn, mk_d, qpd = llama_quant_decoder(drf, pd)
        N, K = 8, 3
        S0 = prompt.shape[1]
        got, rounds = speculative_generate(
            t_fn, pt, d_fn, qpd, prompt, max_new_tokens=N,
            target_cache=mk_t(2, S0 + N + K + 1),
            draft_cache=mk_d(2, S0 + N + K + 1),
            num_draft=K, vocab_size=cfg_t.vocab_size)
        want = generate(t_fn, pt, prompt, max_new_tokens=N,
                        cache=mk_t(2, S0 + N), vocab_size=cfg_t.vocab_size)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert (np.asarray(rounds) >= 1).all()

    def test_int8_target_matches_its_own_greedy(self):
        """The OTHER half of the int8 x speculative matrix cell: an
        int8-quantized TARGET under a bf16 draft. This drives the
        chunk-verify (chunk_decode) attention path through the quant
        decoder's cached attention — previously asserted by apply-
        contract reasoning only (review r5). Greedy speculative output
        must be token-identical to the int8 target's own greedy decode."""
        from apex1_tpu.models.quant_decode import llama_quant_decoder

        rng = np.random.default_rng(33)
        cfg_t = LlamaConfig.tiny(policy=get_policy("O2"), max_seq_len=64)
        cfg_d = LlamaConfig.tiny(policy=get_policy("O0"), max_seq_len=64,
                                 num_layers=1)
        tgt, drf = Llama(cfg_t), Llama(cfg_d)
        prompt = jnp.asarray(rng.integers(1, cfg_t.vocab_size, (2, 5)),
                             jnp.int32)
        pt = tgt.init(jax.random.key(4), prompt)["params"]
        pd = drf.init(jax.random.key(5), prompt)["params"]
        t_fn, mk_t, qpt = llama_quant_decoder(tgt, pt)
        d_fn, mk_d = llama_decoder(drf)
        N, K = 8, 3
        S0 = prompt.shape[1]
        got, rounds = speculative_generate(
            t_fn, qpt, d_fn, pd, prompt, max_new_tokens=N,
            target_cache=mk_t(2, S0 + N + K + 1),
            draft_cache=mk_d(2, S0 + N + K + 1),
            num_draft=K, vocab_size=cfg_t.vocab_size)
        want = generate(t_fn, qpt, prompt, max_new_tokens=N,
                        cache=mk_t(2, S0 + N), vocab_size=cfg_t.vocab_size)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert (np.asarray(rounds) >= 1).all()

    @pytest.mark.slow  # 870s-cap headroom (~14s): ragged x speculative
    # x int8-draft TRIPLE; pairwise halves pinned tier-1 —
    # test_int8_draft_under_bf16_target and test_ragged_sampled_smoke;
    # check_all --all
    def test_int8_draft_ragged(self):
        """The full composition: int8 draft + bf16 target + ragged batch,
        greedy — per-row token identity with solo decode."""
        from apex1_tpu.models.quant_decode import llama_quant_decoder

        (cfg, prompt, lens, t_fn, pt, mk_t, _d_fn, _pd, _mk_d) = \
            self._ragged_setup()
        cfg_d = LlamaConfig.tiny(policy=get_policy("O2"), max_seq_len=64,
                                 num_layers=1)
        drf = Llama(cfg_d)
        pd = drf.init(jax.random.key(9), prompt)["params"]
        d_fn, mk_d, qpd = llama_quant_decoder(drf, pd)
        N, K = 6, 2
        S0 = prompt.shape[1]
        got, _ = speculative_generate(
            t_fn, pt, d_fn, qpd, prompt, max_new_tokens=N,
            target_cache=mk_t(3, S0 + N + K + 1),
            draft_cache=mk_d(3, S0 + N + K + 1),
            num_draft=K, vocab_size=cfg.vocab_size, prompt_lens=lens)
        for b, ln in enumerate(lens):
            solo = generate(t_fn, pt, prompt[b:b + 1, :ln],
                            max_new_tokens=N, cache=mk_t(1, ln + N),
                            vocab_size=cfg.vocab_size)
            np.testing.assert_array_equal(
                np.asarray(got[b]), np.asarray(solo[0]),
                err_msg=f"row {b} (len {ln}) diverged from solo decode")
