"""Speculative decoding (`models.generate.speculative_generate`): the
output must be TOKEN-IDENTICAL to plain greedy decoding of the target
model alone — for any draft model (the draft changes only how many target
forwards run). Also pins the chunk-verify attention mode it rides on."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.core.policy import get_policy
from apex1_tpu.models.generate import (generate, gpt2_decoder,
                                       llama_decoder, speculative_generate)
from apex1_tpu.models.gpt2 import GPT2, GPT2Config
from apex1_tpu.models.llama import Llama, LlamaConfig


class TestChunkVerifyAttention:
    def test_chunk_decode_matches_token_by_token(self):
        """Feeding K tokens with chunk_decode=True must give the same
        last-position logits trajectory as K single-token decode steps."""
        cfg = LlamaConfig.tiny(policy=get_policy("O0"), max_seq_len=32)
        model = Llama(cfg)
        rng = np.random.default_rng(3)
        prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 5)),
                             jnp.int32)
        extra = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 3)),
                            jnp.int32)
        params = model.init(jax.random.key(0), prompt)["params"]
        apply_fn, make_cache = llama_decoder(model)

        # path A: prefill + 3 single-token decodes
        cache = make_cache(2, 16)
        la, cache = apply_fn(params, prompt, cache, 0)
        logits_steps = [la[:, -1]]
        for t in range(3):
            lt, cache = apply_fn(params, extra[:, t:t + 1], cache, 5 + t)
            logits_steps.append(lt[:, -1])

        # path B: prefill + ONE 3-token chunk-verify
        cache2 = make_cache(2, 16)
        lb, cache2 = apply_fn(params, prompt, cache2, 0)
        lc, cache2 = apply_fn(params, extra, cache2, 5, chunk_decode=True)
        np.testing.assert_allclose(np.asarray(lb[:, -1]),
                                   np.asarray(logits_steps[0]),
                                   rtol=2e-4, atol=2e-4)
        for t in range(3):
            np.testing.assert_allclose(
                np.asarray(lc[:, t]), np.asarray(logits_steps[t + 1]),
                rtol=2e-4, atol=2e-4,
                err_msg=f"chunk position {t} diverged from step decode")


class TestSpeculativeGenerate:
    def _models(self, family):
        rng = np.random.default_rng(17)
        if family == "llama":
            cfg_t = LlamaConfig.tiny(policy=get_policy("O0"),
                                     max_seq_len=64)
            cfg_d = LlamaConfig.tiny(policy=get_policy("O0"),
                                     max_seq_len=64, num_layers=1,
                                     hidden_size=32, ffn_size=64)
            tgt, drf = Llama(cfg_t), Llama(cfg_d)
            mk = llama_decoder
        else:
            cfg_t = GPT2Config.tiny(policy=get_policy("O0"),
                                    max_seq_len=64)
            cfg_d = GPT2Config.tiny(policy=get_policy("O0"),
                                    max_seq_len=64, num_layers=1,
                                    hidden_size=64)
            tgt, drf = GPT2(cfg_t), GPT2(cfg_d)
            mk = gpt2_decoder
        prompt = jnp.asarray(rng.integers(1, cfg_t.vocab_size, (2, 5)),
                             jnp.int32)
        pt = tgt.init(jax.random.key(0), prompt)["params"]
        pd = drf.init(jax.random.key(1), prompt)["params"]
        t_fn, t_cache = mk(tgt)
        d_fn, d_cache = mk(drf)
        return (cfg_t, prompt, t_fn, pt, t_cache, d_fn, pd, d_cache)

    @pytest.mark.parametrize("family", ["llama", "gpt2"])
    @pytest.mark.parametrize("K", [1, 3, 4])
    def test_matches_target_greedy(self, family, K):
        (cfg, prompt, t_fn, pt, mk_t, d_fn, pd, mk_d) = \
            self._models(family)
        N = 10
        S0 = prompt.shape[1]
        got, rounds = speculative_generate(
            t_fn, pt, d_fn, pd, prompt, max_new_tokens=N,
            target_cache=mk_t(2, S0 + N + K + 1),
            draft_cache=mk_d(2, S0 + N + K + 1),
            num_draft=K, vocab_size=cfg.vocab_size)
        want = generate(t_fn, pt, prompt, max_new_tokens=N,
                        cache=mk_t(2, S0 + N),
                        vocab_size=cfg.vocab_size)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert (np.asarray(rounds) >= 1).all()

    @pytest.mark.parametrize("N", [9, 17, 25])
    def test_self_draft_accepts_everything(self, N):
        """Draft == target: every proposal matches, so each round emits
        num_draft+1 tokens and rounds == ceil((N-1)/(K+1)) EXACTLY.
        The longer N cases are the regression for the draft-cache hole
        (review r4): the draft scan must also write drafts[K-1]'s K/V —
        a skipped slot stayed zero yet attended, and acceptance silently
        collapsed after the first all-accept round (observed 6 rounds vs
        the ideal 4 at N=17 before the fix)."""
        (cfg, prompt, t_fn, pt, mk_t, _, _, _) = self._models("llama")
        K = 3
        S0 = prompt.shape[1]
        got, rounds = speculative_generate(
            t_fn, pt, t_fn, pt, prompt, max_new_tokens=N,
            target_cache=mk_t(2, S0 + N + K + 1),
            draft_cache=mk_t(2, S0 + N + K + 1),
            num_draft=K, vocab_size=cfg.vocab_size)
        want = generate(t_fn, pt, prompt, max_new_tokens=N,
                        cache=mk_t(2, S0 + N),
                        vocab_size=cfg.vocab_size)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # all-accept: ceil((N-1) / (K+1)) rounds after the prefill token
        assert (np.asarray(rounds) == -(-(N - 1) // (K + 1))).all(), (
            np.asarray(rounds))

    def test_undersized_cache_raises(self):
        (cfg, prompt, t_fn, pt, mk_t, d_fn, pd, mk_d) = \
            self._models("llama")
        N, K = 8, 3
        S0 = prompt.shape[1]
        with pytest.raises(ValueError, match="positions"):
            speculative_generate(
                t_fn, pt, d_fn, pd, prompt, max_new_tokens=N,
                target_cache=mk_t(2, S0 + N),  # generate() sizing: too small
                draft_cache=mk_d(2, S0 + N + K + 1),
                num_draft=K, vocab_size=cfg.vocab_size)

    def test_eos_stops_and_pads(self):
        (cfg, prompt, t_fn, pt, mk_t, d_fn, pd, mk_d) = \
            self._models("gpt2")
        N, K = 10, 3
        S0 = prompt.shape[1]
        first, _ = speculative_generate(
            t_fn, pt, d_fn, pd, prompt, max_new_tokens=N,
            target_cache=mk_t(2, S0 + N + K + 1),
            draft_cache=mk_d(2, S0 + N + K + 1),
            num_draft=K, vocab_size=cfg.vocab_size)
        eos = int(first[0, 2])  # a token row 0 actually emits mid-stream
        got, _ = speculative_generate(
            t_fn, pt, d_fn, pd, prompt, max_new_tokens=N,
            target_cache=mk_t(2, S0 + N + K + 1),
            draft_cache=mk_d(2, S0 + N + K + 1),
            num_draft=K, vocab_size=cfg.vocab_size,
            eos_id=eos, pad_id=0)
        want = generate(t_fn, pt, prompt, max_new_tokens=N,
                        cache=mk_t(2, S0 + N),
                        vocab_size=cfg.vocab_size, eos_id=eos, pad_id=0)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        row = np.asarray(got[0])
        hits = np.nonzero(row == eos)[0]
        assert hits.size > 0 and (row[hits[0] + 1:] == 0).all()

    def test_bad_num_draft_raises(self):
        (cfg, prompt, t_fn, pt, mk_t, d_fn, pd, mk_d) = \
            self._models("llama")
        with pytest.raises(ValueError, match="num_draft"):
            speculative_generate(
                t_fn, pt, d_fn, pd, prompt, max_new_tokens=4,
                target_cache=mk_t(2, 16), draft_cache=mk_d(2, 16),
                num_draft=0)
