"""C++ host runtime tests — flatten/unflatten parity (≙ tests around
``apex_C`` in ``tests/L0/run_fp16util``), normalize parity vs numpy, bf16
round-trip vs jax, PrefetchLoader ordering/overlap, numpy fallback."""

import numpy as np
import pytest

import apex1_tpu.runtime as rt


def test_native_library_builds():
    assert rt.native_available(), "g++ build of _runtime.cpp failed"


def test_flatten_unflatten_roundtrip(rng):
    arrays = [rng.normal(size=(4, 5)).astype(np.float32),
              rng.integers(0, 100, (7,)).astype(np.int32),
              rng.normal(size=(2, 3, 8)).astype(np.float64),
              np.asarray(3.5, np.float32)]
    flat = rt.flatten(arrays)
    assert flat.dtype == np.uint8
    assert flat.nbytes == sum(a.nbytes for a in arrays)
    outs = rt.unflatten(flat, [(a.shape, a.dtype) for a in arrays])
    for a, b in zip(arrays, outs):
        np.testing.assert_array_equal(a, b)


def test_unflatten_size_mismatch(rng):
    flat = rt.flatten([np.zeros((4,), np.float32)])
    with pytest.raises(ValueError):
        rt.unflatten(flat, [((5,), np.float32)])


def test_normalize_matches_numpy(rng):
    imgs = rng.integers(0, 256, (4, 16, 16, 3)).astype(np.uint8)
    mean, std = (0.485, 0.456, 0.406), (0.229, 0.224, 0.225)
    got = rt.normalize_images(imgs, mean, std)
    want = ((imgs.astype(np.float32) / 255.0
             - np.asarray(mean, np.float32))
            / np.asarray(std, np.float32))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_bf16_bits_match_jax(rng):
    import jax.numpy as jnp
    x = rng.normal(size=(1024,)).astype(np.float32) * 100
    bits = rt.f32_to_bf16_bits(x)
    want = np.asarray(jnp.asarray(x).astype(jnp.bfloat16)).view(np.uint16)
    np.testing.assert_array_equal(bits, want)
    back = rt.bf16_bits_to_f32(bits)
    np.testing.assert_array_equal(
        back, np.asarray(jnp.asarray(x).astype(jnp.bfloat16),
                         dtype=np.float32))


def test_numpy_fallback_paths(rng, monkeypatch):
    monkeypatch.setattr(rt, "_LIB", None)
    arrays = [rng.normal(size=(3, 3)).astype(np.float32),
              rng.integers(0, 9, (4,)).astype(np.int64)]
    outs = rt.unflatten(rt.flatten(arrays),
                        [(a.shape, a.dtype) for a in arrays])
    for a, b in zip(arrays, outs):
        np.testing.assert_array_equal(a, b)
    imgs = rng.integers(0, 256, (2, 4, 4, 3)).astype(np.uint8)
    got = rt.normalize_images(imgs, (0.5, 0.5, 0.5), (0.5, 0.5, 0.5))
    assert got.dtype == np.float32
    x = rng.normal(size=(64,)).astype(np.float32)
    np.testing.assert_array_equal(rt.f32_to_bf16_bits(x),
                                  rt.f32_to_bf16_bits(x))


def test_prefetch_loader_order_and_transform(rng):
    batches = [rng.normal(size=(4, 4)).astype(np.float32)
               for _ in range(5)]
    loader = rt.PrefetchLoader(batches, transform=lambda b: b * 2,
                               device_put=False)
    got = list(loader)
    assert len(got) == 5
    for src, out in zip(batches, got):
        np.testing.assert_allclose(out, src * 2)


def test_prefetch_loader_device_put(rng):
    import jax
    batches = [{"x": rng.normal(size=(2, 2)).astype(np.float32)}
               for _ in range(3)]
    got = list(rt.PrefetchLoader(batches, prefetch=2))
    assert len(got) == 3
    assert isinstance(got[0]["x"], jax.Array)


def test_prefetch_loader_propagates_errors():
    def gen():
        yield np.zeros((2,))
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        list(rt.PrefetchLoader(gen(), device_put=False))


def test_bf16_nan_preserved(rng):
    import jax.numpy as jnp
    x = np.array([np.nan, -np.nan, np.inf, -np.inf, 1.5], np.float32)
    x[0] = np.frombuffer(np.uint32(0x7FFFFFFF).tobytes(), np.float32)[0]
    bits = rt.f32_to_bf16_bits(x)
    back = rt.bf16_bits_to_f32(bits)
    assert np.isnan(back[0]) and np.isnan(back[1])
    assert np.isposinf(back[2]) and np.isneginf(back[3])
    assert back[4] == 1.5


def test_bf16_nan_preserved_fallback(rng, monkeypatch):
    monkeypatch.setattr(rt, "_LIB", None)
    x = np.array([np.nan, 2.0], np.float32)
    x[0] = np.frombuffer(np.uint32(0x7FFFFFFF).tobytes(), np.float32)[0]
    back = rt.bf16_bits_to_f32(rt.f32_to_bf16_bits(x))
    assert np.isnan(back[0]) and back[1] == 2.0


def test_prefetch_loader_early_stop_no_leak(rng):
    import threading
    n_before = threading.active_count()
    src = (np.zeros((2,)) for _ in range(100))
    for batch in rt.PrefetchLoader(src, device_put=False, prefetch=1):
        break  # early exit must unblock + reap the worker
    import time
    deadline = time.time() + 5
    while threading.active_count() > n_before and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= n_before
