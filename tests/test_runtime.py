"""C++ host runtime tests — flatten/unflatten parity (≙ tests around
``apex_C`` in ``tests/L0/run_fp16util``), normalize parity vs numpy, bf16
round-trip vs jax, PrefetchLoader ordering/overlap, numpy fallback."""

import numpy as np
import pytest

import apex1_tpu.runtime as rt


def test_native_library_builds():
    assert rt.native_available(), "g++ build of _runtime.cpp failed"


def test_flatten_unflatten_roundtrip(rng):
    arrays = [rng.normal(size=(4, 5)).astype(np.float32),
              rng.integers(0, 100, (7,)).astype(np.int32),
              rng.normal(size=(2, 3, 8)).astype(np.float64),
              np.asarray(3.5, np.float32)]
    flat = rt.flatten(arrays)
    assert flat.dtype == np.uint8
    assert flat.nbytes == sum(a.nbytes for a in arrays)
    outs = rt.unflatten(flat, [(a.shape, a.dtype) for a in arrays])
    for a, b in zip(arrays, outs):
        np.testing.assert_array_equal(a, b)


def test_unflatten_size_mismatch(rng):
    flat = rt.flatten([np.zeros((4,), np.float32)])
    with pytest.raises(ValueError):
        rt.unflatten(flat, [((5,), np.float32)])


def test_normalize_matches_numpy(rng):
    imgs = rng.integers(0, 256, (4, 16, 16, 3)).astype(np.uint8)
    mean, std = (0.485, 0.456, 0.406), (0.229, 0.224, 0.225)
    got = rt.normalize_images(imgs, mean, std)
    want = ((imgs.astype(np.float32) / 255.0
             - np.asarray(mean, np.float32))
            / np.asarray(std, np.float32))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_bf16_bits_match_jax(rng):
    import jax.numpy as jnp
    x = rng.normal(size=(1024,)).astype(np.float32) * 100
    bits = rt.f32_to_bf16_bits(x)
    want = np.asarray(jnp.asarray(x).astype(jnp.bfloat16)).view(np.uint16)
    np.testing.assert_array_equal(bits, want)
    back = rt.bf16_bits_to_f32(bits)
    np.testing.assert_array_equal(
        back, np.asarray(jnp.asarray(x).astype(jnp.bfloat16),
                         dtype=np.float32))


def test_numpy_fallback_paths(rng, monkeypatch):
    monkeypatch.setattr(rt, "_LIB", None)
    arrays = [rng.normal(size=(3, 3)).astype(np.float32),
              rng.integers(0, 9, (4,)).astype(np.int64)]
    outs = rt.unflatten(rt.flatten(arrays),
                        [(a.shape, a.dtype) for a in arrays])
    for a, b in zip(arrays, outs):
        np.testing.assert_array_equal(a, b)
    imgs = rng.integers(0, 256, (2, 4, 4, 3)).astype(np.uint8)
    got = rt.normalize_images(imgs, (0.5, 0.5, 0.5), (0.5, 0.5, 0.5))
    assert got.dtype == np.float32
    x = rng.normal(size=(64,)).astype(np.float32)
    np.testing.assert_array_equal(rt.f32_to_bf16_bits(x),
                                  rt.f32_to_bf16_bits(x))


def test_prefetch_loader_order_and_transform(rng):
    batches = [rng.normal(size=(4, 4)).astype(np.float32)
               for _ in range(5)]
    loader = rt.PrefetchLoader(batches, transform=lambda b: b * 2,
                               device_put=False)
    got = list(loader)
    assert len(got) == 5
    for src, out in zip(batches, got):
        np.testing.assert_allclose(out, src * 2)


def test_prefetch_loader_device_put(rng):
    import jax
    batches = [{"x": rng.normal(size=(2, 2)).astype(np.float32)}
               for _ in range(3)]
    got = list(rt.PrefetchLoader(batches, prefetch=2))
    assert len(got) == 3
    assert isinstance(got[0]["x"], jax.Array)


def test_prefetch_loader_propagates_errors():
    def gen():
        yield np.zeros((2,))
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        list(rt.PrefetchLoader(gen(), device_put=False))


def test_bf16_nan_preserved(rng):
    import jax.numpy as jnp
    x = np.array([np.nan, -np.nan, np.inf, -np.inf, 1.5], np.float32)
    x[0] = np.frombuffer(np.uint32(0x7FFFFFFF).tobytes(), np.float32)[0]
    bits = rt.f32_to_bf16_bits(x)
    back = rt.bf16_bits_to_f32(bits)
    assert np.isnan(back[0]) and np.isnan(back[1])
    assert np.isposinf(back[2]) and np.isneginf(back[3])
    assert back[4] == 1.5


def test_bf16_nan_preserved_fallback(rng, monkeypatch):
    monkeypatch.setattr(rt, "_LIB", None)
    x = np.array([np.nan, 2.0], np.float32)
    x[0] = np.frombuffer(np.uint32(0x7FFFFFFF).tobytes(), np.float32)[0]
    back = rt.bf16_bits_to_f32(rt.f32_to_bf16_bits(x))
    assert np.isnan(back[0]) and back[1] == 2.0


def test_prefetch_loader_early_stop_no_leak(rng):
    import threading
    n_before = threading.active_count()
    src = (np.zeros((2,)) for _ in range(100))
    for batch in rt.PrefetchLoader(src, device_put=False, prefetch=1):
        break  # early exit must unblock + reap the worker
    import time
    deadline = time.time() + 5
    while threading.active_count() > n_before and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= n_before


# ---------------------------------------------------------------------------
# TokenDataset — native mmap loader vs NumPy fallback
# ---------------------------------------------------------------------------

def _token_file(tmp_path, n_tokens=997, dtype=np.uint16, name="toks.bin"):
    rng = np.random.default_rng(7)
    toks = rng.integers(0, np.iinfo(dtype).max, n_tokens).astype(dtype)
    path = str(tmp_path / name)
    rt.write_token_file(path, toks)
    return path, toks


@pytest.mark.parametrize("dtype", [np.uint16, np.int32])
@pytest.mark.parametrize("shuffle", [False, True])
def test_token_dataset_native_vs_numpy(tmp_path, monkeypatch, dtype,
                                       shuffle):
    """The native loader and the NumPy fallback must produce bit-identical
    batches (same splitmix64 + cycle-walk permutation)."""
    path, _ = _token_file(tmp_path, dtype=dtype)
    kw = dict(seq_len=16, batch_size=4, dtype=dtype, seed=3,
              shuffle=shuffle)
    had_lib = rt._LIB is not None
    with rt.TokenDataset(path, **kw) as native:
        batches_native = [native.batch_at(s) for s in range(40)]
        # if the library built, the native loader MUST have engaged —
        # otherwise this test would compare NumPy against NumPy
        assert native.native == had_lib
    monkeypatch.setattr(rt, "_LIB", None)
    with rt.TokenDataset(path, **kw) as fallback:
        assert not fallback.native
        for s in range(40):
            np.testing.assert_array_equal(batches_native[s],
                                          fallback.batch_at(s))


def test_token_dataset_epoch_is_permutation(tmp_path):
    """One epoch visits every sequence exactly once (exact shuffle, not
    sampling-with-replacement)."""
    path, toks = _token_file(tmp_path, n_tokens=41 * 8)
    with rt.TokenDataset(path, seq_len=8, batch_size=1, seed=11,
                         shuffle=True) as ds:
        assert ds.num_sequences == 41
        rows = [tuple(ds.batch_at(s)[0]) for s in range(41)]
        expect = {tuple(toks[i * 8:(i + 1) * 8].astype(np.int32))
                  for i in range(41)}
        assert set(rows) == expect and len(rows) == len(expect)
        # second epoch: same coverage, different order
        rows2 = [tuple(ds.batch_at(41 + s)[0]) for s in range(41)]
        assert set(rows2) == expect and rows2 != rows


def test_token_dataset_resume_and_iter(tmp_path):
    """batch_at is pure in (file, seed, step): resuming from a step
    reproduces the stream — the checkpoint story needs only the counter."""
    path, toks = _token_file(tmp_path)
    with rt.TokenDataset(path, seq_len=16, batch_size=4, seed=5) as ds:
        direct = [ds.batch_at(s) for s in range(10)]
        it = ds.iter_from(6)
        np.testing.assert_array_equal(next(it), direct[6])
        np.testing.assert_array_equal(next(it), direct[7])
        # unshuffled dataset reads sequences in file order
    with rt.TokenDataset(path, seq_len=16, batch_size=2,
                         shuffle=False) as seq:
        np.testing.assert_array_equal(
            seq.batch_at(0)[0], toks[:16].astype(np.int32))
        # step 1, row 0 -> global sequence index step*batch = 2
        np.testing.assert_array_equal(
            seq.batch_at(1)[0], toks[32:48].astype(np.int32))


def test_token_dataset_closed_and_seed_wrap(tmp_path):
    path, _ = _token_file(tmp_path)
    ds = rt.TokenDataset(path, seq_len=16, batch_size=2, seed=-1)
    b0 = ds.batch_at(0)
    # -1 wraps to 2^64-1 identically on native and NumPy paths
    with rt.TokenDataset(path, seq_len=16, batch_size=2,
                         seed=(1 << 64) - 1) as same:
        np.testing.assert_array_equal(b0, same.batch_at(0))
    ds.close()
    ds.close()  # idempotent
    with pytest.raises(RuntimeError, match="closed"):
        ds.batch_at(0)


def test_pack_documents_long_doc_positions():
    """Docs longer than seq_len split into chunks; positions continue by
    default (RoPE) and restart with restart_chunk_positions (learned PE,
    which would otherwise silently clamp the table gather)."""
    doc = np.arange(20, dtype=np.int32)
    t, s, p = rt.pack_documents([doc], seq_len=8)
    assert t.shape[0] >= 2 and p.max() == 19  # continues within the doc
    t2, s2, p2 = rt.pack_documents([doc], seq_len=8,
                                   restart_chunk_positions=True)
    assert p2.max() <= 7                      # always table-safe
    # chunks are distinct segments either way (no cross-chunk attention)
    row0 = s[0][s[0] >= 0]
    assert len(np.unique(row0)) >= 1


def test_pack_documents_native_matches_python():
    """The native threaded fill (apex1_pack_fill) and the NumPy fallback
    must be byte-identical across ragged docs, long-doc chunking, and
    both position modes."""
    if not rt.native_available():
        pytest.skip("native runtime not built — nothing to compare")
    rng = np.random.default_rng(11)
    docs = [rng.integers(1, 500, int(n)).astype(np.int32)
            for n in rng.integers(1, 70, 300)]
    for restart in (False, True):
        native = rt.pack_documents(docs, 24, pad_id=7,
                                   restart_chunk_positions=restart)
        lib, rt._LIB = rt._LIB, None
        try:
            fallback = rt.pack_documents(docs, 24, pad_id=7,
                                         restart_chunk_positions=restart)
        finally:
            rt._LIB = lib
        for a, b in zip(native, fallback):
            np.testing.assert_array_equal(a, b)
    # total token conservation + pad marking
    t, s, p = rt.pack_documents(docs, 24)
    assert int((s >= 0).sum()) == sum(len(d) for d in docs)
    assert (t[s < 0] == 0).all()
    # must raise BEFORE reaching the native planner (whose chunk loop
    # cannot advance at seq_len <= 0)
    with pytest.raises(ValueError, match="seq_len"):
        rt.pack_documents(docs, 0)


def test_sharded_token_dataset(tmp_path):
    """Global exact shuffle over concatenated shards: one epoch covers
    every sequence of every shard exactly once; single-file dataset over
    the concatenation produces the SAME batches (same permutation)."""
    rng = np.random.default_rng(5)
    shards, all_toks = [], []
    for i, n in enumerate((7, 13, 5)):  # sequences per shard (seq_len 8)
        toks = rng.integers(0, 60000, n * 8).astype(np.uint16)
        p = str(tmp_path / f"shard{i}.bin")
        rt.write_token_file(p, toks)
        shards.append(p)
        all_toks.append(toks)
    concat = str(tmp_path / "concat.bin")
    rt.write_token_file(concat, np.concatenate(all_toks))

    with rt.ShardedTokenDataset(shards, seq_len=8, batch_size=5,
                                seed=9) as ds, \
         rt.TokenDataset(concat, seq_len=8, batch_size=5, seed=9) as ref:
        assert ds.num_sequences == ref.num_sequences == 25
        assert ds.steps_per_epoch() == 5
        for step in range(8):  # crosses the epoch boundary
            np.testing.assert_array_equal(ds.batch_at(step),
                                          ref.batch_at(step))
        # epoch coverage: rows of one epoch == all sequences
        rows = {tuple(ds.batch_at(s)[r]) for s in range(5)
                for r in range(5)}
        expect = {tuple(t[i * 8:(i + 1) * 8].astype(np.int32))
                  for t in all_toks for i in range(len(t) // 8)}
        assert rows == expect


def test_token_dataset_fetch(tmp_path):
    path, toks = _token_file(tmp_path)
    with rt.TokenDataset(path, seq_len=16, batch_size=2) as ds:
        np.testing.assert_array_equal(ds.fetch(3),
                                      toks[48:64].astype(np.int32))
        with pytest.raises(IndexError):
            ds.fetch(ds.num_sequences)
