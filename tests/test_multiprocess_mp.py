"""Cross-PROCESS tensor/pipeline parallelism — VERDICT r4 Missing #4.

Every tp/pp/ep/cp collective elsewhere in the suite runs single-process
on 8 virtual devices; only pure-dp psum ever crossed a real process
boundary (`test_fault_recovery`). Real multi-controller JAX (one
process per host, per-process device subsets) exercises different
runtime paths — global-array assembly from per-process shards,
cross-host ppermute, collective orbax barriers over SHARDED state — the
paths a TPU pod hits (SURVEY.md §4.2.4's distributed-tests tier).

Harness: `parallel.multiproc.launch` spawns 2 CPU processes x 1 device
joined by `jax.distributed.initialize`; each child runs the distributed
program AND the single-device gold math (same seed), asserting parity
shard-by-shard in-process, so the host test only checks exit codes.
"""

import os
import pathlib
import socket
import textwrap

import pytest

pytestmark = pytest.mark.slow  # full run via check_all.sh --all

_REPO = str(pathlib.Path(__file__).resolve().parents[1])


def _free_port() -> int:
    """OS-assigned free port for the jax.distributed coordinator — a
    hardcoded port collides with concurrent suite runs / TIME_WAIT
    leftovers from a crashed child (review r5). The tiny bind-release
    race is acceptable for a test harness."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(tmp_path, body, args=()):
    from apex1_tpu.parallel import multiproc

    script = tmp_path / "child.py"
    script.write_text(_PRELUDE + textwrap.dedent(body))
    return multiproc.launch(
        str(script), [str(a) for a in args], num_processes=2,
        cpu_devices_per_process=1, coordinator_port=_free_port(),
        env={"PYTHONPATH": _REPO + os.pathsep
             + os.environ.get("PYTHONPATH", "")})


_PRELUDE = textwrap.dedent("""
    import sys
    import jax
    from apex1_tpu.parallel import multiproc
    multiproc.init_from_env()
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 2, jax.devices()
    assert len(jax.local_devices()) == 1  # the multi-controller property

    def mk(mesh, full, spec):
        # global array assembled from PER-PROCESS shards — the exact
        # multi-controller path single-process tests cannot reach
        return jax.make_array_from_callback(
            full.shape, NamedSharding(mesh, spec), lambda idx: full[idx])

    def check_shards(got, full_gold, name, tol=2e-5):
        for s in got.addressable_shards:
            np.testing.assert_allclose(
                np.asarray(s.data), full_gold[s.index],
                rtol=tol, atol=tol, err_msg=name)
""")


_TP_CHILD = """
from apex1_tpu.transformer.tensor_parallel import layers as tpl
from apex1_tpu.checkpoint import CheckpointManager

ckdir = sys.argv[1]
mesh = Mesh(np.array(jax.devices()), ("tp",))
rng = np.random.default_rng(0)
B, H, F = 4, 8, 16
xf = rng.normal(size=(B, H)).astype(np.float32)
w1f = (rng.normal(size=(H, F)) * 0.1).astype(np.float32)
b1f = (rng.normal(size=(F,)) * 0.1).astype(np.float32)
w2f = (rng.normal(size=(F, H)) * 0.1).astype(np.float32)

x = mk(mesh, xf, P())
w1 = mk(mesh, w1f, P(None, "tp"))
b1 = mk(mesh, b1f, P("tp"))
w2 = mk(mesh, w2f, P("tp", None))

def local(x, w1, b1, w2):
    def loss_fn(w1, b1, w2):
        h = tpl.column_parallel_linear(x, w1, b1)
        h = jax.nn.gelu(h)
        y = tpl.row_parallel_linear(h, w2)
        return jnp.sum(y.astype(jnp.float32) ** 2)
    return jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(w1, b1, w2)

step = jax.jit(jax.shard_map(
    local, mesh=mesh,
    in_specs=(P(), P(None, "tp"), P("tp"), P("tp", None)),
    out_specs=(P(), (P(None, "tp"), P("tp"), P("tp", None))),
    check_vma=False))
loss, (gw1, gb1, gw2) = step(x, w1, b1, w2)

def gold_loss(w1, b1, w2):
    h = jax.nn.gelu(xf @ w1 + b1)
    return jnp.sum((h @ w2).astype(jnp.float32) ** 2)

gl, (ggw1, ggb1, ggw2) = jax.value_and_grad(
    gold_loss, argnums=(0, 1, 2))(jnp.asarray(w1f), jnp.asarray(b1f),
                                  jnp.asarray(w2f))
np.testing.assert_allclose(float(loss), float(gl), rtol=2e-5)
check_shards(gw1, np.asarray(ggw1), "gw1")
check_shards(gb1, np.asarray(ggb1), "gb1")
check_shards(gw2, np.asarray(ggw2), "gw2")

# cross-process checkpoint of TP-SHARDED state: orbax's collective save
# barriers + shard reassembly on restore (the dp fault test only ever
# checkpointed replicated state)
state = {"w1": gw1, "b1": gb1, "w2": gw2}
specs = {"w1": P(None, "tp"), "b1": P("tp"), "w2": P("tp", None)}
with CheckpointManager(ckdir) as mgr:
    mgr.save(0, state, force=True)
    mgr.wait_until_finished()
    back = mgr.restore(state, mesh=mesh, spec_tree=specs)
for k in sorted(state):
    # BIT-exact vs the saved shards (gold comparison above already
    # anchored the values; restore must not perturb them at all)
    for sa, sb in zip(back[k].addressable_shards,
                      state[k].addressable_shards):
        assert sa.index == sb.index
        np.testing.assert_array_equal(np.asarray(sa.data),
                                      np.asarray(sb.data), err_msg=k)
print(f"rank {jax.process_index()} tp=2 parity + sharded ckpt OK",
      flush=True)
"""


_PP_CHILD = """
from apex1_tpu.transformer.pipeline_parallel.schedules import pipeline_apply

mesh = Mesh(np.array(jax.devices()), ("pp",))
rng = np.random.default_rng(1)
V, S, hid, M, mb = 1, 2, 8, 4, 2
pf = (rng.normal(size=(V, S, hid, hid)) * 0.5).astype(np.float32)
mbf = rng.normal(size=(M, mb, hid)).astype(np.float32)

params = mk(mesh, pf, P(None, "pp"))
mbs = mk(mesh, mbf, P())

def stage_fn(p, x):
    return jnp.tanh(x @ p)

def fwd(chunk_params, mbs):
    def local(chunk_params, mbs):
        local_p = chunk_params[:, 0]          # (V=1, hid, hid)
        outs = pipeline_apply(stage_fn, local_p, mbs, num_chunks=1)
        return jnp.sum(outs.astype(jnp.float32))
    return jax.shard_map(local, mesh=mesh,
                         in_specs=(P(None, "pp"), P()), out_specs=P(),
                         check_vma=False)(chunk_params, mbs)

# value + grad: the backward scan's ppermute transpose (residual ring)
# crosses the real process boundary here
loss, grad = jax.jit(jax.value_and_grad(fwd))(params, mbs)

def gold(pfull, mbs_full):
    y = mbs_full
    for s in range(S):
        y = jnp.tanh(y @ pfull[0, s])
    return jnp.sum(y.astype(jnp.float32))

gl, gg = jax.value_and_grad(gold)(jnp.asarray(pf), jnp.asarray(mbf))
np.testing.assert_allclose(float(loss), float(gl), rtol=2e-5, atol=2e-5)
check_shards(grad, np.asarray(gg), "pipeline param grad")
print(f"rank {jax.process_index()} pp=2 parity OK", flush=True)
"""


@pytest.mark.slow
def test_cross_process_tp2_parity_and_sharded_checkpoint(tmp_path):
    rc = _launch(tmp_path, _TP_CHILD, [tmp_path / "ckpts"])
    assert rc == 0


@pytest.mark.slow
def test_cross_process_pp2_pipeline_parity(tmp_path):
    rc = _launch(tmp_path, _PP_CHILD)
    assert rc == 0
