"""Cross-PROCESS tensor/pipeline parallelism — VERDICT r4 Missing #4.

Every tp/pp/ep/cp collective elsewhere in the suite runs single-process
on 8 virtual devices; only pure-dp psum ever crossed a real process
boundary (`test_fault_recovery`). Real multi-controller JAX (one
process per host, per-process device subsets) exercises different
runtime paths — global-array assembly from per-process shards,
cross-host ppermute, collective orbax barriers over SHARDED state — the
paths a TPU pod hits (SURVEY.md §4.2.4's distributed-tests tier).

Harness: `parallel.multiproc.launch` spawns 2 CPU processes x 1 device
joined by `jax.distributed.initialize`; each child runs the distributed
program AND the single-device gold math (same seed), asserting parity
shard-by-shard in-process, so the host test only checks exit codes.
"""

import os
import pathlib
import socket
import textwrap

import pytest

pytestmark = pytest.mark.slow  # full run via check_all.sh --all

_REPO = str(pathlib.Path(__file__).resolve().parents[1])


def _free_port() -> int:
    """OS-assigned free port for the jax.distributed coordinator — a
    hardcoded port collides with concurrent suite runs / TIME_WAIT
    leftovers from a crashed child (review r5). The tiny bind-release
    race is acceptable for a test harness."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(tmp_path, body, args=()):
    from apex1_tpu.parallel import multiproc

    script = tmp_path / "child.py"
    script.write_text(_PRELUDE + textwrap.dedent(body))
    from apex1_tpu.testing import child_cache_env

    return multiproc.launch(
        str(script), [str(a) for a in args], num_processes=2,
        cpu_devices_per_process=1, coordinator_port=_free_port(),
        env={"PYTHONPATH": _REPO + os.pathsep
             + os.environ.get("PYTHONPATH", ""),
             # children are fresh processes each test: share the suite's
             # persistent compile cache or every run recompiles cold
             **child_cache_env()})


_PRELUDE = textwrap.dedent("""
    import sys
    import jax
    from apex1_tpu.parallel import multiproc
    multiproc.init_from_env()
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 2, jax.devices()
    assert len(jax.local_devices()) == 1  # the multi-controller property

    def mk(mesh, full, spec):
        # global array assembled from PER-PROCESS shards — the exact
        # multi-controller path single-process tests cannot reach
        return jax.make_array_from_callback(
            full.shape, NamedSharding(mesh, spec), lambda idx: full[idx])

    def check_shards(got, full_gold, name, tol=2e-5):
        for s in got.addressable_shards:
            np.testing.assert_allclose(
                np.asarray(s.data), full_gold[s.index],
                rtol=tol, atol=tol, err_msg=name)
""")


_TP_CHILD = """
from apex1_tpu.transformer.tensor_parallel import layers as tpl
from apex1_tpu.checkpoint import CheckpointManager

ckdir = sys.argv[1]
mesh = Mesh(np.array(jax.devices()), ("tp",))
rng = np.random.default_rng(0)
B, H, F = 4, 8, 16
xf = rng.normal(size=(B, H)).astype(np.float32)
w1f = (rng.normal(size=(H, F)) * 0.1).astype(np.float32)
b1f = (rng.normal(size=(F,)) * 0.1).astype(np.float32)
w2f = (rng.normal(size=(F, H)) * 0.1).astype(np.float32)

x = mk(mesh, xf, P())
w1 = mk(mesh, w1f, P(None, "tp"))
b1 = mk(mesh, b1f, P("tp"))
w2 = mk(mesh, w2f, P("tp", None))

def local(x, w1, b1, w2):
    def loss_fn(w1, b1, w2):
        h = tpl.column_parallel_linear(x, w1, b1)
        h = jax.nn.gelu(h)
        y = tpl.row_parallel_linear(h, w2)
        return jnp.sum(y.astype(jnp.float32) ** 2)
    return jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(w1, b1, w2)

step = jax.jit(jax.shard_map(
    local, mesh=mesh,
    in_specs=(P(), P(None, "tp"), P("tp"), P("tp", None)),
    out_specs=(P(), (P(None, "tp"), P("tp"), P("tp", None))),
    check_vma=False))
loss, (gw1, gb1, gw2) = step(x, w1, b1, w2)

def gold_loss(w1, b1, w2):
    h = jax.nn.gelu(xf @ w1 + b1)
    return jnp.sum((h @ w2).astype(jnp.float32) ** 2)

gl, (ggw1, ggb1, ggw2) = jax.value_and_grad(
    gold_loss, argnums=(0, 1, 2))(jnp.asarray(w1f), jnp.asarray(b1f),
                                  jnp.asarray(w2f))
np.testing.assert_allclose(float(loss), float(gl), rtol=2e-5)
check_shards(gw1, np.asarray(ggw1), "gw1")
check_shards(gb1, np.asarray(ggb1), "gb1")
check_shards(gw2, np.asarray(ggw2), "gw2")

# cross-process checkpoint of TP-SHARDED state: orbax's collective save
# barriers + shard reassembly on restore (the dp fault test only ever
# checkpointed replicated state)
state = {"w1": gw1, "b1": gb1, "w2": gw2}
specs = {"w1": P(None, "tp"), "b1": P("tp"), "w2": P("tp", None)}
with CheckpointManager(ckdir) as mgr:
    mgr.save(0, state, force=True)
    mgr.wait_until_finished()
    back = mgr.restore(state, mesh=mesh, spec_tree=specs)
for k in sorted(state):
    # BIT-exact vs the saved shards (gold comparison above already
    # anchored the values; restore must not perturb them at all)
    for sa, sb in zip(back[k].addressable_shards,
                      state[k].addressable_shards):
        assert sa.index == sb.index
        np.testing.assert_array_equal(np.asarray(sa.data),
                                      np.asarray(sb.data), err_msg=k)
print(f"rank {jax.process_index()} tp=2 parity + sharded ckpt OK",
      flush=True)
"""


_PP_CHILD = """
from apex1_tpu.transformer.pipeline_parallel.schedules import pipeline_apply

mesh = Mesh(np.array(jax.devices()), ("pp",))
rng = np.random.default_rng(1)
V, S, hid, M, mb = 1, 2, 8, 4, 2
pf = (rng.normal(size=(V, S, hid, hid)) * 0.5).astype(np.float32)
mbf = rng.normal(size=(M, mb, hid)).astype(np.float32)

params = mk(mesh, pf, P(None, "pp"))
mbs = mk(mesh, mbf, P())

def stage_fn(p, x):
    return jnp.tanh(x @ p)

def fwd(chunk_params, mbs):
    def local(chunk_params, mbs):
        local_p = chunk_params[:, 0]          # (V=1, hid, hid)
        outs = pipeline_apply(stage_fn, local_p, mbs, num_chunks=1)
        return jnp.sum(outs.astype(jnp.float32))
    return jax.shard_map(local, mesh=mesh,
                         in_specs=(P(None, "pp"), P()), out_specs=P(),
                         check_vma=False)(chunk_params, mbs)

# value + grad: the backward scan's ppermute transpose (residual ring)
# crosses the real process boundary here
loss, grad = jax.jit(jax.value_and_grad(fwd))(params, mbs)

def gold(pfull, mbs_full):
    y = mbs_full
    for s in range(S):
        y = jnp.tanh(y @ pfull[0, s])
    return jnp.sum(y.astype(jnp.float32))

gl, gg = jax.value_and_grad(gold)(jnp.asarray(pf), jnp.asarray(mbf))
np.testing.assert_allclose(float(loss), float(gl), rtol=2e-5, atol=2e-5)
check_shards(grad, np.asarray(gg), "pipeline param grad")
print(f"rank {jax.process_index()} pp=2 parity OK", flush=True)
"""


_EP_CHILD = """
from apex1_tpu.transformer import moe as moe_lib

mesh = Mesh(np.array(jax.devices()), ("ep",))
cfg = moe_lib.MoEConfig(num_experts=2, top_k=1, capacity_factor=32.0,
                        hidden_size=8, ffn_size=16)
rng = np.random.default_rng(2)
T, H, F = 8, 8, 16
xf = rng.normal(size=(T, H)).astype(np.float32)
wgf = rng.normal(size=(H, 2)).astype(np.float32)
w1f = (rng.normal(size=(2, H, F)) * 0.1).astype(np.float32)
w2f = (rng.normal(size=(2, F, H)) * 0.1).astype(np.float32)

x = mk(mesh, xf, P("ep"))
wg = mk(mesh, wgf, P())
w1 = mk(mesh, w1f, P("ep"))
w2 = mk(mesh, w2f, P("ep"))

def local(x, wg, w1, w2):
    ep = jax.lax.axis_size("ep")
    def loss_fn(wg, w1, w2):
        # both all_to_alls (dispatch + return) cross the REAL process
        # boundary here; so do their transposes in the backward pass.
        # stats_axes="ep" psums the router stats, making aux exactly
        # the global-router aux on every shard.
        y, aux = moe_lib.moe_shard_map_apply(x, wg, w1, w2, cfg,
                                             stats_axes="ep")
        # LOCAL partial loss — the docs/parallel.md "inside-grad"
        # convention: differentiating a psum'd loss inside shard_map
        # scales every grad by the axis size (psum transposes to psum;
        # observed here as an exactly-2x gwg before the fix). aux is
        # replicated, so aux/ep makes the psum-of-partials below equal
        # the global loss with aux counted once.
        return jnp.sum(y.astype(jnp.float32) ** 2) + aux / ep
    lval, (gwg, gw1, gw2) = jax.value_and_grad(
        loss_fn, argnums=(0, 1, 2))(wg, w1, w2)
    loss = jax.lax.psum(lval, "ep")
    # replicated-in wg: each shard's backward holds only the paths
    # through ITS router invocation (its local tokens). Sharded w1/w2
    # grads are already complete — remote tokens' contributions arrive
    # through the all_to_all transpose.
    gwg = jax.lax.psum(gwg, "ep")
    return loss, (gwg, gw1, gw2)

step = jax.jit(jax.shard_map(
    local, mesh=mesh,
    in_specs=(P("ep"), P(), P("ep"), P("ep")),
    out_specs=(P(), (P(), P("ep"), P("ep"))),
    check_vma=False))
loss, (gwg, gw1, gw2) = step(x, wg, w1, w2)
# (ample capacity => no drops can differ between local and global routing)

def gold_loss(wg, w1, w2):
    dispatch, combine, aux = moe_lib.router(jnp.asarray(xf), wg, cfg)
    xe = jnp.einsum("tec,th->ech", dispatch, jnp.asarray(xf))
    h = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", xe, w1))
    ye = jnp.einsum("ecf,efh->ech", h, w2)
    y = jnp.einsum("tec,ech->th", combine, ye)
    return jnp.sum(y.astype(jnp.float32) ** 2) + aux

gl, (ggwg, ggw1, ggw2) = jax.value_and_grad(
    gold_loss, argnums=(0, 1, 2))(jnp.asarray(wgf), jnp.asarray(w1f),
                                  jnp.asarray(w2f))
np.testing.assert_allclose(float(loss), float(gl), rtol=2e-4, atol=2e-5)
check_shards(gwg, np.asarray(ggwg), "gwg", tol=2e-4)
check_shards(gw1, np.asarray(ggw1), "gw1", tol=2e-4)
check_shards(gw2, np.asarray(ggw2), "gw2", tol=2e-4)
print(f"rank {jax.process_index()} ep=2 a2a parity OK", flush=True)
"""


@pytest.mark.slow
def test_cross_process_tp2_parity_and_sharded_checkpoint(tmp_path):
    rc = _launch(tmp_path, _TP_CHILD, [tmp_path / "ckpts"])
    assert rc == 0


@pytest.mark.slow
def test_cross_process_pp2_pipeline_parity(tmp_path):
    rc = _launch(tmp_path, _PP_CHILD)
    assert rc == 0


@pytest.mark.slow
def test_cross_process_ep2_all_to_all_parity(tmp_path):
    """Expert-parallel all_to_all (+ its backward transpose) across two
    REAL processes — the last collective family whose only prior
    coverage was single-process virtual devices (VERDICT r4 Missing #4
    named tp/pp; this closes ep the same way)."""
    rc = _launch(tmp_path, _EP_CHILD)
    assert rc == 0
