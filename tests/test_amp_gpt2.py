"""M1 end-to-end slice — BASELINE config 1 ("GPT-2 125M, amp O1 + Adam"),
scaled down: the cross-product loss-parity methodology of
``tests/L1/cross_product/run.sh`` + ``compare.py``: train the same tiny
GPT-2 from identical init under several policies and assert loss curves
agree; fp16 dynamic scaling must recover from an injected overflow."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu import amp as amp_lib
from apex1_tpu.models.gpt2 import GPT2, GPT2Config, gpt2_loss_fn
from apex1_tpu.optim import fused_adam


def make_setup(opt_level, **overrides):
    cfg = GPT2Config.tiny(policy=_policy(opt_level, **overrides))
    model = GPT2(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(7), (4, 32), 0,
                                cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    a = amp_lib.Amp(tx=fused_adam(1e-3), opt_level=opt_level, **overrides)
    state = a.init(params)
    step = jax.jit(a.make_train_step(gpt2_loss_fn(model)))
    return a, state, step, tokens


def _policy(opt_level, **overrides):
    from apex1_tpu.core.policy import get_policy
    return get_policy(opt_level, **overrides)


def run(steps, state, step_fn, tokens):
    losses = []
    for _ in range(steps):
        state, m = step_fn(state, tokens)
        losses.append(float(m["loss"]))
    return state, losses, m


class TestEndToEnd:
    @pytest.mark.slow  # multi-step training loop; step math covered by parity tests
    def test_o0_trains(self):
        _, state, step, tokens = make_setup("O0")
        state, losses, m = run(8, state, step, tokens)
        assert losses[-1] < losses[0] - 0.3, losses
        assert bool(m["grads_finite"])

    def test_o1_matches_o0(self):
        # ≙ L1 cross-product: bf16 O1 loss curve tracks fp32 O0
        _, s0, f0, tokens = make_setup("O0")
        _, s1, f1, _ = make_setup("O1")
        _, l0, _ = run(8, s0, f0, tokens)
        _, l1, _ = run(8, s1, f1, tokens)
        np.testing.assert_allclose(l0, l1, rtol=0.05)

    def test_o2_trains(self):
        _, state, step, tokens = make_setup("O2")
        state, losses, _ = run(8, state, step, tokens)
        assert losses[-1] < losses[0] - 0.3

    def test_o1_fp16_dynamic_scaling(self):
        a, state, step, tokens = make_setup("O1_fp16")
        assert float(state.loss_scale.scale) == 2.0 ** 16
        state, losses, m = run(10, state, step, tokens)
        # may skip during calibration, but must end up training
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]
        assert float(m["loss_scale"]) <= 2.0 ** 16

    def test_fp16_overflow_skips_and_recovers(self):
        a, state, step, tokens = make_setup("O1_fp16")
        # force an overflow by injecting a huge loss-scale
        import dataclasses
        from apex1_tpu.core.loss_scale import LossScaleState
        # near fp32 max so the scaled loss itself overflows to inf
        state = dataclasses.replace(
            state, loss_scale=LossScaleState(
                scale=jnp.float32(2.0 ** 126),
                growth_count=jnp.int32(0),
                overflow_count=jnp.int32(0),
                hysteresis_left=jnp.int32(1)))
        params_before = jax.tree_util.tree_leaves(state.params)[0]
        state, m = step(state, tokens)
        assert not bool(m["grads_finite"])
        params_after = jax.tree_util.tree_leaves(state.params)[0]
        np.testing.assert_array_equal(np.asarray(params_before),
                                      np.asarray(params_after))
        # halved then clamped to max_loss_scale (2^24, reference default)
        assert float(state.loss_scale.scale) == 2.0 ** 24
        assert int(state.loss_scale.overflow_count) == 1

    def test_master_params_fp32_under_o2(self):
        a, state, step, tokens = make_setup("O2")
        for leaf in jax.tree_util.tree_leaves(a.master_params(state)):
            if jnp.issubdtype(leaf.dtype, jnp.floating):
                assert leaf.dtype == jnp.float32
        for leaf in jax.tree_util.tree_leaves(a.model_params(state)):
            if jnp.issubdtype(leaf.dtype, jnp.floating):
                assert leaf.dtype == jnp.bfloat16

    def test_state_dict_roundtrip(self):
        a, state, step, tokens = make_setup("O1_fp16")
        state, _ = step(state, tokens)
        sd = a.state_dict(state)
        restored = a.load_state_dict(state, sd)
        assert float(restored.loss_scale.scale) == float(
            state.loss_scale.scale)

    def test_max_grad_norm(self):
        cfg = GPT2Config.tiny()
        model = GPT2(cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(7), (2, 16), 0,
                                    cfg.vocab_size)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        a = amp_lib.Amp(tx=fused_adam(1e-3), opt_level="O0",
                        max_grad_norm=1e-8)
        state = a.init(params)
        step = jax.jit(a.make_train_step(gpt2_loss_fn(model)))
        before = jax.tree_util.tree_leaves(state.params)[0]
        state, m = step(state, tokens)
        after = jax.tree_util.tree_leaves(state.params)[0]
        # clipped to ~zero grads → params barely move
        assert float(jnp.max(jnp.abs(after - before))) < 1e-3


class TestMultiLossAndForward:
    """≙ ``amp.initialize(num_losses=N)`` + ``cast_model_outputs`` — one
    scaler per loss (independent backoff), O2-style patched forward."""

    def _setup(self):
        cfg = GPT2Config.tiny()
        model = GPT2(cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        return model, tokens, params

    def test_per_loss_scalers_independent(self):
        model, tokens, params = self._setup()
        a = amp_lib.Amp(tx=fused_adam(1e-3), opt_level="O1_fp16",
                        num_losses=2)
        state = a.init(params)
        loss_fn = gpt2_loss_fn(model)

        def exploding(p, t):  # loss 1 always overflows its scaled grads
            return loss_fn(p, t) * 1e38

        step0 = jax.jit(a.make_train_step(loss_fn, loss_id=0))
        step1 = jax.jit(a.make_train_step(exploding, loss_id=1))
        state, m0 = step0(state, tokens)
        state, m1 = step1(state, tokens)
        s0, s1 = state.loss_scale
        assert float(m0["grads_finite"]) == 1.0
        assert float(m1["grads_finite"]) == 0.0
        # scaler 1 backed off; scaler 0 untouched by loss 1's overflow
        assert float(s1.scale) < float(s0.scale)
        assert int(s0.overflow_count) == 0 and int(s1.overflow_count) == 1

    def test_multi_loss_state_dict_roundtrip(self):
        _, tokens, params = self._setup()
        a = amp_lib.Amp(tx=fused_adam(1e-3), opt_level="O1_fp16",
                        num_losses=2)
        state = a.init(params)
        sd = a.state_dict(state)
        assert set(sd) == {"loss_scaler0", "loss_scaler1"}
        restored = a.load_state_dict(state, sd)
        assert float(restored.loss_scale[1].scale) == float(
            state.loss_scale[1].scale)

    def test_make_forward_casts(self):
        model, tokens, params = self._setup()
        a = amp_lib.Amp(tx=fused_adam(1e-3), opt_level="O2",
                        cast_model_outputs=jnp.float32)
        state = a.init(params)

        def forward(p, t):
            return model.apply({"params": p}, t)

        fwd = jax.jit(a.make_forward(forward))
        logits = fwd(state, tokens)
        assert logits.dtype == jnp.float32  # cast_model_outputs
        # prove the param/input casts really happen: a policy-UNAWARE
        # function (dtype follows operands) must see bf16 operands
        a2 = amp_lib.Amp(tx=fused_adam(1e-3), opt_level="O3")
        raw = {"w": jnp.ones((4, 4), jnp.float32)}
        x = jnp.ones((4, 2), jnp.float32)
        out = jax.eval_shape(a2.make_forward(lambda p, x: p["w"] @ x),
                             a2.init(raw).params, x)
        assert out.dtype == jnp.bfloat16


class TestGradAccumulation:
    """accum_steps=k over k microbatches ≡ one step on the concatenated
    batch (mean-loss semantics average the grads either way)."""

    def test_matches_big_batch(self):
        # fp32 + plain SGD so param delta == -lr * grad: the accumulation
        # contract (mean of microbatch grads == big-batch grad) shows up
        # directly, without Adam amplifying near-zero-grad sign noise to
        # +-lr per element
        from apex1_tpu.optim import fused_sgd
        cfg = GPT2Config.tiny()
        model = GPT2(cfg)
        rng = np.random.default_rng(0)
        A, B, S = 4, 2, 16
        tokens = jnp.asarray(
            rng.integers(0, cfg.vocab_size, (A * B, S)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens[:B])["params"]
        a = amp_lib.Amp(tx=fused_sgd(0.1), opt_level="O0")

        big = jax.jit(a.make_train_step(gpt2_loss_fn(model)))
        acc = jax.jit(a.make_train_step(gpt2_loss_fn(model),
                                        accum_steps=A))
        s1, m1 = big(a.init(params), tokens)
        s2, m2 = acc(a.init(params), tokens.reshape(A, B, S))
        np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                                   rtol=1e-6)
        for x, y in zip(jax.tree.leaves(s1.params),
                        jax.tree.leaves(s2.params)):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=1e-4, atol=1e-6)

    def test_fp16_overflow_skips_whole_step(self):
        cfg = GPT2Config.tiny()
        model = GPT2(cfg)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(
            rng.integers(0, cfg.vocab_size, (2, 2, 16)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens[0])["params"]
        a = amp_lib.Amp(tx=fused_adam(1e-3), opt_level="O1_fp16")
        bad = lambda p, t: gpt2_loss_fn(model)(p, t) * 1e38
        step = jax.jit(a.make_train_step(bad, accum_steps=2))
        st = a.init(params)
        st2, m = step(st, tokens)
        assert float(m["grads_finite"]) == 0.0
        for x, y in zip(jax.tree.leaves(st.params),
                        jax.tree.leaves(st2.params)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    def test_aux_shape_stable_across_accum(self):
        cfg = GPT2Config.tiny()
        model = GPT2(cfg)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(
            rng.integers(0, cfg.vocab_size, (2, 2, 16)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens[0])["params"]
        base = gpt2_loss_fn(model)
        loss_aux = lambda p, t: (base(p, t), {"acc": base(p, t) * 0 + 1.0})
        a = amp_lib.Amp(tx=fused_adam(1e-3), opt_level="O0")
        s1, m1 = jax.jit(a.make_train_step(loss_aux, has_aux=True))(
            a.init(params), tokens[0])
        s2, m2 = jax.jit(a.make_train_step(loss_aux, has_aux=True,
                                           accum_steps=2))(
            a.init(params), tokens)
        assert m1["aux"]["acc"].shape == m2["aux"]["acc"].shape == ()
        np.testing.assert_allclose(float(m2["aux"]["acc"]), 1.0)

    def test_grad_dtype_contract_across_accum(self):
        """VERDICT r2 item 8 / ADVICE r1 item 3: the dtype handed to the
        optimizer must not depend on accum_steps. Under O3_fp16 the
        masters are fp16, so grads w.r.t. them are fp16 at accum_steps=1;
        the accumulation path accumulates in fp32 for sum accuracy but
        must cast back before tx.update sees the grads."""
        import optax

        cfg = GPT2Config.tiny()
        model = GPT2(cfg)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(
            rng.integers(0, cfg.vocab_size, (4, 2, 16)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens[0])["params"]
        seen = {}

        def probe_tx(tag):
            def update(grads, st, params=None):
                seen[tag] = {jax.tree_util.keystr(k): g.dtype
                             for k, g in
                             jax.tree_util.tree_leaves_with_path(grads)}
                return jax.tree_util.tree_map(jnp.zeros_like, grads), st
            return optax.GradientTransformation(
                lambda p: optax.EmptyState(), update)

        for tag, accum, batch in (("a1", 1, tokens.reshape(8, 16)),
                                  ("a4", 4, tokens)):
            a = amp_lib.Amp(tx=probe_tx(tag), opt_level="O3_fp16")
            st = a.init(params)
            jax.jit(a.make_train_step(gpt2_loss_fn(model),
                                      accum_steps=accum))(st, batch)
            master_dt = {jax.tree_util.keystr(k): p.dtype
                         for k, p in
                         jax.tree_util.tree_leaves_with_path(st.params)}
            assert seen[tag] == master_dt, f"{tag}: grad dtypes != masters"
        assert seen["a1"] == seen["a4"]


@pytest.mark.slow  # 870s-cap headroom (~10s): packed-data x gpt2-train
# COMPOSITION; halves pinned tier-1 — pack_documents plan/fill units
# (test_runtime) and gpt2 train-step parity (TestEndToEnd);
# check_all --all
def test_gpt2_packed_equals_separate():
    """GPT-2 packed batches (segment ids + per-row learned positions)
    reproduce each document's standalone forward — ≙ fmha cu_seqlens."""
    from apex1_tpu.runtime import pack_documents
    cfg = GPT2Config.tiny()
    model = GPT2(cfg)
    rng = np.random.default_rng(2)
    d1 = rng.integers(0, cfg.vocab_size, 11).astype(np.int32)
    d2 = rng.integers(0, cfg.vocab_size, 8).astype(np.int32)
    tokens, segs, pos = pack_documents([d1, d2], seq_len=24)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.asarray(tokens))["params"]
    packed = model.apply({"params": params}, jnp.asarray(tokens),
                         segment_ids=jnp.asarray(segs),
                         positions=jnp.asarray(pos))
    lone1 = model.apply({"params": params}, jnp.asarray(d1[None]))
    lone2 = model.apply({"params": params}, jnp.asarray(d2[None]))
    np.testing.assert_allclose(np.asarray(packed[0, :11]),
                               np.asarray(lone1[0]), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(packed[0, 11:19]),
                               np.asarray(lone2[0]), rtol=2e-4, atol=2e-4)
    loss = gpt2_loss_fn(model)(params, jnp.asarray(tokens),
                               jnp.asarray(segs), jnp.asarray(pos))
    assert np.isfinite(float(loss))



def test_gpt2_dropout_reachable_through_train_step():
    """cfg.dropout > 0 must be ACTIVATABLE from the repo's own training
    entry point: `gpt2_loss_fn(dropout_rng=...)` rides the batch tail
    through `Amp.make_train_step` (regression: the Block wiring existed
    with no way to turn it on, so dropout configs silently trained
    deterministic)."""
    cfg = GPT2Config.tiny(policy=_policy("O0"), dropout=0.1,
                          num_layers=1, hidden_size=64, num_heads=2)
    model = GPT2(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(7), (2, 32), 0,
                                cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    loss_fn = gpt2_loss_fn(model)
    a = amp_lib.Amp(tx=fused_adam(1e-3), opt_level="O0")
    state = a.init(params)
    step = jax.jit(a.make_train_step(loss_fn))
    key = jax.random.key(11)
    _, m_drop = step(state, tokens, None, None, key)
    _, m_drop2 = step(state, tokens, None, None, key)
    assert bool(m_drop["grads_finite"])
    # the seed makes the dropout'd step replayable
    assert float(m_drop["loss"]) == float(m_drop2["loss"])
    # dropout machinery is actually IN the traced program (trace-only —
    # the counter-hash xor chain appears iff the rng is threaded)
    txt_drop = str(jax.make_jaxpr(
        lambda p, t: loss_fn(p, t, dropout_rng=key))(params, tokens))
    txt_det = str(jax.make_jaxpr(loss_fn)(params, tokens))
    assert "xor" in txt_drop and "xor" not in txt_det
    # a key with dropout=0 is a config mistake, not a silent no-op
    cfg0 = GPT2Config.tiny(policy=_policy("O0"), num_layers=1,
                           hidden_size=64, num_heads=2)
    with pytest.raises(ValueError, match="dropout_rng"):
        gpt2_loss_fn(GPT2(cfg0))(params, tokens, dropout_rng=key)
