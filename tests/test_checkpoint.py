"""Checkpoint/resume + observability tests — SURVEY.md §5.4/§5.1.

Key reference behaviors: amp loss-scaler state round-trips; sharded opt
state saves/restores; resume onto a different mesh layout; exact training
continuation after restore."""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex1_tpu.amp import Amp
from apex1_tpu.checkpoint import (CheckpointManager, restore_checkpoint,
                                  save_checkpoint)
from apex1_tpu.core.mesh import make_mesh
from apex1_tpu.optim.fused_adam import fused_adam
from apex1_tpu.utils.observability import (MetricsLogger, Timers, annotate,
                                           cost_analysis)


def _state_and_step():
    amp = Amp(tx=fused_adam(1e-2), opt_level="O1_fp16",
              loss_scale="dynamic")
    params = {"w": jnp.ones((8,), jnp.float32),
              "b": jnp.zeros((4,), jnp.float32)}
    state = amp.init(params)
    step = jax.jit(amp.make_train_step(
        lambda p, x: jnp.sum(jnp.square(p["w"])) * x + jnp.sum(p["b"])))
    return amp, state, step


def test_roundtrip_amp_state(tmp_path):
    amp, state, step = _state_and_step()
    for _ in range(3):
        state, _ = step(state, jnp.float32(1.0))
    save_checkpoint(tmp_path / "ckpt", state)
    restored = restore_checkpoint(tmp_path / "ckpt", template=state)
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # training continues identically from the restore
    s1, m1 = step(state, jnp.float32(1.0))
    s2, m2 = step(restored, jnp.float32(1.0))
    np.testing.assert_allclose(np.asarray(m1["loss"]),
                               np.asarray(m2["loss"]))
    for a, b in zip(jax.tree.leaves(s1), jax.tree.leaves(s2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow  # 870s-cap headroom: quant x checkpoint COMPOSITION
# (26s: two generate compiles); each layer stays pinned in tier-1 —
# int8 generate parity in test_quantized, orbax round-trip fidelity in
# test_roundtrip_amp_state/test_loss_scale_state_round_trips
def test_quantized_decode_params_round_trip(tmp_path):
    """int8 serving trees (models.quant_decode) checkpoint bit-exactly —
    int8 weights, fp32 scales, bf16 embedding table all survive orbax,
    and a restored tree generates identical tokens."""
    from apex1_tpu.core.policy import get_policy
    from apex1_tpu.models.generate import generate
    from apex1_tpu.models.llama import Llama, LlamaConfig
    from apex1_tpu.models.quant_decode import llama_quant_decoder

    # O2 so the embedding table really is bf16 (O0 would make every
    # non-int8 leaf fp32 and silently drop the mixed-dtype coverage)
    cfg = LlamaConfig.tiny(policy=get_policy("O2"), max_seq_len=32)
    model = Llama(cfg)
    rng = np.random.default_rng(3)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 4)),
                         jnp.int32)
    params = model.init(jax.random.key(0), prompt)["params"]
    apply_q, make_cache, qparams = llama_quant_decoder(model, params)
    assert any(l.dtype == jnp.bfloat16
               for l in jax.tree.leaves(qparams))  # coverage is real
    save_checkpoint(tmp_path / "q", qparams)
    restored = restore_checkpoint(tmp_path / "q", template=qparams)
    for a, b in zip(jax.tree.leaves(qparams), jax.tree.leaves(restored)):
        assert a.dtype == b.dtype  # int8 stays int8
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    t1 = generate(apply_q, qparams, prompt, max_new_tokens=4,
                  cache=make_cache(2, 8), vocab_size=cfg.vocab_size)
    t2 = generate(apply_q, restored, prompt, max_new_tokens=4,
                  cache=make_cache(2, 8), vocab_size=cfg.vocab_size)
    np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))


def test_loss_scale_state_round_trips(tmp_path):
    amp, state, step = _state_and_step()
    state, _ = step(state, jnp.float32(1e30))   # overflow: scale halves
    state, _ = step(state, jnp.float32(1.0))
    save_checkpoint(tmp_path / "c2", state)
    restored = restore_checkpoint(tmp_path / "c2", template=state)
    assert float(restored.loss_scale.scale) == float(state.loss_scale.scale)
    # fp16 calibration may overflow more than once while the scale walks
    # down from 2^16 (reference-faithful); the COUNT must round-trip exactly
    assert (int(restored.loss_scale.overflow_count)
            == int(state.loss_scale.overflow_count) >= 1)


def test_restore_onto_mesh(tmp_path, devices):
    """Save unsharded, restore sharded over fsdp=4 — topology-change
    resume the reference cannot do."""
    x = jnp.arange(32, dtype=jnp.float32).reshape(8, 4)
    state = {"w": x, "step": jnp.int32(7)}
    save_checkpoint(tmp_path / "c3", state)
    mesh = make_mesh(fsdp=4, dp=1, devices=devices[:4])
    specs = {"w": P("fsdp", None), "step": P()}
    restored = restore_checkpoint(tmp_path / "c3", template=state,
                                  mesh=mesh, spec_tree=specs)
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.asarray(x))
    assert restored["w"].sharding.spec == P("fsdp", None)


def test_manager_rotation_and_resume(tmp_path):
    amp, state, step = _state_and_step()
    with CheckpointManager(tmp_path / "mgr", max_to_keep=2) as mgr:
        for i in range(4):
            state, _ = step(state, jnp.float32(1.0))
            mgr.save(i, state, force=True)
        mgr.wait_until_finished()
        assert mgr.latest() == 3
        restored = mgr.restore(jax.eval_shape(lambda: state))
        np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                      np.asarray(state.params["w"]))
        kept = {os.path.basename(p) for p in
                glob.glob(str(tmp_path / "mgr" / "*")) if
                os.path.basename(p).isdigit()}
        assert kept == {"2", "3"}


def test_cost_analysis_flops():
    a = jnp.ones((128, 128), jnp.float32)
    ca = cost_analysis(lambda a: a @ a, a)
    assert ca.get("flops", 0) >= 2 * 128 ** 3 * 0.9


def test_timers_and_annotate():
    t = Timers()
    with annotate("fwd"):
        t("fwd").start()
        x = jnp.ones((64, 64)) @ jnp.ones((64, 64))
        t("fwd").stop(sync=x)
    out = t.log()
    assert out["fwd"] > 0


def test_metrics_logger():
    lines = []
    ml = MetricsLogger(writer=lines.append, n_chips=1)
    ml.log(0, {"loss": jnp.float32(2.5)}, tokens=100)
    ml.log(1, {"loss": jnp.float32(2.0)}, tokens=100)
    import json
    recs = [json.loads(l) for l in lines]
    assert recs[0]["loss"] == 2.5
    assert "tokens_per_sec_per_chip" in recs[1]
