"""FusedDense / FusedDenseGeluDense / MLP parity — reference analogues:
``tests/L0/run_mlp/test_mlp.py`` (MLP vs torch.nn.Sequential gold),
``apex/contrib/test`` fused_dense tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.ops.fused_dense import (FusedDense, FusedDenseGeluDense, MLP,
                                       fused_dense)


def test_fused_dense_matches_gold(rng):
    x = jnp.asarray(rng.normal(size=(6, 16)), jnp.float32)
    m = FusedDense(16, 8)
    p = m.init(jax.random.key(0), x)["params"]
    out = m.apply({"params": p}, x)
    gold = np.asarray(x) @ np.asarray(p["weight"]).T + np.asarray(p["bias"])
    np.testing.assert_allclose(out, gold, rtol=1e-5, atol=1e-6)


def test_fused_dense_no_bias(rng):
    x = jnp.asarray(rng.normal(size=(6, 16)), jnp.float32)
    m = FusedDense(16, 8, bias=False)
    p = m.init(jax.random.key(0), x)["params"]
    assert "bias" not in p
    out = m.apply({"params": p}, x)
    np.testing.assert_allclose(out, np.asarray(x) @ np.asarray(
        p["weight"]).T, rtol=1e-5, atol=1e-6)


def test_gelu_dense_matches_composite(rng):
    x = jnp.asarray(rng.normal(size=(4, 16)), jnp.float32)
    m = FusedDenseGeluDense(16, 32, 8)
    p = m.init(jax.random.key(0), x)["params"]
    out = m.apply({"params": p}, x)
    h = fused_dense(x, p["weight1"], p["bias1"])
    h = jax.nn.gelu(h, approximate=True)
    gold = fused_dense(h, p["weight2"], p["bias2"])
    np.testing.assert_allclose(out, gold, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("activation", ["none", "relu", "sigmoid"])
def test_mlp_matches_gold(rng, activation):
    sizes = (16, 32, 8)
    x = jnp.asarray(rng.normal(size=(5, 16)), jnp.float32)
    m = MLP(sizes, activation=activation)
    p = m.init(jax.random.key(0), x)["params"]
    out = m.apply({"params": p}, x)
    h = np.asarray(x)
    for i in range(2):
        h = h @ np.asarray(p[f"weight_{i}"]).T + np.asarray(p[f"bias_{i}"])
        if activation != "none" and i < 1:
            h = {"relu": lambda t: np.maximum(t, 0),
                 "sigmoid": lambda t: 1 / (1 + np.exp(-t))}[activation](h)
    np.testing.assert_allclose(out, h, rtol=1e-5, atol=1e-5)


def test_mlp_grads(rng):
    x = jnp.asarray(rng.normal(size=(5, 16)), jnp.float32)
    m = MLP((16, 32, 8))
    p = m.init(jax.random.key(0), x)["params"]
    g = jax.grad(lambda p: jnp.sum(jnp.square(
        m.apply({"params": p}, x))))(p)
    for leaf in jax.tree.leaves(g):
        assert np.all(np.isfinite(leaf))


def test_bf16_accumulates_fp32(rng):
    # large-K matmul: fp32 accumulation must not lose more than bf16 eps
    x = jnp.asarray(rng.normal(size=(4, 2048)), jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(8, 2048)), jnp.bfloat16)
    out = fused_dense(x, w)
    gold = np.asarray(x, np.float32) @ np.asarray(w, np.float32).T
    np.testing.assert_allclose(np.asarray(out, np.float32), gold,
                               rtol=2e-2, atol=1e-1)
