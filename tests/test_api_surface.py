"""SURVEY.md Appendix B parity contract: every public-API equivalent the
blueprint promises must exist at its documented path. Pure import/hasattr
checks — the behavioral coverage lives in the per-component suites."""

import importlib

import pytest

SURFACE = {
    "apex1_tpu.amp": [
        "Amp", "initialize", "scale_loss", "AmpState"],
    "apex1_tpu.optim": [
        "fused_adam", "fused_lamb", "fused_sgd", "fused_novograd",
        "fused_adagrad", "clip_grad_norm", "clip_grad_norm_"],
    "apex1_tpu.optim.larc": ["larc", "LARC"],
    "apex1_tpu.ops": [
        "layer_norm", "rms_norm", "FusedLayerNorm", "FusedRMSNorm",
        "scaled_masked_softmax", "scaled_upper_triang_masked_softmax",
        "FusedScaleMaskSoftmax", "softmax_cross_entropy_loss",
        "apply_rotary_pos_emb", "rope_tables", "set_impl", "force_impl"],
    "apex1_tpu.ops.fused_dense": [
        "FusedDense", "FusedDenseGeluDense", "MLP", "fused_dense",
        "fused_dense_gelu_dense", "fused_glu", "check_glu_geometry"],
    "apex1_tpu.ops.chunked_loss": [
        "chunked_logprob", "chunked_dpo_loss", "chunked_orpo_loss",
        "chunked_kl_loss", "check_chunk_geometry"],
    "apex1_tpu.ops.lora_epilogue": ["lora_delta", "check_lora_geometry"],
    "apex1_tpu.serving.lora": ["LoraAdapterStore"],
    "apex1_tpu.ops.attention": ["flash_attention", "fmha"],
    "apex1_tpu.ops.stochastic": [
        "fused_bias_dropout_add", "fused_dropout_add_layer_norm",
        "seed_from_key", "fold_seed"],
    "apex1_tpu.ops.linear_xent": ["linear_cross_entropy",
                                  "shard_stats_packed"],
    "apex1_tpu.ops.fused_collective": [
        "fused_matmul_reduce_scatter", "fused_all_gather_matmul",
        "fused_all_gather_matmul_serial", "all_gather_flash_attention",
        "fused_vocab_parallel_merge", "matmul_reduce_scatter_rdma"],
    "apex1_tpu.parallel": [
        "DistributedDataParallel", "SyncBatchNorm",
        "convert_syncbn_model"],
    "apex1_tpu.parallel.distributed_optimizer": [
        "distributed_fused_adam", "distributed_fused_lamb",
        "shard_opt_state_specs", "fsdp_param_specs",
        "flat_param_len", "shard_padded_len", "repack_flat_shard"],
    "apex1_tpu.parallel.ring_attention": ["ring_attention",
                                          "ring_attention_serial"],
    "apex1_tpu.parallel.ulysses": ["ulysses_attention"],
    "apex1_tpu.parallel.halo": ["halo_exchange", "exchange_overlap"],
    "apex1_tpu.testing.hlo_probe": ["optimized_hlo",
                                    "check_collective_overlap",
                                    "assert_collective_overlap"],
    "apex1_tpu.contrib": [
        "fmha", "SelfMultiheadAttn", "EncdecMultiheadAttn",
        "SoftmaxCrossEntropyLoss", "clip_grad_norm_", "GroupBatchNorm2d",
        "GroupNorm", "focal_loss", "index_mul_2d", "TransducerJoint",
        "TransducerLoss", "ASP", "permutation_search",
        "distributed_fused_adam", "distributed_fused_lamb"],
    "apex1_tpu.transformer.parallel_state": [
        "initialize_model_parallel", "destroy_model_parallel",
        "model_parallel_is_initialized", "get_tensor_model_parallel_group",
        "get_pipeline_model_parallel_group", "get_data_parallel_group",
        "get_embedding_group", "is_rank_in_embedding_group",
        "get_tensor_model_parallel_world_size",
        "get_pipeline_model_parallel_world_size",
        "get_tensor_model_parallel_rank",
        "get_pipeline_model_parallel_rank",
        "is_pipeline_first_stage", "is_pipeline_last_stage",
        "set_virtual_pipeline_model_parallel_rank",
        "get_virtual_pipeline_model_parallel_world_size"],
    "apex1_tpu.transformer.tensor_parallel": [
        "ColumnParallelLinear", "RowParallelLinear",
        "VocabParallelEmbedding", "column_parallel_linear",
        "row_parallel_linear", "vocab_parallel_embedding",
        "vocab_parallel_cross_entropy",
        "vocab_parallel_linear_cross_entropy", "checkpoint",
        "model_parallel_seed", "get_rng_tracker", "broadcast_data",
        "copy_to_tensor_model_parallel_region",
        "reduce_from_tensor_model_parallel_region",
        "scatter_to_tensor_model_parallel_region",
        "gather_from_tensor_model_parallel_region",
        "scatter_to_sequence_parallel_region",
        "gather_from_sequence_parallel_region",
        "reduce_scatter_to_sequence_parallel_region",
        "all_gather_matmul", "matmul_reduce_scatter",
        "VocabUtility", "divide", "split_tensor_along_last_dim"],
    "apex1_tpu.transformer.pipeline_parallel": [
        "get_forward_backward_func", "forward_backward_no_pipelining",
        "forward_backward_pipelining_without_interleaving",
        "forward_backward_pipelining_with_interleaving",
        "pipeline_apply", "pipeline_tied_apply",
        "allreduce_embedding_grads", "pipelined_loss_fn",
        "p2p_communication"],
    "apex1_tpu.transformer.microbatches": [
        "build_num_microbatches_calculator"],
    "apex1_tpu.transformer.moe": [
        "MoEConfig", "MoEMLP", "moe_shard_map_apply", "router"],
    "apex1_tpu.fp16_utils": [
        "FP16_Optimizer", "network_to_half",
        "master_params_to_model_params", "prep_param_lists"],
    "apex1_tpu.runtime": [
        "PrefetchLoader", "TokenDataset", "pack_documents",
        "write_token_file", "flatten", "unflatten", "RequestFeeder"],
    "apex1_tpu.serving": [
        "Engine", "EngineConfig", "RequestResult", "Scheduler",
        "Request", "Backpressure", "KVPool", "PagedKVPool",
        "PagedPrefix", "PrefixPage", "RadixIndex", "ngram_propose",
        "ServingMetrics", "RequestRecord"],
    "apex1_tpu.ops.paged_decode": [
        "PagedCache", "cache_attend", "check_paged_geometry",
        "fused_sample", "gather_pages", "paged_attend",
        "paged_update_attend", "sample_token", "scatter_pages"],
    "apex1_tpu.models.generate": [
        "generate", "speculative_generate", "beam_search", "t5_generate",
        "init_cache", "cached_attention", "sample_token",
        "counter_sample", "last_real_logits"],
    "apex1_tpu.core.mesh": [
        "make_mesh", "make_hybrid_mesh", "MeshConfig", "MeshResource",
        "shard_batch", "replicate"],
    "apex1_tpu.core.policy": ["PrecisionPolicy", "get_policy"],
    "apex1_tpu.core.loss_scale": [
        "make_loss_scale", "all_finite", "select_tree"],
    "apex1_tpu.core.capability": [
        "get_capability", "detect_generation", "require", "vmem_budget"],
    "apex1_tpu.checkpoint": [
        "save_checkpoint", "restore_checkpoint", "CheckpointManager"],
    "apex1_tpu.models.gpt2": ["GPT2", "GPT2Config", "gpt2_loss_fn"],
    "apex1_tpu.models.bert": ["BertConfig", "BertPretrain"],
    "apex1_tpu.models.resnet": ["ResNet", "ResNetConfig", "Bottleneck",
                                "SpatialBottleneck"],
    "apex1_tpu.models.llama": ["Llama", "LlamaConfig", "LlamaBlock",
                               "llama_loss_fn"],
    "apex1_tpu.models.llama_3d": [
        "Llama3DConfig", "make_train_step", "build_step",
        "abstract_state", "from_llama_params", "reshape_chunks",
        "combine_grads", "state_template"],
    "apex1_tpu.resilience.reshard": [
        "LayoutMismatch", "reshard_state", "reshard_checkpoint",
        "read_plan", "plan_meta", "mesh_str"],
    "apex1_tpu.resilience.elastic": [
        "ElasticDecision", "elastic_resume", "drill"],
    "apex1_tpu.utils.observability": ["MetricsLogger", "Timers"],
    "apex1_tpu.obs": ["ObsRun", "StopWatch", "default_run", "emit",
                      "read_events", "TraceError", "build_report",
                      "parse_xspace", "write_report"],
    "apex1_tpu.obs.calibrate": [
        "collect_pairs", "fit", "build_calibration", "load_calibration",
        "step_slowdown", "kernel_slowdown", "newest_prediction_path",
        "roofline_ms"],
    "apex1_tpu.testing": [
        "force_virtual_cpu_devices", "enable_persistent_compilation_cache",
        "honor_jax_platforms_env", "distributed_mesh", "standalone_gpt",
        "standalone_bert"],
    "apex1_tpu.lint": [
        "lint_paths", "lint_files", "lint_sources", "LintResult",
        "RULES", "RULE_SLUGS"],
    "apex1_tpu.lint.kernels": [
        "check_kernels", "KERNEL_RULES", "KernelRule"],
    "apex1_tpu.lint.protocols": [
        "check_protocols", "PROTOCOL_RULES", "ProtocolRule"],
    "apex1_tpu.vmem_model": [
        "CHECKS", "budget_bytes", "flash_check", "row_check",
        "linear_xent_check", "cm_check", "agf_check", "int8_check",
        "rdma_check", "rdma_slot_bytes", "static_frame_bytes",
        "paged_decode_check", "fused_sample_check",
        "chunked_loss_check", "fused_swiglu_check",
        "lora_epilogue_check"],
    "apex1_tpu.perf_model": [
        "roofline", "kernel_cases", "flash_flops_bytes",
        "linear_xent_flops", "ring_attention_comms",
        "sp_boundary_comms", "allreduce_bytes",
        "kv_cache_bytes", "serving_capacity", "speculative_speedup"],
    "apex1_tpu.autopilot": [
        "Autopilot", "AutopilotConfig", "SLOTarget", "FleetView",
        "ControllerState", "Action", "decide", "default_slo"],
    "apex1_tpu.testing.fleetsim": [
        "VirtualClock", "SimRequest", "Trace", "synthetic_trace",
        "FleetSimConfig", "FleetSim", "SimReport", "run_fleet",
        "kill_k_of_n"],
    "apex1_tpu.planner": [
        "ModelShape", "Layout", "Violation", "BANKED_SHAPES",
        "check_layout", "check_plan_model", "enumerate_layouts",
        "fit_check",
        "hbm_breakdown", "price_layout", "calibration_factor",
        "make_plan", "search_layouts", "PlanError", "plan_json",
        "save_plan", "load_plan", "partition_rules", "rules_to_specs",
        "plan_param_specs", "llama3d_config_from_plan",
        "layout_from_plan", "PLAN_SCHEMA", "PLAN_SPEC_KEYS",
        "plan_for_layout", "plan_spec", "model_shape_from_plan"],
}


@pytest.mark.parametrize("module", sorted(SURFACE))
def test_surface(module):
    mod = importlib.import_module(module)
    missing = [n for n in SURFACE[module] if not hasattr(mod, n)]
    assert not missing, f"{module} missing {missing}"
