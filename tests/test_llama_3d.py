"""3D-parallel Llama (dp x pp x tp + SP) vs the unpartitioned model —
the flagship BASELINE config-4 composition at tiny size: loss and grads
through ONE shard_mapped train step must match `models.llama.Llama` run
flat on one logical device. ≙ reference `tests/L0/run_transformer`'s
pipeline/TP parity suites composed together."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.core.policy import get_policy
from apex1_tpu.models.llama import Llama, LlamaConfig
from apex1_tpu.models.llama_3d import (Llama3DConfig, combine_grads,
                                       from_llama_params, loss_fn,
                                       make_train_step)
from apex1_tpu.ops import rope_tables, softmax_cross_entropy_loss

pytestmark = pytest.mark.slow  # composed-step / fuzz suite: full run via check_all.sh --all

DP, PP, TP = 2, 2, 2
M, MB = 4, 2          # microbatches, global sequences per microbatch


@pytest.fixture(params=[1, 2], ids=["V1", "V2-interleaved"])
def setup(rng, devices, request):
    mcfg = LlamaConfig.tiny(num_layers=4, max_seq_len=32, vocab_size=64,
                            num_heads=4, num_kv_heads=2, hidden_size=32,
                            ffn_size=64, policy=get_policy("O0"))
    cfg = Llama3DConfig(model=mcfg, dp=DP, pp=PP, tp=TP,
                        num_chunks=request.param,
                        num_microbatches=M, microbatch_size=MB // DP)
    model = Llama(mcfg)
    tokens = jnp.asarray(
        rng.integers(0, mcfg.vocab_size, (M, mcfg.max_seq_len, MB)),
        jnp.int32)
    labels = jnp.asarray(
        rng.integers(0, mcfg.vocab_size, (M, mcfg.max_seq_len, MB)),
        jnp.int32)
    flat = model.init(jax.random.key(0),
                      tokens[0].transpose(1, 0))["params"]
    return cfg, model, flat, tokens, labels


def gold_loss(model, flat, tokens, labels):
    """Unpartitioned: mean CE over every (microbatch, position, seq)."""
    def per_mb(tok_m, lbl_m):
        logits = model.apply({"params": flat}, tok_m.transpose(1, 0))
        return softmax_cross_entropy_loss(
            logits.astype(jnp.float32),
            lbl_m.transpose(1, 0))  # (mb, S)

    ces = jax.vmap(per_mb)(tokens, labels)
    return jnp.mean(ces)


def test_loss_and_grads_match_unpartitioned(setup, devices):
    from jax.sharding import PartitionSpec as Ps

    from apex1_tpu.core.mesh import make_mesh
    from apex1_tpu.models.llama_3d import (chunk_param_specs,
                                           shared_param_specs)

    cfg, model, flat, tokens, labels = setup
    mesh = make_mesh(dp=DP, pp=PP, tp=TP)
    chunk, shared = from_llama_params(flat, cfg)
    cos, sin = rope_tables(jnp.arange(cfg.model.max_seq_len),
                           cfg.model.head_dim, base=cfg.model.rope_base)

    def g_inner(chunk, shared, tokens, labels):
        def scalar(chunk, shared):
            return loss_fn(cfg, chunk, shared, tokens, labels, cos, sin)

        loss_part, (g_c, g_s) = jax.value_and_grad(
            scalar, argnums=(0, 1))(chunk, shared)
        loss = jax.lax.pmean(jax.lax.psum(loss_part, "pp"), "dp")
        g_c, g_s = combine_grads(g_c, g_s, cfg)
        return loss, g_c, g_s

    cspecs, sspecs = chunk_param_specs(cfg), shared_param_specs()
    data_spec = Ps(None, None, "dp")
    loss, g_c, g_s = jax.jit(jax.shard_map(
        g_inner, mesh=mesh,
        in_specs=(cspecs, sspecs, data_spec, data_spec),
        out_specs=(Ps(), cspecs, sspecs),
        check_vma=False))(chunk, shared, tokens, labels)

    want_loss, want_grads = jax.value_and_grad(
        lambda p: gold_loss(model, p, tokens, labels))(flat)
    np.testing.assert_allclose(float(loss), float(want_loss), rtol=2e-5)

    gold_c, gold_s = from_llama_params(want_grads, cfg)
    for k in g_c:
        np.testing.assert_allclose(np.asarray(g_c[k]),
                                   np.asarray(gold_c[k]),
                                   rtol=2e-4, atol=2e-5, err_msg=k)
    for k in g_s:
        np.testing.assert_allclose(np.asarray(g_s[k]),
                                   np.asarray(gold_s[k]),
                                   rtol=2e-4, atol=2e-5, err_msg=k)


def test_checkpoint_cross_topology_resume(setup, devices, tmp_path):
    """Save the 3D-sharded state mid-training on one pipeline layout and
    resume on a DIFFERENT one (dp2 pp2 tp2 [xV] -> dp1 pp4 tp2 V=1): the
    chunk-major stack re-partitions by reshape_chunks, and the
    post-restore loss matches continuing on the original mesh
    (≙ reference cross-topology resume, SURVEY §5.4)."""
    from apex1_tpu.checkpoint import restore_checkpoint, save_checkpoint
    from apex1_tpu.models.llama_3d import reshape_chunks

    cfg, model, flat, tokens, labels = setup
    params = {}
    params["chunk"], params["shared"] = from_llama_params(flat, cfg)
    step, state, _ = make_train_step(cfg, params=params)
    state, _ = step(state, tokens, labels)

    path = tmp_path / "ck3d"
    save_checkpoint(path, state)
    state, loss_cont = step(state, tokens, labels)  # continue on mesh A

    cfg_b = dataclasses.replace(cfg, dp=1, pp=4, tp=2, num_chunks=1)
    host = restore_checkpoint(path, template=state)  # keeps pytree types
    remap = lambda t: {"chunk": reshape_chunks(t["chunk"], cfg_b),
                       "shared": t["shared"]}
    state_b = {
        "step": host["step"],
        "params": remap(host["params"]),
        "opt": type(host["opt"])(
            step=host["opt"].step,
            exp_avg=remap(host["opt"].exp_avg),
            exp_avg_sq=remap(host["opt"].exp_avg_sq)),
    }
    step_b, _, _ = make_train_step(cfg_b, params=state_b["params"])
    state_b, loss_res = step_b(state_b, tokens, labels)
    np.testing.assert_allclose(float(loss_res), float(loss_cont),
                               rtol=2e-5)
    assert int(state_b["step"]) == int(state["step"])


def test_moe_ep_matches_unpartitioned(devices, rng):
    """4-axis composition: dp x pp x ep x tp with every FFN expert-routed
    — loss and grads (incl. the ep-sharded expert weights through the
    double all_to_all) must match the flat MoE Llama. capacity_factor is
    set high enough that no token drops, so dispatch is grouping-
    invariant and flat-vs-sharded parity is exact."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as Ps

    from apex1_tpu.core.mesh import make_mesh
    from apex1_tpu.models.llama_3d import (chunk_param_specs,
                                           combine_grads, loss_fn,
                                           shared_param_specs)

    mcfg = LlamaConfig.tiny(num_layers=4, max_seq_len=32, vocab_size=64,
                            num_heads=4, num_kv_heads=2, hidden_size=32,
                            ffn_size=64, moe_every=1, num_experts=4,
                            moe_top_k=2, moe_capacity_factor=4.0,
                            policy=get_policy("O0"))
    dp, pp, ep, tp = 1, 2, 2, 2
    cfg = Llama3DConfig(model=mcfg, dp=dp, pp=pp, ep=ep, tp=tp, moe=True,
                        num_microbatches=M, microbatch_size=1)
    model = Llama(mcfg)
    mb_glob = ep * dp
    tokens = jnp.asarray(
        rng.integers(0, 64, (M, mcfg.max_seq_len, mb_glob)), jnp.int32)
    labels = jnp.asarray(
        rng.integers(0, 64, (M, mcfg.max_seq_len, mb_glob)), jnp.int32)
    flat = model.init(jax.random.key(0),
                      tokens[0].transpose(1, 0))["params"]
    mesh = make_mesh(dp=dp, pp=pp, ep=ep, tp=tp)
    chunk, shared = from_llama_params(flat, cfg)
    cos, sin = rope_tables(jnp.arange(mcfg.max_seq_len), mcfg.head_dim,
                           base=mcfg.rope_base)

    def g_inner(chunk, shared, tokens, labels):
        def scalar(chunk, shared):
            return loss_fn(cfg, chunk, shared, tokens, labels, cos, sin)

        loss_part, (g_c, g_s) = jax.value_and_grad(
            scalar, argnums=(0, 1))(chunk, shared)
        loss = jax.lax.pmean(jax.lax.psum(loss_part, "pp"), ("dp", "ep"))
        g_c, g_s = combine_grads(g_c, g_s, cfg)
        return loss, g_c, g_s

    cspecs, sspecs = chunk_param_specs(cfg), shared_param_specs()
    data_spec = Ps(None, None, ("dp", "ep"))
    loss, g_c, g_s = jax.jit(jax.shard_map(
        g_inner, mesh=mesh,
        in_specs=(cspecs, sspecs, data_spec, data_spec),
        out_specs=(Ps(), cspecs, sspecs),
        check_vma=False))(chunk, shared, tokens, labels)

    # gold: the flat MoE Llama, loss = per-replica mean CE averaged over
    # the (dp, ep) replicas — each replica is one mb column — PLUS the
    # sowed Switch aux balance terms (aux_loss_weight is the LlamaConfig
    # default 1e-2 > 0, so this asserts the pipelined aux channel too)
    def gold(flat):
        def per_mb(tok_m, lbl_m):
            logits, aux_vars = model.apply(
                {"params": flat}, tok_m.transpose(1, 0),
                mutable=["losses"])
            ce = softmax_cross_entropy_loss(
                logits.astype(jnp.float32), lbl_m.transpose(1, 0))
            aux = sum(jnp.sum(jnp.asarray(v)) for v in
                      jax.tree_util.tree_leaves(
                          aux_vars.get("losses", {})))
            return ce, aux

        # replica r owns mb column r: per-replica mean over (M, S) then
        # mean over replicas == overall mean here (equal token counts)
        ces, auxes = jax.vmap(per_mb)(tokens, labels)
        return jnp.mean(ces) + jnp.mean(auxes)

    want_loss, want_grads = jax.value_and_grad(gold)(flat)
    np.testing.assert_allclose(float(loss), float(want_loss), rtol=2e-5)
    gold_c, gold_s = from_llama_params(want_grads, cfg)
    for k in g_c:
        np.testing.assert_allclose(np.asarray(g_c[k]),
                                   np.asarray(gold_c[k]),
                                   rtol=3e-4, atol=3e-5, err_msg=k)
    for k in g_s:
        np.testing.assert_allclose(np.asarray(g_s[k]),
                                   np.asarray(gold_s[k]),
                                   rtol=3e-4, atol=3e-5, err_msg=k)


def test_cp_ring_matches_unpartitioned(devices, rng):
    """Context parallelism composed in: dp x pp x cp x tp with the
    sequence sharded over cp (ring attention, global rope positions,
    cp-sharded CE) — loss and grads must match the flat model on the
    full sequence."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as Ps

    from apex1_tpu.core.mesh import make_mesh
    from apex1_tpu.models.llama_3d import (chunk_param_specs,
                                           combine_grads, loss_fn,
                                           shared_param_specs)

    mcfg = LlamaConfig.tiny(num_layers=4, max_seq_len=64, vocab_size=64,
                            num_heads=4, num_kv_heads=2, hidden_size=32,
                            ffn_size=64, policy=get_policy("O0"))
    dp, pp, cp, tp = 1, 2, 2, 2
    cfg = Llama3DConfig(model=mcfg, dp=dp, pp=pp, cp=cp, tp=tp,
                        num_microbatches=M, microbatch_size=1)
    model = Llama(mcfg)
    tokens = jnp.asarray(
        rng.integers(0, 64, (M, mcfg.max_seq_len, 1)), jnp.int32)
    labels = jnp.asarray(
        rng.integers(0, 64, (M, mcfg.max_seq_len, 1)), jnp.int32)
    flat = model.init(jax.random.key(0),
                      tokens[0].transpose(1, 0))["params"]
    mesh = make_mesh(dp=dp, pp=pp, cp=cp, tp=tp)
    chunk, shared = from_llama_params(flat, cfg)
    cos, sin = rope_tables(jnp.arange(mcfg.max_seq_len), mcfg.head_dim,
                           base=mcfg.rope_base)

    def g_inner(chunk, shared, tokens, labels):
        def scalar(chunk, shared):
            return loss_fn(cfg, chunk, shared, tokens, labels, cos, sin)

        loss_part, (g_c, g_s) = jax.value_and_grad(
            scalar, argnums=(0, 1))(chunk, shared)
        loss = jax.lax.pmean(jax.lax.psum(loss_part, "pp"),
                             ("dp", "ep", "cp"))
        g_c, g_s = combine_grads(g_c, g_s, cfg)
        return loss, g_c, g_s

    cspecs, sspecs = chunk_param_specs(cfg), shared_param_specs()
    data_spec = Ps(None, "cp", ("dp", "ep"))
    loss, g_c, g_s = jax.jit(jax.shard_map(
        g_inner, mesh=mesh,
        in_specs=(cspecs, sspecs, data_spec, data_spec),
        out_specs=(Ps(), cspecs, sspecs),
        check_vma=False))(chunk, shared, tokens, labels)

    want_loss, want_grads = jax.value_and_grad(
        lambda p: gold_loss(model, p, tokens, labels))(flat)
    np.testing.assert_allclose(float(loss), float(want_loss), rtol=2e-5)
    gold_c, gold_s = from_llama_params(want_grads, cfg)
    for k in g_c:
        np.testing.assert_allclose(np.asarray(g_c[k]),
                                   np.asarray(gold_c[k]),
                                   rtol=3e-4, atol=3e-5, err_msg=k)
    for k in g_s:
        np.testing.assert_allclose(np.asarray(g_s[k]),
                                   np.asarray(gold_s[k]),
                                   rtol=3e-4, atol=3e-5, err_msg=k)


def test_dynamic_loss_scale_threads_through(devices, rng):
    """fp16-style dynamic loss scaling under the full 3D step — the
    MP-aware GradScaler semantics (global finite-check psum over
    dp/pp/tp, skip-on-overflow, hysteresis) at flagship composition."""
    mcfg = LlamaConfig.tiny(num_layers=4, max_seq_len=32, vocab_size=64,
                            num_heads=4, num_kv_heads=2, hidden_size=32,
                            ffn_size=64,
                            policy=get_policy("O2", loss_scale="dynamic"))
    cfg = Llama3DConfig(model=mcfg, dp=DP, pp=PP, tp=TP,
                        num_microbatches=M, microbatch_size=MB // DP)
    step, state, _ = make_train_step(cfg)
    assert "scale" in state
    tokens = jnp.asarray(
        rng.integers(0, 64, (M, mcfg.max_seq_len, MB)), jnp.int32)
    labels = jnp.asarray(
        rng.integers(0, 64, (M, mcfg.max_seq_len, MB)), jnp.int32)
    p0 = jax.tree_util.tree_leaves(state["params"])[0]
    for _ in range(3):
        state, loss = step(state, tokens, labels)
    assert np.isfinite(float(loss))
    # bf16 compute never overflows here: every step must be CLEAN —
    # scale untouched at 2^16, 3 consecutive clean steps counted, zero
    # skips, and params actually updated (a broken finite check would
    # freeze params and halve the scale)
    assert int(state["step"]) == 3
    sc = state["scale"]
    assert float(sc.scale) == 2.0 ** 16
    assert int(sc.growth_count) == 3
    assert int(sc.overflow_count) == 0
    assert not np.allclose(np.asarray(p0),
                           np.asarray(jax.tree_util.tree_leaves(
                               state["params"])[0]))


class Test1F1BSchedule:
    """``schedule='1f1b'`` (the true staggered-fwd/bwd residual-ring
    schedule) must produce the same loss and updated state as the scan
    schedule — which is itself flat-parity-tested above — across the
    dense, MoE (aux seed), cp-ring (masked idle ticks), and fp16
    dynamic-scale compositions."""

    @staticmethod
    def _run_both(cfg, tokens, labels, params, steps=1):
        out = {}
        for sched in ("scan", "1f1b"):
            c = dataclasses.replace(cfg, schedule=sched)
            step, state, _ = make_train_step(
                c, params=jax.tree_util.tree_map(jnp.copy, params))
            for _ in range(steps):
                state, loss = step(state, tokens, labels)
            out[sched] = (state, float(loss))
        return out["scan"], out["1f1b"]

    @staticmethod
    def _assert_grads_match(cfg, params, tokens, labels):
        """Compare combined UNSCALED grads between the two schedules
        under a static 2^16 loss scale (inside one shard_map each)."""
        from jax.sharding import PartitionSpec as Ps

        from apex1_tpu.core.mesh import make_mesh
        from apex1_tpu.models.llama_3d import (chunk_param_specs,
                                               combine_grads,
                                               loss_and_grads_1f1b,
                                               loss_fn,
                                               shared_param_specs)

        mesh = make_mesh(dp=cfg.dp, pp=cfg.pp, cp=cfg.cp, ep=cfg.ep,
                         tp=cfg.tp)
        cos, sin = rope_tables(jnp.arange(cfg.model.max_seq_len),
                               cfg.model.head_dim,
                               base=cfg.model.rope_base)
        SCALE = 2.0 ** 16

        def inner(schedule, params, tokens, labels):
            if schedule == "1f1b":
                grads, _ = loss_and_grads_1f1b(
                    cfg, params, tokens, labels, cos, sin,
                    jnp.float32(SCALE))
            else:
                def scalar(p):
                    return SCALE * loss_fn(cfg, p["chunk"], p["shared"],
                                           tokens, labels, cos, sin)
                grads = jax.grad(scalar)(params)
            g_c, g_s = combine_grads(grads["chunk"], grads["shared"],
                                     cfg)
            return jax.tree_util.tree_map(lambda g: g / SCALE,
                                          {"chunk": g_c, "shared": g_s})

        pspecs = {"chunk": chunk_param_specs(cfg),
                  "shared": shared_param_specs()}
        data_spec = Ps(None, "cp", ("dp", "ep"))
        out = {}
        for sched in ("scan", "1f1b"):
            out[sched] = jax.jit(jax.shard_map(
                lambda p, t, l, s=sched: inner(s, p, t, l), mesh=mesh,
                in_specs=(pspecs, data_spec, data_spec),
                out_specs=pspecs, check_vma=False))(
                params, tokens, labels)
        want = dict(jax.tree_util.tree_leaves_with_path(out["scan"]))
        for path, leaf in jax.tree_util.tree_leaves_with_path(
                out["1f1b"]):
            np.testing.assert_allclose(
                np.asarray(leaf), np.asarray(want[path]),
                err_msg=jax.tree_util.keystr(path),
                rtol=3e-2, atol=3e-5)

    @pytest.mark.parametrize("variant", ["dense", "moe", "cp", "fp16"])
    def test_matches_scan_schedule(self, devices, rng, variant):
        kw = dict(num_layers=4, max_seq_len=32, vocab_size=64,
                  num_heads=4, num_kv_heads=2, hidden_size=32,
                  ffn_size=64, policy=get_policy("O0"))
        dp, pp, ep, cp, tp = 2, 2, 1, 1, 2
        moe = False
        if variant == "moe":
            kw.update(moe_every=1, num_experts=4, moe_top_k=2,
                      moe_capacity_factor=4.0)
            dp, ep, moe = 1, 2, True
        elif variant == "cp":
            kw.update(max_seq_len=64)
            dp, cp = 1, 2
        elif variant == "fp16":
            kw.update(policy=get_policy("O2", loss_scale="dynamic"))
        mcfg = LlamaConfig.tiny(**kw)
        cfg = Llama3DConfig(model=mcfg, dp=dp, pp=pp, ep=ep, cp=cp,
                            tp=tp, moe=moe, num_microbatches=M,
                            microbatch_size=1)
        mb_glob = dp * ep
        tokens = jnp.asarray(
            rng.integers(0, 64, (M, mcfg.max_seq_len, mb_glob)),
            jnp.int32)
        labels = jnp.asarray(
            rng.integers(0, 64, (M, mcfg.max_seq_len, mb_glob)),
            jnp.int32)
        model = Llama(mcfg)
        flat = model.init(jax.random.key(0),
                          tokens[0].transpose(1, 0))["params"]
        params = {}
        params["chunk"], params["shared"] = from_llama_params(flat, cfg)

        if variant == "fp16":
            # bf16 compute: the schedules group CE/matmul reductions
            # differently, and Adam's first-step g/√g² amplifies that
            # rounding noise into ±lr sign flips near g≈0 — so compare
            # GRADS (which pin the 2^16 scale threading precisely: a
            # scale-wiring bug is off by orders of magnitude), not
            # post-Adam params.
            self._assert_grads_match(cfg, params, tokens, labels)
            (st_scan, loss_scan), (st_1f1b, loss_1f1b) = self._run_both(
                cfg, tokens, labels, params)
            np.testing.assert_allclose(loss_1f1b, loss_scan, rtol=2e-3)
            assert float(st_1f1b["scale"].scale) == float(
                st_scan["scale"].scale)
            assert int(st_1f1b["scale"].overflow_count) == int(
                st_scan["scale"].overflow_count)
            return

        (st_scan, loss_scan), (st_1f1b, loss_1f1b) = self._run_both(
            cfg, tokens, labels, params)
        np.testing.assert_allclose(loss_1f1b, loss_scan, rtol=2e-5)
        flat_scan = jax.tree_util.tree_leaves_with_path(
            st_scan["params"])
        flat_1f1b = dict(jax.tree_util.tree_leaves_with_path(
            st_1f1b["params"]))
        for path, leaf in flat_scan:
            np.testing.assert_allclose(
                np.asarray(flat_1f1b[path]), np.asarray(leaf),
                err_msg=jax.tree_util.keystr(path),
                rtol=2e-4, atol=2e-6)

    def test_interleaved_matches_scan_schedule(self, devices, rng):
        """V=2 interleaved 1F1B (group-cycled chunks) through the full
        flagship step must match the scan schedule's loss and updated
        params — the scan path's V>1 interleave is itself
        flat-parity-tested above."""
        mcfg = LlamaConfig.tiny(num_layers=4, max_seq_len=32,
                                vocab_size=64, num_heads=4,
                                num_kv_heads=2, hidden_size=32,
                                ffn_size=64, policy=get_policy("O0"))
        cfg = Llama3DConfig(model=mcfg, dp=2, pp=2, tp=2, num_chunks=2,
                            num_microbatches=M, microbatch_size=1)
        tokens = jnp.asarray(
            rng.integers(0, 64, (M, mcfg.max_seq_len, 2)), jnp.int32)
        labels = jnp.asarray(
            rng.integers(0, 64, (M, mcfg.max_seq_len, 2)), jnp.int32)
        model = Llama(mcfg)
        flat = model.init(jax.random.key(0),
                          tokens[0].transpose(1, 0))["params"]
        params = {}
        params["chunk"], params["shared"] = from_llama_params(flat, cfg)
        (st_scan, loss_scan), (st_1f1b, loss_1f1b) = self._run_both(
            cfg, tokens, labels, params)
        np.testing.assert_allclose(loss_1f1b, loss_scan, rtol=2e-5)
        flat_1f1b = dict(jax.tree_util.tree_leaves_with_path(
            st_1f1b["params"]))
        for path, leaf in jax.tree_util.tree_leaves_with_path(
                st_scan["params"]):
            np.testing.assert_allclose(
                np.asarray(flat_1f1b[path]), np.asarray(leaf),
                err_msg=jax.tree_util.keystr(path),
                rtol=2e-4, atol=2e-6)

    def test_rejects_interleaved_bad_microbatches(self, rng):
        mcfg = LlamaConfig.tiny(num_layers=4, max_seq_len=32,
                                vocab_size=64, num_heads=4,
                                num_kv_heads=2, hidden_size=32,
                                ffn_size=64, policy=get_policy("O0"))
        with pytest.raises(ValueError, match="interleaved 1F1B"):
            Llama3DConfig(model=mcfg, pp=2, tp=2, num_chunks=2,
                          num_microbatches=3, schedule="1f1b")


def test_train_step_runs_and_descends(setup, devices):
    cfg, model, flat, tokens, labels = setup
    cfg = dataclasses.replace(cfg, learning_rate=5e-3)
    params = {"chunk": {}, "shared": {}}
    params["chunk"], params["shared"] = from_llama_params(flat, cfg)
    step, state, _ = make_train_step(cfg, params=params)
    losses = []
    for _ in range(5):
        state, loss = step(state, tokens, labels)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    assert int(state["step"]) == 5
