"""Fused LM-head + CE ("vocab flash", ``ops/linear_xent.py``) parity —
vs the materialized-logits path it replaces: loss, dx, dW, under label
smoothing / padding_idx / num_classes lane-pad masking, fp32 and bf16.
Reference capability lineage: ``apex/contrib/xentropy`` (the fused-softmax
CE this kernel extends with the head matmul)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu import ops
from apex1_tpu.ops import _common
from apex1_tpu.ops.linear_xent import linear_cross_entropy

FP32_TOL = dict(rtol=2e-5, atol=2e-5)


def _materialized(x, w, labels, **kw):
    logits = jnp.einsum("th,vh->tv", x.astype(jnp.float32),
                        w.astype(jnp.float32))
    return ops.softmax_cross_entropy_loss(logits, labels, **kw)


class TestLinearCrossEntropy:
    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_parity_vs_materialized(self, rng, smoothing):
        T, H, V = 24, 96, 307  # V, H non-multiples of 128 exercise padding
        x = jnp.asarray(rng.normal(size=(T, H)) * 0.3, jnp.float32)
        w = jnp.asarray(rng.normal(size=(V, H)) * 0.3, jnp.float32)
        labels = jnp.asarray(rng.integers(0, V, size=(T,)), jnp.int32)

        def fused(x, w):
            with _common.force_impl("pallas"):
                return linear_cross_entropy(x, w, labels,
                                            smoothing=smoothing,
                                            block_t=16, block_v=64)

        def gold(x, w):
            return _materialized(x, w, labels, smoothing=smoothing)

        np.testing.assert_allclose(np.asarray(fused(x, w)),
                                   np.asarray(gold(x, w)), **FP32_TOL)
        gp = jax.grad(lambda x, w: jnp.sum(fused(x, w)), argnums=(0, 1))(
            x, w)
        gg = jax.grad(lambda x, w: jnp.sum(gold(x, w)), argnums=(0, 1))(
            x, w)
        for a, b in zip(gp, gg):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       **FP32_TOL)

    def test_padding_idx_and_weighted_cotangent(self, rng):
        T, H, V = 16, 64, 130
        pad = 7
        x = jnp.asarray(rng.normal(size=(T, H)) * 0.3, jnp.float32)
        w = jnp.asarray(rng.normal(size=(V, H)) * 0.3, jnp.float32)
        labels = np.asarray(rng.integers(0, V, size=(T,)), np.int32)
        labels[::3] = pad
        labels = jnp.asarray(labels)
        ct = jnp.asarray(rng.normal(size=(T,)), jnp.float32)  # non-unit

        def fused(x, w):
            with _common.force_impl("pallas"):
                return linear_cross_entropy(x, w, labels, padding_idx=pad,
                                            block_t=16, block_v=64)

        def gold(x, w):
            return _materialized(x, w, labels, padding_idx=pad)

        lf, lg = fused(x, w), gold(x, w)
        assert np.all(np.asarray(lf)[::3] == 0.0)
        np.testing.assert_allclose(np.asarray(lf), np.asarray(lg),
                                   **FP32_TOL)
        gp = jax.grad(lambda x, w: jnp.sum(fused(x, w) * ct),
                      argnums=(0, 1))(x, w)
        gg = jax.grad(lambda x, w: jnp.sum(gold(x, w) * ct),
                      argnums=(0, 1))(x, w)
        for a, b in zip(gp, gg):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       **FP32_TOL)

    def test_num_classes_masks_padded_vocab_rows(self, rng):
        """W carries Megatron-style lane-padded rows; they must get zero
        probability mass and zero gradient."""
        T, H, K, Vp = 16, 64, 100, 128
        x = jnp.asarray(rng.normal(size=(T, H)) * 0.3, jnp.float32)
        w = jnp.asarray(rng.normal(size=(Vp, H)) * 0.3, jnp.float32)
        labels = jnp.asarray(rng.integers(0, K, size=(T,)), jnp.int32)

        def fused(x, w):
            with _common.force_impl("pallas"):
                return linear_cross_entropy(x, w, labels, num_classes=K,
                                            block_t=16, block_v=64)

        def gold(x, w):
            return _materialized(x, w[:K], labels)

        np.testing.assert_allclose(np.asarray(fused(x, w)),
                                   np.asarray(gold(x, w)), **FP32_TOL)
        dw = jax.grad(lambda w: jnp.sum(fused(x, w)))(w)
        assert np.all(np.asarray(dw)[K:] == 0.0)
        np.testing.assert_allclose(
            np.asarray(dw)[:K],
            np.asarray(jax.grad(lambda w: jnp.sum(gold(x, w)))(w)[:K]),
            **FP32_TOL)

    def test_bf16_inputs(self, rng):
        T, H, V = 32, 128, 256
        x = jnp.asarray(rng.normal(size=(T, H)) * 0.3, jnp.bfloat16)
        w = jnp.asarray(rng.normal(size=(V, H)) * 0.3, jnp.bfloat16)
        labels = jnp.asarray(rng.integers(0, V, size=(T,)), jnp.int32)

        def fused(x, w):
            with _common.force_impl("pallas"):
                return linear_cross_entropy(x, w, labels,
                                            block_t=16, block_v=128)

        lf = fused(x, w)
        lg = _materialized(x, w, labels)
        np.testing.assert_allclose(np.asarray(lf), np.asarray(lg),
                                   rtol=2e-2, atol=2e-2)
        dx, dw = jax.grad(lambda x, w: jnp.sum(fused(x, w)),
                          argnums=(0, 1))(x, w)
        assert dx.dtype == jnp.bfloat16 and dw.dtype == jnp.bfloat16
        gx, gw = jax.grad(
            lambda x, w: jnp.sum(_materialized(x, w, labels)),
            argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(dx, np.float32),
                                   np.asarray(gx, np.float32),
                                   rtol=5e-2, atol=5e-2)
        np.testing.assert_allclose(np.asarray(dw, np.float32),
                                   np.asarray(gw, np.float32),
                                   rtol=5e-2, atol=5e-2)

    def test_leading_dims_and_xla_path(self, rng):
        B, S, H, V = 2, 8, 64, 130
        x = jnp.asarray(rng.normal(size=(B, S, H)) * 0.3, jnp.float32)
        w = jnp.asarray(rng.normal(size=(V, H)) * 0.3, jnp.float32)
        labels = jnp.asarray(rng.integers(0, V, size=(B, S)), jnp.int32)
        with _common.force_impl("xla"):
            lx = linear_cross_entropy(x, w, labels)
        with _common.force_impl("pallas"):
            lp = linear_cross_entropy(x, w, labels, block_t=16, block_v=64)
        assert lx.shape == (B, S)
        np.testing.assert_allclose(np.asarray(lp), np.asarray(lx),
                                   **FP32_TOL)

    def test_shape_validation(self, rng):
        x = jnp.zeros((4, 8))
        w = jnp.zeros((16, 9))
        with pytest.raises(ValueError):
            linear_cross_entropy(x, w, jnp.zeros((4,), jnp.int32))
        with pytest.raises(ValueError):
            linear_cross_entropy(x, jnp.zeros((16, 8)),
                                 jnp.zeros((4,), jnp.int32), num_classes=17)
