"""graftlint suite: per rule family a positive fixture (the hazard is
found), a negative fixture (the clean idiom is NOT flagged), and a
suppressed fixture (the allow() grammar covers it, reason mandatory) —
plus the repo-wide self-check that makes the linter a tier-1 gate: the
installed ``apex1_tpu`` package must lint clean.

Fixtures are linted in memory through ``lint_sources`` — no tmpdir, no
subprocess — so the whole suite runs in well under a second. The CLI
surface (exit codes, --json, --changed plumbing) is covered at the
bottom via the real ``tools/lint.py``.
"""

import json
import os
import subprocess
import sys
import textwrap


from apex1_tpu.lint import (RULES, canonical_rule, lint_paths,
                            lint_sources)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_lint(src, path="fix/mod.py", modname="fix.mod"):
    return lint_sources({path: (modname, textwrap.dedent(src))})


def codes(res, *, suppressed=False):
    pool = res.suppressed() if suppressed else res.unsuppressed()
    return {f.rule for f in pool}


# ---------------------------------------------------------------------------
# APX101 host-sync
# ---------------------------------------------------------------------------

HOST_POS = """
    import jax
    import numpy as np

    @jax.jit
    def step(x):
        y = np.asarray(x)           # sync 1
        jax.device_get(y)           # sync 2
        return y.item()             # sync 3

    def helper(x):                  # hot only via the call below
        return x.tolist()

    @jax.jit
    def outer(x):
        return helper(x)
"""

HOST_NEG = """
    import jax
    import jax.numpy as jnp
    import numpy as np

    @jax.jit
    def step(x):
        return jnp.sum(x * 2)

    def host_loop(step_fn, xs):
        # host code may sync freely: not jit-reachable
        out = [np.asarray(step_fn(x)) for x in xs]
        return [o.item() for o in out]

    def callback_target(x):
        return np.asarray(x)        # runs host-side by construction

    @jax.jit
    def with_callback(x):
        jax.debug.callback(callback_target, x)
        return x
"""

HOST_SUP = """
    import jax
    import numpy as np

    @jax.jit
    def step(x):
        y = np.asarray(x)  # graftlint: allow(APX101) -- warmup-only path, measured free
        return y
"""


class TestHostSync:
    def test_positive(self):
        res = run_lint(HOST_POS)
        bad = [f for f in res.unsuppressed() if f.rule == "APX101"]
        assert len(bad) == 4, [f.render() for f in res.findings]
        # propagation: the helper called from a jit body is flagged too
        assert any("helper" in f.message for f in bad)

    def test_negative(self):
        res = run_lint(HOST_NEG)
        assert "APX101" not in codes(res), \
            [f.render() for f in res.unsuppressed()]

    def test_suppressed(self):
        res = run_lint(HOST_SUP)
        assert "APX101" not in codes(res)
        sup = [f for f in res.suppressed() if f.rule == "APX101"]
        assert len(sup) == 1
        assert sup[0].reason == "warmup-only path, measured free"


# ---------------------------------------------------------------------------
# APX102 retrace
# ---------------------------------------------------------------------------

RETRACE_POS = """
    import time
    import functools
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnums=(5,))
    def bad_static(x, y):
        return x + y

    @functools.partial(jax.jit, static_argnames=("missing",))
    def bad_staticname(x, mode="a"):
        return x

    @functools.partial(jax.jit, static_argnames=("cfg",))
    def bad_default(x, cfg={"a": 1}):
        return x

    @jax.jit
    def clocky(x):
        t = time.time()
        s = jnp.sum(x)
        if s > 0:
            return x
        lab = f"sum was {s}"
        return x * t
"""

RETRACE_NEG = """
    import functools
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnums=(1,))
    def ok_static(x, mode):
        if mode == "double":        # static python value: branch is fine
            return x * 2
        return x

    @jax.jit
    def ok_body(x, n_heads):
        n = jax.lax.axis_size("dp")     # static int at trace time
        if n > 1:
            x = jax.lax.psum(x, "dp")
        s = jnp.sum(x)
        if x.shape[0] > 2:              # shapes are static
            x = x[:2]
        if n_heads is not None:         # identity check is static
            x = x * n_heads
        # traced value used the right way:
        x = jnp.where(s > 0, x, -x)
        assert x.ndim >= 1, f"rank collapsed: {x.shape}"
        return x

    @jax.jit
    def ok_raise(x):
        s = jnp.sum(x)
        if x.shape[0] == 0:
            raise ValueError(f"empty input {x.shape}")
        return s
"""

RETRACE_SUP = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def warmup_probe(x):
        s = jnp.sum(x)
        if s > 0:  # graftlint: allow(retrace) -- eager-only probe, never jitted in prod
            return x
        return -x
"""


class TestRetrace:
    def test_positive(self):
        res = run_lint(RETRACE_POS)
        msgs = [f.message for f in res.unsuppressed()
                if f.rule == "APX102"]
        assert any("out of range" in m for m in msgs), msgs
        assert any("does not name a parameter" in m for m in msgs), msgs
        assert any("mutable default" in m for m in msgs), msgs
        assert any("time.time" in m for m in msgs), msgs
        assert any("python if on traced value 's'" in m
                   for m in msgs), msgs
        assert any("f-string" in m for m in msgs), msgs

    def test_negative(self):
        res = run_lint(RETRACE_NEG)
        assert "APX102" not in codes(res), \
            [f.render() for f in res.unsuppressed()]

    def test_suppressed(self):
        res = run_lint(RETRACE_SUP)
        assert "APX102" not in codes(res)
        assert codes(res, suppressed=True) == {"APX102"}


# ---------------------------------------------------------------------------
# APX103 prng-reuse
# ---------------------------------------------------------------------------

PRNG_POS = """
    import jax

    def double_draw(key):
        a = jax.random.normal(key, (2,))
        b = jax.random.uniform(key, (2,))
        return a + b

    def split_after_draw(key):
        a = jax.random.normal(key, (2,))
        k1, k2 = jax.random.split(key)      # splitting a used key
        return a, k1, k2

    def loop_reuse(key, n):
        tot = 0.0
        for _ in range(n):
            tot = tot + jax.random.normal(key)
        return tot
"""

PRNG_NEG = """
    import jax

    def chained(key):
        key, sub = jax.random.split(key)
        a = jax.random.normal(sub, (2,))
        key, sub = jax.random.split(key)
        b = jax.random.uniform(sub, (2,))
        return a + b

    def folded(key, n):
        tot = 0.0
        for i in range(n):
            sub = jax.random.fold_in(key, i)    # sanctioned base-key use
            tot = tot + jax.random.normal(sub)
        return tot

    def fanned(key, n):
        keys = jax.random.split(key, n)
        return jax.vmap(lambda k: jax.random.normal(k, (2,)))(keys)

    def branch_draw(key, flag):
        # one draw per path: never two draws from one key on ANY path
        if flag:
            return jax.random.normal(key)
        return jax.random.uniform(key)
"""

PRNG_SUP = """
    import jax

    def identical_masks(key):
        a = jax.random.bernoulli(key, 0.5, (4,))
        b = jax.random.bernoulli(key, 0.5, (4,))  # graftlint: allow(prng-reuse) -- tied masks are the contract here
        return a, b
"""

# pltpu.prng_seed consumes int32 COUNTER SEEDS, not keys: re-seeding in
# the forward kernel and again in the backward's mask recompute is the
# in-kernel stochasticity contract (ops.stochastic), not key reuse —
# even when the seed variable is key-NAMED. Deriving the seed with ONE
# jax.random.randint draw at the call site is the sanctioned idiom.
PRNG_KERNEL_NEG = """
    import jax
    from jax.experimental.pallas import tpu as pltpu

    def kernel_reseed(seed_rng, o_ref):
        pltpu.prng_seed(seed_rng, 0)        # fwd tile stream
        a = pltpu.prng_random_bits((8, 128))
        pltpu.prng_seed(seed_rng, 1)        # bwd recompute: NOT reuse
        b = pltpu.prng_random_bits((8, 128))
        o_ref[...] = a ^ b

    def call_site(rng, fwd, bwd, x):
        seed = jax.random.randint(rng, (), 0, 2**31 - 1)  # one draw
        y = fwd(x, seed)         # the int32 seed is reused freely by
        dx = bwd(x, seed)        # the fwd and bwd kernels — not a key
        return y, dx
"""

# the exemption must NOT leak: a real key double-drawn around kernel
# PRNG calls is still flagged
PRNG_KERNEL_POS = """
    import jax
    from jax.experimental.pallas import tpu as pltpu

    def mixed(key):
        a = jax.random.normal(key, (2,))
        pltpu.prng_seed(key, 0)             # exempt — not a consumption
        b = jax.random.uniform(key, (2,))   # second REAL draw: flagged
        return a, b
"""


class TestPrngReuse:
    def test_positive(self):
        res = run_lint(PRNG_POS)
        bad = [f for f in res.unsuppressed() if f.rule == "APX103"]
        assert len(bad) == 3, [f.render() for f in res.findings]
        assert any("loop-carried" in f.message for f in bad)

    def test_negative(self):
        res = run_lint(PRNG_NEG)
        assert "APX103" not in codes(res), \
            [f.render() for f in res.unsuppressed()]

    def test_suppressed(self):
        res = run_lint(PRNG_SUP)
        assert "APX103" not in codes(res)
        sup = res.suppressed()
        assert len(sup) == 1 and "tied masks" in sup[0].reason

    def test_kernel_prng_seed_is_not_key_reuse(self):
        res = run_lint(PRNG_KERNEL_NEG)
        assert "APX103" not in codes(res), \
            [f.render() for f in res.unsuppressed()]

    def test_kernel_prng_exemption_does_not_leak(self):
        res = run_lint(PRNG_KERNEL_POS)
        bad = [f for f in res.unsuppressed() if f.rule == "APX103"]
        assert len(bad) == 1, [f.render() for f in res.findings]
        assert "jax.random.uniform" in bad[0].message


# ---------------------------------------------------------------------------
# APX104 donation
# ---------------------------------------------------------------------------

DON_POS = """
    import jax

    def make(f):
        g = jax.jit(f, donate_argnums=(0,))

        def run(state, x):
            new_state = g(state, x)
            stale = state + 1          # read after donation
            return new_state, stale
        return run
"""

DON_NEG = """
    import jax

    def make(f):
        g = jax.jit(f, donate_argnums=(0,))

        def run(state, x):
            state = g(state, x)        # rebind revives the name
            return state + 1

        def run_tuple(state, x):
            state, aux = g(state, x), x * 2
            return state, aux
        return run, run_tuple

    class Engine:
        def __init__(self, f):
            self._step = jax.jit(f, donate_argnums=(1,))

        def step(self, params, pool, tok):
            # the engine idiom: donate + rebind in ONE statement
            tok, pool = self._step(params, pool, tok)
            return tok, pool
"""

DON_SUP = """
    import jax

    def make(f):
        g = jax.jit(f, donate_argnums=(0,))

        def run(state, x):
            out = g(state, x)
            probe = state  # graftlint: allow(donation) -- CPU-only debug harness, no donation there
            return out, probe
        return run
"""


class TestDonation:
    def test_positive(self):
        res = run_lint(DON_POS)
        bad = [f for f in res.unsuppressed() if f.rule == "APX104"]
        assert len(bad) == 1, [f.render() for f in res.findings]
        assert "'state'" in bad[0].message

    def test_negative(self):
        res = run_lint(DON_NEG)
        assert "APX104" not in codes(res), \
            [f.render() for f in res.unsuppressed()]

    def test_suppressed(self):
        res = run_lint(DON_SUP)
        assert "APX104" not in codes(res)
        assert codes(res, suppressed=True) == {"APX104"}


# ---------------------------------------------------------------------------
# APX105 compat-spelling
# ---------------------------------------------------------------------------

COMPAT_POS = """
    import jax
    from jax.experimental.shard_map import shard_map

    def apply(mesh, specs, x):
        f = jax.shard_map(lambda a: a, mesh=mesh, in_specs=specs,
                          out_specs=specs, check_rep=False)
        vma = jax.typeof(x).vma
        return f(x), vma
"""

COMPAT_NEG = """
    import jax
    import apex1_tpu  # installs the compat bridge

    def apply(mesh, specs, x):
        f = jax.shard_map(lambda a: a, mesh=mesh, in_specs=specs,
                          out_specs=specs, check_vma=False)
        with jax.set_mesh(mesh):
            return f(x)
"""

COMPAT_SUP = """
    import jax

    def probe(x):
        return jax.typeof(x)  # graftlint: allow(compat-spelling) -- version probe, guarded by caller
"""


class TestCompatSpelling:
    def test_positive(self):
        res = run_lint(COMPAT_POS, path="tools/fix.py",
                       modname="tools.fix")
        msgs = [f.message for f in res.unsuppressed()
                if f.rule == "APX105"]
        assert any("legacy" in m for m in msgs), msgs
        assert any("never imports apex1_tpu" in m for m in msgs), msgs
        assert any("check_rep" in m for m in msgs), msgs
        assert any("jax.typeof" in m for m in msgs), msgs

    def test_negative(self):
        res = run_lint(COMPAT_NEG, path="tools/fix.py",
                       modname="tools.fix")
        assert "APX105" not in codes(res), \
            [f.render() for f in res.unsuppressed()]

    def test_negative_inside_package(self):
        # package modules get the bridge via __init__: no import needed
        src = """
            import jax

            def apply(mesh, specs, x):
                return jax.shard_map(lambda a: a, mesh=mesh,
                                     in_specs=specs, out_specs=specs)(x)
        """
        res = run_lint(src, path="apex1_tpu/parallel/fix.py",
                       modname="apex1_tpu.parallel.fix")
        msgs = [f.message for f in res.unsuppressed()
                if f.rule == "APX105"]
        assert not msgs, msgs

    def test_bridge_modules_exempt(self):
        src = """
            import jax

            def shard_map(f=None, **kw):
                kw.pop("check_vma", None)
                kw["check_rep"] = False
                return jax.experimental.shard_map.shard_map(f, **kw)
        """
        res = run_lint(src, path="apex1_tpu/__init__.py",
                       modname="apex1_tpu")
        assert "APX105" not in codes(res)

    def test_suppressed(self):
        res = run_lint(COMPAT_SUP, path="tools/fix.py",
                       modname="tools.fix")
        assert "APX105" not in codes(res)
        assert codes(res, suppressed=True) == {"APX105"}


# ---------------------------------------------------------------------------
# suppression grammar
# ---------------------------------------------------------------------------

class TestSuppressionGrammar:
    def test_reason_is_mandatory(self):
        src = """
            import jax
            import numpy as np

            @jax.jit
            def step(x):
                return np.asarray(x)  # graftlint: allow(APX101)
        """
        res = run_lint(src)
        assert codes(res) == {"APX000", "APX101"}, \
            [f.render() for f in res.unsuppressed()]

    def test_unknown_rule_is_flagged(self):
        src = "x = 1  # graftlint: allow(APX999) -- whatever\n"
        res = run_lint(src)
        assert codes(res) == {"APX000"}

    def test_standalone_comment_covers_next_line(self):
        src = """
            import jax
            import numpy as np

            @jax.jit
            def step(x):
                # graftlint: allow(host-sync) -- covers the line below
                y = np.asarray(x)
                return y
        """
        res = run_lint(src)
        assert "APX101" not in codes(res)
        assert codes(res, suppressed=True) == {"APX101"}

    def test_multi_rule_allow(self):
        src = """
            import time
            import jax
            import numpy as np

            @jax.jit
            def step(x):
                return np.asarray(x) * time.time()  # graftlint: allow(APX101, APX102) -- fixture
        """
        res = run_lint(src)
        assert not res.unsuppressed(), \
            [f.render() for f in res.unsuppressed()]
        assert codes(res, suppressed=True) == {"APX101", "APX102"}

    def test_suppression_is_rule_specific(self):
        src = """
            import time
            import jax
            import numpy as np

            @jax.jit
            def step(x):
                return np.asarray(x) * time.time()  # graftlint: allow(APX101) -- only the sync
        """
        res = run_lint(src)
        assert codes(res) == {"APX102"}

    def test_marker_requires_reason(self):
        src = """
            def f(x):  # graftlint: hot
                return x
        """
        res = run_lint(src)
        assert codes(res) == {"APX000"}

    def test_standalone_directive_skips_comment_lines(self):
        # a multi-line marker comment must bind to the next CODE line
        # (the def), not the next comment line — the amp train_step
        # marker regression
        src = """
            import numpy as np

            # graftlint: hot -- first line of the marker comment,
            # which continues onto a second comment line
            def traced_by_contract(x):
                return np.asarray(x)
        """
        res = run_lint(src)
        assert codes(res) == {"APX101"}, \
            [f.render() for f in res.findings]

    def test_detached_marker_is_a_finding(self):
        # a marker binding to no function would silently change gate
        # coverage: fail loudly instead
        src = """
            x = 1
            # graftlint: hot -- nothing below is a def
            y = 2
        """
        res = run_lint(src)
        assert codes(res) == {"APX000"}
        assert any("detached" in f.message for f in res.unsuppressed())

    def test_marker_binds_to_innermost_function(self):
        # when a nested def is the enclosing function's first
        # statement both spans contain the def line; only the nested
        # function is the marker's subject — the enclosing factory may
        # do host work freely
        src = """
            import numpy as np

            def make(cfg):
                # graftlint: hot -- returned for the caller to jit
                def step(x):
                    return x
                host_probe = np.asarray(cfg).item()
                return step, host_probe
        """
        res = run_lint(src)
        assert not res.unsuppressed(), \
            [f.render() for f in res.unsuppressed()]

    def test_hot_marker_forces_reachability(self):
        src = """
            import numpy as np

            # graftlint: hot -- returned for the caller to jit
            def traced_by_contract(x):
                return np.asarray(x)
        """
        res = run_lint(src)
        assert codes(res) == {"APX101"}

    def test_cold_marker_severs_reachability(self):
        src = """
            import jax
            import numpy as np

            # graftlint: cold -- only ever run under pure_callback
            def host_side(x):
                return np.asarray(x)

            @jax.jit
            def step(x):
                return host_side(x)
        """
        res = run_lint(src)
        assert "APX101" not in codes(res)

    def test_canonical_rule_names(self):
        assert canonical_rule("APX103") == "APX103"
        assert canonical_rule("prng-reuse") == "APX103"
        assert canonical_rule("HOST-SYNC") == "APX101"  # case-blind
        assert canonical_rule("apx101") == "APX101"
        assert canonical_rule("nope") is None

    def test_syntax_error_is_reported_not_crashed(self):
        res = run_lint("def f(:\n")
        assert codes(res) == {"APX001"}


# ---------------------------------------------------------------------------
# the gate: repo-wide self-check (tier-1)
# ---------------------------------------------------------------------------

class TestRepoSelfCheck:
    def test_repo_self_check(self):
        """The installed apex1_tpu package (plus tools/ and examples/)
        lints clean: zero unsuppressed findings, and every suppression
        that exists carries a reason. THIS test is what makes graftlint
        a gate — a hazard introduced anywhere in the package fails
        tier-1, not just check_all."""
        res = lint_paths(["apex1_tpu", "tools", "examples"], root=REPO)
        bad = res.unsuppressed()
        assert not bad, "unsuppressed graftlint findings:\n" + \
            "\n".join(f.render() for f in bad)
        for f in res.suppressed():
            assert f.reason and f.reason.strip(), f.render()

    def test_rules_registered(self):
        assert [r.code for r in RULES] == [
            "APX101", "APX102", "APX103", "APX104", "APX105"]


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------

class TestCli:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "lint.py"),
             *args],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})

    def test_clean_repo_exits_zero_and_json(self):
        p = self._run("--json", "apex1_tpu/lint")
        assert p.returncode == 0, p.stdout + p.stderr
        doc = json.loads(p.stdout)
        assert doc["ok"] is True
        assert set(doc["rules"]) == {"APX101", "APX102", "APX103",
                                     "APX104", "APX105"}

    def test_every_rule_positive_exits_nonzero(self, tmp_path):
        """One subprocess over a directory holding every rule family's
        positive fixture: the CLI must exit 1 and report all five
        codes. (One spawn, not five — each CLI start pays the jax
        import; the per-rule finding behavior is covered in-memory
        above.)"""
        d = tmp_path / "tools"      # tools/-like modname for compat
        d.mkdir()
        for name, fixture in [("host.py", HOST_POS),
                              ("retrace.py", RETRACE_POS),
                              ("prng.py", PRNG_POS),
                              ("don.py", DON_POS),
                              ("compat.py", COMPAT_POS)]:
            (d / name).write_text(textwrap.dedent(fixture))
        p = self._run(str(d))
        assert p.returncode == 1, p.stdout + p.stderr
        for rule in ("APX101", "APX102", "APX103", "APX104", "APX105"):
            assert rule in p.stdout, (rule, p.stdout)

    def test_nonexistent_path_fails_closed(self):
        # a typoed path in a CI job must not read as a passing gate
        p = self._run("apex1_tpu/no_such_dir_xyz")
        assert p.returncode == 2, p.stdout + p.stderr
        assert "no such path" in p.stderr

    def test_baseline_is_banked_and_clean(self):
        path = os.path.join(REPO, "perf_results", "lint_baseline.json")
        assert os.path.exists(path), \
            "perf_results/lint_baseline.json missing (bank it with " \
            "`python tools/lint.py --kernels --json > " \
            "perf_results/lint_baseline.json`)"
        doc = json.load(open(path))
        assert doc["ok"] is True
        assert doc["counts"]["unsuppressed"] == 0
