"""M0 tests: mesh construction (≙ tests/L0/run_transformer/test_parallel_state.py
group math), precision policy (≙ tests/L0/run_amp cast tests), loss scaling
(≙ run_amp loss-scale tests), pytree/flat utilities."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.core import mesh as mesh_lib
from apex1_tpu.core import policy as policy_lib
from apex1_tpu.core import loss_scale as ls
from apex1_tpu.core import pytree as pt
from apex1_tpu.core.mesh import MeshConfig, make_mesh


class TestMesh:
    def test_resolve_wildcard(self):
        cfg = MeshConfig(dp=-1, tp=2).resolve(8)
        assert cfg.dp == 4 and cfg.tp == 2 and cfg.pp == 1
        assert cfg.shape == (4, 1, 1, 1, 1, 2)

    def test_resolve_exact(self):
        cfg = MeshConfig(dp=2, pp=2, tp=2).resolve(8)
        assert cfg.shape == (2, 1, 2, 1, 1, 2)

    def test_resolve_errors(self):
        with pytest.raises(ValueError):
            MeshConfig(dp=3, tp=2).resolve(8)
        with pytest.raises(ValueError):
            MeshConfig(dp=-1, tp=-1).resolve(8)

    def test_make_mesh_axes(self, devices):
        m = make_mesh(dp=2, tp=4)
        assert m.shape == {"dp": 2, "fsdp": 1, "pp": 1, "cp": 1,
                           "ep": 1, "tp": 4}
        assert mesh_lib.data_parallel_size(m) == 2

    def test_tp_ranks_contiguous(self, devices):
        # Megatron invariant: TP group = contiguous device ids (innermost
        # axis). parallel_state.initialize_model_parallel docstring contract.
        m = make_mesh(dp=2, tp=4)
        arr = np.asarray(m.devices).reshape(2, 4)
        ids = [[d.id for d in row] for row in arr]
        for row in ids:
            assert row == sorted(row)
            assert row[-1] - row[0] == 3

    def test_hybrid_mesh_dcn_dp_outer(self, devices):
        """Multi-slice mesh: each dp index must live on ONE slice so the
        dp gradient reduction decomposes into intra-slice ICI + one DCN
        exchange (SURVEY §5.8 fabric mapping)."""

        class FakeDev:
            def __init__(self, d, slice_index, i):
                self.slice_index = slice_index
                self.id = i
                self.process_index = slice_index
                self.platform = d.platform
                self.device_kind = d.device_kind

        fakes = [FakeDev(devices[i], i // 4, i) for i in range(8)]
        m = mesh_lib.make_hybrid_mesh(MeshConfig(dp=1, pp=2, tp=2),
                                      dcn_dp=2, devices=fakes)
        assert m.shape == {"dp": 2, "fsdp": 1, "pp": 2, "cp": 1,
                           "ep": 1, "tp": 2}
        arr = np.asarray(m.devices)
        for a in range(2):
            slices = {d.slice_index for d in arr[a].ravel()}
            assert slices == {a}, f"dp index {a} spans slices {slices}"

    def test_hybrid_mesh_granule_ids_runnable(self, devices):
        """granule_ids builds the slice-major dp order from REAL devices
        (virtual CPU devices carry no slice_index), so the hybrid mesh is
        runnable — a psum over the DCN-outer dp axis must execute."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as Ps

        devs = list(devices)[:8]
        m = mesh_lib.make_hybrid_mesh(
            MeshConfig(dp=1, pp=2, tp=2), dcn_dp=2, devices=devs,
            granule_ids=[i // 4 for i in range(8)])
        assert m.shape["dp"] == 2
        arr = np.asarray(m.devices)
        dp_ax = mesh_lib.MESH_AXES.index("dp")
        for a in range(2):
            ids = {d.id for d in np.take(arr, a, axis=dp_ax).ravel()}
            want = {d.id for d in devs[a * 4:(a + 1) * 4]}
            assert ids == want, f"dp index {a} not slice-major: {ids}"

        def f(x):
            return jax.lax.psum(x, "dp")

        out = jax.jit(jax.shard_map(
            f, mesh=m, in_specs=Ps("dp"), out_specs=Ps()))(
                jnp.arange(2, dtype=jnp.float32))
        assert float(out[0]) == 1.0  # 0 + 1 across the DCN-outer axis

        with pytest.raises(ValueError, match="granule"):
            mesh_lib.make_hybrid_mesh(
                MeshConfig(dp=1, pp=2, tp=2), dcn_dp=2, devices=devs,
                granule_ids=[0] * 8)

    def test_hybrid_mesh_single_slice_delegates(self, devices):
        m = mesh_lib.make_hybrid_mesh(dcn_dp=1, dp=2, tp=4)
        assert m.shape["dp"] == 2 and m.shape["tp"] == 4
        with pytest.raises(ValueError):
            mesh_lib.make_hybrid_mesh(dcn_dp=3, dp=1,
                                      devices=list(devices))

    def test_resource_spec(self):
        res = mesh_lib.MeshResource()
        spec = res.spec("batch", None, "heads")
        assert spec == jax.sharding.PartitionSpec(("dp", "fsdp"), None, "tp")

    def test_shard_batch(self, devices):
        m = make_mesh(dp=8)
        x = np.arange(64, dtype=np.float32).reshape(8, 8)
        y = mesh_lib.shard_batch(m, {"x": x})["x"]
        assert y.sharding.spec == jax.sharding.PartitionSpec(("dp", "fsdp"))
        np.testing.assert_array_equal(np.asarray(y), x)


class TestPolicy:
    def test_presets(self):
        o2 = policy_lib.get_policy("O2")
        assert o2.param_dtype == jnp.float32
        assert o2.compute_dtype == jnp.bfloat16
        assert o2.is_mixed and not o2.uses_loss_scaling
        o0 = policy_lib.get_policy("O0")
        assert not o0.is_mixed
        fp16 = policy_lib.get_policy("O2_fp16")
        assert fp16.loss_scale == "dynamic"

    def test_overrides(self):
        p = policy_lib.get_policy("O1", loss_scale=128.0,
                                  keep_norms_fp32=False)
        assert p.loss_scale == 128.0 and not p.keep_norms_fp32

    def test_unknown_raises(self):
        with pytest.raises(ValueError):
            policy_lib.get_policy("O9")

    def test_casts_skip_ints(self):
        p = policy_lib.get_policy("O1")
        tree = {"w": jnp.ones((2,), jnp.float32), "i": jnp.ones((2,), jnp.int32)}
        out = p.cast_to_compute(tree)
        assert out["w"].dtype == jnp.bfloat16
        assert out["i"].dtype == jnp.int32

    def test_cast_dtype_under_jit(self):
        # ≙ run_amp/test_basic_casts.py, but asserted on the traced program.
        p = policy_lib.get_policy("O1")

        def f(w, x):
            return x @ p.cast_to_compute(w)

        out = jax.eval_shape(f, jnp.ones((4, 4)), jnp.ones((2, 4), jnp.bfloat16))
        assert out.dtype == jnp.bfloat16


class TestLossScale:
    def test_dynamic_state_machine(self):
        # ≙ scaler.py semantics: ÷2 on overflow, ×2 after growth_interval.
        d = ls.DynamicLossScale(init_scale=2.0 ** 8, growth_interval=4)
        s = d.init()
        assert float(s.scale) == 256.0
        s = d.adjust(s, jnp.bool_(False))
        assert float(s.scale) == 128.0 and int(s.overflow_count) == 1
        assert int(s.growth_count) == 0
        for i in range(3):
            s = d.adjust(s, jnp.bool_(True))
            assert float(s.scale) == 128.0
        s = d.adjust(s, jnp.bool_(True))  # 4th clean step → grow
        assert float(s.scale) == 256.0 and int(s.growth_count) == 0

    def test_clamps(self):
        d = ls.DynamicLossScale(init_scale=2.0, min_loss_scale=1.0,
                                growth_interval=1, max_loss_scale=4.0)
        s = d.init()
        s = d.adjust(s, jnp.bool_(False))
        s = d.adjust(s, jnp.bool_(False))
        assert float(s.scale) == 1.0  # clamped at min
        for _ in range(5):
            s = d.adjust(s, jnp.bool_(True))
        assert float(s.scale) == 4.0  # clamped at max

    def test_all_finite(self):
        good = {"a": jnp.ones(3), "b": jnp.zeros(2)}
        bad = {"a": jnp.ones(3), "b": jnp.array([1.0, jnp.inf])}
        nan = {"a": jnp.array([jnp.nan]), "b": jnp.zeros(2)}
        assert bool(ls.all_finite(good))
        assert not bool(ls.all_finite(bad))
        assert not bool(ls.all_finite(nan))

    def test_scale_unscale_roundtrip(self):
        st = ls.StaticLossScale(1024.0)
        s = st.init()
        g = {"w": jnp.full((4,), 2.0, jnp.float32)}
        scaled = st.scale(jnp.float32(3.0), s)
        assert float(scaled) == 3.0 * 1024.0
        back = st.unscale({"w": g["w"] * 1024.0}, s)
        np.testing.assert_allclose(np.asarray(back["w"]), 2.0, rtol=1e-6)

    def test_select_tree_skip(self):
        old = {"w": jnp.zeros(2)}
        new = {"w": jnp.ones(2)}
        kept = ls.select_tree(jnp.bool_(False), new, old)
        np.testing.assert_array_equal(np.asarray(kept["w"]), 0.0)

    def test_jittable(self):
        d = ls.DynamicLossScale(growth_interval=2)

        @jax.jit
        def step(state, finite):
            return d.adjust(state, finite)

        s = d.init()
        s = step(s, jnp.bool_(True))
        s = step(s, jnp.bool_(False))
        assert float(s.scale) == 2.0 ** 15


class TestPytree:
    def test_flatten_roundtrip(self):
        tree = {"a": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
                "b": {"c": jnp.ones((4,), jnp.bfloat16)}}
        flat, unflatten = pt.flatten_tree(tree)
        assert flat.shape == (10,)
        back = unflatten(flat)
        np.testing.assert_array_equal(np.asarray(back["a"]),
                                      np.asarray(tree["a"]))
        assert back["b"]["c"].dtype == jnp.bfloat16

    def test_global_norm(self):
        tree = {"a": jnp.full((3,), 2.0), "b": jnp.full((4,), 1.0)}
        g = pt.global_norm(tree)
        np.testing.assert_allclose(float(g), np.sqrt(3 * 4 + 4), rtol=1e-6)
        g2, per = pt.global_norm(tree, per_leaf=True)
        assert len(per) == 2
        np.testing.assert_allclose(float(per[1]), 2.0, rtol=1e-6)

    def test_named_tree_map(self):
        tree = {"layer": {"w": jnp.ones(2), "b": jnp.ones(1)}}
        names = []
        pt.named_tree_map(lambda n, x: names.append(n) or x, tree)
        assert names == ["layer/b", "layer/w"] or names == ["layer/w", "layer/b"]


class TestHysteresis:
    """``update_scale_hysteresis.cu`` semantics: the scale halves only when
    the hysteresis budget is exhausted by overflows; clean steps don't
    refill the budget (only an actual backoff does)."""

    def test_halves_only_after_budget_exhausted(self):
        import jax.numpy as jnp
        from apex1_tpu.core.loss_scale import DynamicLossScale
        sc = DynamicLossScale(init_scale=1024.0, hysteresis=3,
                              growth_interval=4)
        st = sc.init()
        st = sc.adjust(st, jnp.bool_(False))      # overflow 1
        assert float(st.scale) == 1024.0 and int(st.hysteresis_left) == 2
        st = sc.adjust(st, jnp.bool_(True))       # clean: budget unchanged
        assert int(st.hysteresis_left) == 2
        st = sc.adjust(st, jnp.bool_(False))      # overflow 2
        assert float(st.scale) == 1024.0 and int(st.hysteresis_left) == 1
        st = sc.adjust(st, jnp.bool_(False))      # overflow 3 -> halve
        assert float(st.scale) == 512.0
        # exhausted budget does NOT refill on backoff (reference: keeps
        # halving on every overflow until growth refills it)
        assert int(st.hysteresis_left) == 0
        st = sc.adjust(st, jnp.bool_(False))      # overflow 4 -> halve again
        assert float(st.scale) == 256.0
        assert int(st.overflow_count) == 4
        # 4 clean steps -> growth fires: scale x2 AND budget refills
        for _ in range(4):
            st = sc.adjust(st, jnp.bool_(True))
        assert float(st.scale) == 512.0
        assert int(st.hysteresis_left) == 3

    def test_default_hysteresis_is_classic(self):
        import jax.numpy as jnp
        from apex1_tpu.core.loss_scale import DynamicLossScale
        sc = DynamicLossScale(init_scale=64.0)
        st = sc.adjust(sc.init(), jnp.bool_(False))
        assert float(st.scale) == 32.0


class TestCapability:
    """≙ the reference's setup.py sm-arch gating, as a runtime data table
    (SURVEY.md §2 #62, §5.6)."""

    def test_table_lookup_and_detection(self):
        from apex1_tpu.core import capability as cap
        c = cap.get_capability("v5e")
        assert c.mxu == (128, 128) and not c.sparsecore
        assert cap.get_capability("v5p").sparsecore
        assert cap.vmem_budget("v5p") > cap.vmem_budget("v3")

    def test_env_detection(self, monkeypatch):
        from apex1_tpu.core import capability as cap
        monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "v5p")
        cap.detect_generation.cache_clear()
        try:
            assert cap.detect_generation() == "v5p"
            assert cap.get_capability().generation == "v5p"
        finally:
            cap.detect_generation.cache_clear()

    def test_require_gates(self):
        import pytest as _pytest

        from apex1_tpu.core import capability as cap
        cap.require("sparsecore", generation="v5p")
        with _pytest.raises(cap.CapabilityError):
            cap.require("sparsecore", generation="v5e")
        with _pytest.raises(cap.CapabilityError):
            cap.require("ici_3d", generation="v5e")
        with _pytest.raises(ValueError):
            cap.require("warp_specialization", generation="v5e")

    def test_unknown_generation(self):
        import pytest as _pytest

        from apex1_tpu.core import capability as cap
        with _pytest.raises(ValueError):
            cap.get_capability("v99")


class TestO1OpRegistration:
    """≙ amp.half_function / float_function / promote_function — the O1
    op-list extension surface (SURVEY #3), as policy-bound wrappers."""

    def test_casts(self):
        import jax
        import jax.numpy as jnp

        from apex1_tpu.core.policy import get_policy
        p = get_policy("O1")  # bf16 compute
        dtype_of = lambda f, *a: jax.eval_shape(f, *a).dtype
        x32 = jnp.zeros((4, 4), jnp.float32)
        xb = jnp.zeros((4, 4), jnp.bfloat16)
        matmul = lambda a, b: a @ b
        assert dtype_of(p.half_function(matmul), x32, x32) == jnp.bfloat16
        assert dtype_of(p.float_function(matmul), xb, xb) == jnp.float32
        # promote-widest: bf16 + fp32 -> fp32
        assert dtype_of(p.promote_function(matmul), xb, x32) == jnp.float32
        assert dtype_of(p.promote_function(matmul), xb, xb) == jnp.bfloat16
        # non-float args pass through untouched
        take = lambda a, i: a[i]
        got = p.half_function(take)(x32, jnp.int32(1))
        assert got.dtype == jnp.bfloat16

    def test_module_level_and_bound(self):
        import jax.numpy as jnp

        from apex1_tpu import amp as amp_lib
        from apex1_tpu.optim import fused_adam
        f = amp_lib.float_function(lambda x: x)
        assert f(jnp.zeros((2,), jnp.bfloat16)).dtype == jnp.float32
        # bound form follows the Amp's OWN policy (fp16 here, not bf16)
        a = amp_lib.Amp(tx=fused_adam(1e-3), opt_level="O1_fp16")
        g = a.half_function(lambda x: x)
        assert g(jnp.zeros((2,), jnp.float32)).dtype == jnp.float16
        h = amp_lib.half_function(lambda x: x, "O1_fp16")
        assert h(jnp.zeros((2,), jnp.float32)).dtype == jnp.float16
