"""`apex1_tpu.serving` tests — the continuous-batching engine must be
INVISIBLE in the tokens: requests joining and leaving mid-flight
produce output token-identical to a solo `models.generate` run of each
request, with exactly TWO traced executables for the whole workload
(the compilation-count hook `Engine.trace_counts`). Plus the control
plane: backpressure rejection, deadline eviction freeing the slot,
cancellation, prefix-page refcounts never freeing a live page, and the
scheduler/pool/feeder units."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.core.policy import get_policy
from apex1_tpu.models.generate import generate, gpt2_decoder
from apex1_tpu.models.gpt2 import GPT2, GPT2Config
from apex1_tpu.runtime import RequestFeeder
from apex1_tpu.serving import (Backpressure, Engine, EngineConfig, KVPool,
                               Request, Scheduler)


@pytest.fixture(scope="module")
def tiny():
    """Tiny fp32 GPT-2 + its decoder pair + a solo-generate oracle."""
    cfg = GPT2Config.tiny(policy=get_policy("O0"), max_seq_len=64)
    model = GPT2(cfg)
    rng = np.random.default_rng(11)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 7)), jnp.int32)
    params = model.init(jax.random.key(0), prompt)["params"]
    apply_fn, make_cache = gpt2_decoder(model)

    def solo(tokens, n_new):
        cache = make_cache(1, len(tokens) + n_new)
        return np.asarray(generate(
            apply_fn, params, jnp.asarray([tokens], jnp.int32),
            max_new_tokens=n_new, cache=cache,
            vocab_size=cfg.vocab_size))[0]

    return cfg, params, apply_fn, make_cache, solo


def _engine(tiny, **kw):
    cfg, params, apply_fn, make_cache, _ = tiny
    ekw = dict(max_slots=3, max_len=48, prefill_chunk=4,
               vocab_size=cfg.vocab_size)
    ekw.update(kw)
    return Engine(apply_fn, make_cache, params, EngineConfig(**ekw))


class TestContinuousBatching:
    def test_staggered_join_leave_token_identical_two_executables(
            self, tiny, rng):
        """The acceptance workload: more requests than slots, mixed
        prompt lengths (crossing chunk boundaries), mixed output
        lengths, arrivals staggered across live decode steps — every
        completed request must match its solo `generate` run and the
        engine must have traced exactly its two executables."""
        cfg, _, _, _, solo = tiny
        eng = _engine(tiny)
        lens = [3, 7, 5, 9, 4, 6]          # 3,5 < chunk=4 <= 5,7,9
        news = [6, 5, 7, 4, 6, 5]
        prompts = [rng.integers(0, cfg.vocab_size, (L,)).tolist()
                   for L in lens]
        ids = [eng.submit(p, max_new_tokens=n)
               for p, n in zip(prompts[:3], news[:3])]
        eng.step()                          # 3 in flight
        ids.append(eng.submit(prompts[3], max_new_tokens=news[3]))
        eng.step()                          # joins as slots free
        ids += [eng.submit(p, max_new_tokens=n)
                for p, n in zip(prompts[4:], news[4:])]
        eng.run(max_steps=200)
        for p, n, rid in zip(prompts, news, ids):
            res = eng.results[rid]
            assert res.status == "done"
            np.testing.assert_array_equal(res.tokens, solo(p, n))
        # the compilation-count hook: requests of 6 shapes joined and
        # left; the engine must not have retraced for any of it
        assert eng.trace_counts == {"prefill": 1, "decode": 1}
        # with 6 requests over 3 slots, slots were genuinely reused
        assert eng.metrics.summary()["done"] == 6

    def test_eos_early_stop_matches_solo_truncation(self, tiny, rng):
        cfg, _, _, _, solo = tiny
        prompt = rng.integers(0, cfg.vocab_size, (6,)).tolist()
        full = solo(prompt, 8)
        eos = int(full[3])                  # an id greedy decoding emits
        eng = _engine(tiny, eos_id=eos)
        rid = eng.submit(prompt, max_new_tokens=8)
        eng.run(max_steps=50)
        res = eng.results[rid]
        assert res.status == "done" and res.reason == "eos"
        want = full[:list(full).index(eos) + 1]
        np.testing.assert_array_equal(res.tokens, want)

    def test_prefix_sharing_token_identical_and_counted(self, tiny, rng):
        """Sharers of a system prompt must decode exactly as if the
        full (prefix + own) prompt had been prefilled solo, while the
        prefix's K/V is computed once (page hits prove the reuse)."""
        cfg, _, _, _, solo = tiny
        eng = _engine(tiny, max_slots=2)
        sysp = tuple(rng.integers(0, cfg.vocab_size, (9,)).tolist())
        owns = [rng.integers(0, cfg.vocab_size, (L,)).tolist()
                for L in (4, 6, 3)]
        ids = [eng.submit(o, max_new_tokens=5, prefix=sysp) for o in owns]
        eng.run(max_steps=100)
        for o, rid in zip(owns, ids):
            np.testing.assert_array_equal(eng.results[rid].tokens,
                                          solo(list(sysp) + o, 5))
        (stats,) = eng.kv.prefix_stats().values()
        assert stats["hits"] == 3 and stats["refcount"] == 0
        assert eng.trace_counts == {"prefill": 1, "decode": 1}

    def test_backpressure_rejection_with_reason(self, tiny, rng):
        cfg = tiny[0]
        eng = _engine(tiny, max_slots=1, max_queue=2)
        p = rng.integers(0, cfg.vocab_size, (4,)).tolist()
        eng.submit(p, max_new_tokens=4)
        eng.submit(p, max_new_tokens=4)
        with pytest.raises(Backpressure, match="queue full"):
            eng.submit(p, max_new_tokens=4)
        assert eng.metrics.summary()["rejected"] == 1
        eng.run(max_steps=50)               # the accepted two still finish
        assert eng.metrics.summary()["done"] == 2

    def test_oversized_request_is_contract_error_not_backpressure(
            self, tiny):
        eng = _engine(tiny, max_len=16)
        with pytest.raises(ValueError, match="cache positions"):
            eng.submit(list(range(10)), max_new_tokens=10)

    def test_deadline_eviction_frees_slot_for_next_request(self, tiny,
                                                           rng):
        """A request whose deadline passes mid-decode is evicted with
        its partial output, and the freed slot serves the next request
        to completion."""
        cfg, _, _, _, solo = tiny
        eng = _engine(tiny, max_slots=1)
        p1 = rng.integers(0, cfg.vocab_size, (5,)).tolist()
        p2 = rng.integers(0, cfg.vocab_size, (6,)).tolist()
        r1 = eng.submit(p1, max_new_tokens=30,
                        deadline=time.monotonic() + 0.05)
        eng.step()                          # admitted, decoding
        assert eng.n_active == 1
        time.sleep(0.06)                    # let the deadline lapse
        eng.step()                          # eviction observed here
        res1 = eng.results[r1]
        assert res1.status == "evicted" and "deadline" in res1.reason
        assert 0 < res1.tokens.size < 30    # partial output survives
        assert eng.n_active == 0 and eng.kv.n_free == 1
        r2 = eng.submit(p2, max_new_tokens=5)
        eng.run(max_steps=50)
        assert eng.results[r2].status == "done"
        np.testing.assert_array_equal(eng.results[r2].tokens, solo(p2, 5))

    def test_cancel_queued_and_running(self, tiny, rng):
        cfg = tiny[0]
        eng = _engine(tiny, max_slots=1)
        p = rng.integers(0, cfg.vocab_size, (4,)).tolist()
        r1 = eng.submit(p, max_new_tokens=20)
        r2 = eng.submit(p, max_new_tokens=4)
        eng.step()                          # r1 running, r2 queued
        assert eng.cancel(r2)               # queued: removed outright
        assert eng.cancel(r1)               # running: retires next step
        eng.step()
        assert eng.results[r2].status == "cancelled"
        assert eng.results[r1].status == "cancelled"
        assert eng.results[r1].tokens.size > 0
        assert eng.kv.n_free == 1
        assert not eng.cancel(r1)           # already terminal

    def test_tail_chunk_pad_never_clamps_past_max_len(self, tiny, rng):
        """A request whose FINAL right-padded prefill chunk extends past
        max_len must still decode token-identically: without the pool's
        prefill_chunk-1 slack, dynamic_update_slice would clamp the
        chunk's start and silently shift its K/V onto earlier positions
        (review finding)."""
        cfg, _, _, _, solo = tiny
        # max_len=16, chunk=8, 1-token prefix: own chunks start at 1
        # and 9, so the padded second chunk writes [9, 17) — one past
        # max_len. total_len = 1+13+3-1 = 16 <= 16 is admissible, so
        # only the pool's slack keeps the write from being clamped
        eng = _engine(tiny, max_slots=1, max_len=16, prefill_chunk=8)
        # the invariant that prevents the clamp: the pool allocates
        # prefill_chunk-1 positions past the usable max_len, so every
        # padded chunk write [start, start+chunk) fits
        s_max = jax.tree_util.tree_leaves(eng.kv.cache)[0].shape[2]
        assert s_max == 16 + 8 - 1
        sysp = tuple(rng.integers(0, cfg.vocab_size, (1,)).tolist())
        p = rng.integers(0, cfg.vocab_size, (13,)).tolist()
        rid = eng.submit(p, max_new_tokens=3, prefix=sysp)
        eng.run(max_steps=30)
        res = eng.results[rid]
        assert res.status == "done"
        np.testing.assert_array_equal(res.tokens,
                                      solo(list(sysp) + p, 3))

    def test_rejection_reason_reflects_cause(self, tiny, rng):
        """The rejected metrics event must carry the scheduler's actual
        reason, not a hardcoded 'queue full' (review finding)."""
        cfg = tiny[0]
        eng = _engine(tiny)
        p = rng.integers(0, cfg.vocab_size, (4,)).tolist()
        with pytest.raises(Backpressure):
            eng.submit(p, max_new_tokens=4,
                       deadline=time.monotonic() - 1.0)
        (rec,) = eng.metrics.records.values()
        assert rec.status == "rejected"
        assert "deadline" in rec.reason

    def test_metrics_lifecycle_and_ttft(self, tiny, rng):
        cfg = tiny[0]
        eng = _engine(tiny)
        p = rng.integers(0, cfg.vocab_size, (5,)).tolist()
        rid = eng.submit(p, max_new_tokens=4)
        eng.run(max_steps=50)
        rec = eng.metrics.records[rid]
        assert rec.status == "done"
        assert (rec.t_queued <= rec.t_prefill <= rec.t_first_token
                <= rec.t_done)
        assert rec.ttft is not None and rec.ttft >= 0
        s = eng.metrics.summary()
        assert s["generated_tokens"] == 4
        assert 0 < s["mean_occupancy"] <= 1
        assert "ttft_p50_ms" in s and "ttft_p99_ms" in s


class TestPrefixRefcounts:
    def test_refcount_never_frees_live_page(self, tiny, rng):
        cfg = tiny[0]
        eng = _engine(tiny, max_slots=2)
        sysp = tuple(rng.integers(0, cfg.vocab_size, (6,)).tolist())
        own = rng.integers(0, cfg.vocab_size, (3,)).tolist()
        eng.submit(own, max_new_tokens=10, prefix=sysp)
        eng.submit(own, max_new_tokens=10, prefix=sysp)
        eng.step()                          # both admitted, page live
        (stats,) = eng.kv.prefix_stats().values()
        assert stats["refcount"] == 2
        assert eng.kv.evict_prefix(sysp) is False      # refused
        with pytest.raises(RuntimeError, match="live page"):
            eng.kv.evict_prefix(sysp, force=True)      # loud, still no
        assert eng.kv.has_prefix(sysp)
        eng.run(max_steps=100)              # both retire -> refcount 0
        (stats,) = eng.kv.prefix_stats().values()
        assert stats["refcount"] == 0
        assert eng.kv.evict_prefix(sysp) is True
        assert not eng.kv.has_prefix(sysp)


class TestScheduler:
    def _req(self, n, **kw):
        return Request(tokens=np.arange(1, n + 1), max_new_tokens=4, **kw)

    def test_fifo_order(self):
        s = Scheduler(max_queue=8)
        ids = [s.submit(self._req(n)) for n in (5, 2, 9)]
        assert [r.req_id for r in s.pop(3)] == ids

    def test_sjf_prefers_short_prompts(self):
        s = Scheduler(max_queue=8, policy="sjf")
        long = s.submit(self._req(9))
        short = s.submit(self._req(2))
        mid = s.submit(self._req(5))
        assert [r.req_id for r in s.pop(2)] == [short, mid]
        assert [r.req_id for r in s.pop(2)] == [long]

    def test_bound_and_reasons(self):
        s = Scheduler(max_queue=1)
        s.submit(self._req(3))
        with pytest.raises(Backpressure) as ei:
            s.submit(self._req(3))
        assert "queue full" in ei.value.reason
        s2 = Scheduler(max_queue=4)
        with pytest.raises(Backpressure, match="deadline"):
            s2.submit(self._req(3, deadline=time.monotonic() - 1))

    def test_cancel_and_expire(self):
        s = Scheduler(max_queue=8)
        a = s.submit(self._req(3))
        b = s.submit(self._req(3, deadline=time.monotonic() + 100))
        assert s.cancel(a) and not s.cancel(a)
        assert s.expire(now=time.monotonic() + 200)[0].req_id == b
        assert s.depth == 0

    def test_request_validation(self):
        with pytest.raises(ValueError, match="empty prompt"):
            Request(tokens=[], max_new_tokens=4)
        with pytest.raises(ValueError, match="max_new_tokens"):
            Request(tokens=[1], max_new_tokens=0)
        with pytest.raises(ValueError, match="policy"):
            Scheduler(policy="lifo")


class TestKVPool:
    def test_alloc_free_cycle(self, tiny):
        _, _, _, make_cache, _ = tiny
        pool = KVPool(make_cache, max_slots=2, max_len=8)
        a, b = pool.alloc(), pool.alloc()
        assert (a, b) == (0, 1) and pool.alloc() is None
        assert pool.occupancy == 1.0
        pool.free(a)
        assert pool.n_free == 1 and pool.alloc() == 0
        with pytest.raises(ValueError, match="double-freed"):
            pool.free(b) or pool.free(b)

    def test_duplicate_prefix_registration_rejected(self, tiny):
        _, _, _, make_cache, _ = tiny
        pool = KVPool(make_cache, max_slots=1, max_len=8)
        pool.put_prefix((1, 2), pool.zeros_lane, 2)
        with pytest.raises(ValueError, match="already registered"):
            pool.put_prefix((1, 2), pool.zeros_lane, 2)


class TestRequestFeeder:
    def test_feeder_drives_engine_through_backpressure(self, tiny, rng):
        """Ingest thread tokenizes + submits under a deliberately tiny
        queue; the engine loop drains it; nothing is lost."""
        cfg, _, _, _, solo = tiny
        eng = _engine(tiny, max_slots=2, max_queue=2)
        prompts = [rng.integers(0, cfg.vocab_size, (3 + i % 4,)).tolist()
                   for i in range(7)]

        def tokenize(text):
            return text, {"max_new_tokens": 4}

        feeder = RequestFeeder(prompts, tokenize, eng.submit,
                               retries=1000, retry_wait_s=0.001).start()
        deadline = time.monotonic() + 30.0
        while ((not feeder.idle or eng.scheduler.depth or eng.n_active)
               and time.monotonic() < deadline):
            eng.step()
        feeder.join(timeout=10.0)
        assert not feeder.dropped
        assert len(feeder.submitted) == 7
        # retries reuse one req_id per item: no phantom per-attempt
        # rejected records, despite the deliberately tiny queue
        assert len(eng.metrics.records) == 7
        assert eng.metrics.summary()["rejected"] == 0
        for p, rid in zip(prompts, feeder.submitted):
            np.testing.assert_array_equal(eng.results[rid].tokens,
                                          solo(p, 4))
        assert eng.trace_counts == {"prefill": 1, "decode": 1}

    def test_backpressure_backoff_then_success_counts_retries(self):
        """Satellite contract: Backpressure is absorbed with bounded
        exponential backoff (resilience.retry schedule) and the
        counters record the aggregate — no engine needed."""
        calls = {"n": 0}

        def submit(tokens, **kw):
            calls["n"] += 1
            if calls["n"] <= 3:
                raise Backpressure("queue full")
            return kw["req_id"]

        feeder = RequestFeeder([[1, 2]], lambda t: (t, {}), submit,
                               retries=10, retry_wait_s=1e-4).start()
        feeder.join(timeout=10.0)
        assert len(feeder.submitted) == 1 and not feeder.dropped
        assert feeder.counters["submitted"] == 1
        assert feeder.counters["retries"] == 3
        assert feeder.counters["dropped_backpressure"] == 0

    def test_backpressure_retries_exhausted_drops_with_reason(self):
        def submit(tokens, **kw):
            raise Backpressure("queue full")

        feeder = RequestFeeder([[1], [2]], lambda t: (t, {}), submit,
                               retries=2, retry_wait_s=1e-4).start()
        feeder.join(timeout=10.0)
        assert len(feeder.dropped) == 2
        assert all("retries exhausted" in r for _, r in feeder.dropped)
        assert feeder.counters["dropped_backpressure"] == 2
        assert feeder.counters["retries"] == 4       # 2 per item

    def test_backpressure_deadline_sheds_load(self):
        """Drop-after-deadline: an item must not stretch tail latency
        unboundedly even with retries left."""
        def submit(tokens, **kw):
            raise Backpressure("queue full")

        feeder = RequestFeeder([[1]], lambda t: (t, {}), submit,
                               retries=10_000, retry_wait_s=0.05,
                               jitter=0.0, deadline_s=0.12).start()
        feeder.join(timeout=10.0)
        assert len(feeder.dropped) == 1
        assert "deadline" in feeder.dropped[0][1]
        assert feeder.counters["dropped_backpressure"] == 1
        # bounded: far fewer sleeps than the retry budget allowed
        assert feeder.counters["retries"] < 10

    def test_per_item_error_drops_item_and_feed_continues(self, tiny,
                                                          rng):
        """One malformed request (submit's contract ValueError) must
        land in `dropped` while the rest of the stream is still served
        — not silently abort the feed (review finding)."""
        cfg = tiny[0]
        eng = _engine(tiny, max_len=32)
        good = rng.integers(0, cfg.vocab_size, (4,)).tolist()
        work = [good, list(range(40)), good]   # middle one can't fit
        feeder = RequestFeeder(
            work, lambda t: (t, {"max_new_tokens": 4}),
            eng.submit).start()
        deadline = time.monotonic() + 30.0
        while ((not feeder.idle or eng.scheduler.depth or eng.n_active)
               and time.monotonic() < deadline):
            eng.step()
        assert len(feeder.submitted) == 2      # both good ones served
        assert len(feeder.dropped) == 1
        assert "cache positions" in feeder.dropped[0][1]
        with pytest.raises(ValueError, match="cache positions"):
            feeder.join()                      # error still surfaced
        assert all(eng.results[r].status == "done"
                   for r in feeder.submitted)
