"""`apex1_tpu.serving` tests — the continuous-batching engine must be
INVISIBLE in the tokens: requests joining and leaving mid-flight
produce output token-identical to a solo `models.generate` run of each
request, with exactly TWO traced executables for the whole workload
(the compilation-count hook `Engine.trace_counts`). Plus the control
plane: backpressure rejection, deadline eviction freeing the slot,
cancellation, prefix-page refcounts never freeing a live page, and the
scheduler/pool/feeder units."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.core.policy import get_policy
from apex1_tpu.models.generate import generate, gpt2_decoder
from apex1_tpu.models.gpt2 import GPT2, GPT2Config
from apex1_tpu.runtime import RequestFeeder
from apex1_tpu.serving import (Backpressure, Engine, EngineConfig,
                               FrontendConfig, KVPool, ReplicaConfig,
                               Request, Scheduler, ServingFrontend,
                               ServingMetrics)


@pytest.fixture(scope="module")
def tiny():
    """Tiny fp32 GPT-2 + its decoder pair + a solo-generate oracle."""
    cfg = GPT2Config.tiny(policy=get_policy("O0"), max_seq_len=64)
    model = GPT2(cfg)
    rng = np.random.default_rng(11)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 7)), jnp.int32)
    params = model.init(jax.random.key(0), prompt)["params"]
    apply_fn, make_cache = gpt2_decoder(model)

    def solo(tokens, n_new):
        cache = make_cache(1, len(tokens) + n_new)
        return np.asarray(generate(
            apply_fn, params, jnp.asarray([tokens], jnp.int32),
            max_new_tokens=n_new, cache=cache,
            vocab_size=cfg.vocab_size))[0]

    return cfg, params, apply_fn, make_cache, solo


def _engine(tiny, **kw):
    cfg, params, apply_fn, make_cache, _ = tiny
    ekw = dict(max_slots=3, max_len=48, prefill_chunk=4,
               vocab_size=cfg.vocab_size)
    ekw.update(kw)
    return Engine(apply_fn, make_cache, params, EngineConfig(**ekw))


class TestContinuousBatching:
    def test_staggered_join_leave_token_identical_two_executables(
            self, tiny, rng):
        """The acceptance workload: more requests than slots, mixed
        prompt lengths (crossing chunk boundaries), mixed output
        lengths, arrivals staggered across live decode steps — every
        completed request must match its solo `generate` run and the
        engine must have traced exactly its two executables."""
        cfg, _, _, _, solo = tiny
        eng = _engine(tiny)
        lens = [3, 7, 5, 9, 4, 6]          # 3,5 < chunk=4 <= 5,7,9
        news = [6, 5, 7, 4, 6, 5]
        prompts = [rng.integers(0, cfg.vocab_size, (L,)).tolist()
                   for L in lens]
        ids = [eng.submit(p, max_new_tokens=n)
               for p, n in zip(prompts[:3], news[:3])]
        eng.step()                          # 3 in flight
        ids.append(eng.submit(prompts[3], max_new_tokens=news[3]))
        eng.step()                          # joins as slots free
        ids += [eng.submit(p, max_new_tokens=n)
                for p, n in zip(prompts[4:], news[4:])]
        eng.run(max_steps=200)
        for p, n, rid in zip(prompts, news, ids):
            res = eng.results[rid]
            assert res.status == "done"
            np.testing.assert_array_equal(res.tokens, solo(p, n))
        # the compilation-count hook: requests of 6 shapes joined and
        # left; the engine must not have retraced for any of it
        assert eng.trace_counts == {"prefill": 1, "decode": 1}
        # with 6 requests over 3 slots, slots were genuinely reused
        assert eng.metrics.summary()["done"] == 6

    def test_eos_early_stop_matches_solo_truncation(self, tiny, rng):
        cfg, _, _, _, solo = tiny
        prompt = rng.integers(0, cfg.vocab_size, (6,)).tolist()
        full = solo(prompt, 8)
        eos = int(full[3])                  # an id greedy decoding emits
        eng = _engine(tiny, eos_id=eos)
        rid = eng.submit(prompt, max_new_tokens=8)
        eng.run(max_steps=50)
        res = eng.results[rid]
        assert res.status == "done" and res.reason == "eos"
        want = full[:list(full).index(eos) + 1]
        np.testing.assert_array_equal(res.tokens, want)

    @pytest.mark.slow  # 870s-cap headroom (~13s): prefix-page x engine
    # full-parity COMPOSITION; halves pinned tier-1 — page refcount
    # machinery (TestPrefixRefcounts + TestCancelReleasesImmediately),
    # the prefix install/admission path with token parity
    # (test_tail_chunk_pad_never_clamps_past_max_len submits via
    # prefix=), and generate-level prefix caching
    # (test_generate::TestPrefixCaching); full run via check_all --all
    def test_prefix_sharing_token_identical_and_counted(self, tiny, rng):
        """Sharers of a system prompt must decode exactly as if the
        full (prefix + own) prompt had been prefilled solo, while the
        prefix's K/V is computed once (page hits prove the reuse)."""
        cfg, _, _, _, solo = tiny
        eng = _engine(tiny, max_slots=2)
        sysp = tuple(rng.integers(0, cfg.vocab_size, (9,)).tolist())
        owns = [rng.integers(0, cfg.vocab_size, (L,)).tolist()
                for L in (4, 6, 3)]
        ids = [eng.submit(o, max_new_tokens=5, prefix=sysp) for o in owns]
        eng.run(max_steps=100)
        for o, rid in zip(owns, ids):
            np.testing.assert_array_equal(eng.results[rid].tokens,
                                          solo(list(sysp) + o, 5))
        (stats,) = eng.kv.prefix_stats().values()
        assert stats["hits"] == 3 and stats["refcount"] == 0
        assert eng.trace_counts == {"prefill": 1, "decode": 1}

    def test_backpressure_rejection_with_reason(self, tiny, rng):
        cfg = tiny[0]
        eng = _engine(tiny, max_slots=1, max_queue=2)
        p = rng.integers(0, cfg.vocab_size, (4,)).tolist()
        eng.submit(p, max_new_tokens=4)
        eng.submit(p, max_new_tokens=4)
        with pytest.raises(Backpressure, match="queue full"):
            eng.submit(p, max_new_tokens=4)
        assert eng.metrics.summary()["rejected"] == 1
        eng.run(max_steps=50)               # the accepted two still finish
        assert eng.metrics.summary()["done"] == 2

    def test_oversized_request_is_contract_error_not_backpressure(
            self, tiny):
        eng = _engine(tiny, max_len=16)
        with pytest.raises(ValueError, match="cache positions"):
            eng.submit(list(range(10)), max_new_tokens=10)

    def test_deadline_eviction_frees_slot_for_next_request(self, tiny,
                                                           rng):
        """A request whose deadline passes mid-decode is evicted with
        its partial output, and the freed slot serves the next request
        to completion."""
        cfg, _, _, _, solo = tiny
        eng = _engine(tiny, max_slots=1)
        p1 = rng.integers(0, cfg.vocab_size, (5,)).tolist()
        p2 = rng.integers(0, cfg.vocab_size, (6,)).tolist()
        r1 = eng.submit(p1, max_new_tokens=30,
                        deadline=time.monotonic() + 0.05)
        eng.step()                          # admitted, decoding
        assert eng.n_active == 1
        time.sleep(0.06)                    # let the deadline lapse
        eng.step()                          # eviction observed here
        res1 = eng.results[r1]
        assert res1.status == "evicted" and "deadline" in res1.reason
        assert 0 < res1.tokens.size < 30    # partial output survives
        assert eng.n_active == 0 and eng.kv.n_free == 1
        r2 = eng.submit(p2, max_new_tokens=5)
        eng.run(max_steps=50)
        assert eng.results[r2].status == "done"
        np.testing.assert_array_equal(eng.results[r2].tokens, solo(p2, 5))

    def test_cancel_queued_and_running(self, tiny, rng):
        cfg = tiny[0]
        eng = _engine(tiny, max_slots=1)
        p = rng.integers(0, cfg.vocab_size, (4,)).tolist()
        r1 = eng.submit(p, max_new_tokens=20)
        r2 = eng.submit(p, max_new_tokens=4)
        eng.step()                          # r1 running, r2 queued
        assert eng.cancel(r2)               # queued: removed outright
        assert eng.cancel(r1)               # running: retires next step
        eng.step()
        assert eng.results[r2].status == "cancelled"
        assert eng.results[r1].status == "cancelled"
        assert eng.results[r1].tokens.size > 0
        assert eng.kv.n_free == 1
        assert not eng.cancel(r1)           # already terminal

    def test_tail_chunk_pad_never_clamps_past_max_len(self, tiny, rng):
        """A request whose FINAL right-padded prefill chunk extends past
        max_len must still decode token-identically: without the pool's
        prefill_chunk-1 slack, dynamic_update_slice would clamp the
        chunk's start and silently shift its K/V onto earlier positions
        (review finding)."""
        cfg, _, _, _, solo = tiny
        # max_len=16, chunk=8, 1-token prefix: own chunks start at 1
        # and 9, so the padded second chunk writes [9, 17) — one past
        # max_len. total_len = 1+13+3-1 = 16 <= 16 is admissible, so
        # only the pool's slack keeps the write from being clamped
        eng = _engine(tiny, max_slots=1, max_len=16, prefill_chunk=8)
        # the invariant that prevents the clamp: the pool allocates
        # prefill_chunk-1 positions past the usable max_len, so every
        # padded chunk write [start, start+chunk) fits
        s_max = jax.tree_util.tree_leaves(eng.kv.cache)[0].shape[2]
        assert s_max == 16 + 8 - 1
        sysp = tuple(rng.integers(0, cfg.vocab_size, (1,)).tolist())
        p = rng.integers(0, cfg.vocab_size, (13,)).tolist()
        rid = eng.submit(p, max_new_tokens=3, prefix=sysp)
        eng.run(max_steps=30)
        res = eng.results[rid]
        assert res.status == "done"
        np.testing.assert_array_equal(res.tokens,
                                      solo(list(sysp) + p, 3))

    def test_rejection_reason_reflects_cause(self, tiny, rng):
        """The rejected metrics event must carry the scheduler's actual
        reason, not a hardcoded 'queue full' (review finding)."""
        cfg = tiny[0]
        eng = _engine(tiny)
        p = rng.integers(0, cfg.vocab_size, (4,)).tolist()
        with pytest.raises(Backpressure):
            eng.submit(p, max_new_tokens=4,
                       deadline=time.monotonic() - 1.0)
        (rec,) = eng.metrics.records.values()
        assert rec.status == "rejected"
        assert "deadline" in rec.reason

    def test_metrics_lifecycle_and_ttft(self, tiny, rng):
        cfg = tiny[0]
        eng = _engine(tiny)
        p = rng.integers(0, cfg.vocab_size, (5,)).tolist()
        rid = eng.submit(p, max_new_tokens=4)
        eng.run(max_steps=50)
        rec = eng.metrics.records[rid]
        assert rec.status == "done"
        assert (rec.t_queued <= rec.t_prefill <= rec.t_first_token
                <= rec.t_done)
        assert rec.ttft is not None and rec.ttft >= 0
        s = eng.metrics.summary()
        assert s["generated_tokens"] == 4
        assert 0 < s["mean_occupancy"] <= 1
        assert "ttft_p50_ms" in s and "ttft_p99_ms" in s


class TestPrefixRefcounts:
    def test_refcount_never_frees_live_page(self, tiny, rng):
        cfg = tiny[0]
        eng = _engine(tiny, max_slots=2)
        sysp = tuple(rng.integers(0, cfg.vocab_size, (6,)).tolist())
        own = rng.integers(0, cfg.vocab_size, (3,)).tolist()
        eng.submit(own, max_new_tokens=10, prefix=sysp)
        eng.submit(own, max_new_tokens=10, prefix=sysp)
        eng.step()                          # both admitted, page live
        (stats,) = eng.kv.prefix_stats().values()
        assert stats["refcount"] == 2
        assert eng.kv.evict_prefix(sysp) is False      # refused
        with pytest.raises(RuntimeError, match="live page"):
            eng.kv.evict_prefix(sysp, force=True)      # loud, still no
        assert eng.kv.has_prefix(sysp)
        eng.run(max_steps=100)              # both retire -> refcount 0
        (stats,) = eng.kv.prefix_stats().values()
        assert stats["refcount"] == 0
        assert eng.kv.evict_prefix(sysp) is True
        assert not eng.kv.has_prefix(sysp)


class TestRadixIndex:
    def test_longest_prefix_match_with_cap(self):
        from apex1_tpu.serving import RadixIndex
        idx = RadixIndex()
        idx.insert((1, 2))
        idx.insert((1, 2, 3, 4))
        assert idx.match([1, 2, 3, 4, 5], 99) == (1, 2, 3, 4)
        assert idx.match([1, 2, 3, 4, 5], 3) == (1, 2)   # cap honored
        assert idx.match([1, 2, 9], 99) == (1, 2)
        assert idx.match([9, 1, 2], 99) is None
        assert idx.match([1, 2], 1) is None

    def test_remove_prunes_and_keeps_shorter_keys(self):
        from apex1_tpu.serving import RadixIndex
        idx = RadixIndex()
        idx.insert((1, 2))
        idx.insert((1, 2, 3, 4))
        idx.remove((1, 2, 3, 4))
        assert len(idx) == 1
        assert idx.match([1, 2, 3, 4], 99) == (1, 2)
        idx.remove((1, 2))
        assert len(idx) == 0 and not idx._root.children  # fully pruned
        idx.remove((1, 2))                               # idempotent


class TestRadixPrefixCache:
    def test_cross_request_match_without_explicit_prefix(self, tiny,
                                                         rng):
        """The tentpole: two requests sharing a long prompt prefix —
        NEITHER passes prefix= — dedupe through the radix matcher; the
        second admission hits the first's chunk-aligned auto page, and
        both decode token-identically to their solo runs."""
        cfg, _, _, _, solo = tiny
        eng = _engine(tiny, max_slots=2)
        shared = rng.integers(0, cfg.vocab_size, (9,)).tolist()
        p1, p2 = shared + [1, 2], shared + [3]
        r1 = eng.submit(p1, max_new_tokens=5)
        eng.run(max_steps=40)
        r2 = eng.submit(p2, max_new_tokens=5)
        eng.run(max_steps=40)
        np.testing.assert_array_equal(eng.results[r1].tokens,
                                      solo(p1, 5))
        np.testing.assert_array_equal(eng.results[r2].tokens,
                                      solo(p2, 5))
        # chunk=4, len(p1)=11 -> auto page at ((11-1)//4)*4 = 8, which
        # is a prefix of p2 as well
        (stats,) = eng.kv.prefix_stats().values()
        assert stats["length"] == 8 and stats["hits"] >= 2
        s = eng.metrics.summary()
        assert s["prefix_hit_rate"] == 0.5           # miss then hit
        assert s["prefix_saved_tokens"] == 8
        rec = eng.metrics.records[r2]
        assert rec.prefix_hit is True and rec.prefix_saved == 8
        assert eng.metrics.records[r1].prefix_hit is False
        assert eng.trace_counts == {"prefill": 1, "decode": 1}

    def test_radix_hit_vs_cold_miss_parity(self, tiny, rng):
        """Satellite parity pin: the same request admitted COLD (fresh
        engine, full prefill) and WARM (radix hit installs a page)
        emits identical tokens."""
        cfg, _, _, _, _ = tiny
        prompt = rng.integers(0, cfg.vocab_size, (10,)).tolist()
        cold = _engine(tiny)
        rc = cold.submit(prompt, max_new_tokens=6)
        cold.run(max_steps=40)
        warm = _engine(tiny)
        w1 = warm.submit(prompt, max_new_tokens=6)
        warm.run(max_steps=40)
        w2 = warm.submit(prompt, max_new_tokens=6)   # the radix hit
        warm.run(max_steps=40)
        assert warm.metrics.records[w2].prefix_hit is True
        np.testing.assert_array_equal(cold.results[rc].tokens,
                                      warm.results[w1].tokens)
        np.testing.assert_array_equal(warm.results[w1].tokens,
                                      warm.results[w2].tokens)

    def test_explicit_prefix_page_serves_auto_requests(self, tiny, rng):
        """The explicit prefix= API is a thin wrapper over the radix
        store: a later request whose FLAT prompt starts with the same
        tokens hits the explicit page without naming it."""
        cfg, _, _, _, solo = tiny
        eng = _engine(tiny, max_slots=2)
        sysp = tuple(rng.integers(0, cfg.vocab_size, (7,)).tolist())
        own = rng.integers(0, cfg.vocab_size, (3,)).tolist()
        r1 = eng.submit(own, max_new_tokens=4, prefix=sysp)
        eng.run(max_steps=40)
        flat = list(sysp) + own
        r2 = eng.submit(flat, max_new_tokens=4)      # no prefix=
        eng.run(max_steps=40)
        rec = eng.metrics.records[r2]
        assert rec.prefix_hit is True and rec.prefix_saved == 7
        np.testing.assert_array_equal(eng.results[r1].tokens,
                                      eng.results[r2].tokens)
        np.testing.assert_array_equal(eng.results[r2].tokens,
                                      solo(flat, 4))

    def test_lru_eviction_under_page_pressure(self, tiny, rng):
        """max_prefix_pages bounds the store: the least-recently-hit
        refcount-0 page goes first, live pages never."""
        cfg, _, _, _, _ = tiny
        eng = _engine(tiny, max_slots=1, max_prefix_pages=2)
        prompts = [rng.integers(0, cfg.vocab_size, (9,)).tolist()
                   for _ in range(3)]
        keys = []
        for p in prompts:
            rid = eng.submit(p, max_new_tokens=3)
            eng.run(max_steps=30)
            assert eng.results[rid].status == "done"
            keys.append(tuple(p[:8]))                # chunk-aligned
        assert len(eng.kv.prefix_stats()) == 2
        assert not eng.kv.has_prefix(keys[0])        # LRU evicted
        assert eng.kv.has_prefix(keys[1])
        assert eng.kv.has_prefix(keys[2])

    def test_registration_never_evicts_its_own_page(self, tiny, rng):
        """Review-finding regression: with the store at max_pages and
        every OTHER page live, registering a new page must not evict
        the page being registered (put-then-acquire would KeyError and
        crash the step) — the bound goes soft instead."""
        cfg, _, _, _, _ = tiny
        eng = _engine(tiny, max_slots=2, max_prefix_pages=1)
        p1 = rng.integers(0, cfg.vocab_size, (9,)).tolist()
        r1 = eng.submit(p1, max_new_tokens=20)
        eng.step()                       # r1 live, holds its auto page
        (stats,) = eng.kv.prefix_stats().values()
        assert stats["refcount"] == 1
        p2 = rng.integers(0, cfg.vocab_size, (9,)).tolist()
        r2 = eng.submit(p2, max_new_tokens=3)
        eng.run(max_steps=60)            # must not crash the admission
        assert eng.results[r2].status == "done"
        assert eng.results[r1].status == "done"
        # both registrations survived the all-live window (soft bound);
        # a later registration with everything dead re-tightens it
        assert len(eng.kv.prefix_stats()) == 2
        p3 = rng.integers(0, cfg.vocab_size, (9,)).tolist()
        eng.submit(p3, max_new_tokens=3)
        eng.run(max_steps=30)
        assert len(eng.kv.prefix_stats()) == 1

    def test_prefix_aware_admission_near_capacity(self, tiny, rng):
        """Near capacity (queue deeper than free slots) a same-class
        radix HIT is dequeued before an older miss — and never across
        the QoS lattice."""
        cfg, _, _, _, _ = tiny
        eng = _engine(tiny, max_slots=1)
        warm = rng.integers(0, cfg.vocab_size, (9,)).tolist()
        r0 = eng.submit(warm, max_new_tokens=3)      # registers a page
        eng.run(max_steps=30)
        blocker = eng.submit(rng.integers(0, cfg.vocab_size,
                                          (4,)).tolist(),
                             max_new_tokens=20)
        eng.step()                                   # blocker holds it
        miss = eng.submit(rng.integers(0, cfg.vocab_size,
                                       (9,)).tolist(),
                          max_new_tokens=3)
        hit = eng.submit(warm + [5], max_new_tokens=3)
        assert eng.cancel(blocker)
        eng.step()                                   # one free slot
        assert eng.slot_view()[0] == hit             # hit jumped miss
        eng.run(max_steps=40)
        assert eng.results[miss].status == "done"    # miss still served
        # cross-class: a sheddable hit never jumps a guaranteed miss
        blocker2 = eng.submit(warm, max_new_tokens=20)
        eng.step()
        g_miss = eng.submit(rng.integers(0, cfg.vocab_size,
                                         (9,)).tolist(),
                            max_new_tokens=3, qos="guaranteed")
        s_hit = eng.submit(warm + [5], max_new_tokens=3,
                           qos="sheddable")
        assert eng.cancel(blocker2)
        eng.step()
        assert eng.slot_view()[0] == g_miss
        eng.run(max_steps=60)
        assert eng.results[s_hit].status == "done"

    def test_prefix_cache_off_banks_no_rate(self, tiny, rng):
        cfg, _, _, _, _ = tiny
        eng = _engine(tiny, prefix_cache=False)
        rid = eng.submit(rng.integers(0, cfg.vocab_size, (9,)).tolist(),
                         max_new_tokens=3)
        eng.run(max_steps=30)
        assert eng.results[rid].status == "done"
        s = eng.metrics.summary()
        assert "prefix_hit_rate" not in s            # fields-only-when-data
        assert not eng.kv.prefix_stats()
        assert eng.metrics.records[rid].prefix_hit is None

    def test_prefix_cache_off_keeps_exact_tuple_sharing(self, tiny,
                                                        rng):
        """Review-finding regression: with the radix matcher DISABLED,
        the PR-7 explicit-prefix contract must survive — a second
        sharer of the same prefix= tuple reuses the page (no
        'already registered' crash, one page, two hits, parity)."""
        cfg, _, _, _, solo = tiny
        eng = _engine(tiny, max_slots=2, prefix_cache=False)
        sysp = tuple(rng.integers(0, cfg.vocab_size, (7,)).tolist())
        owns = [rng.integers(0, cfg.vocab_size, (3,)).tolist()
                for _ in range(2)]
        ids = [eng.submit(o, max_new_tokens=4, prefix=sysp)
               for o in owns]
        eng.run(max_steps=60)
        for o, rid in zip(owns, ids):
            np.testing.assert_array_equal(eng.results[rid].tokens,
                                          solo(list(sysp) + o, 4))
        (stats,) = eng.kv.prefix_stats().values()
        assert stats["hits"] == 2 and stats["refcount"] == 0


class TestFirstSharerStranding:
    def test_midprefill_failure_strands_nothing(self, tiny, rng):
        """ISSUE 15 satellite regression: a prefill chain that dies
        mid-flight (chaos kill, XLA error) while a first sharer is
        paying for its prefix must not leak the slot, leave a dangling
        page refcount, or register a half-built page — and the same
        prefix must admit cleanly afterwards."""
        cfg, _, _, _, solo = tiny
        eng = _engine(tiny, max_slots=2)
        sysp = tuple(rng.integers(0, cfg.vocab_size, (9,)).tolist())
        own = rng.integers(0, cfg.vocab_size, (3,)).tolist()
        orig = eng._prefill
        calls = {"n": 0}

        def boom(*a, **kw):
            calls["n"] += 1
            if calls["n"] == 2:          # chunk 2 of 3: mid-prefix
                raise RuntimeError("chaos: replica killed mid-prefill")
            return orig(*a, **kw)

        eng._prefill = boom
        eng.submit(own, max_new_tokens=5, prefix=sysp)
        with pytest.raises(RuntimeError, match="mid-prefill"):
            eng.step()
        # the stranding window: nothing half-built survives
        assert eng.kv.n_free == 2
        assert not eng.kv.prefix_stats()
        assert eng.slot_view() == [None, None]
        # the pool is consistent — the same prefix admits as a clean
        # first sharer and decodes to parity
        eng._prefill = orig
        rid = eng.submit(own, max_new_tokens=5, prefix=sysp)
        eng.run(max_steps=40)
        np.testing.assert_array_equal(eng.results[rid].tokens,
                                      solo(list(sysp) + own, 5))
        (stats,) = eng.kv.prefix_stats().values()
        assert stats["refcount"] == 0 and stats["hits"] == 1

    def test_cancel_landing_mid_admission_is_honored(self, tiny, rng):
        """A cancel that lands while the admission's prefill chain runs
        (ingest thread racing the engine loop) retires the request the
        moment the chain completes — no zombie slot, no lost cancel."""
        cfg, _, _, _, _ = tiny
        eng = _engine(tiny, max_slots=2)
        sysp = tuple(rng.integers(0, cfg.vocab_size, (6,)).tolist())
        own = rng.integers(0, cfg.vocab_size, (3,)).tolist()
        rid = eng.submit(own, max_new_tokens=10, prefix=sysp)
        orig = eng._prefill

        def sneaky(*a, **kw):
            out = orig(*a, **kw)
            assert eng.cancel(rid)       # lands mid-admission
            return out

        eng._prefill = sneaky
        eng.step()
        eng._prefill = orig
        res = eng.results[rid]
        assert res.status == "cancelled"
        assert eng.kv.n_free == 2 and eng.n_active == 0
        (stats,) = eng.kv.prefix_stats().values()
        assert stats["refcount"] == 0    # page released, evictable


class TestScheduler:
    def _req(self, n, **kw):
        return Request(tokens=np.arange(1, n + 1), max_new_tokens=4, **kw)

    def test_fifo_order(self):
        s = Scheduler(max_queue=8)
        ids = [s.submit(self._req(n)) for n in (5, 2, 9)]
        assert [r.req_id for r in s.pop(3)] == ids

    def test_sjf_prefers_short_prompts(self):
        s = Scheduler(max_queue=8, policy="sjf")
        long = s.submit(self._req(9))
        short = s.submit(self._req(2))
        mid = s.submit(self._req(5))
        assert [r.req_id for r in s.pop(2)] == [short, mid]
        assert [r.req_id for r in s.pop(2)] == [long]

    def test_bound_and_reasons(self):
        s = Scheduler(max_queue=1)
        s.submit(self._req(3))
        with pytest.raises(Backpressure) as ei:
            s.submit(self._req(3))
        assert "queue full" in ei.value.reason
        s2 = Scheduler(max_queue=4)
        with pytest.raises(Backpressure, match="deadline"):
            s2.submit(self._req(3, deadline=time.monotonic() - 1))

    def test_cancel_and_expire(self):
        s = Scheduler(max_queue=8)
        a = s.submit(self._req(3))
        b = s.submit(self._req(3, deadline=time.monotonic() + 100))
        assert s.cancel(a) and not s.cancel(a)
        assert s.expire(now=time.monotonic() + 200)[0].req_id == b
        assert s.depth == 0

    def test_request_validation(self):
        with pytest.raises(ValueError, match="empty prompt"):
            Request(tokens=[], max_new_tokens=4)
        with pytest.raises(ValueError, match="max_new_tokens"):
            Request(tokens=[1], max_new_tokens=0)
        with pytest.raises(ValueError, match="policy"):
            Scheduler(policy="lifo")


class TestSchedulerQoS:
    def _req(self, n, **kw):
        return Request(tokens=np.arange(1, n + 1), max_new_tokens=4, **kw)

    def test_pop_priority_with_intra_class_fairness(self):
        """Cross-class: guaranteed before best_effort before sheddable.
        Intra-class: arrival order untouched (fifo) — the class lattice
        must never reorder same-class tenants."""
        s = Scheduler(max_queue=8)
        b1 = s.submit(self._req(3, qos="best_effort", tenant="t1"))
        sh = s.submit(self._req(2, qos="sheddable"))
        g1 = s.submit(self._req(9, qos="guaranteed"))
        b2 = s.submit(self._req(5, qos="best_effort", tenant="t2"))
        g2 = s.submit(self._req(4, qos="guaranteed"))
        assert [r.req_id for r in s.pop(5)] == [g1, g2, b1, b2, sh]

    def test_sjf_applies_within_class(self):
        s = Scheduler(max_queue=8, policy="sjf")
        b_long = s.submit(self._req(9))
        g_long = s.submit(self._req(7, qos="guaranteed"))
        b_short = s.submit(self._req(2))
        g_short = s.submit(self._req(3, qos="guaranteed"))
        assert [r.req_id for r in s.pop(4)] == [g_short, g_long,
                                                b_short, b_long]

    def test_full_queue_sheds_weakest_youngest_first(self):
        """A stronger-class submit on a full queue sheds the weakest
        class's YOUNGEST request (it waited least); the victim surfaces
        via drain_shed, never silently."""
        s = Scheduler(max_queue=3)
        s.submit(self._req(3, qos="sheddable"), now=1.0)
        sh_young = s.submit(self._req(3, qos="sheddable"), now=2.0)
        s.submit(self._req(3, qos="best_effort"), now=3.0)
        b = s.submit(self._req(3, qos="best_effort"), now=4.0)
        assert [r.req_id for r in s.drain_shed()] == [sh_young]
        assert s.depth == 3 and not s.drain_shed()
        # the displaced best_effort is still queued; a guaranteed
        # arrival sheds the remaining sheddable, then best_effort
        g = s.submit(self._req(3, qos="guaranteed"), now=5.0)
        (v1,) = s.drain_shed()
        assert v1.qos == "sheddable"
        g2 = s.submit(self._req(3, qos="guaranteed"), now=6.0)
        (v2,) = s.drain_shed()
        assert v2.qos == "best_effort" and v2.req_id == b
        assert {g, g2} < set(s.snapshot())

    def test_guaranteed_never_shed_while_sheddable_present(self):
        """The QoS contract's core: no arrival ever sheds an equal or
        stronger class — a full queue of guaranteed work rejects even
        another guaranteed request rather than shed one."""
        s = Scheduler(max_queue=2)
        s.submit(self._req(3, qos="guaranteed"))
        s.submit(self._req(3, qos="sheddable"))
        s.submit(self._req(3, qos="guaranteed"))     # sheds the sheddable
        (v,) = s.drain_shed()
        assert v.qos == "sheddable"
        with pytest.raises(Backpressure) as ei:      # only guaranteed left
            s.submit(self._req(3, qos="guaranteed"))
        assert ei.value.queue_depth == 2
        assert ei.value.retry_after_s > 0
        assert all(r.qos == "guaranteed"
                   for r in [self._lookup(s, i) for i in s.snapshot()])

    @staticmethod
    def _lookup(s, rid):
        return next(r for r in s._queue if r.req_id == rid)

    def test_expire_orders_class_then_deadline(self):
        s = Scheduler(max_queue=8)
        t = time.monotonic()
        b = s.submit(self._req(3, qos="best_effort", deadline=t + 1))
        g_late = s.submit(self._req(3, qos="guaranteed", deadline=t + 2))
        sh = s.submit(self._req(3, qos="sheddable", deadline=t + 1))
        g_early = s.submit(self._req(3, qos="guaranteed", deadline=t + 1))
        live = s.submit(self._req(3, qos="sheddable", deadline=t + 99))
        dead = s.expire(now=t + 10)
        assert [r.req_id for r in dead] == [g_early, g_late, b, sh]
        assert s.snapshot() == [live]

    def test_structured_backpressure_fields(self):
        s = Scheduler(max_queue=1, retry_after_s=0.2)
        s.submit(self._req(3))
        with pytest.raises(Backpressure) as full:
            s.submit(self._req(3))
        assert full.value.queue_depth == 1
        assert full.value.retry_after_s == pytest.approx(0.2)
        with pytest.raises(Backpressure) as dead:
            Scheduler(max_queue=4).submit(
                self._req(3, deadline=time.monotonic() - 1))
        assert dead.value.retry_after_s == 0.0   # retrying is pointless

    def test_unknown_qos_rejected_loudly(self):
        with pytest.raises(ValueError, match="qos"):
            self._req(3, qos="platinum")

    def test_engine_submit_finishes_shed_victims(self, tiny, rng):
        """The engine surfaces scheduler sheds as evicted results with
        a shed reason + counter — shed load is observable load."""
        cfg = tiny[0]
        eng = _engine(tiny, max_slots=1, max_queue=1)
        p = rng.integers(0, cfg.vocab_size, (4,)).tolist()
        shed_rid = eng.submit(p, max_new_tokens=4, qos="sheddable")
        # full queue: the guaranteed arrival displaces the sheddable
        g = eng.submit(p, max_new_tokens=4, qos="guaranteed")
        res = eng.results[shed_rid]
        assert res.status == "evicted" and "shed" in res.reason
        assert eng.metrics.summary()["counters"]["sheds"] == 1
        eng.run(max_steps=60)
        assert eng.results[g].status == "done"


class TestCancelReleasesImmediately:
    def test_running_cancel_frees_slot_and_prefix_refcount_now(
            self, tiny, rng):
        """Satellite audit: cancelling an ADMITTED request must release
        its KV slot and shared-prefix page refcount immediately — not
        at the next step boundary (an idle engine would leak the slot
        forever) and not at natural retirement."""
        cfg = tiny[0]
        eng = _engine(tiny, max_slots=2)
        sysp = tuple(rng.integers(0, cfg.vocab_size, (6,)).tolist())
        own = rng.integers(0, cfg.vocab_size, (3,)).tolist()
        rid = eng.submit(own, max_new_tokens=20, prefix=sysp)
        eng.step()                           # admitted + decoding
        assert eng.n_active == 1 and eng.kv.n_free == 1
        (stats,) = eng.kv.prefix_stats().values()
        assert stats["refcount"] == 1
        assert eng.cancel(rid)
        # NO step() between cancel and these asserts — the release
        # must already have happened
        assert eng.kv.n_free == 2
        assert eng.n_active == 0
        (stats,) = eng.kv.prefix_stats().values()
        assert stats["refcount"] == 0        # page released, evictable
        assert eng.kv.evict_prefix(sysp) is True
        res = eng.results[rid]
        assert res.status == "cancelled" and res.tokens.size > 0


class TestPerRequestSeeds:
    """Sampling is a pure function of (params, prompt, seed): the
    idempotent-resubmission contract the replica supervisor rides."""

    def _toy_engine(self, **kw):
        from apex1_tpu.testing.chaos import toy_decoder
        apply_fn, make_cache, params = toy_decoder()
        ekw = dict(max_slots=3, max_len=48, prefill_chunk=4,
                   vocab_size=61, temperature=0.9, seed=5)
        ekw.update(kw)
        return Engine(apply_fn, make_cache, params, EngineConfig(**ekw))

    def test_same_seed_same_stream_across_engines_and_batches(self):
        """A sampled request regenerates bit-identically on a FRESH
        engine, even when the two engines batch it with different
        neighbors — seed + output position is the whole key."""
        a = self._toy_engine()
        ra = a.submit([7, 3, 9], max_new_tokens=10, seed=1234)
        a.run(max_steps=60)

        b = self._toy_engine()
        # different batch composition on engine b
        b.submit([1, 2, 3, 4, 5], max_new_tokens=6)
        rb = b.submit([7, 3, 9], max_new_tokens=10, seed=1234)
        b.submit([9, 9], max_new_tokens=4)
        b.run(max_steps=80)
        np.testing.assert_array_equal(a.results[ra].tokens,
                                      b.results[rb].tokens)

    def test_different_seeds_different_streams(self):
        eng = self._toy_engine()
        r1 = eng.submit([7, 3, 9], max_new_tokens=12, seed=1)
        r2 = eng.submit([7, 3, 9], max_new_tokens=12, seed=2)
        eng.run(max_steps=80)
        assert not np.array_equal(eng.results[r1].tokens,
                                  eng.results[r2].tokens)

    def test_derived_seed_stable_for_stable_req_id(self):
        """No explicit seed: the engine derives one from (engine seed,
        request id) — a resubmission carrying the same id onto a fresh
        engine regenerates the identical stream."""
        from apex1_tpu.serving import new_request_id
        rid = new_request_id()
        a = self._toy_engine()
        a.submit([5, 1, 2, 8], max_new_tokens=9, req_id=rid)
        a.run(max_steps=60)
        b = self._toy_engine()
        b.submit([5, 1, 2, 8], max_new_tokens=9, req_id=rid)
        b.run(max_steps=60)
        np.testing.assert_array_equal(a.results[rid].tokens,
                                      b.results[rid].tokens)
        # ...and a different id derives a different seed. (Seed-level,
        # not token-level: the toy decoder's peaked distribution makes
        # two DIFFERENT seeds sample identical short streams for ~25%
        # of adjacent id pairs, so a token comparison flakes on where
        # the global id counter happens to sit.)
        from apex1_tpu.serving.engine import derive_request_seed
        c = self._toy_engine()
        rid2 = c.submit([5, 1, 2, 8], max_new_tokens=9)
        c.run(max_steps=60)
        assert rid2 != rid
        assert (derive_request_seed(c.cfg.seed, rid2)
                != derive_request_seed(c.cfg.seed, rid))


class TestReplicaKillDrill:
    def test_two_replica_kill_mid_stream_bit_identical(self, tiny, rng):
        """THE acceptance drill on the real tiny GPT-2: 2-replica
        frontend, one replica chaos-killed mid-stream. Every request
        must complete with tokens BIT-IDENTICAL to the uninterrupted
        solo-generate oracle, the dead replica restarts exactly once,
        and every engine generation compiled exactly its two
        executables."""
        from apex1_tpu.testing.chaos import ReplicaKill
        cfg, params, apply_fn, make_cache, solo = tiny

        def make_engine():
            return Engine(apply_fn, make_cache, params,
                          EngineConfig(max_slots=2, max_len=48,
                                       prefill_chunk=4,
                                       vocab_size=cfg.vocab_size))

        kill = ReplicaKill(replica=0, at_step=3)
        front = ServingFrontend(
            make_engine,
            FrontendConfig(n_replicas=2, capacity_per_replica=6,
                           hedge_after_s=None,
                           replica=ReplicaConfig(watchdog_s=120.0)),
            fault=kill)
        lens = [3, 7, 5, 9, 4]
        news = [6, 5, 7, 4, 6]
        prompts = [rng.integers(0, cfg.vocab_size, (L,)).tolist()
                   for L in lens]
        rids = [front.submit(p, max_new_tokens=n)
                for p, n in zip(prompts, news)]
        front.run_until_drained(timeout_s=300.0)
        assert kill.fired == 1
        for p, n, rid in zip(prompts, news, rids):
            res = front.poll(rid)
            assert res.status == "done", (rid, res)
            np.testing.assert_array_equal(res.tokens, solo(p, n))
        # the dead replica restarted once, with a FRESH two-executable
        # engine; the survivor kept its original pair
        summ = front.summary()
        assert summ["counters"]["replica_restarts"] == 1
        assert summ["replicas"][0]["restarts"] == 1
        assert summ["replicas"][0]["engines_built"] == 2
        assert summ["replicas"][1]["engines_built"] == 1
        for rep in front.replicas:
            assert rep.trace_counts() == {"prefill": 1, "decode": 1}
        # the death + restart are banked transitions
        events = [t["event"] for t in front.metrics.transitions]
        assert "replica_dead" in events and "replica_restart" in events


class TestServingMetricsFailurePaths:
    def test_counters_and_percentiles_on_synthetic_stream(self):
        """Satellite: summary() carries the failure-path counters
        (always, zeros included) and p50/p99 for BOTH TTFT and
        end-to-end latency — asserted on a hand-built event stream
        with exact timestamps."""
        m = ServingMetrics()
        # 10 requests: queued at t=i, first token at t=i+ttft,
        # done at t=i+lat, with ttft = 10..100ms, lat = 2x ttft
        for i in range(10):
            ttft = 0.01 * (i + 1)
            m.event(i, "queued", now=float(i), n_prompt=4)
            m.event(i, "prefill", now=float(i))
            m.event(i, "first_token", now=float(i) + ttft)
            m.event(i, "done", now=float(i) + 2 * ttft,
                    reason="length", n_generated=8)
        m.incr("retries", 3)
        m.incr("hedges_fired")
        m.incr("hedges_won")
        m.incr("sheds", 2)
        m.incr("replica_restarts")
        m.incr("custom_path")                    # ad-hoc names ride along
        s = m.summary()
        c = s["counters"]
        assert c["retries"] == 3 and c["hedges_fired"] == 1
        assert c["hedges_won"] == 1 and c["sheds"] == 2
        assert c["replica_restarts"] == 1
        assert c["evictions"] == 0               # present even when 0
        assert c["custom_path"] == 1
        ttfts_ms = [10.0 * (i + 1) for i in range(10)]
        assert s["ttft_p50_ms"] == pytest.approx(
            float(np.percentile(ttfts_ms, 50)), rel=1e-6)
        assert s["ttft_p99_ms"] == pytest.approx(
            float(np.percentile(ttfts_ms, 99)), rel=1e-6)
        assert s["latency_p50_ms"] == pytest.approx(
            float(np.percentile([2 * t for t in ttfts_ms], 50)),
            rel=1e-6)
        assert s["latency_p99_ms"] == pytest.approx(
            float(np.percentile([2 * t for t in ttfts_ms], 99)),
            rel=1e-6)

    def test_transitions_banked_and_logged(self):
        lines = []
        from apex1_tpu.utils.observability import MetricsLogger
        m = ServingMetrics(MetricsLogger(writer=lines.append,
                                         n_chips=1))
        m.transition("mode", frm="normal", to="shedding",
                     load_fraction=0.9)
        m.transition("replica_restart", replica=1, generation=2)
        assert [t["event"] for t in m.transitions] == [
            "mode", "replica_restart"]
        assert m.transitions[0]["to"] == "shedding"
        import json
        recs = [json.loads(ln) for ln in lines]
        assert recs[0]["event"] == "mode"
        assert recs[1]["replica"] == 1


class TestKVPool:
    def test_alloc_free_cycle(self, tiny):
        _, _, _, make_cache, _ = tiny
        pool = KVPool(make_cache, max_slots=2, max_len=8)
        a, b = pool.alloc(), pool.alloc()
        assert (a, b) == (0, 1) and pool.alloc() is None
        assert pool.occupancy == 1.0
        pool.free(a)
        assert pool.n_free == 1 and pool.alloc() == 0
        with pytest.raises(ValueError, match="double-freed"):
            pool.free(b) or pool.free(b)

    def test_duplicate_prefix_registration_rejected(self, tiny):
        _, _, _, make_cache, _ = tiny
        pool = KVPool(make_cache, max_slots=1, max_len=8)
        pool.put_prefix((1, 2), pool.zeros_lane, 2)
        with pytest.raises(ValueError, match="already registered"):
            pool.put_prefix((1, 2), pool.zeros_lane, 2)


class TestRequestFeeder:
    def test_feeder_drives_engine_through_backpressure(self, tiny, rng):
        """Ingest thread tokenizes + submits under a deliberately tiny
        queue; the engine loop drains it; nothing is lost."""
        cfg, _, _, _, solo = tiny
        eng = _engine(tiny, max_slots=2, max_queue=2)
        prompts = [rng.integers(0, cfg.vocab_size, (3 + i % 4,)).tolist()
                   for i in range(7)]

        def tokenize(text):
            return text, {"max_new_tokens": 4}

        feeder = RequestFeeder(prompts, tokenize, eng.submit,
                               retries=1000, retry_wait_s=0.001).start()
        deadline = time.monotonic() + 30.0
        while ((not feeder.idle or eng.scheduler.depth or eng.n_active)
               and time.monotonic() < deadline):
            eng.step()
        feeder.join(timeout=10.0)
        assert not feeder.dropped
        assert len(feeder.submitted) == 7
        # retries reuse one req_id per item: no phantom per-attempt
        # rejected records, despite the deliberately tiny queue
        assert len(eng.metrics.records) == 7
        assert eng.metrics.summary()["rejected"] == 0
        for p, rid in zip(prompts, feeder.submitted):
            np.testing.assert_array_equal(eng.results[rid].tokens,
                                          solo(p, 4))
        assert eng.trace_counts == {"prefill": 1, "decode": 1}

    def test_backpressure_backoff_then_success_counts_retries(self):
        """Satellite contract: Backpressure is absorbed with bounded
        exponential backoff (resilience.retry schedule) and the
        counters record the aggregate — no engine needed."""
        calls = {"n": 0}

        def submit(tokens, **kw):
            calls["n"] += 1
            if calls["n"] <= 3:
                raise Backpressure("queue full")
            return kw["req_id"]

        feeder = RequestFeeder([[1, 2]], lambda t: (t, {}), submit,
                               retries=10, retry_wait_s=1e-4).start()
        feeder.join(timeout=10.0)
        assert len(feeder.submitted) == 1 and not feeder.dropped
        assert feeder.counters["submitted"] == 1
        assert feeder.counters["retries"] == 3
        assert feeder.counters["dropped_backpressure"] == 0

    def test_backpressure_retries_exhausted_drops_with_reason(self):
        def submit(tokens, **kw):
            raise Backpressure("queue full")

        feeder = RequestFeeder([[1], [2]], lambda t: (t, {}), submit,
                               retries=2, retry_wait_s=1e-4).start()
        feeder.join(timeout=10.0)
        assert len(feeder.dropped) == 2
        assert all("retries exhausted" in r for _, r in feeder.dropped)
        assert feeder.counters["dropped_backpressure"] == 2
        assert feeder.counters["retries"] == 4       # 2 per item

    def test_retry_after_hint_floors_the_backoff(self):
        """Satellite: a structured rejection's retry_after_s is the
        FLOOR on the feeder's next sleep — the exponential schedule may
        wait longer, never shorter."""
        calls = {"n": 0}
        floor = 0.06

        def submit(tokens, **kw):
            calls["n"] += 1
            if calls["n"] <= 2:
                raise Backpressure("queue full", queue_depth=9,
                                   retry_after_s=floor)
            return kw["req_id"]

        t0 = time.monotonic()
        feeder = RequestFeeder([[1, 2]], lambda t: (t, {}), submit,
                               retries=10, retry_wait_s=1e-4,
                               retry_cap_s=1e-3).start()
        feeder.join(timeout=10.0)
        elapsed = time.monotonic() - t0
        assert len(feeder.submitted) == 1 and not feeder.dropped
        assert feeder.counters["retries"] == 2
        # without the floor both sleeps are <= 1ms; with it, >= 2*floor
        assert elapsed >= 2 * floor

    def test_backpressure_deadline_sheds_load(self):
        """Drop-after-deadline: an item must not stretch tail latency
        unboundedly even with retries left."""
        def submit(tokens, **kw):
            raise Backpressure("queue full")

        feeder = RequestFeeder([[1]], lambda t: (t, {}), submit,
                               retries=10_000, retry_wait_s=0.05,
                               jitter=0.0, deadline_s=0.12).start()
        feeder.join(timeout=10.0)
        assert len(feeder.dropped) == 1
        assert "deadline" in feeder.dropped[0][1]
        assert feeder.counters["dropped_backpressure"] == 1
        # bounded: far fewer sleeps than the retry budget allowed
        assert feeder.counters["retries"] < 10

    def test_per_item_error_drops_item_and_feed_continues(self, tiny,
                                                          rng):
        """One malformed request (submit's contract ValueError) must
        land in `dropped` while the rest of the stream is still served
        — not silently abort the feed (review finding)."""
        cfg = tiny[0]
        eng = _engine(tiny, max_len=32)
        good = rng.integers(0, cfg.vocab_size, (4,)).tolist()
        work = [good, list(range(40)), good]   # middle one can't fit
        feeder = RequestFeeder(
            work, lambda t: (t, {"max_new_tokens": 4}),
            eng.submit).start()
        deadline = time.monotonic() + 30.0
        while ((not feeder.idle or eng.scheduler.depth or eng.n_active)
               and time.monotonic() < deadline):
            eng.step()
        assert len(feeder.submitted) == 2      # both good ones served
        assert len(feeder.dropped) == 1
        assert "cache positions" in feeder.dropped[0][1]
        with pytest.raises(ValueError, match="cache positions"):
            feeder.join()                      # error still surfaced
        assert all(eng.results[r].status == "done"
                   for r in feeder.submitted)
