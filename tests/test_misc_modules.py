"""RNN / weight-norm / ASP / multiproc / examples smoke tests
(reference: ``apex/RNN``, ``apex/reparameterization``,
``apex/contrib/sparsity``, ``apex/parallel/multiproc.py``,
``examples/``)."""

import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.contrib.sparsity import ASP, compute_m4n2_mask
from apex1_tpu.reparameterization import (WeightNormDense,
                                          remove_weight_norm, weight_norm)
from apex1_tpu.rnn import GRU, LSTM, RNNReLU, RNNTanh


class TestRNN:
    def test_lstm_shapes_and_gold(self, rng):
        T, B, I, H = 5, 2, 4, 8
        xs = jnp.asarray(rng.normal(size=(T, B, I)), jnp.float32)
        m = LSTM(input_size=I, hidden_size=H, num_layers=2)
        p = m.init(jax.random.key(0), xs)["params"]
        outs, (h_n, c_n) = m.apply({"params": p}, xs)
        assert outs.shape == (T, B, H)
        assert h_n.shape == (2, B, H) and c_n.shape == (2, B, H)
        # step-by-step numpy gold for layer 0
        wi = np.asarray(p["l0_ih_w"])
        bi = np.asarray(p["l0_ih_b"])
        wh = np.asarray(p["l0_hh_w"])
        h = np.zeros((B, H), np.float32)
        c = np.zeros((B, H), np.float32)
        sig = lambda v: 1 / (1 + np.exp(-v))
        for t in range(T):
            gates = np.asarray(xs[t]) @ wi + bi + h @ wh
            i_, f_, g_, o_ = np.split(gates, 4, axis=-1)
            c = sig(f_) * c + sig(i_) * np.tanh(g_)
            h = sig(o_) * np.tanh(c)
        np.testing.assert_allclose(h_n[0], h, rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("cls", [GRU, RNNReLU, RNNTanh])
    def test_variants_run_and_grad(self, rng, cls):
        xs = jnp.asarray(rng.normal(size=(4, 2, 4)), jnp.float32)
        m = cls(input_size=4, hidden_size=6)
        p = m.init(jax.random.key(0), xs)["params"]
        outs, _ = m.apply({"params": p}, xs)
        assert outs.shape == (4, 2, 6)
        g = jax.grad(lambda p: jnp.sum(
            jnp.square(m.apply({"params": p}, xs)[0])))(p)
        assert all(np.all(np.isfinite(x)) for x in jax.tree.leaves(g))


class TestWeightNorm:
    def test_norm_property(self, rng):
        v = jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)
        g = jnp.asarray(rng.uniform(1, 2, (4,)), jnp.float32)
        w = weight_norm(v, g, dim=1)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(w), axis=0), np.asarray(g),
            rtol=1e-5)

    def test_dense_and_remove(self, rng):
        x = jnp.asarray(rng.normal(size=(3, 8)), jnp.float32)
        m = WeightNormDense(features=4)
        p = m.init(jax.random.key(0), x)["params"]
        out = m.apply({"params": p}, x)
        collapsed = remove_weight_norm(dict(p))
        want = x @ collapsed["kernel"] + p["bias"]
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)

    def test_fp16_safe(self, rng):
        # large fan-in fp16 vector whose naive ||v||^2 overflows fp16
        v = jnp.full((4096, 2), 8.0, jnp.float16)
        w = weight_norm(v, jnp.ones((2,), jnp.float16), dim=1)
        assert np.all(np.isfinite(np.asarray(w, np.float32)))


class TestASP:
    def test_mask_pattern(self, rng):
        w = jnp.asarray(rng.normal(size=(4, 8)), jnp.float32)
        mask = compute_m4n2_mask(w)
        grouped = np.asarray(mask).reshape(4, 2, 4)
        assert np.all(grouped.sum(-1) == 2)  # exactly 2 of every 4
        # kept entries are the 2 largest |w| per group
        wg = np.abs(np.asarray(w)).reshape(4, 2, 4)
        for i in range(4):
            for j in range(2):
                kept = set(np.flatnonzero(grouped[i, j]))
                top2 = set(np.argsort(-wg[i, j])[:2])
                assert kept == top2

    def test_permutation_search_improves_crafted_case(self, rng):
        """Columns arranged so all large magnitudes share one stripe: the
        unpermuted 2:4 mask must drop large entries; the searched
        permutation spreads them and strictly improves efficacy."""
        from apex1_tpu.contrib.sparsity import (mask_efficacy,
                                                permutation_search)
        R, C = 8, 8
        w = np.full((R, C), 0.01, np.float32)
        w[:, :4] = 10.0 + rng.random((R, 4))   # one all-large stripe
        w = jnp.asarray(w)
        base = float(mask_efficacy(w))
        perm, mask, eff = permutation_search(w)
        assert sorted(np.asarray(perm).tolist()) == list(range(C))
        # mask is a valid 2:4 pattern in the PERMUTED order
        mp = np.asarray(mask)[:, np.asarray(perm)].reshape(R, C // 4, 4)
        assert np.all(mp.sum(-1) == 2)
        assert float(eff) > base + 0.2  # large entries now all retained

    def test_permutation_search_never_hurts(self, rng):
        from apex1_tpu.contrib.sparsity import (mask_efficacy,
                                                permutation_search)
        w = jnp.asarray(rng.normal(size=(16, 12)), jnp.float32)
        base = float(mask_efficacy(w))
        _, _, eff = permutation_search(w, max_swaps=64)
        assert float(eff) >= base - 1e-6

    def test_apply_masks(self, rng):
        params = {"dense": {"kernel": jnp.asarray(
            rng.normal(size=(8, 8)), jnp.float32),
            "bias": jnp.ones((8,))}}
        asp = ASP()
        asp.compute_sparse_masks(params)
        sparse = asp.apply_masks(params)
        k = np.asarray(sparse["dense"]["kernel"]).reshape(8, 2, 4)
        assert np.all((k != 0).sum(-1) <= 2)
        np.testing.assert_array_equal(sparse["dense"]["bias"],
                                      params["dense"]["bias"])


# every example script, grouped so each child process (one cold JAX
# import + backend init, ~10-12s) amortizes over several scripts —
# 9 solo children cost ~1.5 min of pure startup on the single-core box
#
# SHARED-BACKEND CONSTRAINT: a group is ONE process, so JAX's backend
# (platform + virtual device count from XLA_FLAGS) is pinned by
# whichever script initializes it first — every script grouped together
# must expect the same platform/device-count (all current examples use
# the default cpu x 8). A future example needing a different count must
# go in its OWN group (or the runner must assert jax.device_count()
# per script) — grouped after an 8-device script it would silently run
# under a stale mesh (ADVICE r5).
_EXAMPLE_GROUPS = {
    "data_parallel": [
        ("examples/distributed_data_parallel.py", []),
        ("examples/gpt2_amp.py", ["--tiny", "--steps", "3", "--seq", "64"]),
        ("examples/imagenet_amp.py", ["--tiny", "--steps", "3", "--batch",
                                      "8", "--image", "32"]),
    ],
    "model_parallel": [
        ("examples/llama_distributed.py", ["--steps", "2", "--tp", "2",
                                           "--fsdp", "2", "--dp", "2",
                                           "--batch", "4", "--seq", "64"]),
        ("examples/gpt2_pp_tied.py", ["--steps", "3", "--seq", "32",
                                      "--hidden", "32"]),
        ("examples/llama_3d.py", ["--steps", "3", "--seq", "32",
                                  "--hidden", "32", "--chunks", "2"]),
    ],
    "encdec_serving": [
        ("examples/t5_seq2seq.py", ["--steps", "3", "--batch", "4"]),
        ("examples/rnnt_speech.py", ["--steps", "3", "--batch", "4"]),
        ("examples/serving_llama.py", ["--tiny", "--new", "6", "--beams",
                                       "2", "--prompt-len", "6"]),
    ],
}

# each script execs in a pristine __main__-style namespace inside the
# shared child; a failure names the script in the marker line
_GROUP_RUNNER = """
import sys
import jax
jax.config.update('jax_platforms', 'cpu')
for script, args in SCRIPTS:
    print('==RUNNING==', script, flush=True)
    sys.argv = [script] + args
    exec(compile(open(script).read(), script, 'exec'), {'__name__': '__main__'})
    print('==OK==', script, flush=True)
"""


@pytest.mark.parametrize("group", sorted(_EXAMPLE_GROUPS))
@pytest.mark.slow
def test_examples_smoke(group):
    """≙ reference examples/ as integration tests (SURVEY §4.1 L1)."""
    import os
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    env["APEX1_FORCE_CPU"] = "1"
    # the driver environment exports JAX_PLATFORMS=axon; examples honor
    # that env var by design (it must beat the sitecustomize pin), so
    # the harness must hand the child a fully-specified platform env —
    # an inherited 'axon' would override the jax.config cpu preamble
    # and hang on a dead tunnel
    env["JAX_PLATFORMS"] = "cpu"
    # warm-cache economics for the suite (VERDICT r4 Weak #5): the
    # example children are fresh processes, so without the persistent
    # cache every suite run pays their full compile cost. Env-var form
    # because the examples themselves stay plain user scripts.
    from apex1_tpu.testing import child_cache_env
    env.update(child_cache_env())
    scripts = _EXAMPLE_GROUPS[group]
    # 300s per script, as before grouping (cold-cache compiles on the
    # single-core box need the full budget); a timeout still names the
    # hung script via the last ==RUNNING== marker
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             f"SCRIPTS = {scripts!r}\n" + _GROUP_RUNNER],
            capture_output=True, text=True, timeout=300 * len(scripts),
            env=env, cwd=".")
        rc, out, err = r.returncode, r.stdout, r.stderr
    except subprocess.TimeoutExpired as e:
        def _txt(b):
            return b.decode("utf-8", "replace") if isinstance(b, bytes) \
                else (b or "")
        rc, out, err = "timeout", _txt(e.stdout), _txt(e.stderr)
    markers = [l for l in out.splitlines() if l.startswith("==")]
    assert rc == 0, (f"rc={rc} last marker: {markers[-1:]}\n{err[-2000:]}")
