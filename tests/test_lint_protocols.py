"""graftlint APX3xx suite — the serving-protocol model checker.

The acceptance spine (ISSUE 17): every shipped PR 7/PR 16 review-fix
race, re-introduced into the committed fixture corpus under
tests/fixtures/protocols/, MUST be flagged with its rule id AND a
state-trace counterexample naming the interleaving; the golden
(post-fix) variants and the live serving/autopilot tree MUST pass
clean. The fact-flip matrix pins every single-guard regression to the
rule it produces, and the two-tier lint cache (whole-run memo +
per-file parse memo) is pinned by behavioral tests.

Fixtures are PARSE-ONLY: they run in memory through
``lint_sources(protocols=True)`` — the very same extractors that check
the live tree — and are never imported.
"""

import json
import os
import pickle
import re
import subprocess
import sys
import textwrap

import pytest

from apex1_tpu.lint import lint_files, lint_paths, lint_sources

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXDIR = os.path.join(REPO, "tests", "fixtures", "protocols")

_TRACE = re.compile(r"counterexample \(\d+ steps\): .+ -> ")


def fixture(name):
    with open(os.path.join(FIXDIR, name), encoding="utf-8") as fh:
        return fh.read()


def run_fixture(name, **kw):
    return lint_sources({f"fix/{name}": (f"fix.{name[:-3]}",
                                         fixture(name))},
                        protocols=True, **kw)


def run_lint(src, path="fix/mod.py", modname="fix.mod"):
    return lint_sources({path: (modname, textwrap.dedent(src))},
                        protocols=True)


def codes(res, *, suppressed=False):
    pool = res.suppressed() if suppressed else res.unsuppressed()
    return {f.rule for f in pool}


GOLDEN = ["sched_golden.py", "replica_golden.py", "frontend_golden.py",
          "disagg_golden.py", "kv_golden.py", "autopilot_golden.py"]

#: (fixture, must-flag rule, message fragment, trace expected?)
MUST_FLAG = [
    ("sched_shed_bug.py", "APX303", "not strictly weaker", True),
    ("replica_restart_resurrect_bug.py", "APX304",
     "restart() resubmitted r0 while its cancel was pending", True),
    ("replica_drain_resurrect_bug.py", "APX304",
     "drain_inflight() forwarded r0 with its cancel still pending",
     True),
    ("replica_unfenced_bug.py", "APX302",
     "two terminal results published for r0", True),
    ("frontend_displace_first_bug.py", "APX306",
     "feasibility must be checked before displacement", True),
    ("frontend_hedge_streaming_bug.py", "APX306",
     "already streaming", True),
    ("frontend_hedge_routed_bug.py", "APX302",
     "hedge fired onto replica B", True),
    ("frontend_failover_bug.py", "APX302",
     "failover resubmitted g0", True),
    ("frontend_route_strand_bug.py", "APX305",
     "late result for g0 is stranded", True),
    ("frontend_unbanked_bug.py", "APX308",
     "'mode' is never banked", False),
    ("disagg_cancel_window_bug.py", "APX304",
     "resurrected from the handoff window", True),
    ("disagg_unbounded_bug.py", "APX307",
     "re-route ladder never terminates", True),
    ("kv_noverify_bug.py", "APX307",
     "installed without the arrival re-digest", True),
    ("autopilot_blind_relax_bug.py", "APX307",
     "relaxed during a metrics blackout", True),
    ("autopilot_kind_drift_bug.py", "APX308",
     "Action kind 'shift_pool'", False),
    ("autopilot_ladder_bug.py", "APX307",
     "no MODES_DOWN edge", False),
    ("drift_bug.py", "APX301", "required method", False),
]


class TestFixtureCorpus:
    @pytest.mark.parametrize("name", GOLDEN)
    def test_golden_fixtures_lint_clean(self, name):
        res = run_fixture(name)
        assert res.ok, [f.render() for f in res.unsuppressed()]

    @pytest.mark.parametrize("name,rule,frag,traced", MUST_FLAG,
                             ids=[m[0] for m in MUST_FLAG])
    def test_must_flag_with_counterexample(self, name, rule, frag,
                                           traced):
        res = run_fixture(name)
        hits = [f for f in res.unsuppressed()
                if f.rule == rule and frag in f.message]
        assert hits, (rule, frag,
                      [f.render() for f in res.unsuppressed()])
        if traced:
            assert _TRACE.search(hits[0].message), hits[0].message
        # every finding is anchored to a real line of the fixture
        n_lines = fixture(name).count("\n") + 1
        for f in res.unsuppressed():
            assert 1 <= f.line <= n_lines, f.render()

    def test_fixture_corpus_is_exhaustive(self):
        """Every .py file in the corpus is either golden or must-flag —
        a fixture added without a pin here is a test hole."""
        on_disk = {f for f in os.listdir(FIXDIR) if f.endswith(".py")}
        pinned = set(GOLDEN) | {m[0] for m in MUST_FLAG}
        assert on_disk == pinned, on_disk ^ pinned

    def test_without_protocols_flag_fixtures_pass(self):
        src = fixture("replica_drain_resurrect_bug.py")
        res = lint_sources({"fix/m.py": ("fix.m", src)})
        assert not {c for c in codes(res) if c.startswith("APX3")}

    def test_suppression_grammar_covers_apx3xx(self):
        src = fixture("sched_shed_bug.py").replace(
            "if r.rank < incoming_rank:",
            "if r.rank < incoming_rank:  "
            "# graftlint: allow(APX303) -- fixture: pre-fix shape kept "
            "on purpose")
        res = lint_sources({"fix/m.py": ("fix.m", src)}, protocols=True)
        assert res.ok, [f.render() for f in res.unsuppressed()]
        assert "APX303" in codes(res, suppressed=True)


class TestFactFlipMatrix:
    """models.py unit surface: all-true facts explore clean; every
    single-guard flip produces exactly the rule the ladder documents."""

    FLIPS = [
        ("scheduler", "shed_strictly_weaker", {"APX303"}),
        ("replica", "restart_honors_pending_cancels", {"APX304"}),
        ("replica", "drain_honors_pending_cancels", {"APX304"}),
        ("replica", "generation_fenced", {"APX302"}),
        ("replica", "restart_quarantines_poison", {"APX307"}),
        ("frontend", "feasibility_before_displacement", {"APX306"}),
        ("frontend", "displace_skips_already_shed", {"APX306"}),
        ("frontend", "route_waits_for_pending_legs",
         {"APX305", "APX307"}),
        ("frontend", "hedge_requires_no_first_token", {"APX306"}),
        ("frontend", "hedge_excludes_routed", {"APX302"}),
        ("frontend", "failover_skips_live_hedge", {"APX302"}),
        ("disagg", "reroute_bounded", {"APX307"}),
        ("disagg", "verify_before_install", {"APX307"}),
        ("autopilot", "evidence_freeze", {"APX307"}),
        ("autopilot", "donor_keeps_one", {"APX306"}),
    ]

    def test_all_true_explores_clean(self):
        from apex1_tpu.lint.protocols.models import (FAMILY_FACTS,
                                                     run_protocol)
        for family in FAMILY_FACTS:
            assert run_protocol(family, frozenset()) == ()

    @pytest.mark.parametrize("family,fact,expected", FLIPS,
                             ids=[f"{f[0]}-{f[1]}" for f in FLIPS])
    def test_single_flip_produces_documented_rule(self, family, fact,
                                                  expected):
        from apex1_tpu.lint.protocols.models import run_protocol
        out = run_protocol(family, frozenset([(fact, False)]))
        assert {p.code for p in out} == expected, \
            [(p.code, p.key) for p in out]

    def test_window_guards_are_defense_in_depth(self):
        """Neither window guard alone resurrects a cancel — the purge
        and the _live check each cover the other — but dropping BOTH
        reaches the APX304 resurrection. Pins why
        disagg_cancel_window_bug.py removes the pair."""
        from apex1_tpu.lint.protocols.models import run_protocol
        for fact in ("pending_checks_live", "cancel_purges_window"):
            assert run_protocol("disagg",
                                frozenset([(fact, False)])) == ()
        out = run_protocol("disagg",
                           frozenset([("pending_checks_live", False),
                                      ("cancel_purges_window", False)]))
        assert "APX304" in {p.code for p in out}

    def test_explorer_truncation_is_loud(self):
        """A model that never quiesces blows the state budget and is
        reported, never silently dropped."""
        from apex1_tpu.lint.protocols.explore import explore

        class Runaway:
            name, config = "runaway", "loop"

            def initial(self):
                return 0

            def actions(self, s):
                return [(f"tick {s}", s + 1, ())]

            def check(self, s):
                return ()

            def quiescence(self, s):
                return ()

        res = explore(Runaway(), max_states=500)
        assert res.truncated
        assert res.n_states >= 500


class TestRepoSelfCheck:
    def test_live_tree_protocols_clean(self):
        res = lint_paths(["apex1_tpu", "tools", "examples"], root=REPO,
                         protocols=True)
        apx3 = [f for f in res.unsuppressed()
                if f.rule.startswith("APX3")]
        assert not apx3, [f.render() for f in apx3]
        assert res.n_files > 160

    def test_live_families_all_extracted(self):
        """The extractors must keep matching the real classes — a
        rename that breaks detection would silently skip the family."""
        from apex1_tpu.lint import collect_files, module_name_for
        from apex1_tpu.lint.core import parse_module
        from apex1_tpu.lint.protocols.extract import extract_all
        fams = set()
        for f in collect_files(["apex1_tpu"], root=REPO):
            rel = os.path.relpath(f, REPO)
            mod = parse_module(rel, open(f, encoding="utf-8").read(),
                               module_name_for(f, REPO))
            for ex in extract_all(mod):
                fams.add((ex.family, ex.name))
                assert not ex.missing, (ex.family, ex.name, ex.missing)
                for fact, val in ex.facts.items():
                    assert val is True, (ex.family, ex.name, fact)
        assert ("scheduler", "Scheduler") in fams
        assert ("replica", "ReplicaSupervisor") in fams
        assert ("frontend", "ServingFrontend") in fams
        assert ("disagg", "DisaggFrontend") in fams
        assert ("kv", "<module>") in fams
        assert ("policy", "<module>") in fams
        assert ("controller", "Autopilot") in fams

    def test_protocol_rules_registered(self):
        from apex1_tpu.lint.core import RULE_SLUGS
        from apex1_tpu.lint.protocols import PROTOCOL_RULES
        assert [r.code for r in PROTOCOL_RULES] == [
            "APX301", "APX302", "APX303", "APX304", "APX305",
            "APX306", "APX307", "APX308"]
        for r in PROTOCOL_RULES:
            assert RULE_SLUGS[r.code] == r.slug

    def test_baseline_banked_with_protocol_family(self):
        path = os.path.join(REPO, "perf_results", "lint_baseline.json")
        doc = json.load(open(path))
        assert doc["ok"] is True
        assert doc["counts"]["unsuppressed"] == 0
        assert "APX304" in doc["rules"], \
            "re-bank with `python tools/lint.py --kernels --protocols" \
            " --json`"


class TestLintCache:
    """The two-tier .graftlint_cache: whole-run memo + parse memo."""

    def _write(self, d, name, src):
        p = d / name
        p.write_text(src)
        return str(p)

    def test_memo_hit_skips_parsing_and_keeps_findings(
            self, tmp_path, monkeypatch):
        f = self._write(tmp_path, "bug.py",
                        fixture("sched_shed_bug.py"))
        cache = str(tmp_path / "cache")
        first = lint_files([f], root=str(tmp_path), protocols=True,
                           cache=cache)
        assert "APX303" in codes(first)
        import apex1_tpu.lint as lintmod

        def boom(*a, **kw):
            raise AssertionError("memo miss: parse_module was called")

        monkeypatch.setattr(lintmod, "parse_module", boom)
        second = lint_files([f], root=str(tmp_path), protocols=True,
                            cache=cache)
        assert codes(second) == codes(first)
        assert [x.render() for x in second.findings] == \
            [x.render() for x in first.findings]

    def test_changed_file_invalidates_run_memo(self, tmp_path):
        f = self._write(tmp_path, "bug.py",
                        fixture("sched_shed_bug.py"))
        cache = str(tmp_path / "cache")
        assert "APX303" in codes(
            lint_files([f], root=str(tmp_path), protocols=True,
                       cache=cache))
        self._write(tmp_path, "bug.py", fixture("sched_golden.py"))
        res = lint_files([f], root=str(tmp_path), protocols=True,
                         cache=cache)
        assert res.ok, [x.render() for x in res.unsuppressed()]

    def test_parse_tier_reparses_only_the_changed_file(
            self, tmp_path, monkeypatch):
        fa = self._write(tmp_path, "a.py", fixture("sched_golden.py"))
        fb = self._write(tmp_path, "b.py",
                         fixture("replica_golden.py"))
        cache = str(tmp_path / "cache")
        lint_files([fa, fb], root=str(tmp_path), protocols=True,
                   cache=cache)
        import apex1_tpu.lint as lintmod
        real = lintmod.parse_module
        parsed = []

        def spy(path, text, modname=""):
            parsed.append(path)
            return real(path, text, modname)

        monkeypatch.setattr(lintmod, "parse_module", spy)
        self._write(tmp_path, "b.py",
                    fixture("replica_golden.py") + "\n# touched\n")
        res = lint_files([fa, fb], root=str(tmp_path), protocols=True,
                         cache=cache)
        assert res.ok
        assert parsed == ["b.py"], parsed

    def test_run_memo_keyed_by_flags(self, tmp_path):
        f = self._write(tmp_path, "bug.py",
                        fixture("sched_shed_bug.py"))
        cache = str(tmp_path / "cache")
        plain = lint_files([f], root=str(tmp_path), cache=cache)
        assert plain.ok
        with_protocols = lint_files([f], root=str(tmp_path),
                                    protocols=True, cache=cache)
        assert "APX303" in codes(with_protocols)

    def test_corrupt_cache_fails_open(self, tmp_path):
        f = self._write(tmp_path, "bug.py",
                        fixture("sched_shed_bug.py"))
        cache = tmp_path / "cache"
        for payload in (b"", b"not a pickle",
                        pickle.dumps({"version": -1, "runs": {},
                                      "entries_blob": None}),
                        pickle.dumps(["wrong", "shape"])):
            cache.write_bytes(payload)
            res = lint_files([str(f)], root=str(tmp_path),
                             protocols=True, cache=str(cache))
            assert "APX303" in codes(res)

    def test_suppression_state_resets_on_parse_cache_hit(
            self, tmp_path):
        """A cache-hit module must start the run pristine: its
        suppression `used` bits are per-run state."""
        src = fixture("sched_shed_bug.py").replace(
            "if r.rank < incoming_rank:",
            "if r.rank < incoming_rank:  "
            "# graftlint: allow(APX303) -- fixture: kept on purpose")
        fa = self._write(tmp_path, "a.py", src)
        cache = str(tmp_path / "cache")
        first = lint_files([fa], root=str(tmp_path), protocols=True,
                           cache=cache)
        assert first.ok and not first.unused
        # invalidate only the RUN memo (flag flip) so the parse-tier
        # entry is reused for a fresh apply_suppressions pass
        second = lint_files([fa], root=str(tmp_path), protocols=True,
                            kernels=True, cache=cache)
        assert second.ok, [x.render() for x in second.unsuppressed()]
        assert not second.unused
        assert "APX303" in codes(second, suppressed=True)


class TestChangedMergeBase:
    def _git(self, cwd, *args):
        return subprocess.run(["git", *args], cwd=cwd,
                              capture_output=True, text=True,
                              check=True)

    def _load_cli(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "lint_cli_under_test",
            os.path.join(REPO, "tools", "lint.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_changed_diffs_against_merge_base(self, tmp_path,
                                              monkeypatch):
        """The pre-commit scope must include commits already on the
        branch — the old vs-HEAD diff silently skipped them."""
        repo = tmp_path / "r"
        (repo / "apex1_tpu").mkdir(parents=True)
        self._git(tmp_path, "init", "-b", "main", "r")
        self._git(repo, "config", "user.email", "t@example.com")
        self._git(repo, "config", "user.name", "t")
        (repo / "apex1_tpu" / "base.py").write_text("BASE = 1\n")
        self._git(repo, "add", "-A")
        self._git(repo, "commit", "-m", "base")
        self._git(repo, "checkout", "-b", "feature")
        (repo / "apex1_tpu" / "committed.py").write_text("X = 1\n")
        self._git(repo, "add", "-A")
        self._git(repo, "commit", "-m", "feature change")
        (repo / "apex1_tpu" / "untracked.py").write_text("Y = 1\n")
        cli = self._load_cli()
        monkeypatch.setattr(cli, "REPO", str(repo))
        base = cli.merge_base()
        head = self._git(repo, "rev-parse",
                         "main").stdout.strip()
        assert base == head
        assert cli.changed_files() == ["apex1_tpu/committed.py",
                                       "apex1_tpu/untracked.py"]

    def test_merge_base_falls_back_to_head(self, tmp_path,
                                           monkeypatch):
        """Detached/remoteless repos with no base ref keep the old
        vs-HEAD behavior rather than erroring."""
        repo = tmp_path / "r"
        (repo / "apex1_tpu").mkdir(parents=True)
        self._git(tmp_path, "init", "-b", "work", "r")
        self._git(repo, "config", "user.email", "t@example.com")
        self._git(repo, "config", "user.name", "t")
        (repo / "apex1_tpu" / "base.py").write_text("BASE = 1\n")
        self._git(repo, "add", "-A")
        self._git(repo, "commit", "-m", "base")
        cli = self._load_cli()
        monkeypatch.setattr(cli, "REPO", str(repo))
        assert cli.merge_base() == "HEAD"


class TestCliProtocols:
    def _run(self, *args, env_extra=None):
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               **(env_extra or {})}
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "lint.py"),
             *args],
            capture_output=True, text=True, cwd=REPO, env=env)

    def test_protocols_flag_finds_fixture_race(self, tmp_path):
        d = tmp_path / "apex1_tpu"
        d.mkdir()
        (d / "bug.py").write_text(
            fixture("replica_drain_resurrect_bug.py"))
        p = self._run("--protocols", "--no-cache", str(d))
        assert p.returncode == 1, p.stdout + p.stderr
        assert "APX304" in p.stdout
        assert "counterexample" in p.stdout

    def test_protocols_flag_clean_on_golden(self, tmp_path):
        d = tmp_path / "apex1_tpu"
        d.mkdir()
        (d / "ok.py").write_text(fixture("replica_golden.py"))
        p = self._run("--protocols", "--no-cache", str(d))
        assert p.returncode == 0, p.stdout + p.stderr

    def test_list_rules_includes_family(self):
        p = self._run("--list-rules")
        assert p.returncode == 0
        for code in ("APX301", "APX304", "APX308"):
            assert code in p.stdout

    def test_cli_protocols_path_is_jax_free(self, tmp_path):
        """The check_all step's cold-start contract: the --protocols
        CLI never imports jax. Poison jax on the path — the model
        checker must still run and still find the fixture race."""
        poison = tmp_path / "site"
        poison.mkdir()
        (poison / "jax.py").write_text(
            "raise ImportError('poisoned: the lint CLI must stay "
            "jax-free')\n")
        d = tmp_path / "apex1_tpu"
        d.mkdir()
        (d / "bug.py").write_text(fixture("sched_shed_bug.py"))
        p = self._run("--protocols", "--no-cache", str(d),
                      env_extra={"PYTHONPATH": str(poison)})
        assert p.returncode == 1, p.stdout + p.stderr
        assert "poisoned" not in p.stderr
        assert "APX303" in p.stdout
