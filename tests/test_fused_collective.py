"""Fused computation-collective forms (`ops.fused_collective`) vs their
decomposed PR 4 counterparts, on the 8-device virtual CPU mesh.

The pins, per form:

- fused SP matmuls (`fused_matmul_reduce_scatter` /
  `fused_all_gather_matmul`): BITWISE vs `mappings.matmul_reduce_scatter`
  / `all_gather_matmul` on BOTH dispatch paths (interpret Pallas and
  XLA composite), custom-VJP grads vs the decomposed VJPs, layer-level
  ``fused=`` plumbing, and the dependence-mode hlo_probe with the
  serialized rotate-then-dot form as the falsifiable negative control.
- all-gather-fused flash attention: BITWISE vs `ring_attention` on the
  XLA path (identical code), ulp-tight on the interpret path (the merge
  runs inside the kernel there; XLA CPU's fusion-context FMA
  contraction moves the last bit of `out_prev·w_a + out_t·w_b` — the
  components are bitwise in isolation), grads vs the ring VJP incl.
  GQA group-sum, segments, and cp=2, plus the dependence probe (the
  serialized ring is the shared negative control).
- fused vocab-parallel linear CE merge: BITWISE loss AND grads vs the
  decomposed 4-collective ladder on both paths, plus the structural
  2-vs-4 all-reduce count via `hlo_probe.count_collectives` (the
  decomposed program is the falsifiable high-count control).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex1_tpu.parallel.ring_attention import (ring_attention,
                                               ring_attention_serial)
from apex1_tpu.core.mesh import make_mesh
from apex1_tpu.ops import fused_collective as fc
from apex1_tpu.ops._common import force_impl
from apex1_tpu.testing.hlo_probe import (assert_collective_overlap,
                                         check_collective_overlap,
                                         count_collectives, optimized_hlo)
from apex1_tpu.transformer import tensor_parallel as tp


@pytest.fixture()
def mesh(devices):
    return make_mesh(dp=2, tp=4)


def tp_sm(mesh, fn, in_specs, out_specs):
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)


def _bitwise(a, b):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestFusedMatmuls:
    """fused_matmul_reduce_scatter / fused_all_gather_matmul vs the
    decomposed PR 4 forms — the acceptance-critical bitwise pins."""

    S, IN, OUT = 32, 16, 24

    def _arrs(self, rng):
        x = jnp.asarray(rng.normal(size=(self.S, self.IN)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(self.IN, self.OUT)), jnp.float32)
        return x, w

    @pytest.mark.parametrize("impl", ["xla", "pallas"])
    def test_mrs_bitwise_vs_decomposed(self, mesh, rng, impl):
        x, w = self._arrs(rng)
        specs = ((P(None, "tp"), P("tp", None)), P("tp", None))
        with force_impl(impl):
            a = tp_sm(mesh, lambda x, w: fc.fused_matmul_reduce_scatter(
                x, w, "tp", 0), *specs)(x, w)
            b = tp_sm(mesh, lambda x, w: tp.matmul_reduce_scatter(
                x, w, "tp", 0), *specs)(x, w)
        _bitwise(a, b)

    @pytest.mark.parametrize("impl", ["xla", "pallas"])
    def test_agm_bitwise_vs_decomposed(self, mesh, rng, impl):
        x, w = self._arrs(rng)
        specs = ((P("tp", None), P(None, "tp")), P(None, "tp"))
        with force_impl(impl):
            a = tp_sm(mesh, lambda x, w: fc.fused_all_gather_matmul(
                x, w, "tp", 0), *specs)(x, w)
            b = tp_sm(mesh, lambda x, w: tp.all_gather_matmul(
                x, w, "tp", 0), *specs)(x, w)
        _bitwise(a, b)

    def test_rank3_operand_bitwise(self, mesh, rng):
        """The SP activations are (S, mb, hid) in the 3D step — the
        whole-tile kernel's rank-preserving dot must still match."""
        x = jnp.asarray(rng.normal(size=(self.S, 2, self.IN)),
                        jnp.float32)
        w = jnp.asarray(rng.normal(size=(self.IN, self.OUT)), jnp.float32)
        specs = ((P(None, None, "tp"), P("tp", None)), P("tp",))
        with force_impl("pallas"):
            a = tp_sm(mesh, lambda x, w: fc.fused_matmul_reduce_scatter(
                x, w, "tp", 0), *specs)(x, w)
            b = tp_sm(mesh, lambda x, w: tp.matmul_reduce_scatter(
                x, w, "tp", 0), *specs)(x, w)
        _bitwise(a, b)

    def test_serial_matches_overlapped_values(self, mesh, rng):
        """The serialized negative-control form computes the same
        gathered product (only its schedule differs)."""
        x, w = self._arrs(rng)
        specs = ((P("tp", None), P(None, "tp")), P(None, "tp"))
        with force_impl("pallas"):
            a = tp_sm(mesh,
                      lambda x, w: fc.fused_all_gather_matmul_serial(
                          x, w, "tp", 0), *specs)(x, w)
            b = tp_sm(mesh, lambda x, w: fc.fused_all_gather_matmul(
                x, w, "tp", 0), *specs)(x, w)
        _bitwise(a, b)

    def test_explicit_blocks_grid_path(self, mesh, rng):
        """Explicit (block_m, block_n) exercise the TILED kernel grid in
        interpret mode — allclose vs the decomposed form (tiling
        re-associates nothing: K is untiled, so this is tight)."""
        x, w = self._arrs(rng)
        specs = ((P(None, "tp"), P("tp", None)), P("tp", None))
        with force_impl("pallas"):
            a = tp_sm(mesh, lambda x, w: fc.fused_matmul_reduce_scatter(
                x, w, "tp", 0, 16, 128), *specs)(x, w)
            b = tp_sm(mesh, lambda x, w: tp.matmul_reduce_scatter(
                x, w, "tp", 0), *specs)(x, w)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)

    @pytest.mark.parametrize("which", ["mrs", "agm"])
    @pytest.mark.parametrize("impl", ["xla", "pallas"])
    def test_grads_match_decomposed(self, mesh, rng, which, impl):
        """Custom-VJP parity: dx routes through the dual's fused form,
        dw through the re-gathered contraction — same math as the
        decomposed VJPs, so grads must be bitwise too."""
        x, w = self._arrs(rng)
        if which == "mrs":
            in_specs = (P(None, "tp"), P("tp", None))
            fused = lambda x, w: fc.fused_matmul_reduce_scatter(
                x, w, "tp", 0)
            dec = lambda x, w: tp.matmul_reduce_scatter(x, w, "tp", 0)
        else:
            in_specs = (P("tp", None), P(None, "tp"))
            fused = lambda x, w: fc.fused_all_gather_matmul(
                x, w, "tp", 0)
            dec = lambda x, w: tp.all_gather_matmul(x, w, "tp", 0)

        def grads(f):
            sm = tp_sm(mesh, lambda x, w: jnp.sum(f(x, w) ** 2),
                       in_specs, P())
            return jax.jit(jax.grad(lambda x, w: sm(x, w).sum(),
                                    argnums=(0, 1)))(x, w)

        with force_impl(impl):
            for a, b in zip(grads(fused), grads(dec)):
                _bitwise(a, b)

    def test_layer_fused_kwarg_parity(self, mesh, rng):
        """column/row SP paths with fused= on == overlap= numbers, and
        fused=+overlap= together is rejected."""
        x, w = self._arrs(rng)

        def col(**kw):
            return tp_sm(
                mesh,
                lambda x, w: tp.column_parallel_linear(
                    x, w, sequence_parallel_enabled=True,
                    axis_name="tp", **kw),
                (P("tp", None), P(None, "tp")), P(None, "tp"))(x, w)

        with force_impl("pallas"):
            _bitwise(col(fused=True), col(overlap=True))

        def row(**kw):
            return tp_sm(
                mesh,
                lambda x, w: tp.row_parallel_linear(
                    x, w, sequence_parallel_enabled=True,
                    axis_name="tp", **kw),
                (P(None, "tp"), P("tp", None)), P("tp", None))(x, w)

        with force_impl("pallas"):
            _bitwise(row(fused=True), row(overlap=True))
        with pytest.raises(ValueError, match="exclusive"):
            tp.column_parallel_linear(x, w, overlap=True, fused=True)
        with pytest.raises(ValueError, match="exclusive"):
            tp.row_parallel_linear(x, w, overlap=True, fused=True)

    def test_rdma_form_raises_off_tpu(self, rng):
        x = jnp.zeros((32, 128), jnp.float32)
        w = jnp.zeros((128, 128), jnp.float32)
        with pytest.raises(NotImplementedError, match="compiled-TPU"):
            fc.matmul_reduce_scatter_rdma(x, w, "tp")


class TestFusedMatmulProbes:
    """Dependence-mode overlap pins (the tier-1 half of the probe
    contract; tools/aot_check.py runs the async half on v5e
    executables)."""

    def _mlp(self, mesh, rng, fn_ag, fn_rs):
        x = jnp.asarray(rng.normal(size=(64, 16)), jnp.float32)
        w1 = jnp.asarray(rng.normal(size=(16, 24)), jnp.float32)
        w2 = jnp.asarray(rng.normal(size=(24, 16)), jnp.float32)

        def local(x, w1, w2):
            h = fn_ag(x, w1, "tp", 0)
            return fn_rs(h.astype(jnp.float32), w2, "tp", 0)

        return tp_sm(mesh, local,
                     (P("tp"), P(None, "tp"), P("tp", None)),
                     P("tp")), (x, w1, w2)

    @pytest.mark.parametrize("impl", ["xla", "pallas"])
    def test_fused_mlp_overlapped(self, mesh, rng, impl):
        with force_impl(impl):
            f, arrs = self._mlp(mesh, rng, fc.fused_all_gather_matmul,
                                fc.fused_matmul_reduce_scatter)
            rep = assert_collective_overlap(optimized_hlo(f, *arrs),
                                            expect_mode="dependence")
        assert len(rep.bodies) >= 2

    @pytest.mark.parametrize("impl", ["xla", "pallas"])
    def test_serialized_agm_fails_probe(self, mesh, rng, impl):
        x = jnp.asarray(rng.normal(size=(64, 16)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(16, 24)), jnp.float32)
        with force_impl(impl):
            f = tp_sm(mesh,
                      lambda x, w: fc.fused_all_gather_matmul_serial(
                          x, w, "tp", 0),
                      (P("tp", None), P(None, "tp")), P(None, "tp"))
            rep = check_collective_overlap(optimized_hlo(f, x, w))
        assert rep.bodies and not rep.ok, rep.detail

    def test_fused_grad_overlapped(self, mesh, rng):
        """The custom VJPs route dx through the dual fused ring — the
        backward loop bodies must pass the dependence probe too."""
        with force_impl("pallas"):
            f, arrs = self._mlp(mesh, rng, fc.fused_all_gather_matmul,
                                fc.fused_matmul_reduce_scatter)

            def loss(x, w1, w2):
                return jnp.sum(f(x, w1, w2).astype(jnp.float32) ** 2)

            rep = assert_collective_overlap(
                optimized_hlo(jax.grad(loss, argnums=(0, 1, 2)), *arrs),
                expect_mode="dependence")
        assert len(rep.bodies) >= 2


class TestAllGatherFlashAttention:
    """all_gather_flash_attention vs ring_attention (its decomposed PR 4
    counterpart): same schedule, merge fused into the kernel epilogue."""

    def _qkv(self, rng, B=1, Hq=4, Hkv=4, S=128, D=32, dtype=jnp.float32):
        q = jnp.asarray(rng.normal(size=(B, Hq, S, D)), dtype)
        k = jnp.asarray(rng.normal(size=(B, Hkv, S, D)), dtype)
        v = jnp.asarray(rng.normal(size=(B, Hkv, S, D)), dtype)
        return q, k, v

    def _sm(self, cp, fn, n_extra=0):
        mesh = make_mesh(cp=cp, dp=1, devices=jax.devices()[:cp])
        spec = P(None, None, "cp", None)
        extra = (P(None, "cp"),) * n_extra
        return jax.shard_map(fn, mesh=mesh, in_specs=(spec,) * 3 + extra,
                             out_specs=spec, check_vma=False)

    @pytest.mark.parametrize("causal", [False, True])
    def test_xla_path_bitwise_vs_ring(self, devices, rng, causal):
        q, k, v = self._qkv(rng)
        with force_impl("xla"):
            a = self._sm(4, lambda q, k, v: fc.all_gather_flash_attention(
                q, k, v, "cp", causal=causal))(q, k, v)
            b = self._sm(4, lambda q, k, v: ring_attention(
                q, k, v, "cp", causal=causal))(q, k, v)
        _bitwise(a, b)

    @pytest.mark.parametrize("causal", [False, True])
    def test_interpret_path_ulp_vs_ring(self, devices, rng, causal):
        """Interpret path: the merge runs INSIDE the kernel; XLA CPU's
        fusion-context FMA contraction moves at most the last bit of
        `out_prev*w_a + out_t*w_b` (components verified bitwise in
        isolation), so the pin is <= 2 ulp, not bitwise."""
        q, k, v = self._qkv(rng)
        with force_impl("pallas"):
            a = self._sm(4, lambda q, k, v: fc.all_gather_flash_attention(
                q, k, v, "cp", causal=causal))(q, k, v)
            b = self._sm(4, lambda q, k, v: ring_attention(
                q, k, v, "cp", causal=causal))(q, k, v)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)

    def test_gqa_and_cp2(self, devices, rng):
        q, k, v = self._qkv(rng, Hq=4, Hkv=2, S=64)
        for impl in ("xla", "pallas"):
            with force_impl(impl):
                a = self._sm(2, lambda q, k, v:
                             fc.all_gather_flash_attention(
                                 q, k, v, "cp", causal=True))(q, k, v)
                b = self._sm(2, lambda q, k, v: ring_attention(
                    q, k, v, "cp", causal=True))(q, k, v)
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)

    def test_segments(self, devices, rng):
        q, k, v = self._qkv(rng, S=64)
        segs = jnp.asarray(
            rng.integers(0, 3, (1, 64)).cumsum(axis=-1) // 2, jnp.int32)
        for impl in ("xla", "pallas"):
            with force_impl(impl):
                a = self._sm(4, lambda q, k, v, s:
                             fc.all_gather_flash_attention(
                                 q, k, v, "cp", segment_ids=s),
                             n_extra=1)(q, k, v, segs)
                b = self._sm(4, lambda q, k, v, s: ring_attention(
                    q, k, v, "cp", segment_ids=s), n_extra=1)(q, k, v,
                                                              segs)
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)

    @pytest.mark.parametrize("gqa", [False, True])
    def test_grads_vs_ring(self, devices, rng, gqa):
        """Custom-VJP grad parity incl. the GQA group-sum — the fused
        forward saves the same (out, lse) residuals the ring backward
        consumes, so gradients track the forward's ulp bound."""
        q, k, v = self._qkv(rng, Hq=4, Hkv=2 if gqa else 4, S=64)

        def grads(fn):
            sm = self._sm(2, lambda q, k, v: fn(q, k, v))

            def loss(q, k, v):
                return jnp.sum(sm(q, k, v).astype(jnp.float32) ** 2)

            return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)

        for impl in ("xla", "pallas"):
            with force_impl(impl):
                ga = grads(lambda q, k, v: fc.all_gather_flash_attention(
                    q, k, v, "cp", causal=True))
                gb = grads(lambda q, k, v: ring_attention(
                    q, k, v, "cp", causal=True))
            for a, b in zip(ga, gb):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("impl", ["xla", "pallas"])
    def test_overlap_probe(self, devices, rng, impl):
        q, k, v = self._qkv(rng, S=64)
        with force_impl(impl):
            f = self._sm(4, lambda q, k, v: fc.all_gather_flash_attention(
                q, k, v, "cp", causal=True))
            rep = assert_collective_overlap(optimized_hlo(f, q, k, v),
                                            expect_mode="dependence")
        assert rep.ok
        # the serialized ring is the shared falsifiable negative control
        with force_impl(impl):
            g = self._sm(4, lambda q, k, v: ring_attention_serial(
                q, k, v, "cp", causal=True))
            srep = check_collective_overlap(optimized_hlo(g, q, k, v))
        assert srep.bodies and not srep.ok

    def test_dropout_rejected(self, devices, rng):
        q, k, v = self._qkv(rng, S=64)
        with pytest.raises(TypeError):
            fc.all_gather_flash_attention(q, k, v, "cp", dropout_p=0.1)


class TestFusedVocabParallelCE:
    """vocab_parallel_linear_cross_entropy(fused=True): packed final-
    vocab-tile stats + the 2-collective merge, vs the decomposed
    4-collective ladder."""

    T, H, V = 24, 16, 64

    def _arrs(self, rng):
        x = jnp.asarray(rng.normal(size=(self.T, self.H)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(self.V, self.H)) * 0.1,
                        jnp.float32)
        t = jnp.asarray(rng.integers(0, self.V, (self.T,)), jnp.int32)
        return x, w, t

    def _fn(self, mesh, fused, **kw):
        def run(x, w, t):
            return tp.vocab_parallel_linear_cross_entropy(
                x, w, t, axis_name="tp", fused=fused, **kw)

        return tp_sm(mesh, run, (P(), P("tp", None), P()), P())

    @pytest.mark.parametrize("impl", ["xla", "pallas"])
    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_loss_bitwise(self, mesh, rng, impl, smoothing):
        x, w, t = self._arrs(rng)
        with force_impl(impl):
            a = self._fn(mesh, True, label_smoothing=smoothing)(x, w, t)
            b = self._fn(mesh, False, label_smoothing=smoothing)(x, w, t)
        _bitwise(a, b)

    @pytest.mark.parametrize("impl", ["xla", "pallas"])
    def test_grads_bitwise(self, mesh, rng, impl):
        x, w, t = self._arrs(rng)
        with force_impl(impl):
            def grads(fused):
                f = self._fn(mesh, fused, padding_idx=0)
                return jax.jit(jax.grad(
                    lambda x, w: jnp.sum(f(x, w, t)),
                    argnums=(0, 1)))(x, w)

            for a, b in zip(grads(True), grads(False)):
                _bitwise(a, b)

    def test_collective_count_2_vs_4(self, mesh, rng):
        """The structural pin: the fused merge compiles to exactly TWO
        all-reduces; the decomposed ladder's FOUR is the falsifiable
        negative control (if packing regressed, the counts converge)."""
        x, w, t = self._arrs(rng)
        with force_impl("xla"):
            nf = count_collectives(
                optimized_hlo(self._fn(mesh, True), x, w, t))
            nd = count_collectives(
                optimized_hlo(self._fn(mesh, False), x, w, t))
        assert nf == 2, f"fused form must run 2 all-reduces, saw {nf}"
        assert nd == 4, f"decomposed control must run 4, saw {nd}"

    def test_packed_stats_bitwise(self, rng):
        """shard_stats_packed columns == shard_stats outputs (the same
        scratch reads leave the kernel through one stream)."""
        from apex1_tpu.ops.linear_xent import shard_stats, shard_stats_packed
        x = jnp.asarray(rng.normal(size=(self.T, self.H)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(32, self.H)) * 0.1, jnp.float32)
        t = jnp.asarray(rng.integers(0, 32, (self.T, 1)), jnp.int32)
        with force_impl("pallas"):
            sep = shard_stats(x, w, t, col_offset=32, num_classes=64)
            packed = shard_stats_packed(x, w, t, col_offset=32,
                                        num_classes=64)
        for i, s in enumerate(sep):
            _bitwise(packed[:, i], s)


class TestFusedTuningSpecs:
    """Registry entries for the new kernels: present, VMEM-gated, and
    consulted by the block resolution."""

    def test_specs_present(self):
        from apex1_tpu.tuning.registry import SPECS
        assert SPECS["fused_collective_matmul"].params == ("block_m",
                                                           "block_n")
        assert SPECS["fused_ag_flash"].params == ("block_q", "block_k")

    def test_vmem_model_rejects_huge_blocks(self):
        from apex1_tpu.core.capability import vmem_budget
        from apex1_tpu.tuning.registry import SPECS
        ok, _ = SPECS["fused_collective_matmul"].check(
            {"block_m": 8192, "block_n": 8192}, {"Kp": 8192}, 2,
            vmem_budget())
        assert not ok
        ok, _ = SPECS["fused_ag_flash"].check(
            {"block_q": 256, "block_k": 256}, {"Dp": 128, "Sb": 1024}, 2,
            vmem_budget())
        assert ok

    def test_table_lookup_consulted(self, tmp_path, monkeypatch):
        """A banked fused_collective_matmul winner is served by
        _cm_blocks (and an absent table falls through to the
        heuristic)."""
        from apex1_tpu import tuning
        monkeypatch.setenv("APEX1_TUNING_DIR", str(tmp_path))
        tuning.clear_cache()
        try:
            assert fc._cm_blocks(128, None, None, jnp.float32) == (256,
                                                                   512)
            tuning.record("fused_collective_matmul", {"Kp": 128},
                          "float32", {"block_m": 64, "block_n": 128})
            tuning.save("fused_collective_matmul")
            tuning.clear_cache()
            assert fc._cm_blocks(128, None, None, jnp.float32) == (64,
                                                                   128)
        finally:
            tuning.clear_cache()
