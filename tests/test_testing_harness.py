"""`apex1_tpu.testing` — the importable test harness (≙
``apex/transformer/testing``): distributed_mesh context, global args,
standalone test models."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from apex1_tpu import testing
from apex1_tpu.transformer import parallel_state


def test_distributed_mesh_context(devices):
    parallel_state.destroy_model_parallel()
    with testing.distributed_mesh(dp=2, tp=2, pp=2) as mesh:
        assert set(mesh.axis_names) >= {"dp", "tp", "pp"}
        assert parallel_state.get_tensor_model_parallel_world_size() == 2
        assert parallel_state.model_parallel_is_initialized()
    assert not parallel_state.model_parallel_is_initialized()


def test_global_args_roundtrip():
    a = testing.TestArgs(seq_length=16, hidden_size=32)
    testing.set_global_args(a)
    try:
        assert testing.get_args().seq_length == 16
    finally:
        testing.set_global_args(None)  # type: ignore[arg-type]
    assert testing.get_args().seq_length == 32  # defaults restored


@pytest.mark.slow
def test_standalone_models_train_one_step(devices):
    for build in (testing.standalone_gpt, testing.standalone_bert):
        model, batch, params, loss_fn = build()
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        assert np.isfinite(float(loss))
        assert all(np.all(np.isfinite(g)) for g in jax.tree.leaves(grads))


class TestChildCacheEnv:
    """`testing.child_cache_env` must honor the OPERATOR's exported
    `JAX_COMPILATION_CACHE_DIR` by presence, not truthiness (exported
    EMPTY = deliberately disabled), and always carry the min-compile
    override (ADVICE r5)."""

    def test_exported_empty_dir_is_not_reenabled(self, monkeypatch):
        monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "")
        monkeypatch.delenv("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                           raising=False)
        out = testing.child_cache_env()
        assert "JAX_COMPILATION_CACHE_DIR" not in out  # inherit the disable
        assert out["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] == "0.1"

    def test_disabled_path_still_lowers_min_compile_time(self, monkeypatch):
        monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
        monkeypatch.delenv("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                           raising=False)
        monkeypatch.setenv("APEX1_JAX_CACHE_DIR", "")  # disable convention
        out = testing.child_cache_env()
        assert "JAX_COMPILATION_CACHE_DIR" not in out
        assert out["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] == "0.1"

    def test_exported_dir_wins_and_is_inherited(self, monkeypatch):
        monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "/tmp/op_cache")
        out = testing.child_cache_env()
        # dir reaches the child via dict(os.environ); no duplicate key
        assert "JAX_COMPILATION_CACHE_DIR" not in out
